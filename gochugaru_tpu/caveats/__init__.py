"""CEL-subset caveat expressions.

SpiceDB caveats are CEL programs evaluated against a context assembled from
the relationship's stored context merged with the request's context (stored
values take precedence).  The reference treats caveats as first-class in its
data model (rel/relationship.go:35-37,174-188); evaluation happens
server-side.  Here ``compile_cel`` parses a supported CEL subset once at
schema-write time; the host evaluator backs the oracle, and the same program
lowers to the device caveat VM for on-device predicate evaluation.
"""

from .cel import (
    CelCompileError,
    CelProgram,
    CelType,
    UNKNOWN,
    compile_cel,
)

__all__ = ["compile_cel", "CelProgram", "CelCompileError", "CelType", "UNKNOWN"]

"""A CEL-subset compiler and tri-state host evaluator.

Supported subset (the fragment that covers typical authorization caveats and
vectorizes onto TPU):

- literals: int, float, string, bool, null
- identifiers and dotted member access into the context map
- operators: ``?:``, ``||``, ``&&``, ``!``, comparisons
  (``== != < <= > >=``), arithmetic (``+ - * / %``, unary ``-``), ``in``
  (membership in a list literal or list-valued context value)
- parentheses
- ``timestamp("<RFC 3339>")`` and ``duration("1h30m")`` constructors
  (host evaluation): timestamps and durations compare and do the CEL
  arithmetic (ts − ts = dur, ts ± dur = ts, dur ± dur = dur); context
  parameters DECLARED as ``timestamp``/``duration`` coerce from RFC 3339
  / CEL duration strings (or datetimes / numeric seconds) at evaluation
  time.  Params DECLARED timestamp/duration and folded time literals
  also lower onto the device as exact-µs i32 limb pairs
  (caveats/device.py); only the dynamic constructor form
  (``timestamp(x)`` over a non-literal) stays host-only

Evaluation is three-valued: a missing context parameter makes the result
UNKNOWN rather than an error — SpiceDB's CONDITIONAL permissionship — and
UNKNOWN propagates through Kleene logic (``T || U = T``, ``F && U = F``,
comparisons with UNKNOWN are UNKNOWN).  The engine collapses UNKNOWN to
"no permission" at the API boundary, where the reference client also
collapses permissionship to bool (client/client.go:277).
"""

from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass
from functools import cached_property
from typing import Any, Dict, List, Mapping, Optional, Tuple


class CelCompileError(ValueError):
    pass


class _Unknown:
    """The UNKNOWN truth value (missing context)."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover
        return "UNKNOWN"


UNKNOWN = _Unknown()


class CelType:
    """CEL caveat parameter types we accept in declarations."""

    KNOWN = {
        "int", "uint", "double", "bool", "string", "timestamp", "duration",
        "any", "list", "map",
    }


class _TimeValue:
    """Shared microsecond scalar: construction + the ordered
    comparisons (strictly same-typed, like CEL).  The subclasses own
    equality, hashing, and the time algebra."""

    __slots__ = ("us",)
    _kind = "time"

    def __init__(self, us: int) -> None:
        self.us = int(us)

    def __repr__(self) -> str:  # pragma: no cover
        return f"{self._kind}({self.us}us)"

    def _cmp(self, other: Any):
        if type(other) is not type(self):
            raise TypeError(
                f"{self._kind} compared with non-{self._kind}"
            )
        return self.us, other.us

    def __lt__(self, other):
        a, b = self._cmp(other)
        return a < b

    def __le__(self, other):
        a, b = self._cmp(other)
        return a <= b

    def __gt__(self, other):
        a, b = self._cmp(other)
        return a > b

    def __ge__(self, other):
        a, b = self._cmp(other)
        return a >= b


class Timestamp(_TimeValue):
    """A CEL timestamp: microseconds since the Unix epoch.  Orders
    against other timestamps; ``ts - ts`` is a Duration, ``ts ± dur``
    a Timestamp — the CEL time algebra the host evaluator computes."""

    __slots__ = ()
    _kind = "timestamp"

    def __eq__(self, other: Any) -> Any:
        return isinstance(other, Timestamp) and self.us == other.us

    def __hash__(self) -> int:
        return hash(("ts", self.us))

    def __sub__(self, other):
        if isinstance(other, Timestamp):
            return Duration(self.us - other.us)
        if isinstance(other, Duration):
            return Timestamp(self.us - other.us)
        raise TypeError("timestamp - non-time")

    def __add__(self, other):
        if isinstance(other, Duration):
            return Timestamp(self.us + other.us)
        raise TypeError("timestamp + non-duration")


class Duration(_TimeValue):
    """A CEL duration: signed microseconds."""

    __slots__ = ()
    _kind = "duration"

    def __eq__(self, other: Any) -> Any:
        return isinstance(other, Duration) and self.us == other.us

    def __hash__(self) -> int:
        return hash(("dur", self.us))

    def __add__(self, other):
        if isinstance(other, Duration):
            return Duration(self.us + other.us)
        if isinstance(other, Timestamp):
            return Timestamp(self.us + other.us)
        raise TypeError("duration + non-time")

    def __sub__(self, other):
        if isinstance(other, Duration):
            return Duration(self.us - other.us)
        raise TypeError("duration - non-duration")

    def __neg__(self):
        return Duration(-self.us)


#: parts are UNSIGNED — like Go's time.ParseDuration, only ONE leading
#: sign is legal ("1h-30m" and a bare "-" are rejected, not summed)
_DUR_PART = re.compile(r"(\d+(?:\.\d+)?)(h|ms|us|ns|m|s)")
_DUR_SCALE = {
    "h": 3_600_000_000, "m": 60_000_000, "s": 1_000_000,
    "ms": 1_000, "us": 1, "ns": 1e-3,
}


def parse_duration(s: str) -> Duration:
    """CEL/Go duration literal: "1h30m", "300s", "1.5s", "-2m" ..."""
    body = s.strip()
    neg = body.startswith("-")
    if neg or body.startswith("+"):
        body = body[1:]
    if not body:
        raise CelCompileError(f"empty duration literal {s!r}")
    if body == "0":  # Go accepts the bare zero without a unit
        return Duration(0)
    pos = 0
    total = 0.0
    while pos < len(body):
        m = _DUR_PART.match(body, pos)
        if m is None:
            raise CelCompileError(f"bad duration literal {s!r}")
        total += float(m.group(1)) * _DUR_SCALE[m.group(2)]
        pos = m.end()
    return Duration(round(-total if neg else total))


def parse_timestamp(s: str) -> Timestamp:
    """RFC 3339 timestamp ("2024-01-02T03:04:05Z", offsets allowed)."""
    body = s.strip()
    try:
        dt = _dt.datetime.fromisoformat(body.replace("Z", "+00:00"))
    except ValueError as e:
        raise CelCompileError(f"bad timestamp literal {s!r}") from e
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return Timestamp(round(dt.timestamp() * 1_000_000))


#: host-evaluable builtin constructors (the device VM declines these)
_CEL_FUNCS = {"timestamp", "duration"}


_CEL_TOKEN = re.compile(
    r"""
      (?P<ws>\s+)
    | (?P<float>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+[eE][+-]?\d+)
    | (?P<int>\d+u?)
    | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<op>\|\||&&|==|!=|<=|>=|[-+*/%!<>()?:,.\[\]])
    """,
    re.VERBOSE,
)


def _tokenize(src: str) -> List[Tuple[str, str]]:
    toks = []
    pos = 0
    while pos < len(src):
        m = _CEL_TOKEN.match(src, pos)
        if m is None:
            raise CelCompileError(f"unexpected character {src[pos]!r} in caveat expression")
        kind = m.lastgroup
        if kind != "ws":
            toks.append((kind, m.group()))
        pos = m.end()
    toks.append(("eof", ""))
    return toks


# AST: tuples (op, ...)
#   ("lit", value) ("var", name) ("member", base, name)
#   ("not", x) ("neg", x) ("or", a, b) ("and", a, b) ("cond", c, t, f)
#   ("cmp", op, a, b) ("arith", op, a, b) ("in", a, b) ("list", [items])
#   ("call", fname, [args])  — timestamp()/duration() constructors


class _CelParser:
    def __init__(self, src: str) -> None:
        self.toks = _tokenize(src)
        self.i = 0

    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> None:
        k, t = self.next()
        if t != text:
            raise CelCompileError(f"expected {text!r}, got {t!r}")

    def parse(self):
        e = self.parse_ternary()
        if self.peek()[0] != "eof":
            raise CelCompileError(f"trailing tokens at {self.peek()[1]!r}")
        return e

    def parse_ternary(self):
        cond = self.parse_or()
        if self.peek()[1] == "?":
            self.next()
            t = self.parse_ternary()
            self.expect(":")
            f = self.parse_ternary()
            return ("cond", cond, t, f)
        return cond

    def parse_or(self):
        left = self.parse_and()
        while self.peek()[1] == "||":
            self.next()
            left = ("or", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_rel()
        while self.peek()[1] == "&&":
            self.next()
            left = ("and", left, self.parse_rel())
        return left

    _CMP = {"==", "!=", "<", "<=", ">", ">="}

    def parse_rel(self):
        left = self.parse_add()
        while True:
            t = self.peek()[1]
            if t in self._CMP:
                self.next()
                left = ("cmp", t, left, self.parse_add())
            elif t == "in":
                self.next()
                left = ("in", left, self.parse_add())
            else:
                return left

    def parse_add(self):
        left = self.parse_mul()
        while self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            left = ("arith", op, left, self.parse_mul())
        return left

    def parse_mul(self):
        left = self.parse_unary()
        while self.peek()[1] in ("*", "/", "%"):
            op = self.next()[1]
            left = ("arith", op, left, self.parse_unary())
        return left

    def parse_unary(self):
        t = self.peek()[1]
        if t == "!":
            self.next()
            return ("not", self.parse_unary())
        if t == "-":
            self.next()
            return ("neg", self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self):
        e = self.parse_primary()
        while self.peek()[1] == ".":
            self.next()
            k, name = self.next()
            if k != "ident":
                raise CelCompileError(f"expected member name after '.', got {name!r}")
            e = ("member", e, name)
        return e

    def parse_primary(self):
        kind, text = self.next()
        if text == "(":
            e = self.parse_ternary()
            self.expect(")")
            return e
        if text == "[":
            items = []
            while self.peek()[1] != "]":
                items.append(self.parse_ternary())
                if self.peek()[1] == ",":
                    self.next()
            self.expect("]")
            return ("list", items)
        if kind == "int":
            return ("lit", int(text.rstrip("u")))
        if kind == "float":
            return ("lit", float(text))
        if kind == "string":
            return ("lit", _unescape(text[1:-1]))
        if kind == "ident":
            if text == "true":
                return ("lit", True)
            if text == "false":
                return ("lit", False)
            if text == "null":
                return ("lit", None)
            if text == "in":
                raise CelCompileError("misplaced 'in'")
            if self.peek()[1] == "(":
                if text not in _CEL_FUNCS:
                    raise CelCompileError(f"unknown function {text!r}")
                self.next()
                args = []
                while self.peek()[1] != ")":
                    args.append(self.parse_ternary())
                    if self.peek()[1] not in (",", ")"):
                        raise CelCompileError(
                            f"expected ',' or ')' in {text}() arguments"
                        )
                    if self.peek()[1] == ",":
                        self.next()
                self.expect(")")
                # arity/shape checked at COMPILE time; a literal argument
                # parses eagerly (bad literals are schema-write errors,
                # not first-check errors) and folds to its host value —
                # the device lowering declines the folded literal the
                # same way it declines the call
                if len(args) != 1:
                    raise CelCompileError(
                        f"{text}() takes one string argument"
                    )
                if args[0][0] == "lit":
                    v = args[0][1]
                    if not isinstance(v, str):
                        raise CelCompileError(
                            f"{text}() takes one string argument"
                        )
                    return ("lit", (
                        parse_timestamp(v) if text == "timestamp"
                        else parse_duration(v)
                    ))
                return ("call", text, args)
            return ("var", text)
        raise CelCompileError(f"unexpected token {text!r}")


_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f", "v": "\v",
    "0": "\0", "\\": "\\", '"': '"', "'": "'", "`": "`", "?": "?",
}


def _unescape(body: str) -> str:
    """Decode CEL string escapes (\\n, \\t, \\uXXXX, \\xXX, ...)."""
    out = []
    i = 0
    n = len(body)
    while i < n:
        ch = body[i]
        if ch != "\\" or i + 1 >= n:
            out.append(ch)
            i += 1
            continue
        esc = body[i + 1]
        if esc in _ESCAPES:
            out.append(_ESCAPES[esc])
            i += 2
        elif esc == "u" and i + 5 < n + 1:
            out.append(chr(int(body[i + 2 : i + 6], 16)))
            i += 6
        elif esc == "x" and i + 3 < n + 1:
            out.append(chr(int(body[i + 2 : i + 4], 16)))
            i += 4
        else:
            raise CelCompileError(f"unsupported string escape \\{esc}")
    return "".join(out)


def _is_unknown(v: Any) -> bool:
    return v is UNKNOWN


def _truthy(v: Any):
    if _is_unknown(v):
        return UNKNOWN
    if isinstance(v, bool):
        return v
    raise CelCompileError(f"non-boolean used as condition: {v!r}")


@dataclass(frozen=True)
class CelProgram:
    """A compiled caveat expression: AST + declared params."""

    name: str
    params: Mapping[str, str]
    ast: Any
    source: str

    def referenced_vars(self) -> List[str]:
        out: List[str] = []

        def walk(node) -> None:
            op = node[0]
            if op == "var":
                out.append(node[1])
            elif op == "lit":
                pass
            elif op == "member":
                walk(node[1])
            elif op in ("not", "neg"):
                walk(node[1])
            elif op in ("or", "and", "in"):
                walk(node[1]); walk(node[2])
            elif op == "cmp" or op == "arith":
                walk(node[2]); walk(node[3])
            elif op == "cond":
                walk(node[1]); walk(node[2]); walk(node[3])
            elif op == "list":
                for it in node[1]:
                    walk(it)
            elif op == "call":
                for a in node[2]:
                    walk(a)

        walk(self.ast)
        return out

    # -- host evaluation ---------------------------------------------------
    @cached_property
    def _timed_params(self) -> Mapping[str, str]:
        """Params declared timestamp/duration, computed once per program
        (host evaluation runs per caveated edge per check)."""
        return {
            n: t.split("<", 1)[0] for n, t in self.params.items()
            if t.split("<", 1)[0] in ("timestamp", "duration")
        }

    def _coerced(self, context: Mapping[str, Any]) -> Mapping[str, Any]:
        """Coerce context values of params DECLARED timestamp/duration
        into the comparable host types: RFC 3339 / Go-duration strings,
        datetimes, or numeric seconds."""
        timed = self._timed_params
        need = [
            n for n in timed
            if context.get(n) is not None
            and not isinstance(context[n], _TimeValue)
        ]
        if not need:
            return context
        out = dict(context)
        for n in need:
            base = timed[n]
            v = out[n]
            if base == "timestamp":
                if isinstance(v, _dt.datetime):
                    out[n] = Timestamp(round(v.timestamp() * 1_000_000))
                elif isinstance(v, str):
                    out[n] = parse_timestamp(v)
                # bool is an int subtype but a True/False "timestamp"
                # is garbage — ERROR, never coerce to a grantable epoch
                elif isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[n] = Timestamp(round(v * 1_000_000))
                else:
                    raise CelCompileError(
                        f"caveat {self.name!r}: cannot coerce {v!r} to"
                        " timestamp"
                    )
            else:
                if isinstance(v, _dt.timedelta):
                    out[n] = Duration(round(v.total_seconds() * 1_000_000))
                elif isinstance(v, str):
                    out[n] = parse_duration(v)
                elif isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[n] = Duration(round(v * 1_000_000))
                else:
                    raise CelCompileError(
                        f"caveat {self.name!r}: cannot coerce {v!r} to"
                        " duration"
                    )
        return out

    def evaluate(self, context: Mapping[str, Any]):
        """Evaluate against a merged context.  Returns True / False /
        UNKNOWN (missing context parameter somewhere it mattered)."""
        result = self._eval(self.ast, self._coerced(context))
        if _is_unknown(result):
            return UNKNOWN
        if not isinstance(result, bool):
            raise CelCompileError(
                f"caveat {self.name!r} evaluated to non-boolean {result!r}"
            )
        return result

    def _eval(self, node, ctx: Mapping[str, Any]):
        op = node[0]
        if op == "lit":
            return node[1]
        if op == "var":
            if node[1] in ctx:
                return ctx[node[1]]
            return UNKNOWN
        if op == "member":
            base = self._eval(node[1], ctx)
            if _is_unknown(base):
                return UNKNOWN
            if isinstance(base, Mapping) and node[2] in base:
                return base[node[2]]
            return UNKNOWN
        if op == "list":
            items = [self._eval(it, ctx) for it in node[1]]
            return UNKNOWN if any(_is_unknown(i) for i in items) else items
        if op == "not":
            v = _truthy(self._eval(node[1], ctx))
            return UNKNOWN if _is_unknown(v) else (not v)
        if op == "neg":
            v = self._eval(node[1], ctx)
            return UNKNOWN if _is_unknown(v) else -v
        if op == "or":
            a = _truthy(self._eval(node[1], ctx))
            if a is True:
                return True
            b = _truthy(self._eval(node[2], ctx))
            if b is True:
                return True
            if _is_unknown(a) or _is_unknown(b):
                return UNKNOWN
            return False
        if op == "and":
            a = _truthy(self._eval(node[1], ctx))
            if a is False:
                return False
            b = _truthy(self._eval(node[2], ctx))
            if b is False:
                return False
            if _is_unknown(a) or _is_unknown(b):
                return UNKNOWN
            return True
        if op == "cond":
            c = _truthy(self._eval(node[1], ctx))
            if _is_unknown(c):
                return UNKNOWN
            return self._eval(node[2] if c else node[3], ctx)
        if op == "cmp":
            a = self._eval(node[2], ctx)
            b = self._eval(node[3], ctx)
            if _is_unknown(a) or _is_unknown(b):
                return UNKNOWN
            o = node[1]
            try:
                if o == "==":
                    return a == b
                if o == "!=":
                    return a != b
                if o == "<":
                    return a < b
                if o == "<=":
                    return a <= b
                if o == ">":
                    return a > b
                return a >= b
            except TypeError as e:
                raise CelCompileError(f"type error in caveat {self.name!r}: {e}") from e
        if op == "arith":
            a = self._eval(node[2], ctx)
            b = self._eval(node[3], ctx)
            if _is_unknown(a) or _is_unknown(b):
                return UNKNOWN
            o = node[1]
            try:
                if o == "+":
                    return a + b
                if o == "-":
                    return a - b
                if o == "*":
                    return a * b
                if o == "/":
                    # CEL int division truncates toward zero
                    if isinstance(a, int) and isinstance(b, int):
                        q = abs(a) // abs(b)
                        return q if (a >= 0) == (b >= 0) else -q
                    return a / b
                # CEL '%' is the truncated remainder (sign of the dividend),
                # not Python's floored remainder — must match the device
                # lowering (device.py emit_ar) for negative operands
                if isinstance(a, int) and isinstance(b, int):
                    q = abs(a) // abs(b)
                    q = q if (a >= 0) == (b >= 0) else -q
                    return a - q * b
                return a % b
            except (TypeError, ZeroDivisionError) as e:
                raise CelCompileError(f"arithmetic error in caveat {self.name!r}: {e}") from e
        if op == "in":
            a = self._eval(node[1], ctx)
            b = self._eval(node[2], ctx)
            if _is_unknown(a) or _is_unknown(b):
                return UNKNOWN
            if not isinstance(b, (list, tuple, set, frozenset, str, Mapping)):
                raise CelCompileError(f"'in' target not a collection in {self.name!r}")
            return a in b
        if op == "call":
            args = [self._eval(a, ctx) for a in node[2]]
            if any(_is_unknown(a) for a in args):
                return UNKNOWN
            if len(args) != 1 or not isinstance(args[0], str):
                raise CelCompileError(
                    f"{node[1]}() takes one string argument in {self.name!r}"
                )
            return (
                parse_timestamp(args[0]) if node[1] == "timestamp"
                else parse_duration(args[0])
            )
        raise CelCompileError(f"unknown node {op!r}")


def compile_cel(name: str, params: Mapping[str, str], source: str) -> CelProgram:
    """Compile a caveat body.  Unknown parameter types and references to
    undeclared identifiers are rejected at schema-write time."""
    for pname, ptype in params.items():
        base = ptype.split("<", 1)[0]
        if base not in CelType.KNOWN:
            raise CelCompileError(f"caveat {name!r}: unknown parameter type {ptype!r}")
    ast = _CelParser(source).parse()
    prog = CelProgram(name=name, params=dict(params), ast=ast, source=source)
    for var in prog.referenced_vars():
        if var not in params:
            raise CelCompileError(
                f"caveat {name!r} references undeclared identifier {var!r}"
            )
    return prog

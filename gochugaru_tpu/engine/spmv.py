"""Masked frontier SpMV: LookupResources/LookupSubjects on the device.

The host walker (engine/lookup.py) answers the inverse-of-Check
questions by sorting transposed O(E) views on the host and running a
numpy worklist — O(E log E) cold start per snapshot and per-hop host
work proportional to the touched edges.  This module replaces that with
the GraphBLAS push idiom (RedisGraph, arXiv:1905.01294; Graphulo's
tables-as-matrices framing, arXiv:1609.08642) over the reverse-CSR
tables built alongside the forward layout (engine/rev.py):

- the frontier is a set of packed keys (k2 = (subject, srel1) for
  reverse reachability; k1 = (slot, resource) forward; child nodes for
  arrow traversal);
- one hop = one vectorized probe kernel (hash bucket + short in-bucket
  bisect finds each key's contiguous run) + budgeted emission kernels
  (a fixed-shape chunk of matching rows per dispatch, whatever the
  fan-out — the SpMV "gather" with the frontier as the mask);
- caveats/expirations filter the frontier IN the emission kernel via
  the same packed decode layer the Check kernel uses
  (engine/packed.py decode_block): an expired edge, or a caveated edge
  with no stored context (conditional-by-construction, and conditional
  results are omitted from lookups — the bool collapse), never leaves
  the device;
- the host only dedups (bitmap seen-sets), applies the schema-level
  worklist rules (membership-chain keys, permission-userset chains,
  wildcard handling — mirroring the walker's proven superset
  discipline), and streams candidate blocks to the exact filter.

Candidates stream in DETERMINISTIC discovery order (device kernels are
deterministic, host dedup is order-stable), which is what makes the
cursor contract exact: a ``LookupCursor`` pins (revision, query
fingerprint, results emitted) and a resume either continues the cached
live stream or deterministically recomputes and skips — no duplicate
and no lost IDs across page boundaries (tests/test_lookup_stream.py).

Eligibility: full prepares with the reverse index (FlatMeta.has_rev)
and no LSM delta level — delta chains keep the walker, whose
advance_lookup_index machinery is already delta-exact.  The sharded
stacked layout routes each hop's frontier to owner shards
(parallel/sharded.py lookup support) and only owner-crossing IDs move.
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..utils import faults, metrics
from .hash import _ceil_pow2, mix32, take_in_bounds

_mt = metrics.default

#: continuation cache per DeviceSnapshot (live candidate streams keyed
#: by cursor token; LRU — an evicted stream resumes by deterministic
#: recompute-and-skip)
_STREAM_CACHE_MAX = 16


# ---------------------------------------------------------------------------
# cursors
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LookupCursor:
    """Revision-pinned resumable position in one lookup's result stream.

    ``pos`` counts RESULTS already emitted (not candidates): the stream
    is deterministic per (snapshot revision, query, evaluation time), so
    skipping ``pos`` results reproduces the exact continuation even with
    no server-side state.  ``now_us`` pins that evaluation time: a
    caller who never passed one gets wall clock resolved ONCE at stream
    creation — a recompute-resume at a later wall clock would otherwise
    re-evaluate expiry gates and silently lose/duplicate IDs."""

    revision: int
    token: str  # query fingerprint — a cursor never resumes a different query
    pos: int
    now_us: Optional[int] = None

    def encode(self) -> str:
        raw = json.dumps(
            {"r": self.revision, "t": self.token, "p": self.pos,
             "n": self.now_us},
            separators=(",", ":"),
        ).encode()
        return base64.urlsafe_b64encode(raw).decode()

    @staticmethod
    def decode(s: str) -> "LookupCursor":
        from ..utils.errors import PreconditionFailedError

        try:
            d = json.loads(base64.urlsafe_b64decode(s.encode()))
            n = d.get("n")
            return LookupCursor(
                int(d["r"]), str(d["t"]), int(d["p"]),
                int(n) if n is not None else None,
            )
        except Exception as e:
            raise PreconditionFailedError(f"malformed lookup cursor: {e}")


def query_token(*parts) -> str:
    """Stable query fingerprint for cursor validation."""
    import hashlib

    h = hashlib.sha1("\x1f".join(str(p) for p in parts).encode()).hexdigest()
    return h[:16]


def resolve_now_us(cursor: Optional["LookupCursor"],
                   now_us: Optional[int]) -> int:
    """The lookup's pinned evaluation time: an explicit ``now_us`` wins,
    a resuming cursor reuses the one its stream was created with, and a
    fresh implicit-time lookup resolves wall clock ONCE — so
    recompute-resumes re-evaluate expiry/caveat gates at the SAME
    instant and the no-dup/no-loss contract holds."""
    import time as _time

    if now_us is not None:
        return int(now_us)
    if cursor is not None and cursor.now_us is not None:
        return int(cursor.now_us)
    return int(_time.time() * 1_000_000)


# ---------------------------------------------------------------------------
# host-side seen-sets (bitmaps; order-stable dedup)
# ---------------------------------------------------------------------------


class _Seen:
    """Bitmap over a dense int domain; ``fresh`` returns the sorted
    unique not-yet-seen subset and marks it."""

    def __init__(self, domain: int) -> None:
        self._bm = np.zeros((max(domain, 1) + 7) >> 3, np.uint8)

    def fresh(self, ids: np.ndarray) -> np.ndarray:
        if ids.size == 0:
            return ids.astype(np.int64)
        ids = np.unique(ids.astype(np.int64))
        byte = ids >> 3
        bit = (1 << (ids & 7)).astype(np.uint8)
        take = (self._bm[byte] & bit) == 0
        ids, byte, bit = ids[take], byte[take], bit[take]
        if ids.size:
            # two fresh ids can share a byte: sorted ids put them in one
            # run — OR-reduce per distinct byte, then one plain scatter
            # (np.bitwise_or.at is ~50x slower than this at volume)
            ub, first = np.unique(byte, return_index=True)
            self._bm[ub] |= np.bitwise_or.reduceat(bit, first)
        return ids


#: bitmap byte budget per seen-set — worlds whose key domain would need
#: more fall back to the host walker
_SEEN_BUDGET_BYTES = 1 << 27


# ---------------------------------------------------------------------------
# device kernels (per-FlatMeta, cached on the engine)
# ---------------------------------------------------------------------------


def _field0_reader(spec, w: int):
    """Reader of column 0 at flat row indices (the bisect compare):
    packed specs decode just the lanes field 0 lives in — same shift/
    mask decode the Check kernel fuses into its gathers."""
    import jax.numpy as jnp

    if spec is None:

        def rd(tbl, idx):
            return take_in_bounds(tbl.reshape(-1), idx * w)

        return rd

    lanes = spec[1]
    bits, base, delta_of, dict_id, off_bit = spec[2][0]
    assert off_bit == 0 and delta_of < 0 and dict_id < 0, (
        "reverse-index key columns are plain ranges at bit 0"
    )

    def rd(tbl, idx):
        flat = tbl.reshape(-1)
        v = take_in_bounds(flat, idx * lanes).astype(jnp.int32)
        if bits > 16:
            v = v | (take_in_bounds(flat, idx * lanes + 1).astype(jnp.int32) << 16)
        if bits < 32:
            v = v & jnp.int32((1 << bits) - 1)
        return v + jnp.int32(base) if base else v

    return rd


def _decoder(spec):
    import jax.numpy as jnp

    if spec is None:
        return lambda blk: blk

    from .packed import decode_block

    return lambda blk: decode_block(blk, spec)


class FrontierKernels:
    """The jitted probe/emit kernels of one FlatMeta geometry (cached on
    the engine keyed by meta — delta-free full prepares with the same
    geometry share compiled programs)."""

    def __init__(self, meta, config) -> None:
        import jax

        self.meta = meta
        self.CH = int(config.lookup_chunk)
        self.F_min = int(config.lookup_frontier_min)
        self._pk = dict(meta.packed)
        self._pko = dict(meta.packed_off)
        # Pallas fused probe backend (engine/pallas.py): the point-run
        # probes route through the ``runs`` kernel when the knob
        # resolves on and the layout is single-shard — the sharded
        # engine shard_maps the raw bodies, where the XLA chain must
        # stay verbatim.  Per-call the offsets must also fit the
        # VMEM-resident plan; otherwise the body keeps the XLA bisect.
        from . import pallas as _pallas

        self._pls = (not meta.sharded) and _pallas.resolve(config)
        e_gates = (["cav", "ctx"] if meta.e_hascav else []) + (
            ["exp"] if meta.e_hasexp else []
        )
        ar_gates = (["cav", "ctx"] if meta.ar_hascav else []) + (
            ["exp"] if meta.ar_hasexp else []
        )
        self.w_rv = 2 + len(e_gates)
        self.w_ra = 2 + len(ar_gates)
        #: raw (unjitted) bodies — the sharded engine shard_maps these
        #: over the model axis verbatim: inside a shard the off/table
        #: BLOCKS have exactly the single-shard shapes, so one body
        #: serves both layouts (parallel/sharded.py lookup hops)
        self.raw_runs = {
            "rv": self._make_runs("rvx", "rv_off", meta.rv_cap, self.w_rv),
            "ra": self._make_runs("rax", "ra_off", meta.ra_cap, self.w_ra),
        }
        self.raw_emits = {
            "rv": self._make_emit("rvx", self.w_rv, 2, meta.e_hascav,
                                  meta.e_hasexp),
            "ra": self._make_emit("rax", self.w_ra, 2, meta.ar_hascav,
                                  meta.ar_hasexp),
        }
        if meta.has_fw:
            self.raw_runs["fw"] = self._make_runs(
                "fwx", "fw_off", meta.fw_cap, self.w_rv
            )
            self.raw_emits["fw"] = self._make_emit(
                "fwx", self.w_rv, 2, meta.e_hascav, meta.e_hasexp
            )
        # forward arrows ride the EXISTING argx/arx range view
        self._arg_aligned = "argx" in {k for k, _w, _c in meta.aligned}
        self.raw_runs["arg"] = self._make_runs_group()
        w_arx = 1 + len(ar_gates)
        self.raw_emits["arg"] = self._make_emit(
            "arx", w_arx, 1, meta.ar_hascav, meta.ar_hasexp
        )
        self._runs = {k: jax.jit(v) for k, v in self.raw_runs.items()}
        # the chunk size is a static arg: emission kernels compile per
        # pow2 chunk tier, so a 200-row hop costs O(256) work, not
        # O(lookup_chunk) — the fixed budget only caps the LARGEST tier
        self._emits = {
            k: jax.jit(v, static_argnums=5) for k, v in self.raw_emits.items()
        }
        # fused hop: probe + FIRST emission chunk in one compiled
        # program — most hops emit fewer than CH0 rows, so the common
        # case is one dispatch + one fetch per hop instead of two of
        # each (the per-dispatch fixed cost is the frontier's floor on
        # gather-poor hosts)
        self.CH0 = min(4096, self.CH)
        self._hops_fused = {
            k: self._make_hop(k) for k in self.raw_runs if k != "arg"
        }
        if not self._arg_aligned:
            self._hops_fused["arg"] = self._make_hop("arg")
        #: (kind, frontier-pad) shapes already registered with the perf
        #: cost ledger — the hot hop path checks this local set only
        self._cost_reg: set = set()

    def _make_hop(self, kind: str):
        import jax
        import jax.numpy as jnp

        runs_raw = self.raw_runs[kind]
        emit_raw = self.raw_emits[kind]
        CH0 = self.CH0

        def fn(off, off_a, tbl, emit_tbl, keys, now):
            lo, ln = runs_raw(off, off_a, tbl, keys)
            rows, live = emit_raw(emit_tbl, lo, ln, jnp.int32(0), now, CH0)
            return lo, ln, rows, live

        return jax.jit(fn)

    # -- offset reads (anchor+residual when packed) ----------------------
    def _off_reader(self, off_key: str):
        import jax.numpy as jnp

        shift = self._pko.get(off_key)

        def rd(off, off_a, idx):
            if shift is None:
                return take_in_bounds(off, idx)
            return take_in_bounds(off_a, idx >> shift) + take_in_bounds(
                off, idx
            ).astype(jnp.int32)

        return rd

    # -- point-run probe: hash bucket + in-bucket bisect ------------------
    def _make_runs(self, tbl_key: str, off_key: str, cap: int, w: int):
        import jax.numpy as jnp

        steps = max(int(cap).bit_length(), 1)
        spec = self._pk.get(tbl_key)
        shift = self._pko.get(off_key)
        col0 = _field0_reader(spec, w)
        offr = self._off_reader(off_key)
        use_pls = self._pls

        def fn(off, off_a, tbl, keys):
            if use_pls:
                from . import pallas as _pallas

                if _pallas.vmem_ok(off) and (
                    shift is None or _pallas.vmem_ok(off_a)
                ):
                    return _pallas.fused_probe(
                        (keys,), off, tbl, cap=cap, spec=spec,
                        off_a=off_a if shift is not None else None,
                        ashift=shift, mode="runs",
                    )
            size = (off.shape[0] - 1)  # single-shard layout (M=1)
            h = (mix32([keys], jnp) & jnp.uint32(size - 1)).astype(jnp.int32)
            start = offr(off, off_a, h)
            end = offr(off, off_a, h + 1)
            last = tbl.shape[0] - 1

            def bisect(left: bool):
                lo = start
                n = end - start
                for _ in range(steps):
                    # n == 0 must freeze: an unguarded step would read
                    # past the bucket end (the next bucket's rows — or
                    # pad) and walk lo out of the run
                    alive = n > 0
                    half = n >> 1
                    mid = lo + half
                    v = col0(tbl, jnp.clip(mid, 0, last))
                    go = alive & ((v < keys) if left else (v <= keys))
                    lo = jnp.where(go, mid + 1, lo)
                    n = jnp.where(go, n - half - 1, jnp.where(alive, half, 0))
                return lo

            lo = bisect(True)
            ln = bisect(False) - lo
            dead = keys < 0
            return jnp.where(dead, 0, lo), jnp.where(dead, 0, ln)

        return fn

    # -- group-table probe (argx range view: hash probe or aligned ladder)
    def _make_runs_group(self):
        import jax.numpy as jnp

        meta = self.meta
        al = {k: (w, caps) for k, w, caps in meta.aligned}
        dec = _decoder(self._pk.get("argx"))
        if "argx" in al:
            from .hash import probe_aligned

            w_log, caps = al["argx"]
            spec = self._pk.get("argx")
            w_eff = spec[1] if spec is not None else w_log

            def fn(tbls, keys):
                blk = dec(probe_aligned(tbls, caps, w_eff, (keys,)))
                hit = (blk[..., 0] == keys[..., None]) & (keys >= 0)[..., None]
                lo = jnp.sum(jnp.where(hit, blk[..., 1], 0), axis=-1)
                hi = jnp.sum(jnp.where(hit, blk[..., 2], 0), axis=-1)
                return lo, hi - lo

            return fn

        from .hash import slice_blocks

        offr = self._off_reader("arr_off")
        cap = meta.arr_cap

        def fn2(off, off_a, gx, keys):
            size = off.shape[0] - 1
            h = (mix32([keys], jnp) & jnp.uint32(size - 1)).astype(jnp.int32)
            start = offr(off, off_a, h)
            blk = dec(slice_blocks(gx, start, cap))
            hit = (blk[..., 0] == keys[..., None]) & (keys >= 0)[..., None]
            lo = jnp.sum(jnp.where(hit, blk[..., 1], 0), axis=-1)
            hi = jnp.sum(jnp.where(hit, blk[..., 2], 0), axis=-1)
            return lo, hi - lo

        return fn2

    # -- budgeted emission: one fixed-shape chunk of matching rows --------
    def _make_emit(self, tbl_key: str, w: int, gate_at: int, hascav: bool,
                   hasexp: bool):
        import jax.numpy as jnp
        from jax import lax

        dec = _decoder(self._pk.get(tbl_key))

        def fn(tbl, lo, ln, chunk0, now, CH: int):
            chunk0 = jnp.asarray(chunk0).reshape(-1)[0]
            F = lo.shape[0]
            cum = jnp.cumsum(ln)
            cumstart = cum - ln
            total = cum[F - 1] if F else jnp.int32(0)
            pos = chunk0 + jnp.arange(CH, dtype=jnp.int32)
            valid = pos < total
            # key index per slot: scatter each in-window run start (runs
            # are disjoint, nonzero runs have unique starts), then a
            # running max — O(F + CH), no per-slot binary search
            rel = cumstart - chunk0
            inw = (rel > 0) & (rel < CH) & (ln > 0)
            sidx = jnp.where(inw, rel, CH)  # CH = dropped
            marks = jnp.full(CH, -1, jnp.int32).at[sidx].max(
                jnp.arange(F, dtype=jnp.int32), mode="drop"
            )
            base = jnp.max(
                jnp.where((ln > 0) & (cumstart <= chunk0),
                          jnp.arange(F, dtype=jnp.int32), -1)
            )
            marks = marks.at[0].max(base)
            ki = lax.cummax(marks)
            kic = jnp.clip(ki, 0, max(F - 1, 0))
            ok = valid & (ki >= 0)
            ridx = take_in_bounds(lo, kic) + pos - take_in_bounds(
                cumstart, kic
            )
            ridx = jnp.where(ok, ridx, 0)
            rows = dec(take_in_bounds(tbl, ridx))
            live = ok
            if hasexp:
                exp = rows[..., gate_at + (2 if hascav else 0)]
                live = live & ((exp == 0) | (exp > now))
            if hascav:
                # a caveated edge with stored context can still be
                # DEFINITE (the CEL VM resolves it); only the
                # conditional-by-construction case (no stored context —
                # lookups carry no request context) filters here
                cav = rows[..., gate_at]
                ctx = rows[..., gate_at + 1]
                live = live & ((cav == 0) | (ctx >= 0))
            return rows, live

        return fn

    # -- host-callable wrappers ------------------------------------------
    def pad_keys(self, keys: np.ndarray) -> np.ndarray:
        F = _ceil_pow2(max(keys.shape[0], 1), self.F_min)
        out = np.full(F, -1, np.int32)
        out[: keys.shape[0]] = keys
        return out

    def runs(self, kind: str, args: Tuple, keys: np.ndarray):
        """(lo, ln, total) device handles + host total for padded keys."""
        faults.fire("lookup.dispatch")
        _mt.inc("lookup.dispatches")
        kp = self.pad_keys(keys)
        import jax.numpy as jnp

        if kind == "arg" and self._arg_aligned:
            self._register_cost(kind, self._runs[kind], (tuple(args), kp))
            lo, ln = self._runs[kind](tuple(args), jnp.asarray(kp))
        else:
            self._register_cost(kind, self._runs[kind], (*args, kp))
            lo, ln = self._runs[kind](*args, jnp.asarray(kp))
        total = int(np.asarray(ln).sum())
        return lo, ln, total

    def _register_cost(
        self, kind: str, fn, call_args: Tuple, F: Optional[int] = None
    ) -> None:
        """Lazy cost-ledger registration for one frontier kernel shape
        (kernel-cache time, realized only on explicit demand).  The
        per-kernels ``_cost_reg`` set makes the steady-state hop path
        one local set-lookup — no global ledger lock, no meta hash, no
        key formatting per hop."""
        if F is None:
            F = int(call_args[-1].shape[0])
        if (kind, F) in self._cost_reg:
            return
        self._cost_reg.add((kind, F))
        from ..utils import perf as _perf

        key = f"{kind};F={F};meta={hash(self.meta) & 0xFFFFFFFF:08x}"
        _perf.register_cost_thunk(
            "spmv", key,
            lambda fn=fn, avals=_perf.avals_of(call_args): fn.lower(
                *avals
            ).compile(),
        )

    def emit(self, kind: str, tbl, lo, ln, chunk0: int, now,
             ch: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        import jax
        import jax.numpy as jnp

        _mt.inc("lookup.dispatches")
        rows, live = self._emits[kind](
            tbl, lo, ln, jnp.int32(chunk0), now, ch or self.CH
        )
        rows, live = jax.device_get((rows, live))
        return rows, live

    def _tier(self, n: int) -> int:
        return min(_ceil_pow2(max(n, 1), 256), self.CH)

    def expand(self, kind: str, args: Tuple, tbl, keys: np.ndarray, now):
        """Full budgeted expansion of ``keys`` over one view: yields
        (rows int32[n, w], already live-filtered) per chunk.  ``args``
        is the probe argument tuple (incl. the rows table); ``tbl`` the
        rows table the emission gathers from."""
        import jax
        import jax.numpy as jnp

        if keys.shape[0] == 0:
            return
        fused = self._hops_fused.get(kind)
        _mt.inc("lookup.hops")
        if fused is not None:
            faults.fire("lookup.dispatch")
            _mt.inc("lookup.dispatches")
            kp = self.pad_keys(keys)
            self._register_cost(
                f"hop:{kind}", fused,
                (args[0], args[1], args[2], tbl, kp,
                 now if hasattr(now, "dtype") else jnp.int32(now)),
                F=int(kp.shape[0]),
            )
            lo, ln, rows, live = fused(
                args[0], args[1], args[2], tbl, jnp.asarray(kp), now
            )
            ln_h, rows, live = jax.device_get((ln, rows, live))
            total = int(ln_h.sum())
            yield rows[live]
            at = self.CH0
        else:
            lo, ln, total = self.runs(kind, args, keys)
            at = 0
        while at < total:
            ch = self._tier(total - at)
            rows, live = self.emit(kind, tbl, lo, ln, at, now, ch)
            yield rows[live]
            at += ch


def kernels_for(engine, meta) -> FrontierKernels:
    cache = engine.__dict__.setdefault("_spmv_kernels", {})
    k = cache.get(meta)
    if k is None:
        k = FrontierKernels(meta, engine.config)
        while len(cache) >= 8:
            cache.pop(next(iter(cache)))
        cache[meta] = k
    return k


# ---------------------------------------------------------------------------
# per-snapshot frontier state (dense maps, table arg tuples)
# ---------------------------------------------------------------------------


def frontier_static_ok(meta, snap) -> bool:
    """The STATIC half of frontier eligibility — reverse index present
    and the seen-set bitmap domains fit budget.  Shared with the
    prewarm decision (engine/device.py): a snapshot failing this always
    walker-serves, so it wants the background transposed-index build."""
    if meta is None or not meta.has_rev:
        return False
    NS1 = meta.N * meta.S1
    NSr = meta.N * (max(snap.num_slots, 1) + 1)  # raw pair bitmap domain
    return max(NS1, NSr) <= _SEEN_BUDGET_BYTES * 8


def frontier_ok(engine, dsnap) -> bool:
    """Device frontier eligibility: the static half plus the
    per-revision conditions — no LSM delta level riding (the walker's
    advance machinery is the delta-exact path), and sharded snapshots
    only when the engine has the owner-routed hop path."""
    meta = dsnap.flat_meta
    if not frontier_static_ok(meta, dsnap.snapshot):
        return False
    if meta.delta is not None:
        return False
    if meta.sharded and not hasattr(engine, "lookup_hops_for"):
        return False
    return True


class FrontierState:
    """Per-DeviceSnapshot lookup server: dense slot maps, device table
    argument tuples, and the candidate-stream generators (cached on the
    snapshot via ``state_for``)."""

    def __init__(self, engine, dsnap) -> None:
        import jax.numpy as jnp

        self.engine = engine
        self.dsnap = dsnap
        self.meta = meta = dsnap.flat_meta
        self.kern = kernels_for(engine, meta)
        self.snap = snap = dsnap.snapshot
        self.N = meta.N
        self.S1 = meta.S1
        self.logN = self.N.bit_length() - 1
        from .flat import _dense_np

        self.k1d = _dense_np(meta.k1_dense)  # raw slot → dense k1 (-1 = none)
        self.k2d = _dense_np(meta.k2_dense)
        n_k1 = int(self.k1d.max()) + 1 if self.k1d.size else 0
        self.k1_raw = np.full(max(n_k1, 1), -1, np.int32)
        for raw, d in enumerate(self.k1d):
            if d >= 0:
                self.k1_raw[d] = raw
        # dense k1 slot → (dense k2 of the same raw slot) + 1; 0 = the
        # relation is never a userset target, so no membership-chain key
        self.k2p1_of_k1d = np.zeros(max(n_k1, 1), np.int64)
        for d in range(n_k1):
            raw = self.k1_raw[d]
            if raw >= 0 and self.k2d[raw] >= 0:
                self.k2p1_of_k1d[d] = int(self.k2d[raw]) + 1
        # -- schema-level type-safety pruning (the big frontier lever) --
        # a userset (t, r) can only ever BE a subject where the schema
        # declares ``t#r`` as an allowed subject form, and a node can
        # only be an arrow CHILD if its type is a declared direct
        # subject of some tupleset relation — so chain keys / reverse-
        # arrow probes for other (type, slot) combinations are
        # structurally dead and never reach the device.  Without this a
        # 100k-candidate hop probes 100k impossible keys (Zanzibar's
        # type safety, applied as frontier pruning)
        compiled = snap.compiled
        interner = snap.interner
        num_slots = max(compiled.num_slots, 1)
        n_types = max(interner.num_types, 1)
        self.chain_ok = np.zeros((n_types + 1, self.S1 + 1), bool)
        self.child_ok = np.zeros(n_types + 1, bool)
        self.slot_of_type = np.zeros((n_types + 1, num_slots), bool)
        tname_of_tid = {tid: t for t, tid in compiled.type_ids.items()}
        for tname, tid in compiled.type_ids.items():
            itid = interner.type_lookup(tname)
            ct = compiled.types[tid]
            if itid >= 0:
                self.slot_of_type[itid, sorted(ct.relations)] = True
            for slot, relation in ct.relations.items():
                is_ts = slot in compiled.tupleset_slots
                for a in relation.allowed:
                    a_itid = interner.type_lookup(tname_of_tid[a.type_id])
                    if a_itid < 0:
                        continue
                    if a.relation_slot >= 0:
                        d = self.k2d[a.relation_slot]
                        if d >= 0:
                            self.chain_ok[a_itid, d + 1] = True
                    elif is_ts:
                        self.child_ok[a_itid] = True
        # permission slots per interner type id, dense-k2 + declared-
        # subject-form filtered (the permission-userset chain)
        self.perm_chains = bool(compiled.has_permission_usersets)
        self.perm_k2p1_of_tid: Dict[int, np.ndarray] = {}
        tbl = np.zeros((n_types, num_slots), bool)
        for tname, d in compiled.schema.definitions.items():
            itid = interner.type_lookup(tname)
            if itid < 0:
                continue
            slots = sorted(compiled.slot_of_name[p] for p in d.permissions)
            if slots:
                tbl[itid, slots] = True
                k2p1 = np.asarray(
                    [self.k2d[s] + 1 for s in slots
                     if self.k2d[s] >= 0
                     and self.chain_ok[itid, self.k2d[s] + 1]],
                    np.int64,
                )
                if k2p1.size:
                    self.perm_k2p1_of_tid[itid] = k2p1
        self.perm_raw_table = tbl
        self.ts_slots = sorted(compiled.tupleset_slots)
        arrs = dsnap.arrays
        dummy = jnp.zeros(1, jnp.int32)

        def args_of(off_key):
            return (arrs[off_key], arrs.get(off_key + "_a", dummy))

        self.rv_args = args_of("rv_off") + (arrs["rvx"],)
        self.ra_args = args_of("ra_off") + (arrs["rax"],)
        self.fw_args = (
            args_of("fw_off") + (arrs["fwx"],) if meta.has_fw else None
        )
        al = {k for k, _w, _c in meta.aligned}
        if "argx" in al:
            from .flat import _al_key

            n_lv = len(dict((k, c) for k, _w, c in meta.aligned)["argx"])
            self.arg_args = tuple(arrs[_al_key("argx", l)] for l in range(n_lv))
            self.arg_aligned = True
        else:
            self.arg_args = args_of("arr_off") + (arrs["argx"],)
            self.arg_aligned = False
        self.arx = arrs["arx"]
        #: owner-routed hop backend for bucket-sharded stacked tables
        #: (parallel/sharded.py): each hop's frontier keys route to
        #: their owner shards, only owner-crossing IDs move
        self._hops = (
            engine.lookup_hops_for(dsnap, self.kern)
            if meta.sharded else None
        )
        #: wildcard-widening cache: sorted unique direct subjects
        self._all_subj: Optional[np.ndarray] = None
        #: fused K-hop SpMM server (engine/spmm.py): the whole frontier
        #: fixpoint in ONE pinned dispatch when eligible; None keeps the
        #: looped per-hop path below byte-for-byte (EngineConfig.spmm
        #: off, sharded snapshots, or oversized key domains)
        from . import spmm as _spmm_mod

        self._spmm = _spmm_mod.fused_for(engine, self)

    # -- expansion primitives --------------------------------------------
    def _now(self, now_us):
        import jax.numpy as jnp

        return jnp.int32(self.snap.now_rel32(now_us))

    def expand_rv(self, keys: np.ndarray, now):
        if self._hops is not None:
            return self._hops.expand("rv", keys, now)
        return self.kern.expand("rv", self.rv_args, self.rv_args[2],
                                keys, now)

    def expand_ra(self, keys: np.ndarray, now):
        if self._hops is not None:
            return self._hops.expand("ra", keys, now)
        return self.kern.expand("ra", self.ra_args, self.ra_args[2],
                                keys, now)

    def expand_fw(self, keys: np.ndarray, now):
        if self._hops is not None:
            return self._hops.expand("fw", keys, now)
        return self.kern.expand("fw", self.fw_args, self.fw_args[2],
                                keys, now)

    def expand_arrows_fwd(self, keys: np.ndarray, now):
        """Forward tupleset traversal over the EXISTING argx/arx view."""
        if keys.shape[0] == 0:
            return iter(())
        if self._hops is not None:
            return self._hops.expand("arg", keys, now)
        lo, ln, total = self.kern.runs("arg", self.arg_args, keys)
        _mt.inc("lookup.hops")

        def gen():
            at = 0
            while at < total:
                rows, live = self.kern.emit("arg", self.arx, lo, ln, at, now)
                yield rows[live]
                at += self.kern.CH

        return gen()

    def node_type_of(self, nodes: np.ndarray) -> np.ndarray:
        nt = self.snap.node_type
        out = np.full(nodes.shape[0], -1, np.int64)
        ok = (nodes >= 0) & (nodes < nt.shape[0])
        out[ok] = nt[nodes[ok]]
        return out

    def all_subjects(self) -> np.ndarray:
        if self._all_subj is None:
            self._all_subj = np.unique(self.snap.e_subj).astype(np.int64)
        return self._all_subj

    # -- LookupResources candidate stream --------------------------------
    def resource_candidates(
        self, rtid: int, subj_node: int, srel_slot: int, wc_node: int,
        now_us: Optional[int],
    ) -> Iterator[np.ndarray]:
        """Deterministic stream of candidate resource-node blocks — the
        walker's reverse worklist, each hop one masked SpMV over the
        reverse tables.  Soundness: every DEFINITE grant has a live,
        resolvable positive edge path; the in-kernel gate filter drops
        only edges that can never be part of one.

        With the fused SpMM core (engine/spmm.py) the whole fixpoint
        runs in ONE device dispatch; overflow (frontier/emission/
        candidate capacity, round budget) falls back to the looped
        per-hop body below, which is also the streaming path big
        answers want."""
        if self._spmm is not None:
            blocks = self._spmm.resources(
                rtid, subj_node, srel_slot, wc_node, now_us
            )
            if blocks is not None:
                for b in blocks:
                    if b.size:
                        _mt.inc("lookup.candidates", b.size)
                        yield b
                return
            _mt.inc("spmm.fallbacks")
        N, S1, logN = self.N, self.S1, self.logN
        now = self._now(now_us)
        seen_keys = _Seen(N * S1)
        seen_nodes = _Seen(N)
        nt_shape = self.snap.node_type.shape[0]

        seeds: List[np.ndarray] = []
        if 0 <= subj_node < N:
            if srel_slot < 0:
                seeds.append(np.asarray([subj_node * S1], np.int64))
            elif self.k2d[srel_slot] >= 0:
                seeds.append(np.asarray(
                    [subj_node * S1 + int(self.k2d[srel_slot]) + 1], np.int64
                ))
        if 0 <= wc_node < N:
            seeds.append(np.asarray([wc_node * S1], np.int64))
        # self-identity: the subject node itself may be the resource
        first_nodes = (
            np.asarray([subj_node], np.int64)
            if 0 <= subj_node < nt_shape else np.empty(0, np.int64)
        )
        first_nodes = seen_nodes.fresh(first_nodes)
        if first_nodes.size:
            cand = first_nodes[self.node_type_of(first_nodes) == rtid]
            if cand.size:
                _mt.inc("lookup.candidates", cand.size)
                yield cand
        frontier = seen_keys.fresh(
            np.concatenate(seeds) if seeds else np.empty(0, np.int64)
        )
        while frontier.size:
            new_keys: List[np.ndarray] = []
            node_parts: List[np.ndarray] = []
            for rows in self.expand_rv(frontier.astype(np.int32), now):
                if rows.shape[0] == 0:
                    continue
                k1 = rows[:, 1].astype(np.int64)
                res = k1 & (N - 1)
                slotd = k1 >> logN
                node_parts.append(res)
                # granted usersets continue the membership chain — only
                # where the schema declares (type(res), rel) a legal
                # subject form (type-safety pruning: everything else is
                # structurally dead and never probes)
                nk = self.k2p1_of_k1d[slotd]
                chain = (nk > 0) & self.chain_ok[
                    self.node_type_of(res), np.maximum(nk, 0)
                ]
                if chain.any():
                    new_keys.append(res[chain] * S1 + nk[chain])
            nodes = seen_nodes.fresh(
                np.concatenate(node_parts)
                if node_parts else np.empty(0, np.int64)
            )
            # close candidates under reverse arrows (parents granting
            # through tupleset traversal) — device hops over rax
            while nodes.size:
                cand = nodes[self.node_type_of(nodes) == rtid]
                if cand.size:
                    _mt.inc("lookup.candidates", cand.size)
                    yield cand
                if self.perm_chains:
                    tids = self.node_type_of(nodes)
                    for t in np.unique(tids):
                        k2p1 = self.perm_k2p1_of_tid.get(int(t))
                        if k2p1 is None:
                            continue
                        nn = nodes[tids == t]
                        new_keys.append(
                            (nn[:, None] * S1 + k2p1[None, :]).ravel()
                        )
                # only declared arrow-child types can have parents
                ch = nodes[self.child_ok[self.node_type_of(nodes)]]
                parent_parts = [
                    rows[:, 1].astype(np.int64) & (N - 1)
                    for rows in self.expand_ra(ch.astype(np.int32), now)
                    if rows.shape[0]
                ]
                nodes = seen_nodes.fresh(
                    np.concatenate(parent_parts)
                    if parent_parts else np.empty(0, np.int64)
                )
            frontier = seen_keys.fresh(
                np.concatenate(new_keys)
                if new_keys else np.empty(0, np.int64)
            )

    # -- LookupSubjects candidate stream ---------------------------------
    def subject_candidates(
        self, res_node: int, stid: int, srel_slot: int, wc_node: int,
        now_us: Optional[int],
    ) -> Iterator[np.ndarray]:
        """Forward frontier expansion from the resource over the fw/argx
        views — the walker's node/pair worklist as device hops (or ONE
        fused SpMM dispatch, overflow falling back here)."""
        if self._spmm is not None:
            blocks = self._spmm.subjects(
                res_node, stid, srel_slot, wc_node, now_us
            )
            if blocks is not None:
                for b in blocks:
                    if b.size:
                        _mt.inc("lookup.candidates", b.size)
                        yield b
                return
            _mt.inc("spmm.fallbacks")
        N, S1, logN = self.N, self.S1, self.logN
        snap = self.snap
        num_slots = max(snap.num_slots, 1)
        now = self._now(now_us)
        seen_nodes = _Seen(N)
        seen_pairs = _Seen(N * (num_slots + 1))
        seen_cand = _Seen(N)
        pair_list: List[np.ndarray] = []  # raw (g·NS + r) pairs, for srel
        wildcard_found = [False]
        # dense k2 value+1 → raw slot (decoding emitted userset subjects)
        k2p1_raw = np.full(S1 + 1, -1, np.int64)
        for raw, d in enumerate(self.k2d):
            if d >= 0:
                k2p1_raw[d + 1] = raw
        e_slot_raw = np.asarray(
            [s for s in self.meta.e_slots if self.k1d[s] >= 0], np.int64
        )
        e_slot_k1d = self.k1d[e_slot_raw].astype(np.int64)
        ts_raw = np.asarray(
            [s for s in self.ts_slots if self.k1d[s] >= 0], np.int64
        )
        ts_k1d = self.k1d[ts_raw].astype(np.int64)

        def absorb(k2vals: np.ndarray):
            """Emitted subject keys → (direct candidate block or None,
            new raw pairs)."""
            direct = k2vals % S1 == 0
            dn = k2vals[direct] // S1
            cand = None
            if srel_slot < 0 and dn.size:
                fresh = seen_cand.fresh(dn[self.node_type_of(dn) == stid])
                cand = fresh if fresh.size else None
            if (
                wc_node >= 0 and not wildcard_found[0]
                and dn.size and bool(np.any(dn == wc_node))
            ):
                wildcard_found[0] = True
            um = ~direct
            g = k2vals[um] // S1
            r = k2p1_raw[k2vals[um] % S1]
            pairs = g * (num_slots + 1) + r  # r ≥ 0: emitted userset rows
            return cand, pairs

        def fw_keys_of_nodes(nodes: np.ndarray) -> np.ndarray:
            if nodes.size == 0 or e_slot_k1d.size == 0:
                return np.empty(0, np.int64)
            # type-safety pruning: only (slot, node) pairs where the
            # node's type declares the relation can have edges
            ok = self.slot_of_type[
                self.node_type_of(nodes)[:, None], e_slot_raw[None, :]
            ]
            kk = nodes[:, None] + (e_slot_k1d[None, :] * N)
            return kk[ok].ravel()

        node_frontier = seen_nodes.fresh(
            np.asarray([res_node], np.int64)
            if 0 <= res_node < N else np.empty(0, np.int64)
        )
        pair_frontier = np.empty(0, np.int64)
        pending_nodes: List[np.ndarray] = []
        while node_frontier.size or pair_frontier.size:
            new_pairs: List[np.ndarray] = []
            if node_frontier.size:
                # arrow closure of the frontier, then every edge off it
                fresh_all: List[np.ndarray] = [node_frontier]
                cur = node_frontier
                while cur.size and ts_k1d.size:
                    tok = self.slot_of_type[
                        self.node_type_of(cur)[:, None], ts_raw[None, :]
                    ]
                    keys = (cur[:, None] + ts_k1d[None, :] * N)[tok].ravel()
                    child_parts = [
                        rows[:, 0].astype(np.int64)
                        for rows in self.expand_arrows_fwd(
                            keys.astype(np.int32), now
                        )
                        if rows.shape[0]
                    ]
                    cur = seen_nodes.fresh(
                        np.concatenate(child_parts)
                        if child_parts else np.empty(0, np.int64)
                    )
                    if cur.size:
                        fresh_all.append(cur)
                nodes = np.concatenate(fresh_all)
                for rows in self.expand_fw(
                    fw_keys_of_nodes(nodes).astype(np.int32), now
                ):
                    if rows.shape[0] == 0:
                        continue
                    cand, pairs = absorb(rows[:, 1].astype(np.int64))
                    if cand is not None:
                        _mt.inc("lookup.candidates", cand.size)
                        yield cand
                    if pairs.size:
                        new_pairs.append(pairs)
            if pair_frontier.size:
                g = pair_frontier // (num_slots + 1)
                r = pair_frontier % (num_slots + 1)
                tids = self.node_type_of(g)
                ok_t = (tids >= 0) & (r < num_slots)
                is_perm = np.zeros(g.shape[0], bool)
                if self.perm_raw_table is not None:
                    is_perm[ok_t] = self.perm_raw_table[
                        tids[ok_t], r[ok_t]
                    ]
                # permission pairs: holders of g#p ⊆ expansion of g
                pending_nodes.append(g[is_perm])
                rel_g, rel_r = g[~is_perm], r[~is_perm]
                kd = self.k1d[np.clip(rel_r, 0, self.k1d.shape[0] - 1)]
                okk = (kd >= 0) & (rel_r < self.k1d.shape[0])
                keys = kd[okk] * N + rel_g[okk]
                for rows in self.expand_fw(keys.astype(np.int32), now):
                    if rows.shape[0] == 0:
                        continue
                    cand, pairs = absorb(rows[:, 1].astype(np.int64))
                    if cand is not None:
                        _mt.inc("lookup.candidates", cand.size)
                        yield cand
                    if pairs.size:
                        new_pairs.append(pairs)
            pair_frontier = seen_pairs.fresh(
                np.concatenate(new_pairs)
                if new_pairs else np.empty(0, np.int64)
            )
            if pair_frontier.size:
                pair_list.append(pair_frontier)
            node_frontier = seen_nodes.fresh(
                np.concatenate(pending_nodes)
                if pending_nodes else np.empty(0, np.int64)
            )
            pending_nodes = []

        # trailing blocks, same order as the walker's tail
        if srel_slot >= 0 and pair_list:
            allp = np.concatenate(pair_list)
            gs = allp[allp % (num_slots + 1) == srel_slot] // (num_slots + 1)
            cand = seen_cand.fresh(gs[self.node_type_of(gs) == stid])
            if cand.size:
                _mt.inc("lookup.candidates", cand.size)
                yield cand
        if 0 <= res_node and self.node_type_of(
            np.asarray([res_node], np.int64)
        )[0] == stid:
            cand = seen_cand.fresh(np.asarray([res_node], np.int64))
            if cand.size:
                yield cand
        if wildcard_found[0] and srel_slot < 0:
            subs = self.all_subjects()
            cand = seen_cand.fresh(subs[self.node_type_of(subs) == stid])
            if cand.size:
                _mt.inc("lookup.candidates", cand.size)
                yield cand


def state_for(engine, dsnap) -> FrontierState:
    st = dsnap.__dict__.get("_frontier_state")
    if st is None or st.engine is not engine:
        st = FrontierState(engine, dsnap)
        dsnap.__dict__["_frontier_state"] = st
    return st


# ---------------------------------------------------------------------------
# cursor-paginated result streaming (shared by frontier + walker paths)
# ---------------------------------------------------------------------------


class _ResultStream:
    """A lookup's granted-result stream: candidate blocks → exact filter
    → result ids, with the emitted-count bookkeeping cursors resume on."""

    def __init__(self, cand_iter: Iterator[np.ndarray],
                 filter_fn: Callable[[np.ndarray], List[int]],
                 id_of: Callable[[int], str],
                 cost_bytes: int = 1 << 20) -> None:
        self._cands = cand_iter
        self._filter = filter_fn
        self._id_of = id_of
        self._pending: List[str] = []
        self.emitted = 0
        self.exhausted = False
        #: estimated held host bytes (frontier seen-set bitmaps dominate)
        #: — paginate's cache evicts by this, not just count
        self.cost_bytes = int(cost_bytes)

    def take(self, n: int) -> List[str]:
        out: List[str] = []
        while len(out) < n:
            if self._pending:
                k = min(n - len(out), len(self._pending))
                out.extend(self._pending[:k])
                del self._pending[:k]
                continue
            block = next(self._cands, None)
            if block is None:
                self.exhausted = True
                break
            if block.size == 0:
                continue
            granted = self._filter(block)
            self._pending.extend(self._id_of(int(g)) for g in granted)
        self.emitted += len(out)
        return out

    def skip(self, n: int) -> None:
        while n > 0:
            got = self.take(min(n, 4096))
            n -= len(got)
            if self.exhausted and not self._pending and not got:
                break


#: byte budget for cached live continuations per DeviceSnapshot: a big
#: world's stream holds seen-set bitmaps (up to _SEEN_BUDGET_BYTES
#: each), so eviction is by ESTIMATED bytes, with the count cap as the
#: small-stream backstop
_STREAM_CACHE_BYTES = 256 << 20


def paginate(
    dsnap,
    token: str,
    make_stream: Callable[[], _ResultStream],
    page_size: int,
    cursor: Optional[LookupCursor],
    now_us: Optional[int] = None,
) -> Tuple[List[str], Optional[LookupCursor]]:
    """One page of results with exact resume semantics.  The live stream
    is cached on the DeviceSnapshot keyed by ``token``; an evicted or
    cross-process resume deterministically recomputes and skips
    ``cursor.pos`` results.  ``now_us`` (already resolved via
    resolve_now_us) rides the returned cursor so the recompute is
    evaluated at the same instant."""
    from ..utils.errors import PreconditionFailedError

    cache: Dict[str, _ResultStream] = dsnap.__dict__.setdefault(
        "_lookup_streams", {}
    )
    pos = 0
    if cursor is not None:
        if cursor.token != token:
            raise PreconditionFailedError(
                "lookup cursor does not match this query"
            )
        if cursor.revision != dsnap.revision:
            raise PreconditionFailedError(
                f"lookup cursor pinned to revision {cursor.revision}, "
                f"snapshot is at {dsnap.revision}"
            )
        pos = cursor.pos
    stream = cache.pop(token, None)
    if stream is None or stream.emitted != pos:
        stream = make_stream()
        _mt.inc("lookup.stream_recomputes" if pos else "lookup.streams")
        stream.skip(pos)
    ids = stream.take(page_size)
    done = stream.exhausted and not stream._pending
    nxt = None
    if not done:
        nxt = LookupCursor(dsnap.revision, token, stream.emitted, now_us)
        cache[token] = stream
        while len(cache) > _STREAM_CACHE_MAX or (
            len(cache) > 1
            and sum(s.cost_bytes for s in cache.values())
            > _STREAM_CACHE_BYTES
        ):
            cache.pop(next(iter(cache)))
    return ids, nxt

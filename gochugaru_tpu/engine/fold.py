"""Permission folding (P-index): whole union-of-{leaf, arrow-chain}
rewrites flattened into root-level probe tables at prepare time.

The flat kernel (engine/flat.py) removed per-query *loops*; this layer
removes per-query *levels*.  A `document#view = viewer + folder->view`
check still walks the doc→folder→…→root lattice at trace time, paying an
e-probe + T-probe + arrow-range per level — ~20 dependent gathers into
multi-GB tables for BASELINE config 3's 5-hop world.  Folding joins the
rewrite's arrow chains into the leaf rows once per revision, so the same
check is ONE direct-identity probe (pf_e) plus ONE membership probe
(pf_t), regardless of depth — the full Leopard construction: resource-
side ancestor flattening ⋈ userset edges ⋈ the member closure
(store/closure.py), with expiries folded along paths through the same
max-min two-plane semiring.

Eligibility is per (type, permission): the program must be a union tree
over relation leaves, same-type folded permissions, and arrows through
caveat-free tuplesets whose targets are relations or already-folded
permissions (self-recursive hierarchies go through the ancestor closure
of engine/flat.py:_arrow_closure; mutual cross-type recursion stays on
the walked path).  Direct rows keep their caveat/ctx columns (the CEL VM
gates them at the probe site); userset rows under the fold must be
caveat-free and not permission-valued — the same bar the T-index sets.

Folded tables serve BASE data only.  A Watch-delta level rides on the
unfolded walk (engine/flat.py compiles the full program when a delta is
present), which keeps add/tombstone semantics exact without Leopard's
incremental-maintenance machinery; compaction re-folds.

Replaces the server-side evaluation behind the reference's
CheckBulkPermissions (/root/reference/client/client.go:238-266) for the
deep-nesting worlds where the walked kernel was 20× off its target.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..schema.compiler import CompiledSchema
from ..store.closure import NO_EXP, _expand_join
from .plan import DevicePlan, EngineConfig, ExprIR


@dataclass
class _Rows:
    """Folded rows of one (type, permission): direct-identity rows (the
    pf_e side; caveats ride along) and userset rows (the pf_t side;
    caveat-free by eligibility).  ``until`` is epoch-relative seconds
    with NO_EXP = never expires — the min over the path's arrow/leaf
    expiries."""

    e_res: np.ndarray
    e_k2: np.ndarray
    e_cav: np.ndarray
    e_ctx: np.ndarray
    e_until: np.ndarray
    u_res: np.ndarray
    u_subj: np.ndarray
    u_srel: np.ndarray
    u_until: np.ndarray

    @property
    def total(self) -> int:
        return int(self.e_res.shape[0] + self.u_res.shape[0])


def _empty_rows() -> _Rows:
    z = np.zeros(0, np.int32)
    return _Rows(z, z, z, z, z, z, z, z, z)


def _concat_rows(parts: List[_Rows]) -> _Rows:
    if not parts:
        return _empty_rows()
    return _Rows(*(
        np.concatenate([getattr(p, f) for p in parts])
        for f in ("e_res", "e_k2", "e_cav", "e_ctx", "e_until",
                  "u_res", "u_subj", "u_srel", "u_until")
    ))


def _until_of(exp: np.ndarray) -> np.ndarray:
    return np.where(exp == 0, np.int64(NO_EXP), exp.astype(np.int64)).astype(
        np.int32
    )


def _dedup_rows(r: _Rows) -> _Rows:
    """Max-until dedup per identity: folding through multiple paths keeps
    the most permissive admissibility, exactly like the closure's
    group_max."""
    if r.e_res.shape[0]:
        o = np.lexsort((r.e_ctx, r.e_cav, r.e_k2, r.e_res))
        er, ek, ec, ex, eu = (
            r.e_res[o], r.e_k2[o], r.e_cav[o], r.e_ctx[o], r.e_until[o]
        )
        first = np.ones(er.shape[0], bool)
        first[1:] = (
            (er[1:] != er[:-1]) | (ek[1:] != ek[:-1])
            | (ec[1:] != ec[:-1]) | (ex[1:] != ex[:-1])
        )
        st = np.nonzero(first)[0]
        er, ek, ec, ex = er[first], ek[first], ec[first], ex[first]
        eu = np.maximum.reduceat(eu, st)
    else:
        er, ek, ec, ex, eu = (r.e_res,) * 5
    if r.u_res.shape[0]:
        o = np.lexsort((r.u_srel, r.u_subj, r.u_res))
        ur, us, ul, uu = r.u_res[o], r.u_subj[o], r.u_srel[o], r.u_until[o]
        first = np.ones(ur.shape[0], bool)
        first[1:] = (
            (ur[1:] != ur[:-1]) | (us[1:] != us[:-1]) | (ul[1:] != ul[:-1])
        )
        st = np.nonzero(first)[0]
        ur, us, ul = ur[first], us[first], ul[first]
        uu = np.maximum.reduceat(uu, st)
    else:
        ur, us, ul, uu = (r.u_res,) * 4
    return _Rows(er, ek, ec, ex, eu, ur, us, ul, uu)


def _lift(rows: _Rows, src: np.ndarray, dst: np.ndarray,
          p_until: np.ndarray) -> _Rows:
    """Re-key ``rows`` through join pairs (src → dst): every row at
    res == dst lifts to res = src with until min'd against the pair's
    path admissibility.  Both row sets must be sorted by res."""
    out_parts: List[_Rows] = []
    if rows.e_res.shape[0] and src.shape[0]:
        reps, ii = _expand_join(rows.e_res, dst)
        if reps.shape[0]:
            out_parts.append(_Rows(
                src[reps], rows.e_k2[ii], rows.e_cav[ii], rows.e_ctx[ii],
                np.minimum(rows.e_until[ii], p_until[reps]),
                *(np.zeros(0, np.int32),) * 4,
            ))
    if rows.u_res.shape[0] and src.shape[0]:
        reps, ii = _expand_join(rows.u_res, dst)
        if reps.shape[0]:
            out_parts.append(_Rows(
                *(np.zeros(0, np.int32),) * 5,
                src[reps], rows.u_subj[ii], rows.u_srel[ii],
                np.minimum(rows.u_until[ii], p_until[reps]),
            ))
    return _concat_rows(out_parts)


def _sorted_by_res(r: _Rows) -> _Rows:
    oe = np.argsort(r.e_res, kind="stable")
    ou = np.argsort(r.u_res, kind="stable")
    return _Rows(
        r.e_res[oe], r.e_k2[oe], r.e_cav[oe], r.e_ctx[oe], r.e_until[oe],
        r.u_res[ou], r.u_subj[ou], r.u_srel[ou], r.u_until[ou],
    )


@dataclass
class FoldResult:
    """Folded rows keyed ready for table build: pf_e identity rows and
    pf_u userset rows, both carrying the owning permission slot."""

    e_slot: np.ndarray
    e_res: np.ndarray
    e_k2: np.ndarray
    e_cav: np.ndarray
    e_ctx: np.ndarray
    e_until: np.ndarray
    u_slot: np.ndarray
    u_res: np.ndarray
    u_subj: np.ndarray
    u_srel: np.ndarray
    u_until: np.ndarray
    #: the folded (type_name, perm_slot) pairs — the kernel skips these
    #: programs when no delta level is present
    pairs: Tuple[Tuple[str, int], ...]


def _union_leaves(expr: ExprIR) -> Optional[List[ExprIR]]:
    """Flatten a union tree to its leaves; None when the tree contains
    intersection/exclusion (ineligible for folding)."""
    tag = expr[0]
    if tag == "union":
        out: List[ExprIR] = []
        for c in expr[1]:
            got = _union_leaves(c)
            if got is None:
                return None
            out.extend(got)
        return out
    if tag in ("ref", "arrow", "nil"):
        return [expr]
    return None


def fold_permissions(
    snap, config: EngineConfig, plan: DevicePlan, cl
) -> Optional[FoldResult]:
    """Fold every eligible (type, permission) of the snapshot's schema.
    Returns None when folding is disabled, inapplicable, or over budget
    (the walked kernel answers those worlds exactly as before)."""
    if not config.flat_fold or not plan.topo_programs:
        return None
    if cl.ovf_src.shape[0]:
        # overflowed closure sources make the T-side incomplete; the
        # walked path flags affected queries per site — folding can't
        return None
    compiled: CompiledSchema = snap.compiled
    S1 = snap.num_slots + 1

    # slot-granular userset eligibility, the T-index's bar: caveated /
    # permission-valued rows and rows whose group may extend through a
    # permission chain (pus) can't fold into an until-only table
    bad_us = (snap.us_caveat != 0) | (snap.us_perm != 0)
    if snap.pus_n.shape[0]:
        pus_k = np.sort(snap.pus_n.astype(np.int64) * S1 + snap.pus_r + 1)
        uk = snap.us_subj.astype(np.int64) * S1 + snap.us_srel + 1
        pos = np.clip(np.searchsorted(pus_k, uk), 0, pus_k.shape[0] - 1)
        bad_us |= pus_k[pos] == uk
    bad_rel_slots = set(np.unique(snap.us_rel[bad_us]).tolist())
    cav_ts_slots = set(np.unique(snap.ar_rel[snap.ar_caveat != 0]).tolist())

    # interner type id per schema type (node_type holds interner ids)
    itid: Dict[str, int] = {
        t: snap.interner.type_lookup(t) for t in compiled.type_ids
    }
    ntype = snap.node_type
    e_type = ntype[np.clip(snap.e_res, 0, max(snap.num_nodes - 1, 0))]
    us_type = ntype[np.clip(snap.us_res, 0, max(snap.num_nodes - 1, 0))]
    ar_type = ntype[np.clip(snap.ar_res, 0, max(snap.num_nodes - 1, 0))]
    ar_ctype = ntype[np.clip(snap.ar_child, 0, max(snap.num_nodes - 1, 0))]

    rel_leaf = frozenset(plan.rel_leaf_slots)
    budget = config.flat_fold_factor * max(
        int(snap.e_rel.shape[0] + snap.us_rel.shape[0]), 4096
    )
    spent = 0

    def leaf_rows(tname: str, rel_slot: int) -> Optional[_Rows]:
        if rel_slot in bad_rel_slots:
            return None
        tid = itid[tname]
        m = (snap.e_rel == rel_slot) & (e_type == tid)
        # RAW int64 identity key (subj·(num_slots+1)+srel1): internal to
        # the fold, immune to the int32 packing cliff — build_flat_arrays
        # decomposes and repacks with the dense radices
        e_k2 = snap.e_subj[m].astype(np.int64) * S1 + snap.e_srel1[m]
        mu = (snap.us_rel == rel_slot) & (us_type == tid)
        return _Rows(
            snap.e_res[m], e_k2, snap.e_caveat[m], snap.e_ctx[m],
            _until_of(snap.e_exp[m]),
            snap.us_res[mu], snap.us_subj[mu], snap.us_srel[mu],
            _until_of(snap.us_exp[mu]),
        )

    def arrow_pairs(tname: str, ts_slot: int):
        """(src, dst, p_until) arrow rows of ``tname`` under ``ts_slot``,
        sorted by dst for _lift."""
        m = (snap.ar_rel == ts_slot) & (ar_type == itid[tname]) & (
            snap.ar_child >= 0
        )
        src, dst = snap.ar_res[m], snap.ar_child[m]
        p_until = _until_of(snap.ar_exp[m])
        o = np.argsort(dst, kind="stable")
        return src[o], dst[o], p_until[o]

    folded: Dict[Tuple[str, int], _Rows] = {}
    name_of_slot = compiled.name_of_slot

    for (tname, tid, slot, expr) in plan.topo_programs:
        leaves = _union_leaves(expr)
        if leaves is None:
            continue
        ct = compiled.types[compiled.type_ids[tname]]
        tid_i = itid[tname]
        parts: List[_Rows] = []
        self_ts: Optional[int] = None
        ok = True
        for child in leaves:
            tag = child[0]
            if tag == "nil":
                continue
            if tag == "ref":
                # slots are per-NAME: the same slot can be a relation on
                # one type and a permission on another — resolve against
                # THIS type's definition
                s = child[1]
                sname = name_of_slot.get(s, "")
                if sname in compiled.schema.definitions[tname].relations:
                    got = leaf_rows(tname, s)
                elif (tname, s) in folded:
                    got = folded[(tname, s)]
                else:
                    got = None
                if got is None:
                    ok = False
                    break
                parts.append(got)
                continue
            # arrow
            ts_slot = plan.ts_slots[child[1]]
            right = child[2]
            if ts_slot in cav_ts_slots:
                ok = False
                break
            relation = ct.relations.get(ts_slot)
            if relation is None:
                continue  # no such tupleset on this type: contributes ∅
            if any(a.relation_slot >= 0 or a.wildcard for a in relation.allowed):
                # arrows traverse direct subjects only; userset/wildcard
                # tupleset subjects keep the walked path
                ok = False
                break
            child_types = {ct2 for a in relation.allowed
                           for ct2 in (compiled.types[a.type_id].name,)}
            if right == slot and child_types == {tname}:
                if self_ts is not None and self_ts != ts_slot:
                    ok = False  # two distinct self-recursive tuplesets
                    break
                self_ts = ts_slot
                continue
            src, dst, p_until = arrow_pairs(tname, ts_slot)
            for c_t in sorted(child_types):
                c_has_rel = (
                    right in rel_leaf
                    and name_of_slot.get(right)
                    in compiled.schema.definitions[c_t].relations
                )
                if c_has_rel:
                    got = leaf_rows(c_t, right)
                elif (c_t, right) in folded:
                    got = folded[(c_t, right)]
                elif compiled.schema.definitions[c_t].item(
                    name_of_slot.get(right, "")
                ) is None:
                    continue  # child type lacks the item: contributes ∅
                else:
                    got = None
                if got is None:
                    ok = False
                    break
                parts.append(_lift(_sorted_by_res(got), src, dst, p_until))
            if not ok:
                break
        if not ok:
            continue
        rows = _dedup_rows(_concat_rows(parts))
        if self_ts is not None:
            from .flat import _arrow_closure  # deferred: flat imports us

            built = _arrow_closure(snap, self_ts)
            if built is None:
                continue  # data cycle / over cap: keep the walked path
            c_src, c_anc, c_d, _c_p = built  # cav-free ts ⇒ d == p
            # slots are per-NAME: the closure selects by slot only, so
            # another type sharing the tupleset name contributes pairs
            # whose SOURCE is not this type — drop them, or folded grants
            # would leak onto that type's resources under this perm slot
            tm = ntype[np.clip(c_src, 0, max(snap.num_nodes - 1, 0))] == tid_i
            c_src, c_anc, c_d = c_src[tm], c_anc[tm], c_d[tm]
            o = np.argsort(c_anc, kind="stable")
            rows = _dedup_rows(_concat_rows([
                rows, _lift(_sorted_by_res(rows), c_src[o], c_anc[o], c_d[o]),
            ]))
        if spent + rows.total > budget:
            continue  # over budget: this pair stays on the walked path
        spent += rows.total
        folded[(tname, slot)] = rows

    if not folded:
        return None
    pairs = tuple(sorted(folded))
    return FoldResult(
        e_slot=np.concatenate([
            np.full(folded[p].e_res.shape[0], p[1], np.int32) for p in pairs
        ]),
        e_res=np.concatenate([folded[p].e_res for p in pairs]),
        e_k2=np.concatenate([folded[p].e_k2 for p in pairs]),
        e_cav=np.concatenate([folded[p].e_cav for p in pairs]),
        e_ctx=np.concatenate([folded[p].e_ctx for p in pairs]),
        e_until=np.concatenate([folded[p].e_until for p in pairs]),
        u_slot=np.concatenate([
            np.full(folded[p].u_res.shape[0], p[1], np.int32) for p in pairs
        ]),
        u_res=np.concatenate([folded[p].u_res for p in pairs]),
        u_subj=np.concatenate([folded[p].u_subj for p in pairs]),
        u_srel=np.concatenate([folded[p].u_srel for p in pairs]),
        u_until=np.concatenate([folded[p].u_until for p in pairs]),
        pairs=pairs,
    )


def t_join_core(
    k1: np.ndarray, pe: np.ndarray, w: np.ndarray,
    cl_k1: np.ndarray, cl_k2: np.ndarray,
    c_d: np.ndarray, c_p: np.ndarray, cap_rows: int,
) -> Optional[Tuple[np.ndarray, ...]]:
    """The T-index join shared by the base table (flat.py _tindex_join)
    and the fold (fold_tindex_join): userset entries (k1, group-key pe,
    until w) ⋈ closure-by-target, plus the direct group-identity entries,
    deduped max-per-plane.  Sizes the join BEFORE materializing it;
    returns None past ``cap_rows`` (a popular group with a huge closure
    in-degree must disable the index, not OOM)."""
    t_order = np.argsort(cl_k2, kind="stable")
    tgt_sorted = cl_k2[t_order]
    join_rows = int(
        (
            np.searchsorted(tgt_sorted, pe, "right")
            - np.searchsorted(tgt_sorted, pe, "left")
        ).sum()
    )
    if join_rows + pe.shape[0] > cap_rows:
        return None
    reps, ii = _expand_join(tgt_sorted, pe)
    jj = t_order[ii]
    T_k1 = np.concatenate([k1, k1[reps]])
    T_k2 = np.concatenate([pe, cl_k1[jj]])
    T_d = np.concatenate([w, np.minimum(w[reps], c_d[jj])])
    T_p = np.concatenate([w, np.minimum(w[reps], c_p[jj])])
    o2 = np.lexsort((T_k2, T_k1))
    T_k1, T_k2, T_d, T_p = T_k1[o2], T_k2[o2], T_d[o2], T_p[o2]
    first = np.ones(T_k1.shape[0], bool)
    first[1:] = (T_k1[1:] != T_k1[:-1]) | (T_k2[1:] != T_k2[:-1])
    st = np.nonzero(first)[0]
    return (
        T_k1[first], T_k2[first],
        np.maximum.reduceat(T_d, st), np.maximum.reduceat(T_p, st),
    )


def fold_tindex_join(fr: FoldResult, cl, N: int, maps,
                     factor: int) -> Optional[Tuple[np.ndarray, ...]]:
    """pf_t: folded userset rows ⋈ closure-by-target, plus the direct
    group-identity entries — the T-index join over the FOLDED rows,
    packed with the DENSE radices (``maps`` is flat.SlotMaps).  Returns
    (k1, k2, d_until, p_until) or None when over budget (the caller then
    drops folding; the walk still answers)."""
    if fr.u_res.shape[0] == 0:
        z = np.zeros(0, np.int32)
        return z, z, z, z
    from .flat import _m_srel1  # deferred: flat imports us lazily too

    S1 = maps.S1
    k1 = (
        maps.k1[fr.u_slot].astype(np.int64) * N + fr.u_res
    ).astype(np.int32)
    pe = (
        fr.u_subj.astype(np.int64) * S1 + maps.k2[fr.u_srel] + 1
    ).astype(np.int32)
    cl_k1 = (
        cl.c_src.astype(np.int64) * S1 + _m_srel1(maps, cl.c_srel1)
    ).astype(np.int32)
    cl_k2 = (
        cl.c_g.astype(np.int64) * S1 + maps.k2[cl.c_grel] + 1
    ).astype(np.int32)
    return t_join_core(
        k1, pe, fr.u_until, cl_k1, cl_k2, cl.c_d_until, cl.c_p_until,
        factor * max(int(pe.shape[0]), 1024),
    )

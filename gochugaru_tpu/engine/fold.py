"""Permission folding (P-index): whole union-of-{leaf, arrow-chain}
rewrites flattened into root-level probe tables at prepare time.

The flat kernel (engine/flat.py) removed per-query *loops*; this layer
removes per-query *levels*.  A `document#view = viewer + folder->view`
check still walks the doc→folder→…→root lattice at trace time, paying an
e-probe + T-probe + arrow-range per level — ~20 dependent gathers into
multi-GB tables for BASELINE config 3's 5-hop world.  Folding joins the
rewrite's arrow chains into the leaf rows once per revision, so the same
check is ONE direct-identity probe (pf_e) plus ONE bounded-fan userset
slice (pf_u) intersected with the member closure at probe time,
regardless of depth — the Leopard construction with the member
expansion FACTORED OUT: resource-side ancestor flattening ⋈ userset
edges stays precomputed, and the closure (store/closure.py) is probed
per candidate group instead of being joined in (the round-5 dense
T-join materialized resource × member and regressed config 3; see
fold_userset_rows).  Expiries fold along paths through the same max-min
two-plane semiring.

Eligibility is per (type, permission): the program must be a union tree
over relation leaves, same-type folded permissions, and arrows through
caveat-free tuplesets whose targets are relations or already-folded
permissions (self-recursive hierarchies go through the ancestor closure
of engine/flat.py:_arrow_closure; mutual cross-type recursion stays on
the walked path).  Direct rows keep their caveat/ctx columns (the CEL VM
gates them at the probe site); userset rows under the fold must be
caveat-free and not permission-valued — the same bar the T-index sets.

Watch-delta levels ride the fold INCREMENTALLY (fold_delta_update,
round 5): the base pf tables stay resident; each revision recomputes
folded rows for exactly the delta-affected resources and ships them as
small replicated overlays, with a dirty-key set voiding the stale base
hits — Leopard's incremental index maintenance as subset-recompute, so
deletions need no derivation counting.  Conditions the subset recompute
can't keep sound or cheap downgrade the chain to the walked program
(sticky pf_off) until compaction re-folds.

Replaces the server-side evaluation behind the reference's
CheckBulkPermissions (/root/reference/client/client.go:238-266) for the
deep-nesting worlds where the walked kernel was 20× off its target.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..schema.compiler import CompiledSchema
from ..store.closure import NO_EXP, _expand_join
from .plan import DevicePlan, EngineConfig, ExprIR


@dataclass
class _Rows:
    """Folded rows of one (type, permission): direct-identity rows (the
    pf_e side; caveats ride along) and userset rows (the pf_t side;
    caveat-free by eligibility).  ``until`` is epoch-relative seconds
    with NO_EXP = never expires — the min over the path's arrow/leaf
    expiries."""

    e_res: np.ndarray
    e_k2: np.ndarray
    e_cav: np.ndarray
    e_ctx: np.ndarray
    e_until: np.ndarray
    u_res: np.ndarray
    u_subj: np.ndarray
    u_srel: np.ndarray
    u_until: np.ndarray

    @property
    def total(self) -> int:
        return int(self.e_res.shape[0] + self.u_res.shape[0])


def _empty_rows() -> _Rows:
    z = np.zeros(0, np.int32)
    return _Rows(z, z, z, z, z, z, z, z, z)


def _concat_rows(parts: List[_Rows]) -> _Rows:
    if not parts:
        return _empty_rows()
    return _Rows(*(
        np.concatenate([getattr(p, f) for p in parts])
        for f in ("e_res", "e_k2", "e_cav", "e_ctx", "e_until",
                  "u_res", "u_subj", "u_srel", "u_until")
    ))


def _until_of(exp: np.ndarray) -> np.ndarray:
    # pure int32 (NO_EXP fits): no int64 round trip on the 30M-row pass
    return np.where(exp == 0, NO_EXP, exp).astype(np.int32)


def _strictly_inc2(a: np.ndarray, b: np.ndarray) -> bool:
    """Rows strictly increasing by (a, b) — sorted AND unique."""
    if a.shape[0] < 2:
        return True
    gt = a[1:] > a[:-1]
    eq = a[1:] == a[:-1]
    return bool((gt | (eq & (b[1:] > b[:-1]))).all())


def _strictly_inc3(a: np.ndarray, b: np.ndarray, c: np.ndarray) -> bool:
    if a.shape[0] < 2:
        return True
    gt = a[1:] > a[:-1]
    eq = a[1:] == a[:-1]
    gtb = eq & (b[1:] > b[:-1])
    eqb = eq & (b[1:] == b[:-1])
    return bool((gt | gtb | (eqb & (c[1:] > c[:-1]))).all())


def _dedup_rows(r: _Rows) -> _Rows:
    """Max-until dedup per identity: folding through multiple paths keeps
    the most permissive admissibility, exactly like the closure's
    group_max.  Sort keys pack into uint64 words for the native parallel
    radix (all components non-negative except ctx, biased by +1 — an
    order-preserving transform, so the permutation is the np.lexsort
    one); gathers apply in parallel."""
    from ..native.sort import sortperm_words, take32, take64

    if r.e_res.shape[0] and _strictly_inc2(
        r.e_res, r.e_k2
    ):
        # identity rows arriving strictly (res, k2)-sorted (a single
        # leaf's rows out of the unique-identity primary view) dedup to
        # themselves: the stable sort is the identity permutation and
        # every run has length 1 — passthrough, bit-identical
        er, ek, ec, ex, eu = r.e_res, r.e_k2, r.e_cav, r.e_ctx, r.e_until
    elif r.e_res.shape[0]:
        w2 = (r.e_cav.astype(np.uint64) << np.uint64(32)) | (
            r.e_ctx.astype(np.int64) + 1
        ).astype(np.uint64)
        o = sortperm_words(
            [r.e_res.astype(np.int64), r.e_k2, w2],
            (r.e_ctx, r.e_cav, r.e_k2, r.e_res),
        )
        er, ek = take32(r.e_res, o), take64(r.e_k2, o)
        ec, ex, eu = take32(r.e_cav, o), take32(r.e_ctx, o), take32(r.e_until, o)
        first = np.ones(er.shape[0], bool)
        first[1:] = (
            (er[1:] != er[:-1]) | (ek[1:] != ek[:-1])
            | (ec[1:] != ec[:-1]) | (ex[1:] != ex[:-1])
        )
        st = np.nonzero(first)[0]
        er, ek, ec, ex = er[first], ek[first], ec[first], ex[first]
        eu = np.maximum.reduceat(eu, st)
    else:
        er, ek, ec, ex, eu = (r.e_res,) * 5
    if r.u_res.shape[0] and _strictly_inc3(r.u_res, r.u_subj, r.u_srel):
        ur, us, ul, uu = r.u_res, r.u_subj, r.u_srel, r.u_until
    elif r.u_res.shape[0]:
        w1 = (r.u_subj.astype(np.uint64) << np.uint64(32)) | r.u_srel.astype(
            np.uint64
        )
        o = sortperm_words(
            [r.u_res.astype(np.int64), w1], (r.u_srel, r.u_subj, r.u_res)
        )
        ur, us = take32(r.u_res, o), take32(r.u_subj, o)
        ul, uu = take32(r.u_srel, o), take32(r.u_until, o)
        first = np.ones(ur.shape[0], bool)
        first[1:] = (
            (ur[1:] != ur[:-1]) | (us[1:] != us[:-1]) | (ul[1:] != ul[:-1])
        )
        st = np.nonzero(first)[0]
        ur, us, ul = ur[first], us[first], ul[first]
        uu = np.maximum.reduceat(uu, st)
    else:
        ur, us, ul, uu = (r.u_res,) * 4
    return _Rows(er, ek, ec, ex, eu, ur, us, ul, uu)


def _lift(rows: _Rows, src: np.ndarray, dst: np.ndarray,
          p_until: np.ndarray) -> _Rows:
    """Re-key ``rows`` through join pairs (src → dst): every row at
    res == dst lifts to res = src with until min'd against the pair's
    path admissibility.  Both row sets must be sorted by res."""
    out_parts: List[_Rows] = []
    if rows.e_res.shape[0] and src.shape[0]:
        reps, ii = _expand_join(rows.e_res, dst)
        if reps.shape[0]:
            out_parts.append(_Rows(
                src[reps], rows.e_k2[ii], rows.e_cav[ii], rows.e_ctx[ii],
                np.minimum(rows.e_until[ii], p_until[reps]),
                *(np.zeros(0, np.int32),) * 4,
            ))
    if rows.u_res.shape[0] and src.shape[0]:
        reps, ii = _expand_join(rows.u_res, dst)
        if reps.shape[0]:
            out_parts.append(_Rows(
                *(np.zeros(0, np.int32),) * 5,
                src[reps], rows.u_subj[ii], rows.u_srel[ii],
                np.minimum(rows.u_until[ii], p_until[reps]),
            ))
    return _concat_rows(out_parts)


def _is_sorted(a: np.ndarray) -> bool:
    return a.shape[0] < 2 or bool((a[1:] >= a[:-1]).all())


def _sorted_by_res(r: _Rows) -> _Rows:
    from ..native.sort import argsort1, take32, take64

    # leaf rows masked out of the (rel, res, ...)-sorted primary/userset
    # views arrive already res-sorted: a stable sort is then the identity
    # permutation, so returning the rows untouched is bit-identical and
    # skips two 30M-row sorts on the trivial-union fold path
    e_sorted = _is_sorted(r.e_res)
    u_sorted = _is_sorted(r.u_res)
    if e_sorted and u_sorted:
        return r
    if e_sorted:
        er, ek, ec, ex, eu = r.e_res, r.e_k2, r.e_cav, r.e_ctx, r.e_until
    else:
        oe = argsort1(r.e_res)
        er, ek = take32(r.e_res, oe), take64(r.e_k2, oe)
        ec, ex = take32(r.e_cav, oe), take32(r.e_ctx, oe)
        eu = take32(r.e_until, oe)
    if u_sorted:
        ur, us, ul, uu = r.u_res, r.u_subj, r.u_srel, r.u_until
    else:
        ou = argsort1(r.u_res)
        ur, us = take32(r.u_res, ou), take32(r.u_subj, ou)
        ul, uu = take32(r.u_srel, ou), take32(r.u_until, ou)
    return _Rows(er, ek, ec, ex, eu, ur, us, ul, uu)


@dataclass
class _Recipe:
    """The structural recipe of one folded (type, permission) — enough to
    recompute its rows for a subset of resources during incremental
    maintenance (fold_delta_update)."""

    tname: str
    tid_i: int  # interner type id
    slot: int
    #: direct leaf contributions: (type_name, relation_slot) — same type
    leaves: List[Tuple[str, int]]
    #: same-type folded-permission refs
    fold_refs: List[Tuple[str, int]]
    #: arrow contributions: (ts_slot, [("leaf"|"fold", child_type, slot)])
    arrows: List[Tuple[int, List[Tuple[str, str, int]]]]
    self_ts: Optional[int] = None


@dataclass
class FoldState:
    """Host-side base-revision inputs for O(delta) fold maintenance
    across a Watch chain (engine/flat.py build_delta_arrays →
    fold_delta_update).  Everything here is immutable along the chain:
    overlays are recomputed from (this state, accumulated delta) each
    revision.  The Leopard-style incremental-maintenance answer to the
    reference's Watch-driven re-index contract
    (/root/reference/client/client.go:364-413)."""

    order: List[Tuple[str, int]]  # folded pairs, topo (build) order
    recipes: Dict[Tuple[str, int], _Recipe]
    #: base leaf rows per (type_name, rel_slot), sorted by res both sides
    leaf_cache: Dict[Tuple[str, int], _Rows]
    #: base arrow rows per (type_name, ts_slot): two sorted copies
    #: (src, dst, p_until) — by dst (lift joins) and by src (subsetting)
    arrow_by_dst: Dict[Tuple[str, int], Tuple[np.ndarray, ...]]
    arrow_by_src: Dict[Tuple[str, int], Tuple[np.ndarray, ...]]
    #: base POST rows (after self-closure lift) per pair, sorted by res
    post_rows: Dict[Tuple[str, int], _Rows]
    #: base PRE rows (before self-closure lift; == post for non-self
    #: pairs) per pair, sorted by res
    pre_rows: Dict[Tuple[str, int], _Rows]
    #: self-recursive ancestor closure per pair: (src, anc, d_until)
    #: sorted by anc
    self_closure: Dict[Tuple[str, int], Tuple[np.ndarray, ...]]
    #: tupleset slots whose arrow rows any fold traverses (incl. self):
    #: deltas touching these with a caveat — or self ones at all — bail
    fold_ts_slots: frozenset
    self_ts_slots: frozenset
    #: relation slots folded as direct leaves (delta us adds with a
    #: caveat landing on one of these flip eligibility → bail)
    folded_leaf_slots: frozenset
    #: sorted permission-userset subject keys (subj·S1_raw + srel1):
    #: a delta us add whose subject key is here extends groups through a
    #: permission chain — the fold's T side can't represent it → bail
    pus_keys: np.ndarray
    itid: Dict[str, int]
    S1_raw: int
    wc_nodes: np.ndarray
    # attached by build_flat_arrays* after packing succeeds:
    maps: object = None  # flat.SlotMaps
    N: int = 0


@dataclass
class FoldResult:
    """Folded rows keyed ready for table build: pf_e identity rows and
    pf_u userset rows, both carrying the owning permission slot."""

    e_slot: np.ndarray
    e_res: np.ndarray
    e_k2: np.ndarray
    e_cav: np.ndarray
    e_ctx: np.ndarray
    e_until: np.ndarray
    u_slot: np.ndarray
    u_res: np.ndarray
    u_subj: np.ndarray
    u_srel: np.ndarray
    u_until: np.ndarray
    #: the folded (type_name, perm_slot) pairs — the kernel skips these
    #: programs when no delta level is present
    pairs: Tuple[Tuple[str, int], ...]


def _union_leaves(expr: ExprIR) -> Optional[List[ExprIR]]:
    """Flatten a union tree to its leaves; None when the tree contains
    intersection/exclusion (ineligible for folding)."""
    tag = expr[0]
    if tag == "union":
        out: List[ExprIR] = []
        for c in expr[1]:
            got = _union_leaves(c)
            if got is None:
                return None
            out.extend(got)
        return out
    if tag in ("ref", "arrow", "nil"):
        return [expr]
    return None


def fold_permissions(
    snap, config: EngineConfig, plan: DevicePlan, cl
) -> Optional[Tuple[FoldResult, FoldState]]:
    """Fold every eligible (type, permission) of the snapshot's schema.
    Returns (rows, maintenance state) or None when folding is disabled,
    inapplicable, or over budget (the walked kernel answers those worlds
    exactly as before)."""
    if not config.flat_fold or not plan.topo_programs:
        return None
    if cl.ovf_src.shape[0]:
        # overflowed closure sources make the T-side incomplete; the
        # walked path flags affected queries per site — folding can't
        return None
    compiled: CompiledSchema = snap.compiled
    S1 = snap.num_slots + 1

    # slot-granular userset eligibility, the T-index's bar: caveated /
    # permission-valued rows and rows whose group may extend through a
    # permission chain (pus) can't fold into an until-only table
    bad_us = (snap.us_caveat != 0) | (snap.us_perm != 0)
    if snap.pus_n.shape[0]:
        pus_k = np.sort(snap.pus_n.astype(np.int64) * S1 + snap.pus_r + 1)
        uk = snap.us_subj.astype(np.int64) * S1 + snap.us_srel + 1
        pos = np.clip(np.searchsorted(pus_k, uk), 0, pus_k.shape[0] - 1)
        bad_us |= pus_k[pos] == uk
    bad_rel_slots = set(np.unique(snap.us_rel[bad_us]).tolist())
    cav_ts_slots = set(np.unique(snap.ar_rel[snap.ar_caveat != 0]).tolist())

    # interner type id per schema type (node_type holds interner ids)
    itid: Dict[str, int] = {
        t: snap.interner.type_lookup(t) for t in compiled.type_ids
    }
    ntype = snap.node_type
    e_type = ntype[np.clip(snap.e_res, 0, max(snap.num_nodes - 1, 0))]
    us_type = ntype[np.clip(snap.us_res, 0, max(snap.num_nodes - 1, 0))]
    ar_type = ntype[np.clip(snap.ar_res, 0, max(snap.num_nodes - 1, 0))]
    ar_ctype = ntype[np.clip(snap.ar_child, 0, max(snap.num_nodes - 1, 0))]

    rel_leaf = frozenset(plan.rel_leaf_slots)
    budget = config.flat_fold_factor * max(
        int(snap.e_rel.shape[0] + snap.us_rel.shape[0]), 4096
    )
    spent = 0

    leaf_memo: Dict[Tuple[str, int], Optional[_Rows]] = {}

    def leaf_rows(tname: str, rel_slot: int) -> Optional[_Rows]:
        """Base leaf rows of (type, relation), sorted by res (memoized —
        the sorted copies double as the maintenance state's leaf cache)."""
        key = (tname, rel_slot)
        if key in leaf_memo:
            return leaf_memo[key]
        if rel_slot in bad_rel_slots:
            leaf_memo[key] = None
            return None
        tid = itid[tname]
        m = (snap.e_rel == rel_slot) & (e_type == tid)
        # RAW int64 identity key (subj·(num_slots+1)+srel1): internal to
        # the fold, immune to the int32 packing cliff — build_flat_arrays
        # decomposes and repacks with the dense radices
        e_k2 = snap.e_subj[m].astype(np.int64) * S1 + snap.e_srel1[m]
        mu = (snap.us_rel == rel_slot) & (us_type == tid)
        got = _sorted_by_res(_Rows(
            snap.e_res[m], e_k2, snap.e_caveat[m], snap.e_ctx[m],
            _until_of(snap.e_exp[m]),
            snap.us_res[mu], snap.us_subj[mu], snap.us_srel[mu],
            _until_of(snap.us_exp[mu]),
        ))
        leaf_memo[key] = got
        return got

    arrow_by_dst: Dict[Tuple[str, int], Tuple[np.ndarray, ...]] = {}
    arrow_by_src: Dict[Tuple[str, int], Tuple[np.ndarray, ...]] = {}

    def arrow_pairs(tname: str, ts_slot: int):
        """(src, dst, p_until) arrow rows of ``tname`` under ``ts_slot``,
        sorted by dst for _lift (memoized; a by-src copy is kept for the
        maintenance state)."""
        key = (tname, ts_slot)
        if key in arrow_by_dst:
            return arrow_by_dst[key]
        m = (snap.ar_rel == ts_slot) & (ar_type == itid[tname]) & (
            snap.ar_child >= 0
        )
        src, dst = snap.ar_res[m], snap.ar_child[m]
        p_until = _until_of(snap.ar_exp[m])
        o = np.argsort(dst, kind="stable")
        arrow_by_dst[key] = (src[o], dst[o], p_until[o])
        o2 = np.argsort(src, kind="stable")
        arrow_by_src[key] = (src[o2], dst[o2], p_until[o2])
        return arrow_by_dst[key]

    folded: Dict[Tuple[str, int], _Rows] = {}
    folded_sorted: Dict[Tuple[str, int], _Rows] = {}
    pre_sorted: Dict[Tuple[str, int], _Rows] = {}
    recipes: Dict[Tuple[str, int], _Recipe] = {}
    order: List[Tuple[str, int]] = []
    self_closures: Dict[Tuple[str, int], Tuple[np.ndarray, ...]] = {}
    name_of_slot = compiled.name_of_slot

    for (tname, tid, slot, expr) in plan.topo_programs:
        leaves = _union_leaves(expr)
        if leaves is None:
            continue
        ct = compiled.types[compiled.type_ids[tname]]
        tid_i = itid[tname]
        parts: List[_Rows] = []
        self_ts: Optional[int] = None
        rec = _Recipe(
            tname=tname, tid_i=tid_i, slot=slot,
            leaves=[], fold_refs=[], arrows=[],
        )
        ok = True
        for child in leaves:
            tag = child[0]
            if tag == "nil":
                continue
            if tag == "ref":
                # slots are per-NAME: the same slot can be a relation on
                # one type and a permission on another — resolve against
                # THIS type's definition
                s = child[1]
                sname = name_of_slot.get(s, "")
                if sname in compiled.schema.definitions[tname].relations:
                    got = leaf_rows(tname, s)
                    rec.leaves.append((tname, s))
                elif (tname, s) in folded:
                    got = folded[(tname, s)]
                    rec.fold_refs.append((tname, s))
                else:
                    got = None
                if got is None:
                    ok = False
                    break
                parts.append(got)
                continue
            # arrow
            ts_slot = plan.ts_slots[child[1]]
            right = child[2]
            if ts_slot in cav_ts_slots:
                ok = False
                break
            relation = ct.relations.get(ts_slot)
            if relation is None:
                continue  # no such tupleset on this type: contributes ∅
            if any(a.relation_slot >= 0 or a.wildcard for a in relation.allowed):
                # arrows traverse direct subjects only; userset/wildcard
                # tupleset subjects keep the walked path
                ok = False
                break
            child_types = {ct2 for a in relation.allowed
                           for ct2 in (compiled.types[a.type_id].name,)}
            if right == slot and child_types == {tname}:
                if self_ts is not None and self_ts != ts_slot:
                    ok = False  # two distinct self-recursive tuplesets
                    break
                self_ts = ts_slot
                continue
            src, dst, p_until = arrow_pairs(tname, ts_slot)
            childs: List[Tuple[str, str, int]] = []
            for c_t in sorted(child_types):
                c_has_rel = (
                    right in rel_leaf
                    and name_of_slot.get(right)
                    in compiled.schema.definitions[c_t].relations
                )
                if c_has_rel:
                    got = leaf_rows(c_t, right)
                    childs.append(("leaf", c_t, right))
                elif (c_t, right) in folded:
                    got = folded_sorted[(c_t, right)]
                    childs.append(("fold", c_t, right))
                elif compiled.schema.definitions[c_t].item(
                    name_of_slot.get(right, "")
                ) is None:
                    continue  # child type lacks the item: contributes ∅
                else:
                    got = None
                if got is None:
                    ok = False
                    break
                parts.append(_lift(got, src, dst, p_until))
            if not ok:
                break
            rec.arrows.append((ts_slot, childs))
        if not ok:
            continue
        rows = _dedup_rows(_concat_rows(parts))
        pre = rows
        if self_ts is not None:
            from .flat import _arrow_closure  # deferred: flat imports us

            built = _arrow_closure(snap, self_ts)
            if built is None:
                continue  # data cycle / over cap: keep the walked path
            c_src, c_anc, c_d, _c_p = built  # cav-free ts ⇒ d == p
            # slots are per-NAME: the closure selects by slot only, so
            # another type sharing the tupleset name contributes pairs
            # whose SOURCE is not this type — drop them, or folded grants
            # would leak onto that type's resources under this perm slot
            tm = ntype[np.clip(c_src, 0, max(snap.num_nodes - 1, 0))] == tid_i
            c_src, c_anc, c_d = c_src[tm], c_anc[tm], c_d[tm]
            o = np.argsort(c_anc, kind="stable")
            c_src, c_anc, c_d = c_src[o], c_anc[o], c_d[o]
            rows = _dedup_rows(_concat_rows([
                rows, _lift(_sorted_by_res(rows), c_src, c_anc, c_d),
            ]))
        if spent + rows.total > budget:
            continue  # over budget: this pair stays on the walked path
        spent += rows.total
        rec.self_ts = self_ts
        pair = (tname, slot)
        folded[pair] = rows
        folded_sorted[pair] = _sorted_by_res(rows)
        pre_sorted[pair] = (
            _sorted_by_res(pre) if self_ts is not None else folded_sorted[pair]
        )
        if self_ts is not None:
            self_closures[pair] = (c_src, c_anc, c_d)
        recipes[pair] = rec
        order.append(pair)

    if not folded:
        return None
    if snap.pus_n.shape[0]:
        pus_keys = np.sort(snap.pus_n.astype(np.int64) * S1 + snap.pus_r + 1)
    else:
        pus_keys = np.zeros(0, np.int64)
    state = FoldState(
        order=order,
        recipes=recipes,
        leaf_cache={k: v for k, v in leaf_memo.items() if v is not None},
        arrow_by_dst=arrow_by_dst,
        arrow_by_src=arrow_by_src,
        post_rows=folded_sorted,
        pre_rows=pre_sorted,
        self_closure=self_closures,
        fold_ts_slots=frozenset(
            {ts for r in recipes.values() for ts, _ in r.arrows}
            | {r.self_ts for r in recipes.values() if r.self_ts is not None}
        ),
        self_ts_slots=frozenset(
            r.self_ts for r in recipes.values() if r.self_ts is not None
        ),
        folded_leaf_slots=frozenset(
            s for (_t, s), v in leaf_memo.items() if v is not None
        ),
        pus_keys=pus_keys,
        itid=itid,
        S1_raw=S1,
        wc_nodes=snap.wildcard_node_of_type[
            snap.wildcard_node_of_type >= 0
        ].astype(np.int32),
    )
    pairs = tuple(sorted(folded))
    result = FoldResult(
        e_slot=np.concatenate([
            np.full(folded[p].e_res.shape[0], p[1], np.int32) for p in pairs
        ]),
        e_res=np.concatenate([folded[p].e_res for p in pairs]),
        e_k2=np.concatenate([folded[p].e_k2 for p in pairs]),
        e_cav=np.concatenate([folded[p].e_cav for p in pairs]),
        e_ctx=np.concatenate([folded[p].e_ctx for p in pairs]),
        e_until=np.concatenate([folded[p].e_until for p in pairs]),
        u_slot=np.concatenate([
            np.full(folded[p].u_res.shape[0], p[1], np.int32) for p in pairs
        ]),
        u_res=np.concatenate([folded[p].u_res for p in pairs]),
        u_subj=np.concatenate([folded[p].u_subj for p in pairs]),
        u_srel=np.concatenate([folded[p].u_srel for p in pairs]),
        u_until=np.concatenate([folded[p].u_until for p in pairs]),
        pairs=pairs,
    )
    return result, state


# ---------------------------------------------------------------------------
# incremental maintenance: Watch-delta overlays over a folded base
# ---------------------------------------------------------------------------


def _rows_at(rows: _Rows, S: np.ndarray) -> _Rows:
    """``rows`` (res-sorted on both planes) restricted to res ∈ S
    (sorted unique).  Output stays res-sorted."""
    _, ie = _expand_join(rows.e_res, S)
    _, iu = _expand_join(rows.u_res, S)
    return _Rows(
        rows.e_res[ie], rows.e_k2[ie], rows.e_cav[ie], rows.e_ctx[ie],
        rows.e_until[ie],
        rows.u_res[iu], rows.u_subj[iu], rows.u_srel[iu], rows.u_until[iu],
    )


def _in_sorted(sorted_keys: np.ndarray, keys: np.ndarray) -> np.ndarray:
    if sorted_keys.shape[0] == 0 or keys.shape[0] == 0:
        return np.zeros(keys.shape[0], bool)
    pos = np.clip(
        np.searchsorted(sorted_keys, keys), 0, sorted_keys.shape[0] - 1
    )
    return sorted_keys[pos] == keys


def _ident(state: FoldState, rel_slot: int, res, subj, srel1) -> np.ndarray:
    """Primary-row identity packed EXACTLY like the accumulated delta's
    tombstone keys (flat._acc_collapse.pack): dense (k1 << 31) | k2."""
    from .flat import _m_srel1  # deferred: flat imports us

    maps = state.maps
    k1 = np.int64(maps.k1[rel_slot]) * state.N + res.astype(np.int64)
    k2 = subj.astype(np.int64) * maps.S1 + _m_srel1(
        maps, np.asarray(srel1, np.int64).astype(np.int32)
    ).astype(np.int64)
    return (k1 << np.int64(31)) | k2


def _cur_leaf(
    state: FoldState, acc, node_type: np.ndarray, tname: str, rel_slot: int,
    S: np.ndarray,
) -> _Rows:
    """CURRENT (base − tombstones ∪ adds) leaf rows of (type, relation)
    at res ∈ S, res-sorted.  Upserted identities are sound because every
    touched identity is in the tombstone set (flat._acc_collapse)."""
    S1r = state.S1_raw
    g_sorted = acc["a_g_key_sorted"]
    parts: List[_Rows] = []
    base = state.leaf_cache.get((tname, rel_slot))
    if base is not None and base.total:
        sub = _rows_at(base, S)
        me = np.ones(sub.e_res.shape[0], bool)
        mu = np.ones(sub.u_res.shape[0], bool)
        if g_sorted.shape[0]:
            if sub.e_res.shape[0]:
                me = ~_in_sorted(g_sorted, _ident(
                    state, rel_slot, sub.e_res,
                    sub.e_k2 // S1r, sub.e_k2 % S1r,
                ))
            if sub.u_res.shape[0]:
                mu = ~_in_sorted(g_sorted, _ident(
                    state, rel_slot, sub.u_res, sub.u_subj, sub.u_srel + 1,
                ))
        parts.append(_Rows(
            sub.e_res[me], sub.e_k2[me], sub.e_cav[me], sub.e_ctx[me],
            sub.e_until[me],
            sub.u_res[mu], sub.u_subj[mu], sub.u_srel[mu], sub.u_until[mu],
        ))
    tid = state.itid[tname]
    rtypes = node_type[np.clip(acc["a_res"], 0, node_type.shape[0] - 1)]
    m = (
        (acc["a_rel"] == rel_slot) & (rtypes == tid)
        & np.isin(acc["a_res"], S)
    )
    if m.any():
        res = acc["a_res"][m]
        subj = acc["a_subj"][m]
        srel1 = acc["a_srel1"][m]
        until = _until_of(acc["a_exp"][m])
        mu = srel1 > 0
        parts.append(_Rows(
            res, subj.astype(np.int64) * S1r + srel1,
            acc["a_cav"][m], acc["a_ctx"][m], until,
            res[mu], subj[mu], (srel1[mu] - 1).astype(np.int32), until[mu],
        ))
    return _sorted_by_res(_concat_rows(parts))


def _cur_arrows(
    state: FoldState, acc, node_type: np.ndarray, tname: str, ts_slot: int,
    S: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CURRENT arrow rows (src, dst, p_until) of (type, ts) with
    src ∈ S, sorted by dst (the _lift join order)."""
    g_sorted = acc["a_g_key_sorted"]
    base = state.arrow_by_src.get((tname, ts_slot))
    srcs: List[np.ndarray] = []
    dsts: List[np.ndarray] = []
    pus: List[np.ndarray] = []
    if base is not None and base[0].shape[0]:
        _, ii = _expand_join(base[0], S)
        src, dst, pu = base[0][ii], base[1][ii], base[2][ii]
        if g_sorted.shape[0] and src.shape[0]:
            keep = ~_in_sorted(g_sorted, _ident(
                state, ts_slot, src, dst, np.zeros(src.shape[0], np.int32)
            ))
            src, dst, pu = src[keep], dst[keep], pu[keep]
        srcs.append(src); dsts.append(dst); pus.append(pu)
    tid = state.itid[tname]
    rtypes = node_type[np.clip(acc["a_res"], 0, node_type.shape[0] - 1)]
    m = (
        (acc["a_rel"] == ts_slot) & (acc["a_srel1"] == 0) & (rtypes == tid)
        & np.isin(acc["a_res"], S) & (acc["a_subj"] >= 0)
    )
    if m.any():
        srcs.append(acc["a_res"][m])
        dsts.append(acc["a_subj"][m])
        pus.append(_until_of(acc["a_exp"][m]))
    if not srcs:
        z = np.zeros(0, np.int32)
        return z, z, z
    src = np.concatenate(srcs)
    dst = np.concatenate(dsts)
    pu = np.concatenate(pus)
    o = np.argsort(dst, kind="stable")
    return src[o], dst[o], pu[o]


def _cur_pair_rows(
    state: FoldState, pair: Tuple[str, int], new_rows: Dict, D: Dict,
    S: np.ndarray, *, pre: bool,
) -> _Rows:
    """CURRENT pre- or post-rows of an already-maintained folded pair at
    res ∈ S: base rows where unaffected, recomputed rows where dirty."""
    base = (state.pre_rows if pre else state.post_rows)[pair]
    Dp = D[pair]
    inD = np.isin(S, Dp)
    return _sorted_by_res(_concat_rows([
        _rows_at(base, S[~inD]),
        _rows_at(new_rows[pair], S[inD]),
    ]))


def fold_delta_update(
    state: FoldState, acc, node_type: np.ndarray, config: EngineConfig
) -> Optional[Tuple[np.ndarray, Optional[FoldResult]]]:
    """O(delta) incremental fold maintenance: from the base-revision
    FoldState and the chain's accumulated delta, compute (a) the DIRTY
    key set — packed (slot·N + res) whose base pf answers must be
    voided — and (b) replacement rows for exactly those resources,
    recomputed against current (base − tombstones ∪ adds) data in the
    same recipe/topo order the base fold ran.  Deletions are exact by
    construction (affected resources are recomputed wholesale, so no
    derivation counting is needed — the subset-recompute answer to
    Leopard's incremental index maintenance).

    Returns None on any condition the subset recompute cannot keep
    sound/cheap: structural edits to a self-recursive tupleset (the
    ancestor closure would shift), eligibility flips (caveated
    arrow/userset delta rows, pus-extending subjects), or a dirty set
    past the cap.  The caller (flat.build_delta_arrays) then DOWNGRADES
    the chain — sticky pf_off, folded pairs walk with the dl_* overlays
    until compaction re-folds the base — it does not force a rebuild."""
    a_rel, a_res = acc["a_rel"], acc["a_res"]
    a_subj, a_srel1 = acc["a_subj"], acc["a_srel1"]
    g_rel, g_res, g_srel1 = acc["g_rel"], acc["g_res"], acc["g_srel1"]
    all_rel = np.concatenate([a_rel, g_rel])
    all_res = np.concatenate([a_res, g_res])
    all_srel1 = np.concatenate([a_srel1, g_srel1])
    if all_rel.shape[0] == 0:
        return np.zeros(0, np.int32), None

    # -- eligibility bails -------------------------------------------------
    if state.self_ts_slots:
        st = np.asarray(sorted(state.self_ts_slots), np.int64)
        if np.isin(all_rel, st).any():
            return None  # ancestor closure would shift: rebuild
    if state.fold_ts_slots:
        ft = np.asarray(sorted(state.fold_ts_slots), np.int64)
        m = np.isin(a_rel, ft) & (a_srel1 == 0)
        if m.any() and acc["a_cav"][m].any():
            return None  # fold arrows must stay caveat-free
    if state.folded_leaf_slots:
        fl = np.asarray(sorted(state.folded_leaf_slots), np.int64)
        m = np.isin(a_rel, fl) & (a_srel1 > 0)
        if m.any():
            if acc["a_cav"][m].any():
                return None  # caveated userset row flips leaf eligibility
            if state.pus_keys.shape[0]:
                sk = (
                    a_subj[m].astype(np.int64) * state.S1_raw + a_srel1[m]
                )
                if _in_sorted(state.pus_keys, sk).any():
                    return None  # group extends through a permission chain

    # sorted tombstone keys for the current-row extractors
    acc = dict(acc)
    acc["a_g_key_sorted"] = acc["g_key"]  # maintained sorted by collapse

    rtypes = node_type[np.clip(all_res, 0, node_type.shape[0] - 1)]

    # -- affected resource sets, pair by pair in base fold order ----------
    D_pre: Dict[Tuple[str, int], np.ndarray] = {}
    D_post: Dict[Tuple[str, int], np.ndarray] = {}
    total_dirty = 0
    for pair in state.order:
        rec = state.recipes[pair]
        ds: List[np.ndarray] = []
        for (lt, lslot) in rec.leaves:
            ds.append(all_res[(all_rel == lslot) & (rtypes == rec.tid_i)])
        for ref_pair in rec.fold_refs:
            ds.append(D_post[ref_pair])
        for (ts_slot, childs) in rec.arrows:
            ds.append(all_res[
                (all_rel == ts_slot) & (all_srel1 == 0)
                & (rtypes == rec.tid_i)
            ])
            bd = state.arrow_by_dst.get((rec.tname, ts_slot))
            if bd is None or bd[0].shape[0] == 0:
                continue
            for (kind, c_t, c_slot) in childs:
                if kind == "leaf":
                    c_tid = state.itid[c_t]
                    touched = np.unique(all_res[
                        (all_rel == c_slot) & (rtypes == c_tid)
                    ])
                else:
                    touched = D_post[(c_t, c_slot)]
                if touched.shape[0]:
                    _, ii = _expand_join(bd[1], touched)
                    ds.append(bd[0][ii])
        Dp = (
            np.unique(np.concatenate(ds).astype(np.int32))
            if ds else np.zeros(0, np.int32)
        )
        D_pre[pair] = Dp
        if rec.self_ts is not None and Dp.shape[0]:
            c_src, c_anc, _c_d = state.self_closure[pair]
            _, ii = _expand_join(c_anc, Dp)
            Dp2 = np.unique(np.concatenate([Dp, c_src[ii]]))
        else:
            Dp2 = Dp
        D_post[pair] = Dp2
        total_dirty += int(Dp2.shape[0])
        if total_dirty > config.flat_fold_delta_dirty_cap:
            return None  # hot-ancestor touch: downgrade to the walk

    if total_dirty == 0:
        return np.zeros(0, np.int32), None

    # -- subset refold against current data -------------------------------
    new_pre: Dict[Tuple[str, int], _Rows] = {}
    new_post: Dict[Tuple[str, int], _Rows] = {}
    total_rows = 0
    row_cap = max(config.flat_delta_min_compact, 4 * total_dirty)
    for pair in state.order:
        rec = state.recipes[pair]
        S = D_post[pair]
        if S.shape[0] == 0:
            new_pre[pair] = new_post[pair] = _empty_rows()
            continue
        parts: List[_Rows] = []
        for (lt, lslot) in rec.leaves:
            parts.append(_cur_leaf(state, acc, node_type, lt, lslot, S))
        for ref_pair in rec.fold_refs:
            parts.append(_cur_pair_rows(
                state, ref_pair, new_post, D_post, S, pre=False
            ))
        for (ts_slot, childs) in rec.arrows:
            src, dst, pu = _cur_arrows(
                state, acc, node_type, rec.tname, ts_slot, S
            )
            if src.shape[0] == 0:
                continue
            dsts = np.unique(dst)
            for (kind, c_t, c_slot) in childs:
                if kind == "leaf":
                    got = _cur_leaf(state, acc, node_type, c_t, c_slot, dsts)
                else:
                    got = _cur_pair_rows(
                        state, (c_t, c_slot), new_post, D_post, dsts,
                        pre=False,
                    )
                parts.append(_lift(got, src, dst, pu))
        pre = _sorted_by_res(_dedup_rows(_concat_rows(parts)))
        new_pre[pair] = pre
        if rec.self_ts is not None:
            c_src, c_anc, c_d = state.self_closure[pair]
            keep = np.isin(c_src, S)
            cs, ca, cd = c_src[keep], c_anc[keep], c_d[keep]
            ancs = np.unique(ca)
            pre_at_anc = _cur_pair_rows(
                state, pair, new_pre, D_pre, ancs, pre=True
            )
            post = _sorted_by_res(_dedup_rows(_concat_rows([
                pre, _lift(pre_at_anc, cs, ca, cd),
            ])))
        else:
            post = pre
        new_post[pair] = post
        total_rows += post.total
        if total_rows > row_cap:
            return None  # overlay would rival the base: downgrade

    # -- outputs: dirty keys + replacement rows ---------------------------
    maps, N = state.maps, state.N
    dirty_k1 = np.concatenate([
        (np.int64(maps.k1[p[1]]) * N + D_post[p].astype(np.int64)).astype(
            np.int32
        )
        for p in state.order
    ])
    pairs = tuple(sorted(p for p in state.order if new_post[p].total))
    if not pairs:
        return dirty_k1, None
    ovl = FoldResult(
        e_slot=np.concatenate([
            np.full(new_post[p].e_res.shape[0], p[1], np.int32)
            for p in pairs
        ]),
        e_res=np.concatenate([new_post[p].e_res for p in pairs]),
        e_k2=np.concatenate([new_post[p].e_k2 for p in pairs]),
        e_cav=np.concatenate([new_post[p].e_cav for p in pairs]),
        e_ctx=np.concatenate([new_post[p].e_ctx for p in pairs]),
        e_until=np.concatenate([new_post[p].e_until for p in pairs]),
        u_slot=np.concatenate([
            np.full(new_post[p].u_res.shape[0], p[1], np.int32)
            for p in pairs
        ]),
        u_res=np.concatenate([new_post[p].u_res for p in pairs]),
        u_subj=np.concatenate([new_post[p].u_subj for p in pairs]),
        u_srel=np.concatenate([new_post[p].u_srel for p in pairs]),
        u_until=np.concatenate([new_post[p].u_until for p in pairs]),
        pairs=pairs,
    )
    return dirty_k1, ovl


def t_join_core(
    k1: np.ndarray, pe: np.ndarray, w: np.ndarray,
    cl_k1: np.ndarray, cl_k2: np.ndarray,
    c_d: np.ndarray, c_p: np.ndarray, cap_rows: int,
) -> Optional[Tuple[np.ndarray, ...]]:
    """The T-index join shared by the base table (flat.py _tindex_join)
    and (historically) the fold: userset entries (k1, group-key pe,
    until w) ⋈ closure-by-target, plus the direct group-identity entries,
    deduped max-per-plane.  Sizes the join BEFORE materializing it;
    returns None past ``cap_rows`` (a popular group with a huge closure
    in-degree must disable the index, not OOM).

    With ``EngineConfig.spmm`` on, the serving path runs
    engine/spmm.py's ``tjoin_spmm`` — the same join expressed on the
    generic (min, max) until-semiring product — and this bespoke kernel
    is the byte-for-byte parity oracle (tests/test_spmm.py)."""
    t_order = np.argsort(cl_k2, kind="stable")
    tgt_sorted = cl_k2[t_order]
    join_rows = int(
        (
            np.searchsorted(tgt_sorted, pe, "right")
            - np.searchsorted(tgt_sorted, pe, "left")
        ).sum()
    )
    if join_rows + pe.shape[0] > cap_rows:
        return None
    reps, ii = _expand_join(tgt_sorted, pe)
    jj = t_order[ii]
    T_k1 = np.concatenate([k1, k1[reps]])
    T_k2 = np.concatenate([pe, cl_k1[jj]])
    T_d = np.concatenate([w, np.minimum(w[reps], c_d[jj])])
    T_p = np.concatenate([w, np.minimum(w[reps], c_p[jj])])
    o2 = np.lexsort((T_k2, T_k1))
    T_k1, T_k2, T_d, T_p = T_k1[o2], T_k2[o2], T_d[o2], T_p[o2]
    first = np.ones(T_k1.shape[0], bool)
    first[1:] = (T_k1[1:] != T_k1[:-1]) | (T_k2[1:] != T_k2[:-1])
    st = np.nonzero(first)[0]
    return (
        T_k1[first], T_k2[first],
        np.maximum.reduceat(T_d, st), np.maximum.reduceat(T_p, st),
    )


def fold_userset_rows(fr: FoldResult, N: int, maps
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """pf_u: the folded userset rows packed with the DENSE radices
    (``maps`` is flat.SlotMaps), sorted by their (slot·N + res) group key.

    This is the REACHABILITY-PRUNED replacement for the round-5 dense
    fold T-join (u rows ⋈ closure-by-target), which materialized the full
    (resource × member) product — 268M rows at BASELINE config 3, where
    every document repeats its ancestor chain's group closures.  The
    factored form stores only the reachable (resource, group) pairs
    (the Leapfrog-style key intersection: iterate the keys both sides
    share, never the cross product) and the kernel intersects with the
    member closure at probe time — one bounded-fan range slice plus one
    closure probe per candidate group, independent of nesting depth.
    Factoring through the closure also makes the fold's tables
    independent of the membership closure, which is what lets membership
    deltas advance the closure in place without re-folding anything
    (store/closure.py advance_closure)."""
    k1 = (
        maps.k1[fr.u_slot].astype(np.int64) * N + fr.u_res
    ).astype(np.int32)
    gk = (
        fr.u_subj.astype(np.int64) * maps.S1 + maps.k2[fr.u_srel] + 1
    ).astype(np.int32)
    order = np.argsort(k1, kind="stable")
    return k1[order], gk[order], fr.u_until[order]

"""The flat check kernel: statically-unrolled probe programs over hash
indexes and the precomputed membership closure.

This is the TPU-shaped replacement for the two-phase walk in
engine/device.py.  The round-2 engine was correct everywhere and fast
nowhere (~16k checks/sec true device rate): per query it ran a capped
frontier walk with device-side sort/dedup (Phase A) plus a sequential
scan-based subgraph BFS (Phase B) — hundreds of *dependent* scalar steps
per check.  The flat kernel removes every per-query loop:

- **membership** is precomputed: store/closure.py flattens the transitive
  member→group closure once per revision; a userset grant test is one
  4-key hash probe into the flattened table (engine/hash.py);
- **rewrite structure** is unrolled at trace time: each permission's
  expression tree becomes straight-line code; arrows gather a capped,
  hash-indexed child block and recurse on the child axis (acyclic schemas
  unroll exactly; recursive ones unroll to a budget and mark deeper
  queries possible → host oracle);
- every probe site is a batch-wide vectorized gather: the whole dispatch
  is ~a few hundred *data-independent* gather/compare steps regardless of
  batch size, so throughput scales with batch until HBM bandwidth.

Semantics are identical to the legacy engine (differentially tested
against engine/oracle.py): two Kleene planes (definite, possible),
caveats gated per edge through the on-device CEL VM with merged
stored/query context, expiration via the closure's max-min semiring at
membership level and per-edge gates at leaf level, wildcard and userset
subjects, permission-valued userset conservatism (us_perm/pus), and
overflow flags that route capped queries to the host oracle.  The one
intentional degradation: caveats on *membership* edges decide closure
containment per query on the host (possible-plane), because the closure
is precomputed without query context.

Replaces the evaluation behind the reference's CheckBulkPermissions
(client/client.go:238-266).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema.compiler import CompiledSchema
from .hash import (
    _ceil_pow2,
    build_aligned,
    build_hash,
    build_range_hash,
    interleave_buckets,
    interleave_rows,
    mix32,
    probe_aligned,
    probe_block,
    probe_range,
    probe_rows,
    slice_blocks,
    take_in_bounds,
)
from .packed import decode_block as _pk_decode
from .plan import DevicePlan, EngineConfig, ExprIR, _eval_cyclic_pairs


# ---------------------------------------------------------------------------
# static metadata (part of the traced-function cache key)
# ---------------------------------------------------------------------------

#: packed query-matrix row layout (int32[QM_ROWS, B]): the kernel takes
#: ONE batched query argument — q_self rides as 0/1, row 7 is padding so
#: the leading dim stays pow2.  Builders: DeviceEngine.flat_fn_and_args,
#: ShardedEngine._dispatch_flat (data axis = axis 1 there).
QM_LAYOUT = ("q_res", "q_perm", "q_subj", "q_srel1_dense", "q_wc",
             "q_ctx", "q_self", "q_perm_k1")
QM_ROWS = len(QM_LAYOUT)


@lru_cache(maxsize=128)
def _dense_np(t: Tuple[int, ...]) -> np.ndarray:
    return np.asarray(t, np.int32) if t else np.full(1, -1, np.int32)


def build_qm(queries: Dict[str, "np.ndarray"], BP: int, meta: "FlatMeta"):
    """The packed QM_LAYOUT matrix from length-B query columns, padded to
    ``BP`` — the ONE builder both the single-chip and sharded dispatchers
    use, so the pad conventions (-1 keys; 0 for srel1/self) cannot drift.

    Slot-bearing rows map through the meta's DENSE slot maps here on the
    host: row 3 carries the dense srel1 (-1 = the subject relation can
    never match a stored key), row 7 the dense k1 id of q_perm (-1 =
    inactive — the root probes miss, programs still evaluate)."""
    return fill_qm(queries, np.empty((QM_ROWS, BP), np.int32), meta)


def fill_qm(queries: Dict[str, "np.ndarray"], qm: np.ndarray, meta: "FlatMeta"):
    """``build_qm`` into a PREALLOCATED [QM_ROWS, BP] int32 buffer.  The
    latency-mode path (engine/latency.py) keeps one staging buffer per
    batch tier and refills it in place, so steady-state small-batch
    dispatch performs zero host-side array allocation."""
    B = queries["q_res"].shape[0]
    k1d = _dense_np(meta.k1_dense)
    k2d = _dense_np(meta.k2_dense)
    qm.fill(-1)
    qm[3] = qm[6] = 0
    qm[0, :B] = queries["q_res"]
    qm[1, :B] = queries["q_perm"]
    qm[2, :B] = queries["q_subj"]
    srel = queries["q_srel"]
    sd = k2d[np.clip(srel, 0, k2d.shape[0] - 1)]
    qm[3, :B] = np.where(srel < 0, 0, np.where(sd >= 0, sd + 1, -1))
    qm[4, :B] = queries["q_wc"]
    qm[5, :B] = queries["q_ctx"]
    qm[6, :B] = queries["q_self"]
    qp = queries["q_perm"]
    qm[7, :B] = np.where(
        qp >= 0, k1d[np.clip(qp, 0, k1d.shape[0] - 1)], -1
    )
    return qm


@dataclass(frozen=True)
class DeltaMeta:
    """Static geometry of the LSM-style delta level (Watch-driven
    incremental re-index, BASELINE config 5).

    A delta-prepared DeviceSnapshot reuses the base revision's resident
    tables untouched and adds small per-view overlays: an adds level
    (probed exactly like the base, OR-ed in) and tombstone sets (exact
    identity keys that void base hits).  All caps/flags here are pow2/
    stable-bucketed so consecutive deltas reuse the compiled kernel."""

    has_adds: bool = False  # any delta primary rows
    e_cap: int = 4  # delta primary hash bucket cap
    e_slots: Tuple[int, ...] = ()  # slots with delta primary rows
    has_tombs: bool = False  # any removed-row identities
    tb_cap: int = 4
    has_us: bool = False  # delta userset-view rows
    us_cap: int = 4  # delta us group-hash bucket cap
    us_fan: int = 1  # delta us max rows per (slot, res)
    us_slots: Tuple[int, ...] = ()
    has_ustomb: bool = False  # tombstoned userset rows
    utb_cap: int = 4
    t_dirty: bool = False  # tombstoned us rows under T-covered slots
    td_cap: int = 4
    has_ar: bool = False  # delta arrow-view rows
    ar_cap: int = 4
    ar_fan: int = 1
    ar_slots: Tuple[int, ...] = ()
    has_artomb: bool = False
    atb_cap: int = 4
    # delta gate-column presence (the delta tables reuse the BASE layouts,
    # so these can only be true when the base flags are)
    e_hascav: bool = False
    e_hasexp: bool = False
    # permission-fold maintenance overlay (engine/fold.py
    # fold_delta_update): folded slots stay on the pf probe pair under a
    # delta — base hits at DIRTY resources are voided and replacement
    # rows probed from small replicated overlay tables
    #: fold maintenance downgraded for the rest of this chain: folded
    #: pairs compile their WALKED programs (which see the dl_* overlays)
    #: instead of the pf probe pair — set when fold_delta_update
    #: declines (eligibility flip / hot-ancestor dirty set / overlay
    #: past its row cap); sticky until compaction re-folds the base
    pf_off: bool = False
    pf_dirty: bool = False  # any dirty (slot, res) keys
    pfd_cap: int = 4
    pf_ovl_e: bool = False  # overlay pf_e rows
    pfo_e_cap: int = 4
    pf_ovl_hascav: bool = False  # overlay layout flags (independent of base)
    pf_ovl_hasuntil: bool = False
    pf_ovl_haswc: bool = False
    pf_ovl_u: bool = False  # overlay pf_u (folded userset) rows
    pfo_u_cap: int = 4
    pfo_u_fan: int = 1
    #: T-index disabled for the rest of this chain (sticky, like pf_off):
    #: membership-closure deltas staled more baked T rows than the dirty
    #: budget covers — the KU path probes the live closure instead
    t_off: bool = False


@dataclass(frozen=True)
class FlatMeta:
    """Static per-snapshot table geometry the kernel closes over.

    Keys are PACKED into ≤2 int32 columns (``N``/``S1`` radices) — every
    probe step then costs 3 gathers (rows + 2 keys) instead of 5, and
    range probes cost 2.  Graphs too large to pack (num_nodes·num_slots ≥
    2³¹) skip the flat engine and use the legacy two-phase kernel.

    Every count is a pow2 BUCKET (padded array length), not an exact row
    count, and the node radix rounds to pow2 — so Watch-driven deltas keep
    the same FlatMeta (and the same compiled kernel) until a table crosses
    a pow2 boundary, instead of recompiling on every revision."""

    N: int  # node-id packing radix: pow2 ≥ num_nodes
    S1: int  # num_slots + 1 (srel1 radix)
    e_cap: int
    e_n: int  # padded primary-row bucket
    usr_cap: int  # userset (rel, res) range-group table
    usr_gn: int
    us_rows: int
    arr_cap: int  # arrow (rel, res) range-group table
    arr_gn: int
    ar_rows: int
    cl_cap: int  # flattened closure pair table
    cl_n: int
    has_closure: bool
    pus_cap: int
    pus_n: int
    ovf_cap: int  # closure-overflow source table
    ovf_n: int
    has_ovf: bool
    #: ((rel_slot, max_fanout_pow2), ...) actual max children per (slot,
    #: resource) in the arrow view — folder trees have 1 parent, so the
    #: unrolled lattice stays narrow regardless of the config cap
    ar_fanout_by_slot: Tuple[Tuple[int, int], ...] = ()
    #: per-view "any caveated rows" / "any expiring rows" flags: views
    #: without them compile trivial gates (no CEL VM, no expiry gathers)
    e_hascav: bool = False
    e_hasexp: bool = False
    us_hascav: bool = False
    us_hasexp: bool = False
    ar_hascav: bool = False
    ar_hasexp: bool = False
    #: slots with ≥1 row in the primary / userset views — leaf code for a
    #: slot with no data compiles to nothing
    e_slots: Tuple[int, ...] = ()
    us_slots: Tuple[int, ...] = ()
    #: any wildcard-subject edges at all / any wildcard closure sources —
    #: both False in most worlds, erasing the wildcard probe sites
    has_wc_edges: bool = False
    has_wc_closure: bool = False
    #: ((rel_slot, max_userset_edges_pow2), ...) actual max userset grants
    #: per (slot, resource) — org⟶2 teams means 2 closure probes, not the
    #: config cap of 8
    us_fanout_by_slot: Tuple[Tuple[int, int], ...] = ()
    #: T-index: the materialized (slot·N+res, member-key) → until-values
    #: join of userset edges with the closure — a userset grant test is
    #: ONE probe.  ``t_slots`` are the slots it covers (no caveated /
    #: permission-valued userset rows); the dynamic root leaf skips the
    #: KU path when it covers every us-bearing slot of the dispatch
    has_tindex: bool = False
    t_cap: int = 4
    t_n: int = 8
    t_slots: Tuple[int, ...] = ()
    #: any permission-valued userset rows in THIS snapshot (drives whether
    #: the interleaved userset view carries a ``perm`` column)
    us_hasperm: bool = False
    #: block-slice layout active (bucket-ordered interleaved tables probed
    #: with one contiguous [cap, w] slice per query — see engine/hash.py)
    blockslice: bool = False
    #: bucket-ALIGNED tables (engine/hash.py build_aligned): per aligned
    #: table, (tbl_key, w, caps) — ``caps`` is the width-stratum ladder:
    #: arrays ``{tbl_key}_al`` / ``{tbl_key}_als`` / ``{tbl_key}_als2``…
    #: replace the off+interleave pair, and a probe is one row gather
    #: per level (each salted by its level index)
    aligned: Tuple[Tuple[str, int, Tuple[int, ...]], ...] = ()
    #: HBM-lean bit-packed tables (engine/packed.py): (tbl_key, spec)
    #: per packed table — the named array holds uint16 lanes and every
    #: probe site decodes with fused shift/mask ops right after its
    #: gather.  Specs derive from geometry + replicated domains, so the
    #: partitioned multihost build agrees on them before building
    packed: Tuple[Tuple[str, Tuple], ...] = ()
    #: packed bucket-offset arrays: (off_key, anchor_shift) — the named
    #: array holds uint16 residuals and ``{off_key}_a`` the int32 block
    #: anchors; off[i] == anchor[i >> shift] + residual[i]
    packed_off: Tuple[Tuple[str, int], ...] = ()
    #: reverse-CSR lookup index (engine/rev.py; the frontier-SpMV tables
    #: engine/spmv.py hops over): ``rvx``/``rv_off`` (all edges keyed by
    #: k2 — reverse reachability), ``rax``/``ra_off`` (arrow rows keyed
    #: by child — reverse tupleset traversal), and ``fwx``/``fw_off``
    #: (all edges keyed by k1 — forward enumeration for LookupSubjects).
    #: Caps are pow2 max bucket occupancies — the frontier kernel's
    #: in-bucket bisect depth, not probe unroll counts
    has_rev: bool = False
    has_fw: bool = False
    rv_cap: int = 4
    ra_cap: int = 4
    fw_cap: int = 4
    #: LSM delta level riding on this snapshot's base tables (None = the
    #: snapshot was fully prepared)
    delta: Optional[DeltaMeta] = None
    #: tables are bucket-sharded / stacked for shard_map (the kernel must
    #: be built with the matching ``axis``; make_flat_fn enforces this)
    sharded: bool = False
    #: partitioned-SERVE placement (engine/partition.py partition_feed
    #: with serve="routed"): only the primary/fold point tables (ehx,
    #: pfx) are split along the model axis — everything else (userset /
    #: arrow / T / closure / pus / ovf / pfu / csr / rc stacked tables)
    #: is membership- or group-structure-sized and placed WHOLE on every
    #: device, mirroring the host partition (membership subgraph
    #: replicated, edges partitioned).  The kernel then resolves those
    #: tables' bucket owners arithmetically (no collective at the site),
    #: so the only remaining collectives are the e/pf probes at derived
    #: keys — and an owner-ROUTED batch, whose root probes are local by
    #: construction, dispatches with no collectives at all
    part_serve: bool = False
    #: flattened recursive hierarchies (the resource-side Leopard index):
    #: ((ts_slot, group_cap, fan), ...) — per eligible tupleset, the
    #: ancestor-closure tables rc{ts}_off / rc{ts}gx / rc{ts}x exist and
    #: the kernel evaluates ``perm = ∃ ancestor: rest`` in ONE level
    rc_slots: Tuple[Tuple[int, int, int], ...] = ()
    #: longest arrow chain in the DATA (longest path over the ar view),
    #: or -1 when the arrow graph has a cycle / exceeded the probe cap.
    #: Bounds recursion unrolling: beyond this many arrow hops there are
    #: no real children, so deeper unrolls are provably dead — a schema-
    #: recursive folder tree of depth 4 compiles 4 levels, not the full
    #: flat_recursion budget.  Pow2-bucketed for delta stability
    ar_data_depth: int = -1
    #: dense slot remap (SlotMaps): raw slot → packed k1 / k2 id, -1 =
    #: inactive (a key using it can never match).  Static kernel sites
    #: map at trace time; the query matrix maps on the host (build_qm).
    #: This is what moves the int32 cliff from schema-slot count to
    #: ACTIVE-slot count
    k1_dense: Tuple[int, ...] = ()
    k2_dense: Tuple[int, ...] = ()
    #: permission fold (engine/fold.py P-index): (type_name, perm_slot)
    #: pairs whose BASE evaluation is the pf_e probe + the pf_u range
    #: slice intersected with the closure — their programs compile to
    #: nothing when no delta level rides the base (a delta reverts to
    #: the walked program, which keeps add/tombstone semantics exact
    #: without incremental fold maintenance)
    fold_pairs: Tuple[Tuple[str, int], ...] = ()
    pf_e_cap: int = 4
    pf_u_cap: int = 4  # pf_u group-table probe cap
    pf_u_fan: int = 1  # max folded groups per (slot, resource), pow2
    #: csr closure-by-source view (the fold's subject side): probe cap of
    #: the source-keyed group table and max closure rows per source.
    #: The kernel slices the subject's group closure ONCE per query and
    #: intersects it with each pf_u group list in registers — the
    #: sorted-key-column intersection that replaces both the dense
    #: (resource × member) T-join and per-group hash probes
    pf_s_cap: int = 4
    pf_s_fan: int = 1
    #: DIRECT range lookup for the fold's pf_u/csr views (single-chip):
    #: ``pfu_start``/``csr_start`` offset arrays indexed by the packed
    #: key itself — two element gathers per range instead of a hash
    #: probe (~14× cheaper on gather-poor CPUs; measured in-repo).
    #: False = the key space outgrew the budget, hash group tables used.
    #: The csr side has its own flag: membership-delta chains flip it to
    #: the hash layout (rebuilding the dense offset array per revision
    #: costs more than the write budget; a full prepare restores direct)
    pf_direct: bool = False
    pf_s_direct: bool = False
    #: every pf_u row / closure row is unexpiring on both planes: the
    #: kernel skips the until-column slices and plane masks entirely
    pf_u_alllive: bool = False
    pf_s_alllive: bool = False
    pf_hascav: bool = False
    pf_hasuntil: bool = False
    pf_haswc: bool = False
    pf_has_e: bool = False
    pf_has_u: bool = False


def placement_split(dsnap) -> Dict[str, int]:
    """{"total", "sharded", "replicated"} resident device-table bytes:
    of this snapshot's arrays, how many a routed partitioned serve
    (``FlatMeta.part_serve``) would SPLIT along the model axis — the
    primary/fold-point tables (ehx*, pfx*) and their width-stratum
    views — versus replicate whole on every device.  The placement
    advisor (tune/) reads this to decide whether routing buys enough
    per-device HBM to be worth the mesh: a snapshot whose bytes are
    dominated by membership-sized replicated tables gains nothing from
    partitioning."""
    total = 0
    sharded = 0
    for k, a in dsnap.arrays.items():
        nb = int(getattr(a, "nbytes", 0))
        total += nb
        if k.startswith("ehx") or k.startswith("pfx"):
            sharded += nb
    return {
        "total": total, "sharded": sharded,
        "replicated": total - sharded,
    }


def _gate_cols(hascav: bool, hasexp: bool) -> list:
    return (["cav", "ctx"] if hascav else []) + (["exp"] if hasexp else [])


def _lay(names: list) -> Dict[str, int]:
    return {n: i for i, n in enumerate(names)}


def e_layout(meta: "FlatMeta") -> Dict[str, int]:
    """Column layout of the interleaved primary-edge bucket table."""
    return _lay(["k1", "k2"] + _gate_cols(meta.e_hascav, meta.e_hasexp))


def us_layout(meta: "FlatMeta") -> Dict[str, int]:
    """Column layout of the interleaved userset-view row table."""
    return _lay(
        ["subj", "srel"]
        + _gate_cols(meta.us_hascav, meta.us_hasexp)
        + (["perm"] if meta.us_hasperm else [])
    )


def ar_layout(meta: "FlatMeta") -> Dict[str, int]:
    """Column layout of the interleaved arrow-view row table."""
    return _lay(["child"] + _gate_cols(meta.ar_hascav, meta.ar_hasexp))


def _round_cap(c: int) -> int:
    """Hash-probe caps bucket to pow2 with a floor of 4: a few extra
    unrolled probe steps are cheaper than recompiling the kernel every
    time a delta nudges a table's max bucket occupancy between 1, 2, 4."""
    for p in (4, 8, 16, 32):
        if c <= p:
            return p
    return c


def _round_fan(c: int) -> int:
    """Arrow/userset fan-outs bucket to pow2 with NO floor: a folder tree
    with 1 parent must keep its width-1 lattice (4^depth would blow the
    flat_max_width budget and degrade deep grants to host fallbacks)."""
    for p in (1, 2, 4, 8, 16, 32):
        if c <= p:
            return p
    return c


def _pad(a: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full(size, fill, np.int32)
    out[: a.shape[0]] = a
    return out


def _pack(a: np.ndarray, radix: int, b) -> np.ndarray:
    from ..native.sort import pack32

    return pack32(a, b, radix)


def _uniq_small(parts, domain: int) -> np.ndarray:
    """Sorted unique over int columns whose values live in [0, domain)
    (slot ids): an occupancy scatter + flatnonzero instead of the
    concatenate+sort np.unique pays — O(E) with no 30M-row sort.
    Output is int64, matching np.unique of int64-cast inputs."""
    occ = np.zeros(max(domain, 1), bool)
    for p in parts:
        if p.shape[0]:
            occ[p] = True
    return np.flatnonzero(occ)


@dataclass(frozen=True)
class SlotMaps:
    """Dense remap of the ACTIVE slots — the packing radices cover only
    slots that actually appear in keys, not the schema's full slot count.
    A 100M-node world with 15 active slots packs fine even when the
    schema declares hundreds (the int32 cliff moves from
    pow2(nodes)·(schema slots+1) to pow2(nodes)·(active slots+1)).

    ``k1[slot]`` → dense row-key id (slots with stored/folded rows;
    queried permissions map through the same table, -1 = can never
    match).  ``k2[slot]`` → dense subject-relation id (slots appearing
    in any subject-relation position); ``S1`` = len(active k2) + 1, the
    k2 radix (0 stays "direct subject")."""

    k1: np.ndarray  # int32[num_slots] → dense id or -1
    k2: np.ndarray  # int32[num_slots] → dense id or -1
    k1_raw: np.ndarray  # int32[n_k1] dense → raw slot (inverse)
    k2_raw: np.ndarray  # int32[S1-1] dense → raw slot (inverse)
    n_k1: int
    S1: int


def _active_maps(snap, cl, extra_k1) -> SlotMaps:
    """The dense slot maps of one snapshot (+closure, + fold slots).
    Slot values live in [0, num_slots): uniques come from an occupancy
    scatter (_uniq_small) — no concatenated 30M-row sort."""
    ns = max(snap.num_slots, 1)
    k1_raw = _uniq_small([
        snap.e_rel, snap.us_rel, snap.ar_rel,
        np.asarray(sorted(extra_k1), np.int64),
    ], ns)
    # us_srel covers every stored subject-relation by construction (the
    # userset view IS the primary rows with srel1 > 0), so the k2 actives
    # need no O(E) pass over e_srel1
    k2_raw = _uniq_small([
        snap.us_srel,
        cl.c_srel1[cl.c_srel1 > 0] - 1,
        cl.c_grel,
        snap.pus_r,
        cl.ovf_srel1[cl.ovf_srel1 > 0] - 1,
    ], ns)
    k1 = np.full(ns, -1, np.int32)
    k1[k1_raw] = np.arange(k1_raw.shape[0], dtype=np.int32)
    k2 = np.full(ns, -1, np.int32)
    k2[k2_raw] = np.arange(k2_raw.shape[0], dtype=np.int32)
    return SlotMaps(
        k1=k1, k2=k2,
        k1_raw=k1_raw.astype(np.int32), k2_raw=k2_raw.astype(np.int32),
        n_k1=int(k1_raw.shape[0]),
        S1=int(k2_raw.shape[0]) + 1,
    )


def _m_srel1(maps: SlotMaps, srel1: np.ndarray) -> np.ndarray:
    """Raw srel1 column (0 = direct, else slot+1) → dense srel1.  One
    fused native pass when available (numpy chain fallback, identical
    values)."""
    from ..native import lib as _native_lib

    L = _native_lib()
    n = int(srel1.shape[0])
    if L is not None and n >= (1 << 16):
        import ctypes

        s = np.ascontiguousarray(srel1, np.int32)
        k2 = np.ascontiguousarray(maps.k2, np.int32)
        out = np.empty(n, np.int32)
        p32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        L.gi_msrel1(
            p32(s), p32(k2), ctypes.c_int64(k2.shape[0]),
            ctypes.c_int64(n), p32(out),
        )
        return out
    return np.where(
        srel1 == 0, 0, maps.k2[np.clip(srel1 - 1, 0, None)] + 1
    ).astype(np.int32)


def _node_radix(snap, maps: SlotMaps) -> Optional[int]:
    """The node packing radix N with delta headroom, or None when the
    DENSE keys still don't fit int32 (such graphs use the legacy
    engine)."""
    N = _ceil_pow2(max(snap.num_nodes, 1), 8)
    width = max(maps.n_k1, maps.S1, 1)
    if N * width >= 2**31:
        return None
    # headroom for Watch-driven deltas: new nodes (fresh users/resources)
    # must stay under the packing radix or every delta-prepare bails to a
    # full rebuild — double N whenever the key space still fits int32
    if N < 2 * snap.num_nodes and 2 * N * width < 2**31:
        N *= 2
    return N


def _view_flags_of(snap) -> Dict[str, bool]:
    return dict(
        e_hascav=bool(snap.e_caveat.any()),
        e_hasexp=bool(snap.e_exp.any()),
        us_hascav=bool(snap.us_caveat.any()),
        us_hasexp=bool(snap.us_exp.any()),
        us_hasperm=bool(snap.us_perm.any()),
        ar_hascav=bool(snap.ar_caveat.any()),
        ar_hasexp=bool(snap.ar_exp.any()),
    )


def rc_candidates(compiled: CompiledSchema, plan: DevicePlan):
    """Self-recursive arrow hierarchies eligible for ancestor flattening
    (the resource-side Leopard index): programs of shape
    ``perm = union(rest..., ts->perm)`` on a type whose ``ts`` edges stay
    WITHIN the type (pure hierarchy, e.g. folder.parent).  Returns
    {(type_name, perm_slot): (ts_slot, rest_ir)} where ``rest_ir`` is the
    union of the non-recursive children — the flattened evaluation is
    ``perm(n) = ∃ a ∈ ancestors_ts*(n): rest(a)`` with the path's
    admissibility folded through the closure semiring."""
    out = {}
    for (tname, tid, slot, expr) in plan.topo_programs:
        if expr[0] != "union":
            continue
        ct = compiled.types[compiled.type_ids[tname]]
        rest = []
        ts_slots = set()
        ok = True
        for child in expr[1]:
            if child[0] == "arrow" and plan.ts_slots[child[1]] >= 0:
                ts_slot = plan.ts_slots[child[1]]
                if child[2] == slot:
                    # the recursive child: its tupleset must only reach
                    # this same type (direct subjects; arrows traverse
                    # ellipsis subjects only)
                    relation = ct.relations.get(ts_slot)
                    if relation is None or any(
                        a.type_id != tid or a.relation_slot >= 0
                        or a.wildcard
                        for a in relation.allowed
                    ):
                        ok = False
                        break
                    ts_slots.add(ts_slot)
                    continue
            # non-recursive children must not re-reach this slot at all
            if _ir_refs_slot(child, slot):
                ok = False
                break
            rest.append(child)
        if ok and len(ts_slots) == 1 and rest:
            out[(tname, slot)] = (next(iter(ts_slots)), ("union", tuple(rest)))
    return out


def cfg_budget(config: EngineConfig) -> int:
    """Arrow hops the unrolled recursion can cover exactly."""
    return config.flat_recursion


def _ir_refs_slot(ir: ExprIR, slot: int) -> bool:
    tag = ir[0]
    if tag == "ref":
        return ir[1] == slot
    if tag == "arrow":
        return ir[2] == slot
    if tag in ("union", "inter"):
        return any(_ir_refs_slot(c, slot) for c in ir[1])
    if tag == "excl":
        return _ir_refs_slot(ir[1], slot) or _ir_refs_slot(ir[2], slot)
    return False


def _arrow_closure(snap, ts_slot: int, *, per_node_cap: int = 64,
                   max_hops: int = 64):
    """Reflexive-transitive ancestor closure over ONE tupleset's arrow
    edges, with the membership closure's two-plane max-min expiry
    semiring folded along paths.  Returns (src, anc, d_until, p_until)
    sorted by src — or None when the slot's hierarchy has a data cycle,
    doesn't converge, or some node's ancestor set exceeds the cap
    (the recursive kernel path still answers those worlds)."""
    from ..store.closure import NEVER, NO_EXP

    m = snap.ar_rel == ts_slot
    src = snap.ar_res[m].astype(np.int64)
    dst = snap.ar_child[m].astype(np.int64)
    keep = dst >= 0
    src, dst = src[keep], dst[keep]
    cav = snap.ar_caveat[m][keep]
    exp = snap.ar_exp[m][keep]
    w = np.where(exp == 0, np.int64(NO_EXP), exp.astype(np.int64)).astype(np.int32)
    e_d = np.where(cav == 0, w, NEVER)
    e_p = w
    order = np.argsort(src, kind="stable")
    e_src, e_dst = src[order], dst[order]
    e_d, e_p = e_d[order], e_p[order]

    from ..store.closure import _expand_join

    from ..native.sort import lexsort2

    def dedup(s, a, d, p):
        # native parallel lexsort, same reason as store/closure.py
        # group_max: numpy lexsort is tens of seconds at big pair counts
        o = lexsort2(s.astype(np.int32), a.astype(np.int32))
        s, a, d, p = s[o], a[o], d[o], p[o]
        first = np.ones(s.shape[0], bool)
        first[1:] = (s[1:] != s[:-1]) | (a[1:] != a[:-1])
        st = np.nonzero(first)[0]
        return (
            s[first], a[first],
            np.maximum.reduceat(d, st), np.maximum.reduceat(p, st),
        )

    c_s, c_a, c_d, c_p = dedup(e_src, e_dst, e_d, e_p)
    n_s, n_a, n_d, n_p = c_s, c_a, c_d, c_p
    for _ in range(max_hops):
        if n_s.size == 0:
            break
        reps, ii = _expand_join(e_src, n_a)
        if reps.size == 0:
            break
        j_s = n_s[reps]
        j_a = e_dst[ii]
        j_d = np.minimum(n_d[reps], e_d[ii])
        j_p = np.minimum(n_p[reps], e_p[ii])
        if (j_s == j_a).any():
            return None  # data cycle: keep the recursive path
        m_s = np.concatenate([c_s, j_s])
        m_a = np.concatenate([c_a, j_a])
        m_d = np.concatenate([c_d, j_d])
        m_p = np.concatenate([c_p, j_p])
        new_s, new_a, new_d, new_p = dedup(m_s, m_a, m_d, m_p)
        if new_s.shape[0] == c_s.shape[0] and (new_d == c_d).all() and (
            new_p == c_p
        ).all():
            break
        # the next frontier: improved/new pairs only (semi-naive)
        pk_old = c_s * np.int64(2**31) + c_a
        pk_new = new_s.astype(np.int64) * np.int64(2**31) + new_a
        pos = np.searchsorted(pk_old, pk_new)
        posc = np.clip(pos, 0, max(pk_old.shape[0] - 1, 0))
        found = (pk_old.shape[0] > 0) & (pk_old[posc] == pk_new)
        old_d = np.where(found, c_d[posc], NEVER)
        old_p = np.where(found, c_p[posc], NEVER)
        imp = (new_d > old_d) | (new_p > old_p)
        n_s, n_a = new_s[imp], new_a[imp]
        n_d, n_p = new_d[imp], new_p[imp]
        c_s, c_a, c_d, c_p = new_s, new_a, new_d, new_p
    else:
        return None  # hop budget exhausted

    # STRICT ancestors only: the kernel always evaluates `rest` at the
    # node itself through a dedicated reflexive lane, so a range miss
    # simply means "self only"
    if c_s.size:
        counts = np.bincount(c_s.astype(np.int64))
        if counts.max() > per_node_cap:
            return None
    return c_s.astype(np.int32), c_a.astype(np.int32), c_d, c_p


def _arrow_data_depth(snap, cap: int = 64, ts_slot: Optional[int] = None) -> int:
    """Longest path, in arrow hops, over the DATA's res→child arrow edges
    (all tupleset relations together, or just ``ts_slot``'s); -1 on a
    data cycle or past ``cap``.  Bellman-style relaxation over the
    res-grouped view: converges in (true depth) rounds on a DAG — folder
    trees are ~log-depth, so this is a handful of O(AR) numpy passes at
    prepare time.  The result is bucketed to the next EVEN depth
    (rounding UP keeps every use sound): FlatMeta is the kernel-cache
    key, so a tree deepening 4→5 must not recompile on every prepare —
    and pow2 granularity would round the common depth 5 up to 8, keeping
    60% of the dead unroll the recursion cut exists to remove."""
    if ts_slot is not None:
        m = snap.ar_rel == ts_slot
        res = snap.ar_res[m].astype(np.int64)
        child = np.ascontiguousarray(snap.ar_child[m], np.int64)
    else:
        res = snap.ar_res.astype(np.int64)
        child = np.ascontiguousarray(snap.ar_child, np.int64)
    AR = int(res.shape[0])
    if AR == 0:
        return 0
    order = np.argsort(res, kind="stable")
    res_s, child_s = res[order], child[order]
    first = np.ones(AR, bool)
    first[1:] = res_s[1:] != res_s[:-1]
    starts = np.nonzero(first)[0]
    uniq_res = res_s[starts]
    childc = np.clip(child_s, 0, max(snap.num_nodes - 1, 0))
    cvalid = child_s >= 0
    depth = np.zeros(snap.num_nodes, np.int32)
    for _ in range(cap):
        vals = np.where(cvalid, depth[childc] + 1, 0)
        upd = np.maximum.reduceat(vals, starts)
        if (upd <= depth[uniq_res]).all():
            d = int(depth.max())
            return d + (d & 1)
        depth[uniq_res] = np.maximum(depth[uniq_res], upd)
    return -1


def _run_maxes(gk: np.ndarray, glo: np.ndarray, ghi: np.ndarray, N: int,
               inv: np.ndarray):
    """Per-RAW-slot max run length of a packed (dense_slot·N + res) range
    index (pow2-bucketed so retraces are rare).  ``inv`` maps the packed
    DENSE slot ids back to raw slots (SlotMaps.k1_raw) — the kernel's
    static gating is raw-slot keyed."""
    fans: Dict[int, int] = {}
    if gk.shape[0]:
        slots_of = gk.astype(np.int64) // N
        lens = (ghi - glo).astype(np.int64)
        first = np.ones(gk.shape[0], bool)
        first[1:] = slots_of[1:] != slots_of[:-1]
        starts = np.nonzero(first)[0]
        for s, m in zip(slots_of[starts], np.maximum.reduceat(lens, starts)):
            fans[int(inv[int(s)])] = _round_fan(int(m))
    return tuple(sorted(fans.items()))


def _tindex_join(
    snap, config: EngineConfig, cl, us_gk, cl_k1, cl_k2, pus_k,
    maps: SlotMaps,
):
    """The T-index join (userset edges ⋈ closure-by-target) shared by both
    layout builders: returns (T_k1, T_k2, T_d, T_p, t_slots) or
    None when disabled/ineligible/oversized.  For slots whose userset rows
    carry no caveats and no permission-valued subjects, {edge expiry ×
    closure semiring} folds into ONE (slot·N+res, member-key) →
    until-values table."""
    from ..store.closure import NO_EXP

    if not (config.flat_tindex and snap.us_rel.shape[0]):
        return None
    ok = (snap.us_caveat == 0) & (snap.us_perm == 0)
    pe_all = _pack(snap.us_subj, maps.S1, maps.k2[snap.us_srel] + 1)
    if snap.pus_n.shape[0]:
        pus_sorted = np.sort(pus_k)
        pos = np.clip(
            np.searchsorted(pus_sorted, pe_all), 0, pus_sorted.shape[0] - 1
        )
        ok &= ~(pus_sorted[pos] == pe_all)
    bad_slots = np.unique(snap.us_rel[~ok])
    elig = ~np.isin(snap.us_rel, bad_slots)
    if not elig.any():
        return None
    pe = pe_all[elig]
    ek1 = us_gk[elig]
    w = np.where(
        snap.us_exp[elig] == 0, np.int64(NO_EXP),
        snap.us_exp[elig].astype(np.int64),
    ).astype(np.int32)
    cap_rows = config.flat_tindex_factor * max(int(snap.us_rel.shape[0]), 1024)
    if config.spmm:
        # the unified sparse core's host instance (engine/spmm.py):
        # same (min, max) until-semiring product, bitwise-identical
        # output — t_join_core below stays as the parity oracle
        from .spmm import tjoin_spmm

        got = tjoin_spmm(
            ek1, pe, w, cl_k1, cl_k2, cl.c_d_until, cl.c_p_until, cap_rows
        )
    else:
        from .fold import t_join_core

        got = t_join_core(
            ek1, pe, w, cl_k1, cl_k2, cl.c_d_until, cl.c_p_until, cap_rows
        )
    if got is None:
        return None
    return (
        *got,
        tuple(int(s) for s in _uniq_small([snap.us_rel[elig]], snap.num_slots)),
    )


def _rc_build(
    snap, config: EngineConfig, plan: Optional[DevicePlan], ar_depth: int
):
    """Ancestor closures for every flattenable recursive hierarchy:
    {ts_slot: (src, anc, d_until, p_until, fan)} (engine-level R-index).

    Built only when the DATA is deeper than the recursion budget: within
    the budget, the unrolled recursion is exact and CHEAPER (narrow
    lattices, no closure fetch); beyond it, the flattened form is the
    only device-exact path — either way no host fallback."""
    if plan is None or not config.flat_rc_index:
        return {}
    if 0 <= ar_depth <= cfg_budget(config):
        return {}  # every hierarchy fits the unroll: nothing to flatten
    cands = rc_candidates(snap.compiled, plan)
    out = {}
    for (_tname, _slot), (ts_slot, _rest) in cands.items():
        if ts_slot in out:
            continue
        # per-tupleset depth: one deep hierarchy must not force closure
        # builds for shallow ones the recursion already answers exactly
        slot_depth = _arrow_data_depth(snap, ts_slot=ts_slot)
        if 0 <= slot_depth <= cfg_budget(config):
            continue
        built = _arrow_closure(snap, ts_slot)
        if built is None:
            continue
        src, anc, d_until, p_until = built
        counts = np.bincount(src.astype(np.int64)) if src.size else np.zeros(1)
        out[ts_slot] = (src, anc, d_until, p_until, _round_fan(int(counts.max())))
    return out


def _fold_packed(fr, snap, maps: SlotMaps, N: int, config: EngineConfig):
    """Dense-packed fold arrays shared by both layout builders:
    (pf_k1, pf_k2, pf_subj, (u_k1, u_gk, u_until, u_fan), flags) or None
    when some resource's folded group fan exceeds the cap (the fold then
    declines; the walked path answers).  Fold rows carry RAW int64
    (subj·(num_slots+1)+srel1) identity keys — decomposed here and
    repacked with the dense radices.  The u side is the reachability-
    pruned (resource, group) table of fold_userset_rows: the member
    closure is intersected at probe time, never joined in."""
    from ..store.closure import NO_EXP
    from .fold import fold_userset_rows

    u_k1, u_gk, u_until = fold_userset_rows(fr, N, maps)
    u_fan = 0
    if u_k1.shape[0]:
        _, counts = np.unique(u_k1, return_counts=True)
        u_fan = int(counts.max())
        if u_fan > config.flat_fold_u_fan_cap:
            return None
    S1_raw = snap.num_slots + 1
    pf_subj = (fr.e_k2 // S1_raw).astype(np.int32)
    pf_srel1 = (fr.e_k2 % S1_raw).astype(np.int32)
    pf_k1 = _pack(maps.k1[fr.e_slot], N, fr.e_res)
    pf_k2 = _pack(pf_subj, maps.S1, _m_srel1(maps, pf_srel1))
    flags = dict(
        pf_hascav=bool((fr.e_cav != 0).any()),
        pf_hasuntil=bool((fr.e_until != NO_EXP).any()),
    )
    return pf_k1, pf_k2, pf_subj, (u_k1, u_gk, u_until, _round_fan(u_fan)), flags


class ClosureHostState:
    """Per-prepared-snapshot host state for the membership-delta path
    (build_delta_arrays): the store-level closure advance state plus the
    reverse indexes the engine needs to keep the device tables honest.

    ``used`` is the BASE revision's userset-subject key set and stays the
    chain's classification authority: every advance classifies delta rows
    against it, so the maintained closure covers the base's used-superset
    even when a chain delta removes a userset's last referencing row.
    That superset is probe-equivalent (closure rows of a dereferenced
    group can only be reached through a userset row citing the group, and
    none exist) and keeps later re-references exact — the group's rows
    were maintained all along.  ``t_pe``/``t_k1`` map raw packed group
    keys of T-covered userset rows to their dense (slot·N + res) keys:
    the rows whose baked T-index entries go stale when a group's closure
    changes."""

    __slots__ = ("st", "used", "t_pe", "t_k1")

    def __init__(self, st, used, t_pe, t_k1):
        self.st = st
        self.used = used
        self.t_pe = t_pe
        self.t_k1 = t_k1


def _closure_host_state(snap, cl, config: EngineConfig, us_gk, t_slots):
    """Build the advance-ready closure state at full-prepare time."""
    from ..store.closure import build_closure_state

    used = getattr(snap, "us_used_keys", None)
    if used is None:
        return None
    num_slots = snap.num_slots
    if t_slots and snap.us_rel.shape[0]:
        elig = np.isin(snap.us_rel, np.asarray(t_slots, np.int64))
        pe = (
            snap.us_subj[elig].astype(np.int64) * (num_slots + 1)
            + snap.us_srel[elig] + 1
        )
        from ..native.sort import sortperm_words, take32, take64

        order = sortperm_words([pe], (pe,))
        t_pe, t_k1 = take64(pe, order), take32(us_gk[elig], order)
    else:
        t_pe = np.zeros(0, np.int64)
        t_k1 = np.zeros(0, np.int32)
    return ClosureHostState(
        build_closure_state(
            snap, cl, per_source_cap=config.closure_source_cap
        ),
        used, t_pe, t_k1,
    )


def _pf_starts(keys: np.ndarray, size: int) -> np.ndarray:
    """Offset array of a key-sorted row set over a dense key domain:
    ``start[k] .. start[k+1]`` is key ``k``'s row range."""
    counts = np.bincount(keys, minlength=size)
    st = np.zeros(size + 1, np.int64)
    np.cumsum(counts, out=st[1:])
    return st.astype(np.int32)


def _pf_col(a: np.ndarray, pad: int, fill) -> np.ndarray:
    """One split pf-view row column: [pow2(rows+pad), 1] int32."""
    n = _ceil_pow2(max(a.shape[0] + pad, 1))
    padded = np.full((n, 1), fill, np.int32)
    padded[: a.shape[0], 0] = a
    return padded


def _max_run_sorted(keys: np.ndarray) -> int:
    """Longest equal-key run of a SORTED key column, O(n) with no sort
    (np.unique would re-sort; this sits on the membership-write path)."""
    if keys.shape[0] == 0:
        return 0
    bounds = np.flatnonzero(np.diff(keys)) + 1
    return int(np.diff(
        np.concatenate([[0], bounds, [keys.shape[0]]])
    ).max())


def _pf_view_tables(
    u_k1, u_gk, u_until, u_fan,
    cl_k1, cl_k2, cl_d, cl_p, s_fan,
    *, maps: SlotMaps, N: int, S1: int, fold_slots, config: EngineConfig,
    hk: Optional[Dict] = None,
):
    """Single-chip pf_u / csr view tables: SPLIT 1-wide row columns
    (narrow contiguous slices vectorize ~15× better than wide ones on
    gather-poor CPUs; measured in-repo) with the row range resolved
    DIRECTLY — ``pfu_start``/``csr_start`` offset arrays indexed by the
    packed key itself, two element gathers per range — or through legacy
    hash group tables when the key space is over budget.  Until columns
    are omitted entirely when every row is unexpiring (the common case;
    the kernel then skips the plane masks).  Returns (arrays, meta kw)."""
    from ..store.closure import NO_EXP

    out: Dict[str, np.ndarray] = {}
    pad_u, pad_s = max(64, u_fan), max(64, s_fan)
    out["pfu_gk"] = _pf_col(u_gk, pad_u, -1)
    u_alllive = bool((u_until == NO_EXP).all()) if u_until.shape[0] else True
    if not u_alllive:
        out["pfu_u"] = _pf_col(u_until, pad_u, 0)
    out["csr_gk"] = _pf_col(cl_k2, pad_s, -1)
    s_alllive = (
        bool((cl_d == NO_EXP).all() and (cl_p == NO_EXP).all())
        if cl_k1.shape[0] else True
    )
    if not s_alllive:
        out["csr_d"] = _pf_col(cl_d, pad_s, 0)
        out["csr_p"] = _pf_col(cl_p, pad_s, 0)
    n_f = max(len(fold_slots), 1)
    budget = config.flat_pf_direct_max_entries
    u_direct = n_f * N + 1 <= budget
    s_direct = N * S1 + 1 <= budget
    kw = dict(
        pf_direct=u_direct, pf_s_direct=s_direct,
        pf_u_alllive=u_alllive, pf_s_alllive=s_alllive,
    )
    hk = hk or {}
    if u_direct:
        # remap fold slots to a compact id so pfu_start spans only
        # fold-slots·N entries (the full active-k1 domain would be ~3×)
        fidx = np.full(max(maps.n_k1, 1), -1, np.int64)
        for i, s in enumerate(fold_slots):
            fidx[maps.k1[s]] = i
        u64 = u_k1.astype(np.int64)
        out["pfu_start"] = _pf_starts(fidx[u64 // N] * N + u64 % N, n_f * N)
    else:
        pfu = build_range_hash(u_k1, **hk)
        out["pfu_off"] = pfu.index.off
        out["pfugx"] = interleave_buckets(
            pfu.index, [pfu.gk, pfu.glo, pfu.ghi]
        )
        kw.update(pf_u_cap=_round_cap(pfu.index.cap))
    if s_direct:
        out["csr_start"] = _pf_starts(cl_k1.astype(np.int64), N * S1)
    else:
        csr = build_range_hash(cl_k1, **hk)
        out["csr_off"] = csr.index.off
        out["csrgx"] = interleave_buckets(
            csr.index, [csr.gk, csr.glo, csr.ghi]
        )
        kw.update(pf_s_cap=_round_cap(csr.index.cap))
    return out, kw


# ---------------------------------------------------------------------------
# HBM-lean packing (engine/packed.py): spec derivation + post-pass
# ---------------------------------------------------------------------------


def _al_key(tbl_key: str, lvl: int) -> str:
    """Device-array name of one aligned width-stratum level."""
    if lvl == 0:
        return tbl_key + "_al"
    return tbl_key + "_als" + ("" if lvl == 1 else str(lvl))


def _until_dom(*arrays) -> Optional[Tuple[int, ...]]:
    """Dictionary domain of until-value columns: the closure semiring
    only ever emits {NEVER, NO_EXP, real timestamps}; almost every world
    has no expiring membership edges, so the whole column fits a 2-bit
    dictionary over {NEVER, -1 (pad), 0, NO_EXP}.  Returns None when
    real timestamps appear (the column stays a 32-bit field)."""
    from ..store.closure import NEVER, NO_EXP

    cand = np.asarray(
        sorted({int(NEVER), -1, 0, int(NO_EXP)}), np.int64
    )
    for a in arrays:
        if a is None or a.shape[0] == 0:
            continue
        v = a.astype(np.int64, copy=False)
        if not bool(np.isin(v, cand).all()):
            return None
    return tuple(int(c) for c in cand)


def _pack_domains(snap, config: EngineConfig) -> Dict:
    """Replicated per-world pack domains every build path derives
    identically (raw snapshot columns are process-replicated even under
    the multihost partitioned feed — only built TABLES are sharded):
    gate-column value bounds.  Until dictionaries and fan bounds join
    per builder at the sites that compute those arrays globally."""
    mx = lambda *cols: max(
        [int(c.max()) for c in cols if c is not None and c.shape[0]] or [0]
    )
    return {
        "max_cav": mx(snap.e_caveat, snap.us_caveat, snap.ar_caveat),
        "max_ctx": mx(snap.e_ctx, snap.us_ctx, snap.ar_ctx),
        "until": {},
        "fan": {},
    }


#: group tables and the row views their (glo, ghi) ranges index into —
#: candidates per table because the single-chip fold keeps split 1-wide
#: row columns instead of an interleaved view
_PACK_GROUPS = {
    "usgx": ("usx",),
    "argx": ("arx",),
    "pfugx": ("pfux", "pfu_gk"),
    "csrgx": ("csrx", "csr_gk"),
}


def _pack_descs(name: str, meta: FlatMeta, dom: Dict, out: Dict):
    """Column descriptors of one packable table, derived from geometry
    (radices, layout flags, shapes) + the replicated domains — never
    from scanning the built table, so partitioned shard builds agree."""
    from . import packed as pk

    N, S1 = meta.N, meta.S1
    n_k1 = max(int(x) for x in meta.k1_dense) + 1 if meta.k1_dense else 1
    K1 = pk.col_range(-1, max(n_k1, 1) * N - 1)  # (slot, res) point keys
    K2 = pk.col_range(-1, N * S1 - 1)  # (subj, srel1) / closure keys
    NODE = pk.col_range(-1, N - 1)
    I32 = pk.col_range(-(2 ** 31), 2 ** 31 - 1)

    def until(key: str):
        d = dom["until"].get(key)
        return pk.col_dict(d) if d is not None else I32

    def gates(prefix_cav: bool, prefix_exp: bool):
        g = []
        if prefix_cav:
            g += [pk.col_range(-1, dom["max_cav"]),
                  pk.col_range(-1, dom["max_ctx"])]
        if prefix_exp:
            # rel32 expiry stamps are signed (already-expired edges sit
            # below the epoch): full int32 — no byte win on this field,
            # but every OTHER field in the row still packs, and the
            # domain stays provably sound for owned-subset shard builds
            # (a spec must never commit on one process and fail on
            # another — the agreement-before-build contract)
            g += [I32]
        return g

    if name == "ehx":
        return [K1, K2] + gates(meta.e_hascav, meta.e_hasexp)
    if name == "tx":
        return [K1, K2, until("tx"), until("tx")]
    if name == "clx":
        return [K2, K2, until("clx"), until("clx")]
    if name == "pfx":
        return (
            [K1, K2]
            + gates(meta.pf_hascav, False)
            + ([until("pfx")] if meta.pf_hasuntil else [])
        )
    if name in _PACK_GROUPS:
        rows_len = max(
            [int(out[r].shape[0]) for r in _PACK_GROUPS[name] if r in out]
            or [1]
        )
        gk = {"usgx": K1, "argx": K1, "pfugx": K1, "csrgx": K2}[name]
        fan = int(dom["fan"].get(name, 0))
        return [gk, pk.col_range(-1, rows_len - 1), pk.col_delta(0, fan, 1)]
    if name.startswith("rc") and name.endswith("gx"):
        rows_len = int(out[name[:-2] + "x"].shape[0])
        fan = int(dom["fan"].get(name, 0))
        return [NODE, pk.col_range(-1, rows_len - 1), pk.col_delta(0, fan, 1)]
    if name == "rvx":
        return [K2, K1] + gates(meta.e_hascav, meta.e_hasexp)
    if name == "fwx":
        return [K1, K2] + gates(meta.e_hascav, meta.e_hasexp)
    if name == "rax":
        return [NODE, K1] + gates(meta.ar_hascav, meta.ar_hasexp)
    if name == "usx":
        return (
            [NODE, pk.col_range(-1, S1 - 2)]
            + gates(meta.us_hascav, meta.us_hasexp)
            + ([pk.col_range(-1, 1)] if meta.us_hasperm else [])
        )
    if name == "arx":
        return [NODE] + gates(meta.ar_hascav, meta.ar_hasexp)
    if name == "pfux":
        return [K2, until("pfux")]
    if name == "csrx":
        return [K2, until("clx"), until("clx")]
    if name.startswith("rc") and name.endswith("x"):
        return [NODE, until(name), until(name)]
    return None


#: point-table offset arrays eligible for the anchor+residual encoding
#: (single-chip layouts; stacked offs stay int32 — a shard cannot
#: verify other shards' residual bounds before building).  The fold's
#: DIRECT offset arrays (pfu_start/csr_start — dense-key-indexed, not
#: bucket-indexed) pack under the same scheme: they are monotone row
#: offsets like every other entry here, and the kernel's off_read
#: decodes them identically (ROADMAP "pack the fold's direct offset
#: arrays" follow-on)
_PACK_OFF_KEYS = (
    "eh_off", "th_off", "pfh_off", "clh_off", "usr_off", "arr_off",
    "pfu_off", "csr_off", "push_off", "ovfh_off",
    "pfu_start", "csr_start",
    "rv_off", "ra_off", "fw_off",
)


def _pack_flat(
    out: Dict[str, np.ndarray], meta: FlatMeta, config: EngineConfig,
    dom: Dict, *, pack_off: bool,
) -> Dict:
    """The HBM-lean post-pass: bit-pack every eligible table in ``out``
    in place (chunked — no full-width intermediate copy) and return the
    FlatMeta field overrides ({} when packing is off or nothing won).
    Aligned width-stratum levels share their table's one spec."""
    if not config.packed_on():
        return {}
    from . import packed as pk

    names = (
        ["ehx", "clx", "pfx", "tx", "usx", "arx", "pfux", "csrx",
         "usgx", "argx", "pfugx", "csrgx", "rvx", "fwx", "rax"]
        + [k for k in out if k.startswith("rc") and k.endswith(("x", "gx"))
           and not k.endswith("_off")]
    )
    specs: List[Tuple[str, Tuple]] = []
    for name in names:
        tgt = [k for k in (
            [name] + [_al_key(name, l) for l in range(16)]
        ) if k in out]
        if not tgt:
            continue
        descs = _pack_descs(name, meta, dom, out)
        if descs is None:
            continue
        spec = pk.make_spec(descs)
        if spec is None:
            continue
        w, lanes = spec[0], spec[1]
        ok = True
        packed_arrays = {}
        try:
            for k in tgt:
                a = out[k]
                if k == name:
                    if len(a.shape) != 2 or a.shape[1] != w:
                        ok = False
                        break
                    if hasattr(a, "map_blocks"):  # multihost ShardSlices
                        # a PackError here must FAIL LOUDLY: each process
                        # validates only its owned blocks, and a silent
                        # local despec would diverge FlatMeta across the
                        # processes of one collective program
                        packed_arrays[k] = a.map_blocks(
                            lambda b: pk.pack_rows(b, spec), lanes,
                            np.uint16,
                        )
                    else:
                        packed_arrays[k] = pk.pack_rows(a, spec)
                else:
                    # aligned level: rows are cap*w int32 → cap*lanes
                    size, roww = a.shape
                    cap = roww // w
                    packed_arrays[k] = pk.pack_rows(
                        a.reshape(size * cap, w), spec
                    ).reshape(size, cap * lanes)
        except pk.PackError:
            if any(hasattr(out[k], "map_blocks") for k in tgt):
                raise  # multihost: local despec would diverge the mesh
            ok = False
        if not ok:
            continue
        out.update(packed_arrays)
        specs.append((name, spec))
    off_specs: List[Tuple[str, int]] = []
    if pack_off:
        off_keys = list(_PACK_OFF_KEYS) + [
            k for k in out if k.startswith("rc") and k.endswith("_off")
        ]
        for ok_ in off_keys:
            a = out.get(ok_)
            if a is None or a.dtype != np.int32:
                continue
            got = pk.pack_off(a)
            if got is None:
                continue
            res, anchor = got
            if res.nbytes + anchor.nbytes >= a.nbytes:
                continue
            out[ok_] = res
            out[ok_ + "_a"] = anchor
            off_specs.append((ok_, pk.OFF_ANCHOR_SHIFT))
    up: Dict = {}
    if specs:
        up["packed"] = tuple(sorted(specs))
    if off_specs:
        up["packed_off"] = tuple(sorted(off_specs))
    return up


def build_flat_arrays(
    snap, config: EngineConfig, plan: Optional[DevicePlan] = None
) -> Optional[Tuple[Dict[str, np.ndarray], FlatMeta, Optional[object],
                    Optional[ClosureHostState]]]:
    """Hash-index the snapshot + flatten its membership closure.  Returns
    padded host arrays (merged into DeviceSnapshot.arrays), the static
    FlatMeta, the fold maintenance state, and the closure advance state —
    or None when even the DENSE keys don't pack into int32
    (pow2(num_nodes) · max(active k1 slots, active srels+1) ≥ 2³¹; such
    graphs use the legacy engine).

    Every stage publishes a ``prepare.*`` sample-ring timer
    (utils/metrics.py) so the cold-start wall clock decomposes in the
    bench output: closure flatten, permission fold, dense key packing,
    hash/interleave table builds, T-index join.  ``prepare.build`` is the
    staged pipeline's fault-injection site (utils/faults.py): a transient
    failure here surfaces as a classified retriable error to the client
    envelope, like the round-7 dispatch sites."""
    from ..store.closure import NEVER, build_closure
    from ..utils import faults, metrics

    faults.fire("prepare.build")
    _mt = metrics.default

    # cheap pre-bail for clearly-over-bound worlds, BEFORE the closure
    # and fold are paid for: distinct stored slots lower-bound the dense
    # width (the closure/fold can only add to it).  The O(E) uniques run
    # only when the RAW worst case is over-bound — worlds that fit even
    # without the dense remap skip straight through
    Npre = _ceil_pow2(max(snap.num_nodes, 1), 8)
    if Npre * (snap.num_slots + 1) >= 2**31:
        width_lb = max(
            np.unique(np.concatenate(
                [snap.e_rel, snap.us_rel, snap.ar_rel]
            )).shape[0] if snap.e_rel.shape[0] else 1,
            (np.unique(snap.us_srel).shape[0] + 1)
            if snap.us_srel.shape[0] else 1,
            1,
        )
        if Npre * width_lb >= 2**31:
            return None

    with _mt.timer("prepare.closure_s"):
        cl = build_closure(snap, per_source_cap=config.closure_source_cap)

    # the permission fold runs BEFORE key packing: folded permission
    # slots join the k1 radix (engine/fold.py packs its internal keys in
    # int64 with raw radices, so it is cliff-immune itself)
    BS = config.flat_blockslice
    fr = fstate = None
    if BS and plan is not None:
        from .fold import fold_permissions

        with _mt.timer("prepare.fold_s"):
            got_fold = fold_permissions(snap, config, plan, cl)
        if got_fold is not None:
            fr, fstate = got_fold

    with _mt.timer("prepare.pack_s"):
        maps = _active_maps(
            snap, cl, {slot for _, slot in fr.pairs} if fr is not None else ()
        )
        N = _node_radix(snap, maps)
        if N is None:
            return None
        S1 = maps.S1

        e_k1 = _pack(maps.k1[snap.e_rel], N, snap.e_res)
        e_k2 = _pack(snap.e_subj, S1, _m_srel1(maps, snap.e_srel1))
        us_gk = _pack(maps.k1[snap.us_rel], N, snap.us_res)
        ar_gk = _pack(maps.k1[snap.ar_rel], N, snap.ar_res)
        cl_k1 = _pack(cl.c_src, S1, _m_srel1(maps, cl.c_srel1))
        cl_k2 = _pack(cl.c_g, S1, maps.k2[cl.c_grel] + 1)
        pus_k = _pack(snap.pus_n, S1, maps.k2[snap.pus_r] + 1)
        ovf_k = _pack(cl.ovf_src, S1, _m_srel1(maps, cl.ovf_srel1))

    _t_hash = time.perf_counter()
    # HBM-lean mode: bucket growth bounded (a deeper probe cap costs a
    # few fused compares; 8x offsets cost hundreds of MB), and the pack
    # domains collected alongside the global joins below
    PKD = config.packed_on()
    hk = (
        {"max_factor": config.flat_packed_max_factor, "lean": True}
        if PKD else {}
    )
    dom = _pack_domains(snap, config)
    dom["until"]["clx"] = _until_dom(cl.c_d_until, cl.c_p_until)
    usr = build_range_hash(us_gk, **hk)
    arr = build_range_hash(ar_gk, **hk)
    push = build_hash([pus_k], **hk)
    ovfh = build_hash([ovf_k], **hk)
    dom["fan"]["usgx"] = usr.max_run
    dom["fan"]["argx"] = arr.max_run
    eh = clh = None  # big indexes: built lazily (skipped when aligned)

    out: Dict[str, np.ndarray] = {}
    # view flags, computed up front: they pick the interleaved layouts
    flags = _view_flags_of(snap)
    e_hascav, e_hasexp = flags["e_hascav"], flags["e_hasexp"]
    us_hascav, us_hasexp = flags["us_hascav"], flags["us_hasexp"]
    us_hasperm = flags["us_hasperm"]
    ar_hascav, ar_hasexp = flags["ar_hascav"], flags["ar_hasexp"]

    def put_hash(prefix: str, h) -> None:
        # off keeps its exact size+1 length: the device probe derives the
        # bucket mask from off.shape[0] - 1, which must equal the build
        # size (a pow2 already, so shapes stay bucketed for jit)
        out[prefix + "_off"] = h.off
        out[prefix + "_rows"] = _pad(h.rows, _ceil_pow2(h.rows.shape[0]), 0)

    def put_range(prefix: str, r) -> None:
        G = _ceil_pow2(max(r.gk.shape[0], 1))
        out[prefix + "_gk"] = _pad(r.gk, G, -1)
        out[prefix + "_glo"] = _pad(r.glo, G, 0)
        out[prefix + "_ghi"] = _pad(r.ghi, G, 0)
        put_hash(prefix, r.index)

    # bucket-ALIGNED layout (engine/hash.py build_aligned): on by
    # default on TPU — each point probe is ONE row gather instead of an
    # offsets gather + a serialized block slice (~48M vs 0.75M probes/s
    # measured on silicon, tpu_attempts/micro_blocks.py)
    if config.flat_aligned is not None:
        AL = bool(config.flat_aligned)
    else:
        import jax

        AL = jax.default_backend() == "tpu"
    al_meta: List[Tuple[str, int, int, int]] = []

    def put_block(tbl_key: str, off_key: str, h, key_cols, cols,
                  row_quantum: Optional[int] = None):
        """One point-probe table: bucket-aligned when enabled and it
        fits the byte budget, else bucket offsets + interleaved rows.
        ``h`` is a HashIndex or a zero-arg thunk building one (the
        legacy index is skipped entirely — including its size-doubling
        scan — when the aligned layout lands); returns the HashIndex
        when the legacy layout was emitted, else None.  ``row_quantum``
        trims the rows table's pow2 padding to a multiple (the T join's
        up-to-2x waste; see interleave_buckets)."""
        if AL:
            ai = build_aligned(
                key_cols, cols, max_bytes=config.flat_aligned_max_bytes,
                cover=config.flat_aligned_cover,
            )
            if ai is not None:
                for lvl, (tbl, _cap) in enumerate(ai.levels):
                    out[_al_key(tbl_key, lvl)] = tbl
                al_meta.append((tbl_key, ai.w, ai.caps))
                return None
        if callable(h):
            h = h()
        out[off_key] = h.off
        out[tbl_key] = interleave_buckets(h, cols, quantum=row_quantum)
        return h

    e_gates = (
        ([snap.e_caveat, snap.e_ctx] if e_hascav else [])
        + ([snap.e_exp] if e_hasexp else [])
    )
    ar_gates = (
        ([snap.ar_caveat, snap.ar_ctx] if ar_hascav else [])
        + ([snap.ar_exp] if ar_hasexp else [])
    )
    if BS:
        # block-slice layout: per point-probe table, the bucket offsets +
        # ONE bucket-ordered interleaved matrix (keys ++ payloads) — or
        # its aligned form; per range view, the group table interleaved
        # by bucket and the row view interleaved in its existing
        # key-sorted order
        eh = put_block(
            "ehx", "eh_off", lambda: build_hash([e_k1, e_k2], **hk),
            [e_k1, e_k2],
            [e_k1, e_k2] + e_gates,
        )
        put_block(
            "usgx", "usr_off", usr.index, [usr.gk],
            [usr.gk, usr.glo, usr.ghi],
        )
        out["usx"] = interleave_rows(
            # srel rides DENSE (maps.k2): gk packing in the kernel must
            # match the dense closure/T keys
            [snap.us_subj, maps.k2[snap.us_srel]]
            + ([snap.us_caveat, snap.us_ctx] if us_hascav else [])
            + ([snap.us_exp] if us_hasexp else [])
            + ([snap.us_perm] if us_hasperm else []),
            pad=max(64, config.us_leaf_cap),
        )
        put_block(
            "argx", "arr_off", arr.index, [arr.gk],
            [arr.gk, arr.glo, arr.ghi],
        )
        out["arx"] = interleave_rows(
            [snap.ar_child]
            + ([snap.ar_caveat, snap.ar_ctx] if ar_hascav else [])
            + ([snap.ar_exp] if ar_hasexp else []),
            pad=max(64, config.arrow_fanout),
        )
        clh = put_block(
            "clx", "clh_off", lambda: build_hash([cl_k1, cl_k2], **hk),
            [cl_k1, cl_k2],
            [cl_k1, cl_k2, cl.c_d_until, cl.c_p_until],
        )
        put_block("pusx", "push_off", push, [pus_k], [pus_k])
        put_block("ovfx", "ovfh_off", ovfh, [ovf_k], [ovf_k])
    else:
        eh = build_hash([e_k1, e_k2])
        clh = build_hash([cl_k1, cl_k2])
        put_hash("eh", eh)
        put_range("usr", usr)
        put_range("arr", arr)
        put_hash("clh", clh)
        put_hash("push", push)
        put_hash("ovfh", ovfh)

        # dense srel column for the scattered ku path (the raw us_srel
        # base column no longer matches the dense closure keys)
        out["us_srel_d"] = _pad(
            maps.k2[snap.us_srel],
            _ceil_pow2(max(int(snap.us_rel.shape[0]), 1)), -1,
        )
        E = _ceil_pow2(max(e_k1.shape[0], 1))
        out["e_k1"] = _pad(e_k1, E, -1)
        out["e_k2"] = _pad(e_k2, E, -1)
        P = _ceil_pow2(max(cl.num_pairs, 1))
        out["cl_k1"] = _pad(cl_k1, P, -1)
        out["cl_k2"] = _pad(cl_k2, P, -1)
        out["cl_d_until"] = _pad(cl.c_d_until, P, NEVER)
        out["cl_p_until"] = _pad(cl.c_p_until, P, NEVER)
        out["pus_k"] = _pad(pus_k, _ceil_pow2(max(pus_k.shape[0], 1)), -1)
        out["ovf_k"] = _pad(ovf_k, _ceil_pow2(max(ovf_k.shape[0], 1)), -1)
    _mt.observe("prepare.hash_s", time.perf_counter() - _t_hash)

    # ---- T-index: userset edges ⋈ closure-by-target (shared join) -------
    _t_tindex = time.perf_counter()
    t_kw = dict(has_tindex=False, t_cap=4, t_n=8, t_slots=())
    tj = _tindex_join(snap, config, cl, us_gk, cl_k1, cl_k2, pus_k, maps)
    if tj is not None:
        T_k1, T_k2, T_d, T_p, t_slots = tj
        dom["until"]["tx"] = _until_dom(T_d, T_p)
        th = None
        if BS:
            # row_quantum: the T join is the largest rebuilt-per-prepare
            # rows table (~80% of packed bytes at config 3) — round its
            # rows to a 4096 quantum instead of pow2 (ROADMAP "trim the
            # pow2 row padding on the T join"); snapshot.device_bytes.tx
            # shows the reduction live
            th = put_block(
                "tx", "th_off", lambda: build_hash([T_k1, T_k2], **hk),
                [T_k1, T_k2], [T_k1, T_k2, T_d, T_p],
                row_quantum=4096,
            )
        else:
            th = build_hash([T_k1, T_k2])
            put_hash("th", th)
            TP = _ceil_pow2(max(T_k1.shape[0], 1))
            out["t_k1"] = _pad(T_k1, TP, -1)
            out["t_k2"] = _pad(T_k2, TP, -1)
            out["t_d"] = _pad(T_d, TP, NEVER)
            out["t_p"] = _pad(T_p, TP, NEVER)
        t_kw = dict(
            has_tindex=True,
            t_cap=_round_cap(th.cap) if th is not None else 4,
            t_n=_ceil_pow2(max(th.n, 1)) if th is not None else 8,
            t_slots=t_slots,
        )
    _mt.observe("prepare.tindex_s", time.perf_counter() - _t_tindex)

    # ---- reverse-CSR lookup index (engine/rev.py) ----------------------
    # the frontier-SpMV tables LookupResources/LookupSubjects hop over
    # (engine/spmv.py): edges re-keyed by k2 (reverse), by k1 (forward),
    # and arrow rows by child — built from the SAME packed key columns
    # as the forward tables, M=1 stacked layout
    rev_kw: Dict = {}
    if BS and config.flat_rev_index:
        _t_rev = time.perf_counter()
        from .partition import _hash_cols
        from .rev import build_rev_full, rev_geom, rev_meta_kw

        h_rv = _hash_cols([e_k2])
        ge_rv = rev_geom(h_rv, 1)
        rv_cols = [e_k2, e_k1] + e_gates
        out["rv_off"], out["rvx"] = build_rev_full(
            h_rv, rv_cols, ge_rv, len(rv_cols)
        )
        h_ra = _hash_cols([snap.ar_child])
        ge_ra = rev_geom(h_ra, 1)
        ra_cols = [snap.ar_child, ar_gk] + ar_gates
        out["ra_off"], out["rax"] = build_rev_full(
            h_ra, ra_cols, ge_ra, len(ra_cols)
        )
        h_fw = _hash_cols([e_k1])
        ge_fw = rev_geom(h_fw, 1)
        fw_cols = [e_k1, e_k2] + e_gates
        out["fw_off"], out["fwx"] = build_rev_full(
            h_fw, fw_cols, ge_fw, len(fw_cols)
        )
        rev_kw = rev_meta_kw(ge_rv, ge_ra, ge_fw)
        _mt.observe("prepare.rev_s", time.perf_counter() - _t_rev)

    # resource-side Leopard index: flattened ancestor closures for
    # self-recursive arrow hierarchies (block-slice layout only)
    ar_dd = _arrow_data_depth(snap)
    rc_kw: Dict = {}
    if BS:
        rc_list = []
        for ts_slot, (src, anc, d_u, p_u, fan) in _rc_build(
            snap, config, plan, ar_dd
        ).items():
            ri = build_range_hash(src, **hk)
            put_block(
                f"rc{ts_slot}gx", f"rc{ts_slot}_off", ri.index,
                [ri.gk], [ri.gk, ri.glo, ri.ghi],
            )
            out[f"rc{ts_slot}x"] = interleave_rows(
                [anc, d_u, p_u], pad=max(64, fan)
            )
            dom["until"][f"rc{ts_slot}x"] = _until_dom(d_u, p_u)
            dom["fan"][f"rc{ts_slot}gx"] = fan
            rc_list.append((int(ts_slot), _round_cap(ri.index.cap), fan))
        rc_kw = dict(rc_slots=tuple(sorted(rc_list)))

    wc_nodes = snap.wildcard_node_of_type[snap.wildcard_node_of_type >= 0]

    # ---- permission fold (P-index): rewrites → root-level tables -------
    _t_fold = time.perf_counter()
    fold_kw: Dict = {}
    got = _fold_packed(fr, snap, maps, N, config) if fr is not None else None
    if got is not None:
        # subject side: a subject whose closure is wider than the
        # compare-tile cap declines the fold (the walked path answers)
        s_run = _max_run_sorted(cl_k1)
        if s_run > config.flat_fold_subj_fan_cap:
            got = None
    if got is not None:
        pf_k1, pf_k2, pf_subj, (u_k1, u_gk, u_until, u_fan), pff = got
        pfh = put_block(
            "pfx", "pfh_off", lambda: build_hash([pf_k1, pf_k2], **hk),
            [pf_k1, pf_k2],
            [pf_k1, pf_k2]
            + ([fr.e_cav, fr.e_ctx] if pff["pf_hascav"] else [])
            + ([fr.e_until] if pff["pf_hasuntil"] else []),
        )
        dom["until"]["pfx"] = _until_dom(fr.e_until)
        dom["until"]["pfux"] = _until_dom(u_until)
        s_fan = _round_fan(max(s_run, 1))
        fold_slots = tuple(sorted({s for _, s in fr.pairs}))
        dom["fan"]["pfugx"] = u_fan
        dom["fan"]["csrgx"] = s_fan
        pf_arrays, pf_kw = _pf_view_tables(
            u_k1, u_gk, u_until, u_fan,
            cl_k1, cl_k2, cl.c_d_until, cl.c_p_until, s_fan,
            maps=maps, N=N, S1=S1, fold_slots=fold_slots, config=config,
            hk=hk,
        )
        out.update(pf_arrays)
        fold_kw = dict(
            fold_pairs=fr.pairs,
            pf_e_cap=_round_cap(pfh.cap) if pfh is not None else 4,
            pf_u_fan=u_fan,
            pf_s_fan=s_fan,
            pf_haswc=bool(np.isin(pf_subj, wc_nodes).any()),
            pf_has_e=pf_k1.shape[0] > 0,
            pf_has_u=u_k1.shape[0] > 0,
            **pf_kw,
            **pff,
        )
        # arm the maintenance state with the packing context it
        # needs at delta time (fold_delta_update)
        fstate.maps, fstate.N = maps, N
    else:
        fstate = None
    _mt.observe("prepare.fold_s", time.perf_counter() - _t_fold)

    meta = FlatMeta(
        N=N, S1=S1,
        k1_dense=tuple(int(x) for x in maps.k1),
        k2_dense=tuple(int(x) for x in maps.k2),
        **rc_kw,
        **fold_kw,
        **rev_kw,
        e_cap=_round_cap(eh.cap) if eh is not None else 4,
        e_n=_ceil_pow2(max(eh.n, 1)) if eh is not None else 8,
        usr_cap=_round_cap(usr.index.cap),
        usr_gn=_ceil_pow2(max(usr.index.n, 1)),
        us_rows=_ceil_pow2(max(int(snap.us_rel.shape[0]), 1)),
        arr_cap=_round_cap(arr.index.cap),
        arr_gn=_ceil_pow2(max(arr.index.n, 1)),
        ar_rows=_ceil_pow2(max(int(snap.ar_rel.shape[0]), 1)),
        cl_cap=_round_cap(clh.cap) if clh is not None else 4,
        cl_n=_ceil_pow2(max(clh.n, 1)) if clh is not None else 8,
        has_closure=int(cl_k1.shape[0]) > 0,
        pus_cap=_round_cap(push.cap), pus_n=_ceil_pow2(max(push.n, 1)),
        ovf_cap=_round_cap(ovfh.cap), ovf_n=_ceil_pow2(max(ovfh.n, 1)),
        has_ovf=ovfh.n > 0,
        ar_fanout_by_slot=_run_maxes(arr.gk, arr.glo, arr.ghi, N, maps.k1_raw),
        us_fanout_by_slot=_run_maxes(usr.gk, usr.glo, usr.ghi, N, maps.k1_raw),
        **t_kw,
        e_hascav=e_hascav,
        e_hasexp=e_hasexp,
        us_hascav=us_hascav,
        us_hasexp=us_hasexp,
        us_hasperm=us_hasperm,
        ar_hascav=ar_hascav,
        ar_hasexp=ar_hasexp,
        blockslice=BS,
        aligned=tuple(al_meta),
        ar_data_depth=ar_dd,
        e_slots=tuple(int(s) for s in _uniq_small([snap.e_rel], snap.num_slots)),
        us_slots=tuple(int(s) for s in _uniq_small([snap.us_rel], snap.num_slots)),
        has_wc_edges=bool(np.isin(snap.e_subj, wc_nodes).any()),
        has_wc_closure=bool(
            np.isin(cl.c_src[cl.c_srel1 == 0], wc_nodes).any()
            or np.isin(cl.ovf_src[cl.ovf_srel1 == 0], wc_nodes).any()
        ),
    )
    if PKD:
        with _mt.timer("prepare.pack_lanes_s"):
            pk_up = _pack_flat(out, meta, config, dom, pack_off=True)
        if pk_up:
            from dataclasses import replace as _dc_replace

            meta = _dc_replace(meta, **pk_up)
    cstate = (
        _closure_host_state(snap, cl, config, us_gk, t_kw.get("t_slots", ()))
        if config.closure_delta and BS
        else None
    )
    return out, meta, fstate, cstate


# ---------------------------------------------------------------------------
# bucket-sharded layout (multi-chip: shard_map over the model axis)
# ---------------------------------------------------------------------------
#
# Hash tables shard by BUCKET RANGE: device s of M owns buckets
# [s·bpd, (s+1)·bpd) (bpd = size/M, both pow2), the bucket-ordered
# interleaved rows for those buckets (a contiguous slice), and the
# normalized local offsets.  A probe hashes globally, masks "is this my
# bucket", probes locally, and the site's boolean outputs OR-reduce over
# ICI (psum); value blocks (userset/arrow candidate rows) broadcast from
# their single owner via psum-of-masked.  This keeps per-device table
# memory at 1/M — the graph-size scaling axis of SURVEY.md §5 — while the
# kernel stays the same straight-line probe program.


def _stack_point(h: HashIndex, cols: Sequence[np.ndarray], M: int, pad: int = 64):
    """Bucket-sharded point table: (off int32[M·(bpd+1)],
    tbl int32[M·R_pad, w]) — shard_map splits both on the leading axis.
    Fully batched: one interleaved gather for the payload rows, one
    advanced-index scatter placing every shard's slice, one broadcast
    subtraction for the normalized local offsets (no per-shard loops)."""
    from ..native.sort import fill_interleaved

    size, bpd = h.size, h.size // M
    assert bpd * M == h.size and bpd >= 1
    w = max(len(cols), 1)
    n = int(h.rows.shape[0]) if h.n else 0
    off = h.off.astype(np.int64)
    starts = off[np.arange(M) * bpd]
    ends = off[(np.arange(M) + 1) * bpd]
    R_pad = _ceil_pow2(int((ends - starts).max() if M else 1) + max(pad, h.cap))
    tbl = np.full((M, R_pad, w), -1, np.int32)
    if n:
        # rows [0, n) partition contiguously into shards [starts, ends):
        # shard id + local position per global row, then one scatter
        lens = ends - starts
        sh = np.repeat(np.arange(M), lens)
        loc = np.arange(n, dtype=np.int64) - np.repeat(starts, lens)
        rows_mat = np.empty((n, w), np.int32)
        if not fill_interleaved(rows_mat, cols, h.rows[:n]):
            for j, c in enumerate(cols):
                rows_mat[:, j] = np.ascontiguousarray(c, np.int32)[h.rows[:n]]
        tbl[sh, loc] = rows_mat
    bidx = np.arange(M)[:, None] * bpd + np.arange(bpd + 1)[None, :]
    offs = (off[bidx] - starts[:, None]).astype(np.int32)
    return offs.reshape(-1), tbl.reshape(M * R_pad, w)


def _stack_range(ri, row_cols: Sequence[np.ndarray], M: int, fan_pad: int):
    """Bucket-sharded range view: the group table shards like a point
    table, and the underlying rows are PERMUTED into group-bucket order so
    each device's rows are its own groups' rows, contiguous and locally
    indexed.  ``ri`` is a RangeIndex built with min_size ≥ M (its group
    hash is reused, not rebuilt).  Returns (goff, gtbl, rows_tbl,
    group_cap) stacked for shard_map splitting."""
    gk, glo, ghi, gh = ri.gk, ri.glo, ri.ghi, ri.index
    G = int(gk.shape[0])
    size, bpd = gh.size, gh.size // M
    assert bpd * M == size, "RangeIndex must be built with min_size >= M"
    lens = ghi.astype(np.int64) - glo.astype(np.int64)
    w = max(len(row_cols), 1)
    goff = gh.off.astype(np.int64)
    g_starts = goff[np.arange(M) * bpd]
    g_ends = goff[(np.arange(M) + 1) * bpd]
    # one global bucket-ordered row permutation (vectorized), sliced per
    # shard: order_groups lists groups bucket-ordered; their row ranges
    # concatenate in that order
    order_groups = gh.rows[:G]
    lens_o = lens[order_groups] if G else np.zeros(0, np.int64)
    ends_all = np.cumsum(lens_o)
    starts_all = ends_all - lens_o
    total = int(ends_all[-1]) if G else 0
    row_src = (
        np.repeat(glo[order_groups].astype(np.int64), lens_o)
        + (np.arange(total, dtype=np.int64) - np.repeat(starts_all, lens_o))
        if G
        else np.zeros(0, np.int64)
    )
    # batched stacking: groups [0, G) and their rows [0, total) partition
    # contiguously into shards — compute shard-row bases with a running
    # max (empty shards carry the previous base), then place every
    # shard's group and row slices with advanced-index scatters
    shard_row_base = np.zeros(M + 1, np.int64)
    if G:
        cand = np.where(
            g_ends > g_starts, ends_all[np.clip(g_ends - 1, 0, None)], 0
        )
        shard_row_base[1:] = np.maximum.accumulate(cand)
    row_counts = np.diff(shard_row_base)
    R_pad = _ceil_pow2(int(row_counts.max() if M else 1) + max(fan_pad, 64))
    G_pad = _ceil_pow2(int((g_ends - g_starts).max() if M else 1) + max(64, gh.cap))
    rows_tbl = np.full((M, R_pad, w), -1, np.int32)
    gtbl = np.full((M, G_pad, 3), -1, np.int32)
    cols32 = [np.ascontiguousarray(c, np.int32) for c in row_cols]
    if total:
        from ..native.sort import fill_interleaved

        sh_r = np.repeat(np.arange(M), row_counts)
        loc_r = np.arange(total, dtype=np.int64) - np.repeat(
            shard_row_base[:-1], row_counts
        )
        rows_mat = np.empty((total, w), np.int32)
        if not fill_interleaved(rows_mat, cols32, row_src.astype(np.int32)):
            for ci, c in enumerate(cols32):
                rows_mat[:, ci] = c[row_src]
        rows_tbl[sh_r, loc_r] = rows_mat
    if G:
        g_lens = g_ends - g_starts
        sh_g = np.repeat(np.arange(M), g_lens)
        loc_g = np.arange(G, dtype=np.int64) - np.repeat(g_starts, g_lens)
        r0_of = np.repeat(shard_row_base[:-1], g_lens)
        gtbl[sh_g, loc_g, 0] = gk[order_groups]
        gtbl[sh_g, loc_g, 1] = (starts_all - r0_of).astype(np.int32)
        gtbl[sh_g, loc_g, 2] = (ends_all - r0_of).astype(np.int32)
    bidx = np.arange(M)[:, None] * bpd + np.arange(bpd + 1)[None, :]
    goffs = (
        gh.off.astype(np.int64)[bidx] - g_starts[:, None]
    ).astype(np.int32)
    return (
        goffs.reshape(-1),
        gtbl.reshape(M * G_pad, 3),
        rows_tbl.reshape(M * R_pad, w),
        gh.cap,
    )


def _groups_of(k: np.ndarray):
    """(gk, glo, ghi) distinct-key groups of a sorted key column — the
    group arrays build_range_hash materializes, shared by the partitioned
    range stacking and the per-slot fanout meta."""
    from ..native.sort import sorted_runs

    n = int(k.shape[0])
    if n == 0:
        z64 = np.zeros(0, np.int64)
        return np.zeros(0, np.int32), z64, z64
    starts = sorted_runs(k)
    ends = np.concatenate([starts[1:], np.asarray([n])])
    return np.ascontiguousarray(k[starts], np.int32), starts, ends


def _primary_hash_chunked(
    rel: np.ndarray, res: np.ndarray, subj: np.ndarray, srel1: np.ndarray,
    maps: SlotMaps, N: int, S1: int, chunk: int,
):
    """uint32 bucket hash of every primary row's dense (k1, k2) key,
    computed in bounded row chunks: the partitioned build's ownership
    pass never materializes a full-size packed key column (the chunk
    bound is what tests/test_sharded_memory.py's allocation tracker
    asserts).  Column-based so the stacked builder (sorted snapshot
    columns) and the feed partition (raw unsorted columns) share ONE
    definition of the key hash — the bitwise-parity-critical pass."""
    from .partition import _hash_cols

    n = int(rel.shape[0])
    h = np.empty(n, np.uint32)
    for at in range(0, n, max(chunk, 1)):
        sl = slice(at, min(at + chunk, n))
        k1 = _pack(maps.k1[rel[sl]], N, res[sl])
        k2 = _pack(subj[sl], S1, _m_srel1(maps, srel1[sl]))
        h[sl] = _hash_cols([k1, k2])
    return h


def _e_cols_at(snap, maps: SlotMaps, N: int, S1: int, gates):
    """Partition-local primary-table columns: the dense key packs are
    recomputed per shard over just that shard's rows (matching the
    chunked hash pass — no O(E) pack scratch)."""
    from ..native.sort import take32

    def at(rows: np.ndarray):
        idx = np.ascontiguousarray(rows, np.int64)
        rel = take32(snap.e_rel, idx)
        res = take32(snap.e_res, idx)
        subj = take32(snap.e_subj, idx)
        srel1 = take32(snap.e_srel1, idx)
        cols = [
            _pack(maps.k1[rel], N, res),
            _pack(subj, S1, _m_srel1(maps, srel1)),
        ]
        cols.extend(take32(g, idx) for g in gates)
        return cols

    return at


def _rev_key_hash_chunked(
    snap, maps: SlotMaps, N: int, S1: int, chunk: int, which: str
):
    """uint32 bucket hash of every primary row's single-column reverse-
    index key (``which`` = "k2" for the reverse view, "k1" for the
    forward view), computed in bounded row chunks — the reverse index's
    ownership pass materializes no full-size packed key column, same
    contract as _primary_hash_chunked."""
    from .partition import _hash_cols

    n = int(snap.e_rel.shape[0])
    h = np.empty(n, np.uint32)
    for at in range(0, n, max(chunk, 1)):
        sl = slice(at, min(at + chunk, n))
        if which == "k2":
            k = _pack(snap.e_subj[sl], S1, _m_srel1(maps, snap.e_srel1[sl]))
        else:
            k = _pack(maps.k1[snap.e_rel[sl]], N, snap.e_res[sl])
        h[sl] = _hash_cols([k])
    return h


def _rev_cols_at(snap, maps: SlotMaps, N: int, S1: int, gates, which: str):
    """Partition-local reverse-index row columns ([key, other-key] +
    gates), packed per shard — the rv/fw counterpart of _e_cols_at."""
    from ..native.sort import take32

    def at(rows: np.ndarray):
        idx = np.ascontiguousarray(rows, np.int64)
        k1 = _pack(
            maps.k1[take32(snap.e_rel, idx)], N, take32(snap.e_res, idx)
        )
        k2 = _pack(
            take32(snap.e_subj, idx), S1,
            _m_srel1(maps, take32(snap.e_srel1, idx)),
        )
        cols = [k2, k1] if which == "k2" else [k1, k2]
        cols.extend(take32(g, idx) for g in gates)
        return cols

    return at


def build_flat_arrays_sharded(
    snap, config: EngineConfig, model_size: int,
    plan: Optional[DevicePlan] = None,
) -> Optional[Tuple[Dict[str, np.ndarray], FlatMeta, Optional[object],
                    Optional[ClosureHostState]]]:
    """The bucket-sharded counterpart of build_flat_arrays: every hash /
    range / closure / T table stacked per model shard (leading axis splits
    M ways under shard_map; probes mask bucket ownership and OR-reduce).
    Array names and FlatMeta fields match the single-chip layout — the
    kernel distinguishes the layouts by FlatMeta.sharded and must be built
    with the matching ``axis``.  Returns None when keys don't pack (legacy
    sharded path)."""
    from ..store.closure import build_closure
    from ..utils import faults, metrics

    faults.fire("prepare.build")
    M = model_size
    with metrics.default.timer("prepare.closure_s"):
        cl = build_closure(snap, per_source_cap=config.closure_source_cap)

    # the permission fold shards like every other table (stacked pf_e /
    # pf_t; the kernel's pf probes already mask bucket ownership and
    # OR-reduce) — folded slots join the k1 radix
    fr = fstate = None
    if plan is not None:
        from .fold import fold_permissions

        got_fold = fold_permissions(snap, config, plan, cl)
        if got_fold is not None:
            fr, fstate = got_fold
    maps = _active_maps(
        snap, cl, {slot for _, slot in fr.pairs} if fr is not None else ()
    )
    N = _node_radix(snap, maps)
    if N is None:
        return None
    S1 = maps.S1

    us_gk = _pack(maps.k1[snap.us_rel], N, snap.us_res)
    ar_gk = _pack(maps.k1[snap.ar_rel], N, snap.ar_res)
    cl_k1 = _pack(cl.c_src, S1, _m_srel1(maps, cl.c_srel1))
    cl_k2 = _pack(cl.c_g, S1, maps.k2[cl.c_grel] + 1)
    pus_k = _pack(snap.pus_n, S1, maps.k2[snap.pus_r] + 1)
    ovf_k = _pack(cl.ovf_src, S1, _m_srel1(maps, cl.ovf_srel1))

    flags = _view_flags_of(snap)

    ms = max(8, M)
    # partition-first mode (engine/partition.py; config.flat_partition_
    # build, the default): the O(E) tables — primary hash, userset/arrow
    # range views, T-index, fold pf_e — are hashed to bucket shards
    # FIRST and each shard's slice of the stacked arrays is built
    # independently, so the sort/hash/interleave scratch peaks at
    # O(E/M), never O(E).  Output is BITWISE-identical to the legacy
    # build-full-then-stack path below (tests/test_prepare_parity.py).
    # Globally-small derived tables (closure, pus/ovf, fold pf_u/csr,
    # rc) keep the full build: they are sized by the group structure and
    # every process derives them from the replicated membership subgraph
    PART = bool(config.flat_partition_build)
    if PART:
        faults.fire("prepare.partition")
        from .partition import (
            _hash_cols, gather_cols, point_geom, range_geom,
            stack_point, stack_range,
        )
    _t_part = time.perf_counter()

    PKD = config.packed_on()
    hk = (
        {"max_factor": config.flat_packed_max_factor, "lean": True}
        if PKD else {}
    )
    dom = _pack_domains(snap, config)
    dom["until"]["clx"] = _until_dom(cl.c_d_until, cl.c_p_until)

    clh = build_hash([cl_k1, cl_k2], min_size=ms, **hk)
    push = build_hash([pus_k], min_size=ms, **hk)
    ovfh = build_hash([ovf_k], min_size=ms, **hk)

    out: Dict[str, np.ndarray] = {}
    e_gates = (
        ([snap.e_caveat, snap.e_ctx] if flags["e_hascav"] else [])
        + ([snap.e_exp] if flags["e_hasexp"] else [])
    )
    if PART:
        h_e = _primary_hash_chunked(
            snap.e_rel, snap.e_res, snap.e_subj, snap.e_srel1,
            maps, N, S1, config.flat_partition_chunk,
        )
        ge, e_ord = point_geom(
            h_e, M, min_size=ms, return_order=True, **hk
        )
        out["eh_off"], out["ehx"] = stack_point(
            h_e, _e_cols_at(snap, maps, N, S1, e_gates), ge,
            2 + len(e_gates), order=e_ord,
        )
        del h_e, e_ord
        eh_cap, eh_n = ge.cap, ge.n
    else:
        e_k1 = _pack(maps.k1[snap.e_rel], N, snap.e_res)
        e_k2 = _pack(snap.e_subj, S1, _m_srel1(maps, snap.e_srel1))
        eh = build_hash([e_k1, e_k2], min_size=ms, **hk)
        out["eh_off"], out["ehx"] = _stack_point(eh, [e_k1, e_k2] + e_gates, M)
        eh_cap, eh_n = eh.cap, eh.n
    out["clh_off"], out["clx"] = _stack_point(
        clh, [cl_k1, cl_k2, cl.c_d_until, cl.c_p_until], M
    )
    out["push_off"], out["pusx"] = _stack_point(push, [pus_k], M)
    out["ovfh_off"], out["ovfx"] = _stack_point(ovfh, [ovf_k], M)

    # srel rides DENSE, matching the dense closure/T keys
    us_cols = (
        [snap.us_subj, maps.k2[snap.us_srel]]
        + ([snap.us_caveat, snap.us_ctx] if flags["us_hascav"] else [])
        + ([snap.us_exp] if flags["us_hasexp"] else [])
        + ([snap.us_perm] if flags["us_hasperm"] else [])
    )
    ar_cols = (
        [snap.ar_child]
        + ([snap.ar_caveat, snap.ar_ctx] if flags["ar_hascav"] else [])
        + ([snap.ar_exp] if flags["ar_hasexp"] else [])
    )
    if PART:
        us_gkg, us_glo, us_ghi = _groups_of(us_gk)
        ar_gkg, ar_glo, ar_ghi = _groups_of(ar_gk)
        h_usg = _hash_cols([us_gkg])
        gus = range_geom(
            us_gkg, us_ghi - us_glo, h_usg, M, min_size=ms,
            fan_pad=max(64, config.us_leaf_cap), **hk,
        )
        out["usr_off"], out["usgx"], out["usx"] = stack_range(
            us_gkg, us_glo, us_ghi - us_glo, h_usg,
            gather_cols(us_cols), gus, len(us_cols),
        )
        usr_cap = gus.cap
        dom["fan"]["usgx"] = gus.max_run
        h_arg = _hash_cols([ar_gkg])
        gar = range_geom(
            ar_gkg, ar_ghi - ar_glo, h_arg, M, min_size=ms,
            fan_pad=max(64, config.arrow_fanout), **hk,
        )
        out["arr_off"], out["argx"], out["arx"] = stack_range(
            ar_gkg, ar_glo, ar_ghi - ar_glo, h_arg,
            gather_cols(ar_cols), gar, len(ar_cols),
        )
        arr_cap = gar.cap
        dom["fan"]["argx"] = gar.max_run
    else:
        usr = build_range_hash(us_gk, min_size=ms, **hk)
        arr = build_range_hash(ar_gk, min_size=ms, **hk)
        out["usr_off"], out["usgx"], out["usx"], usr_cap = _stack_range(
            usr, us_cols, M, max(64, config.us_leaf_cap),
        )
        out["arr_off"], out["argx"], out["arx"], arr_cap = _stack_range(
            arr, ar_cols, M, max(64, config.arrow_fanout),
        )
        dom["fan"]["usgx"] = usr.max_run
        dom["fan"]["argx"] = arr.max_run
        # the RangeIndexes already hold the group arrays: reuse them for
        # the per-slot fanout meta instead of a second sorted-runs pass
        us_gkg, us_glo, us_ghi = usr.gk, usr.glo, usr.ghi
        ar_gkg, ar_glo, ar_ghi = arr.gk, arr.glo, arr.ghi

    t_kw = dict(has_tindex=False, t_cap=4, t_n=8, t_slots=())
    tj = _tindex_join(snap, config, cl, us_gk, cl_k1, cl_k2, pus_k, maps)
    if tj is not None:
        T_k1, T_k2, T_d, T_p, t_slots = tj
        dom["until"]["tx"] = _until_dom(T_d, T_p)
        if PART:
            h_T = _hash_cols([T_k1, T_k2])
            gT, t_ord = point_geom(
                h_T, M, min_size=ms, return_order=True, **hk
            )
            out["th_off"], out["tx"] = stack_point(
                h_T, gather_cols([T_k1, T_k2, T_d, T_p]), gT, 4,
                order=t_ord,
            )
            th_cap, th_n = gT.cap, gT.n
        else:
            th = build_hash([T_k1, T_k2], min_size=ms, **hk)
            out["th_off"], out["tx"] = _stack_point(
                th, [T_k1, T_k2, T_d, T_p], M
            )
            th_cap, th_n = th.cap, th.n
        t_kw = dict(
            has_tindex=True,
            t_cap=_round_cap(th_cap),
            t_n=_ceil_pow2(max(th_n, 1)),
            t_slots=t_slots,
        )

    # ---- reverse-CSR lookup index (engine/rev.py), stacked M ways ------
    # partition-first on the PART path (owner shard from the key hash,
    # O(E/M) sort/gather scratch per shard — the allocation shim in
    # tests/test_sharded_memory.py covers these calls); the legacy path
    # builds full-then-stack (build_rev_full), the bitwise parity oracle
    rev_kw: Dict = {}
    if config.flat_rev_index:
        from .partition import _hash_cols as _rvh
        from .rev import (
            build_rev_full, build_rev_partitioned, rev_geom, rev_meta_kw,
        )

        _t_rev = time.perf_counter()
        ra_cols_full = [snap.ar_child, ar_gk] + ar_cols[1:]
        if PART:
            ck = config.flat_partition_chunk
            h_rv = _rev_key_hash_chunked(snap, maps, N, S1, ck, "k2")
            ge_rv = rev_geom(h_rv, M)
            w_rv = 2 + len(e_gates)
            out["rv_off"], out["rvx"] = build_rev_partitioned(
                h_rv, _rev_cols_at(snap, maps, N, S1, e_gates, "k2"),
                ge_rv, w_rv,
            )
            del h_rv
            h_ra = _rvh([snap.ar_child])
            ge_ra = rev_geom(h_ra, M)
            out["ra_off"], out["rax"] = build_rev_partitioned(
                h_ra, gather_cols(ra_cols_full), ge_ra, len(ra_cols_full)
            )
            del h_ra
            h_fw = _rev_key_hash_chunked(snap, maps, N, S1, ck, "k1")
            ge_fw = rev_geom(h_fw, M)
            out["fw_off"], out["fwx"] = build_rev_partitioned(
                h_fw, _rev_cols_at(snap, maps, N, S1, e_gates, "k1"),
                ge_fw, w_rv,
            )
            del h_fw
        else:
            h_rv = _rvh([e_k2])
            ge_rv = rev_geom(h_rv, M)
            out["rv_off"], out["rvx"] = build_rev_full(
                h_rv, [e_k2, e_k1] + e_gates, ge_rv, 2 + len(e_gates)
            )
            h_ra = _rvh([snap.ar_child])
            ge_ra = rev_geom(h_ra, M)
            out["ra_off"], out["rax"] = build_rev_full(
                h_ra, ra_cols_full, ge_ra, len(ra_cols_full)
            )
            h_fw = _rvh([e_k1])
            ge_fw = rev_geom(h_fw, M)
            out["fw_off"], out["fwx"] = build_rev_full(
                h_fw, [e_k1, e_k2] + e_gates, ge_fw, 2 + len(e_gates)
            )
        rev_kw = rev_meta_kw(ge_rv, ge_ra, ge_fw)
        metrics.default.observe(
            "prepare.rev_s", time.perf_counter() - _t_rev
        )

    wc_nodes = snap.wildcard_node_of_type[snap.wildcard_node_of_type >= 0]
    fold_kw: Dict = {}
    got = _fold_packed(fr, snap, maps, N, config) if fr is not None else None
    if got is not None:
        csr = build_range_hash(cl_k1, min_size=ms, **hk)
        if int(csr.max_run) > config.flat_fold_subj_fan_cap:
            got = None
    if got is not None:
        pf_k1, pf_k2, pf_subj, (u_k1, u_gk, u_until, u_fan), pff = got
        pf_cols = (
            [pf_k1, pf_k2]
            + ([fr.e_cav, fr.e_ctx] if pff["pf_hascav"] else [])
            + ([fr.e_until] if pff["pf_hasuntil"] else [])
        )
        dom["until"]["pfx"] = _until_dom(fr.e_until)
        dom["until"]["pfux"] = _until_dom(u_until)
        if PART:
            h_pf = _hash_cols([pf_k1, pf_k2])
            gpf, pf_ord = point_geom(
                h_pf, M, min_size=ms, return_order=True, **hk
            )
            out["pfh_off"], out["pfx"] = stack_point(
                h_pf, gather_cols(pf_cols), gpf, len(pf_cols),
                order=pf_ord,
            )
            pfh_cap = gpf.cap
        else:
            pfh = build_hash([pf_k1, pf_k2], min_size=ms, **hk)
            out["pfh_off"], out["pfx"] = _stack_point(pfh, pf_cols, M)
            pfh_cap = pfh.cap
        if PART:
            # fold userset view (u_k1 arrives k1-sorted): partitioned
            # group stacking, same discipline as the usr/arr views
            pfu_gk, pfu_glo, pfu_ghi = _groups_of(u_k1)
            h_pfu = _hash_cols([pfu_gk])
            gpfu = range_geom(
                pfu_gk, pfu_ghi - pfu_glo, h_pfu, M, min_size=ms,
                fan_pad=max(64, u_fan), **hk,
            )
            out["pfu_off"], out["pfugx"], out["pfux"] = stack_range(
                pfu_gk, pfu_glo, pfu_ghi - pfu_glo, h_pfu,
                gather_cols([u_gk, u_until]), gpfu, 2,
            )
            pfu_cap = gpfu.cap
        else:
            pfu = build_range_hash(u_k1, min_size=ms, **hk)
            out["pfu_off"], out["pfugx"], out["pfux"], pfu_cap = _stack_range(
                pfu, [u_gk, u_until], M, max(64, u_fan)
            )
        s_fan = _round_fan(max(int(csr.max_run), 1))
        dom["fan"]["pfugx"] = u_fan
        dom["fan"]["csrgx"] = s_fan
        out["csr_off"], out["csrgx"], out["csrx"], csr_cap = _stack_range(
            csr, [cl_k2, cl.c_d_until, cl.c_p_until], M, max(64, s_fan)
        )
        fold_kw = dict(
            fold_pairs=fr.pairs,
            pf_e_cap=_round_cap(pfh_cap),
            pf_u_cap=_round_cap(pfu_cap),
            pf_u_fan=u_fan,
            pf_s_cap=_round_cap(csr_cap),
            pf_s_fan=s_fan,
            pf_haswc=bool(np.isin(pf_subj, wc_nodes).any()),
            pf_has_e=pf_k1.shape[0] > 0,
            pf_has_u=u_k1.shape[0] > 0,
            **pff,
        )
        # arm the maintenance state with the packing context it
        # needs at delta time (fold_delta_update)
        fstate.maps, fstate.N = maps, N
    else:
        fstate = None

    ar_dd = _arrow_data_depth(snap)
    rc_list = []
    for ts_slot, (src, anc, d_u, p_u, fan) in _rc_build(
        snap, config, plan, ar_dd
    ).items():
        dom["until"][f"rc{ts_slot}x"] = _until_dom(d_u, p_u)
        dom["fan"][f"rc{ts_slot}gx"] = fan
        if PART:
            # ancestor-closure view (src arrives sorted): partitioned
            # group stacking — O(rc/M) fill scratch per shard
            rc_gk, rc_glo, rc_ghi = _groups_of(src)
            h_rc = _hash_cols([rc_gk])
            grc = range_geom(
                rc_gk, rc_ghi - rc_glo, h_rc, M, min_size=ms,
                fan_pad=max(64, fan), **hk,
            )
            (
                out[f"rc{ts_slot}_off"],
                out[f"rc{ts_slot}gx"],
                out[f"rc{ts_slot}x"],
            ) = stack_range(
                rc_gk, rc_glo, rc_ghi - rc_glo, h_rc,
                gather_cols([anc, d_u, p_u]), grc, 3,
            )
            gcap = grc.cap
        else:
            ri = build_range_hash(src, min_size=ms, **hk)
            (
                out[f"rc{ts_slot}_off"],
                out[f"rc{ts_slot}gx"],
                out[f"rc{ts_slot}x"],
                gcap,
            ) = _stack_range(ri, [anc, d_u, p_u], M, max(64, fan))
        rc_list.append((int(ts_slot), _round_cap(gcap), fan))

    if PART:
        metrics.default.observe(
            "prepare.partition_s", time.perf_counter() - _t_part
        )
    meta = FlatMeta(
        N=N, S1=S1,
        k1_dense=tuple(int(x) for x in maps.k1),
        k2_dense=tuple(int(x) for x in maps.k2),
        **fold_kw,
        **rev_kw,
        rc_slots=tuple(sorted(rc_list)),
        e_cap=_round_cap(eh_cap), e_n=_ceil_pow2(max(eh_n, 1)),
        usr_cap=_round_cap(usr_cap),
        usr_gn=8,  # legacy-probe geometry: unused (local shapes rule)
        us_rows=8,
        arr_cap=_round_cap(arr_cap),
        arr_gn=8,
        ar_rows=8,
        cl_cap=_round_cap(clh.cap), cl_n=_ceil_pow2(max(clh.n, 1)),
        has_closure=clh.n > 0,
        pus_cap=_round_cap(push.cap), pus_n=_ceil_pow2(max(push.n, 1)),
        ovf_cap=_round_cap(ovfh.cap), ovf_n=_ceil_pow2(max(ovfh.n, 1)),
        has_ovf=ovfh.n > 0,
        ar_fanout_by_slot=_run_maxes(ar_gkg, ar_glo, ar_ghi, N, maps.k1_raw),
        us_fanout_by_slot=_run_maxes(us_gkg, us_glo, us_ghi, N, maps.k1_raw),
        **t_kw,
        **flags,
        blockslice=True,
        sharded=True,
        ar_data_depth=ar_dd,
        e_slots=tuple(int(s) for s in _uniq_small([snap.e_rel], snap.num_slots)),
        us_slots=tuple(int(s) for s in _uniq_small([snap.us_rel], snap.num_slots)),
        has_wc_edges=bool(np.isin(snap.e_subj, wc_nodes).any()),
        has_wc_closure=bool(
            np.isin(cl.c_src[cl.c_srel1 == 0], wc_nodes).any()
            or np.isin(cl.ovf_src[cl.ovf_srel1 == 0], wc_nodes).any()
        ),
    )
    if PKD:
        with metrics.default.timer("prepare.pack_lanes_s"):
            pk_up = _pack_flat(out, meta, config, dom, pack_off=False)
        if pk_up:
            from dataclasses import replace as _dc_replace

            meta = _dc_replace(meta, **pk_up)
    # closure-delta maintenance is single-chip for now: the sharded
    # incremental prepare bails to a full rebuild on membership rows
    return out, meta, fstate, None


# ---------------------------------------------------------------------------
# delta level (Watch-driven incremental re-index)
# ---------------------------------------------------------------------------


def _perm_table(compiled: CompiledSchema, interner) -> np.ndarray:
    """bool[interner types, slots]: slot is a *permission* on the type."""
    num_slots = max(compiled.num_slots, 1)
    t = np.zeros((max(interner.num_types, 1), num_slots), bool)
    for tname, d in compiled.schema.definitions.items():
        itid = interner.type_lookup(tname)
        if itid < 0:
            continue
        for pname in d.permissions:
            t[itid, compiled.slot_of_name[pname]] = True
    return t


_ACC_COLS = ("rel", "res", "subj", "srel1", "cav", "ctx", "exp")


def _acc_collapse(acc: Optional[Dict], di, N: int, S1: int, m1, m2) -> Dict:
    """Fold one revision's DeltaInfo into the accumulated delta state.

    ``acc`` holds the collapsed adds (payload columns keyed by primary
    identity) and tombstone identities since the base revision; identities
    pack into one int64 (both DENSE halves < 2³¹ by the radix check —
    ``m1``/``m2`` are the base meta's slot maps; the caller bails before
    accumulating any unmappable row)."""

    def pack(rel, res, subj, srel1):
        k1 = m1(rel).astype(np.int64) * N + res.astype(np.int64)
        k2 = subj.astype(np.int64) * S1 + m2(srel1).astype(np.int64)
        return (k1 << np.int64(31)) | k2

    if acc is None:
        acc = {
            "a_key": np.empty(0, np.int64),
            **{f"a_{c}": np.empty(0, np.int32) for c in _ACC_COLS},
            "g_key": np.empty(0, np.int64),
            **{f"g_{c}": np.empty(0, np.int32) for c in _ACC_COLS[:4]},
        }
    a_key = pack(di.a_rel, di.a_res, di.a_subj, di.a_srel1)
    g_key = pack(di.g_rel, di.g_res, di.g_subj, di.g_srel1)

    # Invariant: device view = (base − tombstones) ∪ adds.  EVERY touched
    # identity — deleted OR upserted — goes into the tombstone set: an
    # upsert of a row that lives in the base must void the base copy (its
    # stale payload would otherwise answer alongside the new one), and
    # tombstoning an identity the base never had is a harmless probe miss.
    touched = np.concatenate([g_key, a_key])
    keep = ~np.isin(acc["a_key"], touched)
    out = {"a_key": acc["a_key"][keep]}
    for c in _ACC_COLS:
        out[f"a_{c}"] = acc[f"a_{c}"][keep]
    gk = np.concatenate([acc["g_key"], g_key, a_key])
    gcols = {
        f"g_{c}": np.concatenate(
            [acc[f"g_{c}"], getattr(di, f"g_{c}"), getattr(di, f"a_{c}")]
        )
        for c in _ACC_COLS[:4]
    }
    order = np.argsort(gk, kind="stable")
    gk_sorted = gk[order]
    first = np.ones(gk_sorted.shape[0], bool)
    first[1:] = gk_sorted[1:] != gk_sorted[:-1]
    res = {"g_key": gk_sorted[first]}
    for c in _ACC_COLS[:4]:
        res[f"g_{c}"] = gcols[f"g_{c}"][order][first]
    new_cols = {
        "rel": di.a_rel, "res": di.a_res, "subj": di.a_subj,
        "srel1": di.a_srel1, "cav": di.a_cav, "ctx": di.a_ctx,
        "exp": di.a_exp,
    }
    merged_key = np.concatenate([out["a_key"], a_key])
    order = np.argsort(merged_key, kind="stable")
    res["a_key"] = merged_key[order]
    for c in _ACC_COLS:
        res[f"a_{c}"] = np.concatenate(
            [out[f"a_{c}"], new_cols[c].astype(np.int32)]
        )[order]
    return res


def build_delta_arrays(
    snap, prev_dsnap, compiled: CompiledSchema, config: EngineConfig
) -> Optional[Tuple[Dict[str, np.ndarray], "DeltaMeta", Dict, Dict]]:
    """Advance a blockslice-prepared DeviceSnapshot by one revision's
    delta: returns the small ``dl_*`` overlay arrays, the static DeltaMeta,
    the new accumulated-delta state, and an extras dict ({"meta_up":
    FlatMeta field overrides, "closure_state": the advanced closure host
    state}) — or None when the delta cannot be applied incrementally
    (caller does a full prepare).

    Membership-subgraph rows no longer force a rebuild: the flattened
    closure advances in place (store/closure.py advance_closure, O(Δ·depth)
    host work) and the closure-derived device tables — clx/ovfx, sized
    O(closure), not O(E) — reship with the same names and bucketing, so
    the compiled kernel keeps serving.  Baked T-index rows of groups whose
    member set changed are voided through the dirty mechanism (dl_td);
    past the dirty budget the chain flips the T-index off (sticky
    ``t_off``) and the KU path probes the live closure instead.

    Used-set SHRINK (a userset losing its last referencing row) does NOT
    bail: classification stays pinned to the chain-base superset
    (ClosureHostState.used), whose extra closure rows are unreachable by
    any probe and keep later re-references exact.

    Remaining sound-bail conditions (every one falls back to a FULL
    rebuild, never to wrong answers): affected-source set past the cap,
    newly-used userset subjects, permission-valued userset rows,
    closure-overflow or wildcard-source transitions the compiled kernel
    has no probe sites for, node-radix overflow, wildcard introduction,
    renumbered contexts, gate columns the base layout lacks, and
    accumulated-delta size beyond the compaction threshold."""
    di = getattr(snap, "delta_info", None)
    meta = prev_dsnap.flat_meta
    if (
        di is None
        or meta is None
        or not meta.blockslice
        or di.prev_revision != prev_dsnap.revision
        or di.contexts_renumbered
    ):
        return None
    prev_snap = prev_dsnap.snapshot
    used = getattr(prev_snap, "us_used_keys", None)
    if used is None:
        return None
    if snap.num_nodes > meta.N:
        return None  # node radix outgrown: repack
    if not np.array_equal(
        snap.wildcard_node_of_type, prev_snap.wildcard_node_of_type
    ):
        return None
    num_slots = snap.num_slots
    all_rel = np.concatenate([di.a_rel, di.g_rel])
    all_res = np.concatenate([di.a_res, di.g_res])
    all_subj = np.concatenate([di.a_subj, di.g_subj])
    all_srel1 = np.concatenate([di.a_srel1, di.g_srel1])
    # membership-subgraph test: a row FEEDS the closure when the userset
    # it grants is used as a subject anywhere.  Such rows ride the normal
    # dl_* overlays like any other (they ARE primary/us/ar rows) and
    # ADDITIONALLY advance the flattened closure below.  Classification
    # MUST use the closure state's own base used-set (a chain superset —
    # see ClosureHostState): a mid-chain materialization may recompute a
    # smaller truth on the snapshot, and classifying against that would
    # desynchronize the advance from its own edge sets
    chs = getattr(prev_dsnap, "closure_state", None)
    if chs is not None:
        used = chs.used
    edge_key = all_res.astype(np.int64) * num_slots + all_rel.astype(np.int64)
    mem_any = bool(np.isin(edge_key, used).any())
    if mem_any and (
        not config.closure_delta
        or meta.sharded
        or chs is None
        or not meta.has_closure
    ):
        return None
    us_rows = all_srel1 > 0
    if us_rows.any():
        subj_key = (
            all_subj[us_rows].astype(np.int64) * num_slots
            + (all_srel1[us_rows].astype(np.int64) - 1)
        )
        # a userset subject not already used would need new ms/mp rows
        if not np.isin(subj_key, used).all():
            return None
        pt = _perm_table(compiled, snap.interner)
        stypes = snap.node_type[all_subj[us_rows]]
        if pt[stypes, np.clip(all_srel1[us_rows] - 1, 0, pt.shape[1] - 1)].any():
            return None
    # gate columns ride the BASE layouts, PER VIEW: a caveated/expiring
    # delta row landing in a view whose base layout lacks that column
    # would silently evaluate ungated — bail instead
    a_is_us = di.a_srel1 > 0
    ts_set = np.asarray(sorted(compiled.tupleset_slots), np.int64)
    a_is_ar = np.isin(di.a_rel, ts_set) & (di.a_srel1 == 0)
    for mask, hascav, hasexp in (
        (slice(None), meta.e_hascav, meta.e_hasexp),  # primary: all adds
        (a_is_us, meta.us_hascav, meta.us_hasexp),
        (a_is_ar, meta.ar_hascav, meta.ar_hasexp),
    ):
        if di.a_cav[mask].any() and not hascav:
            return None
        if di.a_exp[mask].any() and not hasexp:
            return None
    # a wildcard-subject add is invisible unless the base kernel compiled
    # its wildcard probe sites
    if not meta.has_wc_edges:
        wc_nodes = snap.wildcard_node_of_type[snap.wildcard_node_of_type >= 0]
        if wc_nodes.size and np.isin(di.a_subj, wc_nodes).any():
            return None

    S1 = meta.S1
    N = meta.N
    # dense remap through the BASE meta's maps: a delta touching a slot
    # the base never packed (fresh relation first used mid-chain) has no
    # dense id — bail to a full prepare, which rebuilds the maps.  The
    # check runs BEFORE accumulation so unmappable keys never enter the
    # chain state
    k1d = np.asarray(meta.k1_dense, np.int32)
    k2d = np.asarray(meta.k2_dense, np.int32)

    def m1(rel):
        return k1d[np.clip(rel, 0, max(k1d.shape[0] - 1, 0))]

    def m2(srel1):
        return np.where(
            srel1 == 0, 0,
            k2d[np.clip(srel1 - 1, 0, max(k2d.shape[0] - 1, 0))] + 1,
        )

    for rel_col, srel_col in (
        (di.a_rel, di.a_srel1), (di.g_rel, di.g_srel1)
    ):
        if rel_col.shape[0] and (
            (m1(rel_col) < 0).any()
            or (m2(srel_col) <= 0)[srel_col > 0].any()
        ):
            return None
    prev_acc = getattr(prev_dsnap, "delta_acc", None)
    acc = _acc_collapse(prev_acc, di, N, S1, m1, m2)
    # chain-stable anchor for the shape floor below: the BASE revision's
    # edge count (a floor derived from the oscillating current count
    # would retrace on every boundary crossing)
    acc["base_edges"] = (
        prev_acc["base_edges"] if prev_acc else int(prev_snap.num_edges)
    )
    if prev_acc and prev_acc.get("pf_off"):
        acc["pf_off"] = True  # sticky downgrade for the chain remainder
    if prev_acc:
        if prev_acc.get("t_off"):
            acc["t_off"] = True  # sticky T disable for the chain remainder
        elif prev_acc.get("cl_dirty_k1") is not None:
            acc["cl_dirty_k1"] = prev_acc["cl_dirty_k1"]
    if meta.rc_slots:
        # rows of a FLATTENED tupleset shift its ancestor closure: bail
        # EARLY (before any table builds) to a full rebuild.  Incremental
        # rc-closure maintenance is a possible future middle ground
        rc_ts = np.asarray([t for t, _, _ in meta.rc_slots], np.int64)
        if (
            (np.isin(acc["a_rel"], rc_ts) & (acc["a_srel1"] == 0)).any()
            or (np.isin(acc["g_rel"], rc_ts) & (acc["g_srel1"] == 0)).any()
        ):
            return None
    n_adds = acc["a_key"].shape[0]
    n_tombs = acc["g_key"].shape[0]
    if n_adds + n_tombs > max(
        config.flat_delta_min_compact, snap.num_edges // 8
    ):
        return None  # compaction: fold the delta into a fresh base

    out: Dict[str, np.ndarray] = {}
    meta_up: Dict = {}
    new_chs = chs
    # packed-base maintenance: reshipped closure-derived tables repack
    # with the BASE spec (no retrace) when their values still fit; a
    # value outside the pinned domain (e.g. a fresh expiring membership
    # edge under a {NEVER, NO_EXP} dictionary) DESPECS that one table —
    # the kernel reads it raw for the rest of the chain (one retrace,
    # never a wrong decode)
    from . import packed as _pkm

    pk_map = dict(meta.packed)
    pko_map = dict(meta.packed_off)
    pk_drop: set = set()
    pko_drop: set = set()
    drop_keys: List[str] = []
    hk = (
        {"max_factor": config.flat_packed_max_factor, "lean": True}
        if config.packed_on() else {}
    )

    def _repack_tbl(tbl_key: str, tbl: np.ndarray) -> np.ndarray:
        spec = pk_map.get(tbl_key)
        if spec is None or tbl_key in pk_drop:
            return tbl
        try:
            return _pkm.pack_rows(tbl, spec)
        except _pkm.PackError:
            pk_drop.add(tbl_key)
            return tbl

    def _reship_off(off_key: str, off: np.ndarray) -> None:
        if off_key in pko_map and off_key not in pko_drop:
            got = _pkm.pack_off(off)
            if got is not None:
                out[off_key], out[off_key + "_a"] = got
                return
            pko_drop.add(off_key)
            drop_keys.append(off_key + "_a")
        out[off_key] = off

    def _extras() -> Dict:
        # runs once per successful incremental advance; a revision span
        # > 1 means this ONE device reship covered a whole write group
        if int(snap.revision) - int(prev_dsnap.revision) > 1:
            from ..utils import metrics as _metrics

            _metrics.default.inc("flat.group_reships")
        if pk_drop:
            meta_up["packed"] = tuple(
                t for t in meta.packed if t[0] not in pk_drop
            )
        if pko_drop:
            meta_up["packed_off"] = tuple(
                t for t in meta.packed_off if t[0] not in pko_drop
            )
        return {
            "meta_up": meta_up, "closure_state": new_chs,
            "drop_keys": drop_keys,
        }

    # ---- membership-closure advance ------------------------------------
    if mem_any:
        from ..store.closure import advance_closure

        S1r = np.int64(num_slots + 1)
        a_mem = np.isin(
            di.a_res.astype(np.int64) * num_slots + di.a_rel, used
        )
        g_mem = np.isin(
            di.g_res.astype(np.int64) * num_slots + di.g_rel, used
        )

        def edges4(mask):
            if not mask.any():
                return None
            return (
                di.a_subj[mask].astype(np.int64) * S1r + di.a_srel1[mask],
                di.a_res[mask].astype(np.int64) * S1r + di.a_rel[mask] + 1,
                di.a_cav[mask], di.a_exp[mask],
            )

        def edges2(mask):
            if not mask.any():
                return None
            return (
                di.g_subj[mask].astype(np.int64) * S1r + di.g_srel1[mask],
                di.g_res[mask].astype(np.int64) * S1r + di.g_rel[mask] + 1,
            )

        adv = advance_closure(
            chs.st, snap.revision,
            pair_add=edges4(a_mem & (di.a_srel1 > 0)),
            pair_del=edges2(g_mem & (di.g_srel1 > 0)),
            seed_add=edges4(a_mem & (di.a_srel1 == 0)),
            seed_del=edges2(g_mem & (di.g_srel1 == 0)),
            affected_cap=config.closure_delta_affected_cap,
        )
        if adv is None:
            return None  # affected set over cap / unconverged: rebuild
        new_cl = adv.state.cl
        wc_nodes = snap.wildcard_node_of_type[snap.wildcard_node_of_type >= 0]
        # transitions the compiled kernel has no probe sites for: overflow
        # appearing under a no-ovf kernel, or under an armed fold (fold
        # eligibility requires an overflow-free closure, so any overflow
        # here IS a transition)
        if adv.state.ovf.shape[0] and (not meta.has_ovf or meta.fold_pairs):
            return None
        if (
            not meta.has_wc_closure
            and wc_nodes.size
            and np.isin(
                (adv.affected_users // S1r).astype(np.int32), wc_nodes
            ).any()
        ):
            return None  # wildcard closure source may appear: rebuild

        # dense-repacked closure keys (the advance cannot introduce slots
        # the base maps lack — `used` is stable — but verify cheaply)
        m_srel = m2(new_cl.c_srel1)
        if ((m_srel <= 0) & (new_cl.c_srel1 > 0)).any():
            return None
        grel_d = k2d[np.clip(new_cl.c_grel, 0, max(k2d.shape[0] - 1, 0))]
        if new_cl.c_grel.shape[0] and (grel_d < 0).any():
            return None
        cl_k1 = (
            new_cl.c_src.astype(np.int64) * S1 + m_srel
        ).astype(np.int32)
        cl_k2 = (
            new_cl.c_g.astype(np.int64) * S1 + grel_d + 1
        ).astype(np.int32)
        aligned_tbls = {t[0]: (t[1], t[2]) for t in meta.aligned}

        def reship_point(tbl_key, off_key, key_cols, cols,
                         cap_key, n_key):
            """Rebuild one closure-derived point table in the base
            layout.  Aligned tables must reproduce their exact geometry
            (width/cap ladder are part of the compiled kernel) — a
            mismatch rebuilds; the legacy layout just re-buckets and
            records the (pow2-stable) cap/size in meta_up.  Packed
            tables repack under the base spec (despec'd on misfit)."""
            if tbl_key in aligned_tbls and tbl_key + "_al" in prev_dsnap.arrays:
                ai = build_aligned(
                    key_cols, cols, max_bytes=config.flat_aligned_max_bytes,
                    cover=config.flat_aligned_cover,
                )
                if ai is None or (ai.w, ai.caps) != aligned_tbls[tbl_key]:
                    return False
                spec = pk_map.get(tbl_key)
                packed_lvls = []
                if spec is not None and tbl_key not in pk_drop:
                    try:
                        for tbl, _c in ai.levels:
                            size, roww = tbl.shape
                            cap = roww // ai.w
                            packed_lvls.append(_pkm.pack_rows(
                                tbl.reshape(size * cap, ai.w), spec
                            ).reshape(size, cap * spec[1]))
                    except _pkm.PackError:
                        pk_drop.add(tbl_key)
                        packed_lvls = []
                if packed_lvls:
                    for lvl, tbl in enumerate(packed_lvls):
                        out[_al_key(tbl_key, lvl)] = tbl
                else:
                    for lvl, (tbl, _c) in enumerate(ai.levels):
                        out[_al_key(tbl_key, lvl)] = tbl
                return True
            h = build_hash(key_cols, **hk)
            _reship_off(off_key, h.off)
            out[tbl_key] = _repack_tbl(tbl_key, interleave_buckets(h, cols))
            meta_up[cap_key] = _round_cap(h.cap)
            meta_up[n_key] = _ceil_pow2(max(h.n, 1))
            return True

        if not reship_point(
            "clx", "clh_off", [cl_k1, cl_k2],
            [cl_k1, cl_k2, new_cl.c_d_until, new_cl.c_p_until],
            "cl_cap", "cl_n",
        ):
            return None
        if meta.has_ovf:
            ovf_srel_d = m2(new_cl.ovf_srel1)
            if ((ovf_srel_d <= 0) & (new_cl.ovf_srel1 > 0)).any():
                return None
            ovf_k = (
                new_cl.ovf_src.astype(np.int64) * S1 + ovf_srel_d
            ).astype(np.int32)
            if not reship_point(
                "ovfx", "ovfh_off", [ovf_k], [ovf_k], "ovf_cap", "ovf_n"
            ):
                return None
        if meta.fold_pairs:
            # the fold's subject-side csr view IS the closure re-keyed by
            # source: reship it alongside clx so pf intersections see the
            # advanced membership.  Gated on the fold being ARMED, not on
            # pf_has_u — a fold with no base userset rows can still grow
            # dl_pfu overlay rows mid-chain, and those intersect against
            # these tables
            from ..store.closure import NO_EXP as _NO_EXP

            s_run = _max_run_sorted(cl_k1)
            if s_run > config.flat_fold_subj_fan_cap:
                return None  # a subject's closure outgrew the tile cap
            s_fan = _round_fan(max(s_run, 1))
            pad_s = max(64, s_fan)
            out["csr_gk"] = _pf_col(cl_k2, pad_s, -1)
            s_alllive = (
                bool(
                    (new_cl.c_d_until == _NO_EXP).all()
                    and (new_cl.c_p_until == _NO_EXP).all()
                )
                if cl_k1.shape[0] else True
            )
            if not s_alllive:
                out["csr_d"] = _pf_col(new_cl.c_d_until, pad_s, 0)
                out["csr_p"] = _pf_col(new_cl.c_p_until, pad_s, 0)
            meta_up["pf_s_fan"] = s_fan
            meta_up["pf_s_alllive"] = s_alllive
            # hash-backed csr along the chain: rebuilding the dense
            # offset array per revision costs more host time + H2D than
            # the whole write budget; the probe-side hash penalty only
            # applies until the next full prepare restores direct
            csr = build_range_hash(cl_k1, **hk)
            _reship_off("csr_off", csr.index.off)
            out["csrgx"] = _repack_tbl("csrgx", interleave_buckets(
                csr.index, [csr.gk, csr.glo, csr.ghi]
            ))
            meta_up["pf_s_cap"] = _round_cap(csr.index.cap)
            if meta.pf_s_direct:
                # the direct offset array (and its packed anchor, when
                # the base packed it) is dead for the rest of the chain:
                # drop it so device_bytes stays honest
                drop_keys.extend(["csr_start", "csr_start_a"])
                if "csr_start" in pko_map:
                    pko_drop.add("csr_start")
            meta_up["pf_s_direct"] = False

        # stale baked T rows: every T-covered userset row whose group's
        # member set changed gets its (slot·N + res) key dirtied; past
        # the budget the chain turns the T-index off instead
        if meta.has_tindex and not acc.get("t_off"):
            from ..store.closure import _expand_join as _xj

            if adv.changed_dsts.shape[0] and new_chs.t_pe.shape[0]:
                _, ii = _xj(new_chs.t_pe, adv.changed_dsts)
                fresh_dirty = np.unique(new_chs.t_k1[ii])
            else:
                fresh_dirty = np.zeros(0, np.int32)
            prev_dirty = acc.get("cl_dirty_k1")
            dirty = (
                np.union1d(prev_dirty, fresh_dirty)
                if prev_dirty is not None else fresh_dirty
            )
            if dirty.shape[0] > config.flat_tindex_dirty_cap:
                acc["t_off"] = True
                acc.pop("cl_dirty_k1", None)
            elif dirty.shape[0]:
                acc["cl_dirty_k1"] = dirty.astype(np.int32)
        new_chs = ClosureHostState(adv.state, chs.used, chs.t_pe, chs.t_k1)

    def pk(a, radix, b):
        return (a.astype(np.int64) * radix + b).astype(np.int32)

    a_k1 = pk(m1(acc["a_rel"]), N, acc["a_res"])
    a_k2 = pk(acc["a_subj"], S1, m2(acc["a_srel1"]))
    g_k1 = pk(m1(acc["g_rel"]), N, acc["g_res"])
    g_k2 = pk(acc["g_subj"], S1, m2(acc["g_srel1"]))

    # shape floor: every dl_* table pre-sizes to F rows (2F buckets), so
    # a chain of Watch revisions reuses ONE compiled kernel — without it,
    # each pow2 row-count boundary retraces (~1s), dominating the
    # re-index loop.  Scaled down for small graphs where retraces are
    # cheap and the floor would out-size the base
    F = min(
        config.flat_delta_floor,
        _ceil_pow2(max(64, acc["base_edges"] // 4)),
    )

    def _q4(n: int) -> int:
        # pow2 with the exponent rounded up to EVEN — shapes step in 4×
        # bands, so a chain whose accumulated rows outgrow the F floor
        # retraces half as often on its way to the compaction bound
        p = _ceil_pow2(max(n, 1))
        return p if (p.bit_length() - 1) % 2 == 0 else p << 1

    def dlband(n: int) -> int:
        """THE shared shape band of a dl_* table of ``n`` rows: the 2F
        floor, then 4×-quantized steps.  Both the hash size and the
        interleave pad derive from this one value, so a table's off and
        row shapes step at the same revision (one retrace, not two) —
        including when F itself is an odd power of two."""
        return max(2 * F, _q4(4 * n))

    dlpad = dlband  # interleave pad target — same band by construction

    def floored_hash(cols):
        # deterministic sizing (max_factor=1): the adaptive cap-chasing
        # growth in build_hash would re-step the off shape at pow2
        # boundaries of its own; a fixed ≤0.25 load factor in 4× bands
        # keeps shapes put, and the declared probe caps below carry a
        # floor of 16 to absorb the occupancy wobble that load allows
        n = int(cols[0].shape[0]) if cols else 0
        return build_hash(cols, min_size=dlband(n), max_factor=1)

    kw = {}
    if n_adds:
        eh = floored_hash([a_k1, a_k2])
        out["dl_eh_off"] = eh.off
        out["dl_ehx"] = interleave_buckets(
            eh,
            [a_k1, a_k2]
            + ([acc["a_cav"], acc["a_ctx"]] if meta.e_hascav else [])
            + ([acc["a_exp"]] if meta.e_hasexp else []),
            pad=dlpad(n_adds),
        )
        kw.update(
            has_adds=True,
            e_cap=_round_cap(max(16, eh.cap)),
            e_slots=tuple(int(s) for s in np.unique(acc["a_rel"])),
            e_hascav=meta.e_hascav,
            e_hasexp=meta.e_hasexp,
        )
    if n_tombs:
        tb = floored_hash([g_k1, g_k2])
        out["dl_tb_off"] = tb.off
        out["dl_tbx"] = interleave_buckets(tb, [g_k1, g_k2], pad=dlpad(n_tombs))
        kw.update(has_tombs=True, tb_cap=_round_cap(max(16, tb.cap)))

    # delta userset view (adds with a subject relation)
    am = acc["a_srel1"] > 0
    if am.any():
        gk_all = a_k1[am]
        order = np.argsort(gk_all, kind="stable")
        u_gk = gk_all[order]
        usr = build_range_hash(
            u_gk, min_size=max(2 * F, _q4(4 * int(u_gk.shape[0]))),
            max_factor=1,
        )
        out["dl_usr_off"] = usr.index.off
        out["dl_usgx"] = interleave_buckets(
            usr.index, [usr.gk, usr.glo, usr.ghi], pad=dlpad(int(am.sum()))
        )
        cols = [
            acc["a_subj"][am][order],
            # dense srel, matching the base us view and the closure keys
            (m2(acc["a_srel1"][am]) - 1)[order],
        ]
        if meta.us_hascav:
            cols += [acc["a_cav"][am][order], acc["a_ctx"][am][order]]
        if meta.us_hasexp:
            cols += [acc["a_exp"][am][order]]
        if meta.us_hasperm:
            # permission-valued delta rows bail above: flag column is 0
            cols += [np.zeros(int(am.sum()), np.int32)]
        # fan floor 8: per-group occupancy creeps up as a chain
        # accumulates, and each pow2 step would retrace
        fan = _round_fan(max(8, min(usr.max_run, 32)))
        out["dl_usx"] = interleave_rows(cols, pad=max(dlpad(int(am.sum())), fan))
        kw.update(
            has_us=True,
            us_cap=_round_cap(max(16, usr.index.cap)),
            us_fan=fan,
            us_slots=tuple(int(s) for s in np.unique(acc["a_rel"][am])),
        )
    gm = acc["g_srel1"] > 0
    if gm.any():
        utb = floored_hash([g_k1[gm], g_k2[gm]])
        out["dl_utb_off"] = utb.off
        out["dl_utbx"] = interleave_buckets(
            utb, [g_k1[gm], g_k2[gm]], pad=dlpad(int(gm.sum()))
        )
        kw.update(has_ustomb=True, utb_cap=_round_cap(max(16, utb.cap)))
    if acc.get("t_off"):
        kw.update(t_off=True)  # T disabled: no voiding needed, KU answers
    elif meta.has_tindex:
        dirty_parts = []
        if gm.any():
            dirty_parts.append(np.unique(
                g_k1[gm][
                    np.isin(acc["g_rel"][gm], np.asarray(meta.t_slots, np.int64))
                ]
            ))
        cld = acc.get("cl_dirty_k1")
        if cld is not None and cld.shape[0]:
            dirty_parts.append(cld)
        dirty = (
            np.unique(np.concatenate(dirty_parts))
            if dirty_parts else np.zeros(0, np.int32)
        )
        if dirty.size:
            td = floored_hash([dirty])
            out["dl_td_off"] = td.off
            out["dl_tdx"] = interleave_buckets(
                td, [dirty], pad=dlpad(int(dirty.size))
            )
            kw.update(t_dirty=True, td_cap=_round_cap(max(16, td.cap)))

    # delta arrow view (tupleset relations, direct subjects)
    ts = np.asarray(sorted(compiled.tupleset_slots), np.int64)
    aam = np.isin(acc["a_rel"], ts) & (acc["a_srel1"] == 0)
    if aam.any():
        gk_all = a_k1[aam]
        order = np.argsort(gk_all, kind="stable")
        arr = build_range_hash(
            gk_all[order],
            min_size=max(2 * F, _q4(4 * int(gk_all.shape[0]))),
            max_factor=1,
        )
        out["dl_arr_off"] = arr.index.off
        out["dl_argx"] = interleave_buckets(
            arr.index, [arr.gk, arr.glo, arr.ghi], pad=dlpad(int(aam.sum()))
        )
        cols = [acc["a_subj"][aam][order]]
        if meta.ar_hascav:
            cols += [acc["a_cav"][aam][order], acc["a_ctx"][aam][order]]
        if meta.ar_hasexp:
            cols += [acc["a_exp"][aam][order]]
        fan = _round_fan(max(8, min(arr.max_run, 32)))
        out["dl_arx"] = interleave_rows(cols, pad=max(dlpad(int(aam.sum())), fan))
        kw.update(
            has_ar=True,
            ar_cap=_round_cap(max(16, arr.index.cap)),
            ar_fan=fan,
            ar_slots=tuple(int(s) for s in np.unique(acc["a_rel"][aam])),
        )
    gam = np.isin(acc["g_rel"], ts) & (acc["g_srel1"] == 0)
    if gam.any():
        # identity for arrow-candidate masking is (group key, child node) —
        # the kernel holds the child id, not the packed subject key
        atb = floored_hash([g_k1[gam], acc["g_subj"][gam]])
        out["dl_atb_off"] = atb.off
        out["dl_atbx"] = interleave_buckets(
            atb, [g_k1[gam], acc["g_subj"][gam]], pad=dlpad(int(gam.sum()))
        )
        kw.update(has_artomb=True, atb_cap=_round_cap(max(16, atb.cap)))

    # permission-fold maintenance: folded slots KEEP answering from the
    # pf probe pair across the chain — base hits at dirty resources are
    # voided and replacement rows (recomputed for exactly those
    # resources against current data) ride small replicated overlays.
    # When the subset recompute can't stay sound/cheap it DOWNGRADES
    # (sticky pf_off: folded pairs walk, with the overlays, until
    # compaction re-folds) rather than forcing an O(E) rebuild
    if meta.fold_pairs:
        fstate = getattr(prev_dsnap, "fold_state", None)
        from .fold import fold_delta_update

        got = None
        if fstate is not None and not acc.get("pf_off"):
            got = fold_delta_update(fstate, acc, snap.node_type, config)
        if got is None:
            acc["pf_off"] = True
            kw.update(pf_off=True)
            return out, DeltaMeta(**kw), acc, _extras()
        dirty_k1, ovl = got
        if dirty_k1.shape[0]:
            pdh = floored_hash([dirty_k1])
            out["dl_pfd_off"] = pdh.off
            out["dl_pfdx"] = interleave_buckets(
                pdh, [dirty_k1], pad=dlpad(int(dirty_k1.shape[0]))
            )
            kw.update(pf_dirty=True, pfd_cap=_round_cap(max(16, pdh.cap)))
        if ovl is not None:
            packed = _fold_packed(ovl, snap, fstate.maps, N, config)
            if packed is None:
                # overlay fan past the cap: downgrade the chain (sticky
                # pf_off — folded pairs walk until compaction re-folds)
                acc["pf_off"] = True
                kw.update(pf_off=True)
                return out, DeltaMeta(**kw), acc, _extras()
            pf_k1, pf_k2, pf_subj, (u_k1, u_gk, u_until, u_fan), pff = packed
            if pf_k1.shape[0]:
                peh = floored_hash([pf_k1, pf_k2])
                out["dl_pfe_off"] = peh.off
                out["dl_pfex"] = interleave_buckets(
                    peh,
                    [pf_k1, pf_k2]
                    + ([ovl.e_cav, ovl.e_ctx] if pff["pf_hascav"] else [])
                    + ([ovl.e_until] if pff["pf_hasuntil"] else []),
                    pad=dlpad(int(pf_k1.shape[0])),
                )
                kw.update(
                    pf_ovl_e=True,
                    pfo_e_cap=_round_cap(max(16, peh.cap)),
                    pf_ovl_hascav=pff["pf_hascav"],
                    pf_ovl_hasuntil=pff["pf_hasuntil"],
                    pf_ovl_haswc=bool(
                        np.isin(pf_subj, fstate.wc_nodes).any()
                    ),
                )
            if u_k1.shape[0]:
                n_u = int(u_k1.shape[0])
                pfu = build_range_hash(
                    u_k1, min_size=max(2 * F, _q4(4 * n_u)), max_factor=1
                )
                out["dl_pfu_off"] = pfu.index.off
                out["dl_pfugx"] = interleave_buckets(
                    pfu.index, [pfu.gk, pfu.glo, pfu.ghi], pad=dlpad(n_u)
                )
                fan = _round_fan(max(8, u_fan))
                out["dl_pfux"] = interleave_rows(
                    [u_gk, u_until], pad=max(dlpad(n_u), fan)
                )
                kw.update(
                    pf_ovl_u=True,
                    pfo_u_cap=_round_cap(max(16, pfu.index.cap)),
                    pfo_u_fan=fan,
                )

    return out, DeltaMeta(**kw), acc, _extras()


# ---------------------------------------------------------------------------
# kernel codegen
# ---------------------------------------------------------------------------


#: stacked tables that stay model-split under the partitioned-serve
#: placement (FlatMeta.part_serve) — the O(E)-scale primary and folded
#: identity point tables plus the T join.  Everything else is
#: membership/group-structure sized and resident whole per device
#: there.  tx's bucket geometry differs from the routing geometry, so
#: routed kernels never compile a T probe (sharded.py _routable sends
#: T-probing slots to the psum fallback, whose ownership-mask probe is
#: geometry-self-consistent)
PART_SHARDED_TBLS = frozenset({"ehx", "pfx", "tx"})
PART_SHARDED_KEYS = frozenset(
    {"ehx", "eh_off", "pfx", "pfh_off", "tx", "th_off"}
)


def make_flat_fn(
    compiled: CompiledSchema,
    plan: DevicePlan,
    cfg: EngineConfig,
    meta: FlatMeta,
    slots: Tuple[int, ...],
    caveat_plan=None,
    jit: bool = True,
    axis: Optional[str] = None,
    model_size: int = 1,
    routed: bool = False,
    witness: bool = False,
):
    """Build the batched flat check function for a static set of permission
    slots.  Queries select their slot's result with a vectorized compare —
    evaluating ≤ flat_max_slots programs over the whole batch is far
    cheaper than any per-query dispatch.

    With ``axis`` (inside shard_map over the model axis, tables built by
    build_flat_arrays_sharded) every probe masks bucket ownership, boolean
    site outputs OR-reduce with psum over ICI, and userset/arrow candidate
    blocks broadcast from their single owning shard — the program is the
    same straight-line probe pipeline with one collective per site.

    With ``meta.part_serve`` (partitioned-serve placement) only the
    primary/fold point tables are model-split; every other stacked table
    is whole per device, probed by resolving its owner's block
    arithmetically — those sites need NO collective, so the only psums
    left are the e/pf probes.  With ``routed=True`` on top, the batch
    axis itself is owner-routed (each shard holds exactly the queries
    whose root (k1, k2) bucket it owns): the e/pf root probes drop their
    ownership mask — a row with the probed key can only live in its
    owner's buckets, so a non-owner probe misses by construction — and
    the compiled program contains no collective at all.  Routed kernels
    are only built for ROUTABLE slot sets (fully folded permissions and
    bare relation leaves, no wildcard edges): the dispatcher enforces
    this, because a routed sub-batch is shard-local and a psum over it
    would merge unrelated queries.

    ``witness=True`` arms DECISION-PROVENANCE extraction: the kernel
    emits a fourth int32[B] output — a per-query witness code naming the
    winning branch (direct edge / wildcard / T-probe / fold / userset ×
    closure / rewrite / reflexive self, plus a recursion-level class in
    the upper bits; codes in engine/explain.py) for device-definite
    allowed verdicts, 0 otherwise.  The masks are REUSED from the probe
    sites the kernel computes anyway — the armed cost is the final
    select cascade.  Disarmed (the default) the traced program is
    byte-identical to the pre-witness kernel: no extra output, no extra
    ops — the trace.py NOOP discipline applied to kernel outputs."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..caveats.device import make_tri_fn
    from .explain import (
        WIT_DIRECT,
        WIT_FOLD,
        WIT_LEVEL_SHIFT,
        WIT_REWRITE,
        WIT_SELF,
        WIT_TPROBE,
        WIT_USERSET,
        WIT_WILDCARD,
    )

    tri = make_tri_fn(caveat_plan) if caveat_plan is not None else None
    SH = axis is not None
    PART = bool(meta.part_serve)
    # Pallas fused probe backend (engine/pallas.py): unsharded blockslice
    # probes route through the hand-fused kernel when the knob resolves
    # on.  Sharded/part-serve/routed layouts keep the XLA chain — their
    # probes carry ownership masks and collectives the kernel doesn't
    # model; the resolve is deterministic per process+config, so pinned
    # latency tiers keep the no-retrace contract
    from . import pallas as _pallas

    PLS = (not SH) and _pallas.resolve(cfg)
    # under sharding the delta overlay tables are REPLICATED (they are
    # small): delta probe sites use plain unsharded probes whose results
    # are identical on every shard, composed after the base sites'
    # OR-reductions — no extra collectives
    if SH != meta.sharded:
        raise ValueError(
            "kernel/layout mismatch: bucket-sharded tables need the model"
            " axis and vice versa (FlatMeta.sharded vs make_flat_fn axis)"
        )
    if (PART or routed) and not SH:
        raise ValueError(
            "partitioned-serve/routed kernels need the model axis"
        )
    if routed and not PART:
        raise ValueError("routed dispatch requires part_serve placement")

    perm_programs: Dict[int, List[Tuple[str, int, ExprIR]]] = {}
    for (tname, tid, slot, expr) in plan.topo_programs:
        perm_programs.setdefault(slot, []).append((tname, tid, expr))
    # flattened recursive hierarchies whose closure tables were built:
    # (type, slot) → (ts_slot, rest_ir); geometry per ts_slot from meta
    rc_geom = {ts: (cap, fan) for ts, cap, fan in meta.rc_slots}
    rc_map = {
        key: (ts_slot, rest)
        for key, (ts_slot, rest) in rc_candidates(compiled, plan).items()
        if ts_slot in rc_geom
    }
    rel_slots = frozenset(plan.rel_leaf_slots)
    # permission fold: BASE answers come from the pf_e/pf_t probe pair;
    # folded programs compile to nothing.  A delta level rides along via
    # incremental maintenance (engine/fold.py fold_delta_update): base pf
    # hits at dirty resources are voided and replacement rows probed from
    # the replicated dl_pf* overlays — folded worlds keep fold-speed
    # answers across a Watch chain
    fold_on = bool(meta.fold_pairs) and not (
        meta.delta is not None and meta.delta.pf_off
    )
    folded_pairs = frozenset(meta.fold_pairs) if fold_on else frozenset()
    pf_slots = frozenset(s for _, s in folded_pairs)
    cyclic = _eval_cyclic_pairs(compiled)
    KU = cfg.us_leaf_cap
    K = cfg.arrow_fanout
    all_types = frozenset(compiled.type_ids)
    tname_of_tid = {tid: t for t, tid in compiled.type_ids.items()}

    def arrow_child_types(ts_slot: int, types: frozenset) -> frozenset:
        """Types an arrow through ``ts_slot`` can reach from ``types`` —
        the static pruning that makes the unroll follow the TYPE-level
        dependency graph (plan._eval_dep_graph), not name collisions."""
        out = set()
        for tname in types:
            ct = compiled.types[compiled.type_ids[tname]]
            rel = ct.relations.get(ts_slot)
            if rel is None:
                continue
            for a in rel.allowed:
                if a.relation_slot < 0:  # arrows traverse direct subjects
                    out.add(tname_of_tid[a.type_id])
        return frozenset(out)

    K1D = meta.k1_dense  # static sites pack with DENSE slot ids

    def k1c(slot: int):
        return jnp.int32(K1D[slot] if slot < len(K1D) else -1)

    def fn(arrs, tid_map, now, qm, qctx):
        # packed query matrix int32[8, B] (QM_LAYOUT): one host→device
        # transfer per dispatch instead of seven — on a remote-attached
        # chip each extra arg is a tunnel round-trip in the p99.  Rows 3
        # and 7 arrive DENSE-mapped (build_qm)
        q_res, q_perm, q_subj = qm[0], qm[1], qm[2]
        q_srel1, q_wc, q_ctx = qm[3], qm[4], qm[5]
        q_self = qm[6] != 0
        q_perm_k1 = qm[7]
        if tri is not None:
            tables = {
                "ectx_vi": arrs["ectx_vi"], "ectx_vf": arrs["ectx_vf"],
                "ectx_pr": arrs["ectx_pr"], "ectx_host": arrs["ectx_host"],
                "qctx_vi": qctx["vi"], "qctx_vf": qctx["vf"],
                "qctx_pr": qctx["pr"], "qctx_host": qctx["host"],
            }
        else:
            tables = None
        node_type = arrs["node_type"]
        Nc0 = jnp.int32(meta.N)
        # ids interned AFTER this snapshot (shared append-only interner,
        # older pinned generation) exceed the packing radix: treat them as
        # invalid (-1) — they have no edges at this revision, so every
        # probe must miss, and aliased packed keys must never be formed
        q_res = jnp.where(q_res < Nc0, q_res, -1)
        q_subj = jnp.where(q_subj < Nc0, q_subj, -1)
        q_wc = jnp.where(q_wc < Nc0, q_wc, -1)
        # wildcard closure-source only applies to direct-object subjects
        q_wcc = jnp.where(q_srel1 == 0, q_wc, -1)

        def bq(a, nd: int):
            """Broadcast a [B] query column against [B, ...] node dims."""
            return a.reshape(a.shape + (1,) * (nd - 1))

        def reduceB(x):
            return x if x.ndim == 1 else jnp.any(x, axis=tuple(range(1, x.ndim)))

        tk = take_in_bounds  # indices below are clipped non-negative
        BS = meta.blockslice
        eL, usL, arL = e_layout(meta), us_layout(meta), ar_layout(meta)

        # HBM-lean packed tables (engine/packed.py): uint16-lane arrays
        # decode with shift/mask ops fused into the consuming compares;
        # packed offset arrays read anchor + residual.  Both maps are
        # empty on unpacked snapshots and every helper then passes
        # through untouched — one code path serves both layouts
        PK = dict(meta.packed)
        PKO = dict(meta.packed_off)

        def _dec(tbl_key: str, blk):
            spec = PK.get(tbl_key)
            return blk if spec is None else _pk_decode(blk, spec)

        def off_read(off_key: str, idx):
            A = PKO.get(off_key)
            if A is None:
                return tk(arrs[off_key], idx)
            return tk(arrs[off_key + "_a"], idx >> A) + tk(
                arrs[off_key], idx
            ).astype(jnp.int32)

        def sblock(tbl_key: str, lo, cap: int):
            """slice_blocks through the packed decode."""
            return _dec(tbl_key, slice_blocks(arrs[tbl_key], lo, cap))

        _view_flags = {
            "e": (meta.e_hascav, meta.e_hasexp),
            "us": (meta.us_hascav, meta.us_hasexp),
            "ar": (meta.ar_hascav, meta.ar_hasexp),
        }

        def gate2(prefix: str, rowidx, hit):
            """(definite, possible) admissibility of the hit edges, with
            the CEL VM evaluated ONCE per site and skipped statically for
            views with no caveated/expiring rows."""
            hascav, hasexp = _view_flags[prefix]
            if not hascav and not hasexp:
                return hit, hit
            rc = jnp.clip(rowidx, 0, arrs[prefix + "_caveat"].shape[0] - 1)
            live = hit
            if hasexp:
                exp = tk(arrs[prefix + "_exp"], rc)
                live = hit & ((exp == 0) | (exp > now))
            if not hascav:
                return live, live
            cav = tk(arrs[prefix + "_caveat"], rc)
            if tri is None:
                d = live & (cav == 0)
                return d, live
            ctxc = tk(arrs[prefix + "_ctx"], rc)
            qb = jnp.broadcast_to(bq(q_ctx, rowidx.ndim), cav.shape)
            t = tri(cav, ctxc, qb, tables)
            return live & (t == 2), live & (t >= 1)

        def gate2_blk(prefix: str, blk, lay: Dict[str, int], hit):
            """gate2 over an interleaved block's payload columns: the gate
            values ride in the SAME contiguous slice as the keys, so no
            second gather happens.  Padded/overshoot rows are neutralized
            through ``hit`` (their gate inputs are clamped first — they may
            hold -1 or a neighbouring bucket's payloads)."""
            hascav, hasexp = _view_flags[prefix]
            if not hascav and not hasexp:
                return hit, hit
            live = hit
            if hasexp:
                exp = jnp.where(hit, blk[..., lay["exp"]], 0)
                live = hit & ((exp == 0) | (exp > now))
            if not hascav:
                return live, live
            cav = jnp.where(hit, blk[..., lay["cav"]], 0)
            if tri is None:
                return live & (cav == 0), live
            ctxc = jnp.where(hit, blk[..., lay["ctx"]], -1)
            qb = jnp.broadcast_to(bq(q_ctx, cav.ndim), cav.shape)
            t = tri(cav, ctxc, qb, tables)
            return live & (t == 2), live & (t >= 1)

        dm = meta.delta
        me = lax.axis_index(axis) if SH else None
        # part-serve: every non-e/pf stacked table is whole per device
        # and its probes resolve ownership arithmetically — the owner-
        # broadcast/OR sites become identity (SH_VB guards them)
        SH_VB = SH and not PART

        def por(x):
            """Boolean OR-reduce over the model axis (identity 1-chip,
            and identity under part-serve, where every non-e/pf probe is
            locally complete)."""
            return (
                x if not SH_VB
                else lax.psum(x.astype(jnp.int32), axis).astype(bool)
            )

        def por_m(x, mine):
            """OR-reduce for model-split point sites: needed exactly when
            the probe carried a bucket-ownership mask; a maskless probe
            was locally complete (1-chip, part-serve whole-resident
            table, or a routed batch on its owner shard)."""
            return (
                x if mine is None
                else lax.psum(x.astype(jnp.int32), axis).astype(bool)
            )

        def vbcast(own, x):
            """Single-owner int32 broadcast over the model axis: exactly
            one shard contributes (its bucket owns the key), the psum of
            masked values IS the value (identity 1-chip; identity under
            part-serve, where the sliced block is already the owner's)."""
            return x if not SH_VB else lax.psum(jnp.where(own, x, 0), axis)

        def blk_hit(blk, q_cols, mine=None):
            """Exact-key hit mask over a probe block's candidates, with
            ≥0 validity guards on every query column (padded/overshoot
            rows hold -1 keys or other buckets' keys and never match) and
            the bucket-ownership mask under sharding."""
            h = jnp.ones(blk.shape[:-1], bool)
            g = None
            for j, qc in enumerate(q_cols):
                h = h & (blk[..., j] == qc[..., None])
                g = (qc >= 0) if g is None else (g & (qc >= 0))
            h = h & g[..., None]
            if mine is not None:
                h = h & mine[..., None]
            return h

        ALD = {k: (w, caps) for (k, w, caps) in meta.aligned}

        def psite(off_key: str, tbl_key: str, cap: int, q_cols,
                  mode: str = "block",
                  gate3: Tuple[bool, bool, bool] = (False, False, False),
                  lay: Optional[Dict[str, int]] = None,
                  need_now: bool = False):
            """Route one unsharded blockslice probe through the Pallas
            fused kernel (engine/pallas.py); None = keep the XLA chain
            (knob off, sharded layout, or the site's offset arrays are
            too big for the VMEM-resident plan).  The kernel replicates
            mix32 / the slice clamp / decode_block verbatim, so ``block``
            mode is bitwise the XLA block and the reduced modes are
            bitwise its downstream folds."""
            if not PLS:
                return None
            spec = PK.get(tbl_key)
            nw = now if need_now else None
            al = ALD.get(tbl_key)
            if al is not None and tbl_key + "_al" in arrs:
                w_, caps = al
                sw = w_ if spec is None else spec[1]
                tbls = [
                    arrs[_al_key(tbl_key, lvl)]
                    for lvl in range(len(caps))
                    if _al_key(tbl_key, lvl) in arrs
                ]
                return _pallas.fused_probe_aligned(
                    q_cols, tbls, caps[: len(tbls)], sw, spec=spec,
                    mode=mode, now=nw, gate=gate3, lay=lay,
                )
            A = PKO.get(off_key)
            off = arrs[off_key]
            off_a = arrs[off_key + "_a"] if A is not None else None
            if not _pallas.vmem_ok(off) or (
                off_a is not None and not _pallas.vmem_ok(off_a)
            ):
                return None
            return _pallas.fused_probe(
                q_cols, off, arrs[tbl_key], cap=cap, spec=spec,
                off_a=off_a, ashift=A, mode=mode, now=nw, gate=gate3,
                lay=lay,
            )

        def pblock(off_key: str, tbl_key: str, cap: int, q_cols):
            """Layout-dispatched bucket probe: (blk, mine) — the block
            already DECODED to logical int32 columns when the table is
            packed.

            Bucket-ALIGNED tables (``{tbl_key}_al`` present, unsharded
            base layout) probe with one row gather per width-stratum
            level; otherwise the off+interleave block slice.  Sharded
            tables derive bpd from the LOCAL off length (shapes inside
            shard_map are per-shard)."""
            if not SH:
                pb = psite(off_key, tbl_key, cap, q_cols, mode="block")
                if pb is not None:
                    return pb, None
                al = ALD.get(tbl_key)
                if al is not None and tbl_key + "_al" in arrs:
                    w_, caps = al
                    spec = PK.get(tbl_key)
                    sw = w_ if spec is None else spec[1]
                    tbls = [
                        arrs[_al_key(tbl_key, lvl)]
                        for lvl in range(len(caps))
                        if _al_key(tbl_key, lvl) in arrs
                    ]
                    return _dec(tbl_key, probe_aligned(
                        tbls, caps[: len(tbls)], sw, q_cols
                    )), None
                size = arrs[off_key].shape[0] - 1
                h = (
                    mix32(q_cols, jnp) & jnp.uint32(size - 1)
                ).astype(jnp.int32)
                start = off_read(off_key, h)
                return sblock(tbl_key, start, cap), None
            off, tbl = arrs[off_key], arrs[tbl_key]
            if PART and tbl_key not in PART_SHARDED_TBLS:
                # whole-resident stacked table: resolve the owner shard's
                # block arithmetically (off is the full [M·(bpd+1)]
                # stacked offsets; rows live at [s·R_pad + local]) — no
                # ownership mask, no collective.  Overshooting a shard's
                # padding reads a neighbour's rows, whose keys carry a
                # different owner and can never equal the probed key
                bpd = off.shape[0] // model_size - 1
                R_pad = jnp.int32(tbl.shape[0] // model_size)
                h = (
                    mix32(q_cols, jnp) & jnp.uint32(bpd * model_size - 1)
                ).astype(jnp.int32)
                s = h // jnp.int32(bpd)
                start = take_in_bounds(
                    off, s * jnp.int32(bpd + 1) + (h & jnp.int32(bpd - 1))
                ) + s * R_pad
                return sblock(tbl_key, start, cap), None
            bpd = off.shape[0] - 1
            h = (
                mix32(q_cols, jnp) & jnp.uint32(bpd * model_size - 1)
            ).astype(jnp.int32)
            # routed batches sit on their owner shard already, and a
            # non-owner probe of a model-split table misses by key
            # construction — no mask, no psum at the site
            if routed:
                start = take_in_bounds(off, h & jnp.int32(bpd - 1))
                return sblock(tbl_key, start, cap), None
            mine = (h // jnp.int32(bpd)) == me
            start = take_in_bounds(off, h & jnp.int32(bpd - 1))
            return sblock(tbl_key, start, cap), mine

        def range_probe(off_key: str, tbl_key: str, cap: int, q,
                        rep: bool = False, rows_key: Optional[str] = None):
            """(lo, hi) LOCAL row range of group key ``q``; (0, 0) on a
            miss or on non-owning shards.  ``rep`` marks a REPLICATED
            table (delta overlays): the bucket-ownership math would use
            the wrong hash mask there, so it probes plainly.  Under
            part-serve the group entry's row range is local to its
            owner's block of the whole-resident stacked rows table
            (``rows_key``), so the owner's base offset is added — on a
            miss lo == hi keeps the slice empty."""
            if rep:
                blk, mine = probe_block(
                    arrs[off_key], arrs[tbl_key], cap, (q,)
                ), None
            else:
                blk, mine = pblock(off_key, tbl_key, cap, (q,))
            hit = blk_hit(blk, (q,), mine)
            lo = jnp.max(jnp.where(hit, blk[..., 1], 0), axis=-1)
            hi = jnp.max(jnp.where(hit, blk[..., 2], 0), axis=-1)
            if PART and not rep and rows_key is not None:
                goff = arrs[off_key]
                bpd = goff.shape[0] // model_size - 1
                R_rows = jnp.int32(arrs[rows_key].shape[0] // model_size)
                hq = (
                    mix32((q,), jnp) & jnp.uint32(bpd * model_size - 1)
                ).astype(jnp.int32)
                base = (hq // jnp.int32(bpd)) * R_rows
                lo = lo + base
                hi = hi + base
            return lo, hi

        def range_of(prefix: str, cap: int, n: int, q):
            if BS:
                return range_probe(
                    prefix + "_off",
                    {"usr": "usgx", "arr": "argx"}[prefix],
                    cap, q,
                    rows_key={"usr": "usx", "arr": "arx"}[prefix],
                )
            ri = {
                k: arrs[prefix + "_" + k]
                for k in ("gk", "glo", "ghi", "off", "rows")
            }
            return probe_range(ri, cap, n, q)

        def cl_probe(srck, gk):
            """Closure containment per plane via until-value comparison.
            Keys are packed (src·S1+srel1, g·S1+grel+1); -1 never matches."""
            if not meta.has_closure:
                z = jnp.zeros(
                    jnp.broadcast_shapes(jnp.shape(srck), jnp.shape(gk)), bool
                )
                return z, z
            if BS:
                pr = psite("clh_off", "clx", meta.cl_cap, (srck, gk),
                           mode="until2", need_now=True)
                if pr is not None:
                    return pr
                blk, mine = pblock(
                    "clh_off", "clx", meta.cl_cap, (srck, gk)
                )
                hit = blk_hit(blk, (srck, gk), mine)
                return (
                    por_m(jnp.any(hit & (blk[..., 2] > now), axis=-1), mine),
                    por_m(jnp.any(hit & (blk[..., 3] > now), axis=-1), mine),
                )
            row = probe_rows(
                arrs["clh_off"], arrs["clh_rows"],
                (arrs["cl_k1"], arrs["cl_k2"]), (srck, gk),
                meta.cl_cap, meta.cl_n,
            )
            rc = jnp.clip(row, 0, arrs["cl_k1"].shape[0] - 1)
            hit = row >= 0
            return (
                hit & (tk(arrs["cl_d_until"], rc) > now),
                hit & (tk(arrs["cl_p_until"], rc) > now),
            )

        zB = jnp.zeros(q_res.shape, bool)

        class _WitColl:
            """Witness-mask collector (armed kernels only).  ``add``
            OR-accumulates a branch's definite mask, gated by the
            collector's selection mask (which root slot / node type the
            enclosing program applies to) — an ungated mask from another
            type's program must never claim a branch for a query it
            cannot grant.  Deeper node lattices (arrow children, rc
            ancestors) are skipped: grants found there report as the
            ``rewrite`` branch."""

            __slots__ = ("store", "mask")

            def __init__(self, store, mask=None):
                self.store = store
                self.mask = mask

            def add(self, key, m):
                if m.ndim != 1:
                    return
                if self.mask is not None:
                    m = m & self.mask
                prev = self.store.get(key)
                self.store[key] = m if prev is None else (prev | m)

            def masked(self, mask):
                return _WitColl(
                    self.store,
                    mask if self.mask is None else (self.mask & mask),
                )

        Nc = jnp.int32(meta.N)
        S1c = jnp.int32(meta.S1)
        # packed per-query subject keys: -1 = "matches nothing"
        # (q_srel1 < 0 = the subject relation has no dense id)
        q_k2 = jnp.where(
            (q_subj >= 0) & (q_srel1 >= 0), q_subj * S1c + q_srel1, -1
        )
        w_k2 = jnp.where((q_wc >= 0) & (q_srel1 == 0), q_wc * S1c, -1)
        wcl_k = jnp.where(q_wcc >= 0, q_wcc * S1c, -1)
        us_fans = dict(meta.us_fanout_by_slot)
        # the dynamic root leaf serves exactly the dispatch's static slot
        # set: base sites whose slots can't occur compile to nothing (a
        # fully folded dispatch is JUST the two pf probes)
        dyn_e = any(s in meta.e_slots for s in slots)
        dyn_us_fan = max((us_fans.get(s, 0) for s in slots), default=0)
        # sticky chain-level T disable (membership-closure deltas staled
        # more baked T rows than the dirty budget): the KU path probes
        # the live closure instead
        t_on = meta.has_tindex and not (dm is not None and dm.t_off)
        t_cover = t_on and all(
            s in meta.t_slots for s in slots if s in meta.us_slots
        )
        dyn_t = t_on and t_cover and any(
            s in meta.t_slots for s in slots
        )

        pfL = _lay(
            ["k1", "k2"]
            + (["cav", "ctx"] if meta.pf_hascav else [])
            + (["until"] if meta.pf_hasuntil else [])
        )

        # fold subject side: the query subject's (and wildcard node's)
        # group-closure slices from the csr closure-by-source view,
        # computed ONCE per dispatch — [B, S] key/plane-liveness tiles
        # the pf_u sites intersect against in registers.  This is the
        # sorted-key-column intersection (Leopard's skipping-list read)
        # that replaces the dense (resource × member) fold T-join: no
        # per-group hash probes, no product materialization.  Single-chip
        # layouts slice SPLIT 1-wide columns with the range resolved from
        # the csr_start offset array (two element gathers); the sharded
        # layout keeps the packed bucket-sharded view
        _pf_subj_cell: List = []

        def pf_subj_slices():
            if _pf_subj_cell:
                return _pf_subj_cell[0]
            fanS = max(meta.pf_s_fan, 1)

            def csr_slice(k):
                ok = k >= 0
                # part-serve with the direct view: the dense offset
                # array + split columns are replicated (they are the
                # COMPACT closure-by-source form — the bucket-hash
                # group tables cost ~16× the bytes), so the single-chip
                # two-element-gather path applies on every shard
                split = (not SH) or (PART and meta.pf_s_direct)
                if split and meta.pf_s_direct:
                    kc = jnp.where(ok, k, 0)
                    lo = off_read("csr_start", kc)
                    hi = jnp.where(ok, off_read("csr_start", kc + 1), lo)
                else:
                    lo, hi = range_probe(
                        "csr_off", "csrgx", meta.pf_s_cap, k,
                        rows_key="csrx",
                    )
                valid = (
                    jnp.arange(fanS, dtype=jnp.int32) < (hi - lo)[..., None]
                ) & ok[..., None]
                if not split:
                    blk = sblock("csrx", lo, fanS)
                    blk = vbcast(valid[..., None], blk)
                    valid = por(valid)
                    gk = jnp.where(valid, blk[..., 0], -1)
                    dok = valid & (jnp.where(valid, blk[..., 1], 0) > now)
                    pok = valid & (jnp.where(valid, blk[..., 2], 0) > now)
                    return gk, dok, pok
                gk = slice_blocks(arrs["csr_gk"], lo, fanS)[..., 0]
                gk = jnp.where(valid, gk, -1)
                if meta.pf_s_alllive:
                    # None planes: containment alone grants both (the
                    # intersection then runs ONE reduce with no plane
                    # tiles — invalid lanes are already -1-masked)
                    return gk, None, None
                dv = slice_blocks(arrs["csr_d"], lo, fanS)[..., 0]
                pv = slice_blocks(arrs["csr_p"], lo, fanS)[..., 0]
                dok = valid & (jnp.where(valid, dv, 0) > now)
                pok = valid & (jnp.where(valid, pv, 0) > now)
                return gk, dok, pok

            slices = [csr_slice(q_k2)]
            if meta.has_wc_closure:
                slices.append(csr_slice(wcl_k))
            _pf_subj_cell.append(slices)
            return slices

        # fold-slot compact ids for the direct pfu_start lookup
        if fold_on and meta.pf_has_u and meta.pf_direct:
            _fm = np.full(max(plan.num_slots, 1), -1, np.int32)
            for _i, _s in enumerate(sorted({s for _, s in meta.fold_pairs})):
                _fm[_s] = _i
            pf_fidx_t = jnp.asarray(_fm)
        else:
            pf_fidx_t = None

        def pf_isect(gk, live):
            """(d, p) of the folded userset rows ``gk``/``live``
            ([..., fan], lattice-shaped) against the subject slices:
            a broadcast [fan × S] compare, reduced over both axes."""
            d = jnp.zeros(live.shape[:-1], bool)
            p = jnp.zeros(live.shape[:-1], bool)
            for (sgk, sdok, spok) in pf_subj_slices():
                shp = (sgk.shape[0],) + (1,) * (gk.ndim - 2) + (1, sgk.shape[1])
                m = live[..., None] & (gk[..., None] == sgk.reshape(shp))
                if sdok is None:  # all-live closure: one containment reduce
                    hit = jnp.any(m, axis=(-1, -2))
                    d, p = d | hit, p | hit
                else:
                    d = d | jnp.any(m & sdok.reshape(shp), axis=(-1, -2))
                    p = p | jnp.any(m & spok.reshape(shp), axis=(-1, -2))
            return d, p

        def pf_probe(slot, nodes, coll=None):
            """Folded-permission test at a [B, ...] node lattice: ONE
            direct-identity probe (pf_e) + one bounded-fan userset slice
            (pf_u) intersected with the member closure — the rewrite
            pre-joined at prepare time (engine/fold.py), the membership
            expansion factored out so the tables never materialize the
            (resource × member) product and the closure can advance in
            place under membership deltas.  ``slot=None`` = dynamic
            (q_perm is the slot).  Fold tables are exact — the fan covers
            the true max group count, so no overflow contributions."""
            nd = nodes.ndim
            zn = jnp.zeros(nodes.shape, bool)
            d = p = zn
            exists = nodes >= 0
            sc = bq(q_perm_k1, nd) if slot is None else k1c(slot)
            k1 = sc * Nc + jnp.where(exists, nodes, 0)
            if meta.pf_has_e:
                def pe_site(k2q):
                    blk, mine = pblock(
                        "pfh_off", "pfx", meta.pf_e_cap, (k1, k2q)
                    )
                    hit = blk_hit(blk, (k1, k2q), mine) & exists[..., None]
                    live = hit
                    if meta.pf_hasuntil:
                        u = jnp.where(hit, blk[..., pfL["until"]], 0)
                        live = hit & (u > now)
                    if not meta.pf_hascav:
                        hd = hp = live
                    else:
                        cav = jnp.where(live, blk[..., pfL["cav"]], 0)
                        if tri is None:
                            hd, hp = live & (cav == 0), live
                        else:
                            ctxc = jnp.where(live, blk[..., pfL["ctx"]], -1)
                            qb = jnp.broadcast_to(
                                bq(q_ctx, cav.ndim), cav.shape
                            )
                            t = tri(cav, ctxc, qb, tables)
                            hd, hp = live & (t == 2), live & (t >= 1)
                    return (
                        por_m(jnp.any(hd, axis=-1), mine),
                        por_m(jnp.any(hp, axis=-1), mine),
                    )

                ed, ep = pe_site(bq(q_k2, nd))
                d, p = d | ed, p | ep
                if meta.pf_haswc:
                    wd, wp = pe_site(bq(w_k2, nd))
                    d, p = d | wd, p | wp
            if meta.pf_has_u:
                # folded userset groups: one contiguous fan slice, then
                # the register intersection with the subject's closure
                # slice (the Leopard skipping-list read — never the dense
                # product, never per-group hash probes)
                fanU = max(meta.pf_u_fan, 1)
                split_u = (not SH) or (PART and meta.pf_direct)
                if split_u and meta.pf_direct:
                    fc = (
                        tk(pf_fidx_t, jnp.clip(bq(q_perm, nd), 0, None))
                        if slot is None
                        else jnp.int32(
                            sorted({s for _, s in meta.fold_pairs}).index(slot)
                        )
                    )
                    ok = exists & (fc >= 0)
                    base = jnp.where(ok, fc * Nc + nodes, 0)
                    lo = off_read("pfu_start", base)
                    hi = jnp.where(ok, off_read("pfu_start", base + 1), lo)
                else:
                    lo, hi = range_probe(
                        "pfu_off", "pfugx", meta.pf_u_cap, k1,
                        rows_key="pfux",
                    )
                valid = (
                    jnp.arange(fanU, dtype=jnp.int32) < (hi - lo)[..., None]
                ) & exists[..., None]
                if not split_u:
                    ublk = sblock("pfux", lo, fanU)
                    ublk = vbcast(valid[..., None], ublk)
                    valid = por(valid)
                    gk = jnp.where(valid, ublk[..., 0], -1)
                    live = valid & (jnp.where(valid, ublk[..., 1], 0) > now)
                else:
                    gk = slice_blocks(arrs["pfu_gk"], lo, fanU)[..., 0]
                    gk = jnp.where(valid, gk, -1)
                    if meta.pf_u_alllive:
                        live = valid
                    else:
                        uv = slice_blocks(arrs["pfu_u"], lo, fanU)[..., 0]
                        live = valid & (jnp.where(valid, uv, 0) > now)
                nd2 = nd + 1
                ud, up = pf_isect(gk, live)
                refl = (gk == bq(q_k2, nd2)) & (bq(q_k2, nd2) >= 0)
                r_hit = jnp.any(live & refl, axis=-1)
                d = d | ud | r_hit
                p = p | up | r_hit
            # incremental maintenance: void base hits at DIRTY resources,
            # then OR in the recomputed replacement rows.  The overlay
            # tables are replicated (plain probes, identical on every
            # shard) and sit after the base sites' OR-reductions
            if dm is not None and dm.pf_dirty:
                pdb = probe_block(
                    arrs["dl_pfd_off"], arrs["dl_pfdx"], dm.pfd_cap, (k1,)
                )
                dirty = jnp.any(blk_hit(pdb, (k1,)), axis=-1)
                d, p = d & ~dirty, p & ~dirty
            if dm is not None and dm.pf_ovl_e:
                oL = _lay(
                    ["k1", "k2"]
                    + (["cav", "ctx"] if dm.pf_ovl_hascav else [])
                    + (["until"] if dm.pf_ovl_hasuntil else [])
                )

                def po_site(k2q):
                    blk = probe_block(
                        arrs["dl_pfe_off"], arrs["dl_pfex"], dm.pfo_e_cap,
                        (k1, k2q),
                    )
                    hit = blk_hit(blk, (k1, k2q)) & exists[..., None]
                    live = hit
                    if dm.pf_ovl_hasuntil:
                        u = jnp.where(hit, blk[..., oL["until"]], 0)
                        live = hit & (u > now)
                    if not dm.pf_ovl_hascav:
                        hd = hp = live
                    else:
                        cav = jnp.where(live, blk[..., oL["cav"]], 0)
                        if tri is None:
                            hd, hp = live & (cav == 0), live
                        else:
                            ctxc = jnp.where(live, blk[..., oL["ctx"]], -1)
                            qb = jnp.broadcast_to(
                                bq(q_ctx, cav.ndim), cav.shape
                            )
                            t = tri(cav, ctxc, qb, tables)
                            hd, hp = live & (t == 2), live & (t >= 1)
                    return jnp.any(hd, axis=-1), jnp.any(hp, axis=-1)

                od, op_ = po_site(bq(q_k2, nd))
                d, p = d | od, p | op_
                if dm.pf_ovl_haswc:
                    owd, owp = po_site(bq(w_k2, nd))
                    d, p = d | owd, p | owp
            if dm is not None and dm.pf_ovl_u:
                # replacement folded-userset rows for dirty resources:
                # replicated range view, same register intersection
                fanO = max(dm.pfo_u_fan, 1)
                lo, hi = range_probe(
                    "dl_pfu_off", "dl_pfugx", dm.pfo_u_cap, k1, rep=True
                )
                valid = (
                    jnp.arange(fanO, dtype=jnp.int32) < (hi - lo)[..., None]
                ) & exists[..., None]
                ublk = slice_blocks(arrs["dl_pfux"], lo, fanO)
                gk = jnp.where(valid, ublk[..., 0], -1)
                live = valid & (jnp.where(valid, ublk[..., 1], 0) > now)
                nd2 = nd + 1
                od, op_ = pf_isect(gk, live)
                refl = (gk == bq(q_k2, nd2)) & (bq(q_k2, nd2) >= 0)
                r_hit = jnp.any(live & refl, axis=-1)
                d = d | od | r_hit
                p = p | op_ | r_hit
            if coll is not None:
                coll.add("fold", d)
            return d, p

        # Every eval function returns (definite, possible, ovf, used):
        # d/p shaped like the node lattice, ovf/used reduced to [B].
        # Compositional returns let ONE memo serve every root slot while
        # keeping overflow attribution per query.

        def leaf(slot, nodes, coll=None):
            """Direct + wildcard + userset leaf tests at a [B, ...] node
            lattice.  ``slot`` is a static int for program-internal
            references; ``None`` means dynamic — the query's own q_perm
            column is the relation, so ONE probe site at the root covers
            every slot's direct relation check.  ``coll`` (the ROOT
            dynamic call only, witness armed) collects per-branch
            definite masks for the witness plane — None compiles to
            nothing."""
            nd = nodes.ndim
            zn = jnp.zeros(nodes.shape, bool)
            d, p, ovf, used = zn, zn, zB, zB
            exists = nodes >= 0
            dyn = slot is None
            sc = bq(q_perm_k1, nd) if dyn else k1c(slot)
            # packed (slot, node) key; invalid nodes use 0 and are masked
            # by `exists` wherever the (possibly aliased) probe lands
            k1 = sc * Nc + jnp.where(exists, nodes, 0)

            run_e = dyn_e if dyn else (slot in meta.e_slots)
            run_ed = dm is not None and dm.has_adds and (
                bool(dm.e_slots) if dyn else (slot in dm.e_slots)
            )
            if run_e and BS or run_ed:
                def e_site(k2q):
                    """Direct-edge test: (base hit minus tombstones) OR
                    delta-level hit — exact replacement semantics, since
                    tombstones carry full primary identities."""
                    hd = hp = jnp.zeros(nodes.shape, bool)
                    if run_e:
                        pg = psite(
                            "eh_off", "ehx", meta.e_cap, (k1, k2q),
                            mode="gate",
                            gate3=(meta.e_hasexp, meta.e_hascav,
                                   meta.e_hascav and tri is not None),
                            lay=eL, need_now=meta.e_hasexp,
                        )
                        if pg is not None:
                            # expiry gate fused in-kernel; the CEL tri VM
                            # runs on the compact cav/ctx lanes.  exists
                            # is lane-constant: ANDing it after the
                            # kernel's hit/live masks commutes (dead
                            # lanes' cav/ctx feed tri but live kills
                            # them), so parity with gate2_blk is exact
                            live = pg[1] & exists[..., None]
                            if not meta.e_hascav:
                                bd = bp = live
                            elif tri is None:
                                bd, bp = live & (pg[2] == 0), live
                            else:
                                qb = jnp.broadcast_to(
                                    bq(q_ctx, pg[2].ndim), pg[2].shape
                                )
                                tv = tri(pg[2], pg[3], qb, tables)
                                bd = live & (tv == 2)
                                bp = live & (tv >= 1)
                            hd = jnp.any(bd, axis=-1)
                            hp = jnp.any(bp, axis=-1)
                        else:
                            blk, mine = pblock(
                                "eh_off", "ehx", meta.e_cap,
                                (k1, k2q),
                            )
                            hit = (
                                blk_hit(blk, (k1, k2q), mine)
                                & exists[..., None]
                            )
                            bd, bp = gate2_blk("e", blk, eL, hit)
                            hd = por_m(jnp.any(bd, axis=-1), mine)
                            hp = por_m(jnp.any(bp, axis=-1), mine)
                        if dm is not None and dm.has_tombs:
                            tb = probe_block(
                                arrs["dl_tb_off"], arrs["dl_tbx"],
                                dm.tb_cap, (k1, k2q),
                            )
                            tomb = jnp.any(blk_hit(tb, (k1, k2q)), axis=-1)
                            hd, hp = hd & ~tomb, hp & ~tomb
                    if run_ed:
                        dblk = probe_block(
                            arrs["dl_eh_off"], arrs["dl_ehx"], dm.e_cap,
                            (k1, k2q),
                        )
                        dhit = blk_hit(dblk, (k1, k2q)) & exists[..., None]
                        dd, dp = gate2_blk("e", dblk, eL, dhit)
                        hd = hd | jnp.any(dd, axis=-1)
                        hp = hp | jnp.any(dp, axis=-1)
                    return hd, hp

                d, p = e_site(bq(q_k2, nd))
                if coll is not None:
                    coll.add("direct", d)
                if meta.has_wc_edges:
                    # wildcard edges only grant direct-object subjects
                    wd, wp = e_site(bq(w_k2, nd))
                    if coll is not None:
                        coll.add("wildcard", wd)
                    d, p = d | wd, p | wp
            elif run_e:
                ecols = (arrs["e_k1"], arrs["e_k2"])
                row = probe_rows(
                    arrs["eh_off"], arrs["eh_rows"], ecols,
                    (k1, bq(q_k2, nd)), meta.e_cap, meta.e_n,
                )
                d, p = gate2("e", row, (row >= 0) & exists)
                if coll is not None:
                    coll.add("direct", d)
                if meta.has_wc_edges:
                    wrow = probe_rows(
                        arrs["eh_off"], arrs["eh_rows"], ecols,
                        (k1, bq(w_k2, nd)), meta.e_cap, meta.e_n,
                    )
                    wd, wp = gate2("e", wrow, (wrow >= 0) & exists)
                    if coll is not None:
                        coll.add("wildcard", wd)
                    d, p = d | wd, p | wp

            # T-index fast path: one probe folds {userset edge × closure}
            use_t = dyn_t if dyn else (
                t_on and slot in meta.t_slots
            )
            if use_t:
                def t_site(k2q):
                    if BS:
                        pr = psite("th_off", "tx", meta.t_cap, (k1, k2q),
                                   mode="until2", need_now=True)
                        if pr is not None:
                            # exists is lane-constant, so ANDing it after
                            # the in-kernel OR-reduce is exact
                            return pr[0] & exists, pr[1] & exists
                        blk, mine = pblock(
                            "th_off", "tx", meta.t_cap, (k1, k2q)
                        )
                        hit = blk_hit(blk, (k1, k2q), mine) & exists[..., None]
                        return (
                            por_m(
                                jnp.any(hit & (blk[..., 2] > now), axis=-1),
                                mine,
                            ),
                            por_m(
                                jnp.any(hit & (blk[..., 3] > now), axis=-1),
                                mine,
                            ),
                        )
                    trow = probe_rows(
                        arrs["th_off"], arrs["th_rows"],
                        (arrs["t_k1"], arrs["t_k2"]), (k1, k2q),
                        meta.t_cap, meta.t_n,
                    )
                    trc = jnp.clip(trow, 0, arrs["t_k1"].shape[0] - 1)
                    thit = (trow >= 0) & exists
                    return (
                        thit & (tk(arrs["t_d"], trc) > now),
                        thit & (tk(arrs["t_p"], trc) > now),
                    )

                td, tp = t_site(bq(q_k2, nd))
                if meta.has_wc_closure:
                    wtd, wtp = t_site(bq(wcl_k, nd))
                    td, tp = td | wtd, tp | wtp
                if dm is not None and dm.t_dirty:
                    # groups with tombstoned userset rows: the base T rows
                    # may cite deleted edges — void them; the forced KU
                    # pass below re-derives the live union exactly
                    dtb = probe_block(
                        arrs["dl_td_off"], arrs["dl_tdx"], dm.td_cap, (k1,)
                    )
                    dirty = jnp.any(blk_hit(dtb, (k1,)), axis=-1)
                    td, tp = td & ~dirty, tp & ~dirty
                if coll is not None:
                    coll.add("t", td)
                d, p = d | td, p | tp
                if meta.has_ovf:
                    # T is incomplete for overflowed closure sources: flag
                    # queries whose (slot, node) has userset rows at all
                    lo2, hi2 = range_of("usr", meta.usr_cap, meta.usr_gn, k1)
                    used = used | por(reduceB(exists & (hi2 > lo2)))

            def ku_fetch(prefix: str, cap: int, fan: int):
                """Range-probe a userset view and fetch its candidate
                block; under sharding the single owning shard's rows
                broadcast to every shard (each then tests the candidates
                against ITS closure/pus buckets).  The delta level's
                tables are replicated, so its ranges/blocks are already
                identical everywhere — no collectives."""
                rep = prefix != "usr"
                lo, hi = (
                    range_of("usr", cap, meta.usr_gn, k1)
                    if not rep
                    else range_probe(
                        "dl_usr_off", "dl_usgx", cap, k1, rep=True
                    )
                )
                over = reduceB(exists & ((hi - lo) > fan))
                if not rep:
                    over = por(over)
                valid = (
                    jnp.arange(fan, dtype=jnp.int32) < (hi - lo)[..., None]
                ) & exists[..., None]
                key = "usx" if not rep else "dl_usx"
                ublk = sblock(key, lo, fan) if not rep else slice_blocks(
                    arrs[key], lo, fan
                )
                if SH and not rep:
                    ublk = vbcast(valid[..., None], ublk)
                    valid = por(valid)
                return ublk, valid, over

            def ku_eval(ublk, valid, tombstoned: bool):
                """Userset-grant evaluation over one level's candidate
                block: per-candidate closure/reflexivity/permission tests
                gated by the row's caveat/expiry columns.  Returns the
                (d, p, used) contributions (any-reduced over candidates)."""
                s = jnp.where(valid, ublk[..., usL["subj"]], -1)
                r = jnp.where(valid, ublk[..., usL["srel"]], -1)
                gk = s * S1c + (r + 1)  # invalid rows (-1, -1) → negative
                if tombstoned:
                    # mask deleted base rows by exact (group, subject) id
                    tb = probe_block(
                        arrs["dl_utb_off"], arrs["dl_utbx"], dm.utb_cap,
                        (k1[..., None], gk),
                    )
                    tomb = jnp.any(
                        blk_hit(tb, (k1[..., None], gk)), axis=-1
                    )
                    valid = valid & ~tomb
                    gk = jnp.where(valid, gk, -1)
                nd2 = nd + 1
                in_d, in_p = cl_probe(bq(q_k2, nd2), gk)
                if meta.has_wc_closure:
                    win_d, win_p = cl_probe(bq(wcl_k, nd2), gk)
                    in_d, in_p = in_d | win_d, in_p | win_p
                refl = (gk == bq(q_k2, nd2)) & (bq(q_k2, nd2) >= 0)
                if plan.has_permission_usersets:
                    permf = (
                        (jnp.where(valid, ublk[..., usL["perm"]], 0) != 0)
                        if meta.us_hasperm
                        else jnp.zeros(valid.shape, bool)
                    )
                    pa = psite("push_off", "pusx", meta.pus_cap, (gk,),
                               mode="any")
                    if pa is not None:
                        in_pus = pa
                    else:
                        pblk, pmine = pblock(
                            "push_off", "pusx", meta.pus_cap, (gk,)
                        )
                        in_pus = por_m(
                            jnp.any(blk_hit(pblk, (gk,), pmine), axis=-1),
                            pmine,
                        )
                    in_d = (in_d | refl) & ~permf
                    in_p = in_p | refl | in_pus | permf
                else:
                    in_d = in_d | refl
                    in_p = in_p | refl
                ugd, ugp = gate2_blk("us", ublk, usL, valid)
                return (
                    jnp.any(ugd & in_d, axis=-1),
                    jnp.any(ugp & in_p, axis=-1),
                    reduceB(valid),
                )

            # KU probe path: ineligible slots; the dynamic root leaf on a
            # mixed schema (eligible slots repeat the T answer, sound
            # under OR); or a delta level with tombstoned userset rows
            # (the forced pass replaces voided T answers)
            run_ku = (
                (not use_t)
                or (dyn and not t_cover)
                or (dm is not None and dm.t_dirty)
            )
            KU_site = min(KU, dyn_us_fan if dyn else us_fans.get(slot, 0))
            if run_ku and KU_site > 0 and BS:
                ublk, valid, over = ku_fetch("usr", meta.usr_cap, KU_site)
                ovf = ovf | over
                kd, kp, ku_used = ku_eval(
                    ublk, valid,
                    tombstoned=dm is not None and dm.has_ustomb,
                )
                if coll is not None:
                    coll.add("us", kd)
                d, p, used = d | kd, p | kp, used | ku_used
            elif run_ku and KU_site > 0:
                # scattered (non-blockslice) layout: no delta level exists
                lo, hi = range_of("usr", meta.usr_cap, meta.usr_gn, k1)
                ovf = ovf | reduceB(exists & ((hi - lo) > KU_site))
                valid = (
                    jnp.arange(KU_site, dtype=jnp.int32) < (hi - lo)[..., None]
                ) & exists[..., None]
                used = used | reduceB(valid)
                idx = lo[..., None] + jnp.arange(KU_site, dtype=jnp.int32)
                idxc = jnp.clip(idx, 0, max(meta.us_rows - 1, 0))
                s = tk(arrs["us_subj"], idxc)
                r = tk(arrs["us_srel_d"], idxc)
                gk = s * S1c + (r + 1)  # invalid rows (-1, -1) → negative
                nd2 = nd + 1
                in_d, in_p = cl_probe(bq(q_k2, nd2), gk)
                if meta.has_wc_closure:
                    win_d, win_p = cl_probe(bq(wcl_k, nd2), gk)
                    in_d, in_p = in_d | win_d, in_p | win_p
                refl = (gk == bq(q_k2, nd2)) & (bq(q_k2, nd2) >= 0)
                if plan.has_permission_usersets:
                    permf = tk(arrs["us_perm"], idxc) != 0
                    in_pus = probe_rows(
                        arrs["push_off"], arrs["push_rows"],
                        (arrs["pus_k"],), (gk,),
                        meta.pus_cap, meta.pus_n,
                    ) >= 0
                    in_d = (in_d | refl) & ~permf
                    in_p = in_p | refl | in_pus | permf
                else:
                    in_d = in_d | refl
                    in_p = in_p | refl
                ugd, ugp = gate2("us", idxc, valid)
                kd = jnp.any(ugd & in_d, axis=-1)
                if coll is not None:
                    coll.add("us", kd)
                d = d | kd
                p = p | jnp.any(ugp & in_p, axis=-1)

            # delta-level userset grants (adds with subject relations)
            run_kud = (
                dm is not None
                and dm.has_us
                and (bool(dm.us_slots) if dyn else (slot in dm.us_slots))
            )
            if run_kud:
                ublk, valid, over = ku_fetch("dl_usr", dm.us_cap, dm.us_fan)
                ovf = ovf | over
                kd, kp, ku_used = ku_eval(ublk, valid, tombstoned=False)
                if coll is not None:
                    coll.add("us", kd)
                d, p, used = d | kd, p | kp, used | ku_used
            return d, p, ovf, used

        memo: Dict = {}
        pins: List = []  # keep node arrays alive so id() keys stay unique
        # arrow-recursion cut: beyond the DATA's longest arrow chain there
        # are provably no children, so deeper unrolls are dead code — but
        # a delta level with arrow adds may deepen chains, so it reverts
        # to the schema recursion budget
        ar_bound = meta.ar_data_depth
        if dm is not None and dm.has_ar:
            ar_bound = -1

        def eval_progs(slot: int, nodes, stack: Tuple, types, ar_hops: int,
                       coll=None) -> Tuple:
            """The permission programs of ``slot`` at ``nodes`` (no leaf).
            ``coll`` (witness collection) threads into each program's
            expression GATED by that program's node-type mask, so a leaf
            mask from another type's program can never claim a branch
            for a query it cannot grant."""
            zn = jnp.zeros(nodes.shape, bool)
            d, p, ovf, used = zn, zn, zB, zB
            progs = [
                (tname, tid, expr)
                for (tname, tid, expr) in perm_programs.get(slot, ())
                if tname in types and (tname, slot) not in folded_pairs
            ]
            if progs:
                ntype = jnp.where(
                nodes >= 0,
                tk(
                    node_type, jnp.clip(nodes, 0, node_type.shape[0] - 1)
                ).astype(jnp.int32),
                -1,
            )
            width = 1
            for dim in nodes.shape[1:]:
                width *= dim
            for (tname, tid, expr) in progs:
                mask = ntype == tid_map[tid]
                rc = rc_map.get((tname, slot))
                if rc is not None and width * (
                    rc_geom[rc[0]][1] + 1
                ) <= cfg.flat_max_width:
                    # flattened hierarchy: ONE level over the ancestor
                    # closure instead of recursive unrolling — lane 0 is
                    # the node itself (reflexive), the rest are strict
                    # ancestors gated by the path's semiring values
                    ed, ep, eo, eu = rc_eval(
                        rc[0], rc[1], nodes, stack + ((tname, slot),),
                        frozenset((tname,)), ar_hops,
                    )
                    d = d | (mask & ed)
                    p = p | (mask & ep)
                    ovf, used = ovf | eo, used | eu
                    continue
                if (tname, slot) in cyclic and stack.count(
                    (tname, slot)
                ) >= cfg.flat_recursion:
                    # recursion budget exhausted: deeper evaluation is
                    # unknown → possible-only, the host oracle finishes it
                    p = p | (mask & (nodes >= 0))
                    continue
                ed, ep, eo, eu = eval_expr(
                    expr, nodes, stack + ((tname, slot),),
                    frozenset((tname,)), ar_hops,
                    None if coll is None else coll.masked(mask),
                )
                d = d | (mask & ed)
                p = p | (mask & ep)
                ovf, used = ovf | eo, used | eu
            return d, p, ovf, used

        def rc_eval(ts_slot: int, rest: ExprIR, nodes, stack, types,
                    ar_hops: int):
            """perm(n) = ∃ a ∈ {n} ∪ ancestors(n): rest(a), with the
            ancestor paths' two-plane admissibility from the flattened
            arrow closure (rc{ts} tables)."""
            cap, fan = rc_geom[ts_slot]
            exists = nodes >= 0
            nq = jnp.where(exists, nodes, -1)
            # rc tables follow the base layout: bucket-sharded under SH
            # (owner-local ranges, broadcast below), plain otherwise
            lo, hi = range_probe(
                f"rc{ts_slot}_off", f"rc{ts_slot}gx", cap, nq,
                rows_key=f"rc{ts_slot}x",
            )
            valid = (
                jnp.arange(fan, dtype=jnp.int32) < (hi - lo)[..., None]
            ) & exists[..., None]
            blk = sblock(f"rc{ts_slot}x", lo, fan)
            if SH:
                blk = vbcast(valid[..., None], blk)
                valid = por(valid)
            anc = jnp.where(valid, blk[..., 0], -1)
            path_d = valid & (blk[..., 1] > now)
            path_p = valid & (blk[..., 2] > now)
            # reflexive lane 0: the node itself, path trivially live
            lattice = jnp.concatenate([nodes[..., None], anc], axis=-1)
            path_d = jnp.concatenate([exists[..., None], path_d], axis=-1)
            path_p = jnp.concatenate([exists[..., None], path_p], axis=-1)
            rd, rp, ro, ru = eval_expr(rest, lattice, stack, types, ar_hops)
            return (
                jnp.any(rd & path_d, axis=-1),
                jnp.any(rp & path_p, axis=-1),
                ro, ru,
            )

        def eval_slot(slot: int, nodes, stack: Tuple, types, ar_hops: int,
                      coll=None) -> Tuple:
            cyc_sig = tuple(
                sorted((pr, stack.count(pr)) for pr in set(stack) if pr in cyclic)
            )
            key = (
                slot, id(nodes), types, cyc_sig,
                ar_hops if ar_bound >= 0 else 0,
            )
            got = memo.get(key)
            if got is not None:
                return got
            zn = jnp.zeros(nodes.shape, bool)
            d, p, ovf, used = zn, zn, zB, zB
            if slot in rel_slots:
                d, p, ovf, used = leaf(slot, nodes, coll)
            if slot in pf_slots:
                # folded permission reached as an arrow target / ref from
                # an unfolded program: its base answer is the probe pair
                fd, fp = pf_probe(slot, nodes, coll)
                d, p = d | fd, p | fp
            pd, pp, po, pu = eval_progs(slot, nodes, stack, types, ar_hops,
                                        coll)
            d, p = d | pd, p | pp
            ovf, used = ovf | po, used | pu
            pins.append(nodes)
            memo[key] = (d, p, ovf, used)
            return memo[key]

        def eval_expr(ir: ExprIR, nodes, stack: Tuple, types, ar_hops: int,
                      coll=None) -> Tuple:
            tag = ir[0]
            if tag == "ref":
                return eval_slot(ir[1], nodes, stack, types, ar_hops, coll)
            if tag == "nil":
                z = jnp.zeros(nodes.shape, bool)
                return z, z, zB, zB
            if tag == "arrow":
                if 0 <= ar_bound <= ar_hops:
                    # deeper than any real chain in the data: no children
                    z = jnp.zeros(nodes.shape, bool)
                    return z, z, zB, zB
                ts_slot = plan.ts_slots[ir[1]]
                child_types = arrow_child_types(ts_slot, types)
                data_fan = dict(meta.ar_fanout_by_slot).get(ts_slot, 0)
                d_run = dm is not None and dm.has_ar and ts_slot in dm.ar_slots
                Ksd = dm.ar_fan if d_run else 0
                if not child_types or (data_fan == 0 and Ksd == 0):
                    # no reachable types / no edges of this tupleset at all
                    z = jnp.zeros(nodes.shape, bool)
                    return z, z, zB, zB
                Ks = min(K, data_fan)
                exists = nodes >= 0
                ak = k1c(ts_slot) * Nc + jnp.where(exists, nodes, 0)
                if Ks:
                    lo, hi = range_of("arr", meta.arr_cap, meta.arr_gn, ak)
                else:
                    lo = hi = jnp.zeros(nodes.shape, jnp.int32)
                if Ksd:
                    lod, hid = range_probe(
                        "dl_arr_off", "dl_argx", dm.ar_cap, ak,
                        rep=True,
                    )
                else:
                    lod = hid = jnp.zeros(nodes.shape, jnp.int32)
                width = 1
                for dim in nodes.shape[1:]:
                    width *= dim
                if width * (Ks + Ksd) > cfg.flat_max_width:
                    # lattice budget spent: don't expand — probe child
                    # existence only; real deeper grants surface as
                    # possible and resolve on the host oracle
                    return (
                        jnp.zeros(nodes.shape, bool),
                        por((hi > lo) | (hid > lod)) & exists,
                        zB, zB,
                    )
                ovf = por(reduceB(exists & ((hi - lo) > Ks)))
                valid = (
                    jnp.arange(max(Ks, 1), dtype=jnp.int32) < (hi - lo)[..., None]
                ) & exists[..., None]
                if Ks == 0:
                    children = jnp.full(nodes.shape + (0,), -1, jnp.int32)
                    gd = gp = jnp.zeros(nodes.shape + (0,), bool)
                elif BS:
                    ablk = sblock("arx", lo, Ks)
                    if SH:
                        # the owning shard's rows broadcast; every shard
                        # then recurses on the SAME children lattice
                        ablk = vbcast(valid[..., None], ablk)
                        valid = por(valid)
                    children = jnp.where(valid, ablk[..., arL["child"]], -1)
                    gd, gp = gate2_blk("ar", ablk, arL, valid)
                    if dm is not None and dm.has_artomb:
                        # mask deleted base rows by (group, child) identity
                        tb = probe_block(
                            arrs["dl_atb_off"], arrs["dl_atbx"], dm.atb_cap,
                            (ak[..., None], children),
                        )
                        tomb = jnp.any(
                            blk_hit(tb, (ak[..., None], children)), axis=-1
                        )
                        children = jnp.where(tomb, -1, children)
                        gd, gp = gd & ~tomb, gp & ~tomb
                else:
                    idx = lo[..., None] + jnp.arange(Ks, dtype=jnp.int32)
                    idxc = jnp.clip(idx, 0, max(meta.ar_rows - 1, 0))
                    children = jnp.where(valid, tk(arrs["ar_child"], idxc), -1)
                    gd, gp = gate2("ar", idxc, valid)
                if Ksd:
                    # delta-level arrow rows: extra candidates on the axis
                    ovf = ovf | reduceB(exists & ((hid - lod) > Ksd))
                    dvalid = (
                        jnp.arange(Ksd, dtype=jnp.int32)
                        < (hid - lod)[..., None]
                    ) & exists[..., None]
                    dblk = slice_blocks(arrs["dl_arx"], lod, Ksd)
                    dchildren = jnp.where(dvalid, dblk[..., arL["child"]], -1)
                    dgd, dgp = gate2_blk("ar", dblk, arL, dvalid)
                    children = jnp.concatenate([children, dchildren], axis=-1)
                    gd = jnp.concatenate([gd, dgd], axis=-1)
                    gp = jnp.concatenate([gp, dgp], axis=-1)
                cd, cp, co, cu = eval_slot(
                    ir[2], children, stack, child_types, ar_hops + 1
                )
                return (
                    jnp.any(cd & gd, axis=-1),
                    jnp.any(cp & gp, axis=-1),
                    ovf | co,
                    cu,
                )
            if tag == "union":
                z = jnp.zeros(nodes.shape, bool)
                d, p, ovf, used = z, z, zB, zB
                for c in ir[1]:
                    cd, cp, co, cu = eval_expr(c, nodes, stack, types,
                                               ar_hops, coll)
                    d, p = d | cd, p | cp
                    ovf, used = ovf | co, used | cu
                return d, p, ovf, used
            if tag == "inter":
                # children collect into a sub-store gated by the whole
                # intersection's definite output: a branch hit inside a
                # FAILED intersection is not on the allowed path and must
                # not claim the witness
                o = jnp.ones(nodes.shape, bool)
                d, p, ovf, used = o, o, zB, zB
                sub = None if coll is None else _WitColl({})
                for c in ir[1]:
                    cd, cp, co, cu = eval_expr(c, nodes, stack, types,
                                               ar_hops, sub)
                    d, p = d & cd, p & cp
                    ovf, used = ovf | co, used | cu
                if sub is not None:
                    for wk, wm in sub.store.items():
                        coll.add(wk, wm & d)
                return d, p, ovf, used
            if tag == "excl":
                # the subtracted operand's grants DENY — never collected;
                # the base operand's only count where the exclusion as a
                # whole definitely grants
                sub = None if coll is None else _WitColl({})
                bd, bp, bo, bu = eval_expr(ir[1], nodes, stack, types,
                                           ar_hops, sub)
                sd, sp, so, su = eval_expr(ir[2], nodes, stack, types,
                                           ar_hops, None)
                rd = bd & ~sp
                if sub is not None:
                    for wk, wm in sub.store.items():
                        coll.add(wk, wm & rd)
                return rd, bp & ~sd, bo | so, bu | su
            raise TypeError(f"bad expression IR {ir!r}")

        # subject-closure overflow: the flattened table is incomplete for
        # these sources, so any query that touched a userset probe falls
        # back to the host oracle
        if not meta.has_ovf:
            q_cl_ovf = zB
        else:
            def ovf_probe(k):
                if BS:
                    oa = psite("ovfh_off", "ovfx", meta.ovf_cap, (k,),
                               mode="any")
                    if oa is not None:
                        return oa
                    oblk, omine = pblock(
                        "ovfh_off", "ovfx", meta.ovf_cap, (k,)
                    )
                    return por_m(
                        jnp.any(blk_hit(oblk, (k,), omine), axis=-1), omine
                    )
                return probe_rows(
                    arrs["ovfh_off"], arrs["ovfh_rows"],
                    (arrs["ovf_k"],), (k,), meta.ovf_cap, meta.ovf_n,
                ) >= 0

            q_cl_ovf = ovf_probe(q_k2) | ovf_probe(wcl_k)

        valid_q = (q_res >= 0) & (q_perm >= 0)
        # witness collection (armed kernels only): the ROOT-level sites
        # and the root resource's program expressions drop their definite
        # masks in here; coll=None compiles every capture to nothing, so
        # the disarmed program is byte-identical
        coll = _WitColl({}) if witness else None
        # one dynamic-slot leaf site answers every query whose permission
        # is (also) a stored relation; per-slot work below is programs only
        if meta.e_slots or meta.us_fanout_by_slot:
            d_out, p_out, lovf, lused = leaf(None, q_res, coll)
            ovf_out = lovf | (q_cl_ovf & lused)
        else:
            d_out, p_out, ovf_out = zB, zB, zB
        if fold_on and any(s in pf_slots for s in slots):
            # one dynamic pf site answers every folded permission in the
            # dispatch — for a fully folded slot set this IS the kernel
            fd, fp = pf_probe(None, q_res, coll)
            d_out, p_out = d_out | fd, p_out | fp
        for slot in slots:
            if not perm_programs.get(slot):
                continue
            sel = q_perm == slot
            sd, sp, so, su = eval_progs(
                int(slot), q_res, (), all_types, 0,
                None if coll is None else coll.masked(sel),
            )
            d_out = d_out | (sel & sd)
            p_out = p_out | (sel & sp)
            ovf_out = ovf_out | (sel & (so | (q_cl_ovf & su)))
            if coll is not None:
                coll.add("rewrite", sel & sd)

        d_out = (d_out & valid_q) | q_self
        p_out = (p_out & valid_q) | q_self
        if coll is None:
            return d_out, p_out, ovf_out & ~q_self
        # witness plane: lowest-priority branch first, each later select
        # overwrites — so the cheapest/leaf-most explanation wins (self >
        # direct > wildcard > T > fold > userset > rewrite).  Nonzero
        # only for device-DEFINITE allowed verdicts: conditional/overflow
        # rows resolve on the host oracle, which needs no seed
        wit = jnp.zeros(q_res.shape, jnp.int32)
        for wkey, wcode in (
            ("rewrite", WIT_REWRITE | (1 << WIT_LEVEL_SHIFT)),
            ("us", WIT_USERSET),
            ("fold", WIT_FOLD),
            ("t", WIT_TPROBE),
            ("wildcard", WIT_WILDCARD),
            ("direct", WIT_DIRECT),
        ):
            wm = coll.store.get(wkey)
            if wm is not None:
                wit = jnp.where(wm & valid_q, jnp.int32(wcode), wit)
        wit = jnp.where(q_self, jnp.int32(WIT_SELF), wit)
        wit = jnp.where(d_out, wit, 0)
        return d_out, p_out, ovf_out & ~q_self, wit

    return jax.jit(fn) if jit else fn

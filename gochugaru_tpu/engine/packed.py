"""Bit-packed device tables: the HBM-lean stacked layout.

The aligned/interleaved tables of engine/flat.py spend one int32 lane
per logical column at fixed row width, and their bucket-offset arrays
grow up to 8x the entry count chasing a probe cap of 4 — at the 1B-edge
deployment this (not host RSS) is the binding constraint: ~82 GB of
table bytes per device at PR-7 widths (BENCHMARKS.md "Partitioned
serving").  TpuGraphs (arXiv:2308.13490) documents layout/packing
dominating TPU graph-workload cost; this module is that observation
applied to the probe tables:

- **bit-packed columns** — a dense (slot, node) key needs
  ⌈log2(slots·N)⌉ bits, a caveat id ⌈log2(ncav)⌉, a userset fan length
  a handful; multiple logical columns share uint16 lanes, and the
  kernel decodes with compiled shift/mask ops fused into the existing
  block gathers (the bytes cross HBM packed; registers are free);
- **dictionary columns** — the closure/T until-values are almost always
  one of {NEVER, NO_EXP, pad}: the lane stores a ≤4-bit dictionary
  index and the kernel rematerializes the int32 through a trace-time
  constant table (the round-3 "alllive" elision, generalized from
  all-or-nothing to any small value set);
- **delta-run ranges** — the range group tables store (key, lo, hi)
  with hi a full-width row offset; packed they store (key, lo,
  hi - lo), and the run LENGTH fits the view's fan bits (the
  sorted-runs structure the host build already derives);
- **offset residuals** — bucket offsets are monotone, so ``off[i]``
  splits into a coarse int32 anchor every 2^A buckets plus a uint16
  residual; two tiny gathers replace one over an array 2x the size.

Pack specs are HASHABLE TUPLES riding FlatMeta (they are part of the
compiled-kernel cache key), and crucially they derive from table
GEOMETRY + globally-replicated domains (radices, fan caps, caveat/ctx
counts, until-value dictionaries) — never from scanning a built shard —
so every process of a multihost partitioned build agrees on the packed
bytes before any table exists (the agreement-before-build discipline of
engine/partition.py), and Watch delta chains keep one compiled kernel
(domains are radix-stable under deltas).

Field encoding: ``stored = value - base`` (or a dictionary index) in
``bits`` bits at ``off_bit`` in the row's uint16 lane stream; a field
never spans more than two lanes (decode stays in int32).  ``bits == 0``
is a constant column: nothing is stored, decode broadcasts ``base``.
Decode is exact for every value the spec admits — parity with the
unpacked layout is bit-for-bit by construction, and the packers VERIFY
range membership (a value outside its declared domain raises rather
than aliasing).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: rows per packing chunk: the pack pass walks the source table in
#: bounded windows, so converting a 100M-row table never materializes a
#: second full-width copy (tests/test_packed.py arms alloc_guard on it)
CHUNK = 1 << 20

#: Field = (bits, base, delta_of, dict_id, off_bit)
#:   bits      storage width (0 = constant column, value == base)
#:   base      subtracted before store / added after load (dict: unused)
#:   delta_of  column index whose DECODED value adds back in (-1 = none)
#:   dict_id   index into the spec's dictionaries (-1 = plain range)
#:   off_bit   starting bit offset in the row's uint16 lane stream
#: Spec = (w, lanes, fields, dicts) with dicts a tuple of sorted value
#: tuples — everything ints, hashable, FlatMeta-safe.
Field = Tuple[int, int, int, int, int]
Spec = Tuple[int, int, Tuple[Field, ...], Tuple[Tuple[int, ...], ...]]


class PackError(ValueError):
    """A value fell outside its declared pack domain (builder bug or a
    delta that outgrew a pinned spec — callers bail to unpacked)."""


# ---------------------------------------------------------------------------
# alloc guard (tests): bound every temporary the packers allocate
# ---------------------------------------------------------------------------

_ALLOC_CAP = [None]  # type: List[Optional[int]]


class alloc_guard:
    """Context manager bounding per-temporary bytes inside this module.
    tests/test_packed.py arms it below the full-width table size and
    runs a packed prepare: any single full-size intermediate trips it."""

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = int(max_bytes)

    def __enter__(self):
        _ALLOC_CAP[0] = self.max_bytes
        return self

    def __exit__(self, *exc):
        _ALLOC_CAP[0] = None
        return False


def _tmp(shape, dtype) -> np.ndarray:
    """Temporary buffer, checked against the armed alloc guard."""
    a = np.empty(shape, dtype)
    cap = _ALLOC_CAP[0]
    if cap is not None and a.nbytes > cap:
        raise AssertionError(
            f"packed.py temporary of {a.nbytes} bytes exceeds the armed"
            f" alloc guard ({cap}): full-width intermediate materialized"
        )
    return a


# ---------------------------------------------------------------------------
# spec construction
# ---------------------------------------------------------------------------


def bits_for(lo: int, hi: int) -> int:
    """Storage bits for the inclusive value range [lo, hi]."""
    span = int(hi) - int(lo)
    if span <= 0:
        return 0
    return max(1, span.bit_length())


def col_range(lo: int, hi: int) -> Tuple[str, int, int]:
    """Column descriptor: plain range (pad/-1 must be inside it)."""
    return ("range", int(lo), int(hi))


def col_const(v: int) -> Tuple[str, int, int]:
    return ("range", int(v), int(v))


def col_dict(values) -> Tuple:
    """Column descriptor: small-set dictionary (sorted, deduped here)."""
    vs = tuple(sorted({int(v) for v in values}))
    return ("dict", vs)


def col_delta(lo: int, hi: int, of: int) -> Tuple[str, int, int, int]:
    """Column stored as (value - decoded column ``of``) in [lo, hi]."""
    return ("delta", int(lo), int(hi), int(of))


def make_spec(descs: Sequence[Tuple]) -> Optional[Spec]:
    """Field placement over uint16 lanes; None when packing does not
    shrink the row (lanes*2 >= w*4) or a field cannot be represented."""
    w = len(descs)
    placed: List[Tuple[int, int, int, int, int]] = []
    dicts: List[Tuple[int, ...]] = []
    off = 0
    for d in descs:
        kind = d[0]
        if kind == "dict":
            vs = d[1]
            if len(vs) > 256:
                return None  # not a small set: give up on the table
            bits, base, delta_of, dict_id = (
                bits_for(0, len(vs) - 1), 0, -1, len(dicts)
            )
            dicts.append(vs)
        elif kind == "delta":
            _, lo, hi, of = d
            bits, base, delta_of, dict_id = bits_for(lo, hi), lo, of, -1
        else:
            _, lo, hi = d
            bits, base, delta_of, dict_id = bits_for(lo, hi), lo, -1, -1
        if bits > 32:
            return None
        # a field may straddle at most ONE lane boundary (decode
        # reassembles in int32); bump to the next lane otherwise
        if bits > 16 and (off & 15) + bits > 32:
            off = (off + 15) & ~15
        placed.append((bits, int(base), int(delta_of), int(dict_id), off))
        off += bits
    lanes = max((off + 15) >> 4, 1)
    if lanes * 2 >= w * 4:
        return None  # no byte win: keep the int32 layout
    return (w, lanes, tuple(placed), tuple(dicts))


def spec_lanes(spec: Spec) -> int:
    return spec[1]


def spec_nbytes(spec: Spec, rows: int) -> int:
    return rows * spec[1] * 2


# ---------------------------------------------------------------------------
# host-side pack (chunked, alloc-guarded)
# ---------------------------------------------------------------------------


def _encode_field(v: np.ndarray, bits, base, delta_of, dict_id, dicts,
                  decoded_prev) -> np.ndarray:
    """int32 column chunk → unsigned field values (int64 for safety)."""
    if delta_of >= 0:
        v = v.astype(np.int64) - decoded_prev[delta_of].astype(np.int64)
    else:
        v = v.astype(np.int64)
    if dict_id >= 0:
        dv = np.asarray(dicts[dict_id], np.int64)
        idx = np.searchsorted(dv, v)
        idxc = np.clip(idx, 0, len(dv) - 1)
        if not bool((dv[idxc] == v).all()):
            raise PackError("value outside dictionary domain")
        return idxc.astype(np.int64)
    u = v - base
    if bits == 0:
        if not bool((u == 0).all()):
            raise PackError("non-constant value in constant column")
        return u
    if bool((u < 0).any()) or bool((u >> bits).any()):
        raise PackError("value outside declared pack range")
    return u


def pack_rows(tbl: np.ndarray, spec: Spec) -> np.ndarray:
    """Pack an int32 [n, w] table into uint16 [n, lanes], in CHUNK-row
    windows (every temporary is chunk-sized; see alloc_guard)."""
    w, lanes, fields, dicts = spec
    n = int(tbl.shape[0])
    assert tbl.shape[1] == w, (tbl.shape, w)
    out = np.zeros((n, lanes), np.uint16)
    for at in range(0, max(n, 1), CHUNK):
        hi = min(at + CHUNK, n)
        if hi <= at:
            break
        chunk = tbl[at:hi]
        decoded = [chunk[:, j] for j in range(w)]
        acc = _tmp((hi - at, lanes), np.uint32)
        acc[:] = 0
        for j, (bits, base, delta_of, dict_id, off_bit) in enumerate(fields):
            if bits == 0:
                _encode_field(  # validates constancy
                    decoded[j], bits, base, delta_of, dict_id, dicts, decoded
                )
                continue
            u = _encode_field(
                decoded[j], bits, base, delta_of, dict_id, dicts, decoded
            )
            lane, sh = off_bit >> 4, off_bit & 15
            acc[:, lane] |= ((u << sh) & 0xFFFF).astype(np.uint32)
            if sh + bits > 16:
                acc[:, lane + 1] |= ((u >> (16 - sh)) & 0xFFFF).astype(
                    np.uint32
                )
        out[at:hi] = acc.astype(np.uint16)
    return out


def unpack_rows(packed: np.ndarray, spec: Spec) -> np.ndarray:
    """Host-side inverse of pack_rows (parity tests; small tables)."""
    w, lanes, fields, dicts = spec
    n = int(packed.shape[0])
    out = np.empty((n, w), np.int32)
    l32 = packed.astype(np.int64)
    for j, (bits, base, delta_of, dict_id, off_bit) in enumerate(fields):
        if bits == 0:
            out[:, j] = base
        else:
            lane, sh = off_bit >> 4, off_bit & 15
            v = l32[:, lane] >> sh
            if sh + bits > 16:
                v = v | (l32[:, lane + 1] << (16 - sh))
            if sh + bits > 32:
                v = v | (l32[:, lane + 2] << (32 - sh))  # pragma: no cover
            v = v & ((1 << bits) - 1)
            if dict_id >= 0:
                out[:, j] = np.asarray(dicts[dict_id], np.int64)[v].astype(
                    np.int32
                )
            else:
                out[:, j] = (v + base).astype(np.int32)
        if delta_of >= 0:
            out[:, j] = out[:, j] + out[:, delta_of]
    return out


# ---------------------------------------------------------------------------
# offset residuals (single-chip layouts; sharded offs stay int32)
# ---------------------------------------------------------------------------

#: anchor block shift: one int32 anchor per 2^A buckets.  Larger A →
#: smaller anchors, wider residual range; 11 keeps the anchor array at
#: 1/2048 of the offsets while typical loads (≤4 rows/bucket) stay far
#: inside uint16
OFF_ANCHOR_SHIFT = 11


def pack_off(off: np.ndarray, shift: int = OFF_ANCHOR_SHIFT):
    """(residual uint16[len], anchor int32[ceil(len/2^A)]) with
    ``off[i] == anchor[i >> A] + residual[i]`` — or None when some
    anchor block spans ≥ 2^16 rows (keep int32).  The anchor is the
    block MINIMUM, so residuals are non-negative by construction."""
    n = int(off.shape[0])
    blocks = (n + (1 << shift) - 1) >> shift
    o = off.astype(np.int64)
    pad = blocks * (1 << shift) - n
    if pad:
        o = np.concatenate([o, np.full(pad, o[-1] if n else 0, np.int64)])
    ob = o.reshape(blocks, 1 << shift)
    anchor = ob.min(axis=1)
    res = ob - anchor[:, None]
    if int(res.max(initial=0)) >= (1 << 16):
        return None
    return (
        res.reshape(-1)[:n].astype(np.uint16),
        anchor.astype(np.int32),
    )


# ---------------------------------------------------------------------------
# device-side decode (traced; fused into the probe gathers)
# ---------------------------------------------------------------------------


def decode_block(blk, spec: Spec):
    """uint16[..., lanes] probe block → int32[..., w] logical columns.
    Pure elementwise shift/mask (plus a tiny constant-table gather for
    dictionary columns) — XLA fuses it into the consuming compares, so
    only the packed bytes ever cross HBM."""
    import jax.numpy as jnp

    w, lanes, fields, dicts = spec
    l32 = blk.astype(jnp.int32)
    cols: List = [None] * w
    for j, (bits, base, delta_of, dict_id, off_bit) in enumerate(fields):
        if bits == 0:
            col = jnp.full(blk.shape[:-1], base, jnp.int32)
        else:
            lane, sh = off_bit >> 4, off_bit & 15
            v = l32[..., lane] >> sh if sh else l32[..., lane]
            if sh + bits > 16:
                v = v | (l32[..., lane + 1] << (16 - sh))
            if bits < 32:
                v = v & jnp.int32((1 << bits) - 1)
            if dict_id >= 0:
                col = jnp.asarray(dicts[dict_id], jnp.int32)[v]
            else:
                col = v + jnp.int32(base) if base else v
        if delta_of >= 0:
            col = col + cols[delta_of]
        cols[j] = col
    return jnp.stack(cols, axis=-1)


def narrow_nodes(a: np.ndarray, num_types: int) -> np.ndarray:
    """node_type column in the narrowest dtype its domain allows
    (values in [-1, num_types); the kernel widens after the gather)."""
    if num_types < 127:
        return a.astype(np.int8)
    if num_types < (1 << 15) - 1:
        return a.astype(np.int16)
    return a

"""Partition-first table builds: O(E/M) host scratch per bucket shard.

The stacked (bucket-sharded) layout of engine/flat.py used to be built
build-full-then-stack: every hash/range table was first constructed over
the FULL key columns (global ``build_hash`` → O(E) rows permutation +
offsets), then ``_stack_point``/``_stack_range`` re-materialized the
whole thing again as the [M, R_pad, w] stacked matrix — so a multihost
process paid O(E) host RSS several times over for tables of which its
devices keep 1/M (55.4 GB at 100M edges; ROADMAP "Host-sharded table
build").  This module inverts the order, the partition-then-build-local
discipline of distributed sparse-graph engines (Graphulo,
arXiv:1609.08642; GraphBLAS-backed stores, arXiv:1905.01294):

1. **geometry** — the final table's pow2 bucket count, probe cap, and
   stacked pads are computed from the key HASHES alone (``point_geom`` /
   ``range_geom`` replicate ``build_hash``'s sizing loop bit-for-bit),
   so every process agrees on shapes without building anything;
2. **partition** — each row's owning shard is the high bits of its
   bucket index (shard s owns buckets [s·bpd, (s+1)·bpd)), a stable
   counting sort by owner (``shard_order``);
3. **build local** — each shard's slice of the stacked table is built
   independently from its own rows: the shard-local bucket index equals
   the global bucket's LOW bits (bpd is pow2), and a stable local
   counting sort of the shard's rows by local bucket reproduces the
   global permutation restricted to the shard — so the output is
   BITWISE-identical to the build-full-then-stack path
   (tests/test_partition.py, tests/test_prepare_parity.py), while the
   peak scratch per shard is O(E/M) instead of O(E).

Equal full keys always hash to the same bucket, hence the same shard —
which is what makes per-shard stable sorts reproduce global tie-breaks
exactly, and what lets a multihost process materialize ONLY the feed
rows of shards its devices own (``FeedPartition``, wired through
parallel/multihost.py) while staying bitwise-compatible with every
other process's view of the geometry.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .hash import _ceil_pow2, mix32


def _hash_cols(cols: Sequence[np.ndarray]) -> np.ndarray:
    """mix32 over int32 key columns — native parallel pass when available,
    numpy otherwise (bit-identical by the native parity contract)."""
    from ..native.sort import mix32_native

    cc = [np.ascontiguousarray(c, np.int32) for c in cols]
    h = mix32_native(cc)
    if h is None:
        h = mix32(cc, np)
    return h


# ---------------------------------------------------------------------------
# geometry: sizes/caps/pads from hashes alone (no table built)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PointGeom:
    """Global geometry of one bucketed point table, as ``build_hash`` +
    ``_stack_point`` would decide it — reproduced from the key hashes so
    shard-local builds (and every process of a multihost deployment)
    agree on shapes before any table exists."""

    size: int  # final pow2 bucket count
    cap: int  # max bucket occupancy (probe unroll count)
    n: int  # entries
    M: int  # shard count
    R_pad: int  # stacked rows per shard (pow2)

    @property
    def bpd(self) -> int:
        return self.size // self.M


def point_geom(
    h_full: np.ndarray,
    M: int,
    *,
    target_cap: int = 4,
    min_size: int = 8,
    max_factor: int = 8,
    lean: bool = False,
    pad: int = 64,
    return_order: bool = False,
):
    """Replicates ``build_hash``'s sizing loop (including the ≥16M-row
    growth freeze) and ``_stack_point``'s R_pad from ``h_full`` alone.
    One transient O(size) histogram; no rows permutation, no offsets —
    EXCEPT the frozen branch, whose per-shard cap pass runs the owner
    partition anyway: ``return_order=True`` returns ``(geom, order_
    starts)`` so callers about to ``stack_point`` the same hashes reuse
    that (order, starts) instead of re-running the O(E) counting sort
    (``order_starts`` is None whenever the histogram branch ran)."""
    n = int(h_full.shape[0])
    order_starts: Optional[Tuple[np.ndarray, np.ndarray]] = None
    if n == 0:
        geom = PointGeom(
            size=min_size, cap=1, n=0, M=M,
            R_pad=_ceil_pow2(max(pad, 1)),
        )
        return (geom, None) if return_order else geom
    size = _ceil_pow2(n if lean else 2 * n, min_size)
    if n > (1 << 24):
        # growth frozen (build_hash's own rule): the final size is known
        # up front, so cap comes from per-shard O(size/M) histograms over
        # the stable owner partition instead of one O(size) int64
        # histogram (which would be a 17 GB transient at 2^31 buckets —
        # on the path whose whole point is O(E/M) host RSS).  A bucket
        # lives entirely in one shard, so the max over shard-local
        # histograms IS the global cap, exactly.
        order, starts = shard_order(h_full, size, M)
        order_starts = (order, starts)
        bpd = size // M
        cap = 1
        for s in range(M):
            h_s = h_full[order[starts[s] : starts[s + 1]]]
            if h_s.shape[0]:
                cap = max(cap, int(np.bincount(
                    (h_s & np.uint32(bpd - 1)).astype(np.int64),
                    minlength=1,
                ).max()))
        shard_rows = np.diff(starts)
    else:
        limit = size * max_factor
        while True:
            counts = np.bincount(
                (h_full & np.uint32(size - 1)).astype(np.int64),
                minlength=size,
            )
            cap = int(counts.max())
            if cap <= target_cap or size >= limit:
                break
            size <<= 1
        shard_rows = counts.reshape(M, size // M).sum(axis=1)
    geom = PointGeom(
        size=size, cap=cap, n=n, M=M,
        R_pad=_ceil_pow2(int(shard_rows.max()) + max(pad, cap)),
    )
    return (geom, order_starts) if return_order else geom


@dataclass(frozen=True)
class RangeGeom:
    """Global geometry of one range view (distinct-key group table over a
    sorted column + its permuted row table), matching
    ``build_range_hash`` + ``_stack_range``."""

    gh: PointGeom  # group-key hash geometry (G_pad = gh.R_pad)
    G: int  # distinct keys
    rows: int  # underlying row count
    R_pad: int  # stacked rows per shard (pow2)
    max_run: int  # longest group (RangeIndex.max_run)

    @property
    def cap(self) -> int:
        return self.gh.cap

    @property
    def G_pad(self) -> int:
        return self.gh.R_pad


def range_geom(
    gk: np.ndarray,
    lens: np.ndarray,
    h_g: np.ndarray,
    M: int,
    *,
    min_size: int = 8,
    fan_pad: int = 64,
    max_factor: int = 8,
    lean: bool = False,
) -> RangeGeom:
    """Geometry from the distinct group keys' hashes + group lengths:
    per-shard row totals come from one weighted owner histogram (a
    bucket's groups — and hence their rows — live entirely in one
    shard), no partition pass."""
    gh = point_geom(
        h_g, M, min_size=min_size, pad=64, max_factor=max_factor, lean=lean
    )
    G = int(gk.shape[0])
    if G:
        owner = shard_owner(h_g, gh.size, M).astype(np.int64)
        row_counts = np.bincount(
            owner, weights=lens.astype(np.float64), minlength=M
        ).astype(np.int64)
    else:
        row_counts = np.zeros(M, np.int64)
    return RangeGeom(
        gh=gh, G=G, rows=int(lens.sum()) if G else 0,
        R_pad=_ceil_pow2(int(row_counts.max() if M else 1) + max(fan_pad, 64)),
        max_run=int(lens.max()) if G else 0,
    )


# ---------------------------------------------------------------------------
# partition: stable owner grouping + shard-local bucket index
# ---------------------------------------------------------------------------


def shard_owner(h: np.ndarray, size: int, M: int) -> np.ndarray:
    """Owning shard of each hash: the HIGH bits of the bucket index
    (bucket // bpd) — the ownership rule ``_stack_point`` encodes by
    slicing the bucket range [s·bpd, (s+1)·bpd) per shard."""
    shift = np.uint32((size // M).bit_length() - 1)
    return ((h & np.uint32(size - 1)) >> shift).astype(np.uint32)


def shard_order(
    h_full: np.ndarray, size: int, M: int
) -> Tuple[np.ndarray, np.ndarray]:
    """(order, starts): stable permutation grouping rows by owning shard,
    plus the shard boundaries (int64[M+1]).  ``order[starts[s]:
    starts[s+1]]`` are shard s's rows in their ORIGINAL relative order —
    the property that makes shard-local stable bucket sorts reproduce the
    global permutation's tie-breaks."""
    from ..native.sort import hash_index32

    n = int(h_full.shape[0])
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(M + 1, np.int64)
    owner = shard_owner(h_full, size, M)
    got = hash_index32(owner, M)  # counting sort by owner (= owner & (M-1))
    if got is not None:
        rows, off, _cap = got
        return rows.astype(np.int64), off.astype(np.int64)
    ow = owner.astype(np.int64)
    order = np.argsort(ow, kind="stable")
    off = np.zeros(M + 1, np.int64)
    np.cumsum(np.bincount(ow, minlength=M), out=off[1:])
    return order, off


def local_bucket_index(
    h_s: np.ndarray, bpd: int
) -> Tuple[np.ndarray, np.ndarray]:
    """(perm, off) of ONE shard's rows by shard-local bucket.  The local
    bucket is the global bucket's low bits (bpd pow2), so a stable
    counting sort here == the global ``build_hash`` permutation
    restricted to the shard, and ``off`` == the normalized local offsets
    ``_stack_point`` computes by subtracting the shard's base."""
    from ..native.sort import hash_index32

    n = int(h_s.shape[0])
    got = hash_index32(np.ascontiguousarray(h_s, np.uint32), bpd)
    if got is not None:
        rows, off, _cap = got
        return rows.astype(np.int64), off
    hb = (h_s & np.uint32(bpd - 1)).astype(np.int64)
    counts = np.bincount(hb, minlength=bpd)
    off = np.zeros(bpd + 1, np.int64)
    np.cumsum(counts, out=off[1:])
    return np.argsort(hb, kind="stable"), off.astype(np.int32)


# ---------------------------------------------------------------------------
# owned-subset stacked arrays
# ---------------------------------------------------------------------------


@dataclass
class ShardSlices:
    """A model-sharded stacked array materialized only for OWNED shards —
    the multihost representation (each process holds its devices' slices;
    parallel/sharded.py feeds ``block_for`` to
    ``jax.make_array_from_callback``, which asks only for addressable
    shards)."""

    shape: Tuple[int, ...]
    dtype: np.dtype
    per: int  # leading-axis rows per shard
    blocks: Dict[int, np.ndarray]

    def block_for(self, index) -> np.ndarray:
        s = (index[0].start or 0) // self.per
        blk = self.blocks[s]
        # make_array_from_callback may slice the trailing dims too (it
        # never does for P(model) specs, but stay exact)
        return blk[(slice(None),) + tuple(index[1:])] if len(index) > 1 else blk

    def to_full(self) -> np.ndarray:
        """Assemble the full stacked array (owned == all shards only) —
        the parity-test / single-process form."""
        M = self.shape[0] // self.per
        out = np.empty(self.shape, self.dtype)
        for s in range(M):
            out[s * self.per : (s + 1) * self.per] = self.blocks[s]
        return out

    def map_blocks(self, fn, w: int, dtype) -> "ShardSlices":
        """A new ShardSlices with every owned block transformed (the
        HBM-lean pack applies per block — each process packs only the
        slices it owns, with the globally-agreed spec)."""
        return ShardSlices(
            shape=(self.shape[0], w) if len(self.shape) > 1 else self.shape,
            dtype=np.dtype(dtype),
            per=self.per,
            blocks={s: fn(b) for s, b in self.blocks.items()},
        )

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for b in self.blocks.values())


#: cols_at(rows) -> gathered int32 columns for the given row ids, in that
#: row order.  The row-id space is the caller's (global snapshot rows for
#: the full build; partition-local rows for the multihost feed).
ColsAt = Callable[[np.ndarray], List[np.ndarray]]


def gather_cols(cols: Sequence[np.ndarray]) -> ColsAt:
    """ColsAt over plain full columns (native parallel gathers)."""
    from ..native.sort import take32

    cc = [np.ascontiguousarray(c, np.int32) for c in cols]

    def at(rows: np.ndarray) -> List[np.ndarray]:
        idx = np.ascontiguousarray(rows, np.int64)
        return [take32(c, idx) for c in cc]

    return at


def _fill_block(blk: np.ndarray, vals: List[np.ndarray]) -> None:
    from ..native.sort import fill_interleaved

    n = int(vals[0].shape[0]) if vals else 0
    if n and not fill_interleaved(blk, vals, None):
        for j, c in enumerate(vals):
            blk[:n, j] = c


def stack_point_shards(
    geom: PointGeom,
    w: int,
    shard_h: Callable[[int], np.ndarray],
    shard_cols: Callable[[int, np.ndarray], List[np.ndarray]],
    owned: Optional[Sequence[int]] = None,
):
    """Shard-at-a-time ``_stack_point``: bitwise-identical (off, tbl) with
    O(E/M) peak scratch.  ``shard_h(s)`` returns shard s's row hashes in
    their global relative order; ``shard_cols(s, perm)`` the payload
    columns gathered at the shard-LOCAL positions ``perm`` (the bucket
    permutation).  ``owned=None`` assembles full arrays; a shard subset
    returns ShardSlices holding only those blocks."""
    M, bpd, R_pad = geom.M, geom.bpd, geom.R_pad
    full = owned is None
    shards = range(M) if full else sorted(owned)
    if full:
        off = np.empty(M * (bpd + 1), np.int32)
        tbl = np.full((M * R_pad, w), -1, np.int32)
    else:
        off_blocks: Dict[int, np.ndarray] = {}
        tbl_blocks: Dict[int, np.ndarray] = {}
    for s in shards:
        h_s = shard_h(s)
        perm, off_local = local_bucket_index(h_s, bpd)
        n_s = int(h_s.shape[0])
        if full:
            off[s * (bpd + 1) : (s + 1) * (bpd + 1)] = off_local
            blk = tbl[s * R_pad : (s + 1) * R_pad]
        else:
            off_blocks[s] = np.ascontiguousarray(off_local, np.int32)
            blk = np.full((R_pad, w), -1, np.int32)
            tbl_blocks[s] = blk
        if n_s:
            _fill_block(blk, shard_cols(s, perm))
    if full:
        return off, tbl
    return (
        ShardSlices((M * (bpd + 1),), np.dtype(np.int32), bpd + 1, off_blocks),
        ShardSlices((M * R_pad, w), np.dtype(np.int32), R_pad, tbl_blocks),
    )


def stack_point(
    h_full: np.ndarray,
    cols_at: ColsAt,
    geom: PointGeom,
    w: int,
    owned: Optional[Sequence[int]] = None,
    order: Optional[Tuple[np.ndarray, np.ndarray]] = None,
):
    """``_stack_point(build_hash(keys, ...), cols, M)`` from full columns,
    built shard-at-a-time: partitions rows by owner once, then each
    shard's slice independently.  ``order`` accepts a precomputed
    (order, starts) owner partition of the SAME ``h_full`` —
    ``point_geom(..., return_order=True)``'s frozen-branch byproduct —
    so the >16M-row builds don't pay the counting sort twice."""
    if order is None:
        order, starts = shard_order(h_full, geom.size, geom.M)
    else:
        order, starts = order

    def shard_h(s: int) -> np.ndarray:
        return h_full[order[starts[s] : starts[s + 1]]]

    def shard_cols(s: int, perm: np.ndarray) -> List[np.ndarray]:
        rows = order[starts[s] : starts[s + 1]][perm]
        return cols_at(rows)

    return stack_point_shards(geom, w, shard_h, shard_cols, owned)


def stack_range_shards(
    geom: RangeGeom,
    w: int,
    shard_groups: Callable[[int], Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    rows_at: ColsAt,
    owned: Optional[Sequence[int]] = None,
):
    """Shard-at-a-time ``_stack_range``: bitwise-identical
    (goff, gtbl, rows_tbl).  ``shard_groups(s)`` returns the shard's
    (h_g, gk, glo, lens) in global group order (glo in the row-id space
    ``rows_at`` understands); the row table is each shard's groups' rows
    concatenated in local bucket order, locally re-offset — exactly the
    global bucket-ordered row permutation restricted to the shard."""
    M, bpd = geom.gh.M, geom.gh.bpd
    G_pad, R_pad = geom.G_pad, geom.R_pad
    full = owned is None
    shards = range(M) if full else sorted(owned)
    if full:
        goff = np.empty(M * (bpd + 1), np.int32)
        gtbl = np.full((M * G_pad, 3), -1, np.int32)
        rows_tbl = np.full((M * R_pad, w), -1, np.int32)
    else:
        goff_b: Dict[int, np.ndarray] = {}
        gtbl_b: Dict[int, np.ndarray] = {}
        rows_b: Dict[int, np.ndarray] = {}
    for s in shards:
        h_s, gk_s, glo_s, lens_s = shard_groups(s)
        perm, off_local = local_bucket_index(h_s, bpd)
        n_g = int(h_s.shape[0])
        if full:
            goff[s * (bpd + 1) : (s + 1) * (bpd + 1)] = off_local
            gblk = gtbl[s * G_pad : (s + 1) * G_pad]
            rblk = rows_tbl[s * R_pad : (s + 1) * R_pad]
        else:
            goff_b[s] = np.ascontiguousarray(off_local, np.int32)
            gblk = np.full((G_pad, 3), -1, np.int32)
            rblk = np.full((R_pad, w), -1, np.int32)
            gtbl_b[s], rows_b[s] = gblk, rblk
        if not n_g:
            continue
        lens_f = lens_s[perm].astype(np.int64)
        r_end = np.cumsum(lens_f)
        r_start = r_end - lens_f
        gblk[:n_g, 0] = gk_s[perm]
        gblk[:n_g, 1] = r_start.astype(np.int32)
        gblk[:n_g, 2] = r_end.astype(np.int32)
        total = int(r_end[-1])
        if total:
            row_src = (
                np.repeat(glo_s[perm].astype(np.int64), lens_f)
                + np.arange(total, dtype=np.int64)
                - np.repeat(r_start, lens_f)
            )
            _fill_block(rblk, rows_at(row_src))
    if full:
        return goff, gtbl, rows_tbl
    return (
        ShardSlices((M * (bpd + 1),), np.dtype(np.int32), bpd + 1, goff_b),
        ShardSlices((M * G_pad, 3), np.dtype(np.int32), G_pad, gtbl_b),
        ShardSlices((M * R_pad, w), np.dtype(np.int32), R_pad, rows_b),
    )


def stack_range(
    gk: np.ndarray,
    glo: np.ndarray,
    lens: np.ndarray,
    h_g: np.ndarray,
    rows_at: ColsAt,
    geom: RangeGeom,
    w: int,
    owned: Optional[Sequence[int]] = None,
):
    """``_stack_range(build_range_hash(k, ...), row_cols, M, fan_pad)``
    from full group/row columns, built shard-at-a-time."""
    order, starts = shard_order(h_g, geom.gh.size, geom.gh.M)
    glo64 = glo.astype(np.int64)
    lens64 = lens.astype(np.int64)

    def shard_groups(s: int):
        gi = order[starts[s] : starts[s + 1]]
        return h_g[gi], gk[gi], glo64[gi], lens64[gi]

    return stack_range_shards(geom, w, shard_groups, rows_at, owned)


# ---------------------------------------------------------------------------
# feed partition: O(E/M) host RSS per multihost process
# ---------------------------------------------------------------------------


@dataclass
class FeedPartition:
    """One process's share of a bucket-partitioned store feed, fully
    prepared: the bucket-filtered Snapshot (owned rows + replicated
    membership subgraph), the stacked flat tables (ShardSlices for the
    O(E) tables — only owned blocks exist; plain full arrays for the
    globally-small ones), and the FlatMeta every process agrees on.
    ``ShardedEngine.prepare_partitioned`` turns this into a
    DeviceSnapshot via ``jax.make_array_from_callback``."""

    snapshot: object  # store.snapshot.Snapshot (bucket-filtered)
    arrays: Dict[str, object]  # np.ndarray | ShardSlices
    meta: object  # engine.flat.FlatMeta
    owned: Tuple[int, ...]
    M: int
    #: fold maintenance state (engine/fold.py FoldState, armed with
    #: maps/N) when the fold committed — carried onto the DeviceSnapshot
    #: so incremental prepares keep the fold instead of downgrading
    fold_state: object = None


def _owned_mask_of(owner: np.ndarray, M: int, owned) -> np.ndarray:
    m = np.zeros(M, bool)
    m[np.asarray(owned, np.int64)] = True
    return m[owner.astype(np.int64)]


def snapshot_raw_columns(snap, copy: bool = False) -> Dict[str, np.ndarray]:
    """The raw pre-interned column dict ``partition_feed`` consumes,
    from a resident Snapshot.  The srel re-encoding (``e_srel1 - 1``,
    -1 = direct subject) is the feed's convention and is load-bearing —
    every caller must agree on it, which is why this is THE helper.
    ``copy=True`` hands the feed private arrays (the feed releases its
    refs as it goes but callers that keep using the snapshot may want
    isolation anyway)."""
    cp = (lambda a: a.copy()) if copy else (lambda a: a)
    return dict(
        res=cp(snap.e_res), rel=cp(snap.e_rel), subj=cp(snap.e_subj),
        srel=(snap.e_srel1.astype(np.int32) - 1),
        caveat=cp(snap.e_caveat), ctx=cp(snap.e_ctx),
        exp_us=cp(snap.e_exp_us),
    )


def partition_feed(
    revision: int,
    compiled,
    interner,
    cols: Dict[str, np.ndarray],
    config,
    model_size: int,
    owned: Optional[Sequence[int]] = None,
    *,
    contexts: Optional[list] = None,
    epoch_us: Optional[int] = None,
    plan=None,
    serve: str = "partitioned",
) -> Optional[FeedPartition]:
    """Partition a RAW store feed by bucket-shard ownership and prepare
    the stacked flat tables from the local partitions — the multihost
    counterpart of ``build_flat_arrays_sharded`` with per-process host
    memory O(E/M·|owned|) + the replicated small state, and stacked
    arrays BITWISE-identical to the build-full-then-stack reference at
    the same feed (tests/test_feed_partition.py).

    ``cols`` holds UNSORTED pre-interned columns (res, rel, subj, srel
    with -1 = direct; optional caveat/ctx/exp_us) and is CONSUMED — the
    full-feed columns are released as soon as ownership is decided, so
    the peak holds the raw feed once, never the full sorted world.

    What stays global (replicated, derived from one streaming pass over
    the feed): the membership subgraph (``finish_snapshot`` over userset
    rows ∪ rows feeding used usersets), the flattened closure, the dense
    slot maps and node radix, the T-index JOIN (its rows partition right
    after), pus/ovf/closure tables, and every FlatMeta field.

    With ``plan`` (the engine's DevicePlan) the permission FOLD and rc
    flattening run too: their derivations read the full views through a
    stub (raw primary columns + the replicated membership snapshot's
    userset view + the transient global arrow view) and are CANONICAL —
    dedup sorts by full row identity — so the raw feed order yields the
    same rows as the sorted reference snapshot, and each owned shard's
    slice of the pf/pfu/rc stacked tables is then built independently
    by the same stable owner/local-bucket discipline (bitwise-identical
    to the full derivation; tests/test_fold_partition.py).  The csr
    closure-by-source view is replicated like the closure itself.  With
    ``plan=None`` fold/rc are declined as before (the parity oracle for
    the walked layout).

    The reverse-CSR lookup index (engine/rev.py) is DECLINED on this
    path: its shard ownership is keyed by the SUBJECT hash, not the
    primary (k1, k2) bucket the owned feed rows arrive keyed by — a
    process would need other owners' rows to build its rv slices (an
    owner exchange at feed time; ROADMAP follow-on).  Lookups on a
    feed-partitioned snapshot serve through the host walker.

    ``serve`` picks the placement the tables are built for:

    - ``"partitioned"`` (default): every O(E)-scale table materializes
      owned shard slices only — the bitwise-parity layout
      (``build_flat_arrays_sharded`` with the same plan is the oracle).
    - ``"routed"``: the owner-routed SERVING layout
      (FlatMeta.part_serve) — the O(E)-scale point tables (ehx, pfx,
      tx) keep owned-only slices; the userset/arrow/pfu/rc stacked
      tables build WHOLE on every process (they are membership- or
      group-structure-sized, exactly the state the host already
      replicates), so each device probes them locally and a routed
      query batch dispatches with no collectives
      (parallel/sharded.py).

    Returns None when the dense keys don't pack into int32 (same bail as
    the builders — such worlds use the legacy engine)."""
    import time as _time

    from ..native.sort import lexsort4
    from ..store.columns import filter_columns
    from ..store.snapshot import (
        _exp_to_rel32,
        finish_snapshot,
        partitioned_snapshot,
    )
    from ..utils import faults, metrics
    from .flat import (
        FlatMeta,
        _active_maps,
        _pack_flat,
        _until_dom,
        _arrow_data_depth,
        _ceil_pow2,
        _e_cols_at,
        _fold_packed,
        _groups_of,
        _m_srel1,
        _node_radix,
        _pack,
        _primary_hash_chunked,
        _rc_build,
        _round_cap,
        _round_fan,
        _run_maxes,
        _stack_point,
        _stack_range,
        _tindex_join,
        _uniq_small,
    )
    from .hash import build_hash, build_range_hash

    if serve not in ("partitioned", "routed"):
        raise ValueError(f"unknown serve mode {serve!r}")
    routed = serve == "routed"
    faults.fire("prepare.partition")
    _t0 = _time.perf_counter()
    M = model_size
    owned_t = tuple(range(M)) if owned is None else tuple(sorted(owned))
    # routed serving replicates the membership/group-structure tables on
    # every device; only the primary/fold point tables keep owned slices
    own_small = None if routed else owned_t
    if epoch_us is None:
        epoch_us = int(_time.time() * 1_000_000)
    contexts = contexts or []

    res = np.ascontiguousarray(cols.pop("res"), np.int32)
    rel = np.ascontiguousarray(cols.pop("rel"), np.int32)
    subj = np.ascontiguousarray(cols.pop("subj"), np.int32)
    srel1 = np.ascontiguousarray(cols.pop("srel"), np.int32) + 1
    E = int(res.shape[0])
    caveat = np.ascontiguousarray(
        cols.pop("caveat", np.zeros(E, np.int32)), np.int32
    )
    ctx = np.ascontiguousarray(
        cols.pop("ctx", np.full(E, -1, np.int32)), np.int32
    )
    exp_us = np.ascontiguousarray(
        cols.pop("exp_us", np.zeros(E, np.int64)), np.int64
    )
    exp32 = _exp_to_rel32(exp_us, epoch_us)
    cols.clear()

    num_slots = max(compiled.num_slots, 1)
    # HBM-lean mode: the same bounded bucket growth as the reference
    # builders (parity-critical), and the pack domains — all derived
    # from replicated inputs (the raw feed + membership subgraph), so
    # every process of a multihost build agrees on the packed layout
    # before any table exists
    PKD = config.packed_on()
    hk = (
        {"max_factor": config.flat_packed_max_factor, "lean": True}
        if PKD else {}
    )
    _mx = lambda *cs: max(
        [int(c.max()) for c in cs if c is not None and c.shape[0]] or [0]
    )
    dom: Dict = {
        "max_cav": _mx(caveat), "max_ctx": _mx(ctx), "until": {}, "fan": {},
    }

    # ---- replicated membership snapshot: userset rows ∪ feeders --------
    us_mask = srel1 > 0
    used = np.unique(
        subj[us_mask].astype(np.int64) * num_slots
        + (srel1[us_mask].astype(np.int64) - 1)
    )
    edge_key = res.astype(np.int64) * num_slots + rel.astype(np.int64)
    if used.shape[0]:
        pos = np.clip(np.searchsorted(used, edge_key), 0, used.shape[0] - 1)
        feeds = used[pos] == edge_key
    else:
        feeds = np.zeros(E, bool)
    del edge_key

    def _sorted_subset(rows: np.ndarray) -> Dict[str, np.ndarray]:
        sub = filter_columns(
            {
                "rel": rel, "res": res, "subj": subj, "srel1": srel1,
                "caveat": caveat, "ctx": ctx, "exp": exp32,
                "exp_us": exp_us,
            },
            rows,
        )
        o = lexsort4(sub["rel"], sub["res"], sub["subj"], sub["srel1"])
        return filter_columns(sub, o)

    mem = _sorted_subset(np.flatnonzero(us_mask | feeds))
    del feeds
    mem_snap = finish_snapshot(
        revision, compiled, interner,
        e_rel=mem["rel"], e_res=mem["res"], e_subj=mem["subj"],
        e_srel1=mem["srel1"], e_caveat=mem["caveat"], e_ctx=mem["ctx"],
        e_exp=mem["exp"], e_exp_us=mem["exp_us"],
        contexts=contexts, epoch_us=epoch_us,
    )
    del mem

    # ---- arrow view (full, transient until partitioned) ----------------
    ts = np.asarray(sorted(compiled.tupleset_slots), np.int64)
    ar_full = _sorted_subset(
        np.flatnonzero(np.isin(rel.astype(np.int64), ts) & (srel1 == 0))
    )

    from ..store.closure import build_closure

    with metrics.default.timer("prepare.closure_s"):
        cl = build_closure(mem_snap, per_source_cap=config.closure_source_cap)

    class _Stub:
        pass

    # full-view stub: raw (unsorted) primary columns + the replicated
    # membership snapshot's userset view + the transient global arrow
    # view.  fold_permissions/_rc_build read per-edge views through it;
    # their outputs are CANONICAL (dedup sorts by full row identity), so
    # the raw feed order yields the same FoldResult/ancestor closures as
    # the sorted reference snapshot — bitwise
    stub = _Stub()
    stub.compiled, stub.interner = compiled, interner
    stub.e_rel, stub.e_res, stub.e_subj, stub.e_srel1 = rel, res, subj, srel1
    stub.e_caveat, stub.e_ctx, stub.e_exp = caveat, ctx, exp32
    stub.us_rel, stub.us_res = mem_snap.us_rel, mem_snap.us_res
    stub.us_subj, stub.us_srel = mem_snap.us_subj, mem_snap.us_srel
    stub.us_caveat, stub.us_ctx = mem_snap.us_caveat, mem_snap.us_ctx
    stub.us_exp, stub.us_perm = mem_snap.us_exp, mem_snap.us_perm
    stub.pus_n, stub.pus_r = mem_snap.pus_n, mem_snap.pus_r
    stub.ar_rel, stub.ar_res = ar_full["rel"], ar_full["res"]
    stub.ar_child = ar_full["subj"]
    stub.ar_caveat, stub.ar_ctx = ar_full["caveat"], ar_full["ctx"]
    stub.ar_exp = ar_full["exp"]
    stub.num_slots, stub.num_nodes = num_slots, mem_snap.num_nodes
    stub.node_type = mem_snap.node_type
    stub.wildcard_node_of_type = mem_snap.wildcard_node_of_type

    # permission fold over the full views (engine/fold.py): the
    # derivation is leaf-/group-structure-shaped; only its TABLES are
    # stacked below (owned slices on the partitioned layout).  Folded
    # slots join the k1 radix exactly as in the reference builders
    fr = fstate = None
    if plan is not None:
        from .fold import fold_permissions

        with metrics.default.timer("prepare.fold_s"):
            got_fold = fold_permissions(stub, config, plan, cl)
        if got_fold is not None:
            fr, fstate = got_fold
    maps = _active_maps(
        stub, cl, {slot for _, slot in fr.pairs} if fr is not None else ()
    )
    N = _node_radix(stub, maps)
    if N is None:
        return None
    S1 = maps.S1

    flags = dict(
        e_hascav=bool(caveat.any()), e_hasexp=bool(exp32.any()),
        us_hascav=bool(mem_snap.us_caveat.any()),
        us_hasexp=bool(mem_snap.us_exp.any()),
        us_hasperm=bool(mem_snap.us_perm.any()),
        ar_hascav=bool(ar_full["caveat"].any()),
        ar_hasexp=bool(ar_full["exp"].any()),
    )
    wc_nodes = mem_snap.wildcard_node_of_type[
        mem_snap.wildcard_node_of_type >= 0
    ]
    has_wc_edges = bool(wc_nodes.size and np.isin(subj, wc_nodes).any())
    e_slots = tuple(int(s) for s in _uniq_small([rel], num_slots))
    us_slots = tuple(
        int(s) for s in _uniq_small([mem_snap.us_rel], num_slots)
    )
    ar_dd = _arrow_data_depth(stub)

    ms = max(8, M)
    us_gk = _pack(maps.k1[mem_snap.us_rel], N, mem_snap.us_res)
    ar_gk = _pack(maps.k1[ar_full["rel"]], N, ar_full["res"])
    cl_k1 = _pack(cl.c_src, S1, _m_srel1(maps, cl.c_srel1))
    cl_k2 = _pack(cl.c_g, S1, maps.k2[cl.c_grel] + 1)
    pus_k = _pack(mem_snap.pus_n, S1, maps.k2[mem_snap.pus_r] + 1)
    ovf_k = _pack(cl.ovf_src, S1, _m_srel1(maps, cl.ovf_srel1))

    # fold dense packing + the subject-fan decline, in the reference
    # builder's exact order, and the rc ancestor closures — both read
    # the full-view stub, which the primary partition below releases.
    # Their outputs are self-contained arrays sized by the fold/closure
    # structure, partitioned into stacked slices further down
    got = _fold_packed(fr, stub, maps, N, config) if fr is not None else None
    csr = None
    if got is not None:
        csr = build_range_hash(cl_k1, min_size=ms, **hk)
        if int(csr.max_run) > config.flat_fold_subj_fan_cap:
            got = None
    rc_built = _rc_build(stub, config, plan, ar_dd)

    # ---- primary: hash raw rows chunked, keep only owned ---------------
    h_e = _primary_hash_chunked(
        rel, res, subj, srel1, maps, N, S1,
        max(int(config.flat_partition_chunk), 1),
    )
    ge = point_geom(h_e, M, min_size=ms, **hk)
    e_own_rows = np.flatnonzero(
        _owned_mask_of(shard_owner(h_e, ge.size, M), M, owned_t)
    )
    e_sub = filter_columns(
        {
            "rel": rel, "res": res, "subj": subj, "srel1": srel1,
            "caveat": caveat, "ctx": ctx, "exp": exp32, "exp_us": exp_us,
            "h": h_e.view(np.int32),  # rides the takes; viewed back below
        },
        e_own_rows,
    )
    # stub holds references into the raw columns (maps/radix/depth all
    # computed above) — drop it WITH them or nothing is actually freed
    del stub, h_e, res, rel, subj, srel1, caveat, ctx, exp_us, exp32
    del e_own_rows
    eo = lexsort4(e_sub["rel"], e_sub["res"], e_sub["subj"], e_sub["srel1"])
    e_sub = filter_columns(e_sub, eo)
    del eo
    h_own = e_sub.pop("h").view(np.uint32)

    # ---- userset / arrow views: partition by group bucket --------------
    us_gkg, us_glo, us_ghi = _groups_of(us_gk)
    ar_gkg, ar_glo, ar_ghi = _groups_of(ar_gk)
    h_usg = _hash_cols([us_gkg])
    h_arg = _hash_cols([ar_gkg])
    gus = range_geom(
        us_gkg, us_ghi - us_glo, h_usg, M, min_size=ms,
        fan_pad=max(64, config.us_leaf_cap), **hk,
    )
    dom["fan"]["usgx"] = gus.max_run
    gar = range_geom(
        ar_gkg, ar_ghi - ar_glo, h_arg, M, min_size=ms,
        fan_pad=max(64, config.arrow_fanout), **hk,
    )
    dom["fan"]["argx"] = gar.max_run
    us_fanouts = _run_maxes(us_gkg, us_glo, us_ghi, N, maps.k1_raw)
    ar_fanouts = _run_maxes(ar_gkg, ar_glo, ar_ghi, N, maps.k1_raw)

    def _owned_groups(gkg, glo, ghi, h_g, geom):
        """(row ids of owned groups' rows, local gk/glo/lens/h) with the
        global order preserved — local glo re-offsets into the filtered
        row space."""
        gmask = _owned_mask_of(
            shard_owner(h_g, geom.gh.size, M), M, owned_t
        )
        lens = (ghi - glo).astype(np.int64)
        rows = (
            np.repeat(glo.astype(np.int64)[gmask], lens[gmask])
            + np.arange(int(lens[gmask].sum()), dtype=np.int64)
            - np.repeat(
                np.cumsum(lens[gmask]) - lens[gmask], lens[gmask]
            )
        ) if gmask.any() else np.zeros(0, np.int64)
        l_lens = lens[gmask]
        l_glo = np.cumsum(l_lens) - l_lens
        return rows, gkg[gmask], l_glo, l_lens, h_g[gmask]

    us_rows, us_l_gk, us_l_glo, us_l_lens, us_l_h = _owned_groups(
        us_gkg, us_glo, us_ghi, h_usg, gus
    )
    ar_rows, ar_l_gk, ar_l_glo, ar_l_lens, ar_l_h = _owned_groups(
        ar_gkg, ar_glo, ar_ghi, h_arg, gar
    )
    ar_loc = filter_columns(ar_full, ar_rows)
    del ar_gk
    if not routed:
        del ar_full  # routed serving stacks the WHOLE arrow view below

    # ---- T-index: global join, rows partitioned right after ------------
    tj = _tindex_join(mem_snap, config, cl, us_gk, cl_k1, cl_k2, pus_k, maps)
    del us_gk

    snap = partitioned_snapshot(
        mem_snap,
        e_cols=e_sub,
        us_rows=us_rows,
        ar_cols={
            "rel": ar_loc["rel"], "res": ar_loc["res"],
            "child": ar_loc["subj"], "caveat": ar_loc["caveat"],
            "ctx": ar_loc["ctx"], "exp": ar_loc["exp"],
        },
        owned=owned_t,
    )

    # ---- stacked tables: owned slices only for the O(E) ones -----------
    out: Dict[str, object] = {}
    e_gates = (
        ([snap.e_caveat, snap.e_ctx] if flags["e_hascav"] else [])
        + ([snap.e_exp] if flags["e_hasexp"] else [])
    )
    # _e_cols_at is the stacked builder's own column provider: the pack
    # recompute per shard is defined ONCE (parity-critical)
    out["eh_off"], out["ehx"] = stack_point(
        h_own, _e_cols_at(snap, maps, N, S1, e_gates), ge,
        2 + len(e_gates), owned=owned_t,
    )
    del h_own

    if routed:
        # routed serving: the userset/arrow views are membership- and
        # resource-structure-sized — stack them WHOLE (every device
        # probes its owner's block arithmetically, no collectives).
        # The full userset view IS the replicated membership snapshot's
        us_cols = (
            [mem_snap.us_subj, maps.k2[mem_snap.us_srel]]
            + (
                [mem_snap.us_caveat, mem_snap.us_ctx]
                if flags["us_hascav"] else []
            )
            + ([mem_snap.us_exp] if flags["us_hasexp"] else [])
            + ([mem_snap.us_perm] if flags["us_hasperm"] else [])
        )
        out["usr_off"], out["usgx"], out["usx"] = stack_range(
            us_gkg, us_glo, us_ghi - us_glo, h_usg,
            gather_cols(us_cols), gus, len(us_cols),
        )
        ar_cols = (
            [ar_full["subj"]]
            + (
                [ar_full["caveat"], ar_full["ctx"]]
                if flags["ar_hascav"] else []
            )
            + ([ar_full["exp"]] if flags["ar_hasexp"] else [])
        )
        out["arr_off"], out["argx"], out["arx"] = stack_range(
            ar_gkg, ar_glo, ar_ghi - ar_glo, h_arg,
            gather_cols(ar_cols), gar, len(ar_cols),
        )
        del ar_full
    else:
        us_cols = (
            [snap.us_subj, maps.k2[snap.us_srel]]
            + ([snap.us_caveat, snap.us_ctx] if flags["us_hascav"] else [])
            + ([snap.us_exp] if flags["us_hasexp"] else [])
            + ([snap.us_perm] if flags["us_hasperm"] else [])
        )
        out["usr_off"], out["usgx"], out["usx"] = stack_range(
            us_l_gk, us_l_glo, us_l_lens, us_l_h,
            gather_cols(us_cols), gus, len(us_cols), owned=owned_t,
        )
        ar_cols = (
            [snap.ar_child]
            + ([snap.ar_caveat, snap.ar_ctx] if flags["ar_hascav"] else [])
            + ([snap.ar_exp] if flags["ar_hasexp"] else [])
        )
        out["arr_off"], out["argx"], out["arx"] = stack_range(
            ar_l_gk, ar_l_glo, ar_l_lens, ar_l_h,
            gather_cols(ar_cols), gar, len(ar_cols), owned=owned_t,
        )

    t_kw = dict(has_tindex=False, t_cap=4, t_n=8, t_slots=())
    if tj is not None:
        T_k1, T_k2, T_d, T_p, t_slots = tj
        dom["until"]["tx"] = _until_dom(T_d, T_p)
        h_T = _hash_cols([T_k1, T_k2])
        gT = point_geom(h_T, M, min_size=ms, **hk)
        # owned slices on BOTH layouts: the T join is O(E·fold)-scale —
        # the largest table after the primary — so the routed placement
        # model-splits it like ehx/pfx.  Its bucket geometry differs
        # from the routing geometry, so T-probing slots are simply not
        # routable (parallel/sharded.py _routable): they take the psum
        # fallback, where the ownership-mask probe is exact
        t_own = _owned_mask_of(shard_owner(h_T, gT.size, M), M, owned_t)
        T_cols = [c[t_own] for c in (T_k1, T_k2, T_d, T_p)]
        out["th_off"], out["tx"] = stack_point(
            h_T[t_own], gather_cols(T_cols), gT, 4, owned=owned_t
        )
        t_kw = dict(
            has_tindex=True,
            t_cap=_round_cap(gT.cap),
            t_n=_ceil_pow2(max(gT.n, 1)),
            t_slots=t_slots,
        )
        del tj, T_k1, T_k2, T_d, T_p, h_T, t_own, T_cols

    # globally-small tables: full stacked build on every process (their
    # inputs are the replicated closure / pus derivations)
    dom["until"]["clx"] = _until_dom(cl.c_d_until, cl.c_p_until)
    clh = build_hash([cl_k1, cl_k2], min_size=ms, **hk)
    push = build_hash([pus_k], min_size=ms, **hk)
    ovfh = build_hash([ovf_k], min_size=ms, **hk)
    out["clh_off"], out["clx"] = _stack_point(
        clh, [cl_k1, cl_k2, cl.c_d_until, cl.c_p_until], M
    )
    out["push_off"], out["pusx"] = _stack_point(push, [pus_k], M)
    out["ovfh_off"], out["ovfx"] = _stack_point(ovfh, [ovf_k], M)

    # ---- permission fold (P-index): owned slices of the pf point
    # table + pfu range view; the csr closure-by-source view replicates
    # like the closure it is derived from --------------------------------
    fold_kw: Dict = {}
    if got is not None:
        pf_k1, pf_k2, pf_subj, (u_k1, u_gk, u_until, u_fan), pff = got
        pf_cols = (
            [pf_k1, pf_k2]
            + ([fr.e_cav, fr.e_ctx] if pff["pf_hascav"] else [])
            + ([fr.e_until] if pff["pf_hasuntil"] else [])
        )
        dom["until"]["pfx"] = _until_dom(fr.e_until)
        dom["until"]["pfux"] = _until_dom(u_until)
        h_pf = _hash_cols([pf_k1, pf_k2])
        gpf = point_geom(h_pf, M, min_size=ms, **hk)
        out["pfh_off"], out["pfx"] = stack_point(
            h_pf, gather_cols(pf_cols), gpf, len(pf_cols), owned=owned_t
        )
        s_fan = _round_fan(max(int(csr.max_run), 1))
        dom["fan"]["pfugx"] = u_fan
        dom["fan"]["csrgx"] = s_fan
        extra: Dict = {}
        direct_ok = False
        if routed:
            # routed serving replicates the fold's subject-side views:
            # prefer the COMPACT single-chip form (dense ``pfu_start`` /
            # ``csr_start`` offset arrays + split 1-wide columns — the
            # bucket-hash group tables cost ~16× the bytes per row, all
            # of it replicated on this placement)
            from .flat import _pf_view_tables

            fold_slots = tuple(sorted({s for _, s in fr.pairs}))
            pf_arrays, pf_kw = _pf_view_tables(
                u_k1, u_gk, u_until, u_fan,
                cl_k1, cl_k2, cl.c_d_until, cl.c_p_until, s_fan,
                maps=maps, N=N, S1=S1, fold_slots=fold_slots,
                config=config,
            )
            direct_ok = pf_kw["pf_direct"] and pf_kw["pf_s_direct"]
            if direct_ok:
                out.update(pf_arrays)
                extra = pf_kw
        if not direct_ok:
            # stacked group views: owned slices on the partitioned
            # layout, whole on the routed one (key space over the
            # direct budget)
            pfu_gkg, pfu_glo, pfu_ghi = _groups_of(u_k1)
            h_pfu = _hash_cols([pfu_gkg])
            gpfu = range_geom(
                pfu_gkg, pfu_ghi - pfu_glo, h_pfu, M, min_size=ms,
                fan_pad=max(64, u_fan), **hk,
            )
            out["pfu_off"], out["pfugx"], out["pfux"] = stack_range(
                pfu_gkg, pfu_glo, pfu_ghi - pfu_glo, h_pfu,
                gather_cols([u_gk, u_until]), gpfu, 2, owned=own_small,
            )
            out["csr_off"], out["csrgx"], out["csrx"], csr_cap = _stack_range(
                csr, [cl_k2, cl.c_d_until, cl.c_p_until], M, max(64, s_fan)
            )
            extra = dict(
                pf_u_cap=_round_cap(gpfu.cap),
                pf_s_cap=_round_cap(csr_cap),
            )
        fold_kw = dict(
            fold_pairs=fr.pairs,
            pf_e_cap=_round_cap(gpf.cap),
            pf_u_fan=u_fan,
            pf_s_fan=s_fan,
            pf_haswc=bool(np.isin(pf_subj, wc_nodes).any()),
            pf_has_e=pf_k1.shape[0] > 0,
            pf_has_u=u_k1.shape[0] > 0,
            **extra,
            **pff,
        )
        # arm the maintenance state with the packing context it needs
        # at delta time (fold_delta_update), exactly like the reference
        # builders — without it the first incremental prepare would
        # sticky-downgrade the fold (pf_off) and unroute folded slots
        fstate.maps, fstate.N = maps, N
    else:
        fstate = None

    # ---- rc ancestor closures: owned slices of each range view ---------
    rc_list = []
    for ts_slot, (src, anc, d_u, p_u, fan) in rc_built.items():
        rc_gk, rc_glo, rc_ghi = _groups_of(src)
        h_rc = _hash_cols([rc_gk])
        dom["until"][f"rc{ts_slot}x"] = _until_dom(d_u, p_u)
        dom["fan"][f"rc{ts_slot}gx"] = fan
        grc = range_geom(
            rc_gk, rc_ghi - rc_glo, h_rc, M, min_size=ms,
            fan_pad=max(64, fan), **hk,
        )
        (
            out[f"rc{ts_slot}_off"],
            out[f"rc{ts_slot}gx"],
            out[f"rc{ts_slot}x"],
        ) = stack_range(
            rc_gk, rc_glo, rc_ghi - rc_glo, h_rc,
            gather_cols([anc, d_u, p_u]), grc, 3, owned=own_small,
        )
        rc_list.append((int(ts_slot), _round_cap(grc.cap), fan))

    # routing/attribution gauge: how many primary rows this process's
    # owned shards materialized (the O(E·owned/M) share of the feed)
    metrics.default.set_gauge(
        "partition.owned_rows", float(int(snap.e_rel.shape[0]))
    )

    meta = FlatMeta(
        N=N, S1=S1,
        k1_dense=tuple(int(x) for x in maps.k1),
        k2_dense=tuple(int(x) for x in maps.k2),
        **fold_kw,
        rc_slots=tuple(sorted(rc_list)),
        part_serve=routed,
        e_cap=_round_cap(ge.cap), e_n=_ceil_pow2(max(ge.n, 1)),
        usr_cap=_round_cap(gus.cap),
        usr_gn=8,
        us_rows=8,
        arr_cap=_round_cap(gar.cap),
        arr_gn=8,
        ar_rows=8,
        cl_cap=_round_cap(clh.cap), cl_n=_ceil_pow2(max(clh.n, 1)),
        has_closure=clh.n > 0,
        pus_cap=_round_cap(push.cap), pus_n=_ceil_pow2(max(push.n, 1)),
        ovf_cap=_round_cap(ovfh.cap), ovf_n=_ceil_pow2(max(ovfh.n, 1)),
        has_ovf=ovfh.n > 0,
        ar_fanout_by_slot=ar_fanouts,
        us_fanout_by_slot=us_fanouts,
        **t_kw,
        **flags,
        blockslice=True,
        sharded=True,
        ar_data_depth=ar_dd,
        e_slots=e_slots,
        us_slots=us_slots,
        has_wc_edges=has_wc_edges,
        has_wc_closure=bool(
            np.isin(cl.c_src[cl.c_srel1 == 0], wc_nodes).any()
            or np.isin(cl.ovf_src[cl.ovf_srel1 == 0], wc_nodes).any()
        ),
    )
    if PKD:
        with metrics.default.timer("prepare.pack_lanes_s"):
            pk_up = _pack_flat(out, meta, config, dom, pack_off=False)
        if pk_up:
            from dataclasses import replace as _dc_replace

            meta = _dc_replace(meta, **pk_up)
    metrics.default.observe(
        "prepare.partition_s", _time.perf_counter() - _t0
    )
    return FeedPartition(
        snapshot=snap, arrays=out, meta=meta, owned=owned_t, M=M,
        fold_state=fstate,
    )

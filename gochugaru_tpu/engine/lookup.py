"""Device-backed LookupResources / LookupSubjects.

The reference streams these from the server (client/client.go:508-552,
561-599).  Round 1 ran them as O(candidate-objects × recursive Python
check) host loops; this module is the scalable replacement, a two-stage
pipeline (SURVEY.md §7.7 "lookups as reverse-BFS on transposed
adjacency"):

1. **Reverse candidate expansion (host, vectorized).**  Transposed
   sorted views — all edges keyed by (subject, subject_relation), arrow
   edges keyed by child, plus resource-keyed views — are built lazily
   once per Snapshot.  A worklist over subject-occurrence keys expands a
   **provable superset** of the answer with numpy ``searchsorted`` range
   scans: every grant needs at least one positive edge path from
   resource to subject through the rewrite graph, so reverse
   reachability over {direct-grant edges ∪ arrows ∪ userset membership ∪
   permission-valued userset chains} (ignoring caveat/expiry gates,
   which only shrink results) covers union/intersection/exclusion/
   arrow/wildcard/self-identity semantics.

2. **Exact forward filter (device).**  The candidates run through the
   engine's differentially-tested batched check in one dispatch
   (``check_columns``); definite grants stream back through the
   interner.  Overflowed and possible-not-definite candidates re-check
   on the host oracle, which keeps exactly the definite ones — matching
   oracle.lookup_*'s conditional omission (the bool collapse,
   client/client.go:277) while still resolving permission-userset
   grants the device can only call "possible".

Cost: candidate expansion is O(result-neighborhood · log E) host work
with no per-edge Python; the exact filter is one device dispatch over
|candidates| queries.  Measured at BASELINE config-3 scale (1M docs /
~10M edges, benchmarks/bench3_docs.py, single-core host): ~180 ms warm
per lookup for a ~7k-result subject — vs minutes of recursive host
checks.  The first lookup on a revision additionally builds (or, after
a delta, incrementally advances) the transposed index.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from ..native.sort import argsort1, lexsort2
from ..rel.relationship import WILDCARD_ID
from ..store.snapshot import Snapshot

#: padding floor for the lookup exact-filter batch (see _exact_filter)
LOOKUP_BUCKET_MIN = 4096

_B32 = np.int64(2**32)


def _ranges(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """Concatenated index ranges [lo[i], hi[i]) — the ragged gather that
    turns per-key searchsorted bounds into one flat index array."""
    counts = (hi - lo).astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, np.int64)
    starts = np.repeat(lo.astype(np.int64), counts)
    ends = np.cumsum(counts)
    offs = np.arange(total, dtype=np.int64) - np.repeat(ends - counts, counts)
    return starts + offs


@dataclass
class LookupIndex:
    """Transposed sorted views for reverse expansion, built once per
    Snapshot (lazily) and cached on it."""

    #: all edges keyed by packed (subject, srel1), sorted
    rs_key: np.ndarray  # int64[E] = subj * (num_slots+1) + srel1
    rs_res: np.ndarray  # int32[E]
    rs_rel: np.ndarray  # int32[E]
    #: arrow edges keyed by child node, sorted
    ra_child: np.ndarray  # int32[A]
    ra_res: np.ndarray  # int32[A]
    #: all edges keyed by resource node, sorted (stable → within a run the
    #: residual order is the primary (rel, subj, srel1))
    er_res: np.ndarray  # int32[E]
    er_rel: np.ndarray  # int32[E]
    er_subj: np.ndarray  # int32[E]
    er_srel1: np.ndarray  # int32[E]
    #: primary view packed (rel, res) — already sorted by construction
    e_relres: np.ndarray  # int64[E]
    #: arrow view packed (rel, res) — already sorted by construction
    ar_relres: np.ndarray  # int64[A]
    #: [interner num_types, num_slots] — slot is a permission on the type
    perm_table: np.ndarray
    #: interner tid → permission slots on that type (int64 array)
    perm_slots_of_tid: Dict[int, np.ndarray]


def _perm_tables(snap: Snapshot):
    """Per-interner-type permission tables, sized to the CURRENT interner
    (a delta can intern the first node of a schema type, growing it)."""
    interner = snap.interner
    compiled = snap.compiled
    perm_table = np.zeros((max(interner.num_types, 1), snap.num_slots), bool)
    perm_slots_of_tid: Dict[int, np.ndarray] = {}
    for tname, d in compiled.schema.definitions.items():
        itid = interner.type_lookup(tname)
        if itid < 0:
            continue
        slots = np.asarray(
            sorted(compiled.slot_of_name[p] for p in d.permissions), np.int64
        )
        if slots.size:
            perm_table[itid, slots] = True
            perm_slots_of_tid[itid] = slots
    return perm_table, perm_slots_of_tid


_BUILD_LOCK_GUARD = threading.Lock()


def lookup_index(snap: Snapshot, mark_used: bool = True) -> LookupIndex:
    """The transposed index, built once per snapshot.  ``mark_used``
    records that lookups are actually consumed on this snapshot — the
    signal apply_delta's defer heuristic reads; the prepare-time prewarm
    passes False so merely prewarming never pushes Watch revisions onto
    the eager O(E) path (store/delta.py)."""
    if mark_used:
        snap._lookup_used = True
    idx = getattr(snap, "_lookup_index", None)
    if idx is not None:
        return idx
    # race-safe: the prepare-time prewarm thread (engine/device.py) and a
    # first user lookup may arrive together — one builds, the other
    # waits.  Lock creation itself goes through a module-level guard so
    # two racers can't each mint their own lock and build twice
    with _BUILD_LOCK_GUARD:
        lock = getattr(snap, "_lookup_build_lock", None)
        if lock is None:
            lock = threading.Lock()
            snap._lookup_build_lock = lock
    with lock:
        idx = getattr(snap, "_lookup_index", None)
        if idx is not None:
            return idx
        # chain-advance fast path: materializing a chained LSM snapshot
        # whose BASE carries a LIVE index advances it as part of the
        # merge (store/delta.py _materialize_locked) in O(E + D log E)
        # identity merges; an UNUSED (prewarm-only) index is not paid
        # for per revision — the merge stashes the O(D) advance inputs
        # and the first real lookup advances from the stash here.
        # Either way the O(E log E) rebuild is skipped
        if getattr(snap, "_lsm_base", None) is not None:
            snap._materialize()
        idx = getattr(snap, "_lookup_index", None)
        if idx is not None:  # the materialization advanced it
            return idx
        if redeem_chain_stash(snap):
            return snap._lookup_index
        return _build_lookup_index(snap)


def _build_lookup_index(snap: Snapshot) -> LookupIndex:
    NS1 = snap.num_slots + 1
    order = lexsort2(snap.e_subj, snap.e_srel1)
    rs_key = (
        snap.e_subj[order].astype(np.int64) * NS1
        + snap.e_srel1[order].astype(np.int64)
    )
    ra_order = argsort1(snap.ar_child)
    er_order = argsort1(snap.e_res)
    perm_table, perm_slots_of_tid = _perm_tables(snap)
    idx = LookupIndex(
        rs_key=rs_key,
        rs_res=snap.e_res[order],
        rs_rel=snap.e_rel[order],
        ra_child=snap.ar_child[ra_order],
        ra_res=snap.ar_res[ra_order],
        er_res=snap.e_res[er_order],
        er_rel=snap.e_rel[er_order],
        er_subj=snap.e_subj[er_order],
        er_srel1=snap.e_srel1[er_order],
        e_relres=snap.e_rel.astype(np.int64) * _B32 + snap.e_res.astype(np.int64),
        ar_relres=snap.ar_rel.astype(np.int64) * _B32 + snap.ar_res.astype(np.int64),
        perm_table=perm_table,
        perm_slots_of_tid=perm_slots_of_tid,
    )
    snap._lookup_index = idx
    return idx


def _setdiff(new: np.ndarray, seen: np.ndarray) -> np.ndarray:
    if new.size == 0 or seen.size == 0:
        return new
    return new[~np.isin(new, seen)]


def _exact_filter(
    engine,
    dsnap,
    cand: np.ndarray,
    q_res: np.ndarray,
    q_perm: np.ndarray,
    q_subj: np.ndarray,
    q_srel: np.ndarray,
    q_wc: np.ndarray,
    now_us: Optional[int],
    oracle_check: Callable[[int], bool],
) -> np.ndarray:
    """Run the device forward check over candidate queries; returns the
    subset of ``cand`` definitively granted.  Overflowed AND
    possible-not-definite items re-check on the host oracle — the oracle
    includes the ones it resolves to T and drops genuinely-conditional
    ones, exactly matching oracle.lookup_* (conditional omission = the
    bool collapse, client/client.go:277).  Resolving p&~d on the host
    matters for permission-valued userset subjects, where the device can
    only ever report "possible" but the host answer is definite."""
    # coarse bucket floor: per-subject candidate counts vary, and every
    # fresh pow2 bucket costs a kernel retrace — with a 4096 floor, warm
    # lookups share one compiled program
    d, p, ovf = engine.check_columns(
        dsnap, q_res, q_perm, q_subj, q_srel=q_srel, q_wc=q_wc,
        now_us=now_us, bucket_min=LOOKUP_BUCKET_MIN,
    )
    needs_host = ovf | (p & ~d)
    granted = list(cand[d & ~needs_host])
    for i in np.nonzero(needs_host)[0]:
        if oracle_check(int(cand[i])):
            granted.append(int(cand[i]))
    return np.asarray(granted, np.int64)


def _resolve_resources(dsnap, resource_type, permission, subject_type,
                       subject_id, subject_relation):
    """Shared query lowering of a LookupResources call: (rtid,
    perm_slot, srel_slot, subj_node, wc_node) or None when the answer is
    [] by construction (unknown names)."""
    snap: Snapshot = dsnap.snapshot
    interner = snap.interner
    compiled = snap.compiled
    perm_slot = compiled.slot_of_name.get(permission)
    rtid = interner.type_lookup(resource_type)
    if perm_slot is None or rtid < 0:
        return None
    if subject_relation and subject_relation not in compiled.slot_of_name:
        return None
    srel_slot = compiled.slot_of_name[subject_relation] if subject_relation else -1
    subj_node = interner.lookup(subject_type, subject_id)
    stid = interner.type_lookup(subject_type)
    wc_node = -1
    if (
        srel_slot < 0
        and subject_id != WILDCARD_ID
        and 0 <= stid < snap.wildcard_node_of_type.shape[0]
    ):
        wc_node = int(snap.wildcard_node_of_type[stid])
    if subj_node < 0 and wc_node < 0:
        return None
    return rtid, perm_slot, srel_slot, subj_node, wc_node


def _walk_resource_candidates(
    snap: Snapshot, subj_node: int, srel_slot: int, wc_node: int
) -> np.ndarray:
    """The host walker's reverse worklist expansion: every node on a
    positive reverse path from the subject — the PARITY ORACLE of the
    device frontier path (engine/spmv.py), and the serving fallback for
    snapshots without the reverse-CSR index (legacy layouts, LSM delta
    chains — whose advance_lookup_index machinery keeps this exact).

    The worklist is over *subject-occurrence keys* packed
    (node, srel1): scanning a key yields every edge where that userset
    (or direct subject / wildcard) appears as the subject; each hit's
    resource becomes a candidate, is closed under reverse arrows, and
    contributes new keys — (res, rel+1) for the granted relation (the
    membership chain, generalizing the device's Phase-A closure) and,
    for schemas with permission-valued usersets, (n, p+1) for every
    permission p on each new node n (the subject may hold p on n, so
    edges granted to n#p may be granted to the subject)."""
    compiled = snap.compiled
    NS1 = snap.num_slots + 1
    idx = lookup_index(snap)
    perm_chains = bool(compiled.has_permission_usersets)

    def rev_arrows(frontier: np.ndarray) -> np.ndarray:
        lo = np.searchsorted(idx.ra_child, frontier, "left")
        hi = np.searchsorted(idx.ra_child, frontier, "right")
        return idx.ra_res[_ranges(lo, hi)].astype(np.int64)

    init: List[np.ndarray] = []
    if subj_node >= 0:
        init.append(
            np.array(
                [subj_node * NS1 + (srel_slot + 1 if srel_slot >= 0 else 0)], np.int64
            )
        )
    if wc_node >= 0:
        init.append(np.array([wc_node * NS1], np.int64))
    seen_keys = np.unique(np.concatenate(init))
    key_frontier = seen_keys
    # self-identity: the subject node itself may be the resource
    seen_nodes = (
        np.array([subj_node], np.int64) if subj_node >= 0 else np.empty(0, np.int64)
    )
    while key_frontier.size:
        lo = np.searchsorted(idx.rs_key, key_frontier, "left")
        hi = np.searchsorted(idx.rs_key, key_frontier, "right")
        ii = _ranges(lo, hi)
        new_keys: List[np.ndarray] = []
        if ii.size:
            res = idx.rs_res[ii].astype(np.int64)
            relk = idx.rs_rel[ii].astype(np.int64)
            # granted usersets continue the membership chain
            new_keys.append(res * NS1 + relk + 1)
            # candidates: the resources themselves, closed under reverse
            # arrows (parents granting through tupleset traversal)
            fresh_rounds: List[np.ndarray] = []
            node_frontier = _setdiff(np.unique(res), seen_nodes)
            while node_frontier.size:
                seen_nodes = np.union1d(seen_nodes, node_frontier)
                fresh_rounds.append(node_frontier)
                parents = np.unique(rev_arrows(node_frontier))
                node_frontier = _setdiff(parents, seen_nodes)
            if perm_chains and fresh_rounds:
                # the subject may hold any permission on any fresh
                # candidate node; edges granted to n#p extend the chain
                fresh = np.concatenate(fresh_rounds)
                tids = snap.node_type[fresh]
                for t in np.unique(tids):
                    slots = idx.perm_slots_of_tid.get(int(t))
                    if slots is None:
                        continue
                    nn = fresh[tids == t]
                    new_keys.append(
                        (nn[:, None] * NS1 + slots[None, :] + 1).ravel()
                    )
        if new_keys:
            nk = np.unique(np.concatenate(new_keys))
            key_frontier = _setdiff(nk, seen_keys)
            seen_keys = np.union1d(seen_keys, key_frontier)
        else:
            key_frontier = np.empty(0, np.int64)

    return seen_nodes


def _resolve_subjects(dsnap, resource_type, resource_id, permission,
                      subject_type, subject_relation):
    """Shared query lowering of a LookupSubjects call: (res_node,
    perm_slot, srel_slot, stid, wc_node) or None when the answer is []
    by construction."""
    snap: Snapshot = dsnap.snapshot
    interner = snap.interner
    compiled = snap.compiled
    perm_slot = compiled.slot_of_name.get(permission)
    res_node = interner.lookup(resource_type, resource_id)
    stid = interner.type_lookup(subject_type)
    if perm_slot is None or res_node < 0 or stid < 0:
        return None
    if subject_relation and subject_relation not in compiled.slot_of_name:
        return None
    srel_slot = compiled.slot_of_name[subject_relation] if subject_relation else -1
    wc_node = -1
    if 0 <= stid < snap.wildcard_node_of_type.shape[0]:
        wc_node = int(snap.wildcard_node_of_type[stid])
    return res_node, perm_slot, srel_slot, stid, wc_node


def _walk_subject_candidates(
    snap: Snapshot, res_node: int, stid: int, srel_slot: int, wc_node: int
) -> np.ndarray:
    """The host walker's forward worklist expansion — the parity oracle
    of the device forward-frontier path and the fallback for layouts
    without the reverse-CSR index.

    The worklist alternates nodes and userset pairs: a node contributes
    its arrow subgraph and every edge hanging off it (direct subjects →
    candidates, userset subjects → pairs); a pair (g, r) contributes g's
    members when r is a relation (edges (r, g)), or puts g back on the
    node worklist when r is a *permission* — holders of r on g are found
    by expanding g itself (superset; the forward check is exact)."""
    compiled = snap.compiled
    NS = snap.num_slots
    idx = lookup_index(snap)
    ts_slots = np.asarray(sorted(compiled.tupleset_slots), np.int64)

    def fwd_arrows(frontier: np.ndarray) -> np.ndarray:
        if ts_slots.size == 0:
            return np.empty(0, np.int64)
        kk = (ts_slots[:, None] * _B32 + frontier[None, :]).ravel()
        lo = np.searchsorted(idx.ar_relres, kk, "left")
        hi = np.searchsorted(idx.ar_relres, kk, "right")
        return snap.ar_child[_ranges(lo, hi)].astype(np.int64)

    cand_parts: List[np.ndarray] = []
    wildcard_found = False
    seen_nodes = np.empty(0, np.int64)
    seen_pairs = np.empty(0, np.int64)
    node_frontier = np.array([res_node], np.int64)
    pair_frontier = np.empty(0, np.int64)

    def absorb_edges(subs: np.ndarray, sr1: np.ndarray) -> np.ndarray:
        """Direct subjects → candidates / wildcard flag; userset subjects
        → packed pairs.  Returns the new pairs."""
        nonlocal wildcard_found
        direct = subs[sr1 == 0].astype(np.int64)
        if srel_slot < 0 and direct.size:
            cand_parts.append(direct[snap.node_type[direct] == stid])
        if wc_node >= 0 and not wildcard_found and np.any(direct == wc_node):
            wildcard_found = True
        um = sr1 > 0
        return subs[um].astype(np.int64) * NS + (sr1[um].astype(np.int64) - 1)

    while node_frontier.size or pair_frontier.size:
        new_pairs: List[np.ndarray] = []
        next_nodes: List[np.ndarray] = []
        if node_frontier.size:
            # arrow closure of the frontier, then every edge off the new nodes
            frontier = node_frontier
            fresh_all: List[np.ndarray] = []
            while frontier.size:
                fresh = _setdiff(np.unique(frontier), seen_nodes)
                if fresh.size == 0:
                    break
                seen_nodes = np.union1d(seen_nodes, fresh)
                fresh_all.append(fresh)
                frontier = fwd_arrows(fresh)
            if fresh_all:
                nodes = np.concatenate(fresh_all)
                lo = np.searchsorted(idx.er_res, nodes, "left")
                hi = np.searchsorted(idx.er_res, nodes, "right")
                ii = _ranges(lo, hi)
                new_pairs.append(absorb_edges(idx.er_subj[ii], idx.er_srel1[ii]))
        if pair_frontier.size:
            g = pair_frontier // NS
            r = pair_frontier % NS
            is_perm = idx.perm_table[snap.node_type[g], r]
            # permission pairs: holders of g#p ⊆ expansion of g itself
            if np.any(is_perm):
                next_nodes.append(g[is_perm])
            # relation pairs: members are the subjects of edges (r, g)
            rel_g, rel_r = g[~is_perm], r[~is_perm]
            if rel_g.size:
                kk = rel_r * _B32 + rel_g
                lo = np.searchsorted(idx.e_relres, kk, "left")
                hi = np.searchsorted(idx.e_relres, kk, "right")
                jj = _ranges(lo, hi)
                new_pairs.append(
                    absorb_edges(
                        snap.e_subj[jj].astype(np.int64),
                        snap.e_srel1[jj].astype(np.int64),
                    )
                )
        if new_pairs:
            np_all = np.unique(np.concatenate(new_pairs))
            pair_frontier = _setdiff(np_all, seen_pairs)
            seen_pairs = np.union1d(seen_pairs, pair_frontier)
        else:
            pair_frontier = np.empty(0, np.int64)
        node_frontier = (
            _setdiff(np.unique(np.concatenate(next_nodes)), seen_nodes)
            if next_nodes
            else np.empty(0, np.int64)
        )

    if srel_slot >= 0 and seen_pairs.size:
        # userset-subject lookup: candidate usersets with matching relation
        gs = seen_pairs[seen_pairs % NS == srel_slot] // NS
        cand_parts.append(gs[snap.node_type[gs] == stid])
    # self-identity: the resource itself can be the subject
    if snap.node_type[res_node] == stid:
        cand_parts.append(np.array([res_node], np.int64))
    if wildcard_found and srel_slot < 0:
        # a reachable wildcard grants every direct subject of the type
        # that appears anywhere in the graph (oracle's subjects_of_type)
        all_subj = np.unique(snap.e_subj).astype(np.int64)
        cand_parts.append(all_subj[snap.node_type[all_subj] == stid])

    if not cand_parts:
        return np.empty(0, np.int64)
    return np.unique(np.concatenate(cand_parts))


# ---------------------------------------------------------------------------
# dispatch: device frontier SpMV (engine/spmv.py) with walker fallback,
# cursor-paginated streaming
# ---------------------------------------------------------------------------


def _res_filter(engine, dsnap, resolved, names, now_us, oracle_factory):
    """(filter_fn, id_of) of one LookupResources query — exact device
    forward check over a candidate block, oracle re-checks for
    overflow/possible (shared by the frontier and walker streams)."""
    rtid, perm_slot, srel_slot, subj_node, wc_node = resolved
    resource_type, permission, subject_type, subject_id, subject_relation = names
    interner = dsnap.snapshot.interner
    oracle = [None]

    def oracle_check(node: int) -> bool:
        if oracle[0] is None:
            oracle[0] = oracle_factory()
        from .oracle import T

        _, rid = interner.key_of(node)
        # now_us pins the re-check to the stream's evaluation time — a
        # recompute-resume must not re-gate expirations at a later clock
        return oracle[0].check(
            resource_type, rid, permission,
            subject_type, subject_id, subject_relation,
            now_us=now_us,
        ) == T

    def filt(cand: np.ndarray) -> np.ndarray:
        B = cand.shape[0]
        return _exact_filter(
            engine, dsnap, cand,
            q_res=cand.astype(np.int32),
            q_perm=np.full(B, perm_slot, np.int32),
            q_subj=np.full(B, subj_node, np.int32),
            q_srel=np.full(B, srel_slot, np.int32),
            q_wc=np.full(B, wc_node, np.int32),
            now_us=now_us,
            oracle_check=oracle_check,
        )

    return filt, (lambda n: interner.key_of(n)[1])


def _subj_filter(engine, dsnap, resolved, names, now_us, oracle_factory):
    res_node, perm_slot, srel_slot, stid, wc_node = resolved
    resource_type, resource_id, permission, subject_type, subject_relation = names
    interner = dsnap.snapshot.interner
    oracle = [None]

    def oracle_check(node: int) -> bool:
        if oracle[0] is None:
            oracle[0] = oracle_factory()
        from .oracle import T

        _, sid = interner.key_of(node)
        return oracle[0].check(
            resource_type, resource_id, permission,
            subject_type, sid, subject_relation,
            now_us=now_us,
        ) == T

    def filt(cand: np.ndarray) -> np.ndarray:
        B = cand.shape[0]
        q_wc = np.full(B, -1, np.int32)
        if srel_slot < 0 and wc_node >= 0:
            # a candidate that IS the wildcard node checks as itself, not
            # against the wildcard (oracle: subject_id != WILDCARD guard)
            q_wc = np.where(cand == wc_node, -1, wc_node).astype(np.int32)
        return _exact_filter(
            engine, dsnap, cand,
            q_res=np.full(B, res_node, np.int32),
            q_perm=np.full(B, perm_slot, np.int32),
            q_subj=cand.astype(np.int32),
            q_srel=np.full(B, srel_slot, np.int32),
            q_wc=q_wc,
            now_us=now_us,
            oracle_check=oracle_check,
        )

    return filt, (lambda n: interner.key_of(n)[1])


def _one_block(cand: np.ndarray):
    if cand.size:
        yield cand


def _frontier_stream_bytes(meta, snap) -> int:
    """Estimated host bytes a live frontier stream holds (the seen-set
    bitmaps dominate) — the paginate cache's eviction weight."""
    ns = max(snap.num_slots, 1) + 1
    return (meta.N * meta.S1 + 2 * meta.N + meta.N * ns) >> 3


def lookup_resources_page(
    engine,
    dsnap,
    resource_type: str,
    permission: str,
    subject_type: str,
    subject_id: str,
    subject_relation: str = "",
    *,
    page_size: int = 1_000,
    cursor=None,
    now_us: Optional[int] = None,
    oracle_factory: Optional[Callable[[], object]] = None,
):
    """One cursor-paginated page of LookupResources: (ids, next_cursor).

    Results stream in deterministic discovery order — the first page of
    a 10M-resource answer returns after the first few frontier hops,
    before the fixpoint completes.  ``cursor`` (engine/spmv.py
    LookupCursor) is revision-pinned: resuming continues the cached
    live stream, or deterministically recomputes and skips.  The device
    frontier path (engine/spmv.py) serves snapshots carrying the
    reverse-CSR index; legacy layouts and LSM delta chains keep the
    host walker (delta-exact through advance_lookup_index)."""
    from . import spmv

    names = (resource_type, permission, subject_type, subject_id,
             subject_relation)
    # evaluation time resolves ONCE and rides the cursor: a recompute-
    # resume must re-gate expirations at the same instant (spmv.py)
    now_us = spmv.resolve_now_us(cursor, now_us)
    token = spmv.query_token("res", dsnap.revision, now_us, *names)
    resolved = _resolve_resources(dsnap, *names)
    if resolved is None:
        return [], None
    rtid, perm_slot, srel_slot, subj_node, wc_node = resolved
    filt, id_of = _res_filter(
        engine, dsnap, resolved, names, now_us, oracle_factory
    )
    snap = dsnap.snapshot

    def make_stream():
        if spmv.frontier_ok(engine, dsnap):
            from ..utils import metrics as _m

            _m.default.inc("lookups.frontier")
            st = spmv.state_for(engine, dsnap)
            if st._spmm is not None:
                # served by the fused K-hop SpMM program (engine/spmm.py)
                _m.default.inc("lookups.fused")
            cands = st.resource_candidates(
                rtid, subj_node, srel_slot, wc_node, now_us
            )
            cost = _frontier_stream_bytes(dsnap.flat_meta, snap)
        else:
            from ..utils import metrics as _m

            _m.default.inc("lookups.walker")
            seen = _walk_resource_candidates(
                snap, subj_node, srel_slot, wc_node
            )
            cands = _one_block(seen[snap.node_type[seen] == rtid])
            cost = 1 << 20
        return spmv._ResultStream(cands, filt, id_of, cost_bytes=cost)

    return spmv.paginate(
        dsnap, token, make_stream, page_size, cursor, now_us
    )


def lookup_subjects_page(
    engine,
    dsnap,
    resource_type: str,
    resource_id: str,
    permission: str,
    subject_type: str,
    subject_relation: str = "",
    *,
    page_size: int = 1_000,
    cursor=None,
    now_us: Optional[int] = None,
    oracle_factory: Optional[Callable[[], object]] = None,
):
    """One cursor-paginated page of LookupSubjects: (ids, next_cursor) —
    the forward-frontier mirror of ``lookup_resources_page``."""
    from . import spmv

    names = (resource_type, resource_id, permission, subject_type,
             subject_relation)
    now_us = spmv.resolve_now_us(cursor, now_us)
    token = spmv.query_token("subj", dsnap.revision, now_us, *names)
    resolved = _resolve_subjects(dsnap, *names)
    if resolved is None:
        return [], None
    res_node, perm_slot, srel_slot, stid, wc_node = resolved
    filt, id_of = _subj_filter(
        engine, dsnap, resolved, names, now_us, oracle_factory
    )
    snap = dsnap.snapshot

    def make_stream():
        if spmv.frontier_ok(engine, dsnap) and dsnap.flat_meta.has_fw:
            from ..utils import metrics as _m

            _m.default.inc("lookups.frontier")
            st = spmv.state_for(engine, dsnap)
            if st._spmm is not None:
                _m.default.inc("lookups.fused")
            cands = st.subject_candidates(
                res_node, stid, srel_slot, wc_node, now_us
            )
            cost = _frontier_stream_bytes(dsnap.flat_meta, snap)
        else:
            from ..utils import metrics as _m

            _m.default.inc("lookups.walker")
            cands = _one_block(_walk_subject_candidates(
                snap, res_node, stid, srel_slot, wc_node
            ))
            cost = 1 << 20
        return spmv._ResultStream(cands, filt, id_of, cost_bytes=cost)

    return spmv.paginate(
        dsnap, token, make_stream, page_size, cursor, now_us
    )


def lookup_resources_device(
    engine,
    dsnap,
    resource_type: str,
    permission: str,
    subject_type: str,
    subject_id: str,
    subject_relation: str = "",
    *,
    now_us: Optional[int] = None,
    oracle_factory: Optional[Callable[[], object]] = None,
) -> List[str]:
    """Resource ids of ``resource_type`` the subject definitively holds
    ``permission`` on, sorted — the full-answer surface (drains the
    paginated stream).  Matches oracle.lookup_resources exactly on both
    serving paths (tests/test_lookup.py, tests/test_lookup_stream.py)."""
    out: List[str] = []
    cursor = None
    while True:
        ids, cursor = lookup_resources_page(
            engine, dsnap, resource_type, permission, subject_type,
            subject_id, subject_relation,
            page_size=65_536, cursor=cursor, now_us=now_us,
            oracle_factory=oracle_factory,
        )
        out.extend(ids)
        if cursor is None:
            return sorted(out)


def lookup_subjects_device(
    engine,
    dsnap,
    resource_type: str,
    resource_id: str,
    permission: str,
    subject_type: str,
    subject_relation: str = "",
    *,
    now_us: Optional[int] = None,
    oracle_factory: Optional[Callable[[], object]] = None,
) -> List[str]:
    """Subject ids of ``subject_type`` definitively holding ``permission``
    on the resource, sorted — the full-answer surface of the paginated
    stream.  Matches oracle.lookup_subjects exactly on both paths."""
    out: List[str] = []
    cursor = None
    while True:
        ids, cursor = lookup_subjects_page(
            engine, dsnap, resource_type, resource_id, permission,
            subject_type, subject_relation,
            page_size=65_536, cursor=cursor, now_us=now_us,
            oracle_factory=oracle_factory,
        )
        out.extend(ids)
        if cursor is None:
            return sorted(out)


# ---------------------------------------------------------------------------
# incremental index maintenance (Watch-driven re-index, BASELINE config 5)
# ---------------------------------------------------------------------------


def _view_keys(idx: "LookupIndex", ra_rel_src: Optional[Snapshot]):
    """Packed (k1, k2) int64 key arrays per transposed view, cached on
    the index — advancing then never re-packs or re-casts the O(E)
    columns, only merges them forward (the cache rides to the advanced
    index, so a Watch chain packs once per full build, not per
    revision)."""
    d = idx.__dict__
    if "_rs_k2" not in d:
        d["_rs_k2"] = (
            idx.rs_rel.astype(np.int64) * _B32 + idx.rs_res
        )
    if "_er_k1" not in d:
        d["_er_k1"] = idx.er_res.astype(np.int64)
    if "_er_k2" not in d:
        d["_er_k2"] = (
            (idx.er_rel.astype(np.int64) << np.int64(47))
            | (idx.er_subj.astype(np.int64) << np.int64(16))
            | idx.er_srel1.astype(np.int64)
        )
    if "_ra_k1" not in d:
        d["_ra_k1"] = idx.ra_child.astype(np.int64)
    if "_ra_k2" not in d:
        ra_rel = _ra_rel_of(ra_rel_src, idx)
        d["_ra_k2"] = ra_rel.astype(np.int64) * _B32 + idx.ra_res
    return d


def redeem_chain_stash(snap: Snapshot) -> bool:
    """Consume a deferred chain-advance stash on ``snap`` (written by
    store/delta.py _materialize_locked when the base's index was unused):
    one identity advance produces ``snap._lookup_index``.  Returns True
    when a stash was redeemed."""
    stash = snap.__dict__.pop("_lookup_chain_stash", None)
    if stash is None:
        return False
    (bidx, g_rel, g_res, g_subj, g_srel1,
     a_rel, a_res, a_subj, a_srel1) = stash
    advance_lookup_index(
        bidx, snap,
        num_slots=snap.num_slots,
        tupleset_slots=snap.compiled.tupleset_slots,
        g_rel=g_rel, g_res=g_res, g_subj=g_subj, g_srel1=g_srel1,
        a_rel=a_rel, a_res=a_res, a_subj=a_subj, a_srel1=a_srel1,
    )
    return True


def advance_lookup_index(
    idx: "LookupIndex",
    nxt: Snapshot,
    *,
    num_slots: int,
    tupleset_slots,
    ra_rel_src: Optional[Snapshot] = None,
    g_rel: np.ndarray,
    g_res: np.ndarray,
    g_subj: np.ndarray,
    g_srel1: np.ndarray,
    a_rel: np.ndarray,
    a_res: np.ndarray,
    a_subj: np.ndarray,
    a_srel1: np.ndarray,
) -> None:
    """Produce ``nxt._lookup_index`` from ``prev``'s by removing the
    ``g_*`` identities and merging the sorted ``a_*`` additions into each
    transposed view — O(E + D log E) instead of the full O(E log E)
    rebuild.  Removal is by IDENTITY (not row position), so the delta may
    span a whole LSM chain: apply_delta calls this per eager revision,
    and _materialize_locked calls it when a chained snapshot merges, with
    the base's accumulated tombstones + overlay (store/delta.py).  The
    packed per-view key arrays are cached on the index and merged
    forward (_view_keys), so repeated advances pay only array copies.

    ``idx`` is the index being advanced; ``ra_rel_src`` is the snapshot
    whose ar view recovers the index's ra-rel column on a cache miss —
    None is fine when ``idx`` already carries ``_ra_rel`` (the stash
    path pre-caches it)."""
    from ..store.delta import find_in_view, merge_positions

    keys = _view_keys(idx, ra_rel_src)
    NS1 = np.int64(num_slots + 1)
    g_rel = g_rel.astype(np.int64)
    g_res = g_res.astype(np.int64)
    g_subj = g_subj.astype(np.int64)
    g_srel1 = g_srel1.astype(np.int64)
    a_rel = a_rel.astype(np.int64)
    a_res = a_res.astype(np.int64)
    a_subj = a_subj.astype(np.int64)
    a_srel1 = a_srel1.astype(np.int64)

    def pack_rr(rel, res):
        return rel * _B32 + res

    def pack_rss(rel, subj, srel1):
        return (rel << np.int64(47)) | (subj << np.int64(16)) | srel1

    def advance_view(old_k1, old_k2, cols_old, rem_k1, rem_k2,
                     new_k1, new_k2, cols_new):
        """Merged (k1, k2, cols...) of one lexsorted view post-delta."""
        pos = find_in_view(old_k1, old_k2, rem_k1, rem_k2)
        keep = np.ones(old_k1.shape[0], dtype=bool)
        keep[pos[pos >= 0]] = False
        n_ord = np.lexsort((new_k2, new_k1))
        po, pn = merge_positions(
            old_k1[keep], old_k2[keep], new_k1[n_ord], new_k2[n_ord]
        )
        total = po.shape[0] + pn.shape[0]

        def m(co, cn):
            out = np.empty(total, co.dtype)
            out[po] = co[keep]
            out[pn] = cn[n_ord].astype(co.dtype)
            return out

        return (
            m(old_k1, new_k1), m(old_k2, new_k2),
            [m(co, cn) for co, cn in zip(cols_old, cols_new)],
        )

    # rs view: keyed (subj, srel1); residual order (rel, res)
    rs_key, rs_k2, (rs_res, rs_rel) = advance_view(
        idx.rs_key, keys["_rs_k2"],
        (idx.rs_res, idx.rs_rel),
        g_subj * NS1 + g_srel1, pack_rr(g_rel, g_res),
        a_subj * NS1 + a_srel1, pack_rr(a_rel, a_res),
        (a_res, a_rel),
    )

    # er view: keyed res; residual order (rel, subj, srel1)
    er_k1, er_k2, (er_rel, er_subj, er_srel1) = advance_view(
        keys["_er_k1"], keys["_er_k2"],
        (idx.er_rel, idx.er_subj, idx.er_srel1),
        g_res, pack_rss(g_rel, g_subj, g_srel1),
        a_res, pack_rss(a_rel, a_subj, a_srel1),
        (a_rel, a_subj, a_srel1),
    )

    # ra view: arrow rows only (tupleset relation, direct subject), keyed
    # child node; residual order (rel, res)
    ts = np.asarray(sorted(tupleset_slots), np.int64)
    g_ar = np.isin(g_rel, ts) & (g_srel1 == 0)
    a_ar = np.isin(a_rel, ts) & (a_srel1 == 0)
    prev_ra_rel = _ra_rel_of(ra_rel_src, idx)
    ra_k1, ra_k2, (ra_res, ra_rel) = advance_view(
        keys["_ra_k1"], keys["_ra_k2"],
        (idx.ra_res, prev_ra_rel),
        g_subj[g_ar], pack_rr(g_rel[g_ar], g_res[g_ar]),
        a_subj[a_ar], pack_rr(a_rel[a_ar], a_res[a_ar]),
        (a_res[a_ar], a_rel[a_ar]),
    )

    # the delta may have interned the FIRST node of a schema type, growing
    # the interner's type space — a carried perm_table would be undersized
    # and index out of bounds; the rebuild is O(types × permissions)
    if idx.perm_table.shape[0] >= max(nxt.interner.num_types, 1):
        perm_table, perm_slots = idx.perm_table, idx.perm_slots_of_tid
    else:
        perm_table, perm_slots = _perm_tables(nxt)
    new_idx = LookupIndex(
        rs_key=rs_key,
        rs_res=rs_res, rs_rel=rs_rel,
        ra_child=ra_k1.astype(np.int32), ra_res=ra_res,
        er_res=er_k1.astype(np.int32), er_rel=er_rel,
        er_subj=er_subj, er_srel1=er_srel1,
        e_relres=nxt.e_rel.astype(np.int64) * _B32 + nxt.e_res.astype(np.int64),
        ar_relres=nxt.ar_rel.astype(np.int64) * _B32 + nxt.ar_res.astype(np.int64),
        perm_table=perm_table,
        perm_slots_of_tid=perm_slots,
    )
    # carry the packed key caches: chained advances stay copy-only
    new_idx.__dict__["_rs_k2"] = rs_k2
    new_idx.__dict__["_er_k1"] = er_k1
    new_idx.__dict__["_er_k2"] = er_k2
    new_idx.__dict__["_ra_k1"] = ra_k1
    new_idx.__dict__["_ra_k2"] = ra_k2
    new_idx._ra_rel = ra_rel  # keep chained advances O(E + D log E)
    nxt._lookup_index = new_idx


def _ra_rel_of(snap: Optional[Snapshot], idx: LookupIndex) -> np.ndarray:
    """rel column of the ra view (child-sorted arrow rows), recovered from
    the snapshot's ar view once and cached on the index.  ``snap`` may be
    None only when the cache is already populated (the stash path
    pre-caches before the source snapshot's chain state is dropped)."""
    cached = getattr(idx, "_ra_rel", None)
    if cached is not None:
        return cached
    assert snap is not None, "ra-rel cache miss with no source snapshot"
    ra_order = argsort1(snap.ar_child)
    rel = snap.ar_rel[ra_order].astype(np.int64)
    idx._ra_rel = rel
    return rel

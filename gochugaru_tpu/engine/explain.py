"""Decision provenance: explain trees for any check at a pinned revision.

A verdict out of the fused gathers is a boolean; nothing so far answered
"why was this check *allowed/denied*" — the capability the reference's
server exposes as CheckPermission debug traces (SURVEY: SpiceDB
resolution semantics are the spec for our evaluator) and the first thing
a Zanzibar operator reaches for during an authorization incident.  This
module reconstructs a TYPED RESOLUTION TREE for any check by
instrumenting the existing host oracle walker (engine/oracle.py — the
exact-semantics reference) rather than duplicating semantics: the
oracle's ``check`` accepts a duck-typed ``recorder`` whose hooks cost one
``is not None`` branch when absent, so the hot fallback path is
untouched.

Tree contents:

- membership steps (direct edges, with the caveat/expiry gate detail
  that admitted or killed each one), wildcard grants, userset
  expansions, arrow traversals, union/intersection/exclusion operators;
- caveat evaluations WITH the merged (stored-over-query) context values
  that gated them, and expiry gates with their stamps;
- cycle cuts (least-fixpoint recursion) and memoized sub-answers;
- for denials, the EXHAUSTED FRONTIER: every edge the walk explored and
  why it failed (gated out, subject mismatch count, sub-verdict F).

**Device witness seeding**: the vectorized kernels (engine/flat.py)
optionally emit a per-query WITNESS CODE — the winning branch (direct
edge vs fold vs T-probe vs wildcard vs userset-closure vs rewrite, plus
a recursion-level class) piggybacked as a fourth output plane at zero
cost when disarmed (the trace.py NOOP discipline: the disarmed kernel is
byte-identical, no extra device output, no host allocations).  Explain
for allowed verdicts seeds the oracle walk from the witness
(``seed_branch``) instead of a blind re-walk, and the parity suite
asserts witness ⊆ oracle path on randomized worlds
(tests/test_explain.py).

Rendering mirrors the reference's debug-trace shape: a JSON object with
``resource``/``permission``/``subject``/``result`` and a nested
``tree`` of sub-resolutions.

Fault site ``explain.walk`` rides the chaos registry: an armed walk
raises BEFORE any tree state exists, the classified error reaches the
caller's retry envelope (client.explain), and the chaos suite asserts no
torn trees — a returned tree is always complete.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..utils import faults
from .oracle import F, T, U

__all__ = [
    "Recorder",
    "WIT_DIRECT",
    "WIT_FOLD",
    "WIT_NONE",
    "WIT_REWRITE",
    "WIT_SELF",
    "WIT_TPROBE",
    "WIT_USERSET",
    "WIT_WILDCARD",
    "explain_relationship",
    "seed_for",
    "tree_grant_kinds",
    "witness_branch",
    "witness_consistent",
    "witness_level",
    "witness_name",
]

# ---------------------------------------------------------------------------
# Witness codes (shared with engine/flat.py's armed kernel)
# ---------------------------------------------------------------------------

#: low 4 bits: the winning branch class; bits 4+: recursion-level class
#: (0 = a root-leaf probe answered; 1 = the permission-program rewrite —
#: arrows/unions/flattened hierarchies — carried the grant)
WIT_NONE = 0
WIT_SELF = 1  # reflexive userset identity (X#r ∈ X#r)
WIT_DIRECT = 2  # exact direct-edge hit at the root relation
WIT_WILDCARD = 3  # wildcard (`user:*`) grant at the root relation
WIT_TPROBE = 4  # T-index probe: pre-joined {userset edge × closure}
WIT_FOLD = 5  # permission-fold probe (pf_e / pf_u pair)
WIT_USERSET = 6  # userset row × live closure containment (KU path)
WIT_REWRITE = 7  # permission program (union/arrow/rc) at level ≥ 1

WIT_BRANCH_MASK = 0xF
WIT_LEVEL_SHIFT = 4

_WIT_NAMES = {
    WIT_NONE: None,
    WIT_SELF: "self",
    WIT_DIRECT: "direct",
    WIT_WILDCARD: "wildcard",
    WIT_TPROBE: "t_probe",
    WIT_FOLD: "fold",
    WIT_USERSET: "userset",
    WIT_REWRITE: "rewrite",
}


def witness_branch(code: int) -> int:
    return int(code) & WIT_BRANCH_MASK


def witness_level(code: int) -> int:
    return int(code) >> WIT_LEVEL_SHIFT


def witness_name(code: int) -> Optional[str]:
    return _WIT_NAMES.get(witness_branch(code))


def seed_for(code: int) -> Optional[str]:
    """The oracle-walk seed class for a device witness code: which edge
    class of the ROOT relation the walk should explore first.  T-probe,
    fold and userset-closure wins all correspond to userset edges on the
    host walk (the kernel branches are accelerations of userset ×
    closure / the pre-joined fold of the whole rewrite); rewrite wins
    carry no root-leaf seed."""
    b = witness_branch(code)
    if b == WIT_DIRECT:
        return "direct"
    if b == WIT_WILDCARD:
        return "wildcard"
    if b in (WIT_TPROBE, WIT_USERSET):
        return "userset"
    return None


# ---------------------------------------------------------------------------
# The recorder the oracle walker drives
# ---------------------------------------------------------------------------

_VERDICTS = {T: "allowed", U: "conditional", F: "denied"}


class Recorder:
    """Stack-shaped tree builder driven by ``Oracle.check(recorder=…)``.

    Bounded: past ``max_nodes`` attached nodes, further subtrees are
    built detached (so push/pop stays balanced) and dropped on pop, and
    the rendered tree carries ``truncated: true`` — a pathological world
    cannot blow the explain endpoint's memory."""

    __slots__ = ("root", "_stack", "max_nodes", "nodes", "truncated")

    def __init__(self, max_nodes: int = 50_000) -> None:
        self.root: Optional[Dict[str, Any]] = None
        self._stack: List[Dict[str, Any]] = []
        self.max_nodes = max_nodes
        self.nodes = 0
        self.truncated = False

    def _attach(self, node: Dict[str, Any]) -> None:
        if self.nodes >= self.max_nodes:
            self.truncated = True
            return
        self.nodes += 1
        if self._stack:
            self._stack[-1].setdefault("children", []).append(node)
        elif self.root is None:
            self.root = node

    def push(self, kind: str, **attrs: Any) -> None:
        node: Dict[str, Any] = {"kind": kind}
        for k, v in attrs.items():
            if v is not None:
                node[k] = v
        self._attach(node)
        self._stack.append(node)

    def pop(self, verdict: int) -> None:
        node = self._stack.pop()
        node["verdict"] = _VERDICTS.get(verdict, str(verdict))

    def leaf(self, kind: str, verdict: int, **attrs: Any) -> None:
        self.push(kind, **attrs)
        self.pop(verdict)

    def set(self, key: str, value: Any) -> None:
        if self._stack:
            self._stack[-1][key] = value


# ---------------------------------------------------------------------------
# Explain entry point
# ---------------------------------------------------------------------------


def explain_relationship(
    oracle,
    r,
    *,
    context: Optional[Dict[str, Any]] = None,
    witness: Optional[int] = None,
    revision: Optional[int] = None,
    cached: bool = False,
    now_us: Optional[int] = None,
    strategy: Optional[str] = None,
    max_nodes: int = 50_000,
) -> Dict[str, Any]:
    """One check's full resolution tree at one pinned oracle.

    ``witness`` (a device witness code, engine/flat.py armed kernel)
    seeds the walk toward the branch the kernel proved winning;
    ``cached``/``revision`` record provenance for verdicts that were
    served from the verdict cache — the tree itself is always RE-DERIVED
    against the pinned revision's oracle, never trusted from the cache.
    Raises the armed ``explain.walk`` fault before building any state,
    so a retried walk can never observe a torn tree."""
    faults.fire("explain.walk")
    rec = Recorder(max_nodes=max_nodes)
    seed = seed_for(witness) if witness else None
    t0 = time.perf_counter()
    tri = oracle.check_relationship(
        r, context, now_us=now_us, recorder=rec, seed_branch=seed
    )
    dur_ms = (time.perf_counter() - t0) * 1000.0
    out: Dict[str, Any] = {
        "resource": f"{r.resource_type}:{r.resource_id}",
        "permission": r.resource_relation,
        "subject": (
            f"{r.subject_type}:{r.subject_id}#{r.subject_relation}"
            if r.subject_relation
            else f"{r.subject_type}:{r.subject_id}"
        ),
        "result": _VERDICTS[tri],
        "duration_ms": round(dur_ms, 4),
        "tree": rec.root,
    }
    if revision is not None:
        out["revision"] = int(revision)
    if cached:
        out["cached"] = True
    if strategy is not None:
        out["strategy"] = strategy
    if witness:
        out["witness"] = witness_name(witness)
        out["witness_level"] = witness_level(witness)
    if r.caveat_context:
        out["context"] = dict(r.caveat_context)
    if rec.truncated:
        out["truncated"] = True
    return out


# ---------------------------------------------------------------------------
# Parity helpers (tests + smoke): witness ⊆ oracle path
# ---------------------------------------------------------------------------


def tree_grant_kinds(tree: Optional[Dict[str, Any]]) -> set:
    """The node kinds appearing on DEFINITE-allowed subtrees — the
    oracle path a device witness must be contained in."""
    out: set = set()

    def walk(node: Optional[Dict[str, Any]]) -> None:
        if not node or node.get("verdict") != "allowed":
            return
        out.add(node["kind"])
        for c in node.get("children", ()):  # only allowed subtrees count
            walk(c)

    walk(tree)
    return out


def _root_relation_kinds(tree: Optional[Dict[str, Any]]) -> set:
    """Granting node kinds DIRECTLY under the root item's relation node
    (depth-0 leaf classes — the device's root-leaf site analogue)."""
    if not tree or tree.get("verdict") != "allowed":
        return set()
    if tree.get("kind") not in ("relation",):
        return set()
    return {
        c["kind"] for c in tree.get("children", ())
        if c.get("verdict") == "allowed"
    }


def witness_consistent(explained: Dict[str, Any], code: int) -> bool:
    """witness ⊆ oracle path: does the explain tree contain the branch
    class the device kernel claims won?

    - ``self``: the tree is the reflexive-identity grant;
    - ``direct``/``wildcard``: a definite direct/wildcard edge grant on
      the ROOT relation;
    - ``t_probe``/``userset``: a definite userset expansion on the root
      relation (the T-index and KU branches are device accelerations of
      userset × closure);
    - ``fold``: the fold tables pre-join the whole rewrite, so the
      oracle counterpart is ANY definite path — the verdict must be
      allowed;
    - ``rewrite``: allowed via the permission program (the root node is
      a permission, not a bare relation leaf).
    """
    b = witness_branch(code)
    tree = explained.get("tree")
    if explained.get("result") != "allowed":
        return b == WIT_NONE
    if b == WIT_NONE:
        return False  # an allowed device-definite verdict has a branch
    if b == WIT_SELF:
        return tree is not None and (
            tree.get("kind") == "self"
            or "self" in tree_grant_kinds(tree)
        )
    if b == WIT_FOLD or b == WIT_REWRITE:
        return tree is not None and tree.get("verdict") == "allowed"
    kinds = _root_relation_kinds(tree)
    if not kinds:
        # permission-rooted tree: the root-leaf device site answered a
        # permission slot that is also a stored relation only when the
        # root IS a relation; otherwise fall back to path containment
        kinds = tree_grant_kinds(tree)
    if b == WIT_DIRECT:
        return "direct" in kinds or "self" in kinds
    if b == WIT_WILDCARD:
        return "wildcard" in kinds
    if b in (WIT_TPROBE, WIT_USERSET):
        return "userset" in kinds or "memoized" in kinds
    return False

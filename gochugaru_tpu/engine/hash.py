"""Bucketed hash indexes: host-built, device-probed in O(bucket cap).

The round-2 engine answered every exact-match question with a ~17-step
lexicographic binary search (engine/device.py _lex_search) — 17 dependent
scalar gathers per probe is exactly the memory-latency-bound pattern TPUs
hate.  A bucketed hash index answers the same question in ``cap`` (usually
≤ 4) data-independent steps: hash the key, gather the bucket's row-index
range, compare ``cap`` candidate rows.  Every step is a full-batch-wide
vectorized gather, so XLA emits a handful of fused gather/compare ops per
probe site regardless of table size.

Layout (host build, all vectorized numpy):
- keys live in the caller's existing sorted int32 columns (NOT copied —
  the index stores only a permutation, halving HBM at 100M edges);
- ``rows`` is the permutation grouping row indices by bucket;
- ``off[b]:off[b+1]`` delimits bucket ``b``'s slice of ``rows``;
- ``cap`` is the true max bucket size; the build doubles the table until
  ``cap`` ≤ ``target_cap`` (duplicate full keys bound this from below, so
  growth stops at ``max_factor`` × entries and accepts the larger cap).

The device probe recomputes the same 32-bit mix (mix32 is written against
the array-API surface shared by numpy and jax.numpy, so host and device
hashes agree bit-for-bit) and unrolls ``cap`` gather+compare steps.

No reference counterpart: gochugaru delegates lookups to SpiceDB's
datastore indexes (client/client.go:238-266); this is their on-device
replacement.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

_FNV_OFFSET = 2166136261
_FNV_PRIME = 16777619


def mix32(cols: Sequence, xp=np):
    """FNV-1a over int32 words + murmur3 finalizer, in uint32 wrap-around
    arithmetic.  Identical on numpy and jax.numpy inputs."""
    h = xp.uint32(_FNV_OFFSET)
    for c in cols:
        h = (h ^ c.astype(xp.uint32)) * xp.uint32(_FNV_PRIME)
    h = h ^ (h >> xp.uint32(16))
    h = h * xp.uint32(0x85EBCA6B)
    h = h ^ (h >> xp.uint32(13))
    h = h * xp.uint32(0xC2B2AE35)
    h = h ^ (h >> xp.uint32(16))
    return h


@dataclass
class HashIndex:
    """Bucket offsets + row permutation over the caller's key columns."""

    off: np.ndarray  # int32[size + 1]
    rows: np.ndarray  # int32[max(n, 1)]
    size: int  # pow2 bucket count
    cap: int  # max bucket occupancy (device probe unroll count)
    n: int  # number of entries


def _ceil_pow2(n: int, minimum: int = 8) -> int:
    m = minimum
    while m < n:
        m <<= 1
    return m


def build_hash(
    key_cols: Sequence[np.ndarray],
    *,
    target_cap: int = 4,
    min_size: int = 8,
    max_factor: int = 8,
    lean: bool = False,
) -> HashIndex:
    """Index the rows of lock-step int32 key columns by hash bucket.

    The hot path is native (native/sort.py hash_index32): one fused
    mask/histogram/prefix/stable-scatter pass replaces the
    mask→astype→bincount→argsort→cumsum chain, producing bit-identical
    ``rows``/``off`` (a stable counting sort by bucket IS
    np.argsort(bucket, kind="stable")).  The numpy fallback below is the
    reference implementation the parity test pins the native path to."""
    from ..native.sort import hash_index32, mix32_native

    n = int(key_cols[0].shape[0]) if key_cols else 0
    if n == 0:
        size = min_size
        return HashIndex(
            off=np.zeros(size + 1, np.int32),
            rows=np.zeros(1, np.int32),
            size=size,
            cap=1,
            n=0,
        )
    cols = [np.ascontiguousarray(c, np.int32) for c in key_cols]
    h_full = mix32_native(cols)
    if h_full is None:
        h_full = mix32(cols, np)
    # lean (HBM-packed) sizing starts at ~1 entry/bucket instead of 0.5:
    # the probe cap absorbs the deeper buckets, the offsets array halves
    size = _ceil_pow2(n if lean else 2 * n, min_size)
    # growth chases a small max bucket, but the max of n Poisson draws
    # grows with log n: beyond ~16M rows target_cap=4 is statistically
    # unreachable and doubling would only balloon the offsets array (the
    # 100M-edge table would hit 2^31 buckets) — freeze size and accept
    # the larger probe cap instead
    limit = size if n > (1 << 24) else size * max_factor
    got = hash_index32(h_full, size)
    if got is not None:
        rows, off, cap = got
        while cap > target_cap and size < limit:
            size <<= 1
            rows, off, cap = hash_index32(h_full, size)
        return HashIndex(off=off, rows=rows, size=size, cap=cap, n=n)
    while True:
        h = (h_full & np.uint32(size - 1)).astype(np.int64)
        counts = np.bincount(h, minlength=size)
        cap = int(counts.max())
        if cap <= target_cap or size >= limit:
            break
        size <<= 1
    rows = np.argsort(h, kind="stable").astype(np.int32)
    off = np.zeros(size + 1, np.int64)
    np.cumsum(counts, out=off[1:])
    return HashIndex(
        off=off.astype(np.int32), rows=rows, size=size, cap=cap, n=n
    )


@dataclass
class RangeIndex:
    """key → contiguous row range [lo, hi) in a key-sorted table.

    The group keys/bounds are materialized per distinct key and themselves
    hash-indexed, so a range lookup is one 1-column probe + two payload
    gathers instead of two binary searches."""

    gk: np.ndarray  # int32[G] distinct keys
    glo: np.ndarray  # int32[G] range start in the underlying table
    ghi: np.ndarray  # int32[G] range end
    index: HashIndex  # over gk

    @property
    def max_run(self) -> int:
        return int((self.ghi - self.glo).max()) if self.gk.shape[0] else 0


def build_range_hash(k: np.ndarray, **kw) -> RangeIndex:
    """Build a RangeIndex over a column already sorted ascending (group
    boundaries via the native sorted-runs pass; numpy mask fallback)."""
    from ..native.sort import sorted_runs

    n = int(k.shape[0])
    if n == 0:
        z = np.zeros(0, np.int32)
        return RangeIndex(gk=z, glo=z, ghi=z, index=build_hash([], **kw))
    starts = sorted_runs(k)
    ends = np.concatenate([starts[1:], np.asarray([n])])
    gk = np.ascontiguousarray(k[starts], np.int32)
    return RangeIndex(
        gk=gk,
        glo=starts.astype(np.int32),
        ghi=ends.astype(np.int32),
        index=build_hash([gk], **kw),
    )


# ---------------------------------------------------------------------------
# device-side probes (traced; arrays may be jnp, shapes arbitrary)
# ---------------------------------------------------------------------------


def take_in_bounds(a, i):
    """Gather with mode=promise_in_bounds: for indices that are in range
    BY CONSTRUCTION (hash & mask, clipped slots, row ids), skipping the
    per-gather negative-index normalization chains XLA otherwise emits.
    Callers must clip/mask — out-of-range indices are undefined behavior."""
    return a.at[i].get(mode="promise_in_bounds")


def _probe_rows_impl(off, rows, key_cols, q_cols, cap: int, n: int):
    import jax.numpy as jnp

    take = take_in_bounds

    size = off.shape[0] - 1
    h = (mix32(q_cols, jnp) & jnp.uint32(size - 1)).astype(jnp.int32)
    start = take(off, h)
    end = take(off, h + 1)
    found = jnp.full(jnp.shape(h), -1, jnp.int32)
    last = max(n - 1, 0)
    for j in range(cap):
        slot = start + j
        valid = slot < end
        idx = take(rows, jnp.clip(slot, 0, last))
        hit = valid
        for kc, qc in zip(key_cols, q_cols):
            hit = hit & (take(kc, idx) == qc)
        found = jnp.where((found < 0) & hit, idx, found)
    return found


_probe_rows_jit = None


def probe_rows(off, rows, key_cols: Sequence, q_cols: Sequence, cap: int, n: int):
    """Row index of the entry whose key columns equal q_cols, else -1.
    All q_cols share an arbitrary broadcast shape; the probe is elementwise
    over it.  ``cap``/``n`` are static (from the host HashIndex).  The body
    is a shared jitted subcomputation: a kernel with dozens of probe sites
    traces/compiles each (table, shape) signature once."""
    global _probe_rows_jit
    if _probe_rows_jit is None:
        import jax

        _probe_rows_jit = jax.jit(_probe_rows_impl, static_argnums=(4, 5))
    return _probe_rows_jit(off, rows, tuple(key_cols), tuple(q_cols), cap, n)


def probe_range(ri_arrays, cap: int, n: int, q):
    """Range [lo, hi) for key ``q`` in a RangeIndex; (0, 0) on miss.
    ``ri_arrays`` is the dict of device arrays for one RangeIndex with keys
    'gk', 'glo', 'ghi', 'off', 'rows'."""
    import jax.numpy as jnp

    gi = probe_rows(
        ri_arrays["off"], ri_arrays["rows"], (ri_arrays["gk"],), (q,), cap, n
    )
    gic = jnp.clip(gi, 0, max(n - 1, 0))
    hit = gi >= 0
    lo = jnp.where(hit, take_in_bounds(ri_arrays["glo"], gic), 0)
    hi = jnp.where(hit, take_in_bounds(ri_arrays["ghi"], gic), 0)
    return lo, hi


# ---------------------------------------------------------------------------
# block-slice layout: bucket-ordered interleaved tables
# ---------------------------------------------------------------------------
#
# The scatter probes above cost 2 + cap·(1 + nkey) independent 1-D gathers
# per site — dozens of scattered 32-bit reads per query.  TPUs gather at
# ~one row per cycle regardless of width, so the TPU-shaped layout stores
# each bucket's entries CONTIGUOUSLY with keys and payloads interleaved:
# one [cap, w] dynamic-slice per query fetches the whole bucket (a single
# HBM line or two), and every compare afterwards is elementwise VPU work.
# Probe cost per site drops to 2 gathers (bucket offset + block) total.


def interleave_buckets(
    h: HashIndex, cols: Sequence[np.ndarray], pad: int = 64,
    quantum: Optional[int] = None,
) -> np.ndarray:
    """Bucket-ordered interleaved matrix int32[n_pad, w]: row j holds
    ``cols[:][h.rows[j]]``.  Padded to pow2(n + max(pad, h.cap)) rows of -1
    so a slice of up to ``max(pad, h.cap)`` rows starting at any real
    bucket offset stays in bounds without clipping (padded keys are -1 and
    match nothing).  Callers slicing more than ``h.cap`` rows must pass
    their slice cap as ``pad`` — slice_blocks' clamp would otherwise SHIFT
    the block and break the lane↔row mapping.

    ``quantum`` replaces the pow2 round with round-up-to-a-multiple (the
    slice-safety pad is kept either way): big rebuilt-per-prepare tables
    (the T join — up to 2x pow2 waste at tens of millions of rows) trade
    the coarse shape bucketing for near-exact residency; delta chains
    never reshape base tables, so the retrace bound this table pays is
    one compile per FULL prepare — which a fresh pow2 shape would
    usually pay anyway."""
    from ..native.sort import fill_interleaved

    w = max(len(cols), 1)
    n = int(h.rows.shape[0]) if h.n else 0
    need = max(n, 1) + max(pad, h.cap)
    n_pad = (
        _ceil_pow2(need) if quantum is None else -(-need // quantum) * quantum
    )
    # pad rows get -1; data rows are fully overwritten below, so only the
    # tail needs the fill (a 2-col 30M-row table skips a 256MB memset)
    out = np.empty((n_pad, w), np.int32)
    out[n:] = -1
    if h.n:
        if not fill_interleaved(out, cols, h.rows):
            for j, c in enumerate(cols):
                out[:n, j] = np.ascontiguousarray(c, np.int32)[h.rows]
    return out


def interleave_rows(
    cols: Sequence[np.ndarray], pad: int = 64, pad_fill: int = -1
) -> np.ndarray:
    """Row-order interleaved matrix int32[n_pad, w] over lock-step columns
    (for range views whose rows are already grouped contiguously by key).
    Padded to pow2(n + pad) rows of ``pad_fill``; ``pad`` must be ≥ the
    largest row-slice cap any probe site uses (slice_blocks clamps starts,
    which would silently shift an undersized table's lane↔row mapping)."""
    from ..native.sort import fill_interleaved

    w = max(len(cols), 1)
    n = int(cols[0].shape[0]) if cols else 0
    n_pad = _ceil_pow2(max(n, 1) + max(pad, 1))
    out = np.empty((n_pad, w), np.int32)
    out[n:] = pad_fill
    if n and not fill_interleaved(out, cols, None):
        for j, c in enumerate(cols):
            out[:n, j] = np.ascontiguousarray(c, np.int32)
    return out


def slice_blocks(tbl, start, cap: int):
    """Contiguous [cap, w] block per element of ``start`` (any shape):
    returns int32[..., cap, w].  ``start`` must satisfy 0 ≤ start ≤
    tbl.shape[0] - cap (interleave_* pad enough rows for any real bucket
    offset).

    The lowering is backend-dependent (measured on real silicon,
    tpu_attempts/micro_blocks.py): on TPU a vmapped dynamic_slice
    serializes to ~1.2us per block (0.75M blocks/s), while cap·w
    independent flat 1-D gathers run ~10x faster (7M/s) because TPU 1-D
    gathers pipeline many outstanding HBM loads.  Every other backend
    (CPU at ~80-95M blocks/s, and any backend this lowering was never
    measured on) keeps the fused dynamic_slice form.  The branch keys off
    the process default backend at trace time — an explicit
    jit(backend=...) override on a TPU host still traces the TPU form."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    w = tbl.shape[1]
    s = jnp.clip(start, 0, tbl.shape[0] - cap)
    if jax.default_backend() != "tpu":
        blk = jax.vmap(lambda s: lax.dynamic_slice(tbl, (s, 0), (cap, w)))(
            s.reshape(-1)
        )
        return blk.reshape(tuple(jnp.shape(start)) + (cap, w))
    flat = tbl.reshape(-1)
    # flat addressing can exceed int32 (n_pad·w > 2^31 at ~100M caveated
    # rows): widen the base to int64 there — the gathers themselves move
    # the same bytes, only the index math widens
    if tbl.shape[0] * w > 2**31 - 1:
        base = s.astype(jnp.int64) * w
    else:
        base = s * w
    cols = [
        take_in_bounds(flat, base + (j * w + k))
        for j in range(cap)
        for k in range(w)
    ]
    blk = jnp.stack(cols, axis=-1)
    return blk.reshape(tuple(jnp.shape(start)) + (cap, w))


# ---------------------------------------------------------------------------
# bucket-ALIGNED layout: the whole bucket is one table row
# ---------------------------------------------------------------------------
#
# The off+interleave layout above still pays 2 sequential gathers per
# probe (bucket offset, then block) and — worse — lets build_hash balloon
# the offsets array to 8x entries chasing cap<=4 (a 2.6M-entry fold table
# grew a 256MB off array).  On TPU the winning shape (measured:
# tpu_attempts/micro_blocks.py, ~48M probes/s vs 0.75M for vmapped
# dynamic_slice and 7M for flat gathers) is ONE row gather: store bucket
# b's entries IN row b of an int32[size, cap*w] matrix, padded with -1.
# Probe = hash -> tbl[h] -> compare, a single contiguous 64-128B fetch
# per query.
#
# The Poisson tail would force cap (and the whole matrix width) up to the
# fullest bucket, so entries beyond ``cap`` per bucket SPILL to a second,
# much smaller aligned table under a salted hash; the probe fetches both
# rows (2 gathers, still 24M+/s) and the kernel sees one concatenated
# candidate block.  Worlds whose duplicate-key multiplicity exceeds the
# spill cap fall back to the off+interleave layout (build returns None).

_SPILL_SALT = np.int32(np.uint32(0x9E3779B9).astype(np.int32))


def _level_salt(lvl: int) -> np.int32:
    """Per-stratum probe salt (level 0 unsalted; level 1 == the classic
    spill salt).  uint32 wrap-around so deep ladders don't overflow."""
    return np.int32(
        np.uint32((0x9E3779B9 * lvl) & 0xFFFFFFFF).astype(np.int32)
    )


@dataclass
class AlignedIndex:
    """Bucket-aligned probe table: a ladder of WIDTH-STRATIFIED levels.

    Level 0 holds a cap covering most entries; whatever overflows
    re-hashes (salted) into the next, much smaller level with its own
    cap — per-bucket width classes instead of one table-wide row width
    set by the fullest bucket (the round-5 99.9%-cover trick
    generalized; ``build_aligned``'s ``cover`` ladder picks the caps at
    prepare time).  The classic layout is the 2-level instance
    (primary + spill); ``tbl``/``cap``/``spill``/``spill_cap`` remain
    as views of levels 0/1 for it."""

    levels: List[Tuple[np.ndarray, int]]  # [(int32[size_i, cap_i*w], cap_i)]
    w: int
    n: int

    @property
    def tbl(self) -> np.ndarray:
        return self.levels[0][0]

    @property
    def cap(self) -> int:
        return self.levels[0][1]

    @property
    def spill(self) -> Optional[np.ndarray]:
        return self.levels[1][0] if len(self.levels) > 1 else None

    @property
    def spill_cap(self) -> int:
        return self.levels[1][1] if len(self.levels) > 1 else 0

    @property
    def caps(self) -> Tuple[int, ...]:
        """The width-class ladder (probe geometry; rides FlatMeta)."""
        return tuple(c for _, c in self.levels)

    @property
    def nbytes(self) -> int:
        return sum(t.nbytes for t, _ in self.levels)


def _aligned_fill(
    h: np.ndarray, cols: Sequence[np.ndarray], size: int, cap: int,
    counts: Optional[np.ndarray] = None,
):
    """Place entries into an int32[size, cap*w] matrix; returns
    (tbl, leftover_row_indices) where leftover rows did not fit their
    bucket's ``cap`` slots.  ``counts`` (bincount of ``h``) is reused
    when the caller already computed it."""
    from ..native.sort import hash_index32

    w = len(cols)
    n = int(h.shape[0])
    got = hash_index32(h.astype(np.uint32), size) if size <= 2**31 else None
    if got is not None:
        # native stable counting sort == np.argsort(h, kind="stable"),
        # with the exclusive bucket starts already materialized
        order, off32, _cap = got
        order = order.astype(np.int64)
        hs = h[order]
        off = off32[:-1].astype(np.int64)
    else:
        order = np.argsort(h, kind="stable")
        hs = h[order]
        if counts is None:
            counts = np.bincount(hs, minlength=size)
        off = np.zeros(size, np.int64)
        np.cumsum(counts[:-1], out=off[1:])
    rank = np.arange(n, dtype=np.int64) - off[hs]
    fits = rank < cap
    tbl = np.full((size, cap * w), -1, np.int32)
    rows_in = order[fits]
    slot = (rank[fits] * w).astype(np.int64)
    for j, c in enumerate(cols):
        tbl[hs[fits], slot + j] = np.ascontiguousarray(c, np.int32)[rows_in]
    return tbl, order[~fits]


def _cover_cap(counts: np.ndarray, n: int, start_cap: int, bound: int,
               q: float) -> int:
    """Smallest cap ≥ ``start_cap`` whose buckets hold ≥ q of the n
    entries, bounded — the per-level width-class choice."""
    cap_need = int(counts.max()) if counts.size else 1
    if cap_need <= start_cap:
        return min(start_cap, max(cap_need, 1)) if start_cap else 1
    hist = np.bincount(np.minimum(counts, cap_need))
    ge = np.cumsum(hist[::-1])[::-1]  # ge[j] = #buckets with count>=j
    coverage = np.cumsum(ge[1:])  # coverage[c-1] = entries held at cap c
    bound = min(bound, cap_need)
    c = max(start_cap, 1)
    while c < bound and coverage[c - 1] < q * n:
        c += 1
    return c


def build_aligned(
    key_cols: Sequence[np.ndarray],
    cols: Sequence[np.ndarray],
    *,
    target_cap: int = 4,
    spill_max_cap: int = 16,
    min_size: int = 8,
    max_bytes: Optional[int] = None,
    cover: Sequence[float] = (0.999,),
) -> Optional[AlignedIndex]:
    """Bucket-aligned index over lock-step int32 columns (``key_cols``
    must be a prefix of ``cols`` — the probe compares them in order).

    ``cover`` is the width-stratification ladder: level i's cap is the
    smallest covering ``cover[i]`` of its entries; whatever overflows
    re-hashes (level-salted) into the next level, and a FINAL fit-all
    level closes the ladder.  ``cover=(0.999,)`` is the classic
    primary+spill pair; ``(0.99, 0.999)`` trades a narrower primary row
    (most of the table's bytes) for one extra mid level that still
    probes with a single row gather.  Returns None when the layout
    doesn't fit (final-level tail too deep for ``spill_max_cap`` — e.g.
    one full key duplicated beyond every cap — or ``max_bytes``
    exceeded): callers fall back to the off+interleave layout."""
    w = max(len(cols), 1)
    n = int(cols[0].shape[0]) if cols else 0
    if n == 0:
        return AlignedIndex(
            levels=[(np.full((min_size, target_cap * w), -1, np.int32),
                     target_cap)],
            w=w, n=0,
        )
    ckey = [np.ascontiguousarray(c, np.int32) for c in key_cols]
    ccols = [np.ascontiguousarray(c, np.int32) for c in cols]
    size = _ceil_pow2(max(min_size, (2 * n) // max(target_cap, 1)))
    if max_bytes is not None and size * target_cap * w * 4 > max_bytes:
        return None
    levels: List[Tuple[np.ndarray, int]] = []
    left = np.arange(0, 0, dtype=np.int64)  # current leftover row ids
    cur_key, cur_cols, cur_n = ckey, ccols, n
    for lvl, q in enumerate(tuple(cover) + (None,)):
        if lvl > 0:
            cur_key = [ckey[0][left] ^ _level_salt(lvl)] + [
                c[left] for c in ckey[1:]
            ]
            cur_cols = [c[left] for c in ccols]
            cur_n = int(left.shape[0])
            if cur_n == 0:
                break
            size = _ceil_pow2(max(min_size, cur_n))
        h_full = mix32(cur_key, np)
        if q is None:
            # final level: must hold every remaining entry (grow until
            # the fullest bucket fits spill_max_cap, else unfit)
            while True:
                h = (h_full & np.uint32(size - 1)).astype(np.int64)
                cap = int(np.bincount(h, minlength=size).max())
                if cap <= spill_max_cap:
                    break
                if size >= _ceil_pow2(8 * cur_n):
                    return None  # duplicate-heavy tail: aligned unfit
                size <<= 1
            tbl, over = _aligned_fill(h, cur_cols, size, cap)
            if over.shape[0]:
                return None
            levels.append((tbl, cap))
            break
        h = (h_full & np.uint32(size - 1)).astype(np.int64)
        counts = np.bincount(h, minlength=size)
        # level 0 keeps the classic hot-key bound (3x target); deeper
        # levels start at 1 — their whole point is a narrow width class
        cap = _cover_cap(
            counts, cur_n,
            target_cap if lvl == 0 else 1,
            spill_max_cap if lvl else min(spill_max_cap, 3 * target_cap),
            q,
        )
        if lvl == 0 and max_bytes is not None and size * cap * w * 4 > max_bytes:
            cap = target_cap
        tbl, over = _aligned_fill(h, cur_cols, size, cap, counts=counts)
        levels.append((tbl, cap))
        left = left[over] if lvl > 0 else over
        if left.shape[0] == 0:
            break
    out = AlignedIndex(levels=levels, w=w, n=n)
    if max_bytes is not None and out.nbytes > max_bytes:
        return None
    return out


def probe_aligned(tbls: Sequence, caps: Sequence[int], w: int, q_cols):
    """Candidate block int32[..., sum(caps), w] for the bucket of
    ``q_cols`` — ONE row gather per width-stratum level (each salted
    with its level index).  Padded slots hold -1 and match nothing;
    same-key entries land in the same bucket of SOME level, so callers
    just compare key columns exactly."""
    import jax.numpy as jnp

    blks = []
    for lvl, (tbl, cap) in enumerate(zip(tbls, caps)):
        if lvl == 0:
            qs = tuple(q_cols)
        else:
            qs = (q_cols[0] ^ jnp.int32(_level_salt(lvl)),) + tuple(
                q_cols[1:]
            )
        h = (
            mix32(qs, jnp) & jnp.uint32(tbl.shape[0] - 1)
        ).astype(jnp.int32)
        blks.append(
            take_in_bounds(tbl, h).reshape(jnp.shape(h) + (cap, w))
        )
    return blks[0] if len(blks) == 1 else jnp.concatenate(blks, axis=-2)


def probe_block(off, tbl, cap: int, q_cols: Sequence):
    """Bucket block for the hash of ``q_cols``: int32[..., cap, w].

    The block starts at the bucket's first entry and spans ``cap`` rows
    (the build's max bucket occupancy), so every entry of the bucket is in
    the block; overshoot rows belong to LATER buckets and cannot equal the
    query key (equal keys hash to the same bucket), so callers just compare
    key columns exactly — no per-slot validity mask is needed."""
    import jax.numpy as jnp

    size = off.shape[0] - 1
    h = (mix32(q_cols, jnp) & jnp.uint32(size - 1)).astype(jnp.int32)
    start = take_in_bounds(off, h)
    return slice_blocks(tbl, start, cap)

"""One masked-SpMM sparse core: multi-hop lookups, checks, and the fold
T-join as instances of a single batched semiring primitive.

The engine grew three hand-built kernel families — the forward check
probes (engine/flat.py), the reverse frontier SpMV (engine/spmv.py),
and the factored fold T-join (engine/fold.py) — that are all the same
computation: a masked sparse matrix product over the relation graph,

    C = M .* (A ⊕.⊗ B)

with the semiring multiply ⊗ = the packed caveat/expiry gate (an edge
contributes only while live and unconditionally resolvable — the same
``decode_block`` filter the Check kernel fuses into its gathers), the
add ⊕ = short-circuited max/OR (a grant is a grant; until-values reduce
by max), and the mask M = the seen-set bitmaps plus the schema-level
type-safety pruning tables (RedisGraph runs a whole graph database on
exactly this GraphBLAS reduction, arXiv:1905.01294; Graphulo benchmarks
the server-side kernels at database scale, arXiv:1609.08642).

This module makes the primitive explicit and re-expresses the families
on it:

- **Fused multi-hop lookups** (the tentpole): LookupResources /
  LookupSubjects run their WHOLE frontier fixpoint — up to
  ``spmm_rounds`` hops — in ONE pinned device dispatch.  The frontier
  is carried on-device between hops at a fixed pow2 capacity, dedup is
  on-device uint32 bitmaps (the ⊕ short-circuit: a key contributes
  once), and each hop reuses the spmv probe/emission bodies verbatim —
  one hop IS one masked SpMV, the K-hop program is the SpMM.  The host
  only seeds, paginates, and resolves cursors.  This removes the
  per-hop dispatch floor bench8 measures as 0.04M mixed-user
  candidates/s against 1.50M/s bulk: a ~1k-resource answer pays ONE
  dispatch instead of 2·hops.
- **Overflow honesty**: every fixed capacity (frontier width, per-round
  emission, candidate buffer, round budget) has an on-device overflow
  flag; an overflowing query falls back to the looped spmv path — which
  is also the streaming path bulk answers want — so the fused program
  trades dispatch count for coverage, never correctness.
- **The fold T-join** (``tjoin_spmm``): the userset⋈closure join that
  builds flat.py's T-index is the HOST instance of the same primitive
  over the (min, max) until-semiring — ⊗ intersects validity windows,
  ⊕ keeps the widest — produced by a generic sorted-operand product
  instead of a bespoke kernel.
- **Checks**: the flat probe kernel is the 1-hop degenerate instance
  (frontier = the query batch, one masked gather+gate per probe site);
  it already shares the packed gate decode and, through
  engine/latency.py, the (snapshot, meta, tier) pinned-executable
  discipline this module's fused programs follow.

Parity: ``EngineConfig.spmm`` (default on) is the
``flat_packed=False``-style lever — off reproduces the looped spmv path
and the bespoke ``t_join_core`` byte-for-byte; the fused answers are
asserted bitwise-equal to both the legacy paths and the host walker
(tests/test_spmm.py).  Sharded snapshots keep the owner-routed looped
hop path (parallel/sharded.py ``lookup_hops_for``) — routing happens
per hop batch there, and the fused single-chip program must not change
that contract.

Counters: ``spmm.dispatches`` (fused program launches — a ≥2-hop
lookup answers with exactly ONE), ``spmm.fallbacks`` (overflows to the
looped path), and the ``spmm.dispatch`` fault site (utils/faults.py)
fire under the client's retry envelope exactly like ``lookup.dispatch``.
Fused programs register with the PR-12 cost ledger (utils/perf.py,
kind ``spmm``) so ``/perf`` and the roofline columns attribute their
gathered bytes.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..utils import faults, metrics
from .hash import _ceil_pow2

_mt = metrics.default

#: host-side pad widths of the fused programs' seed arguments (static,
#: so every query of a geometry shares ONE compiled program)
_SEED_KEYS = 4
_SEED_NODES = 2

#: int32 sentinel marking dead lanes in on-device pools (sorts last)
_SENT = (1 << 31) - 1


# ---------------------------------------------------------------------------
# the host instance: the fold T-join as a sorted-operand semiring product
# ---------------------------------------------------------------------------


def masked_semiring_spmm(
    a_i: np.ndarray, a_k: np.ndarray, a_v: np.ndarray,
    b_k: np.ndarray, b_j: np.ndarray, b_planes: Tuple[np.ndarray, ...],
    cap_rows: int,
) -> Optional[Tuple[np.ndarray, ...]]:
    """C = (A ⊕.⊗ B) + A⊗I over sorted sparse operands on the host:
    A's rows are (i, k, v), B's are (k, j, plane-values); ⊗ =
    ``np.minimum`` (until-window intersection), ⊕ = per-(i, j) max
    (the widest surviving window wins), and the identity term keeps A's
    own (i, k) rows riding along (the direct group entries of the
    T-index).  The mask is the size gate: the product is sized with two
    searchsorted passes BEFORE materializing, and ``None`` past
    ``cap_rows`` declines (a popular k with a huge B in-degree must
    disable the index, not OOM).  Returns (C_i, C_j, *plane-maxima)."""
    from ..store.closure import _expand_join

    order = np.argsort(b_k, kind="stable")
    b_sorted = b_k[order]
    join_rows = int(
        (
            np.searchsorted(b_sorted, a_k, "right")
            - np.searchsorted(b_sorted, a_k, "left")
        ).sum()
    )
    if join_rows + a_k.shape[0] > cap_rows:
        return None
    reps, ii = _expand_join(b_sorted, a_k)
    jj = order[ii]
    out_i = np.concatenate([a_i, a_i[reps]])
    out_j = np.concatenate([a_k, b_j[jj]])
    planes = [
        np.concatenate([a_v, np.minimum(a_v[reps], p[jj])]) for p in b_planes
    ]
    o2 = np.lexsort((out_j, out_i))
    out_i, out_j = out_i[o2], out_j[o2]
    first = np.ones(out_i.shape[0], bool)
    first[1:] = (out_i[1:] != out_i[:-1]) | (out_j[1:] != out_j[:-1])
    st = np.nonzero(first)[0]
    return (
        out_i[first], out_j[first],
        *[np.maximum.reduceat(p[o2], st) for p in planes],
    )


def tjoin_spmm(
    k1: np.ndarray, pe: np.ndarray, w: np.ndarray,
    cl_k1: np.ndarray, cl_k2: np.ndarray,
    c_d: np.ndarray, c_p: np.ndarray, cap_rows: int,
) -> Optional[Tuple[np.ndarray, ...]]:
    """The T-index join (flat.py ``_tindex_join``) as the host SpMM
    instance: A = userset entries (row-key k1, group-key pe, until w),
    B = the membership closure by target, planes = (definite, possible)
    untils.  Byte-for-byte the output of fold.py ``t_join_core`` — the
    bespoke kernel stays as the ``EngineConfig.spmm=False`` parity
    oracle (tests/test_spmm.py asserts equality on fuzzed worlds)."""
    return masked_semiring_spmm(
        k1, pe, w, cl_k2, cl_k1, (c_d, c_p), cap_rows
    )


# ---------------------------------------------------------------------------
# on-device set algebra (fixed shapes; the ⊕ short-circuit as bitmaps)
# ---------------------------------------------------------------------------


def _bm_mark(bm, ids, valid):
    """Set ``ids``' bits (ids sorted-unique among ``valid`` — distinct
    (word, bit) pairs, so the scatter-add is an exact OR)."""
    import jax.numpy as jnp

    word = jnp.where(valid, ids >> 5, 0)
    bit = jnp.where(
        valid,
        jnp.uint32(1) << (ids & 31).astype(jnp.uint32),
        jnp.uint32(0),
    )
    return bm.at[word].add(bit)


def _bm_unseen(bm, ids, valid):
    """``valid`` entries whose bit is still clear."""
    import jax.numpy as jnp

    word = jnp.where(valid, ids >> 5, 0)
    got = (bm[word] >> (jnp.where(valid, ids, 0) & 31).astype(jnp.uint32)) & 1
    return valid & (got == 0)


def _fresh(pool, valid, bm):
    """Sorted-unique not-yet-seen subset of ``pool`` (marked into
    ``bm``): returns (sorted pool, fresh mask, bm').  The device twin of
    spmv._Seen.fresh — dead lanes ride as the sort-last sentinel."""
    import jax.numpy as jnp

    x = jnp.sort(jnp.where(valid, pool, _SENT))
    ok = x != _SENT
    uniq = ok & jnp.concatenate(
        [jnp.ones((1,), bool), x[1:] != x[:-1]]
    )
    fresh = _bm_unseen(bm, x, uniq)
    return x, fresh, _bm_mark(bm, x, fresh)


def _compact(vals, mask, cap):
    """Masked entries packed order-stable into a fixed [cap] buffer
    (-1 fill): returns (buffer, count, overflowed)."""
    import jax.numpy as jnp

    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    cnt = jnp.sum(mask.astype(jnp.int32))
    out = jnp.full(cap, -1, jnp.int32).at[
        jnp.where(mask & (pos < cap), pos, cap)
    ].set(jnp.where(mask, vals, 0), mode="drop")
    return out, cnt, cnt > cap


def _append(buf, n, vals, mask, cap):
    """Masked entries appended at offset ``n`` of a fixed [cap] buffer:
    returns (buffer, n', overflowed)."""
    import jax.numpy as jnp

    pos = n + jnp.cumsum(mask.astype(jnp.int32)) - 1
    cnt = jnp.sum(mask.astype(jnp.int32))
    buf = buf.at[jnp.where(mask & (pos < cap), pos, cap)].set(
        jnp.where(mask, vals, 0), mode="drop"
    )
    return buf, jnp.minimum(n + cnt, cap), n + cnt > cap


# ---------------------------------------------------------------------------
# the fused K-hop programs (per-FlatMeta, cached on the engine)
# ---------------------------------------------------------------------------


class SpmmKernels:
    """The fused K-hop lookup programs of one FlatMeta geometry: the
    spmv probe/emission bodies composed under ``lax.while_loop``, all
    shapes static — one compiled executable per (meta, direction,
    snapshot table shapes), pinned the way engine/latency.py pins its
    small-batch tiers.  ``traces`` counts trace entries per direction
    (the no-retrace assertion reads it)."""

    def __init__(self, meta, config) -> None:
        import jax

        self.meta = meta
        self.F = _ceil_pow2(int(config.spmm_frontier), 256)
        self.E = _ceil_pow2(int(config.spmm_emit), 1024)
        self.C = int(config.spmm_candidates)
        self.K = int(config.spmm_rounds)
        self.traces = {"res": 0, "subj": 0}
        self._kern = None  # bound lazily (FrontierKernels of the meta)
        self._res_fn = None
        self._subj_fn = None
        self._cost_reg: set = set()
        self._jit = jax.jit

    def bind(self, kern) -> None:
        """Attach the meta's FrontierKernels (the raw probe/emit bodies
        the fused programs are composed from) and build the jits."""
        if self._kern is not None:
            return
        self._kern = kern
        self._res_fn = self._jit(self._build_resources())
        if self.meta.has_fw:
            self._subj_fn = self._jit(self._build_subjects())

    # -- reverse reachability: LookupResources ---------------------------
    def _build_resources(self):
        import jax.numpy as jnp
        from jax import lax

        kern = self._kern
        meta = self.meta
        N, S1 = meta.N, meta.S1
        logN = N.bit_length() - 1
        F, E, C, K = self.F, self.E, self.C, self.K
        # reverse arrows are fan-in ~1 per frontier node (a folder has
        # one parent), so the arrow emit runs at a fraction of the
        # userset emit — the emit lanes are the program's dominant
        # per-round cost and overflow just falls back to the looped path
        Ea = max(E // 4, 512)
        WK = (N * S1 + 31) // 32
        WN = (N + 31) // 32
        runs_rv = kern.raw_runs["rv"]
        emit_rv = kern.raw_emits["rv"]
        runs_ra = kern.raw_runs["ra"]
        emit_ra = kern.raw_emits["ra"]

        def fn(rv_off, rv_off_a, rvx, ra_off, ra_off_a, rax,
               nt_d, k2p1_d, chain_ok_d, child_ok_d, perm_tab_d,
               seed_keys, seed_nodes, rtid, now):
            self.traces["res"] += 1  # trace-time only: the pin witness
            n_types = child_ok_d.shape[0] - 1
            n_k1 = k2p1_d.shape[0]

            def rowt(nodes, valid):
                t = jnp.where(
                    valid, nt_d[jnp.where(valid, nodes, 0)], jnp.int32(-1)
                )
                return jnp.where(t < 0, n_types, t), t

            bm_k = _bm_mark(
                jnp.zeros(WK, jnp.uint32), seed_keys, seed_keys >= 0
            )
            bm_n = _bm_mark(
                jnp.zeros(WN, jnp.uint32), seed_nodes, seed_nodes >= 0
            )
            kf0 = jnp.full(F, -1, jnp.int32).at[: _SEED_KEYS].set(seed_keys)
            nf0 = jnp.full(F, -1, jnp.int32)

            def cond(c):
                kf, nf, _bk, _bn, _cd, _nc, ovf, r = c
                return (
                    (jnp.any(kf >= 0) | jnp.any(nf >= 0))
                    & ~ovf & (r < K)
                )

            def body(c):
                kf, nf, bm_k, bm_n, cand, ncand, ovf, r = c
                # one masked SpMV over the reverse userset view: which
                # (slot, resource) rows grant the frontier keys
                lo, ln = runs_rv(rv_off, rv_off_a, rvx, kf)
                rows, live = emit_rv(rvx, lo, ln, jnp.int32(0), now, E)
                ovf |= jnp.sum(ln) > E
                k1 = jnp.where(live, rows[:, 1], 0)
                res = k1 & jnp.int32(N - 1)
                slotd = k1 >> logN
                nk = k2p1_d[jnp.clip(slotd, 0, n_k1 - 1)].astype(jnp.int32)
                row_res, _t = rowt(res, live)
                chain = live & (nk > 0) & chain_ok_d[row_res, nk]
                ckeys = jnp.where(chain, res * jnp.int32(S1) + nk, -1)
                # one masked SpMV over the reverse arrows: parents of
                # the node frontier
                lo2, ln2 = runs_ra(ra_off, ra_off_a, rax, nf)
                rows2, live2 = emit_ra(rax, lo2, ln2, jnp.int32(0), now, Ea)
                ovf |= jnp.sum(ln2) > Ea
                par = jnp.where(live2, rows2[:, 1] & jnp.int32(N - 1), -1)
                # fresh nodes (⊕ short-circuit): candidates, arrow
                # children, permission-chain sources
                pool_n = jnp.concatenate(
                    [jnp.where(live, res, -1), par]
                )
                xn, freshn, bm_n = _fresh(pool_n, pool_n >= 0, bm_n)
                rown, tn = rowt(xn, freshn)
                cand, ncand, o1 = _append(
                    cand, ncand, xn, freshn & (tn == rtid), C
                )
                nf2, _cn, o2 = _compact(xn, freshn & child_ok_d[rown], F)
                pk = xn[:, None] * jnp.int32(S1) + perm_tab_d[rown]
                pkeys = jnp.where(
                    freshn[:, None] & (perm_tab_d[rown] > 0), pk, -1
                ).ravel()
                pool_k = jnp.concatenate([ckeys, pkeys])
                xk, freshk, bm_k = _fresh(pool_k, pool_k >= 0, bm_k)
                kf2, _ck, o3 = _compact(xk, freshk, F)
                return (
                    kf2, nf2, bm_k, bm_n, cand, ncand,
                    ovf | o1 | o2 | o3, r + 1,
                )

            kf, nf, bm_k, bm_n, cand, ncand, ovf, _r = lax.while_loop(
                cond, body,
                (kf0, nf0, bm_k, bm_n, jnp.zeros(C, jnp.int32),
                 jnp.int32(0), jnp.bool_(False), jnp.int32(0)),
            )
            converged = ~(jnp.any(kf >= 0) | jnp.any(nf >= 0))
            return cand, ncand, ovf | ~converged

        return fn

    # -- forward reachability: LookupSubjects ----------------------------
    def _build_subjects(self):
        import jax.numpy as jnp
        from jax import lax

        kern = self._kern
        meta = self.meta
        N, S1 = meta.N, meta.S1
        F, E, C, K = self.F, self.E, self.C, self.K
        WN = (N + 31) // 32
        runs_fw = kern.raw_runs["fw"]
        emit_fw = kern.raw_emits["fw"]
        runs_arg = kern.raw_runs["arg"]
        emit_arg = kern.raw_emits["arg"]
        arg_aligned = kern._arg_aligned

        def fn(fw_off, fw_off_a, fwx, arg_p, arx,
               nt_d, slot_e_d, e_k1d_d, slot_ts_d, ts_k1d_d,
               k2p1_raw_d, k1d_d, perm_raw_d,
               seed_nodes, stid, srel_slot, wc_node, now):
            self.traces["subj"] += 1  # trace-time only
            n_types = perm_raw_d.shape[0] - 1
            num_slots = k1d_d.shape[0]
            NSp = num_slots + 1
            ES = e_k1d_d.shape[0]
            TS = ts_k1d_d.shape[0]
            WP = (N * NSp + 31) // 32

            def rowt(nodes, valid):
                t = jnp.where(
                    valid, nt_d[jnp.where(valid, nodes, 0)], jnp.int32(-1)
                )
                return jnp.where(t < 0, n_types, t), t

            bm_n = _bm_mark(
                jnp.zeros(WN, jnp.uint32), seed_nodes, seed_nodes >= 0
            )
            nf0 = jnp.full(F, -1, jnp.int32).at[: _SEED_NODES].set(seed_nodes)
            pf0 = jnp.full(F, -1, jnp.int32)

            def cond(c):
                nf, pf = c[0], c[1]
                ovf, r = c[-2], c[-1]
                return (
                    (jnp.any(nf >= 0) | jnp.any(pf >= 0))
                    & ~ovf & (r < K)
                )

            def body(c):
                (nf, pf, bm_n, bm_p, bm_c, cand, ncand,
                 gsr, ngsr, wc, ovf, r) = c
                valid_n = nf >= 0
                rown, _tn = rowt(nf, valid_n)
                # forward arrow hop (the argx range view)
                children = jnp.full(E, -1, jnp.int32)
                if TS:
                    tok = valid_n[:, None] & slot_ts_d[rown]
                    akeys = jnp.where(
                        tok,
                        nf[:, None] + ts_k1d_d[None, :] * jnp.int32(N),
                        -1,
                    ).ravel()
                    if arg_aligned:
                        lo, ln = runs_arg(arg_p, akeys)
                    else:
                        lo, ln = runs_arg(*arg_p, akeys)
                    rowsa, livea = emit_arg(
                        arx, lo, ln, jnp.int32(0), now, E
                    )
                    ovf |= jnp.sum(ln) > E
                    children = jnp.where(livea, rowsa[:, 0], -1)
                # forward edge hop: node keys + rel-pair keys in ONE
                # masked SpMV over the fw view
                valid_p = pf >= 0
                g = jnp.where(valid_p, pf // NSp, 0)
                rr = jnp.where(valid_p, pf % NSp, 0)
                rowg, _tg = rowt(g, valid_p)
                is_perm = valid_p & perm_raw_d[
                    rowg, jnp.clip(rr, 0, num_slots - 1)
                ] & (rr < num_slots)
                kd = k1d_d[jnp.clip(rr, 0, num_slots - 1)].astype(jnp.int32)
                relm = valid_p & ~is_perm & (kd >= 0) & (rr < num_slots)
                fkeys2 = jnp.where(relm, kd * jnp.int32(N) + g, -1)
                if ES:
                    eok = valid_n[:, None] & slot_e_d[rown]
                    fkeys1 = jnp.where(
                        eok,
                        nf[:, None] + e_k1d_d[None, :] * jnp.int32(N),
                        -1,
                    ).ravel()
                    fkeys = jnp.concatenate([fkeys1, fkeys2])
                else:
                    fkeys = fkeys2
                lo2, ln2 = runs_fw(fw_off, fw_off_a, fwx, fkeys)
                rowsf, livef = emit_fw(fwx, lo2, ln2, jnp.int32(0), now, E)
                ovf |= jnp.sum(ln2) > E
                k2v = jnp.where(livef, rowsf[:, 1], 0)
                direct = livef & (k2v % jnp.int32(S1) == 0)
                dn = k2v // jnp.int32(S1)
                wc = wc | jnp.any(direct & (dn == wc_node) & (wc_node >= 0))
                # direct subjects: candidates (deduped on-device)
                rowd, td = rowt(dn, direct)
                cpool = jnp.where(
                    direct & (td == stid) & (srel_slot < 0), dn, -1
                )
                xc, freshc, bm_c = _fresh(cpool, cpool >= 0, bm_c)
                cand, ncand, o1 = _append(cand, ncand, xc, freshc, C)
                # userset subjects: raw (group, relation) pairs
                um = livef & ~direct
                r2 = k2p1_raw_d[
                    jnp.where(um, k2v % jnp.int32(S1), 0)
                ].astype(jnp.int32)
                pairc = jnp.where(
                    um & (r2 >= 0),
                    (k2v // jnp.int32(S1)) * jnp.int32(NSp) + r2,
                    -1,
                )
                xp, freshp, bm_p = _fresh(pairc, pairc >= 0, bm_p)
                pf2, _cp, o2 = _compact(xp, freshp, F)
                srm = freshp & (srel_slot >= 0) & (
                    xp % jnp.int32(NSp) == srel_slot
                )
                gsr, ngsr, o3 = _append(
                    gsr, ngsr, xp // jnp.int32(NSp), srm, C
                )
                # next node frontier: arrow children + permission-pair
                # sources (holders of g#p ⊆ expansion of g)
                pool_n = jnp.concatenate(
                    [children, jnp.where(is_perm, g, -1)]
                )
                xn, freshn, bm_n = _fresh(pool_n, pool_n >= 0, bm_n)
                nf2, _cn, o4 = _compact(xn, freshn, F)
                return (
                    nf2, pf2, bm_n, bm_p, bm_c, cand, ncand, gsr, ngsr,
                    wc, ovf | o1 | o2 | o3 | o4, r + 1,
                )

            (nf, pf, _bn, _bp, _bc, cand, ncand, gsr, ngsr, wc, ovf,
             _r) = lax.while_loop(
                cond, body,
                (
                    nf0, pf0, bm_n,
                    jnp.zeros(WP, jnp.uint32),
                    jnp.zeros(WN, jnp.uint32),
                    jnp.zeros(C, jnp.int32), jnp.int32(0),
                    jnp.zeros(C, jnp.int32), jnp.int32(0),
                    jnp.bool_(False), jnp.bool_(False), jnp.int32(0),
                ),
            )
            converged = ~(jnp.any(nf >= 0) | jnp.any(pf >= 0))
            return cand, ncand, gsr, ngsr, wc, ovf | ~converged

        return fn


def spmm_kernels_for(engine, meta) -> SpmmKernels:
    """Engine-level cache of the fused programs, keyed by meta — the
    same (snapshot, meta, tier) pin discipline engine/latency.py uses
    for CheckMany: geometry-identical snapshots share executables."""
    cache = engine.__dict__.setdefault("_spmm_kernels", {})
    k = cache.get(meta)
    if k is None:
        k = SpmmKernels(meta, engine.config)
        while len(cache) >= 8:
            cache.pop(next(iter(cache)))
        cache[meta] = k
    return k


# ---------------------------------------------------------------------------
# per-snapshot fused lookup server
# ---------------------------------------------------------------------------


def fused_ok(engine, st) -> bool:
    """Whether the fused K-hop path may serve this FrontierState.
    Sharded snapshots keep the owner-routed looped hops; key/pair
    domains must fit int32 (the on-device bitmap codes)."""
    cfg = engine.config
    if not getattr(cfg, "spmm", False):
        return False
    meta = st.meta
    if meta.sharded:
        return False
    num_slots = max(st.snap.num_slots, 1)
    if st.N * st.S1 >= 1 << 31 or st.N * (num_slots + 1) >= 1 << 31:
        return False
    return True


class FusedLookup:
    """One snapshot's fused-lookup server: the device constant tables
    (type map, pruning masks, permission chains) plus the dispatch
    wrappers.  Built by spmv.FrontierState when ``fused_ok``; answers
    are complete candidate sets from ONE dispatch, or ``None`` on
    overflow (the caller falls back to the looped path)."""

    def __init__(self, engine, st) -> None:
        import jax.numpy as jnp

        self.st = st
        self.kern = spmm_kernels_for(engine, st.meta)
        self.kern.bind(st.kern)
        N, S1 = st.N, st.S1
        snap = st.snap
        nt = np.full(N, -1, np.int32)
        nt[: snap.node_type.shape[0]] = snap.node_type.astype(np.int32)
        self.nt_d = jnp.asarray(nt)
        self.k2p1_d = jnp.asarray(st.k2p1_of_k1d.astype(np.int32))
        self.chain_ok_d = jnp.asarray(st.chain_ok)
        self.child_ok_d = jnp.asarray(st.child_ok)
        n_types = st.child_ok.shape[0] - 1
        # permission-userset chains only when the compiled schema has
        # any (the host gate: FrontierState.perm_chains)
        chains = st.perm_k2p1_of_tid if st.perm_chains else {}
        pmax = max([v.shape[0] for v in chains.values()] or [1])
        ptab = np.zeros((n_types + 1, pmax), np.int32)
        for t, k2p1 in chains.items():
            ptab[t, : k2p1.shape[0]] = k2p1.astype(np.int32)
        self.perm_tab_d = jnp.asarray(ptab)
        self._subj_ready = st.meta.has_fw and self.kern._subj_fn is not None
        if self._subj_ready:
            num_slots = max(snap.num_slots, 1)
            e_slot_raw = np.asarray(
                [s for s in st.meta.e_slots if st.k1d[s] >= 0], np.int64
            )
            ts_raw = np.asarray(
                [s for s in st.ts_slots if st.k1d[s] >= 0], np.int64
            )
            self.slot_e_d = jnp.asarray(
                st.slot_of_type[:, e_slot_raw]
                if e_slot_raw.size
                else np.zeros((n_types + 1, 0), bool)
            )
            self.e_k1d_d = jnp.asarray(
                st.k1d[e_slot_raw].astype(np.int32)
                if e_slot_raw.size else np.zeros(0, np.int32)
            )
            self.slot_ts_d = jnp.asarray(
                st.slot_of_type[:, ts_raw]
                if ts_raw.size
                else np.zeros((n_types + 1, 0), bool)
            )
            self.ts_k1d_d = jnp.asarray(
                st.k1d[ts_raw].astype(np.int32)
                if ts_raw.size else np.zeros(0, np.int32)
            )
            k2p1_raw = np.full(S1 + 1, -1, np.int32)
            for raw, d in enumerate(st.k2d):
                if d >= 0:
                    k2p1_raw[d + 1] = raw
            self.k2p1_raw_d = jnp.asarray(k2p1_raw)
            # pad the raw-slot→dense-k1 map to exactly num_slots so the
            # device pair encoding (g·(num_slots+1)+r) matches the host's
            k1p = np.full(num_slots, -1, np.int32)
            m = min(num_slots, st.k1d.shape[0])
            k1p[:m] = st.k1d[:m]
            self.k1d_d = jnp.asarray(k1p)
            self.perm_raw_d = jnp.asarray(
                np.vstack(
                    [st.perm_raw_table,
                     np.zeros((1, st.perm_raw_table.shape[1]), bool)]
                )
            )
        _ensure_report_section()

    # -- dispatch plumbing ----------------------------------------------
    def _dispatch(self, direction: str, fn, args):
        import jax

        # a fused launch IS a lookup dispatch: both sites fire, so
        # chaos/retry coverage armed on either exercises this path
        faults.fire("lookup.dispatch")
        faults.fire("spmm.dispatch")
        _mt.inc("spmm.dispatches")
        self._register_cost(direction, fn, args)
        return jax.device_get(fn(*args))

    def _register_cost(self, direction: str, fn, args) -> None:
        # per-SpmmKernels (= per-meta) guard, same as the spmv hop path
        if direction in self.kern._cost_reg:
            return
        self.kern._cost_reg.add(direction)
        from ..utils import perf as _perf

        kern = self.kern
        key = (
            f"fused-{direction};F={kern.F};E={kern.E};K={kern.K}"
            f";meta={hash(self.st.meta) & 0xFFFFFFFF:08x}"
        )
        _perf.register_cost_thunk(
            "spmm", key,
            lambda fn=fn, avals=_perf.avals_of(args): fn.lower(
                *avals
            ).compile(),
        )

    # -- LookupResources: the whole reverse fixpoint, one dispatch -------
    def resources(
        self, rtid: int, subj_node: int, srel_slot: int, wc_node: int,
        now_us: Optional[int],
    ) -> Optional[List[np.ndarray]]:
        import jax.numpy as jnp

        st = self.st
        N, S1 = st.N, st.S1
        seeds: List[int] = []
        if 0 <= subj_node < N:
            if srel_slot < 0:
                seeds.append(subj_node * S1)
            elif st.k2d[srel_slot] >= 0:
                seeds.append(subj_node * S1 + int(st.k2d[srel_slot]) + 1)
        if 0 <= wc_node < N:
            seeds.append(wc_node * S1)
        sk = np.full(_SEED_KEYS, -1, np.int32)
        uniq = sorted(set(seeds))[:_SEED_KEYS]
        sk[: len(uniq)] = uniq
        sn = np.full(_SEED_NODES, -1, np.int32)
        if 0 <= subj_node < N:
            sn[0] = subj_node
        blocks: List[np.ndarray] = []
        nt_shape = st.snap.node_type.shape[0]
        if 0 <= subj_node < nt_shape and (
            int(st.snap.node_type[subj_node]) == rtid
        ):
            blocks.append(np.asarray([subj_node], np.int64))
        cand, ncand, ovf = self._dispatch(
            "res", self.kern._res_fn,
            (
                *st.rv_args, *st.ra_args,
                self.nt_d, self.k2p1_d, self.chain_ok_d, self.child_ok_d,
                self.perm_tab_d,
                jnp.asarray(sk), jnp.asarray(sn),
                jnp.int32(rtid), st._now(now_us),
            ),
        )
        if bool(ovf):
            return None
        arr = np.asarray(cand[: int(ncand)], np.int64)
        if arr.size:
            blocks.append(arr)
        return blocks

    # -- LookupSubjects: the whole forward fixpoint, one dispatch --------
    def subjects(
        self, res_node: int, stid: int, srel_slot: int, wc_node: int,
        now_us: Optional[int],
    ) -> Optional[List[np.ndarray]]:
        if not self._subj_ready:
            return None
        import jax.numpy as jnp

        st = self.st
        N = st.N
        sn = np.full(_SEED_NODES, -1, np.int32)
        if 0 <= res_node < N:
            sn[0] = res_node
        arg_p = tuple(st.arg_args) if st.arg_aligned else st.arg_args
        cand, ncand, gsr, ngsr, wc, ovf = self._dispatch(
            "subj", self.kern._subj_fn,
            (
                *st.fw_args, arg_p, st.arx,
                self.nt_d, self.slot_e_d, self.e_k1d_d,
                self.slot_ts_d, self.ts_k1d_d,
                self.k2p1_raw_d, self.k1d_d, self.perm_raw_d,
                jnp.asarray(sn),
                jnp.int32(stid), jnp.int32(srel_slot),
                jnp.int32(wc_node), st._now(now_us),
            ),
        )
        if bool(ovf):
            return None
        blocks: List[np.ndarray] = []
        emitted: set = set()
        arr = np.asarray(cand[: int(ncand)], np.int64)
        if arr.size:
            blocks.append(arr)
            emitted.update(int(x) for x in arr)
        # trailing blocks, mirroring the walker/looped tail order
        nt = st.snap.node_type
        if srel_slot >= 0 and int(ngsr):
            gs = np.unique(np.asarray(gsr[: int(ngsr)], np.int64))
            gs = gs[(gs >= 0) & (gs < nt.shape[0])]
            gs = gs[nt[gs] == stid]
            gs = np.asarray(
                [g for g in gs if int(g) not in emitted], np.int64
            )
            if gs.size:
                blocks.append(gs)
                emitted.update(int(x) for x in gs)
        if (
            0 <= res_node < nt.shape[0]
            and int(nt[res_node]) == stid
            and res_node not in emitted
        ):
            blocks.append(np.asarray([res_node], np.int64))
            emitted.add(res_node)
        if bool(wc) and srel_slot < 0:
            subs = st.all_subjects()
            subs = subs[(subs >= 0) & (subs < nt.shape[0])]
            subs = subs[nt[subs] == stid]
            subs = np.asarray(
                [s for s in subs if int(s) not in emitted], np.int64
            )
            if subs.size:
                blocks.append(subs)
        return blocks


def fused_for(engine, st) -> Optional[FusedLookup]:
    """The FrontierState's fused server, or None when ineligible —
    the single construction gate spmv.py calls."""
    if not fused_ok(engine, st):
        return None
    return FusedLookup(engine, st)


# ---------------------------------------------------------------------------
# /perf visibility
# ---------------------------------------------------------------------------

_SECTION = [False]


def _ensure_report_section() -> None:
    """Ride the /perf payload (utils/perf.py report sections) with the
    fused core's serving counters — dispatches vs fallbacks is the
    fused-coverage ratio the roofline columns contextualize."""
    if _SECTION[0]:
        return
    _SECTION[0] = True
    from ..utils import perf as _perf

    def stats():
        return {
            "dispatches": _mt.counter("spmm.dispatches"),
            "fallbacks": _mt.counter("spmm.fallbacks"),
            "lookup_dispatches_looped": _mt.counter("lookup.dispatches"),
        }

    _perf.register_report_section("spmm", stats)

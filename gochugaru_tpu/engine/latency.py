"""Latency-mode execution path: warm small-batch dispatch with pinned
kernels and an honest per-stage budget.

The throughput path (engine/device.py check_batch / check_columns) is
shaped for giant pipelined batches: pow2 padding that tracks the batch,
lazily-jitted kernels, results fetched whenever the async queue drains.
That is the right shape for 131k-item bulk scans and the wrong shape for
the other half of the north-star metric — p99 < 2 ms — which is a
property of *interactive-sized* dispatches (the small CheckBulkPermissions
batches of the reference, client/client.go:238-266), where any retrace,
fresh allocation, or stream hiccup lands directly in the tail.

This path removes every per-dispatch variable cost it can:

- **pinned executables**: the flat kernel is AOT-lowered and compiled
  ONCE per (snapshot geometry, permission slots, batch tier, qctx shape)
  and the ``Compiled`` object is called directly — a pinned executable
  structurally cannot retrace, so ``compile_count`` is an assertable
  invariant (tests/test_latency_path.py), not a hope.  Pins are shared
  engine-wide across delta revisions whose table shapes are unchanged.
- **batch tiers**: batches pad to a SMALL fixed ladder of tiers
  (EngineConfig.latency_tiers, default 256/1024/4096) instead of the
  batch's own pow2 — a workload whose batch size jitters between 900
  and 1100 stays on ONE pinned kernel.  The ladder is any sorted list
  of sizes, pow2 or not: the offline tuner (gochugaru_tpu/tune) fits
  tiers to the measured occupancy histogram, and pins are keyed by the
  tier value so a tuned (192, 576, 4096) ladder keeps the zero-retrace
  invariant.
- **preallocated staging**: one host-side query-matrix buffer per tier,
  refilled in place (engine/flat.py fill_qm) — steady-state dispatch
  allocates no host arrays; the context-free qctx device singleton is
  reused from the engine cache.
- **buffer donation**: on TPU the query-matrix device buffer is donated
  to the executable (EngineConfig.latency_donate, auto), letting XLA
  alias it for outputs instead of allocating; off on CPU where the
  runtime cannot use the donation and warns.
- **budget breakdown**: every dispatch is timed in four stages — host
  lowering (query packing), H2D (staging transfer), kernel (blocked
  execution), D2H (result fetch) — published through utils/metrics.py
  as ``latency.{host_lower,h2d,kernel,d2h,dispatch}_s`` with live
  p50/p99, and kept on ``last_budget`` for harnesses.  When the 2 ms
  budget is missed, the breakdown says which stage ate it.

Correctness contract is identical to the throughput path: returns the
same (definite, possible, overflow) planes; callers resolve conditional
and overflowed items on the host oracle.  Anything the path cannot serve
(no flat tables, too many distinct permissions, batch beyond the top
tier) returns None and the caller falls back — the latency path narrows
latency, never coverage.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..utils import faults
from ..utils import metrics as _metrics
from ..utils import perf as _perf
from ..utils import trace as _trace
from . import pallas as _pallas
from .flat import QM_ROWS, fill_qm


def tier_for(tiers, B: int) -> Optional[int]:
    """Smallest tier in the ladder holding ``B``, or None (→ the
    throughput path).  Shared by LatencyPath routing and the serving
    micro-batch former (serve/batcher.py), so "which pinned shape would
    this batch land on" has exactly one definition."""
    for t in sorted(tiers):
        if B <= t:
            return int(t)
    return None


@dataclass
class DispatchBudget:
    """Per-dispatch stage timings (seconds) of one latency-mode call."""

    batch: int
    tier: int
    host_lower_s: float
    h2d_s: float
    kernel_s: float
    d2h_s: float
    total_s: float
    #: True when this dispatch had to build a pinned executable (cold);
    #: warm steady-state dispatches are always False
    compiled: bool

    def as_dict(self) -> Dict[str, float]:
        return {
            "batch": self.batch,
            "tier": self.tier,
            "host_lower_s": self.host_lower_s,
            "h2d_s": self.h2d_s,
            "kernel_s": self.kernel_s,
            "d2h_s": self.d2h_s,
            "total_s": self.total_s,
            "compiled": self.compiled,
        }


class LatencyPath:
    """Warm small-batch dispatcher for one DeviceSnapshot.

    Obtained via ``DeviceEngine.latency_path(dsnap)`` (one per prepared
    snapshot; pinned executables are additionally shared engine-wide by
    shape fingerprint, so a Watch delta chain whose table geometry is
    stable re-pins without recompiling)."""

    def __init__(self, engine, dsnap, registry: Optional[Any] = None) -> None:
        self.engine = engine
        self.dsnap = dsnap
        self._m = registry or _metrics.default
        self._lock = threading.Lock()
        #: (slots, tier, qctx_key) → Compiled executable
        self._local: Dict[Tuple, Any] = {}
        #: tier → preallocated int32[QM_ROWS, tier] staging buffer
        self._qm_bufs: Dict[int, np.ndarray] = {}
        #: XLA compilations this path actually paid for (engine-cache
        #: misses) — the no-retrace assertion's subject
        self.compile_count = 0
        #: dispatches this path actually SERVED (not fallbacks) — the
        #: client reads it around check_batch to learn whether a
        #: latency-mode call really ran on this path (the breaker's
        #: half-open probe must not close on a silent batch fallback)
        self.dispatch_count = 0
        #: number of pinned-executable entries (incl. engine-cache hits)
        self.pin_count = 0
        #: (slots, tier, qctx_key) combos this path has SERVED warm — a
        #: fresh compile for a key already here means a pinned executable
        #: was lost (cache eviction, engine churn) and the "no retrace by
        #: construction" invariant is being paid for at serving time:
        #: fire a flight-recorder incident so the recompile is diagnosed
        #: from the traces around it, not discovered in a p99 regression
        self._served_keys: set = set()
        self.last_budget: Optional[DispatchBudget] = None
        self._shape_fp: Optional[Tuple] = None
        #: (clock value, device scalar) — the snapshot-relative clock has
        #: seconds resolution, so steady-state dispatch reuses one device
        #: scalar instead of paying a put per call
        self._now_cache: Optional[Tuple[int, Any]] = None
        #: (qctx device dict identity, shape key) — the context-free
        #: singleton is one stable dict, so its key derivation is free
        self._qctx_key_cache: Optional[Tuple[Any, Tuple]] = None
        #: lazily-computed gathered-bytes/check of this snapshot (the
        #: perf ledger's meta model) — sampled dispatch spans carry
        #: ``bytes_gathered_est`` without recomputing the model per call
        self._bpc_cache: Optional[float] = None
        #: decision-provenance witness extraction (engine/explain.py):
        #: armed, dispatches run the witness kernel variant (pinned under
        #: its own key — the disarmed pins are untouched) and the per-
        #: query winning-branch codes land on ``last_witness``.  Disarmed
        #: (default) the dispatch path pays ONE flag read; no witness
        #: buffer exists, no extra device output ships — the same
        #: zero-cost discipline as trace.NOOP
        self.witness_armed = False
        self.last_witness: Optional[np.ndarray] = None

    def _bytes_per_check(self) -> float:
        v = self._bpc_cache
        if v is None:
            try:
                v = _perf.est_bytes_per_check(self.dsnap)
            except Exception:
                v = 0.0
            self._bpc_cache = v
        return v

    # -- availability ----------------------------------------------------
    def tier_for(self, B: int) -> Optional[int]:
        """Smallest configured tier holding ``B``, or None (→ fall back
        to the throughput path)."""
        return tier_for(self.engine.config.latency_tiers, B)

    def arm_witness(self, on: bool = True) -> None:
        """Toggle witness extraction for subsequent dispatches.  Armed
        and disarmed executables pin under distinct keys, so flipping
        never evicts or retraces the other mode's pins — the first armed
        dispatch per (slots, tier, qctx shape) pays one compile, warm
        dispatches after that are pinned like any other."""
        self.witness_armed = bool(on)
        if not on:
            self.last_witness = None

    # -- pinning ---------------------------------------------------------
    def _fingerprint(self) -> Tuple:
        """Engine-wide pin-cache key component: the exact aval signature
        of the snapshot's device arrays.  Two snapshots with equal
        fingerprints (same FlatMeta, same padded shapes — the common
        case along a Watch delta chain) share pinned executables."""
        if self._shape_fp is None:
            self._shape_fp = tuple(
                sorted(
                    (k, tuple(v.shape), str(v.dtype))
                    for k, v in self.dsnap.arrays.items()
                )
            )
        return self._shape_fp

    def _donate(self) -> bool:
        cfg = self.engine.config
        if cfg.latency_donate is not None:
            return bool(cfg.latency_donate)
        import jax

        return jax.default_backend() == "tpu"

    def _staged_timing(self) -> bool:
        """Fence between budget stages?  Exact per-stage times on TPU;
        on CPU the fences themselves cost ~0.3 ms per dispatch, so the
        auto default folds the (synchronous) H2D remainder into the
        kernel stage instead of paying fences to split hairs."""
        cfg = self.engine.config
        if cfg.latency_staged_timing is not None:
            return bool(cfg.latency_staged_timing)
        import jax

        return jax.default_backend() == "tpu"

    def _pinned_for(self, slots, tier, qctx_key, args):
        """The pinned executable for this (slots, tier, qctx shape) —
        local-first, then the engine-wide cache, then a real compile.
        Witness-armed dispatches pin the witness kernel variant under a
        distinct key; disarmed keys are exactly the pre-witness ones."""
        import jax

        wit = self.witness_armed
        key = (slots, tier, qctx_key) if not wit else (
            slots, tier, qctx_key, "wit"
        )
        fn = self._local.get(key)
        if fn is not None:
            return fn, False, key
        with self._lock:
            fn = self._local.get(key)
            if fn is not None:
                return fn, False, key
            full_key = (self.dsnap.flat_meta, self._fingerprint(), key)
            with self.engine._latency_pins_lock:
                fn = self.engine._latency_pins.get(full_key)
            fresh = fn is None
            if fresh:
                if self._donate():
                    from .flat import make_flat_fn

                    jfn = jax.jit(
                        make_flat_fn(
                            self.engine.compiled, self.engine.plan,
                            self.engine.config, self.dsnap.flat_meta, slots,
                            caveat_plan=self.engine.caveat_plan, jit=False,
                            witness=wit,
                        ),
                        # donate the query matrix: its device buffer is
                        # re-uploaded fresh every dispatch, so XLA may
                        # alias it for the output planes
                        donate_argnums=(3,),
                    )
                else:
                    # share the engine's jit cache with the throughput
                    # path: the trace is reused, only the AOT compile
                    # at the tier's shape is new
                    jfn = self.engine._flat_fn_for(
                        slots, self.dsnap.flat_meta, witness=wit
                    )
                fn = jfn.lower(*args).compile()
                self.compile_count += 1
                self._m.inc("latency.compiles")
                # device cost ledger: the Compiled is in hand, so the
                # XLA cost_analysis capture is free at pin time
                _perf.record_cost(
                    "latency_pin",
                    f"tier={tier};slots={slots}",
                    fn, self._m, tier=int(tier), slots=len(slots),
                )
                with self.engine._latency_pins_lock:
                    pins = self.engine._latency_pins
                    while len(pins) >= self.engine.LATENCY_PIN_CACHE_MAX:
                        pins.pop(next(iter(pins)))
                    pins[full_key] = fn
            self._local[key] = fn
            # same FIFO bound as the engine cache: varying qctx shapes
            # must not accumulate pinned executables without end
            while len(self._local) > self.engine.LATENCY_PIN_CACHE_MAX:
                self._local.pop(next(iter(self._local)))
            self.pin_count += 1
            return fn, fresh, key

    def _qm_buf(self, tier: int) -> np.ndarray:
        buf = self._qm_bufs.get(tier)
        if buf is None:
            buf = np.empty((QM_ROWS, tier), np.int32)
            self._qm_bufs[tier] = buf
        return buf

    # -- dispatch --------------------------------------------------------
    def dispatch(
        self,
        queries: Dict[str, np.ndarray],
        qctx: Dict[str, np.ndarray],
        B: int,
        now,
        t_start: Optional[float] = None,
        span=_trace.NOOP,
    ):
        """One warm small-batch dispatch from already-lowered query
        columns.  ``now`` is the snapshot-relative int32 clock
        (snap.now_rel32).  ``t_start`` backdates the host-lowering stage
        to when the caller began lowering (so the budget charges query
        interning/packing honestly).  ``span`` is the request's trace
        span (utils/trace.py): a sampled dispatch records stage child
        spans rebuilt from the SAME perf_counter stamps the budget uses,
        so span durations and the ``latency.*`` stage timers agree
        exactly; the NOOP span allocates nothing.  Returns trimmed
        (d, p, ovf) numpy arrays, or None when this path cannot serve
        the batch."""
        import jax

        t0 = t_start if t_start is not None else time.perf_counter()
        meta = self.dsnap.flat_meta
        if meta is None or meta.sharded:
            # sharded tables need the shard_map kernel; the latency path
            # is a single-chip construct — callers fall back
            return None
        tier = self.tier_for(B)
        if tier is None:
            return None
        slots = tuple(
            sorted({int(s) for s in np.unique(queries["q_perm"]) if s >= 0})
        )
        if len(slots) > self.engine.config.flat_max_slots:
            return None
        # injection site AFTER the availability checks: a batch this path
        # would decline falls back without ever reaching the fault
        faults.fire("latency.dispatch")
        if _pallas.resolve(self.engine.config):
            # the pinned kernels run the fused Pallas probes when the
            # knob resolves on — a pallas-path fault here classifies and
            # reroutes exactly like a latency-path one (breaker re-form)
            faults.fire("pallas.dispatch")

        # ---- stage 1: host lowering (pack into the staging buffer) -----
        # the staging buffer is shared per tier: hold the path lock from
        # fill through upload so concurrent checkers can't corrupt it
        # (concurrent serving shards by path/thread; the lock only
        # covers the host-side window, not kernel execution)
        staged = self._staged_timing()
        with self._lock:
            qm = self._qm_buf(tier)
            fill_qm(queries, qm, meta)
            qctx_dev = self.engine._qctx_device(qctx)
            kc = self._qctx_key_cache
            if kc is not None and kc[0] is qctx_dev:
                qctx_key = kc[1]
            else:
                qctx_key = tuple(
                    (k, tuple(v.shape), str(v.dtype))
                    for k, v in sorted(qctx_dev.items())
                )
                self._qctx_key_cache = (qctx_dev, qctx_key)
            t1 = time.perf_counter()

            # ---- stage 2: H2D (staging buffer + clock scalar) ----------
            qm_dev = jax.device_put(qm)
            nc = self._now_cache
            if nc is not None and nc[0] == int(now):
                now_dev = nc[1]
            else:
                now_dev = jax.device_put(np.int32(now))
                self._now_cache = (int(now), now_dev)
            if staged or jax.default_backend() != "cpu":
                # the fence is load-bearing off-CPU regardless of the
                # timing knob: the shared staging buffer must not be
                # refilled (lock released) while an async H2D still
                # reads it.  On CPU device_put copies synchronously, so
                # only there may the knob elide the fence
                jax.block_until_ready((qm_dev, now_dev))
        t2 = time.perf_counter()

        # ---- stage 3: pinned kernel (blocked) --------------------------
        args = (self.dsnap.arrays, self.dsnap.tid_map, now_dev, qm_dev, qctx_dev)
        # served-key identity must carry the witness mode: the first
        # ARMED compile for a combo served warm disarmed is a new pin,
        # not a lost one — a false latency.retrace incident otherwise.
        # _pinned_for returns the key it resolved so the mutable
        # witness_armed flag is read exactly once per dispatch
        fn, fresh, pin_key = self._pinned_for(slots, tier, qctx_key, args)
        if fresh and pin_key in self._served_keys:
            # retrace detection: this exact shape was served warm before,
            # so the compile we just paid means its pin was evicted —
            # a silent tail regression in the making.  Counted + incident
            self._m.inc("latency.retraces")
            _trace.trigger_incident(
                "latency.retrace", tier=tier, batch=B, slots=len(slots),
            )
        # profiler correlation: inside a GOCHUGARU_TRACE_DIR session the
        # kernel window is annotated with the request's trace id, so the
        # harvested device trace attributes back to this dispatch
        with _trace.annotate_dispatch(span):
            out = fn(*args)
            jax.block_until_ready(out)
        t3 = time.perf_counter()

        # ---- stage 4: D2H readback -------------------------------------
        got = jax.device_get(out)
        if len(got) == 4:  # witness-armed kernel: fourth plane = codes
            d, p, ovf, w = got
            self.last_witness = w[:B]
        else:
            d, p, ovf = got
        t4 = time.perf_counter()

        budget = DispatchBudget(
            batch=B, tier=tier,
            host_lower_s=t1 - t0, h2d_s=t2 - t1,
            kernel_s=t3 - t2, d2h_s=t4 - t3,
            total_s=t4 - t0, compiled=fresh,
        )
        self.last_budget = budget
        self.dispatch_count += 1
        # pad-waste ledger: B live lanes padded to the tier — direct
        # calls and batcher-formed batches both flow through here, so
        # the serving occupancy is accounted per dispatch
        _perf.record_pad(tier, B, self._m)
        # wall-time ledger stages from the SAME t0..t4 stamps the budget
        # (and the stage spans below) subtract — one branch when no
        # measurement window is armed
        _perf.report_wall_stages(t0, t1, t2, t3, t4)
        if len(self._served_keys) < 4096:  # qctx-shape churn backstop
            self._served_keys.add(pin_key)
        m = self._m
        m.inc("latency.dispatches")
        if not fresh:
            # the dispatch p99 is the serving SLO: a cold compile is a
            # separate (counted) event, not a tail sample — and the
            # compile lands inside the kernel-stage window, so the stage
            # samples skip cold dispatches for the same reason
            m.observe("latency.host_lower_s", budget.host_lower_s)
            m.observe("latency.h2d_s", budget.h2d_s)
            m.observe("latency.kernel_s", budget.kernel_s)
            m.observe("latency.d2h_s", budget.d2h_s)
            m.observe("latency.dispatch_s", budget.total_s)
        if span.sampled:
            # stage spans from the SAME t0..t4 stamps the budget (and so
            # the latency.* timers) subtracted — durations agree exactly
            lsp = span.child(
                "latency.dispatch", t=t0,
                batch=B, tier=tier, compiled=fresh,
                pad_fraction=round(1.0 - B / tier, 4),
                bytes_gathered_est=round(self._bytes_per_check() * B, 1),
            )
            lsp.child_at("stage.host_lower", t0).end(t=t1)
            lsp.child_at("stage.h2d", t1).end(t=t2)
            lsp.child_at("stage.kernel", t2).end(t=t3)
            lsp.child_at("stage.d2h", t3).end(t=t4)
            lsp.end(t=t4)
        return d[:B], p[:B], ovf[:B]

    def dispatch_columns(
        self,
        q_res: np.ndarray,
        q_perm: np.ndarray,
        q_subj: np.ndarray,
        *,
        q_srel: Optional[np.ndarray] = None,
        q_wc: Optional[np.ndarray] = None,
        q_ctx: Optional[np.ndarray] = None,
        qctx_rows=None,
        now_us: Optional[int] = None,
        span=_trace.NOOP,
    ):
        """Latency-path bulk check from pre-interned int32 columns (the
        columnar mirror of the Relationship path; benches and tests call
        this).  Returns (d, p, ovf) or None → caller falls back."""
        t0 = time.perf_counter()
        queries, qctx = self.engine._columns_preamble(
            self.dsnap, q_res, q_perm, q_subj, q_srel, q_wc, q_ctx, qctx_rows
        )
        now = self.dsnap.snapshot.now_rel32(now_us)
        return self.dispatch(
            queries, qctx, q_res.shape[0], now, t_start=t0, span=span
        )

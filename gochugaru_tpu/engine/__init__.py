"""Permission evaluators.

Two implementations of the same semantics:

- ``oracle`` — a pure-Python recursive userset-rewrite walker with exact
  SpiceDB check semantics (tri-state permissionship, caveats, expiration,
  wildcards, userset subjects, arrows).  It is the differential-testing
  reference (SURVEY.md §4's replacement for the dockerized
  `spicedb serve-testing`), the LookupResources/LookupSubjects engine, and
  the fallback for queries that overflow the device engine's static caps.

- ``device`` — the JAX/TPU engine: schemas compile to batched reachability
  programs; checks run as vmapped two-phase evaluation (subject closure +
  resource-subgraph fixpoint) over the snapshot's sorted columnar arrays.

``explain`` bridges the two for decision provenance: the device kernels
optionally emit a per-query witness code (winning branch) that seeds an
instrumented oracle walk into a typed resolution tree — "why was this
check allowed/denied" at a pinned revision.
"""

from .oracle import Oracle, PermTri

__all__ = ["Oracle", "PermTri"]

"""Revision-pinned verdict cache + singleflight dedup for the serving path.

Zanzibar-scale serving lives on two observations: hot (subject, resource,
permission) tuples repeat constantly under skewed traffic, and the
consistency surface (consistency.py) exists precisely so a repeated read
can be answered from a revision-pinned result without re-walking the
graph.  This module supplies both halves:

**VerdictCache** — definite check verdicts keyed on (snapshot revision,
permission slot, resource id, subject id, query-context fingerprint)
under a byte-bounded LRU whose eviction granularity is a whole revision
shard.  Revision keying makes invalidation *structural*: a write mints a
new revision, so a fresh snapshot simply opens a fresh keyspace — there
is no invalidation protocol to get wrong, and a pinned ``Snapshot``
reader keeps hitting its own revision's shard for as long as it stays
resident.  The consistency strategies become the cache's READ POLICY
(``policy_for``): Snapshot/AtLeast reads hit the shard of the revision
the store resolved for them, MinLatency hits the freshest resident
revision (the one ``snapshot_for`` picked), and Full bypasses the cache
entirely — the same PACELC split the reference documents.

Cacheability discipline (the correctness edge):

- caveated verdicts whose caveat read LIVE query context are **never
  cached** — a request carrying ``caveat_context`` bypasses both the
  read and the write for that item (the relationship path detects this
  per item; the columnar path never carries query context);
- context-free caveat outcomes and expiry-gated rows cache with a
  **pinned now_us** recorded on the entry — the same discipline as
  ``LookupCursor.now_us``: a hit serves the verdict as evaluated at the
  pinned time, it never silently re-gates expirations at a later clock.

**Singleflight** — the cross-batch half of check deduplication: while a
formed batch's checks are in flight on the device, the batcher holds an
open *dispatch window* (the batch's key→row map).  A submission arriving
during the window whose rows ALL duplicate in-flight keys **parks** on
the window instead of occupying queue slots and tier lanes; when the
owning batch settles, the verdicts fan back out to every parked future.
The mechanism is deliberately lock-light: the submit path pays one
Python-scalar key probe to rule out the (common) non-duplicate case
before doing any per-row work, columnar windows are a sorted key array
(one bisect per probe, one vectorized searchsorted per park attempt),
and exactly one window is ever open — the serving dispatcher settles
batches strictly in formation order.

Fault site ``cache.lookup`` rides the chaos registry: an armed lookup
raises before any cached state is consulted, the classified error
reaches the caller's retry envelope, and the chaos soak asserts oracle
parity straight through it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import (
    Any, Dict, Iterable, List, Mapping, NamedTuple, Optional, Sequence,
)

import numpy as np

from ..consistency import Requirement, Strategy
from ..utils import faults
from ..utils import metrics as _metrics

__all__ = [
    "CachePolicy",
    "Singleflight",
    "VerdictCache",
    "fingerprint_context",
    "pack_cols",
    "pack_one",
    "policy_for",
    "rel_key",
]


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------

#: query-context fingerprint of the empty context — the only fingerprint
#: cacheable relationship entries ever carry (live-context items bypass)
EMPTY_CTX_FP = 0


def fingerprint_context(ctx: Optional[Mapping[str, Any]]) -> int:
    """64-bit fingerprint of a query caveat context (0 = empty).  Only
    used to KEY dedup of identical in-flight requests — cache entries
    are never written for non-empty contexts, so a fingerprint collision
    can at worst coalesce two genuinely identical dispatches."""
    if not ctx:
        return EMPTY_CTX_FP
    import hashlib

    from ..rel.relationship import _canonical_caveat_json

    h = hashlib.blake2b(
        _canonical_caveat_json(ctx).encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") or 1


def rel_key(r) -> tuple:
    """Dedup/cache key of a Relationship-shaped check: the full 6-field
    identity (resource triple + subject triple) plus the query-context
    fingerprint.  String-keyed on purpose — it captures subject-relation
    and wildcard identity exactly, with no dependence on interner state."""
    return (r.key(), fingerprint_context(r.caveat_context))


#: exact-packing bounds for the columnar int64 key: slot < 2^15,
#: node ids < 2^24 each → 63 bits, no collision possible
_PACK_SLOT_MAX = 1 << 15
_PACK_NODE_MAX = 1 << 24


def pack_cols(q_perm: np.ndarray, q_res: np.ndarray, q_subj: np.ndarray):
    """Columnar check keys: one int64 ndarray when every id fits the
    exact pack (slot<<48 | res<<24 | subj — the common case by orders of
    magnitude), else a list of (perm, res, subj) tuples.  Both forms are
    EXACT — dedup and cache hits must never alias distinct checks."""
    if q_res.size == 0:
        return np.zeros(0, np.int64)
    pmin = int(q_perm.min())
    nmin = min(int(q_res.min()), int(q_subj.min()))
    pmax = int(q_perm.max())
    nmax = max(int(q_res.max()), int(q_subj.max()))
    if pmin >= 0 and nmin >= 0 and pmax < _PACK_SLOT_MAX and nmax < _PACK_NODE_MAX:
        return (
            (q_perm.astype(np.int64) << 48)
            | (q_res.astype(np.int64) << 24)
            | q_subj.astype(np.int64)
        )
    return list(zip(q_perm.tolist(), q_res.tolist(), q_subj.tolist()))


def pack_one(perm: int, res: int, subj: int):
    """The int64 pack of one (perm, res, subj) triple — the submit
    path's scalar fast probe.  Matches pack_cols' bit layout for
    in-bounds ids; out-of-bounds ids return a tuple that simply won't
    match an int-keyed window (degrades parking, never correctness)."""
    if 0 <= perm < _PACK_SLOT_MAX and 0 <= res < _PACK_NODE_MAX \
            and 0 <= subj < _PACK_NODE_MAX:
        return (perm << 48) | (res << 24) | subj
    return (perm, res, subj)


def keys_list(keys) -> list:
    """Python-object view of pack_cols output (dict-key form)."""
    return keys.tolist() if isinstance(keys, np.ndarray) else keys


# ---------------------------------------------------------------------------
# Read policy (consistency.py strategies → cache behavior)
# ---------------------------------------------------------------------------


class CachePolicy(NamedTuple):
    read: bool
    write: bool


CACHE_OFF = CachePolicy(False, False)
CACHE_RW = CachePolicy(True, True)


def policy_for(strategy: Optional[Strategy]) -> CachePolicy:
    """The consistency strategy IS the cache's read policy:

    - ``Full`` bypasses the cache entirely (read-your-writes at the
      latest revision must see the evaluator, never a resident shard);
    - ``Snapshot``/``AtLeast`` read and write the shard of the exact
      revision the store resolved for them (pinned / at-least-as-fresh);
    - ``MinLatency`` reads the freshest resident revision — which is
      precisely the snapshot ``snapshot_for`` hands back.

    ``None`` (no strategy known at this call site) disables caching."""
    if strategy is None or strategy.requirement == Requirement.FULL:
        return CACHE_OFF
    return CACHE_RW


# ---------------------------------------------------------------------------
# The verdict cache
# ---------------------------------------------------------------------------


class _ColShard:
    """One revision's columnar entries: a SORTED int64 snapshot (keys +
    encoded values, probed by np.searchsorted — ~6× cheaper per row
    than dict gets on the serving path, and the probe holds the GIL for
    C time only) plus an ``extra`` dict absorbing inserts between
    rebuilds.  A rebuild merges extra into the snapshot when it grows
    past max(1024, len/4) — O(n log n) amortized over the growth that
    triggered it.  ``tuple_mode`` worlds (ids past the exact int64
    pack) stay dict-only.

    The (keys, vals) pair is published as ONE tuple attribute (``snap``)
    so lock-free readers can never observe a torn pair — two separate
    attribute stores would let a reader bind the new keys against the
    old values and serve a definite verdict for the WRONG tuple.  A
    reader racing ``extra``'s clear can only see a spurious miss (the
    row re-dispatches), never a wrong hit."""

    __slots__ = ("snap", "extra", "tuple_mode")

    REBUILD_MIN = 1024

    def __init__(self) -> None:
        self.snap = (np.zeros(0, np.int64), np.zeros(0, np.int64))
        self.extra: dict = {}
        self.tuple_mode = False

    def __len__(self) -> int:
        return self.snap[0].shape[0] + len(self.extra)

    def maybe_rebuild(self) -> None:
        keys, vals = self.snap
        if self.tuple_mode or len(self.extra) <= max(
            self.REBUILD_MIN, keys.shape[0] // 4
        ):
            return
        ne = len(self.extra)
        ek = np.fromiter(self.extra.keys(), np.int64, count=ne)
        ev = np.fromiter(self.extra.values(), np.int64, count=ne)
        allk = np.concatenate([keys, ek])
        allv = np.concatenate([vals, ev])
        order = np.argsort(allk, kind="stable")
        allk, allv = allk[order], allv[order]
        if allk.shape[0] > 1:
            keep = np.empty(allk.shape[0], bool)
            keep[0] = True
            np.not_equal(allk[1:], allk[:-1], out=keep[1:])
            allk, allv = allk[keep], allv[keep]
        self.snap = (allk, allv)  # one atomic publish
        self.extra = {}


class VerdictCache:
    """Byte-bounded, revision-sharded LRU of definite check verdicts.

    Entries pin ``now_us``, the evaluation time the verdict was computed
    at (expiry gates re-served at the pinned time, the LookupCursor
    discipline).  Shards evict whole-revision at a time — the
    structural-invalidation property — least-recently-USED revision
    first, so a pinned Snapshot reader's shard stays warm under head
    writes for as long as its reads keep refreshing it.

    Thread-safety: mutation is locked; bulk lookups read the shard's
    snapshot arrays and dicts lock-free (arrays are replaced wholesale,
    never mutated; CPython dict gets are safe against concurrent
    inserts; eviction drops whole shard objects) — the same discipline
    as ``Interner.keys_batch``."""

    #: rough per-entry cost estimates driving the byte bound (key +
    #: value tuple + dict slot overhead)
    COL_ENTRY_BYTES = 96
    REL_ENTRY_BYTES = 320

    def __init__(
        self,
        max_bytes: int = 64 << 20,
        *,
        max_revisions: int = 8,
        registry: Optional[_metrics.Metrics] = None,
    ) -> None:
        self.max_bytes = int(max_bytes)
        self.max_revisions = int(max_revisions)
        self._m = registry or _metrics.default
        self._lock = threading.Lock()
        #: revision → {"c": _ColShard, "r": {rel_key: (bool, now_us)}}
        self._revs: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self._bytes = 0
        self._entries = 0
        if self._m is _metrics.default:
            # /perf carries the cache's state next to the cost ledger
            # (last-created cache per process wins — the common shape
            # is one); custom-registry caches (tests) stay off it
            from ..utils import perf as _perf

            _perf.register_report_section("vcache", self.stats)

    # -- internals -------------------------------------------------------
    def _shard(self, revision: int, create: bool):
        with self._lock:
            sh = self._revs.get(revision)
            if sh is not None:
                self._revs.move_to_end(revision)
                return sh
            if not create:
                return None
            sh = {"c": _ColShard(), "r": {}}
            self._revs[revision] = sh
            self._evict_locked()
            self._publish_locked()
            return sh

    def _evict_locked(self) -> None:
        while len(self._revs) > self.max_revisions or (
            self._bytes > self.max_bytes and len(self._revs) > 1
        ):
            _, sh = self._revs.popitem(last=False)
            self._bytes -= self._shard_bytes(sh)
            self._entries -= len(sh["c"]) + len(sh["r"])
            self._m.inc("cache.evicted_revisions")
        if self._bytes > self.max_bytes and self._revs:
            # a single over-budget shard: shed half its columnar
            # snapshot (arrays replaced wholesale — concurrent readers
            # keep their reference) and its oldest rel entries
            sh = next(iter(self._revs.values()))
            c = sh["c"]
            ck, cv = c.snap
            drop = len(c.extra) + ck.shape[0] // 2
            if drop:
                c.extra = {}
                half = ck.shape[0] // 2
                c.snap = (  # one atomic publish — see _ColShard
                    np.ascontiguousarray(ck[half:]),
                    np.ascontiguousarray(cv[half:]),
                )
                self._bytes -= drop * self.COL_ENTRY_BYTES
                self._entries -= drop
            d = sh["r"]
            it = iter(list(d))
            while self._bytes > self.max_bytes and d:
                d.pop(next(it), None)
                self._bytes -= self.REL_ENTRY_BYTES
                self._entries -= 1

    @classmethod
    def _shard_bytes(cls, sh) -> int:
        return (len(sh["c"]) * cls.COL_ENTRY_BYTES
                + len(sh["r"]) * cls.REL_ENTRY_BYTES)

    def _publish_locked(self) -> None:
        self._m.set_gauge("cache.bytes", self._bytes)
        self._m.set_gauge("cache.entries", self._entries)
        self._m.set_gauge("cache.revisions", len(self._revs))

    # -- columnar surface ------------------------------------------------
    # Columnar entries store ``(now_us << 1) | verdict`` as int64: the
    # bulk lookup probes the shard's sorted snapshot with searchsorted
    # (pure C, no per-row interpreter frames) and only the residual
    # misses touch the insert dict.

    def lookup_cols(self, revision: int, keys) -> Optional[np.ndarray]:
        """Bulk lookup of packed columnar keys at one revision: an int64
        array of encoded entries with -1 at misses, or None when the
        revision has no shard at all (the common cold case, returned
        cheaply).  Decode: ``verdict = arr & 1``, ``now_us = arr >> 1``.
        Fires the ``cache.lookup`` chaos site before touching state."""
        faults.fire("cache.lookup")
        sh = self._shard(revision, create=False)
        n = len(keys)
        if sh is None:
            self._m.inc("cache.misses", n)
            return None
        c = sh["c"]
        if isinstance(keys, np.ndarray) and not c.tuple_mode:
            out = np.full(n, -1, np.int64)
            ck, cv = c.snap  # ONE attribute read → never a torn pair
            if ck.shape[0]:
                pos = np.minimum(
                    np.searchsorted(ck, keys), ck.shape[0] - 1
                )
                hit = ck[pos] == keys
                out[hit] = cv[pos[hit]]
            if c.extra:
                miss = np.nonzero(out < 0)[0]
                if miss.size:
                    import itertools

                    out[miss] = np.fromiter(
                        map(c.extra.get, keys[miss].tolist(),
                            itertools.repeat(-1)),
                        np.int64, count=miss.size,
                    )
        else:
            import itertools

            out = np.fromiter(
                map(c.extra.get, keys_list(keys), itertools.repeat(-1)),
                np.int64, count=n,
            )
        nh = int((out >= 0).sum())
        if nh:
            self._m.inc("cache.hits", nh)
        if nh != n:
            self._m.inc("cache.misses", n - nh)
        return out

    def get_col(self, revision: int, key) -> Optional[tuple]:
        """One decoded columnar entry — (verdict, now_us) or None
        (tests/introspection; the serving path uses lookup_cols)."""
        sh = self._shard(revision, create=False)
        if sh is None:
            return None
        c = sh["c"]
        v = c.extra.get(key)
        ck, cv = c.snap
        if v is None and isinstance(key, int) and ck.shape[0]:
            p = int(np.searchsorted(ck, key))
            if p < ck.shape[0] and int(ck[p]) == key:
                v = int(cv[p])
        if v is None:
            return None
        return (bool(v & 1), v >> 1)

    def _shard_for_insert_locked(self, revision: int):
        """Resolve-or-create the shard UNDER the already-held lock: a
        separate resolve-then-relock would let a concurrent eviction pop
        the shard in between, and the insert would then account bytes
        into an orphan no eviction can ever reclaim."""
        sh = self._revs.get(revision)
        if sh is None:
            sh = {"c": _ColShard(), "r": {}}
            self._revs[revision] = sh
        else:
            self._revs.move_to_end(revision)
        return sh

    def insert_cols(self, revision: int, keys, verdicts, now_us: int) -> None:
        """Insert verdicts for packed columnar keys (all cacheable: the
        columnar path carries no live query context by construction;
        time-gated verdicts pin ``now_us`` on the entry)."""
        kl = keys_list(keys)
        if not kl:
            return
        enc_t = (int(now_us) << 1) | 1
        enc_f = int(now_us) << 1
        with self._lock:
            c = self._shard_for_insert_locked(revision)["c"]
            if kl and not isinstance(kl[0], int):
                c.tuple_mode = True
            before = len(c.extra)
            d = c.extra
            for k, v in zip(kl, verdicts):
                if k not in d:
                    d[k] = enc_t if v else enc_f
            new = len(d) - before
            if new:
                c.maybe_rebuild()
                self._bytes += new * self.COL_ENTRY_BYTES
                self._entries += new
                self._m.inc("cache.puts", new)
                self._evict_locked()
                self._publish_locked()

    # -- relationship surface --------------------------------------------
    def lookup_rels(self, revision: int, keys: Sequence[Optional[tuple]]):
        """Bulk lookup of relationship keys; a None key marks an item
        that must bypass the cache (live query context) and is counted
        as a bypass, not a miss."""
        faults.fire("cache.lookup")
        sh = self._shard(revision, create=False)
        nby = sum(1 for k in keys if k is None)
        if nby:
            self._m.inc("cache.bypass", nby)
        if sh is None:
            self._m.inc("cache.misses", len(keys) - nby)
            return [None] * len(keys)
        g = sh["r"].get
        vals = [None if k is None else g(k) for k in keys]
        nh = sum(1 for v in vals if v is not None)
        if nh:
            self._m.inc("cache.hits", nh)
        miss = len(keys) - nby - nh
        if miss:
            self._m.inc("cache.misses", miss)
        return vals

    def insert_rels(self, revision: int, items, now_us: int) -> None:
        """Insert (key, verdict) pairs; keys are ``rel_key`` tuples the
        caller already vetted as cacheable (no live query context)."""
        if not items:
            return
        with self._lock:
            d = self._shard_for_insert_locked(revision)["r"]
            new = 0
            for k, v in items:
                if k not in d:
                    d[k] = (bool(v), now_us)
                    new += 1
            if new:
                self._bytes += new * self.REL_ENTRY_BYTES
                self._entries += new
                self._m.inc("cache.puts", new)
                self._evict_locked()
                self._publish_locked()

    def peek_rel(self, revision: int, key) -> Optional[tuple]:
        """Metric-free single-key probe: the explain surface records
        whether a verdict WOULD have been cache-served (provenance)
        without polluting hit/miss counters, firing the chaos site, or
        refreshing the shard's LRU position."""
        with self._lock:
            sh = self._revs.get(revision)
        if sh is None:
            return None
        return sh["r"].get(key)

    # -- lifecycle / introspection ---------------------------------------
    def set_max_bytes(self, max_bytes: int) -> None:
        """Resize the byte budget at runtime — the online tuner's cache
        knob (tune/controller.py).  Shrinking evicts immediately under
        the lock (LRU revision first, same path as insert pressure);
        growing just raises the ceiling and later inserts fill it.
        Concurrent readers are untouched either way — eviction drops
        whole shard objects, never mutates one."""
        with self._lock:
            self.max_bytes = int(max_bytes)
            self._evict_locked()
            self._publish_locked()

    def drop_revision(self, revision: int) -> None:
        """Structural invalidation hook: when the client's dsnap LRU
        evicts a prepared revision, the matching verdict shard drops
        with it (a no-longer-resident revision will not be read again
        by pinned readers — they get PreconditionFailed upstream)."""
        self.drop_revisions((revision,))

    def drop_revisions(self, revisions: Iterable[int]) -> None:
        """Batched structural invalidation — ONE lock acquisition and one
        gauge publish for a whole set of retired revisions.  This is the
        group-commit shape: a committed group retires every evicted /
        non-resident generation it superseded in one call (client dsnap
        LRU, fleet/replica.py serving advance) instead of a
        lock-acquire-per-write storm.  Counts one
        ``cache.group_invalidations`` per call that dropped > 1 shard."""
        with self._lock:
            dropped = 0
            for revision in revisions:
                sh = self._revs.pop(revision, None)
                if sh is not None:
                    self._bytes -= self._shard_bytes(sh)
                    self._entries -= len(sh["c"]) + len(sh["r"])
                    dropped += 1
            if dropped:
                if dropped > 1:
                    self._m.inc("cache.group_invalidations")
                self._publish_locked()

    def clear(self) -> None:
        with self._lock:
            self._revs.clear()
            self._bytes = 0
            self._entries = 0
            self._publish_locked()

    @property
    def resident_revisions(self) -> List[int]:
        with self._lock:
            return list(self._revs)

    def residency(self) -> Dict[str, Any]:
        """The revision-shard residency report a fleet replica publishes
        (fleet/replica.py health): which revisions hold warm verdicts
        here, and the freshest of them — the router's resident-revision
        placement reads the store's generations for correctness and this
        for cache-affinity visibility."""
        with self._lock:
            revs = sorted(self._revs)
        return {
            "revisions": revs,
            "freshest": revs[-1] if revs else None,
            "entries": self._entries,
        }

    def stats(self) -> Dict[str, Any]:
        """Cheap state dump (incident-bundle context, /perf, smokes)."""
        m = self._m
        hits = m.counter("cache.hits")
        misses = m.counter("cache.misses")
        with self._lock:
            return {
                "bytes": self._bytes,
                "entries": self._entries,
                "revisions": list(self._revs),
                "max_bytes": self.max_bytes,
                "hits": hits,
                "misses": misses,
                "bypass": m.counter("cache.bypass"),
                "puts": m.counter("cache.puts"),
                "hit_rate": round(hits / (hits + misses), 4)
                if (hits + misses) else 0.0,
            }


# ---------------------------------------------------------------------------
# Cross-batch singleflight (the dispatch window)
# ---------------------------------------------------------------------------


class Singleflight:
    """One open dispatch window at a time: while a formed batch's checks
    run on the device, its keys are held here; a submission whose rows
    ALL duplicate in-flight keys parks on the window (no queue slot, no
    tier lane) and resolves when the batch settles.

    Columnar windows hold the batch's keys SORTED (one np.sort at open
    — which also yields the unique-work count the occupancy metrics
    want) so the submit-path probe is a scalar bisect and a full park
    attempt is one vectorized searchsorted; the row mapping (argsort)
    is computed lazily on the first successful park.  Relationship
    windows (the low-rate path) use a plain dict.

    ``active``/``probe`` are read lock-free on the submit path (a stale
    answer just means one missed parking opportunity — never a wrong
    answer); parking and settling are locked.  The owner (the serving
    dispatcher) guarantees open → close pairing: ``close`` fans the
    batch's verdicts out to every parked future, or rejects them
    RETRIABLE on batch failure (the parked submitters' envelopes
    re-submit — they were not at fault)."""

    def __init__(self, registry: Optional[_metrics.Metrics] = None) -> None:
        self._lock = threading.Lock()
        self._sorted: Optional[np.ndarray] = None  # cols window
        self._raw: Optional[np.ndarray] = None
        self._order: Optional[np.ndarray] = None  # lazy argsort of _raw
        self._map: Optional[Dict[Any, int]] = None  # rels window
        self._parked: List[tuple] = []
        self._active = False
        self._m = registry or _metrics.default

    @property
    def active(self) -> bool:
        return self._active

    def open_cols(self, keys: np.ndarray, keys_sorted: np.ndarray) -> None:
        """Open a columnar window: ``keys`` in batch-row order plus the
        caller's sorted copy (the dispatcher sorts once for its
        unique-work metric anyway)."""
        with self._lock:
            self._raw = keys
            self._sorted = keys_sorted
            self._order = None
            self._map = None
            self._parked = []
            self._active = True

    def open_map(self, key_to_row: Dict[Any, int]) -> None:
        """Open a relationship window (key → batch row index)."""
        with self._lock:
            self._map = key_to_row
            self._raw = self._sorted = self._order = None
            self._parked = []
            self._active = True

    def probe(self, key) -> bool:
        """Lock-free scalar probe: could this key be in flight?  False
        rules parking out without any per-row work (the common case);
        True is only a hint — try_park re-checks under the lock."""
        if not self._active:
            return False
        ks = self._sorted
        if ks is not None:
            if not isinstance(key, int) or not ks.shape[0]:
                return False
            p = int(np.searchsorted(ks, key))
            return p < ks.shape[0] and int(ks[p]) == key
        km = self._map
        return km is not None and key in km

    def try_park(self, keys, future, kind: str, n: int) -> bool:
        """Park a whole submission on the open window iff EVERY row
        duplicates an in-flight key.  Partial overlap queues normally
        (the overlapping rows become cache hits one batch later)."""
        with self._lock:
            if not self._active:
                return False
            if self._sorted is not None:
                if not isinstance(keys, np.ndarray):
                    return False
                pos = np.minimum(
                    np.searchsorted(self._sorted, keys),
                    self._sorted.shape[0] - 1,
                )
                if not (self._sorted[pos] == keys).all():
                    return False
                if self._order is None:
                    self._order = np.argsort(self._raw, kind="stable")
                rows = self._order[pos]
            else:
                g = self._map.get
                rows = []
                for k in keys_list(keys):
                    i = g(k)
                    if i is None:
                        return False
                    rows.append(i)
            self._parked.append((rows, future, kind, n))
        self._m.inc("serve.dedup_parked", n)
        return True

    def close(self, verdicts, error: Optional[BaseException],
              t_done: float) -> int:
        """Settle the window: resolve every parked future from the
        batch's verdicts (or reject retriable on ``error``).  Returns
        the number of parked submissions settled."""
        with self._lock:
            if not self._active:
                return 0
            parked, self._parked = self._parked, []
            self._raw = self._sorted = self._order = self._map = None
            self._active = False
        from ..utils.errors import UnavailableError

        m = self._m
        for rows, fut, kind, n in parked:
            if fut.done():
                continue
            if error is not None or verdicts is None:
                fut._reject(UnavailableError(
                    "deduplicated twin's batch failed; re-submit"
                ), t_done)
                continue
            if kind == "cols":
                out = np.asarray(verdicts, bool)[np.asarray(rows, np.int64)]
            else:
                out = [bool(verdicts[i]) for i in rows]
            fut._resolve(out, t_done)
            m.inc("serve.checks", n)
            m.observe("serve.request_s", t_done - fut.t_submit)
        return len(parked)

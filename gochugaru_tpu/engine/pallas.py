"""Pallas fused probe backend: one HBM pass per probed table.

The XLA check kernel (engine/flat.py) compiles each bucket probe as a
chain of separate gather ops — bucket-offset read, contiguous block gather,
packed shift/mask decode, key compare, caveat/expiry gate, OR reduce —
and XLA materializes the gathered (and then the decoded) block between
the stages it cannot fuse across a gather.  On TPU those intermediates
cross HBM; the roofline ledger (utils/perf.py) says the superseded
kernel reached 2-3% of the measured ceiling, and the remaining bytes
are exactly these re-crossings.

This module hand-fuses the whole probe into ONE Pallas kernel per site:

    hash (mix32) → bucket offset (anchor + residual, VMEM-resident)
      → bucket block fetch (double-buffered async-copy DMA from HBM)
      → packed ``decode_block`` in registers
      → key compare (+ expiry/until gate where the site allows)
      → short-circuited OR reduce

so the packed table bytes cross HBM exactly once and the kernel's
output is the site's REDUCED answer (or the few gate lanes the CEL tri
VM still needs), never the decoded block.  Hot state — bucket offsets,
offset anchors, aligned-ladder rows under ``VMEM_TABLE_MAX_BYTES`` —
rides VMEM for the whole batch instead of being re-gathered from HBM
per probe (``perf.vmem_resident_bytes`` reports what is pinned).

Kernel modes (one builder, static tails):

- ``block``   decoded int32[B, cap, W] candidate block — the drop-in
              ``pblock`` replacement; parity with the XLA path is
              bitwise by construction (same clamp, same rows, same
              decode).
- ``any``     bool[B] hit-any (pus / closure-overflow sites): compare
              AND reduce fused, no block output at all.
- ``until2``  (bool[B], bool[B]) — hit ∧ until-plane > now for lanes
              2/3 (T-index and closure probes), reduced in-kernel.
- ``gate``    (hit, live[, cav, ctx]) [B, cap] lanes — the direct-edge
              probe: expiry gate fused; the CEL tri VM (caveats/
              device.py) consumes the cav/ctx lanes outside, which are
              ~W/4 of the decoded block the XLA path materializes.
- ``runs``    (lo, ln) int32[B] — the frontier/SpMM run probe
              (engine/spmv.py): offset + in-bucket bisect over the
              DMA'd block, so the K-hop lookup programs inherit the
              fused probe too.

Portability/fallback contract (ISSUE 20): ``EngineConfig.pallas`` is
tri-state — None (auto: on for TPU, off elsewhere), True (force; used
by tests, which run the kernels in INTERPRET mode under
``JAX_PLATFORMS=cpu``), False (the XLA path, byte-for-byte the parity
oracle).  ``jax.experimental.pallas`` is feature-probed ONCE (the
shard_map feature-detect discipline from parallel/sharded.py): a
jaxlib without it degrades auto/forced to the XLA path with a single
``pallas.degraded`` warning counter — never an ImportError at client
construction.

Interpret-mode honesty: under ``JAX_PLATFORMS=cpu`` every kernel here
runs through the Pallas interpreter — that checks CORRECTNESS
(bitwise parity against the XLA path on randomized worlds), not speed.
The one-pass byte accounting is a model (utils/perf.py
``pallas_bytes_model``), asserted structurally in tests; the measured
win is a silicon expectation, armed as tpu_watch.sh priority 4.0.
First-silicon bring-up may need the scalar-prefetch grid variant
(``PrefetchScalarGridSpec``) for the per-query offset scalars — the
A/B harness exists to find out.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..utils import metrics as _metrics

_mt = _metrics.default

# ---------------------------------------------------------------------------
# feature detect (probed once; the shard_map check_vma discipline)
# ---------------------------------------------------------------------------

_FEATURE: Dict[str, Any] = {"probed": False, "ok": False, "err": ""}
_WARNED: Dict[str, bool] = {"degraded": False}


def available() -> bool:
    """Whether this jaxlib ships a usable ``jax.experimental.pallas``.
    Probed exactly once per process; a missing/old install records the
    error and counts ``pallas.unavailable`` instead of raising."""
    if not _FEATURE["probed"]:
        _FEATURE["probed"] = True
        try:
            from jax.experimental import pallas as _pl  # noqa: F401
            from jax.experimental.pallas import tpu as _pltpu  # noqa: F401

            _FEATURE["ok"] = True
        except Exception as e:  # pragma: no cover - depends on install
            _FEATURE["ok"] = False
            _FEATURE["err"] = f"{type(e).__name__}: {e}"
            _mt.inc("pallas.unavailable")
    return bool(_FEATURE["ok"])


def resolve(config) -> bool:
    """The resolved ``EngineConfig.pallas`` flag: None = auto (on for
    TPU when available, off elsewhere — the XLA path stays the
    portability default); True degrades to False when the feature probe
    fails, with ONE warning + ``pallas.degraded`` counter."""
    knob = getattr(config, "pallas", None)
    if knob is False:
        return False
    ok = available()
    if knob is True:
        if not ok and not _WARNED["degraded"]:
            _WARNED["degraded"] = True
            _mt.inc("pallas.degraded")
            warnings.warn(
                "EngineConfig.pallas=True but jax.experimental.pallas is"
                f" unavailable ({_FEATURE['err']}); serving on the XLA"
                " path",
                RuntimeWarning,
                stacklevel=2,
            )
        return ok
    if not ok:
        return False
    import jax

    return jax.default_backend() == "tpu"


def interpret_mode() -> bool:
    """Interpret off-TPU: the kernels then run through the Pallas
    interpreter (correctness-only; tests pin ``JAX_PLATFORMS=cpu``)."""
    import jax

    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# VMEM residency plan
# ---------------------------------------------------------------------------

#: per-array ceiling for pinning an offsets/anchor/ladder array
#: VMEM-resident (v5e VMEM is 128 MB/core; the budget stays far under
#: it so the compiler keeps headroom for the double-buffered scratch)
VMEM_TABLE_MAX_BYTES = 4 << 20


def _nbytes(a) -> int:
    return int(np.prod(a.shape)) * int(np.dtype(a.dtype).itemsize)


def vmem_ok(a) -> bool:
    """Whether one array is small enough to pin VMEM-resident."""
    return _nbytes(a) <= VMEM_TABLE_MAX_BYTES


def vmem_plan(arrays) -> Dict[str, int]:
    """{key: nbytes} of the arrays the fused kernels pin VMEM-resident:
    bucket offsets, packed-offset anchors, and aligned-ladder level
    tables under the per-array budget.  Pure shape arithmetic — safe at
    prepare time on host or device arrays."""
    out: Dict[str, int] = {}
    for k, v in arrays.items():
        if not (
            k.endswith("_off") or k.endswith("_off_a")
            or k.endswith("_start") or "_al" in k
        ):
            continue
        nb = _nbytes(v)
        if nb <= VMEM_TABLE_MAX_BYTES:
            out[k] = nb
    return out


def publish_vmem(arrays, registry: Optional[_metrics.Metrics] = None) -> int:
    """Publish ``perf.vmem_resident_bytes`` (the hot state the fused
    kernels keep on-chip for the whole batch) at prepare time."""
    m = registry or _metrics.default
    total = sum(vmem_plan(arrays).values())
    m.set_gauge("perf.vmem_resident_bytes", float(total))
    return total


# ---------------------------------------------------------------------------
# the fused probe kernel
# ---------------------------------------------------------------------------

_FNV_OFFSET = 2166136261
_FNV_PRIME = 16777619


def _mix32_scalar(vals, jnp):
    """mix32 (engine/hash.py) on in-kernel scalars — identical uint32
    wrap-around arithmetic, so the bucket choice is bit-identical."""
    h = jnp.uint32(_FNV_OFFSET)
    for v in vals:
        h = (h ^ v.astype(jnp.uint32)) * jnp.uint32(_FNV_PRIME)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _decode(blk, spec, jnp):
    """packed.decode_block, restated kernel-safe.

    The stock decode materializes dictionary columns with
    ``jnp.asarray(dicts[id])[v]`` — a gather from a *captured constant
    array*, which ``pallas_call`` rejects (kernel closures may not hold
    array constants).  The dict values are static Python ints, so inside
    the kernel the lookup becomes a select chain over the (tiny, ≤256)
    domain — bitwise-equal to the gather for every in-domain index, and
    pack_rows guarantees all stored indices are in-domain."""
    if spec is None:
        return blk.astype(jnp.int32)
    w, lanes, fields, dicts = spec
    l32 = blk.astype(jnp.int32)
    cols = [None] * w
    for j, (bits, base, delta_of, dict_id, off_bit) in enumerate(fields):
        if bits == 0:
            col = jnp.full(blk.shape[:-1], base, jnp.int32)
        else:
            lane, sh = off_bit >> 4, off_bit & 15
            v = l32[..., lane] >> sh if sh else l32[..., lane]
            if sh + bits > 16:
                v = v | (l32[..., lane + 1] << (16 - sh))
            if bits < 32:
                v = v & jnp.int32((1 << bits) - 1)
            if dict_id >= 0:
                dv = dicts[dict_id]
                col = jnp.full(v.shape, dv[0], jnp.int32)
                for i, val in enumerate(dv[1:], 1):
                    col = jnp.where(v == i, jnp.int32(val), col)
            else:
                col = v + jnp.int32(base) if base else v
        if delta_of >= 0:
            col = col + cols[delta_of]
        cols[j] = col
    return jnp.stack(cols, axis=-1)


def fused_probe(
    q_cols: Sequence,
    off,
    tbl,
    *,
    cap: int,
    spec=None,
    off_a=None,
    ashift: Optional[int] = None,
    mode: str = "block",
    now=None,
    gate: Tuple[bool, bool, bool] = (False, False, False),
    lay: Optional[Dict[str, int]] = None,
):
    """One fused bucket probe over the off+interleave layout.

    ``q_cols`` are the query key columns (any lattice shape, flattened
    here and restored on return); ``off``/``off_a`` the bucket offsets
    (+ packed anchor, shift ``ashift``); ``tbl`` the interleaved block
    table; ``spec`` the packed decode spec (None = plain int32 table).
    ``mode``/``gate``/``lay``/``now`` select the fused tail — see the
    module docstring.  Returns mode-shaped arrays.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    interp = interpret_mode()
    shape = np.broadcast_shapes(*[tuple(c.shape) for c in q_cols])
    qf = [
        jnp.broadcast_to(c, shape).reshape(-1).astype(jnp.int32)
        for c in q_cols
    ]
    B = int(qf[0].shape[0])
    NQ = len(qf)
    rows, w_raw = int(tbl.shape[0]), int(tbl.shape[1])
    W = int(spec[0]) if spec is not None else w_raw
    size = int(off.shape[0]) - 1
    packed_off = off_a is not None
    hasexp, hascav, needctx = gate
    _mt.inc("pallas.kernel_traces")

    def _start_of(i, refs):
        """Scalar bucket start of query ``i`` (hash → offset read) —
        recomputed at wait time, so the DMA pipeline carries nothing."""
        qs = [refs[j][i] for j in range(NQ)]
        h = (
            _mix32_scalar(qs, jnp) & jnp.uint32(size - 1)
        ).astype(jnp.int32)
        if packed_off:
            o_ref, a_ref = refs[NQ], refs[NQ + 1]
            start = a_ref[h >> ashift] + o_ref[h].astype(jnp.int32)
        else:
            start = refs[NQ][h]
        # slice_blocks' clamp, verbatim: 0 ≤ s ≤ rows - cap
        return jnp.clip(start, 0, rows - cap), qs

    n_in = NQ + (2 if packed_off else 1) + (1 if now is not None else 0)

    def kern(*refs):
        ins = refs[:n_in]
        tbl_ref = refs[n_in]
        outs = refs[n_in + 1:-2]
        scratch, sem = refs[-2], refs[-1]
        nr = ins[-1][0] if now is not None else None

        def fetch(i, slot):
            s0, _ = _start_of(i, ins)
            return pltpu.make_async_copy(
                tbl_ref.at[pl.ds(s0, cap)], scratch.at[slot], sem.at[slot]
            )

        fetch(0, 0).start()

        def body(i, _):
            slot = jax.lax.rem(i, 2)
            nxt = jax.lax.rem(i + 1, 2)

            @pl.when(i + 1 < B)
            def _():  # software pipeline: next bucket in flight
                fetch(i + 1, nxt).start()

            fetch(i, slot).wait()
            _s0, qs = _start_of(i, ins)
            blk = _decode(scratch[slot], spec, jnp)  # [cap, W] registers
            if mode == "runs":
                _emit_runs(i, qs, blk, _s0, outs)
                return 0
            hit = jnp.ones((cap,), bool)
            guard = None
            for j, q in enumerate(qs):
                hit = hit & (blk[:, j] == q)
                guard = (q >= 0) if guard is None else (guard & (q >= 0))
            hit = hit & guard
            if mode == "block":
                outs[0][i] = blk
            elif mode == "any":
                outs[0][i] = jnp.any(hit)
            elif mode == "until2":
                outs[0][i] = jnp.any(hit & (blk[:, 2] > nr))
                outs[1][i] = jnp.any(hit & (blk[:, 3] > nr))
            else:  # gate
                live = hit
                if hasexp:
                    exp = jnp.where(hit, blk[:, lay["exp"]], 0)
                    live = hit & ((exp == 0) | (exp > nr))
                outs[0][i] = hit
                outs[1][i] = live
                if hascav and needctx:
                    outs[2][i] = jnp.where(hit, blk[:, lay["cav"]], 0)
                    outs[3][i] = jnp.where(hit, blk[:, lay["ctx"]], -1)
                elif hascav:
                    outs[2][i] = jnp.where(hit, blk[:, lay["cav"]], 0)
            return 0

        jax.lax.fori_loop(0, B, body, 0)

    def _emit_runs(i, qs, blk, s0, outs):
        """In-bucket bisect over the DMA'd block — spmv._make_runs'
        math verbatim, reading col0 from the VMEM copy."""
        o_ref = refs_runs["o"]
        h = (
            _mix32_scalar(qs, jnp) & jnp.uint32(size - 1)
        ).astype(jnp.int32)
        if packed_off:
            a_ref = refs_runs["a"]
            start = a_ref[h >> ashift] + o_ref[h].astype(jnp.int32)
            end = a_ref[(h + 1) >> ashift] + o_ref[h + 1].astype(jnp.int32)
        else:
            start = o_ref[h]
            end = o_ref[h + 1]
        last = rows - 1
        col0 = blk[:, 0]
        steps = max(int(cap).bit_length(), 1)
        key = qs[0]

        def bisect(left: bool):
            lo = start
            n = end - start
            for _ in range(steps):
                alive = n > 0
                half = n >> 1
                mid = lo + half
                v = col0[jnp.clip(mid, 0, last) - s0]
                go = alive & ((v < key) if left else (v <= key))
                lo = jnp.where(go, mid + 1, lo)
                n = jnp.where(go, n - half - 1, jnp.where(alive, half, 0))
            return lo

        lo = bisect(True)
        ln = bisect(False) - lo
        dead = key < 0
        outs[0][i] = jnp.where(dead, 0, lo)
        outs[1][i] = jnp.where(dead, 0, ln)

    refs_runs: Dict[str, Any] = {}

    # ---- specs: queries + offsets VMEM-resident, table stays in HBM ----
    vm = pltpu.TPUMemorySpace.ANY
    in_specs = [pl.BlockSpec(memory_space=vm) for _ in range(n_in + 1)]
    out_specs, out_shapes = _out_layout(mode, B, cap, W, gate, jnp, pl, vm)
    args = list(qf)
    args.append(off)
    if packed_off:
        args.append(off_a)
    if now is not None:
        args.append(jnp.reshape(now, (1,)).astype(jnp.int32))
    args.append(tbl)

    if mode == "runs":
        # the bisect tail reads the offset refs directly; expose them
        # through the closure by index (qf..., off[, off_a][, now], tbl)
        def kern_runs(*refs):
            refs_runs["o"] = refs[NQ]
            if packed_off:
                refs_runs["a"] = refs[NQ + 1]
            kern(*refs)

        body_fn = kern_runs
    else:
        body_fn = kern

    outs = pl.pallas_call(
        body_fn,
        out_shape=out_shapes,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((2, cap, w_raw), tbl.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interp,
    )(*args)

    return _reshape_out(mode, outs, shape, gate)


def fused_probe_aligned(
    q_cols: Sequence,
    tbls: Sequence,
    caps: Sequence[int],
    sw: int,
    *,
    spec=None,
    mode: str = "block",
    now=None,
    gate: Tuple[bool, bool, bool] = (False, False, False),
    lay: Optional[Dict[str, int]] = None,
):
    """The aligned-ladder twin of :func:`fused_probe`: one row DMA per
    width-stratum level (level ≥ 1 salted — hash.probe_aligned's math
    verbatim), levels concatenated and decoded in registers.  Small
    ladder levels sit VMEM-resident; the fused tail is shared."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .hash import _level_salt

    interp = interpret_mode()
    shape = np.broadcast_shapes(*[tuple(c.shape) for c in q_cols])
    qf = [
        jnp.broadcast_to(c, shape).reshape(-1).astype(jnp.int32)
        for c in q_cols
    ]
    B = int(qf[0].shape[0])
    NQ = len(qf)
    L = len(tbls)
    capT = int(sum(caps))
    W = int(spec[0]) if spec is not None else sw
    sizes = [int(t.shape[0]) for t in tbls]
    hasexp, hascav, needctx = gate
    _mt.inc("pallas.kernel_traces")

    n_in = NQ + (1 if now is not None else 0)

    def kern(*refs):
        ins = refs[:n_in]
        tbl_refs = refs[n_in:n_in + L]
        outs = refs[n_in + L:-2 * L]
        scratches = refs[-2 * L:-L]
        sems = refs[-L:]
        nr = ins[NQ][0] if now is not None else None

        def q_at(i):
            return [ins[j][i] for j in range(NQ)]

        def h_of(qs, lvl):
            q0 = qs[0] ^ jnp.int32(_level_salt(lvl)) if lvl else qs[0]
            return (
                _mix32_scalar([q0] + list(qs[1:]), jnp)
                & jnp.uint32(sizes[lvl] - 1)
            ).astype(jnp.int32)

        def fetch(i, slot, lvl):
            h = h_of(q_at(i), lvl)
            return pltpu.make_async_copy(
                tbl_refs[lvl].at[pl.ds(h, 1)],
                scratches[lvl].at[slot],
                sems[lvl].at[slot],
            )

        for lvl in range(L):
            fetch(0, 0, lvl).start()

        def body(i, _):
            slot = jax.lax.rem(i, 2)
            nxt = jax.lax.rem(i + 1, 2)

            @pl.when(i + 1 < B)
            def _():
                for lvl in range(L):
                    fetch(i + 1, nxt, lvl).start()

            qs = q_at(i)
            parts = []
            for lvl in range(L):
                fetch(i, slot, lvl).wait()
                parts.append(
                    scratches[lvl][slot].reshape(caps[lvl], sw)
                )
            raw = parts[0] if L == 1 else jnp.concatenate(parts, axis=0)
            blk = _decode(raw, spec, jnp)  # [capT, W]
            hit = jnp.ones((capT,), bool)
            guard = None
            for j, q in enumerate(qs):
                hit = hit & (blk[:, j] == q)
                guard = (q >= 0) if guard is None else (guard & (q >= 0))
            hit = hit & guard
            if mode == "block":
                outs[0][i] = blk
            elif mode == "any":
                outs[0][i] = jnp.any(hit)
            elif mode == "until2":
                outs[0][i] = jnp.any(hit & (blk[:, 2] > nr))
                outs[1][i] = jnp.any(hit & (blk[:, 3] > nr))
            else:  # gate
                live = hit
                if hasexp:
                    exp = jnp.where(hit, blk[:, lay["exp"]], 0)
                    live = hit & ((exp == 0) | (exp > nr))
                outs[0][i] = hit
                outs[1][i] = live
                if hascav and needctx:
                    outs[2][i] = jnp.where(hit, blk[:, lay["cav"]], 0)
                    outs[3][i] = jnp.where(hit, blk[:, lay["ctx"]], -1)
                elif hascav:
                    outs[2][i] = jnp.where(hit, blk[:, lay["cav"]], 0)
            return 0

        jax.lax.fori_loop(0, B, body, 0)

    vm = pltpu.TPUMemorySpace.ANY
    in_specs = [pl.BlockSpec(memory_space=vm) for _ in range(n_in + L)]
    out_specs, out_shapes = _out_layout(
        mode, B, capT, W, gate, jnp, pl, vm
    )
    args = list(qf)
    if now is not None:
        args.append(jnp.reshape(now, (1,)).astype(jnp.int32))
    args.extend(tbls)

    outs = pl.pallas_call(
        kern,
        out_shape=out_shapes,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=(
            [pltpu.VMEM((2, 1, int(t.shape[1])), t.dtype) for t in tbls]
            + [pltpu.SemaphoreType.DMA((2,)) for _ in tbls]
        ),
        interpret=interp,
    )(*args)

    return _reshape_out(mode, outs, shape, gate)


def _out_layout(mode, B, cap, W, gate, jnp, pl, vm):
    """(out_specs, out_shapes) per kernel mode."""
    import jax

    hasexp, hascav, needctx = gate
    if mode == "block":
        shapes = [jax.ShapeDtypeStruct((B, cap, W), jnp.int32)]
    elif mode == "any":
        shapes = [jax.ShapeDtypeStruct((B,), jnp.bool_)]
    elif mode == "until2":
        shapes = [
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
        ]
    elif mode == "runs":
        shapes = [
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
        ]
    else:  # gate
        shapes = [
            jax.ShapeDtypeStruct((B, cap), jnp.bool_),
            jax.ShapeDtypeStruct((B, cap), jnp.bool_),
        ]
        if hascav:
            shapes.append(jax.ShapeDtypeStruct((B, cap), jnp.int32))
            if needctx:
                shapes.append(jax.ShapeDtypeStruct((B, cap), jnp.int32))
    specs = [pl.BlockSpec(memory_space=vm) for _ in shapes]
    return specs, shapes


def _reshape_out(mode, outs, shape, gate):
    """Restore the caller's query-lattice shape on every output."""
    hasexp, hascav, needctx = gate
    if mode == "block":
        blk = outs if not isinstance(outs, (list, tuple)) else outs[0]
        return blk.reshape(shape + blk.shape[1:])
    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    done = [o.reshape(shape + o.shape[1:]) for o in outs]
    if mode == "any":
        return done[0]
    return tuple(done)

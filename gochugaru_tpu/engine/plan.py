"""Static device-program structure compiled from a schema.

``build_plan`` turns a CompiledSchema into the *static* structure the JAX
engine's codegen closes over: tupleset slot numbering, the relation slots
that need leaf tests, permission expressions lowered to nested tuples, a
global topological update order, and schema-derived iteration bounds.  None
of this touches tuple data — it is fixed at WriteSchema time, so the jitted
check function is traced once per (schema, config, shape-bucket).

``EngineConfig`` holds the static capacity caps (SURVEY.md §7 "hard parts":
hop caps must be provably sufficient for non-recursive schemas — the
``for_schema`` constructor derives them from the compiler's depth analysis;
recursive schemas fall back to configurable caps with overflow detection
and host-oracle fallback).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

from ..schema.ast import (
    Arrow,
    Exclusion,
    Expr,
    Intersection,
    Nil,
    RelationRef,
    Union,
)
from ..schema.compiler import CompiledSchema

# Expression IR: nested tuples, all leaves static ints.
#   ("ref", slot) ("arrow", ts_idx, right_slot) ("union", (c...))
#   ("inter", (c...)) ("excl", base, sub) ("nil",)
ExprIR = tuple


@dataclass(frozen=True)
class EngineConfig:
    """Static capacity caps for the device evaluator.  Every cap has an
    overflow flag on device; overflowing queries are re-checked on the host
    oracle, so caps trade device coverage for speed, never correctness."""

    closure_size: int = 256  # max usersets a subject transitively belongs to
    seed_cap: int = 64  # max direct group memberships gathered per subject
    prop_cap: int = 8  # max parents per userset per closure hop
    closure_hops: int = 8  # userset-nesting depth walked on device
    subgraph_nodes: int = 8  # max arrow-reachable nodes per resource
    arrow_fanout: int = 4  # max tuples walked per (node, tupleset relation)
    us_leaf_cap: int = 8  # max userset grants tested per (node, relation)
    eval_iters: int = 2  # fixpoint iterations over the rewrite system
    batch_bucket_min: int = 8  # pad batch/unique-subject counts to pow2 ≥ this

    @staticmethod
    def for_schema(compiled: CompiledSchema, **overrides) -> "EngineConfig":
        cfg = EngineConfig()
        userset_depth = _userset_depth(compiled)
        has_arrows = bool(compiled.tupleset_pairs)
        if userset_depth == 0:
            cfg = replace(cfg, closure_hops=0)
        elif userset_depth > 0:
            cfg = replace(cfg, closure_hops=min(userset_depth, cfg.closure_hops))
        # -1 (cyclic): keep the default cap.
        if not has_arrows:
            cfg = replace(cfg, subgraph_nodes=1, eval_iters=1)
        elif not compiled.is_recursive:
            # acyclic arrows: the subgraph is as deep as the longest arrow
            # chain; one topo-ordered iteration resolves everything.
            cfg = replace(
                cfg,
                subgraph_nodes=max(2, min(2 ** (compiled.depth), 32)),
                eval_iters=1,
            )
        else:
            # recursion through arrows (e.g. folder parent->view): value
            # flows one node per iteration along the recursive chain.
            cfg = replace(cfg, eval_iters=cfg.subgraph_nodes)
        return replace(cfg, **overrides)


def _userset_depth(compiled: CompiledSchema) -> int:
    """Nesting depth of the relation-userset graph: 0 = no relation admits
    userset subjects; -1 = cyclic (groups-in-groups); else the max depth."""
    schema = compiled.schema
    edges: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for tname, d in schema.definitions.items():
        for rname, relation in d.relations.items():
            for a in relation.allowed:
                if a.relation:
                    edges.setdefault((tname, rname), []).append((a.type, a.relation))
    if not edges:
        return 0
    memo: Dict[Tuple[str, str], int] = {}
    stack: set = set()
    cyclic = False

    def depth(node: Tuple[str, str]) -> int:
        nonlocal cyclic
        if node in memo:
            return memo[node]
        if node in stack:
            cyclic = True
            return 0
        stack.add(node)
        d = 0
        for nxt in edges.get(node, ()):  # noqa: B905
            d = max(d, 1 + depth(nxt))
        stack.discard(node)
        memo[node] = d
        return d

    m = max(depth(n) for n in list(edges))
    return -1 if cyclic else m


@dataclass(frozen=True)
class TypeProgram:
    type_name: str
    schema_tid: int
    #: (perm_slot, expr_ir) pairs for this type
    perms: Tuple[Tuple[int, ExprIR], ...]


@dataclass(frozen=True)
class DevicePlan:
    """Everything static the device codegen needs."""

    ts_slots: Tuple[int, ...]  # tupleset slots; index = ts_idx in arrays
    rel_leaf_slots: Tuple[int, ...]  # relation slots needing leaf tests
    #: (type_name, schema_tid, perm_slot, expr_ir), globally topo-ordered by
    #: dependency depth so one fixpoint iteration resolves any acyclic chain
    topo_programs: Tuple[Tuple[str, int, int, ExprIR], ...]
    num_slots: int
    two_plane: bool  # caveats present → track (definite, possible) planes
    has_permission_usersets: bool
    num_schema_types: int


def _lower_expr(
    e: Expr, ts_index: Dict[int, int], slot_of: Dict[str, int]
) -> ExprIR:
    if isinstance(e, RelationRef):
        return ("ref", slot_of[e.name])
    if isinstance(e, Arrow):
        return ("arrow", ts_index[slot_of[e.left]], slot_of[e.right])
    if isinstance(e, Union):
        return ("union", tuple(_lower_expr(c, ts_index, slot_of) for c in e.children))
    if isinstance(e, Intersection):
        return ("inter", tuple(_lower_expr(c, ts_index, slot_of) for c in e.children))
    if isinstance(e, Exclusion):
        return (
            "excl",
            _lower_expr(e.base, ts_index, slot_of),
            _lower_expr(e.subtracted, ts_index, slot_of),
        )
    if isinstance(e, Nil):
        return ("nil",)
    raise TypeError(f"unknown expression node {e!r}")


def build_plan(compiled: CompiledSchema) -> DevicePlan:
    ts_slots = tuple(sorted(compiled.tupleset_slots))
    ts_index = {slot: i for i, slot in enumerate(ts_slots)}
    slot_of = compiled.slot_of_name

    rel_leaf = set()
    for d in compiled.schema.definitions.values():
        for rname in d.relations:
            rel_leaf.add(slot_of[rname])

    programs: List[Tuple[str, int, int, ExprIR]] = []
    for tname, d in compiled.schema.definitions.items():
        tid = compiled.type_ids[tname]
        for pname, perm in d.permissions.items():
            programs.append(
                (
                    tname,
                    tid,
                    slot_of[pname],
                    _lower_expr(perm.expr, ts_index, slot_of),
                )
            )
    # Global topological order by dependency depth: shallow first, so within
    # one iteration every acyclic dependency is already updated when read.
    programs.sort(key=lambda p: (compiled.item_depths.get((p[0], _name_of(compiled, p[2])), 0), p[0], p[2]))

    return DevicePlan(
        ts_slots=ts_slots,
        rel_leaf_slots=tuple(sorted(rel_leaf)),
        topo_programs=tuple(programs),
        num_slots=max(compiled.num_slots, 1),
        two_plane=bool(compiled.schema.caveats),
        has_permission_usersets=compiled.has_permission_usersets,
        num_schema_types=len(compiled.type_ids),
    )


def _name_of(compiled: CompiledSchema, slot: int) -> str:
    for name, s in compiled.slot_of_name.items():
        if s == slot:
            return name
    return ""

"""Static device-program structure compiled from a schema.

``build_plan`` turns a CompiledSchema into the *static* structure the JAX
engine's codegen closes over: tupleset slot numbering, the relation slots
that need leaf tests, permission expressions lowered to nested tuples, a
global topological update order, and schema-derived iteration bounds.  None
of this touches tuple data — it is fixed at WriteSchema time, so the jitted
check function is traced once per (schema, config, shape-bucket).

``EngineConfig`` holds the static capacity caps (SURVEY.md §7 "hard parts":
hop caps must be provably sufficient for non-recursive schemas — the
``for_schema`` constructor derives them from the compiler's depth analysis;
recursive schemas fall back to configurable caps with overflow detection
and host-oracle fallback).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

from ..schema.ast import (
    Arrow,
    Exclusion,
    Expr,
    Intersection,
    Nil,
    RelationRef,
    Union,
)
from ..schema.compiler import CompiledSchema, _expr_refs

# Expression IR: nested tuples, all leaves static ints.
#   ("ref", slot) ("arrow", ts_idx, right_slot) ("union", (c...))
#   ("inter", (c...)) ("excl", base, sub) ("nil",)
ExprIR = tuple


@dataclass(frozen=True)
class EngineConfig:
    """Static capacity caps for the device evaluator.  Every cap has an
    overflow flag on device; overflowing queries are re-checked on the host
    oracle, so caps trade device coverage for speed, never correctness."""

    closure_size: int = 256  # max usersets a subject transitively belongs to
    seed_cap: int = 64  # max direct group memberships gathered per subject
    prop_cap: int = 8  # max parents per userset per closure hop
    closure_hops: int = 8  # userset-nesting depth walked on device
    subgraph_nodes: int = 8  # max arrow-reachable nodes per resource
    arrow_fanout: int = 4  # max tuples walked per (node, tupleset relation)
    us_leaf_cap: int = 8  # max userset grants tested per (node, relation)
    eval_iters: int = 2  # fixpoint iterations over the rewrite system
    batch_bucket_min: int = 8  # pad batch/unique-subject counts to pow2 ≥ this
    # -- flat (hash-probe) engine caps (engine/flat.py) -----------------
    use_flat: bool = True  # single-chip checks use the flat kernel
    flat_recursion: int = 8  # inline budget per recursive (type, slot) pair
    flat_max_slots: int = 8  # max distinct permissions per flat dispatch
    closure_source_cap: int = 4096  # max flattened pairs per closure source
    #: max product of arrow-child dims per query in the unrolled lattice;
    #: beyond it an arrow probes child-existence only (possible → host)
    flat_max_width: int = 256
    #: materialize the userset-grant join index (engine/flat.py T-index):
    #: us-edges ⋈ closure, so a userset grant test is ONE hash probe
    flat_tindex: bool = True
    #: T-index size budget as a multiple of the userset row count;
    #: exceeding it disables the index (KU probe path still answers)
    flat_tindex_factor: int = 64
    #: block-slice table layout: bucket-ordered interleaved tables probed
    #: with ONE contiguous [cap, w] slice per query (engine/hash.py) — ~2
    #: gathers per probe site instead of 2 + cap·(1 + nkey) scattered ones.
    #: TPU gathers cost ~a row per cycle regardless of width, so this is
    #: the TPU-shaped layout; False falls back to scattered 1-D probes
    flat_blockslice: bool = True
    #: accumulated delta-level rows (adds + tombstones) beyond
    #: max(this, E/8) trigger compaction: the next prepare rebuilds the
    #: base instead of growing the overlay (engine/flat.py delta level)
    flat_delta_min_compact: int = 65_536
    #: host-side mirror of the same bound: overlay rows beyond
    #: max(this, E/8) make store/delta.py materialize the LSM chain into
    #: a fresh base instead of deferring the merge.  Lower keeps probe
    #: depth (and find_in_view cost) small at the price of more frequent
    #: O(E) merges; the background chain compactor (store/group.py)
    #: works against half this trip so the merge lands off the write
    #: path.  Tunable (tune/tuner.py) off chain-depth telemetry
    lsm_compact_min: int = 65_536
    #: prewarm the transposed lookup index in a background thread at full
    #: prepare time (worlds ≥ LOOKUP_PREWARM_MIN_EDGES edges): cold
    #: lookup_resources joins a mostly-finished build instead of paying
    #: the O(E log E) sort inside the first user-facing query.  Only
    #: engaged when the HOST walker would serve lookups — snapshots
    #: carrying the reverse-CSR index (flat_rev_index) answer on the
    #: device frontier path and never need the transposed host index
    lookup_prewarm: bool = True
    #: build the reverse-CSR lookup index alongside the forward tables
    #: (engine/rev.py: rvx/rax/fwx + offsets): LookupResources/
    #: LookupSubjects then run as device-resident masked frontier SpMV
    #: (engine/spmv.py) instead of the host walker.  Costs ~16-24 packed
    #: bytes/edge of extra residency; False falls back to the walker
    flat_rev_index: bool = True
    #: per-dispatch row budget of the frontier expansion kernel: each
    #: hop emits matches in chunks of this many rows (fixed shape — one
    #: compiled program regardless of fan-out)
    lookup_chunk: int = 65_536
    #: frontier-key padding floor (pow2 tiers above it): bounds expansion
    #: kernel retraces the way batch_bucket_min bounds check dispatches
    lookup_frontier_min: int = 1_024
    #: dl_* table shape floor: delta tables pre-size to this many rows so
    #: consecutive revisions keep ONE compiled kernel instead of
    #: retracing at every pow2 row-count boundary (a retrace costs ~1s —
    #: the dominant term of the Watch-reindex loop without the floor);
    #: beyond the floor, shapes double (log-many retraces per chain)
    flat_delta_floor: int = 16_384
    #: flatten self-recursive arrow hierarchies into precomputed ancestor
    #: closures (the resource-side Leopard index, engine/flat.py
    #: rc_candidates/_arrow_closure): a depth-D folder tree evaluates in
    #: ONE level instead of D unrolled recursion levels
    flat_rc_index: bool = True
    #: fold whole union/arrow-chain permission rewrites into root-level
    #: probe tables (engine/fold.py P-index): a 5-hop nested check
    #: becomes ~2 probes; ineligible shapes keep the walked path
    flat_fold: bool = True
    #: folded row budget as a multiple of (E + US) row counts; pairs
    #: beyond it stay on the walked path
    flat_fold_factor: int = 16
    #: max userset-group fan per folded (slot, resource) in the pf_u
    #: range table (engine/fold.py fold_userset_rows — the factored
    #: replacement for the round-5 dense fold T-join).  A resource whose
    #: folded group list exceeds this would blow the kernel's per-query
    #: slice width, so the fold declines and the walked path answers
    flat_fold_u_fan_cap: int = 64
    #: max closure rows per SOURCE in the fold's subject-side slice (the
    #: csr closure-by-source view): the kernel intersects the resource's
    #: pf_u group list with the subject's group closure as a pure
    #: [u_fan × s_fan] register compare — no per-group gathers — so this
    #: bounds that compare tile.  A world whose hottest subject belongs
    #: to more groups declines the fold (walked path answers)
    flat_fold_subj_fan_cap: int = 64
    #: per-array entry budget for the fold's DIRECT offset arrays
    #: (pfu_start: fold-slots·N entries; csr_start: N·S1 entries) —
    #: two element gathers replace a hash probe per range lookup.  Key
    #: spaces beyond it keep the hash group tables
    flat_pf_direct_max_entries: int = 1 << 25
    #: incremental fold maintenance (engine/fold.py fold_delta_update):
    #: max total dirty resources per delta chain.  Past it the chain
    #: DOWNGRADES folded pairs to their walked programs (sticky pf_off
    #: until compaction re-folds the base) — a delta touching a hot
    #: ancestor can dirty a whole subtree, and recomputing that each
    #: revision would cost more than walking
    flat_fold_delta_dirty_cap: int = 16_384
    #: advance the flattened membership closure in place on membership-
    #: subgraph deltas (store/closure.py advance_closure) instead of
    #: bailing to a full prepare — the O(Δ·depth) write path
    closure_delta: bool = True
    #: max affected closure sources per advance; a delta whose reverse
    #: reachability fans past this rebuilds instead (a hot group touched
    #: near the nesting root can implicate everything below it)
    closure_delta_affected_cap: int = 65_536
    #: max accumulated T-index-dirty resource keys per delta chain.
    #: Membership deltas stale the baked T rows of every resource whose
    #: userset group changed; past this bound the chain flips the
    #: T-index OFF (sticky, like pf_off) and the KU path — which probes
    #: the live closure directly — answers those slots until compaction
    flat_tindex_dirty_cap: int = 65_536
    #: bucket-ALIGNED probe tables (engine/hash.py build_aligned): each
    #: bucket is ONE table row fetched with a single row gather — on TPU
    #: ~48M probes/s vs 0.75M for the off+block layout (measured,
    #: tpu_attempts/micro_blocks.py).  None = auto (on when the default
    #: backend is tpu); tests force True to exercise the layout on CPU
    flat_aligned: Optional[bool] = None
    #: per-table byte budget for the aligned layout; tables whose aligned
    #: form exceeds it keep the off+interleave layout
    flat_aligned_max_bytes: int = 3 << 30
    #: width-stratification ladder for the aligned layout
    #: (engine/hash.py build_aligned ``cover``): level i's row width is
    #: the smallest cap covering this share of its entries, overflow
    #: cascades to the next (salted) level, and a fit-all level closes
    #: the ladder.  The 1-entry default is the classic primary+spill
    #: pair; (0.99, 0.999) buys a narrower primary row — most of the
    #: table's bytes — for one extra single-gather level
    flat_aligned_cover: Tuple[float, ...] = (0.999,)
    # -- HBM-lean packed tables (engine/packed.py) -----------------------
    #: bit-packed device tables: logical int32 columns share uint16
    #: lanes (keys at their radix widths, caveat/ctx ids at their count
    #: widths, range ends as delta-run lengths, until-values as small
    #: dictionaries), bucket offsets split into int32 anchors + uint16
    #: residuals, and point-table bucket growth is bounded by
    #: ``flat_packed_max_factor`` instead of chasing cap ≤ 4 through 8x
    #: offsets.  The kernel decodes with shift/mask ops fused into the
    #: existing block gathers — bitwise-identical query results, ~3-6x
    #: fewer resident table bytes (BENCHMARKS.md "HBM-lean tables").
    #: None = auto (on whenever the blockslice layout is); False is the
    #: parity oracle (the exact pre-packing layout)
    flat_packed: Optional[bool] = None
    #: bucket-count growth bound for the packed layout's hash builds
    #: (size ≤ this x pow2(2n)): a deeper probe cap costs a few fused
    #: compares; an 8x offsets array costs hundreds of MB of HBM
    flat_packed_max_factor: int = 2

    def packed_on(self) -> bool:
        """The resolved flat_packed flag (None = auto: packed whenever
        the blockslice layout is active — the scattered layout keeps
        full-width columns)."""
        if self.flat_packed is not None:
            return bool(self.flat_packed) and self.flat_blockslice
        return self.flat_blockslice
    #: partition-first stacked builds (engine/partition.py): hash keys to
    #: bucket shards FIRST, then build each model shard's slice of the
    #: stacked tables independently — bitwise-identical output with
    #: O(E/M) sort/hash/interleave scratch per shard instead of O(E)
    #: (ROADMAP "Host-sharded table build").  False keeps the reference
    #: build-full-then-stack path (the parity tests' oracle)
    flat_partition_build: bool = True
    #: row-chunk size of the partitioned build's primary-key hash pass:
    #: the dense (k1, k2) packs are computed per chunk, so no full-size
    #: O(E) packed key column is ever materialized (the bound
    #: tests/test_sharded_memory.py's allocation tracker asserts)
    flat_partition_chunk: int = 1 << 22
    #: bulk-check batches beyond this split into sub-dispatches queued
    #: back-to-back (jax async dispatch): device compute overlaps the
    #: next chunk's host lowering/transfer and per-sub-batch results
    #: land early (BASELINE config-4 tail, VERDICT r04 item 8).  None =
    #: auto: 32768 on TPU (queued dispatches genuinely overlap), off on
    #: CPU (one core executes chunks serially and the per-dispatch
    #: overhead costs ~40% throughput — measured, bench4).  0 disables
    flat_pipeline_batch: Optional[int] = None
    # -- latency-mode execution path (engine/latency.py) -----------------
    #: small-batch padding tiers: a latency-mode batch pads to the
    #: smallest tier ≥ B and runs a pinned AOT-compiled kernel for that
    #: tier — a handful of tiers bounds the pinned-executable count
    #: while keeping pad waste bounded; batches beyond the top tier use
    #: the throughput path.  Any sorted tuple of positive ints works —
    #: tiers need NOT be powers of two; the offline tuner
    #: (gochugaru_tpu/tune) emits workload-fit ladders like (192, 576,
    #: 4096) and the no-retrace contract holds because pins are keyed
    #: by the tier value itself, not its log2
    latency_tiers: Tuple[int, ...] = (256, 1024, 4096)
    #: donate the query-matrix device buffer to the pinned executable
    #: (XLA aliases it for outputs — zero per-dispatch device
    #: allocation).  None = auto: on for TPU, off on CPU where the
    #: runtime cannot use the donation and warns per compile
    latency_donate: Optional[bool] = None
    #: fence between budget stages (block after H2D, after kernel) so
    #: each stage's time is exact.  None = auto: on for TPU (the H2D
    #: genuinely overlaps and must be fenced to be measured), off on
    #: CPU (device_put is a synchronous copy; the extra fences cost
    #: ~0.3 ms per dispatch and the kernel stage absorbs any queued
    #: transfer remainder).  Timing-only: off-CPU the H2D fence is kept
    #: regardless (the shared staging buffer must not be refilled while
    #: an async transfer still reads it)
    latency_staged_timing: Optional[bool] = None
    # -- unified masked-SpMM sparse core (engine/spmm.py) ----------------
    #: serve multi-hop lookups through the fused K-hop SpMM program (the
    #: whole reverse/forward frontier fixpoint in ONE pinned dispatch,
    #: frontier carried on-device between hops) and route the fold
    #: T-join through the same semiring primitive.  False is the parity
    #: oracle: the per-hop looped spmv path and the bespoke t_join_core,
    #: byte-for-byte (the flat_packed=False-style lever)
    spmm: bool = True
    #: max fused hop rounds per dispatch; a frontier still live after
    #: this many rounds overflows to the looped path
    spmm_rounds: int = 10
    #: on-device frontier capacity per round (keys AND nodes, pow2);
    #: wider frontiers overflow to the looped path — bulk subjects with
    #: ~1M-candidate answers are the looped path's workload anyway
    spmm_frontier: int = 1_024
    #: per-round emission budget of each fused probe (pow2).  The emit
    #: lanes run at full static width every round, so this is the fused
    #: program's dominant cost — size for the common lookup, not the
    #: worst case: overflow falls back to the looped path correctly
    spmm_emit: int = 2_048
    #: candidate-buffer capacity of one fused dispatch; answers larger
    #: than this overflow to the looped (streaming) path
    spmm_candidates: int = 8_192
    # -- Pallas fused probe backend (engine/pallas.py) -------------------
    #: serve the bucket probes (check direct/T/closure/userset sites and
    #: the frontier run probes) through the hand-fused Pallas kernel:
    #: hash → offset → double-buffered bucket DMA → packed decode → gate
    #: → reduce in ONE HBM pass per table, offsets/ladders VMEM-resident.
    #: None = auto: on for TPU when jax.experimental.pallas is available,
    #: off elsewhere.  False is the parity oracle — the XLA gather chain,
    #: byte-for-byte (the spmm=False / flat_packed=False-style lever).
    #: True forces the kernels even off-TPU (tests: Pallas INTERPRET
    #: mode under JAX_PLATFORMS=cpu — correctness, not speed); a jaxlib
    #: without Pallas degrades True/auto to the XLA path with a single
    #: counted warning, never an ImportError
    pallas: Optional[bool] = None

    @staticmethod
    def for_schema(compiled: CompiledSchema, **overrides) -> "EngineConfig":
        cfg = EngineConfig()
        userset_depth = _userset_depth(compiled)
        arrow_depth = _arrow_depth(compiled)
        if userset_depth == 0:
            cfg = replace(cfg, closure_hops=0)
        elif userset_depth > 0:
            cfg = replace(cfg, closure_hops=min(userset_depth, cfg.closure_hops))
        # -1 (cyclic): keep the default cap.
        if arrow_depth == 0:
            cfg = replace(cfg, subgraph_nodes=1)
        elif arrow_depth > 0:
            # acyclic arrows: the subgraph is as deep as the longest
            # type-level arrow chain (fanout beyond the cap overflows to the
            # host).
            cfg = replace(cfg, subgraph_nodes=max(2, min(1 + 2 * arrow_depth, 32)))
        # else keep the default subgraph cap (recursive hierarchies).
        # Fixpoint iterations: one topo-ordered pass resolves any acyclic
        # rewrite system; cycles through *evaluation* dependencies (mutually
        # recursive permissions, recursive arrows) propagate one dependency
        # step per iteration, so the bound must cover the cycle length AND
        # the subgraph chain length.  Userset (group) recursion is the
        # closure phase's job and does not force iterations here.
        rec = _eval_recursion_bound(compiled)
        if rec == 0:
            cfg = replace(cfg, eval_iters=1)
        else:
            cfg = replace(
                cfg, eval_iters=min(32, max(cfg.subgraph_nodes, rec + 1))
            )
        return replace(cfg, **overrides)


def _longest_path(edges: Dict) -> Tuple[int, set]:
    """Longest path length over an adjacency dict {node: iterable(node)}.
    Returns (depth, cyclic_nodes): depth is -1 if cyclic; cyclic_nodes are
    the nodes observed on a cycle."""
    if not edges:
        return 0, set()
    memo: Dict = {}
    stack: List = []
    on_stack: set = set()
    cyclic_nodes: set = set()

    def depth(node) -> int:
        if node in memo:
            return memo[node]
        if node in on_stack:
            # every node from the first occurrence onward is on the cycle
            i = stack.index(node)
            cyclic_nodes.update(stack[i:])
            return 0
        stack.append(node)
        on_stack.add(node)
        d = 0
        for nxt in edges.get(node, ()):  # noqa: B905
            d = max(d, 1 + depth(nxt))
        stack.pop()
        on_stack.discard(node)
        memo[node] = d
        return d

    m = max(depth(n) for n in list(edges))
    return (-1 if cyclic_nodes else m), cyclic_nodes


def _userset_depth(compiled: CompiledSchema) -> int:
    """Nesting depth of the relation-userset graph: 0 = no relation admits
    userset subjects; -1 = cyclic (groups-in-groups); else the max depth."""
    edges: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for tname, d in compiled.schema.definitions.items():
        for rname, relation in d.relations.items():
            for a in relation.allowed:
                if a.relation:
                    edges.setdefault((tname, rname), []).append((a.type, a.relation))
    depth, _ = _longest_path(edges)
    return depth


def _arrow_depth(compiled: CompiledSchema) -> int:
    """Longest type-level chain of arrow (tupleset) traversals: 0 = no
    arrows, -1 = cyclic (recursive hierarchies), else the max chain length.
    This bounds the resource-subgraph BFS, which only walks arrow edges —
    far tighter than the full item-dependency depth."""
    edges: Dict[str, set] = {}
    for tname, d in compiled.schema.definitions.items():
        for perm in d.permissions.values():
            for ref in _expr_refs(perm.expr):
                if isinstance(ref, Arrow):
                    for a in d.relations[ref.left].allowed:
                        if not a.wildcard:
                            edges.setdefault(tname, set()).add(a.type)
    depth, _ = _longest_path(edges)
    return depth


def _eval_dep_graph(
    compiled: CompiledSchema,
) -> Dict[Tuple[str, str], List[Tuple[str, str]]]:
    """Evaluation-dependency graph over (type, item): permissions depend on
    same-type references and arrow targets; relations are leaves (their
    userset indirection is resolved by the closure phase)."""
    schema = compiled.schema
    edges: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for tname, d in schema.definitions.items():
        for pname, perm in d.permissions.items():
            deps: List[Tuple[str, str]] = []
            for ref in _expr_refs(perm.expr):
                if isinstance(ref, RelationRef):
                    deps.append((tname, ref.name))
                elif isinstance(ref, Arrow):
                    for a in d.relations[ref.left].allowed:
                        if not a.wildcard and schema.definitions[a.type].item(ref.right):
                            deps.append((a.type, ref.right))
            edges[(tname, pname)] = deps
    return edges


def _eval_recursion_bound(compiled: CompiledSchema) -> int:
    """Cycle bound for the fixpoint ITERATION (not the closure).  Returns 0
    if acyclic, else the number of nodes observed on cycles — an upper
    bound on the extra propagation steps a cycle needs."""
    depth, cyclic_nodes = _longest_path(_eval_dep_graph(compiled))
    if depth >= 0:
        return 0
    return max(1, len(cyclic_nodes))


def _eval_cyclic_pairs(compiled: CompiledSchema) -> frozenset:
    """(type_name, slot) pairs on an evaluation-dependency cycle — the
    pairs whose static unrolling needs a recursion budget (engine/flat.py);
    everything else terminates by schema acyclicity."""
    _, cyclic_nodes = _longest_path(_eval_dep_graph(compiled))
    return frozenset(
        (tname, compiled.slot_of_name[iname]) for tname, iname in cyclic_nodes
    )


@dataclass(frozen=True)
class TypeProgram:
    type_name: str
    schema_tid: int
    #: (perm_slot, expr_ir) pairs for this type
    perms: Tuple[Tuple[int, ExprIR], ...]


@dataclass(frozen=True)
class DevicePlan:
    """Everything static the device codegen needs."""

    ts_slots: Tuple[int, ...]  # tupleset slots; index = ts_idx in arrays
    rel_leaf_slots: Tuple[int, ...]  # relation slots needing leaf tests
    #: (type_name, schema_tid, perm_slot, expr_ir), globally topo-ordered by
    #: dependency depth so one fixpoint iteration resolves any acyclic chain
    topo_programs: Tuple[Tuple[str, int, int, ExprIR], ...]
    num_slots: int
    two_plane: bool  # caveats present → track (definite, possible) planes
    has_permission_usersets: bool
    num_schema_types: int


def _lower_expr(
    e: Expr, ts_index: Dict[int, int], slot_of: Dict[str, int]
) -> ExprIR:
    if isinstance(e, RelationRef):
        return ("ref", slot_of[e.name])
    if isinstance(e, Arrow):
        return ("arrow", ts_index[slot_of[e.left]], slot_of[e.right])
    if isinstance(e, Union):
        return ("union", tuple(_lower_expr(c, ts_index, slot_of) for c in e.children))
    if isinstance(e, Intersection):
        return ("inter", tuple(_lower_expr(c, ts_index, slot_of) for c in e.children))
    if isinstance(e, Exclusion):
        return (
            "excl",
            _lower_expr(e.base, ts_index, slot_of),
            _lower_expr(e.subtracted, ts_index, slot_of),
        )
    if isinstance(e, Nil):
        return ("nil",)
    raise TypeError(f"unknown expression node {e!r}")


def build_plan(compiled: CompiledSchema) -> DevicePlan:
    ts_slots = tuple(sorted(compiled.tupleset_slots))
    ts_index = {slot: i for i, slot in enumerate(ts_slots)}
    slot_of = compiled.slot_of_name

    rel_leaf = set()
    for d in compiled.schema.definitions.values():
        for rname in d.relations:
            rel_leaf.add(slot_of[rname])

    programs: List[Tuple[str, int, int, ExprIR]] = []
    for tname, d in compiled.schema.definitions.items():
        tid = compiled.type_ids[tname]
        for pname, perm in d.permissions.items():
            programs.append(
                (
                    tname,
                    tid,
                    slot_of[pname],
                    _lower_expr(perm.expr, ts_index, slot_of),
                )
            )
    # Global topological order by dependency depth: shallow first, so within
    # one iteration every acyclic dependency is already updated when read.
    programs.sort(key=lambda p: (compiled.item_depths.get((p[0], _name_of(compiled, p[2])), 0), p[0], p[2]))

    return DevicePlan(
        ts_slots=ts_slots,
        rel_leaf_slots=tuple(sorted(rel_leaf)),
        topo_programs=tuple(programs),
        num_slots=max(compiled.num_slots, 1),
        two_plane=bool(compiled.schema.caveats),
        has_permission_usersets=compiled.has_permission_usersets,
        num_schema_types=len(compiled.type_ids),
    )


def _name_of(compiled: CompiledSchema, slot: int) -> str:
    for name, s in compiled.slot_of_name.items():
        if s == slot:
            return name
    return ""

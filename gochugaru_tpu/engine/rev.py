"""Reverse-CSR index: the stacked tables of the lookup frontier SpMV.

Check probes ask "does edge (rel, res, subj, srel) exist" — the stacked
point tables of engine/flat.py answer that by hashing the FULL key.
LookupResources/LookupSubjects ask the inverse questions: "every edge
whose SUBJECT is this userset" (reverse) and "every edge hanging off
this RESOURCE" (forward) — ragged fan-out enumeration, which a
cap-bounded point probe cannot serve.  This module builds the three
enumeration views the frontier engine (engine/spmv.py) hops over, as
bucket-sharded stacked arrays that ride DeviceSnapshot.arrays alongside
the forward tables:

- ``rvx``/``rv_off`` — all primary edges keyed by ``k2`` (packed
  (subject, srel1)): one hop of reverse reachability;
- ``rax``/``ra_off`` — arrow rows keyed by CHILD node: reverse
  tupleset-traversal (parents granting through ``ts->perm``);
- ``fwx``/``fw_off`` — all primary edges keyed by ``k1`` (packed
  (slot, resource)): forward enumeration for LookupSubjects.

Layout: rows bucket by ``mix32`` of the single group-key column and are
sorted WITHIN each bucket by full row identity (key, payload, gates) —
so every key's rows form one contiguous run the device finds with a
short per-bucket binary search (``cap`` bounds the bisect depth), and
the layout is a pure function of the row SET, independent of feed
order.  That identity-sort canonicalization is what makes the
partition-first build (owner shard from the bucket's high bits, each
shard sorted independently — O(E/M) scratch, engine/partition.py
discipline) BITWISE-identical to the build-full-then-stack oracle
``build_rev_full`` (tests/test_rev_index.py), the same contract the
fold derivations adopted in round 12.

Bucket sizing always uses the frozen lean geometry (``REV_HK``): fans
are unbounded by design (a popular userset IS the workload), so chasing
a small probe cap through table doubling would only balloon the offset
arrays; the bisect cost grows with log(fan) instead.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .hash import _ceil_pow2
from .partition import ColsAt, PointGeom, ShardSlices, point_geom, shard_order

#: geometry kwargs of every reverse-index bucket table: pow2(n) buckets,
#: growth frozen (max_factor=1) — the bisect absorbs deep buckets, an 8x
#: offsets array would not be HBM-lean
REV_HK = dict(lean=True, max_factor=1)


def rev_geom(h: np.ndarray, M: int, *, pad: int = 64) -> PointGeom:
    """Bucket geometry of one reverse-index view (frozen lean sizing).
    ``cap`` is the max bucket occupancy — the frontier kernel's bisect
    step bound, not a probe unroll count."""
    return point_geom(h, M, pad=pad, **REV_HK)


def _sort_words(lb: np.ndarray, cols: Sequence[np.ndarray]):
    """(words, fallback) sorting rows by (bucket, full row identity):
    up to 5 int32 identity columns + the bucket pack into three uint64
    words (bias int32 → uint32 so the word order matches signed column
    order).  Returns the stable permutation."""
    from ..native.sort import sortperm_words

    assert len(cols) <= 5, "reverse-index rows carry at most 5 columns"
    B = np.int64(1) << np.int64(32)

    def u(c: np.ndarray) -> np.ndarray:
        return c.astype(np.int64) + np.int64(2**31)

    padded = [u(c) for c in cols] + [
        np.zeros(lb.shape[0], np.int64) for _ in range(5 - len(cols))
    ]
    words = [
        lb.astype(np.int64) * B + padded[0],
        padded[1] * B + padded[2],
        padded[3] * B + padded[4],
    ]
    fallback = tuple(reversed([lb] + [np.asarray(c) for c in cols]))
    return sortperm_words(words, fallback)


def _fill_shard(blk: np.ndarray, cols: Sequence[np.ndarray]) -> None:
    from ..native.sort import fill_interleaved

    n = int(cols[0].shape[0]) if cols else 0
    if n and not fill_interleaved(blk, list(cols), None):
        for j, c in enumerate(cols):
            blk[:n, j] = c


def _shard_off(lb: np.ndarray, bpd: int) -> np.ndarray:
    off = np.zeros(bpd + 1, np.int64)
    np.cumsum(np.bincount(lb, minlength=bpd), out=off[1:])
    return off.astype(np.int32)


def build_rev_shards(
    geom: PointGeom,
    w: int,
    shard_h: Callable[[int], np.ndarray],
    shard_cols: Callable[[int, np.ndarray], List[np.ndarray]],
    owned: Optional[Sequence[int]] = None,
):
    """Shard-at-a-time reverse-index build: (off int32[M·(bpd+1)],
    tbl int32[M·R_pad, w]).  ``shard_h(s)`` returns shard s's row hashes
    (any order — the identity sort canonicalizes); ``shard_cols(s, perm)``
    the row columns gathered at shard-local positions ``perm``.  The
    returned permutation applied is (local bucket, full row identity) —
    feed-order-independent, hence bitwise-reproducible from any
    partitioning of the same row set."""
    M, bpd, R_pad = geom.M, geom.bpd, geom.R_pad
    full = owned is None
    shards = range(M) if full else sorted(owned)
    if full:
        off = np.empty(M * (bpd + 1), np.int32)
        tbl = np.full((M * R_pad, w), -1, np.int32)
    else:
        off_b: Dict[int, np.ndarray] = {}
        tbl_b: Dict[int, np.ndarray] = {}
    for s in shards:
        h_s = shard_h(s)
        lb = (h_s & np.uint32(bpd - 1)).astype(np.int64)
        # two-pass sort: bucket-group first (cheap counting sort), then
        # the identity sort runs per shard with the bucket as the major
        # word — one fused sortperm_words pass over the shard's rows
        cols0 = shard_cols(s, np.arange(h_s.shape[0], dtype=np.int64))
        perm = _sort_words(lb, cols0)
        cols = [np.ascontiguousarray(c[perm], np.int32) for c in cols0]
        if full:
            off[s * (bpd + 1) : (s + 1) * (bpd + 1)] = _shard_off(lb, bpd)
            blk = tbl[s * R_pad : (s + 1) * R_pad]
        else:
            off_b[s] = _shard_off(lb, bpd)
            blk = np.full((R_pad, w), -1, np.int32)
            tbl_b[s] = blk
        if cols and cols[0].shape[0]:
            _fill_shard(blk, cols)
    if full:
        return off, tbl
    return (
        ShardSlices((M * (bpd + 1),), np.dtype(np.int32), bpd + 1, off_b),
        ShardSlices((M * R_pad, w), np.dtype(np.int32), R_pad, tbl_b),
    )


def build_rev_partitioned(
    h: np.ndarray,
    cols_at: ColsAt,
    geom: PointGeom,
    w: int,
    owned: Optional[Sequence[int]] = None,
):
    """Partition-FIRST reverse-index build: rows go to their owner shard
    (high bits of the bucket) with one stable counting sort, then each
    shard's slice builds independently — O(E/M) sort/gather scratch per
    shard, the engine/partition.py discipline."""
    order, starts = shard_order(h, geom.size, geom.M)

    def shard_h(s: int) -> np.ndarray:
        return h[order[starts[s] : starts[s + 1]]]

    def shard_cols(s: int, perm: np.ndarray) -> List[np.ndarray]:
        rows = order[starts[s] : starts[s + 1]][perm]
        return cols_at(rows)

    return build_rev_shards(geom, w, shard_h, shard_cols, owned)


def build_rev_full(
    h: np.ndarray,
    cols: Sequence[np.ndarray],
    geom: PointGeom,
    w: int,
):
    """Build-full-then-stack reference: ONE global sort by (bucket, row
    identity), then per-shard slices — the parity oracle the partitioned
    build is asserted bitwise-equal to, and the single-chip (M=1) build
    path."""
    M, bpd, R_pad = geom.M, geom.bpd, geom.R_pad
    size = geom.size
    cc = [np.ascontiguousarray(c, np.int32) for c in cols]
    bucket = (h & np.uint32(size - 1)).astype(np.int64)
    # global bucket == owner·bpd + local bucket, so one sort by (bucket,
    # identity) IS (owner, local bucket, identity)
    perm = _sort_words(bucket, cc)
    bs = bucket[perm]
    owner = bs >> np.int64((bpd).bit_length() - 1)
    lb = bs & np.int64(bpd - 1)
    scols = [c[perm] for c in cc]
    off = np.empty(M * (bpd + 1), np.int32)
    tbl = np.full((M * R_pad, w), -1, np.int32)
    starts = np.zeros(M + 1, np.int64)
    np.cumsum(np.bincount(owner, minlength=M), out=starts[1:])
    for s in range(M):
        lo, hi = int(starts[s]), int(starts[s + 1])
        off[s * (bpd + 1) : (s + 1) * (bpd + 1)] = _shard_off(lb[lo:hi], bpd)
        _fill_shard(
            tbl[s * R_pad : (s + 1) * R_pad], [c[lo:hi] for c in scols]
        )
    return off, tbl


def rev_meta_kw(ge: PointGeom, ga: PointGeom, gf: Optional[PointGeom]) -> Dict:
    """FlatMeta field updates for one built reverse index (pow2-bucketed
    caps: the bisect depth is static in the compiled kernel)."""
    kw = dict(
        has_rev=True,
        rv_cap=_ceil_pow2(max(ge.cap, 1), 1),
        ra_cap=_ceil_pow2(max(ga.cap, 1), 1),
    )
    if gf is not None:
        kw.update(has_fw=True, fw_cap=_ceil_pow2(max(gf.cap, 1), 1))
    return kw

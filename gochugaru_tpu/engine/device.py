"""The JAX device engine: batched two-phase permission checks.

This is the component that replaces the server-side evaluation behind the
reference's ``CheckBulkPermissions`` RPC (client/client.go:238-266): the
batch axis of that RPC becomes the ``vmap`` axis here, and the graph walk
SpiceDB does across its dispatch cluster becomes two static-shape phases
over the snapshot's sorted int32 columns:

- **Phase A — subject closure** (vmapped over the *unique* subjects of the
  batch): a capped frontier walk over the membership (group-nesting) CSR
  computes every userset the subject transitively belongs to, as a sorted
  (node, relation) pair list.  Seeds come from the subject's direct
  membership edges and its type's wildcard node; propagation follows
  userset edges.  With caveats present, two closures are kept — definite
  and possible — mirroring SpiceDB's CONDITIONAL permissionship.

- **Phase B — resource subgraph + fixpoint** (vmapped over queries): a
  capped BFS over tupleset (arrow) edges collects the nodes the resource
  can reach, then relation leaf tests (exact-match binary searches +
  userset-closure probes) seed a dense boolean table V[node, slot] and the
  schema's permission programs — lowered at WriteSchema time to static
  expression IR — iterate to a fixpoint in topological order.

Everything is int32; composite keys are compared lexicographically in a
custom binary search (TPU has no native int64).  Every static cap has an
overflow flag; overflowing queries are re-checked by the host oracle, so
caps bound device work without affecting correctness.

All control flow is static or ``lax`` primitives: the whole check is one
XLA program, traced once per (schema, config, shape bucket).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..caveats.device import (
    CaveatDevicePlan,
    build_caveat_plan,
    encode_contexts,
    make_tri_fn,
)
from ..rel.relationship import Relationship, WILDCARD_ID
from ..schema.compiler import CompiledSchema
from ..store.snapshot import Snapshot
import time as _time

from ..utils import faults, metrics
from ..utils import perf as _perf
from ..utils import trace as _trace
from ..utils.context import background as _background
from ..utils.errors import classify_dispatch_exception
from ..utils.retry import retry_retriable_errors
from . import pallas as _pallas
from .plan import DevicePlan, EngineConfig, build_plan

#: edge-count floor for the prepare-time lookup-index prewarm thread:
#: small worlds build the index in microseconds inside the first lookup
LOOKUP_PREWARM_MIN_EDGES = 65_536

I32_MAX = 2**31 - 1


def _ceil_pow2(n: int, minimum: int = 8) -> int:
    m = minimum
    while m < n:
        m <<= 1
    return m


def _pad_sorted(a: np.ndarray, size: int) -> np.ndarray:
    """Pad a sorted key column with I32_MAX sentinels."""
    out = np.full(size, I32_MAX, dtype=np.int32)
    out[: a.shape[0]] = a
    return out


def _pad_payload(a: np.ndarray, size: int, fill: int = 0) -> np.ndarray:
    out = np.full(size, fill, dtype=np.int32)
    out[: a.shape[0]] = a
    return out


# ---------------------------------------------------------------------------
# device helpers (traced)
# ---------------------------------------------------------------------------


def _lex_search(cols: Sequence[jnp.ndarray], qs: Sequence[jnp.ndarray], side: str):
    """Binary search over columns sorted lexicographically; returns the
    insertion index for (qs) with the given side.  Arrays must be padded
    with I32_MAX so the padded tail sorts last."""
    n = cols[0].shape[0]
    steps = max(1, (n - 1).bit_length() + 1)

    def body(_, lohi):
        lo, hi = lohi
        cont = lo < hi  # converged searches must not move (or read past n)
        mid = jnp.clip((lo + hi) // 2, 0, n - 1)
        lt = jnp.bool_(False)
        eq = jnp.bool_(True)
        for c, q in zip(cols, qs):
            v = c[mid]
            lt = lt | (eq & (v < q))
            eq = eq & (v == q)
        go_right = lt | (eq if side == "right" else jnp.bool_(False))
        lo = jnp.where(cont & go_right, mid + 1, lo)
        hi = jnp.where(cont & ~go_right, mid, hi)
        return lo, hi

    lo, _ = lax.fori_loop(0, steps, body, (jnp.int32(0), jnp.int32(n)))
    return lo


def _lex_range2(c1, c2, q1, q2):
    lo = _lex_search((c1, c2), (q1, q2), "left")
    hi = _lex_search((c1, c2), (q1, q2), "right")
    return lo, hi


def _lex_contains2(c1, c2, q1, q2):
    pos = _lex_search((c1, c2), (q1, q2), "left")
    posc = jnp.clip(pos, 0, c1.shape[0] - 1)
    return (c1[posc] == q1) & (c2[posc] == q2)


def _pany(x, axis: Optional[str]):
    """OR-reduce across the edge-shard mesh axis (identity off-mesh).
    This is the all-reduce(OR) closing reachability across shards that
    SURVEY.md §2.5/§5 calls for — XLA lowers it onto ICI."""
    if axis is None:
        return x
    return lax.psum(x.astype(jnp.int32), axis) > 0


def _agather(x, axis: Optional[str]):
    """Gather shard-local candidate blocks from every edge shard along the
    mesh axis, concatenated on a new leading axis (identity off-mesh)."""
    if axis is None:
        return x[None]
    return lax.all_gather(x, axis)


def _gate(cav, ctx, exp, now, plane: str, qctx=None, tri=None, tables=None):
    """Edge admissibility: expired edges grant nothing; caveated edges run
    the on-device CEL VM (caveats/device.py) against stored-over-query
    merged context.  Definite plane requires tri==TRUE; possible plane
    admits tri>=UNKNOWN (conditional → host oracle resolution).  Without a
    tri fn (schema has no caveats) this degrades to the expiry mask."""
    live = (exp == 0) | (exp > now)
    if tri is None:
        if plane == "p":
            return live
        return live & (cav == 0)
    q = jnp.broadcast_to(qctx, jnp.shape(cav)) if jnp.shape(cav) else qctx
    t = tri(cav, ctx, q, tables)
    if plane == "p":
        return live & (t >= 1)
    return live & (t == 2)


def _dedup_truncate(n: jnp.ndarray, r: jnp.ndarray, C: int):
    """Sort (n, r) pairs lexicographically, drop duplicates and I32_MAX
    sentinels, return the first C pairs plus an overflow flag."""
    if n.shape[0] < C:
        pad = C - n.shape[0]
        n = jnp.concatenate([n, jnp.full(pad, I32_MAX, jnp.int32)])
        r = jnp.concatenate([r, jnp.full(pad, I32_MAX, jnp.int32)])
    n_s, r_s = lax.sort((n, r), num_keys=2)
    first = jnp.concatenate(
        [jnp.array([True]), (n_s[1:] != n_s[:-1]) | (r_s[1:] != r_s[:-1])]
    )
    keep = first & (n_s < I32_MAX)
    n_u = jnp.where(keep, n_s, I32_MAX)
    r_u = jnp.where(keep, r_s, I32_MAX)
    n_f, r_f = lax.sort((n_u, r_u), num_keys=2)
    overflow = jnp.sum(keep) > C
    return n_f[:C], r_f[:C], overflow


# ---------------------------------------------------------------------------
# Phase A: subject closure
# ---------------------------------------------------------------------------


def _closure_one(
    arrs, cfg: EngineConfig, plane: str, now, u_subj, u_srel, u_wc,
    u_qctx=-1, tri=None, tables=None,
    axis: Optional[str] = None,
):
    C, SC, P = cfg.closure_size, cfg.seed_cap, cfg.prop_cap
    ms_subj, ms_res, ms_rel = arrs["ms_subj"], arrs["ms_res"], arrs["ms_rel"]
    ms_cav, ms_exp = arrs["ms_caveat"], arrs["ms_exp"]
    ms_ctx = arrs["ms_ctx"]
    mp_subj, mp_srel = arrs["mp_subj"], arrs["mp_srel"]
    mp_res, mp_rel = arrs["mp_res"], arrs["mp_rel"]
    mp_cav, mp_exp = arrs["mp_caveat"], arrs["mp_exp"]
    mp_ctx = arrs["mp_ctx"]

    overflow = jnp.bool_(False)
    # own key: a userset subject is a member of itself
    own = u_srel >= 0
    bufs_n = [jnp.where(own, u_subj, I32_MAX)[None]]
    bufs_r = [jnp.where(own, u_srel, I32_MAX)[None]]
    # direct seeds (only direct-object subjects have direct membership
    # edges; userset subjects enter via their own key + propagation)
    last = max(ms_subj.shape[0] - 1, 0)
    for src0 in (u_subj, u_wc):
        src = jnp.where(u_srel < 0, src0, -1)
        lo = jnp.searchsorted(ms_subj, src, side="left").astype(jnp.int32)
        hi = jnp.searchsorted(ms_subj, src, side="right").astype(jnp.int32)
        overflow |= (hi - lo) > SC
        idx = lo + jnp.arange(SC, dtype=jnp.int32)
        valid = (idx < hi) & (src >= 0)
        idxc = jnp.clip(idx, 0, last)
        keep = valid & _gate(
            ms_cav[idxc], ms_ctx[idxc], ms_exp[idxc], now, plane,
            u_qctx, tri, tables,
        )
        # each edge shard contributes its local seeds; gather + dedup merges
        bufs_n.append(_agather(jnp.where(keep, ms_res[idxc], I32_MAX), axis).ravel())
        bufs_r.append(_agather(jnp.where(keep, ms_rel[idxc], I32_MAX), axis).ravel())
    c_n, c_r, ovf = _dedup_truncate(
        jnp.concatenate(bufs_n), jnp.concatenate(bufs_r), C
    )
    overflow |= ovf

    lastp = max(mp_subj.shape[0] - 1, 0)
    lex_lo = jax.vmap(lambda a, b: _lex_search((mp_subj, mp_srel), (a, b), "left"))
    lex_hi = jax.vmap(lambda a, b: _lex_search((mp_subj, mp_srel), (a, b), "right"))

    def hop(c_n, c_r, overflow):
        lo = lex_lo(c_n, c_r)
        hi = lex_hi(c_n, c_r)
        overflow |= jnp.any((hi - lo) > P)
        idx = lo[:, None] + jnp.arange(P, dtype=jnp.int32)[None, :]
        valid = (idx < hi[:, None]) & (c_n[:, None] < I32_MAX)
        idxc = jnp.clip(idx, 0, lastp)
        keep = valid & _gate(
            mp_cav[idxc], mp_ctx[idxc], mp_exp[idxc], now, plane,
            u_qctx, tri, tables,
        )
        cand_n = _agather(jnp.where(keep, mp_res[idxc], I32_MAX).ravel(), axis).ravel()
        cand_r = _agather(jnp.where(keep, mp_rel[idxc], I32_MAX).ravel(), axis).ravel()
        c_n, c_r, ovf = _dedup_truncate(
            jnp.concatenate([c_n, cand_n]), jnp.concatenate([c_r, cand_r]), C
        )
        return c_n, c_r, overflow | ovf

    for _ in range(cfg.closure_hops):
        c_n, c_r, overflow = hop(c_n, c_r, overflow)
    if cfg.closure_hops > 0:
        # detection pass: if one more hop still grows the closure, the hop
        # cap was insufficient (nesting deeper than closure_hops) — flag it
        # so the caller falls back to the host oracle instead of silently
        # missing memberships
        size_before = jnp.sum(c_n < I32_MAX)
        c_n, c_r, overflow = hop(c_n, c_r, overflow)
        overflow |= jnp.sum(c_n < I32_MAX) > size_before
    return c_n, c_r, _pany(overflow, axis)


# ---------------------------------------------------------------------------
# Phase B: per-query evaluation
# ---------------------------------------------------------------------------


def _query_one(
    arrs,
    plan: DevicePlan,
    cfg: EngineConfig,
    now,
    tid_map,  # int32[num_schema_types] → interner type id
    Cd_n, Cd_r, Cp_n, Cp_r,  # [U, C] closures
    q_res, q_perm, q_subj, q_srel, q_wc, q_row, q_self,
    q_ctx=-1, tri=None, tables=None,
    axis: Optional[str] = None,
):
    N = cfg.subgraph_nodes
    TS = len(plan.ts_slots)
    K = cfg.arrow_fanout
    KU = cfg.us_leaf_cap
    SLOTS = plan.num_slots

    e_rel, e_res = arrs["e_rel"], arrs["e_res"]
    e_subj, e_srel1 = arrs["e_subj"], arrs["e_srel1"]
    e_cav, e_exp, e_ctx = arrs["e_caveat"], arrs["e_exp"], arrs["e_ctx"]
    us_rel, us_res = arrs["us_rel"], arrs["us_res"]
    us_subj, us_srel = arrs["us_subj"], arrs["us_srel"]
    us_cav, us_exp, us_ctx = arrs["us_caveat"], arrs["us_exp"], arrs["us_ctx"]
    ar_rel, ar_res = arrs["ar_rel"], arrs["ar_res"]
    ar_child = arrs["ar_child"]
    ar_cav, ar_exp, ar_ctx = arrs["ar_caveat"], arrs["ar_exp"], arrs["ar_ctx"]
    node_type = arrs["node_type"]

    my_cd_n, my_cd_r = Cd_n[q_row], Cd_r[q_row]
    my_cp_n, my_cp_r = Cp_n[q_row], Cp_r[q_row]

    overflow = jnp.bool_(False)

    # ---- Phase B1: arrow-subgraph BFS --------------------------------
    nodes = jnp.full(N, -1, jnp.int32).at[0].set(q_res)
    count = jnp.where(q_res >= 0, jnp.int32(1), jnp.int32(0))
    TSax = max(TS, 1)
    # with edge sharding, every shard contributes up to K children per
    # (node, tupleset relation); gathered fanout is M*K
    M = 1 if axis is None else lax.axis_size(axis)
    KE = K * M
    child_slot = jnp.full((N, TSax, KE), -1, jnp.int32)
    child_gd = jnp.zeros((N, TSax, KE), bool)
    child_gp = jnp.zeros((N, TSax, KE), bool)

    if TS > 0:
        last_ar = max(ar_rel.shape[0] - 1, 0)
        lo_f = jax.vmap(lambda a, b: _lex_search((ar_rel, ar_res), (a, b), "left"))
        hi_f = jax.vmap(lambda a, b: _lex_search((ar_rel, ar_res), (a, b), "right"))
        # N-1 hops discover a chain of N nodes; the +1 detection hop scans
        # the last-discovered nodes' children so a subgraph deeper than the
        # cap trips the count>=N overflow instead of silently truncating
        for _hop in range(max(N - 1, 1) + 1):
            cand_children = []
            cand_gd = []
            cand_gp = []
            for ts_slot in plan.ts_slots:
                rq = jnp.full(N, ts_slot, jnp.int32)
                nq = jnp.where(nodes >= 0, nodes, I32_MAX)
                lo = lo_f(rq, nq)
                hi = hi_f(rq, nq)
                overflow |= jnp.any((hi - lo) > K)
                idx = lo[:, None] + jnp.arange(K, dtype=jnp.int32)[None, :]
                valid = (idx < hi[:, None]) & (nodes >= 0)[:, None]
                idxc = jnp.clip(idx, 0, last_ar)
                gd = valid & _gate(
                    ar_cav[idxc], ar_ctx[idxc], ar_exp[idxc], now, "d",
                    q_ctx, tri, tables,
                )
                gp = valid & _gate(
                    ar_cav[idxc], ar_ctx[idxc], ar_exp[idxc], now, "p",
                    q_ctx, tri, tables,
                )
                cand_children.append(jnp.where(valid, ar_child[idxc], -1))
                cand_gd.append(gd)
                cand_gp.append(gp)
            cc = jnp.stack(cand_children)  # [TS, N, K]
            cgd = jnp.stack(cand_gd)
            cgp = jnp.stack(cand_gp)
            if axis is not None:
                # merge every shard's local candidates: [M, TS, N, K] →
                # [TS, N, M*K]; identical on all shards afterwards, so the
                # slot assignment below is replicated deterministically
                cc = _agather(cc, axis).transpose(1, 2, 0, 3).reshape(TS, N, KE)
                cgd = _agather(cgd, axis).transpose(1, 2, 0, 3).reshape(TS, N, KE)
                cgp = _agather(cgp, axis).transpose(1, 2, 0, 3).reshape(TS, N, KE)

            def assign(carry, c):
                nodes_, count_, ovf_ = carry
                valid = c >= 0
                eq = nodes_ == c
                found = jnp.any(eq)
                slot_found = jnp.argmax(eq).astype(jnp.int32)
                can_add = valid & ~found & (count_ < N)
                added = nodes_.at[jnp.clip(count_, 0, N - 1)].set(c)
                nodes_ = jnp.where(can_add, added, nodes_)
                slot = jnp.where(
                    valid,
                    jnp.where(found, slot_found, jnp.where(can_add, count_, -1)),
                    jnp.int32(-1),
                )
                ovf_ = ovf_ | (valid & ~found & (count_ >= N))
                count_ = count_ + can_add.astype(jnp.int32)
                return (nodes_, count_, ovf_), slot

            (nodes, count, ovf), slots = lax.scan(
                assign, (nodes, count, jnp.bool_(False)), cc.ravel()
            )
            overflow |= ovf
            child_slot = slots.reshape(TS, N, KE).transpose(1, 0, 2)
            child_gd = cgd.transpose(1, 0, 2)
            child_gp = cgp.transpose(1, 0, 2)

    # ---- Phase B2: relation leaf tests --------------------------------
    last_e = max(e_rel.shape[0] - 1, 0)
    last_us = max(us_rel.shape[0] - 1, 0)
    CW = my_cd_n.shape[0]

    def leaf(node, rel_slot):
        exists = node >= 0
        node_k = jnp.where(exists, node, I32_MAX)
        # direct subject
        pos = _lex_search(
            (e_rel, e_res, e_subj, e_srel1),
            (rel_slot, node_k, q_subj, q_srel + 1),
            "left",
        )
        posc = jnp.clip(pos, 0, last_e)
        hit = (
            exists
            & (q_subj >= 0)
            & (e_rel[posc] == rel_slot)
            & (e_res[posc] == node)
            & (e_subj[posc] == q_subj)
            & (e_srel1[posc] == q_srel + 1)
        )
        d = hit & _gate(
            e_cav[posc], e_ctx[posc], e_exp[posc], now, "d", q_ctx, tri, tables
        )
        p = hit & _gate(
            e_cav[posc], e_ctx[posc], e_exp[posc], now, "p", q_ctx, tri, tables
        )
        # wildcard (only grants direct-object subject queries)
        wq = jnp.where((q_wc >= 0) & (q_srel < 0), q_wc, I32_MAX)
        wpos = _lex_search(
            (e_rel, e_res, e_subj, e_srel1), (rel_slot, node_k, wq, jnp.int32(0)), "left"
        )
        wposc = jnp.clip(wpos, 0, last_e)
        whit = (
            exists
            & (wq < I32_MAX)
            & (e_rel[wposc] == rel_slot)
            & (e_res[wposc] == node)
            & (e_subj[wposc] == wq)
            & (e_srel1[wposc] == 0)
        )
        d |= whit & _gate(
            e_cav[wposc], e_ctx[wposc], e_exp[wposc], now, "d", q_ctx, tri, tables
        )
        p |= whit & _gate(
            e_cav[wposc], e_ctx[wposc], e_exp[wposc], now, "p", q_ctx, tri, tables
        )
        # userset grants probed against the subject closure
        lo, hi = _lex_range2(us_rel, us_res, rel_slot, node_k)
        ovf = (hi - lo) > KU
        idx = lo + jnp.arange(KU, dtype=jnp.int32)
        valid = (idx < hi) & exists
        idxc = jnp.clip(idx, 0, last_us)
        in_d = jax.vmap(
            lambda s, r: _lex_contains2(my_cd_n, my_cd_r, s, r)
        )(us_subj[idxc], us_srel[idxc])
        in_p = jax.vmap(
            lambda s, r: _lex_contains2(my_cp_n, my_cp_r, s, r)
        )(us_subj[idxc], us_srel[idxc])
        if plan.has_permission_usersets:
            # permission-valued usersets: membership is the permission
            # fixpoint the device doesn't run — the grant is possible
            # (→ per-query host resolution), never device-definite.  Same
            # for relation usersets whose membership may be extended
            # through a permission chain (the static pus pair set).
            permf = arrs["us_perm"][idxc] != 0
            in_pus = jax.vmap(
                lambda s, r: _lex_contains2(arrs["pus_n"], arrs["pus_r"], s, r)
            )(us_subj[idxc], us_srel[idxc])
            in_d = in_d & ~permf
            in_p = in_p | in_pus | permf
        d |= jnp.any(valid & in_d & _gate(
            us_cav[idxc], us_ctx[idxc], us_exp[idxc], now, "d", q_ctx, tri, tables
        ))
        p |= jnp.any(valid & in_p & _gate(
            us_cav[idxc], us_ctx[idxc], us_exp[idxc], now, "p", q_ctx, tri, tables
        ))
        return d, p, ovf

    rs = jnp.asarray(plan.rel_leaf_slots, dtype=jnp.int32)
    if rs.shape[0] == 0:
        rs = jnp.zeros(1, jnp.int32)
    leaf_d, leaf_p, leaf_ovf = jax.vmap(
        lambda n: jax.vmap(lambda r: leaf(n, r))(rs)
    )(nodes)
    # merge shard-local leaf hits: a direct/wildcard/userset grant may live
    # on any edge shard
    leaf_d = _pany(leaf_d, axis)
    leaf_p = _pany(leaf_p, axis)
    overflow |= jnp.any(leaf_ovf & (nodes >= 0)[:, None])

    V_d = jnp.zeros((N, SLOTS), bool)
    V_p = jnp.zeros((N, SLOTS), bool)
    for ri, slot in enumerate(plan.rel_leaf_slots):
        V_d = V_d.at[:, slot].set(leaf_d[:, ri])
        V_p = V_p.at[:, slot].set(leaf_p[:, ri])

    # ---- Phase B3: fixpoint over permission programs -------------------
    ntype = jnp.where(nodes >= 0, node_type[jnp.clip(nodes, 0)], -1)

    def eval_expr(ir, V_d, V_p):
        tag = ir[0]
        if tag == "ref":
            s = ir[1]
            return V_d[:, s], V_p[:, s]
        if tag == "nil":
            z = jnp.zeros(N, bool)
            return z, z
        if tag == "arrow":
            ti, rslot = ir[1], ir[2]
            cs = child_slot[:, ti, :]
            valid = cs >= 0
            csc = jnp.clip(cs, 0)
            d = jnp.any(V_d[csc, rslot] & valid & child_gd[:, ti, :], axis=-1)
            p = jnp.any(V_p[csc, rslot] & valid & child_gp[:, ti, :], axis=-1)
            return d, p
        if tag == "union":
            d = jnp.zeros(N, bool)
            p = jnp.zeros(N, bool)
            for c in ir[1]:
                cd, cp = eval_expr(c, V_d, V_p)
                d, p = d | cd, p | cp
            return d, p
        if tag == "inter":
            d = jnp.ones(N, bool)
            p = jnp.ones(N, bool)
            for c in ir[1]:
                cd, cp = eval_expr(c, V_d, V_p)
                d, p = d & cd, p & cp
            return d, p
        if tag == "excl":
            bd, bp = eval_expr(ir[1], V_d, V_p)
            sd, sp = eval_expr(ir[2], V_d, V_p)
            # Kleene: definitely granted iff base definite and subtracted
            # definitely absent; possible iff base possible and subtracted
            # not definite.
            return bd & ~sp, bp & ~sd
        raise TypeError(f"bad expression IR {ir!r}")

    def iteration(_, carry):
        V_d, V_p = carry
        for (_tname, tid, slot, expr) in plan.topo_programs:
            itid = tid_map[tid]
            mask = (ntype == itid) & (nodes >= 0)
            d, p = eval_expr(expr, V_d, V_p)
            V_d = V_d.at[:, slot].set(jnp.where(mask, d, V_d[:, slot]))
            V_p = V_p.at[:, slot].set(jnp.where(mask, p, V_p[:, slot]))
        return V_d, V_p

    if plan.topo_programs:
        V_d, V_p = lax.fori_loop(0, cfg.eval_iters, iteration, (V_d, V_p))

    valid_q = (q_res >= 0) & (q_perm >= 0)
    perm_c = jnp.clip(q_perm, 0, SLOTS - 1)
    d = (V_d[0, perm_c] & valid_q) | q_self
    p = (V_p[0, perm_c] & valid_q) | q_self
    return d, p, _pany(overflow, axis)


# ---------------------------------------------------------------------------
# the jitted whole-batch function
# ---------------------------------------------------------------------------


def _make_check_fn(plan: DevicePlan, cfg: EngineConfig,
                   axis: Optional[str] = None, jit: bool = True,
                   caveat_plan: Optional[CaveatDevicePlan] = None):
    """Build the whole-batch check function.  With ``axis`` set, the
    function is written for shard_map over that mesh axis: edge arrays are
    shard-local and collectives merge at every gather/test point.  With a
    caveat plan, the on-device CEL VM gates caveated edges against merged
    stored/query context (qctx tables ride along as batch inputs)."""

    tri = make_tri_fn(caveat_plan) if caveat_plan is not None else None

    def fn(arrs, tid_map, now, u_subj, u_srel, u_wc, u_qctx,
           q_res, q_perm, q_subj, q_srel, q_wc, q_row, q_self, q_ctx, qctx):
        if tri is not None:
            tables = {
                "ectx_vi": arrs["ectx_vi"], "ectx_vf": arrs["ectx_vf"],
                "ectx_pr": arrs["ectx_pr"], "ectx_host": arrs["ectx_host"],
                "qctx_vi": qctx["vi"], "qctx_vf": qctx["vf"],
                "qctx_pr": qctx["pr"], "qctx_host": qctx["host"],
            }
        else:
            tables = None
        close_p = jax.vmap(
            lambda s, r, w, qc: _closure_one(
                arrs, cfg, "p", now, s, r, w, qc, tri, tables, axis
            )
        )
        Cp_n, Cp_r, ovf_p = close_p(u_subj, u_srel, u_wc, u_qctx)
        if plan.two_plane:
            close_d = jax.vmap(
                lambda s, r, w, qc: _closure_one(
                    arrs, cfg, "d", now, s, r, w, qc, tri, tables, axis
                )
            )
            Cd_n, Cd_r, ovf_d = close_d(u_subj, u_srel, u_wc, u_qctx)
        else:
            Cd_n, Cd_r, ovf_d = Cp_n, Cp_r, ovf_p

        per_query = jax.vmap(
            lambda a, b, c, d_, e, f, g, qc: _query_one(
                arrs, plan, cfg, now, tid_map,
                Cd_n, Cd_r, Cp_n, Cp_r,
                a, b, c, d_, e, f, g,
                qc, tri, tables,
                axis,
            )
        )
        d, p, ovf_q = per_query(
            q_res, q_perm, q_subj, q_srel, q_wc, q_row, q_self, q_ctx
        )
        u_ovf = ovf_d | ovf_p
        return d, p, ovf_q | u_ovf[q_row]

    return jax.jit(fn) if jit else fn


# ---------------------------------------------------------------------------
# host wrapper
# ---------------------------------------------------------------------------


@dataclass
class DeviceSnapshot:
    """Padded device-resident form of a Snapshot (padded to pow2 buckets so
    jit retraces are bounded)."""

    revision: int
    arrays: Dict[str, jnp.ndarray]
    tid_map: jnp.ndarray  # int32[num_schema_types] → interner type id
    snapshot: Snapshot
    #: string-intern pool for caveat context values (literals + stored
    #: context strings); query-time strings outside it get negative ids
    strings: Optional[Dict[str, int]] = None
    #: static geometry of the flat engine's hash/closure tables (None when
    #: the flat kernel is disabled); see engine/flat.py
    flat_meta: Optional[Any] = None
    #: accumulated host-side delta state since the last FULL prepare (set
    #: on delta-prepared snapshots; engine/flat.py _acc_collapse)
    delta_acc: Optional[Dict[str, np.ndarray]] = None
    #: host-side fold maintenance state (engine/fold.py FoldState), set
    #: at FULL prepare on folded worlds and carried along a delta chain
    #: so each revision's dl_pf* overlay recomputes from (base, acc)
    fold_state: Optional[Any] = None
    #: host-side closure advance state (engine/flat.py ClosureHostState):
    #: set at FULL prepare, ADVANCED each revision by the membership-delta
    #: path (store/closure.py advance_closure) so member-edge writes keep
    #: the flattened closure fresh without a rebuild
    closure_state: Optional[Any] = None
    #: lazily-attached latency-mode dispatcher (engine/latency.py
    #: LatencyPath) — per-snapshot warm state (staging buffers, local
    #: pin table); the executables themselves are shared engine-wide
    latency_path: Optional[Any] = None
    #: the store Snapshot a partitioned prepare was fed from (its
    #: ``snapshot`` is the bucket-filtered view); the client's dsnap
    #: cache identity check consults it
    source_snapshot: Optional[Any] = None
    #: HBM-lean mode keeps the raw O(E) kernel columns HOST-side (the
    #: flat blockslice kernel never reads them; sharded prepares never
    #: shipped them): the rare legacy fallback (a batch with more
    #: distinct permissions than flat_max_slots) ships them lazily once
    #: per snapshot via DeviceEngine._legacy_arrays
    host_arrays: Optional[Dict[str, np.ndarray]] = None
    #: the lazily-shipped legacy argument dict (host_arrays ∪ arrays)
    legacy_cache: Optional[Dict[str, Any]] = None


class DeviceEngine:
    """Compiles a schema into a jitted batched check function and manages
    device-resident snapshots."""

    def __init__(
        self, compiled: CompiledSchema, config: Optional[EngineConfig] = None
    ) -> None:
        self.compiled = compiled
        self.plan = build_plan(compiled)
        self.config = config or EngineConfig.for_schema(compiled)
        self.caveat_plan = (
            build_caveat_plan(compiled) if self.plan.two_plane else None
        )
        self._fn = _make_check_fn(
            self.plan, self.config, caveat_plan=self.caveat_plan
        )
        #: flat-kernel cache: (slots tuple, FlatMeta) → jitted fn
        self._flat_fns: Dict[Any, Any] = {}
        #: pinned latency-mode executables shared across snapshots:
        #: (FlatMeta, array-shape fingerprint, (slots, tier, qctx key))
        #: → AOT-compiled kernel — a Watch delta chain with stable table
        #: geometry re-pins per revision without recompiling.  Guarded by
        #: its own lock: multiple LatencyPaths (concurrent revisions)
        #: share this dict, and the FIFO eviction iterates it
        import threading

        self._latency_pins: Dict[Any, Any] = {}
        self._latency_pins_lock = threading.Lock()
        #: (slots, BP, meta) batch programs already registered with the
        #: perf cost ledger — the per-dispatch path checks this local
        #: set only (no global ledger lock per call)
        self._perf_cost_reg: set = set()
        #: context-free qctx singletons (host + device forms)
        self._empty_qctx_np: Optional[Dict[str, np.ndarray]] = None
        self._empty_qctx_jnp = None
        #: per-client string→node-id memo over the interner (bounded):
        #: the interner's own dict spans EVERY node in the store, so a
        #: lookup under zipf-skewed traffic thrashes CPU cache on a
        #: structure ~10^6× larger than the hot working set — this map
        #: holds just the hot keys.  Sound because node ids are append-
        #: only and stable; MISSES are never memoized (an unknown object
        #: can be interned by a later write, so -1 is not stable)
        self._intern_memo: Dict[Tuple[str, str], int] = {}
        self._intern_memo_src = None  # the Interner the memo is valid for

    #: hot-key memo capacity; on overflow the map clears and re-warms
    #: (zipf traffic repopulates the head in a few batches)
    INTERN_MEMO_MAX = 1 << 16

    #: every per-edge/lookup column _host_arrays emits (the sharded engine
    #: derives its shard_map specs from this — keep in lockstep, enforced
    #: by test_sharded.py's key-parity test)
    ARRAY_COLUMN_KEYS = (
        "e_rel", "e_res", "e_subj", "e_srel1", "e_caveat", "e_ctx", "e_exp",
        "us_rel", "us_res", "us_subj", "us_srel", "us_caveat", "us_ctx",
        "us_exp", "us_perm", "pus_n", "pus_r",
        "ms_subj", "ms_res", "ms_rel", "ms_caveat", "ms_ctx", "ms_exp",
        "mp_subj", "mp_srel", "mp_res", "mp_rel", "mp_caveat", "mp_ctx",
        "mp_exp",
        "ar_rel", "ar_res", "ar_child", "ar_caveat", "ar_ctx", "ar_exp",
        "node_type",
    )

    # -- snapshot preparation -------------------------------------------
    def _host_arrays(self, snap: Snapshot) -> Dict[str, np.ndarray]:
        """Padded host-side columns (shared by single-chip and sharded
        prepare paths)."""
        E = _ceil_pow2(snap.e_rel.shape[0])
        US = _ceil_pow2(snap.us_rel.shape[0])
        MS = _ceil_pow2(snap.ms_subj.shape[0])
        MP = _ceil_pow2(snap.mp_subj.shape[0])
        AR = _ceil_pow2(snap.ar_rel.shape[0])
        # 2x headroom: Watch-driven deltas intern fresh nodes, and the
        # delta-prepare reuses this buffer until the bucket would grow
        NN = _ceil_pow2(2 * snap.num_nodes)
        return {
            "e_rel": _pad_sorted(snap.e_rel, E),
            "e_res": _pad_sorted(snap.e_res, E),
            "e_subj": _pad_sorted(snap.e_subj, E),
            "e_srel1": _pad_sorted(snap.e_srel1, E),
            "e_caveat": _pad_payload(snap.e_caveat, E),
            "e_ctx": _pad_payload(snap.e_ctx, E, -1),
            "e_exp": _pad_payload(snap.e_exp, E),
            "us_rel": _pad_sorted(snap.us_rel, US),
            "us_res": _pad_sorted(snap.us_res, US),
            "us_subj": _pad_payload(snap.us_subj, US, -1),
            "us_srel": _pad_payload(snap.us_srel, US, -1),
            "us_caveat": _pad_payload(snap.us_caveat, US),
            "us_ctx": _pad_payload(snap.us_ctx, US, -1),
            "us_exp": _pad_payload(snap.us_exp, US),
            "us_perm": _pad_payload(snap.us_perm, US),
            "pus_n": _pad_sorted(snap.pus_n, _ceil_pow2(snap.pus_n.shape[0])),
            "pus_r": _pad_sorted(snap.pus_r, _ceil_pow2(snap.pus_n.shape[0])),
            "ms_subj": _pad_sorted(snap.ms_subj, MS),
            "ms_res": _pad_payload(snap.ms_res, MS, -1),
            "ms_rel": _pad_payload(snap.ms_rel, MS, -1),
            "ms_caveat": _pad_payload(snap.ms_caveat, MS),
            "ms_ctx": _pad_payload(snap.ms_ctx, MS, -1),
            "ms_exp": _pad_payload(snap.ms_exp, MS),
            "mp_subj": _pad_sorted(snap.mp_subj, MP),
            "mp_srel": _pad_sorted(snap.mp_srel, MP),
            "mp_res": _pad_payload(snap.mp_res, MP, -1),
            "mp_rel": _pad_payload(snap.mp_rel, MP, -1),
            "mp_caveat": _pad_payload(snap.mp_caveat, MP),
            "mp_ctx": _pad_payload(snap.mp_ctx, MP, -1),
            "mp_exp": _pad_payload(snap.mp_exp, MP),
            "ar_rel": _pad_sorted(snap.ar_rel, AR),
            "ar_res": _pad_sorted(snap.ar_res, AR),
            "ar_child": _pad_payload(snap.ar_child, AR, -1),
            "ar_caveat": _pad_payload(snap.ar_caveat, AR),
            "ar_ctx": _pad_payload(snap.ar_ctx, AR, -1),
            "ar_exp": _pad_payload(snap.ar_exp, AR),
            "node_type": _pad_payload(snap.node_type, NN, -1),
        }

    def _ectx_tables(
        self, snap: Snapshot
    ) -> Tuple[Dict[str, np.ndarray], Optional[Dict[str, int]]]:
        """Encode stored caveat contexts into padded device tables."""
        if self.caveat_plan is None:
            return {}, None
        strings = dict(self.caveat_plan.base_strings)
        table = encode_contexts(self.caveat_plan, snap.contexts, strings)
        # 2x headroom: Watch-driven deltas append stored contexts, and the
        # delta-prepare re-encodes in place only while the bucket holds
        NC = _ceil_pow2(2 * max(table.vi.shape[0], 1), 4)

        def padrows(a: np.ndarray, fill=0) -> np.ndarray:
            out = np.full((NC,) + a.shape[1:], fill, a.dtype)
            out[: a.shape[0]] = a
            return out

        return {
            "ectx_vi": padrows(table.vi),
            "ectx_vf": padrows(table.vf),
            "ectx_pr": padrows(table.present),
            "ectx_host": padrows(table.host),
        }, strings

    @staticmethod
    def record_device_bytes(arrays: Mapping[str, Any]) -> int:
        """Publish the resident table footprint: one
        ``snapshot.device_bytes`` gauge plus a per-table breakdown
        (``snapshot.device_bytes.<table>``) — /metrics and trace spans
        then report HBM residency live, not just at bench time."""
        total = 0
        # drop the previous snapshot's per-table entries first: a delta
        # prepare can remove tables (despec'd offset anchors), and a
        # stale gauge would break breakdown-sums-to-total
        metrics.default.clear_gauges("snapshot.device_bytes.")
        for k, v in arrays.items():
            nb = int(getattr(v, "nbytes", 0))
            total += nb
            metrics.default.set_gauge(f"snapshot.device_bytes.{k}", nb)
        metrics.default.set_gauge("snapshot.device_bytes", total)
        _trace.event_if_active("snapshot.device_bytes", total=total)
        return total

    def prepare(
        self, snap: Snapshot, prev: Optional[DeviceSnapshot] = None
    ) -> DeviceSnapshot:
        """Ship a snapshot to the device.  With ``prev`` (the DeviceSnapshot
        of the revision this one was delta-derived from), try the
        incremental path first: base tables stay resident, only small
        ``dl_*`` overlays ship (engine/flat.py build_delta_arrays) — the
        Watch-driven re-index costs O(delta), not O(E), per revision."""
        faults.fire("device.prepare")
        if prev is not None:
            out = self._prepare_delta(snap, prev)
            if out is not None:
                return out
        _t0 = _time.perf_counter()
        with metrics.default.timer("prepare.host_tables_s"):
            arrays = self._host_arrays(snap)
            ectx, strings = self._ectx_tables(snap)
            arrays.update(ectx)
        flat_meta = None
        fold_state = None
        closure_state = None
        host_arrays = None
        if self.config.use_flat:
            from .flat import build_flat_arrays

            built = build_flat_arrays(snap, self.config, plan=self.plan)
            if built is not None:  # unpackable graphs use the legacy path
                flat_arrays, flat_meta, fold_state, closure_state = built
                arrays.update(flat_arrays)
                if self.config.packed_on() and flat_meta.blockslice:
                    # HBM-lean: the blockslice kernel reads none of the
                    # raw O(E) columns — keep them host-side and ship
                    # them lazily iff the legacy fallback ever fires
                    from .packed import narrow_nodes

                    host_arrays = {
                        k: arrays.pop(k)
                        for k in self.ARRAY_COLUMN_KEYS
                        if k != "node_type" and k in arrays
                    }
                    arrays["node_type"] = narrow_nodes(
                        arrays["node_type"], snap.interner.num_types
                    )
        with metrics.default.timer("prepare.h2d_s"):
            # one batched transfer (the runtime can pipeline leaves)
            # instead of per-array jnp.asarray round trips
            arrays = jax.device_put(arrays)
        self.record_device_bytes(arrays)
        tid_map = np.full(max(self.plan.num_schema_types, 1), -1, dtype=np.int32)
        for tname, tid in self.compiled.type_ids.items():
            tid_map[tid] = snap.interner.type_lookup(tname)
        if not self._frontier_will_serve(flat_meta, snap):
            # snapshots carrying the reverse-CSR index (within the
            # frontier's seen-set budget) answer lookups on the device
            # frontier path (engine/spmv.py) — the O(E log E) transposed
            # host index would be dead weight there; everything else
            # still walker-serves and wants the background build
            self._maybe_prewarm_walker_index(snap)
        metrics.default.observe(
            "prepare.total_s", _time.perf_counter() - _t0
        )
        dsnap = DeviceSnapshot(
            revision=snap.revision,
            arrays=arrays,
            tid_map=jnp.asarray(tid_map),
            snapshot=snap,
            strings=strings,
            flat_meta=flat_meta,
            fold_state=fold_state,
            closure_state=closure_state,
            host_arrays=host_arrays,
        )
        # perf ledger: publish the gathered-bytes model (per-level,
        # per-table) for this snapshot — the roofline numerator rides
        # /metrics and incident bundles from the moment of prepare
        _perf.publish_model(dsnap)
        if _pallas.resolve(self.config):
            # Pallas backend armed: publish what its kernels keep
            # VMEM-resident and the modeled one-pass bytes delta
            _pallas.publish_vmem(arrays)
            _perf.publish_pallas_model(dsnap)
        return dsnap

    @staticmethod
    def _frontier_will_serve(flat_meta, snap) -> bool:
        """Whether lookups on this snapshot take the device frontier
        path (engine/spmv.py) — ONE shared predicate with frontier_ok's
        static half, so the prewarm decision cannot drift from the
        actual lookup routing."""
        from .spmv import frontier_static_ok

        return frontier_static_ok(flat_meta, snap)

    def _maybe_prewarm_walker_index(self, snap: Snapshot) -> None:
        """Build the transposed lookup index off-thread (numpy sorts
        release the GIL): the first walker-served lookup_resources at
        1M+ docs then joins a mostly-finished build instead of paying
        the whole O(E log E) sort inside a user-facing query.  One
        in-flight build per engine — a Watch chain of delta prepares
        must not stack O(E log E) threads (once the first build lands,
        the chain-advance machinery carries it forward in O(D))."""
        if not (
            self.config.lookup_prewarm
            and snap.num_edges >= LOOKUP_PREWARM_MIN_EDGES
            and getattr(snap, "_lookup_index", None) is None
            and not self.__dict__.get("_prewarm_inflight")
        ):
            return
        import threading

        from .lookup import lookup_index

        self._prewarm_inflight = True

        def run():
            try:
                lookup_index(snap, mark_used=False)
            finally:
                self._prewarm_inflight = False

        threading.Thread(
            target=run, name="gochugaru-lookup-prewarm", daemon=True
        ).start()

    def _delta_prev_ok(self, prev: DeviceSnapshot) -> bool:
        """Layout eligibility of ``prev`` for the incremental prepare —
        the sharded engine overrides (its base tables are bucket-sharded)."""
        return prev.flat_meta is not None and not prev.flat_meta.sharded

    def _place_replicated(self, v: np.ndarray):
        """Ship a replicated (non-bucket-sharded) host array — overlays,
        node types, stored-context tables.  The sharded engine overrides
        with an explicitly-replicated device_put."""
        return jnp.asarray(v)

    def _prepare_delta(
        self, snap: Snapshot, prev: DeviceSnapshot
    ) -> Optional[DeviceSnapshot]:
        """The incremental prepare, or None → caller does a full one.

        The produced DeviceSnapshot REUSES prev's device buffers for every
        base table (no re-ship); only the delta overlays, a possibly-grown
        node_type column, and re-encoded stored-context tables move.  The
        legacy (non-flat) kernel columns inside are left at the BASE
        revision — a delta-prepared snapshot serves the flat path, and the
        engine's check paths only fall back to the legacy kernel when
        flat_meta is None, which is never the case here.  Shared verbatim
        by the sharded engine (whose overlay placement is replicated
        across the mesh) through the two hooks above."""
        if not (
            self.config.use_flat
            and self.config.flat_blockslice
            and self._delta_prev_ok(prev)
        ):
            return None
        from dataclasses import replace as _dc_replace

        from .flat import build_delta_arrays

        built = build_delta_arrays(snap, prev, self.compiled, self.config)
        if built is None:
            return None
        dl_arrays, dmeta, acc, extras = built
        arrays = dict(prev.arrays)
        # drop the previous overlay's tables: the new overlay replaces them
        # (a shrunk accumulated delta must not leave stale tables behind)
        for k in [k for k in arrays if k.startswith("dl_")]:
            del arrays[k]
        strings = prev.strings
        if len(snap.contexts) != len(prev.snapshot.contexts):
            ectx, strings = self._ectx_tables(snap)
            old = prev.arrays.get("ectx_vi")
            if old is not None and ectx["ectx_vi"].shape[0] != old.shape[0]:
                return None  # context bucket grew: shapes change, rebuild
            arrays.update(
                {k: self._place_replicated(v) for k, v in ectx.items()}
            )
        if snap.num_nodes > prev.snapshot.num_nodes:
            NN = int(prev.arrays["node_type"].shape[0])
            if snap.num_nodes > NN:
                return None  # node bucket outgrown: every node shape moves
            nt = _pad_payload(snap.node_type, NN, -1)
            prev_dt = prev.arrays["node_type"].dtype
            if prev_dt != nt.dtype:
                # the base narrowed node_type (HBM-lean); fresh interner
                # type ids past the narrow dtype's range would WRAP —
                # bail to a full prepare, which re-derives the width
                if int(nt.max(initial=0)) > np.iinfo(prev_dt).max:
                    return None
                nt = nt.astype(prev_dt)
            arrays["node_type"] = self._place_replicated(nt)
        arrays.update(
            {k: self._place_replicated(v) for k, v in dl_arrays.items()}
        )
        for k in extras.get("drop_keys", ()):
            arrays.pop(k, None)  # despec'd packed-offset anchors
        # an empty collapsed delta (or one that cancelled out) compiles as
        # the plain base kernel — don't pay a retrace for DeltaMeta()
        meta = _dc_replace(
            prev.flat_meta, delta=dmeta if dl_arrays else None,
            **extras.get("meta_up", {}),
        )
        self.record_device_bytes(arrays)
        if meta.delta is not None:
            # an LSM delta level declines the device frontier
            # (engine/spmv.py frontier_ok), so lookups on this chain
            # walker-serve: start the transposed-index build in the
            # background NOW instead of paying it inside the first
            # post-delta lookup (one in-flight build per engine; the
            # chain-advance machinery carries it forward afterwards)
            self._maybe_prewarm_walker_index(snap)
        return DeviceSnapshot(
            revision=snap.revision,
            arrays=arrays,
            tid_map=prev.tid_map,
            snapshot=snap,
            strings=strings,
            flat_meta=meta,
            delta_acc=acc,
            fold_state=prev.fold_state,
            closure_state=extras.get("closure_state"),
            host_arrays=prev.host_arrays,
        )

    # -- query lowering --------------------------------------------------
    def _lower_queries(
        self, snap: Snapshot, rels: Sequence[Relationship],
        strings: Optional[Dict[str, int]] = None,
    ) -> Tuple[Dict[str, np.ndarray], np.ndarray, Dict[str, np.ndarray]]:
        B = len(rels)
        interner = snap.interner
        slot_of = self.compiled.slot_of_name
        wc_of = snap.wildcard_node_of_type

        q_res = np.full(B, -1, np.int32)
        q_perm = np.full(B, -1, np.int32)
        q_subj = np.full(B, -1, np.int32)
        q_srel = np.full(B, -1, np.int32)
        q_wc = np.full(B, -1, np.int32)
        q_ctx = np.full(B, -1, np.int32)
        q_self = np.zeros(B, bool)

        # dedup request contexts (the caveat_context of the query
        # relationship IS the request context, client/client.go:241-259)
        ctx_rows: List[Mapping] = []
        ctx_index: Dict[str, int] = {}
        if self.caveat_plan is not None:
            for i, r in enumerate(rels):
                if r.caveat_context:
                    key = repr(sorted(r.caveat_context.items(), key=lambda kv: kv[0]))
                    at = ctx_index.get(key)
                    if at is None:
                        at = len(ctx_rows)
                        ctx_index[key] = at
                        ctx_rows.append(r.caveat_context)
                    q_ctx[i] = at

        if self._intern_memo_src is not interner:
            # memoized ids are only valid against the interner that
            # assigned them — a snapshot from a different store resets
            # the memo (id identity, not equality: interners only grow)
            self._intern_memo = {}
            self._intern_memo_src = interner
        memo = self._intern_memo
        memo_get = memo.get
        lookup = interner.lookup
        memo_hits = 0
        memo_max = self.INTERN_MEMO_MAX

        def node_of(tname: str, oid: str) -> int:
            nonlocal memo_hits
            k = (tname, oid)
            v = memo_get(k)
            if v is not None:
                memo_hits += 1
                return v
            v = lookup(tname, oid)
            if v >= 0:
                if len(memo) >= memo_max:
                    memo.clear()
                memo[k] = v
            return v

        for i, r in enumerate(rels):
            q_res[i] = node_of(r.resource_type, r.resource_id)
            q_perm[i] = slot_of.get(r.resource_relation, -1)
            q_subj[i] = node_of(r.subject_type, r.subject_id)
            if r.subject_relation:
                srel = slot_of.get(r.subject_relation)
                if srel is None:
                    # unknown subject relation can never be granted; -1
                    # would alias "direct subject", so force the query false
                    q_res[i] = -1
                    q_srel[i] = -1
                else:
                    q_srel[i] = srel
            else:
                q_srel[i] = -1
            stid = interner.type_lookup(r.subject_type)
            if stid >= 0 and stid < wc_of.shape[0] and r.subject_id != WILDCARD_ID:
                q_wc[i] = wc_of[stid]
            q_self[i] = (
                r.resource_type == r.subject_type
                and r.resource_id == r.subject_id
                and r.subject_relation == r.resource_relation
                and r.subject_relation != ""
            )

        if memo_hits:
            metrics.default.inc("intern.memo_hits", memo_hits)
        # unique (subject, query-context) rows for Phase A — context is part
        # of the key because caveat gates make closures context-dependent
        subj_key = np.stack([q_subj, q_srel, q_wc, q_ctx], axis=1)
        uniq, q_row = np.unique(subj_key, axis=0, return_inverse=True)
        queries = {
            "q_res": q_res, "q_perm": q_perm, "q_subj": q_subj,
            "q_srel": q_srel, "q_wc": q_wc, "q_ctx": q_ctx,
            "q_row": q_row.astype(np.int32), "q_self": q_self,
        }
        qctx_tables = self._encode_query_contexts(ctx_rows, strings)
        return queries, uniq.astype(np.int32), qctx_tables

    def _encode_query_contexts(
        self, ctx_rows: List[Mapping], strings: Optional[Dict[str, int]]
    ) -> Dict[str, np.ndarray]:
        """Encode deduped request contexts into padded qctx tables.  The
        context-free case (most checks) returns a per-engine singleton so
        dispatch paths can cache its device form — 4 of the ~12 small
        host→device puts a small-batch check pays."""
        if not ctx_rows and self._empty_qctx_np is not None:
            return self._empty_qctx_np
        if self.caveat_plan is None:
            P = 1
            out = {
                "vi": np.zeros((1, P), np.int32),
                "vf": np.zeros((1, P), np.float32),
                "pr": np.zeros((1, P), bool),
                "host": np.zeros((1, 1), bool),
            }
            if not ctx_rows:
                self._empty_qctx_np = out
            return out
        table = encode_contexts(
            self.caveat_plan, ctx_rows,
            strings if strings is not None else dict(self.caveat_plan.base_strings),
            extra_strings={},
        )
        NQ = _ceil_pow2(table.vi.shape[0], 1)

        def padrows(a: np.ndarray) -> np.ndarray:
            out = np.zeros((NQ,) + a.shape[1:], a.dtype)
            out[: a.shape[0]] = a
            return out

        out = {
            "vi": padrows(table.vi),
            "vf": padrows(table.vf),
            "pr": padrows(table.present),
            "host": padrows(table.host),
        }
        if not ctx_rows:
            self._empty_qctx_np = out
        return out

    def _qctx_device(self, qctx: Dict[str, np.ndarray]):
        """Device form of the qctx tables, cached for the context-free
        singleton (checks without request context skip 4 host→device
        transfers per dispatch)."""
        if qctx is self._empty_qctx_np:
            if self._empty_qctx_jnp is None:
                self._empty_qctx_jnp = {
                    k: jnp.asarray(v) for k, v in qctx.items()
                }
            return self._empty_qctx_jnp
        return {k: jnp.asarray(v) for k, v in qctx.items()}

    # -- latency-mode path (engine/latency.py) ---------------------------
    #: bound on engine-wide pinned latency executables (FIFO, same
    #: rationale as FLAT_FN_CACHE_MAX; each pin is one compiled XLA
    #: program at one small-batch tier)
    LATENCY_PIN_CACHE_MAX = 32

    def latency_path(self, dsnap: DeviceSnapshot):
        """The warm small-batch dispatcher attached to this prepared
        snapshot (created on first use; see engine/latency.py)."""
        if dsnap.latency_path is None:
            from .latency import LatencyPath

            with self._latency_pins_lock:
                if dsnap.latency_path is None:
                    dsnap.latency_path = LatencyPath(self, dsnap)
        return dsnap.latency_path

    #: bounded retries for the deadline-less engine-level latency entry
    #: point (callers with a Context pass their own; the envelope itself
    #: is the client's, utils/retry.py)
    LATENCY_RETRY_TRIES = 3

    def check_columns_latency(
        self,
        dsnap: DeviceSnapshot,
        q_res: np.ndarray,
        q_perm: np.ndarray,
        q_subj: np.ndarray,
        *,
        q_srel: Optional[np.ndarray] = None,
        q_wc: Optional[np.ndarray] = None,
        q_ctx: Optional[np.ndarray] = None,
        qctx_rows: Optional[Sequence[Mapping[str, Any]]] = None,
        now_us: Optional[int] = None,
        ctx: Optional[Any] = None,
    ):
        """Latency-mode bulk check from pre-interned columns: pinned
        kernel, tiered padding, per-stage budget metrics.  Falls back to
        ``check_columns`` when the latency path cannot serve the batch
        (no flat tables, too many distinct permissions, batch beyond the
        top tier) — same result contract either way.

        Failure contract now matches the batch path (client.py check):
        raw dispatch errors are classified onto the retry taxonomy
        (transient → ``UnavailableError``) and transient failures retry
        under the reference's backoff envelope — bounded by ``ctx`` when
        given, else by ``LATENCY_RETRY_TRIES`` so a deadline-less bench
        caller cannot hang on a persistent fault."""

        span = _trace.span_of(ctx) if ctx is not None else _trace.NOOP

        def dispatch():
            try:
                out = self.latency_path(dsnap).dispatch_columns(
                    q_res, q_perm, q_subj, q_srel=q_srel, q_wc=q_wc,
                    q_ctx=q_ctx, qctx_rows=qctx_rows, now_us=now_us,
                    span=span,
                )
                if out is not None:
                    return out
                return self.check_columns(
                    dsnap, q_res, q_perm, q_subj, q_srel=q_srel, q_wc=q_wc,
                    q_ctx=q_ctx, qctx_rows=qctx_rows, now_us=now_us,
                )
            except Exception as e:
                classified = classify_dispatch_exception(e)
                if classified is None or classified is e:
                    raise
                raise classified

        return retry_retriable_errors(
            ctx if ctx is not None else _background(),
            dispatch,
            max_tries=None if ctx is not None else self.LATENCY_RETRY_TRIES,
        )

    # -- flat-kernel plumbing (engine/flat.py) ---------------------------
    #: bound on cached per-permission-subset kernels (simple FIFO eviction:
    #: a pathological workload cycling through C(P, ≤8) subsets pays
    #: recompiles but can't grow device/host memory without bound)
    FLAT_FN_CACHE_MAX = 16

    def _legacy_arrays(self, dsnap: DeviceSnapshot) -> Dict[str, Any]:
        """Argument dict for the legacy (non-flat) kernel.  HBM-lean
        snapshots keep the raw O(E) columns host-side; the first legacy
        fallback ships them once and caches the merged dict on the
        snapshot."""
        if dsnap.host_arrays is None:
            return dsnap.arrays
        if dsnap.legacy_cache is None:
            merged = dict(dsnap.arrays)
            merged.update(jax.device_put(dsnap.host_arrays))
            dsnap.legacy_cache = merged
        return dsnap.legacy_cache

    def _flat_fn_for(self, slots: Tuple[int, ...], meta, witness: bool = False):
        key = (slots, meta) if not witness else (slots, meta, "wit")
        fn = self._flat_fns.get(key)
        if fn is None:
            from .flat import make_flat_fn

            fn = make_flat_fn(
                self.compiled, self.plan, self.config, meta, slots,
                caveat_plan=self.caveat_plan, witness=witness,
            )
            while len(self._flat_fns) >= self.FLAT_FN_CACHE_MAX:
                self._flat_fns.pop(next(iter(self._flat_fns)))
            self._flat_fns[key] = fn
        return fn

    def flat_fn_and_args(
        self,
        dsnap: DeviceSnapshot,
        queries: Dict[str, np.ndarray],
        qctx: Dict[str, np.ndarray],
        now,
        B: int,
        jit: bool = True,
        bucket_min: int = 0,
        witness: bool = False,
    ):
        """The flat kernel + its lowered padded argument tuple — the ONE
        place that knows the kernel's signature (check paths, bench.py,
        __graft_entry__ and the witness extraction all call this).  None
        when the flat path is unavailable (disabled, unpackable graph, or
        more distinct permissions in the batch than flat_max_slots).
        ``witness=True`` selects the armed kernel (same signature, extra
        witness-plane output) — cached separately, never registered in
        the device cost ledger (the ledger key names the serving
        kernel)."""
        if dsnap.flat_meta is None:
            return None
        slots = tuple(
            sorted({int(s) for s in np.unique(queries["q_perm"]) if s >= 0})
        )
        if len(slots) > self.config.flat_max_slots:
            return None
        from .flat import build_qm

        if jit:
            fn = self._flat_fn_for(slots, dsnap.flat_meta, witness=witness)
        else:
            from .flat import make_flat_fn

            fn = make_flat_fn(
                self.compiled, self.plan, self.config, dsnap.flat_meta,
                slots, caveat_plan=self.caveat_plan, jit=False,
                witness=witness,
            )
        BP = _ceil_pow2(B, max(bucket_min, self.config.batch_bucket_min))
        # ONE packed query matrix (flat.QM_LAYOUT) → one device transfer
        args = (
            dsnap.arrays, dsnap.tid_map, now,
            jnp.asarray(build_qm(queries, BP, dsnap.flat_meta)),
            self._qctx_device(qctx),
        )
        if jit and not witness:
            # device cost ledger: the batch-path program registers a
            # LAZY capture over ShapeDtypeStruct avals (no device
            # buffers pinned, no compile here) — realized only when a
            # consumer explicitly asks (/perf?compile=1, perf smoke).
            # The engine-local registered-set keeps the steady-state
            # dispatch path to one set lookup (no global ledger lock,
            # no key formatting per call — same discipline as
            # spmv.FrontierKernels._register_cost)
            rk = (slots, BP, dsnap.flat_meta)
            if rk not in self._perf_cost_reg:
                self._perf_cost_reg.add(rk)
                ck = (
                    f"slots={slots};B={BP};"
                    f"meta={hash(dsnap.flat_meta) & 0xFFFFFFFF:08x}"
                )
                _perf.register_cost_thunk(
                    "batch", ck,
                    lambda fn=fn, avals=_perf.avals_of(args): fn.lower(
                        *avals
                    ).compile(),
                )
        return fn, args

    def _flat_call(
        self,
        dsnap: DeviceSnapshot,
        queries: Dict[str, np.ndarray],
        qctx: Dict[str, np.ndarray],
        now,
        B: int,
        bucket_min: int = 0,
    ):
        """Dispatch the flat kernel; returns padded device (d, p, ovf), or
        None when the flat path is unavailable."""
        got = self.flat_fn_and_args(
            dsnap, queries, qctx, now, B, bucket_min=bucket_min
        )
        if got is None:
            return None
        fn, args = got
        return fn(*args)

    # -- decision provenance (engine/explain.py) -------------------------
    def witness_codes(
        self,
        dsnap: DeviceSnapshot,
        rels: Sequence[Relationship],
        *,
        now_us: Optional[int] = None,
    ) -> Optional[np.ndarray]:
        """Per-check device WITNESS codes for a batch: the winning-branch
        plane the armed flat kernel emits (engine/flat.py
        ``make_flat_fn(witness=True)``; codes in engine/explain.py).
        Nonzero only for device-definite allowed verdicts — conditional/
        overflow rows (host-oracle resolved) report 0, and rows the flat
        path cannot serve at all return None (the explain walk then runs
        unseeded).  Armed kernels cache separately from the serving
        kernels, so calling this never perturbs the disarmed fast path."""
        meta = dsnap.flat_meta
        if meta is None or meta.sharded:
            return None
        snap = dsnap.snapshot
        queries, _uniq, qctx = self._lower_queries(snap, rels, dsnap.strings)
        B = len(rels)
        got = self.flat_fn_and_args(
            dsnap, queries, qctx, jnp.int32(snap.now_rel32(now_us)), B,
            witness=True,
        )
        if got is None:
            return None
        fn, args = got
        d, p, ovf, wit = jax.device_get(fn(*args))
        wit = wit[:B].copy()
        # host-resolved rows (conditional, overflow) carry no trusted
        # device witness — the oracle walk explains them unseeded
        wit[(p[:B] & ~d[:B]) | ovf[:B]] = 0
        return wit

    # -- the batched check ----------------------------------------------
    def check_batch(
        self,
        dsnap: DeviceSnapshot,
        rels: Sequence[Relationship],
        *,
        now_us: Optional[int] = None,
        latency: bool = False,
        span=_trace.NOOP,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (definite, possible, overflow) bool arrays of len(rels).

        ``definite`` → permission granted.  ``possible & ~definite`` →
        conditional on caveats the device didn't evaluate; the caller
        resolves via the host oracle.  ``overflow`` → a static cap was
        exceeded; the caller must re-check on the host.

        With ``latency``, small batches route through the latency-mode
        path (engine/latency.py: pinned kernel at a fixed tier, staged
        budget metrics); batches it cannot serve fall through to the
        ordinary dispatch below, same contract.  ``span`` is the
        request's trace span (utils/trace.py): sampled dispatches record
        a ``device.check_batch`` child with lower/kernel/fetch stage
        boundaries as events; the NOOP span costs one branch."""
        if not rels:
            z = np.zeros(0, bool)
            return z, z, z
        faults.fire("device.dispatch")
        if _pallas.resolve(self.config):
            # pallas-path failures classify through the SAME retry
            # envelope as any dispatch: the chaos soak arms this site to
            # prove the fused-kernel path reroutes like the XLA one
            faults.fire("pallas.dispatch")
        import time as _time

        t_lower = _time.perf_counter()
        dsp = span.child("device.check_batch", t=t_lower, batch=len(rels))
        try:
            snap = dsnap.snapshot
            queries, uniq, qctx = self._lower_queries(snap, rels, dsnap.strings)
            dsp.event("stage.lower")
            B = len(rels)
            if latency:
                out = self.latency_path(dsnap).dispatch(
                    queries, qctx, B, snap.now_rel32(now_us),
                    t_start=t_lower, span=dsp,
                )
                if out is not None:
                    return out
            now_flat = jnp.int32(snap.now_rel32(now_us))
            PB = self._pipeline_batch()
            if PB and B > PB and dsnap.flat_meta is not None:
                # sub-batch pipeline: dispatch every chunk before fetching
                # any (the async queue overlaps lowering with compute); one
                # shared compiled program per PB bucket
                subs = []
                with _trace.annotate_dispatch(span):
                    for lo in range(0, B, PB):
                        sub = {k: v[lo:lo + PB] for k, v in queries.items()}
                        o = self._flat_call(
                            dsnap, sub, qctx, now_flat, min(PB, B - lo),
                            bucket_min=PB,
                        )
                        if o is None:
                            subs = None
                            break
                        subs.append((min(PB, B - lo), o))
                if subs is not None:
                    dsp.event("stage.dispatch", pipelined=len(subs))
                    ds, ps, os_ = [], [], []
                    for n, o in subs:
                        d, p, ovf = jax.device_get(o)
                        ds.append(d[:n]); ps.append(p[:n]); os_.append(ovf[:n])
                    dsp.event("stage.fetch")
                    return (
                        np.concatenate(ds), np.concatenate(ps),
                        np.concatenate(os_),
                    )
            with _trace.annotate_dispatch(span):
                out = self._flat_call(dsnap, queries, qctx, now_flat, B)
            if out is not None:
                dsp.event("stage.dispatch")
                d, p, ovf = jax.device_get(out)
                dsp.event("stage.fetch")
                return d[:B], p[:B], ovf[:B]
            BP = _ceil_pow2(B, self.config.batch_bucket_min)
            U = uniq.shape[0]
            UP = _ceil_pow2(U, self.config.batch_bucket_min)

            def padq(a, fill):
                out = np.full(BP, fill, a.dtype)
                out[:B] = a
                return jnp.asarray(out)

            u_subj = np.full(UP, -1, np.int32)
            u_srel = np.full(UP, -1, np.int32)
            u_wc = np.full(UP, -1, np.int32)
            u_qctx = np.full(UP, -1, np.int32)
            u_subj[:U] = uniq[:, 0]
            u_srel[:U] = uniq[:, 1]
            u_wc[:U] = uniq[:, 2]
            u_qctx[:U] = uniq[:, 3]

            now = jnp.int32(snap.now_rel32(now_us))
            with _trace.annotate_dispatch(span):
                d, p, ovf = self._fn(
                    self._legacy_arrays(dsnap), dsnap.tid_map, now,
                    jnp.asarray(u_subj), jnp.asarray(u_srel), jnp.asarray(u_wc),
                    jnp.asarray(u_qctx),
                    padq(queries["q_res"], -1), padq(queries["q_perm"], -1),
                    padq(queries["q_subj"], -1), padq(queries["q_srel"], -1),
                    padq(queries["q_wc"], -1), padq(queries["q_row"], 0),
                    padq(queries["q_self"], False), padq(queries["q_ctx"], -1),
                    self._qctx_device(qctx),
                )
            dsp.event("stage.dispatch", legacy=True)
            # one device→host fetch for all three planes: separate np.asarray
            # calls round-trip the dispatch boundary once each, which dominates
            # small-batch latency on remote-attached TPUs
            d, p, ovf = jax.device_get((d, p, ovf))
            dsp.event("stage.fetch")
            return d[:B], p[:B], ovf[:B]
        finally:
            dsp.end()

    # -- columnar bulk check ---------------------------------------------
    def _columns_preamble(
        self,
        dsnap: DeviceSnapshot,
        q_res: np.ndarray,
        q_perm: np.ndarray,
        q_subj: np.ndarray,
        q_srel: Optional[np.ndarray],
        q_wc: Optional[np.ndarray],
        q_ctx: Optional[np.ndarray],
        qctx_rows,
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
        """Shared columnar-check preamble: optional-column defaulting,
        query-context encoding, and the reflexive-self derivation — one
        definition so the single-chip and sharded paths cannot drift."""
        B = q_res.shape[0]
        if q_srel is None:
            q_srel = np.full(B, -1, np.int32)
        if q_wc is None:
            q_wc = np.full(B, -1, np.int32)
        if q_ctx is None:
            q_ctx = np.full(B, -1, np.int32)
        qctx = self._encode_query_contexts(list(qctx_rows or []), dsnap.strings)
        queries = {
            "q_res": np.ascontiguousarray(q_res, np.int32),
            "q_perm": np.ascontiguousarray(q_perm, np.int32),
            "q_subj": np.ascontiguousarray(q_subj, np.int32),
            "q_srel": np.ascontiguousarray(q_srel, np.int32),
            "q_wc": np.ascontiguousarray(q_wc, np.int32),
            "q_ctx": np.ascontiguousarray(q_ctx, np.int32),
            # reflexive userset identity (a userset is a member of itself),
            # same semantics as _lower_queries' q_self: slots are shared
            # between q_perm and q_srel, and equal interned nodes mean
            # equal (type, id)
            "q_self": (q_res == q_subj) & (q_srel >= 0) & (q_perm == q_srel),
        }
        return queries, qctx

    def _pipeline_batch(self) -> int:
        """Resolved sub-batch pipeline size (config None = backend auto:
        TPU queues overlap, one CPU core doesn't)."""
        PB = self.config.flat_pipeline_batch
        if PB is None:
            return 32_768 if jax.default_backend() == "tpu" else 0
        return PB

    def check_columns_pipelined(
        self,
        dsnap: DeviceSnapshot,
        q_res: np.ndarray,
        q_perm: np.ndarray,
        q_subj: np.ndarray,
        *,
        q_ctx: Optional[np.ndarray] = None,
        qctx_rows: Optional[Sequence[Mapping[str, Any]]] = None,
        now_us: Optional[int] = None,
        sub_batch: Optional[int] = None,
    ):
        """Pipelined bulk check over pre-interned columns: the batch is
        split into ``sub_batch``-sized dispatches enqueued back-to-back
        (jax async dispatch), then fetched IN ORDER as they complete —
        yields ``(lo, hi, d, p, ovf)`` per sub-batch, so a consumer sees
        the first results after one sub-batch latency instead of the
        whole batch's (BASELINE config-4 tail; the serving analogue of
        the reference's chunked CheckIter, client/client.go:164-180)."""
        PB = sub_batch or self._pipeline_batch() or q_res.shape[0]
        B = q_res.shape[0]
        outs = []
        for lo in range(0, B, PB):
            hi = min(lo + PB, B)
            outs.append((lo, hi, self.check_columns(
                dsnap, q_res[lo:hi], q_perm[lo:hi], q_subj[lo:hi],
                q_ctx=None if q_ctx is None else q_ctx[lo:hi],
                qctx_rows=qctx_rows, now_us=now_us,
                fetch=False, bucket_min=PB,
            )))
        for lo, hi, out in outs:
            d, p, ovf = jax.device_get(out)
            n = hi - lo
            yield lo, hi, d[:n], p[:n], ovf[:n]

    def check_columns(
        self,
        dsnap: DeviceSnapshot,
        q_res: np.ndarray,
        q_perm: np.ndarray,
        q_subj: np.ndarray,
        *,
        q_srel: Optional[np.ndarray] = None,
        q_wc: Optional[np.ndarray] = None,
        q_ctx: Optional[np.ndarray] = None,
        qctx_rows: Optional[Sequence[Mapping[str, Any]]] = None,
        now_us: Optional[int] = None,
        fetch: bool = True,
        bucket_min: int = 0,
    ):
        """Bulk check straight from pre-interned int32 columns — the fast
        path for 100k+-item batches, where per-item Relationship objects
        would dominate (the analogue of the reference's chunked iterator
        APIs, client/client.go:164-180).  ``bucket_min`` raises the pow2
        padding floor — callers with highly variable batch sizes (device
        lookups) use a coarse floor so warm calls share one compiled
        program instead of retracing per fresh bucket.

        With ``fetch`` (default) returns (definite, possible, overflow)
        numpy arrays trimmed to the batch length, fetched in ONE
        device→host transfer.  With ``fetch=False`` returns the raw padded
        device outputs (length = pow2 bucket ≥ B) for pipelined dispatch
        loops; fetch them with ``jax.device_get`` on the full arrays —
        materializing *sliced* views of jit outputs degrades every
        subsequent dispatch on remote-attached platforms.
        """
        faults.fire("device.dispatch")
        if _pallas.resolve(self.config):
            faults.fire("pallas.dispatch")
        snap = dsnap.snapshot
        B = q_res.shape[0]
        BP = _ceil_pow2(B, max(bucket_min, self.config.batch_bucket_min))
        queries, qctx = self._columns_preamble(
            dsnap, q_res, q_perm, q_subj, q_srel, q_wc, q_ctx, qctx_rows
        )
        now_flat = jnp.int32(snap.now_rel32(now_us))
        out = self._flat_call(
            dsnap, queries, qctx, now_flat, B, bucket_min=bucket_min
        )
        if out is not None:
            if not fetch:
                return out
            d, p, ovf = jax.device_get(out)
            return d[:B], p[:B], ovf[:B]
        q_res, q_perm, q_subj = queries["q_res"], queries["q_perm"], queries["q_subj"]
        q_srel, q_wc, q_ctx = queries["q_srel"], queries["q_wc"], queries["q_ctx"]
        q_self = queries["q_self"]

        subj_key = np.stack([q_subj, q_srel, q_wc, q_ctx], axis=1)
        uniq, q_row = np.unique(subj_key, axis=0, return_inverse=True)
        U = uniq.shape[0]
        UP = _ceil_pow2(U, self.config.batch_bucket_min)
        u = np.full((UP, 4), -1, np.int32)
        u[:U] = uniq

        def padq(a, fill):
            out = np.full(BP, fill, np.asarray(a).dtype)
            out[:B] = a
            return jnp.asarray(out)

        now = jnp.int32(snap.now_rel32(now_us))
        d, p, ovf = self._fn(
            self._legacy_arrays(dsnap), dsnap.tid_map, now,
            jnp.asarray(u[:, 0]), jnp.asarray(u[:, 1]), jnp.asarray(u[:, 2]),
            jnp.asarray(u[:, 3]),
            padq(q_res, -1), padq(q_perm, -1), padq(q_subj, -1),
            padq(q_srel, -1), padq(q_wc, -1),
            padq(q_row.astype(np.int32), 0),
            padq(q_self, False), padq(q_ctx, -1),
            self._qctx_device(qctx),
        )
        if not fetch:
            return d, p, ovf
        d, p, ovf = jax.device_get((d, p, ovf))
        return d[:B], p[:B], ovf[:B]

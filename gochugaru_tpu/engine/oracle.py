"""The host oracle: exact SpiceDB check semantics in plain Python.

Permissionship is three-valued, exactly as SpiceDB's
HAS_PERMISSION / NO_PERMISSION / CONDITIONAL (SURVEY.md §7 "hard parts"):
``T`` definite grant, ``F`` definite no, ``U`` conditional on caveat
context that wasn't provided.  Kleene logic combines them (OR = max,
AND = min, NOT = flip), and the engine collapses U → False only at the
client API boundary, mirroring where the reference collapses
Permissionship to bool (client/client.go:277).

Semantics implemented (spec: SURVEY.md §2.6):
- direct, wildcard (``user:*``), and userset (``group#member``) subjects,
  with self-identity (``X#r`` is always a member of itself);
- permissions as rewrite trees: union/intersection/exclusion, ``nil``,
  arrows (tupleset traversal over direct subjects);
- caveats: stored context merged over query context (stored wins),
  missing parameters → conditional;
- expiration: expired edges grant nothing (rel/relationship.go:43-45);
- recursion (nested groups, recursive folders) via in-progress cycle
  detection → least fixpoint;
- checks on nonexistent resources/relations return F, never an error
  (client/client_test.go:209-215).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Iterator, List, Mapping, Optional, Set, Tuple

from ..caveats import UNKNOWN, CelProgram
from ..rel.relationship import Relationship, WILDCARD_ID, expiration_micros
from ..schema.ast import (
    Arrow,
    Exclusion,
    Expr,
    Intersection,
    Nil,
    RelationRef,
    Union,
)
from ..schema.compiler import CompiledSchema

# Tri-state permissionship encoding.
F, U, T = 0, 1, 2


class PermTri:
    FALSE = F
    CONDITIONAL = U
    TRUE = T


@dataclass(frozen=True)
class _Edge:
    subject_type: str
    subject_id: str
    subject_relation: str
    caveat_name: str
    caveat_context: Mapping[str, Any]
    expires_us: int  # 0 = none


def _to_micros(r: Relationship) -> int:
    return expiration_micros(r.expiration) if r.has_expiration() else 0


class Oracle:
    """Reference evaluator over a fixed set of relationships."""

    def __init__(
        self,
        compiled: CompiledSchema,
        relationships: Iterable[Relationship],
        caveat_programs: Optional[Mapping[str, CelProgram]] = None,
        *,
        now_us: Optional[int] = None,
    ) -> None:
        self.compiled = compiled
        self.schema = compiled.schema
        self.caveat_programs = dict(caveat_programs or {})
        #: pinned evaluation time; None = wall clock at each call (an Oracle
        #: is cached per revision, so liveness must not freeze at build time)
        self.now_us = now_us
        # (rtype, rid, relation) → edges
        self._by_onr: Dict[Tuple[str, str, str], List[_Edge]] = {}
        # candidate object ids per type (resources with any tuple)
        self._objects_of_type: Dict[str, Set[str]] = {}
        self._subjects_of_type: Dict[str, Set[str]] = {}
        for r in relationships:
            self._by_onr.setdefault(
                (r.resource_type, r.resource_id, r.resource_relation), []
            ).append(
                _Edge(
                    r.subject_type,
                    r.subject_id,
                    r.subject_relation,
                    r.caveat_name,
                    r.caveat_context,
                    _to_micros(r),
                )
            )
            self._objects_of_type.setdefault(r.resource_type, set()).add(r.resource_id)
            self._subjects_of_type.setdefault(r.subject_type, set()).add(r.subject_id)

    # ------------------------------------------------------------------
    # data access — overridable so SnapshotOracle can lazily binary-search
    # sorted snapshot columns instead of prebuilding O(E) dicts
    def _edges_of(self, rtype: str, rid: str, relation: str) -> Iterable[_Edge]:
        return self._by_onr.get((rtype, rid, relation), ())

    def _object_ids(self, type_name: str) -> Iterable[str]:
        return sorted(self._objects_of_type.get(type_name, ()))

    def _subject_ids(self, type_name: str) -> Iterable[str]:
        return sorted(self._subjects_of_type.get(type_name, ()))

    # ------------------------------------------------------------------
    def _now_us(self) -> int:
        return self.now_us if self.now_us is not None else int(time.time() * 1_000_000)

    def _edge_gate(self, e: _Edge, query_ctx: Mapping[str, Any], now_us: int) -> int:
        """Tri-state admissibility of one edge: expiry mask and caveat."""
        if e.expires_us and e.expires_us <= now_us:
            return F
        if not e.caveat_name:
            return T
        prog = self.caveat_programs.get(e.caveat_name)
        if prog is None:
            # declared but uncompiled caveat — treat as conditional
            return U
        merged = dict(query_ctx)
        merged.update(e.caveat_context)  # stored context takes precedence
        result = prog.evaluate(merged)
        if result is UNKNOWN:
            return U
        return T if result else F

    def _edge_gate_explain(
        self, e: _Edge, query_ctx: Mapping[str, Any], now_us: int
    ):
        """``_edge_gate`` with the WHY: (gate, detail dict or None) — the
        expiry stamp that killed the edge, the caveat name, the merged
        context values that gated it, and the tri-state outcome.  Runs
        only under an explain recorder (engine/explain.py); the hot
        fallback path stays on ``_edge_gate``.  The two MUST agree —
        every return mirrors a ``_edge_gate`` return line-for-line."""
        detail: Dict[str, Any] = {}
        if e.expires_us:
            detail["expires_us"] = e.expires_us
            if e.expires_us <= now_us:
                detail["expired"] = True
                return F, detail
        if not e.caveat_name:
            return T, (detail or None)
        detail["caveat"] = e.caveat_name
        prog = self.caveat_programs.get(e.caveat_name)
        if prog is None:
            detail["caveat_result"] = "uncompiled"
            return U, detail
        merged = dict(query_ctx)
        merged.update(e.caveat_context)
        detail["context"] = dict(merged)
        result = prog.evaluate(merged)
        if result is UNKNOWN:
            detail["caveat_result"] = "missing_context"
            return U, detail
        detail["caveat_result"] = bool(result)
        return (T if result else F), detail

    # ------------------------------------------------------------------
    def check(
        self,
        resource_type: str,
        resource_id: str,
        permission: str,
        subject_type: str,
        subject_id: str,
        subject_relation: str = "",
        context: Optional[Mapping[str, Any]] = None,
        now_us: Optional[int] = None,
        *,
        recorder=None,
        seed_branch: Optional[str] = None,
    ) -> int:
        """Tri-state check of one (resource, permission, subject).
        ``now_us`` pins the evaluation time for this call (cursor-pinned
        lookup re-checks); None keeps the oracle's own clock.

        ``recorder`` (engine/explain.py Recorder, duck-typed: push/pop/
        leaf) instruments THIS walker into a typed resolution tree —
        membership/userset/arrow steps, caveat evaluations with the
        merged context that gated them, expiry gates, wildcard grants,
        cycle cuts, and (for denials) every explored-and-exhausted edge.
        With ``recorder=None`` every hook is one ``is not None`` branch:
        the hot fallback path is unchanged.

        ``seed_branch`` ("direct" | "wildcard" | "userset") reorders the
        ROOT relation's edge iteration to try the named class first —
        the device witness seeds the walk toward the branch the kernel
        already proved won.  Sound by construction: relation evaluation
        is a short-circuited max over edges, and max is commutative, so
        reordering can only change WHICH winning path the tree shows,
        never the verdict."""
        memo: Dict[Tuple[str, str, str], int] = {}
        in_progress: Set[Tuple[str, str, str]] = set()
        # Keys that were returned as F because they were in progress (cycle
        # cuts).  A value computed while its subtree hit a cut on a node
        # still being evaluated is provisional and must NOT be memoized —
        # caching it would freeze the cycle's least-fixpoint seed as the
        # final answer for siblings outside the cycle.
        cut_hits: Set[Tuple[str, str, str]] = set()
        ctx = context or {}
        if now_us is None:
            now_us = self._now_us()
        subject = (subject_type, subject_id, subject_relation)
        rec = recorder
        root_key = (resource_type, resource_id, permission)

        def gate_of(e: _Edge):
            """(gate, detail) — detail only under a recorder."""
            if rec is None:
                return self._edge_gate(e, ctx, now_us), None
            return self._edge_gate_explain(e, ctx, now_us)

        def subj_str(t: str, i: str, r: str) -> str:
            return f"{t}:{i}#{r}" if r else f"{t}:{i}"

        def eval_item(rtype: str, rid: str, item: str) -> int:
            if (rtype, rid, item) == subject:
                if rec is not None:
                    rec.leaf("self", T, resource=f"{rtype}:{rid}", item=item)
                return T  # a userset is always a member of itself
            d = self.schema.definitions.get(rtype)
            if d is None:
                if rec is not None:
                    rec.leaf("missing_type", F, resource=f"{rtype}:{rid}",
                             item=item)
                return F
            key = (rtype, rid, item)
            if key in memo:
                if rec is not None:
                    rec.leaf("memoized", memo[key],
                             resource=f"{rtype}:{rid}", item=item)
                return memo[key]
            if key in in_progress:
                cut_hits.add(key)
                if rec is not None:
                    rec.leaf("cycle_cut", F, resource=f"{rtype}:{rid}",
                             item=item)
                return F  # least fixpoint on recursion
            in_progress.add(key)
            if rec is not None:
                rec.push(
                    "relation" if item in d.relations else (
                        "permission" if item in d.permissions else "missing"
                    ),
                    resource=f"{rtype}:{rid}", item=item,
                )
            out = F
            try:
                if item in d.relations:
                    out = eval_relation(rtype, rid, item)
                elif item in d.permissions:
                    out = eval_expr(rtype, rid, d.permissions[item].expr)
                else:
                    out = F
            finally:
                in_progress.discard(key)
                if rec is not None:
                    rec.pop(out)
            cut_hits.discard(key)  # cuts to this node are resolved by `out`
            if not (cut_hits & in_progress):
                memo[key] = out
            return out

        def eval_relation(rtype: str, rid: str, relation: str) -> int:
            out = F
            edges = self._edges_of(rtype, rid, relation)
            if seed_branch is not None and (rtype, rid) == root_key[:2]:
                # witness-seeded walk: stable-sort the ROOT RESOURCE's
                # relation edges (the checked relation itself, or the
                # leaf relations its permission program references) so
                # the class the device kernel proved winning is explored
                # first (short-circuit lands on it)
                def _cls(e: _Edge) -> int:
                    if e.subject_relation:
                        mine = seed_branch == "userset"
                    elif e.subject_id == WILDCARD_ID:
                        mine = seed_branch == "wildcard"
                    else:
                        mine = seed_branch == "direct"
                    return 0 if mine else 1

                edges = sorted(edges, key=_cls)
            skipped = 0
            for e in edges:
                if rec is None and e.subject_relation == "" \
                        and e.subject_id != WILDCARD_ID \
                        and (e.subject_type, e.subject_id, "") != subject:
                    continue  # cheap pre-skip of non-matching direct edges
                gate, gd = gate_of(e)
                if e.subject_relation == "":
                    if e.subject_id == WILDCARD_ID:
                        # wildcard grants any direct subject of the type
                        if gate != F and subject_relation == "" \
                                and e.subject_type == subject_type \
                                and subject_id != WILDCARD_ID:
                            if rec is not None:
                                rec.leaf(
                                    "wildcard", gate,
                                    subject=f"{e.subject_type}:*",
                                    gate=gd,
                                )
                            out = max(out, gate)
                        elif gate != F and (
                            e.subject_type, e.subject_id, ""
                        ) == subject:
                            if rec is not None:
                                rec.leaf(
                                    "direct", gate,
                                    subject=f"{e.subject_type}:*",
                                    gate=gd,
                                )
                            out = max(out, gate)  # checking the wildcard itself
                        elif rec is not None and gate == F:
                            rec.leaf("wildcard", F,
                                     subject=f"{e.subject_type}:*", gate=gd)
                    elif (e.subject_type, e.subject_id, "") == subject:
                        if rec is not None:
                            rec.leaf(
                                "direct", gate,
                                subject=subj_str(e.subject_type,
                                                 e.subject_id, ""),
                                gate=gd,
                            )
                        out = max(out, gate)
                    else:
                        skipped += 1  # direct edge for another subject
                else:
                    if gate == F:
                        if rec is not None:
                            rec.leaf(
                                "userset", F,
                                subject=subj_str(
                                    e.subject_type, e.subject_id,
                                    e.subject_relation,
                                ),
                                gate=gd,
                            )
                        continue
                    if rec is not None:
                        rec.push(
                            "userset",
                            subject=subj_str(e.subject_type, e.subject_id,
                                             e.subject_relation),
                            gate=gd,
                        )
                    sub = eval_item(e.subject_type, e.subject_id,
                                    e.subject_relation)
                    if rec is not None:
                        rec.pop(min(gate, sub))
                    out = max(out, min(gate, sub))
                if out == T:
                    if rec is not None and skipped:
                        rec.set("edges_skipped", skipped)
                    return T
            if rec is not None and skipped:
                rec.set("edges_skipped", skipped)
            return out

        def eval_expr(rtype: str, rid: str, expr: Expr) -> int:
            if isinstance(expr, RelationRef):
                return eval_item(rtype, rid, expr.name)
            if isinstance(expr, Nil):
                if rec is not None:
                    rec.leaf("nil", F)
                return F
            if isinstance(expr, Arrow):
                if rec is not None:
                    rec.push("arrow", left=expr.left, right=expr.right,
                             resource=f"{rtype}:{rid}")
                out = F
                try:
                    for e in self._edges_of(rtype, rid, expr.left):
                        if e.subject_relation != "" or e.subject_id == WILDCARD_ID:
                            continue  # arrows traverse direct (ellipsis) subjects
                        gate, gd = gate_of(e)
                        if gate == F:
                            if rec is not None:
                                rec.leaf(
                                    "arrow_edge", F,
                                    via=subj_str(e.subject_type,
                                                 e.subject_id, ""),
                                    gate=gd,
                                )
                            continue
                        sub_def = self.schema.definitions.get(e.subject_type)
                        if sub_def is None or sub_def.item(expr.right) is None:
                            continue
                        if rec is not None:
                            rec.push(
                                "arrow_edge",
                                via=subj_str(e.subject_type, e.subject_id, ""),
                                gate=gd,
                            )
                        sub = eval_item(e.subject_type, e.subject_id, expr.right)
                        if rec is not None:
                            rec.pop(min(gate, sub))
                        out = max(out, min(gate, sub))
                        if out == T:
                            return T
                    return out
                finally:
                    if rec is not None:
                        rec.pop(out)
            if isinstance(expr, Union):
                if rec is not None:
                    rec.push("union")
                out = F
                try:
                    for c in expr.children:
                        out = max(out, eval_expr(rtype, rid, c))
                        if out == T:
                            return T
                    return out
                finally:
                    if rec is not None:
                        rec.pop(out)
            if isinstance(expr, Intersection):
                if rec is not None:
                    rec.push("intersection")
                out = T
                try:
                    for c in expr.children:
                        out = min(out, eval_expr(rtype, rid, c))
                        if out == F:
                            return F
                    return out
                finally:
                    if rec is not None:
                        rec.pop(out)
            if isinstance(expr, Exclusion):
                if rec is not None:
                    rec.push("exclusion")
                out = F
                try:
                    base = eval_expr(rtype, rid, expr.base)
                    if base == F:
                        return F
                    sub = eval_expr(rtype, rid, expr.subtracted)
                    out = min(base, 2 - sub)
                    return out
                finally:
                    if rec is not None:
                        rec.pop(out)
            raise TypeError(f"unknown expression node {expr!r}")

        return eval_item(resource_type, resource_id, permission)

    def check_relationship(
        self, r: Relationship, context: Optional[Mapping[str, Any]] = None,
        *, now_us: Optional[int] = None, recorder=None,
        seed_branch: Optional[str] = None,
    ) -> int:
        """Check where the query is phrased as a relationship, as the whole
        Check family does (client/client.go:238-259): resource_relation is
        the permission, caveat_context is the request context.
        ``recorder``/``seed_branch`` thread through to the instrumented
        walk (engine/explain.py)."""
        ctx = dict(context or {})
        if r.caveat_context:
            ctx.update(r.caveat_context)
        return self.check(
            r.resource_type,
            r.resource_id,
            r.resource_relation,
            r.subject_type,
            r.subject_id,
            r.subject_relation,
            ctx,
            now_us=now_us,
            recorder=recorder,
            seed_branch=seed_branch,
        )

    # ------------------------------------------------------------------
    def lookup_resources(
        self,
        resource_type: str,
        permission: str,
        subject_type: str,
        subject_id: str,
        subject_relation: str = "",
        context: Optional[Mapping[str, Any]] = None,
    ) -> Iterator[str]:
        """Stream ids of resources of ``resource_type`` on which the subject
        has the permission definitively (client/client.go:501-552).
        Conditional results are omitted, matching the bool collapse at the
        client layer."""
        for rid in self._object_ids(resource_type):
            if (
                self.check(
                    resource_type, rid, permission,
                    subject_type, subject_id, subject_relation, context,
                )
                == T
            ):
                yield rid

    def lookup_subjects(
        self,
        resource_type: str,
        resource_id: str,
        permission: str,
        subject_type: str,
        subject_relation: str = "",
        context: Optional[Mapping[str, Any]] = None,
    ) -> Iterator[str]:
        """Stream ids of subjects of ``subject_type`` holding the permission
        on the resource (client/client.go:554-599)."""
        for sid in self._subject_ids(subject_type):
            if (
                self.check(
                    resource_type, resource_id, permission,
                    subject_type, sid, subject_relation, context,
                )
                == T
            ):
                yield sid


class SnapshotOracle(Oracle):
    """An Oracle backed directly by a Snapshot's sorted int32 columns.

    Construction is O(1) — no edge iteration, no prebuilt dicts (round-1
    Weak #3: building the fallback oracle was O(E) Python per revision,
    which stalls the first conditional check for minutes at 100M edges).
    ``_edges_of`` binary-searches the primary (rel, res, subj, srel1)
    view per (resource, relation) and memoizes the decoded group, so a
    fallback check costs O(log E + touched edges), matching SURVEY §7's
    "host-fallback split keeps p99 < 2 ms".
    """

    def __init__(
        self,
        snapshot,
        caveat_programs: Optional[Mapping[str, CelProgram]] = None,
        *,
        now_us: Optional[int] = None,
    ) -> None:
        self.compiled = snapshot.compiled
        self.schema = snapshot.compiled.schema
        self.caveat_programs = dict(caveat_programs or {})
        self.now_us = now_us
        self.snapshot = snapshot
        self._edge_memo: Dict[Tuple[str, str, str], Tuple[_Edge, ...]] = {}
        # base-class dicts stay empty; all access is overridden
        self._by_onr = {}
        self._objects_of_type = {}
        self._subjects_of_type = {}
        import numpy as np

        self._np = np
        # packed (rel, res) over the primary sort order — monotone because
        # the primary order is lex (rel, res, subj, srel1)
        self._relres = (
            snapshot.e_rel.astype(np.int64) * (2**32)
            + snapshot.e_res.astype(np.int64)
        )
        self._slot_names = snapshot._slot_names()
        self._caveat_names = snapshot._caveat_names()

    def _edges_of(self, rtype: str, rid: str, relation: str) -> Tuple[_Edge, ...]:
        key = (rtype, rid, relation)
        got = self._edge_memo.get(key)
        if got is not None:
            return got
        snap = self.snapshot
        node = snap.interner.lookup(rtype, rid)
        slot = self.compiled.slot_of_name.get(relation, -1)
        if node < 0 or slot < 0:
            self._edge_memo[key] = ()
            return ()
        np = self._np
        packed = np.int64(slot) * (2**32) + node
        lo = int(np.searchsorted(self._relres, packed, "left"))
        hi = int(np.searchsorted(self._relres, packed, "right"))
        out = []
        for i in range(lo, hi):
            stype, sid = snap.interner.key_of(int(snap.e_subj[i]))
            srel1 = int(snap.e_srel1[i])
            cav_id = int(snap.e_caveat[i])
            ctx_i = int(snap.e_ctx[i])
            out.append(
                _Edge(
                    subject_type=stype,
                    subject_id=sid,
                    subject_relation=(
                        self._slot_names[srel1 - 1] if srel1 > 0 else ""
                    ),
                    caveat_name=self._caveat_names[cav_id] if cav_id else "",
                    caveat_context=(
                        snap.contexts[ctx_i] if ctx_i >= 0 else {}
                    ),
                    expires_us=int(snap.e_exp_us[i]),
                )
            )
        got = tuple(out)
        self._edge_memo[key] = got
        return got

    def _object_ids(self, type_name: str):
        snap = self.snapshot
        np = self._np
        tid = snap.interner.type_lookup(type_name)
        if tid < 0:
            return []
        nodes = np.unique(snap.e_res)
        nodes = nodes[snap.node_type[nodes] == tid]
        return sorted(snap.interner.key_of(int(n))[1] for n in nodes)

    def _subject_ids(self, type_name: str):
        snap = self.snapshot
        np = self._np
        tid = snap.interner.type_lookup(type_name)
        if tid < 0:
            return []
        nodes = np.unique(snap.e_subj)
        nodes = nodes[snap.node_type[nodes] == tid]
        return sorted(snap.interner.key_of(int(n))[1] for n in nodes)

"""Online controller: the three cheap knobs, adjusted live and safely.

Hold-back deadline, verdict-cache byte budget, and the dedup window are
the knobs whose apply is a single attribute swap — no recompile, no
re-prepare — so they are safe to move while serving.  Everything else
(tier ladder, pack spec, placement) stays offline (tune/tuner.py).

Safety posture, in order of importance:

- **bounded step**: every move is ×2 or ÷2 (hold snaps to the offline
  ladder), clamped to an explicit range — a runaway signal cannot fling
  a knob across its domain in one tick.
- **hysteresis**: distinct raise/lower watermarks per signal, so a
  workload sitting ON a threshold doesn't flap the knob every tick.
- **cooldown**: after a move the knob sits out ``cooldown_steps`` ticks
  — the system must re-measure under the new value before the
  controller may judge it.
- **oscillation tripwire**: a knob whose recent moves keep reversing
  direction is frozen and a flight-recorder incident
  (``tune.oscillation``) captures the trajectory — a controller
  fighting the workload is a bug report, not a steady state.
- **one-call revert**: ``revert()`` restores the preset captured at
  construction, unfreezes everything, and counts itself.

Observability: every applied move bumps ``tune.moves`` (and the
per-knob counter), republishes the ``tune.hold_max_s`` /
``tune.vcache_bytes`` / ``tune.dedup`` gauges, and emits a
``tune.applied`` trace event — the telemetry shows the whole
trajectory, which the convergence test replays."""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import replace
from typing import Any, Dict, Optional

from ..utils import metrics as _metrics
from ..utils import trace as _trace
from .tuner import (
    CACHE_MAX_BYTES,
    CACHE_MIN_BYTES,
    DEDUP_OFF_FRAC,
    HOLD_LADDER,
    _ladder_step,
)

#: window signals need this many formed batches / cache lookups before
#: a tick will judge a knob (thin windows are noise)
MIN_WINDOW_FLUSHES = 4
MIN_WINDOW_LOOKUPS = 64
MIN_WINDOW_CHECKS = 64


class OnlineController:
    """Slow feedback loop over live telemetry deltas.

    Construct with the serving pieces to steer (``batcher`` required;
    ``vcache`` optional), then either call ``step()`` on your own
    schedule (tests drive this directly) or ``start()`` the daemon
    thread.  Signals are COUNTER DELTAS between ticks read from the
    metrics registry — the controller needs no hooks into the serving
    path itself."""

    KNOBS = ("hold_max_s", "cache_max_bytes", "dedup")

    def __init__(
        self,
        batcher,
        *,
        vcache=None,
        registry: Optional[_metrics.Metrics] = None,
        interval_s: float = 2.0,
        cooldown_steps: int = 3,
        hold_bounds=(HOLD_LADDER[0], HOLD_LADDER[-1]),
        cache_bounds=(CACHE_MIN_BYTES, CACHE_MAX_BYTES),
        osc_window: int = 8,
        osc_flips: int = 3,
    ) -> None:
        self._b = batcher
        self._vc = vcache
        self._m = registry or _metrics.default
        self.interval_s = float(interval_s)
        self.cooldown_steps = int(cooldown_steps)
        self.hold_bounds = (float(hold_bounds[0]), float(hold_bounds[1]))
        self.cache_bounds = (int(cache_bounds[0]), int(cache_bounds[1]))
        self.osc_flips = int(osc_flips)
        #: the one-call revert target: the config the serving stack was
        #: BUILT with, captured before this controller ever moves
        self._preset = (
            batcher.config,
            int(vcache.max_bytes) if vcache is not None else None,
        )
        self._cool: Dict[str, int] = {k: 0 for k in self.KNOBS}
        #: recent move directions per knob (+1/-1); flips trip the wire
        self._dirs: Dict[str, deque] = {
            k: deque(maxlen=int(osc_window)) for k in self.KNOBS
        }
        self._frozen: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.moves = 0
        self._last = self._read()
        self._publish()

    # -- signal plumbing -------------------------------------------------
    def _read(self) -> Dict[str, float]:
        m = self._m
        names = (
            "serve.flush_full", "serve.flush_maxhold",
            "serve.flush_deadline", "serve.checks", "serve.unique_checks",
            "serve.sheds", "cache.hits", "cache.misses",
            "cache.evicted_revisions",
        )
        out = {n: m.counter(n) for n in names}
        # per-tier occupancy totals for the window's fill fraction
        for name, (_b, _c, count, total, _e) in m.hist_snapshot().items():
            if name.startswith("serve.occupancy.t"):
                tier = int(name[len("serve.occupancy.t"):])
                out[f"occ.{tier}.count"] = count
                out[f"occ.{tier}.sum"] = total
        return out

    def _window(self) -> Dict[str, float]:
        cur = self._read()
        d = {k: cur.get(k, 0.0) - self._last.get(k, 0.0) for k in cur}
        self._last = cur
        # count-weighted typical-batch fill, matching the offline rule
        # (tuner._occ_fill_frac): each formed batch votes once
        fill = 0.0
        n_total = 0.0
        for k, v in d.items():
            if k.startswith("occ.") and k.endswith(".count") and v > 0:
                tier = int(k.split(".")[1])
                fill += d.get(f"occ.{tier}.sum", 0.0) / tier
                n_total += v
        d["fill_frac"] = (fill / n_total) if n_total else -1.0
        return d

    # -- the tick --------------------------------------------------------
    def step(self) -> int:
        """One control tick: read the window, maybe move knobs.
        Returns the number of moves applied this tick."""
        w = self._window()
        applied = 0
        applied += self._step_hold(w)
        applied += self._step_cache(w)
        applied += self._step_dedup(w)
        for k in self._cool:
            if self._cool[k] > 0:
                self._cool[k] -= 1
        return applied

    def _step_hold(self, w: Dict[str, float]) -> int:
        k = "hold_max_s"
        if k in self._frozen or self._cool[k] > 0:
            return 0
        flushes = w["serve.flush_full"] + w["serve.flush_maxhold"] + w[
            "serve.flush_deadline"
        ]
        if flushes < MIN_WINDOW_FLUSHES:
            return 0
        mh = w["serve.flush_maxhold"] / flushes
        dl = w["serve.flush_deadline"] / flushes
        fill = w["fill_frac"]
        cur = float(self._b.config.hold_max_s)
        want = cur
        if dl >= 0.3 or (mh >= 0.6 and 0.0 <= fill <= 0.25):
            want = max(
                self.hold_bounds[0], _ladder_step(HOLD_LADDER, cur, up=False)
            )
        elif mh >= 0.6 and fill >= 0.6:
            want = min(
                self.hold_bounds[1], _ladder_step(HOLD_LADDER, cur, up=True)
            )
        if want == cur:
            return 0
        self._b.apply_config(replace(self._b.config, hold_max_s=want))
        self._applied(
            k, cur, want, +1 if want > cur else -1,
            maxhold_frac=round(mh, 3), deadline_frac=round(dl, 3),
            fill_frac=round(fill, 3),
        )
        return 1

    def _step_cache(self, w: Dict[str, float]) -> int:
        k = "cache_max_bytes"
        vc = self._vc
        if vc is None or k in self._frozen or self._cool[k] > 0:
            return 0
        lookups = w["cache.hits"] + w["cache.misses"]
        if lookups < MIN_WINDOW_LOOKUPS:
            return 0
        hr = w["cache.hits"] / lookups
        cur = int(vc.max_bytes)
        used = self._m.gauge("cache.bytes")
        want = cur
        if (
            hr >= 0.2 and used >= 0.85 * cur
            and w["cache.evicted_revisions"] > 0
        ):
            want = min(cur * 2, self.cache_bounds[1])
        elif hr < 0.02 and used <= 0.25 * cur:
            want = max(cur // 2, self.cache_bounds[0])
        if want == cur:
            return 0
        vc.set_max_bytes(want)
        self._applied(
            k, cur, want, +1 if want > cur else -1,
            hit_rate=round(hr, 3), used_bytes=int(used),
        )
        return 1

    def _step_dedup(self, w: Dict[str, float]) -> int:
        """On→off only: the duplicate fraction is measured by the dedup
        key pass itself, so once off there is no live signal to justify
        re-enabling — that is the offline tuner's (or revert's) call."""
        k = "dedup"
        if k in self._frozen or self._cool[k] > 0:
            return 0
        if not self._b.config.dedup:
            return 0
        checks = w["serve.checks"]
        unique = w["serve.unique_checks"]
        if checks < MIN_WINDOW_CHECKS or unique <= 0:
            return 0
        dup = max(0.0, 1.0 - unique / checks)
        if dup >= DEDUP_OFF_FRAC:
            return 0
        self._b.apply_config(replace(self._b.config, dedup=False))
        self._applied(k, True, False, -1, dup_frac=round(dup, 4))
        return 1

    # -- bookkeeping -----------------------------------------------------
    def _applied(self, knob: str, frm, to, direction: int, **why) -> None:
        self.moves += 1
        # +1 because step()'s end-of-tick decrement also fires on the
        # tick that made this move — the knob must sit out exactly
        # cooldown_steps SUBSEQUENT ticks
        self._cool[knob] = self.cooldown_steps + 1
        m = self._m
        m.inc("tune.moves")
        m.inc(f"tune.moves.{knob}")
        sp = _trace.root_span(
            "tune.applied", knob=knob, frm=frm, to=to, **why
        )
        sp.end()
        dirs = self._dirs[knob]
        dirs.append(direction)
        flips = sum(
            1 for a, b in zip(list(dirs), list(dirs)[1:]) if a != b
        )
        if flips >= self.osc_flips:
            # the knob is fighting the workload: freeze it where it
            # stands and capture the trajectory for diagnosis
            self._frozen.add(knob)
            m.inc("tune.oscillations")
            _trace.trigger_incident(
                "tune.oscillation", knob=knob, moves=list(dirs),
                flips=flips,
            )
        self._publish()

    def _publish(self) -> None:
        m = self._m
        m.set_gauge("tune.hold_max_s", float(self._b.config.hold_max_s))
        m.set_gauge("tune.dedup", 1.0 if self._b.config.dedup else 0.0)
        if self._vc is not None:
            m.set_gauge("tune.vcache_bytes", float(self._vc.max_bytes))
        m.set_gauge("tune.frozen_knobs", float(len(self._frozen)))

    def status(self) -> Dict[str, Any]:
        """Current posture — /perf report section + test assertions."""
        return {
            "moves": self.moves,
            "cooldown": dict(self._cool),
            "frozen": sorted(self._frozen),
            "hold_max_s": float(self._b.config.hold_max_s),
            "dedup": bool(self._b.config.dedup),
            "vcache_bytes": (
                int(self._vc.max_bytes) if self._vc is not None else None
            ),
            "preset_hold_max_s": float(self._preset[0].hold_max_s),
        }

    # -- revert ----------------------------------------------------------
    def revert(self) -> None:
        """One call back to the static preset: serve config and cache
        budget restored, frozen knobs thawed, move history cleared."""
        cfg, cache_bytes = self._preset
        self._b.apply_config(cfg)
        if self._vc is not None and cache_bytes is not None:
            self._vc.set_max_bytes(cache_bytes)
        self._frozen.clear()
        for d in self._dirs.values():
            d.clear()
        for k in self._cool:
            self._cool[k] = 0
        self._m.inc("tune.reverts")
        self._publish()
        sp = _trace.root_span("tune.applied", knob="revert")
        sp.end()

    # -- daemon ----------------------------------------------------------
    def start(self) -> "OnlineController":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="gochugaru-tune", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:
                # a controller crash must never take serving down with
                # it: count, stop moving, leave the knobs where they are
                self._m.inc("tune.controller_errors")
                return

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

"""The offline tune pass: snapshot in, reviewable config diff out.

Every rule here is DETERMINISTIC and EXPLAINABLE: a proposal is a pure
function of the snapshot (plus the constants below), and each emitted
``KnobDiff`` carries the measured evidence it was derived from plus the
predicted deltas the bench A/B (benchmarks/bench11_tune.py) verifies
mechanically.  Purity buys the fixed-point property the round-trip test
asserts: ``propose(snap, apply_diff(t, propose(snap, t)))`` is empty,
because a desired value depends only on the snapshot, never on the
target it is being compared against.

Quantization keeps proposals reviewable and stable: tiers round up to
multiples of ``TIER_QUANTUM`` (non-pow2 is fine — the AOT pin ladder
keys on the plain int tier, engine/latency.py), hold-back snaps to
``HOLD_LADDER``, cache budgets move in powers of two.

Rules and their inputs:

- ``latency_tiers``  ← per-tier occupancy histograms: a tier whose p90
  live-lane count sits at or below half the tier is paying pure pad
  waste; propose the p90 rounded up to the quantum.  The TOP tier never
  shrinks (it is the ladder's coverage guarantee).
- ``hold_max_s``     ← flush-reason mix + occupancy: maxhold-dominated
  flushes at low occupancy mean the hold only adds latency; at high
  occupancy more hold converts maxhold flushes into full ones.
- ``cache_max_bytes``← hit rate + byte pressure + shard evictions.
- ``dedup``          ← measured duplicate fraction, with an on/off
  hysteresis band so borderline workloads don't flap.
- ``flat_packed``    ← offline A/B byte models only (a live snapshot
  sees one layout; the counterfactual comes from scripts/tune.py's
  dual prepare, or the rule stays silent).
- ``pallas``         ← the fused-probe one-pass byte model prepare
  publishes (utils/perf.py ``publish_pallas_model``) against the XLA
  chain's modeled traffic, vetoed by the feature probe and the
  runtime degrade counter — the rule never proposes a backend the
  engine cannot serve.
- ``placement``      ← device-table placement split (engine/flat.py
  ``placement_split``) against the HBM budget.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Mapping, Optional, Tuple

from ..engine import pallas as _pallas
from ..engine.plan import EngineConfig
from ..serve.batcher import ServeConfig

#: minimum histogram mass before the ladder rule trusts a tier's shape
MIN_HIST_SAMPLES = 16
#: minimum flushes before the hold rule reads the reason mix
MIN_FLUSHES = 8
#: minimum cache lookups / served checks before those rules speak
MIN_CACHE_LOOKUPS = 100
MIN_CHECKS = 200
#: proposed tiers round UP to this quantum (compile-count hygiene: a
#: quantum bounds distinct pinned shapes without forcing pow2 waste)
TIER_QUANTUM = 64
#: occupancy p90 at or below this fraction of the tier marks pad waste
TIER_SHRINK_AT = 0.5
#: the shrunk tier is sized at p90 × this headroom: one coalescing
#: burst (two typical submissions landing inside the hold window) must
#: still fit, or the burst spills past the shrunk rung into the next
#: pinned tier and its dispatch cost shows up as a p99 cliff
TIER_HEADROOM = 2.0
#: the hold-back knob's quantized ladder (seconds)
HOLD_LADDER = (0.00025, 0.0005, 0.001, 0.002, 0.004, 0.008)
#: cache budget clamp (bytes); moves are ×2 / ÷2
CACHE_MIN_BYTES = 8 << 20
CACHE_MAX_BYTES = 256 << 20
#: dedup hysteresis watermarks on the measured duplicate fraction
DEDUP_ON_FRAC = 0.05
DEDUP_OFF_FRAC = 0.005
#: pack-layout A/B margin: the cheaper layout must win by this much
PACKED_MARGIN = 0.10
#: fused-probe margin: the modeled one-pass saving must be at least
#: this fraction of the XLA chain's bytes/check before the backend
#: switch is worth proposing
PALLAS_MARGIN = 0.10
#: default per-device HBM budget the placement rule compares against
HBM_BUDGET_BYTES = 4 << 30
#: chain-depth rule: clamp for the host LSM materialization floor
#: (EngineConfig.lsm_compact_min) and the evidence watermarks it moves
#: on — raise only after this many background merges in one window,
#: lower only when a merge-free window left a chain this deep relative
#: to the floor
LSM_COMPACT_FLOOR = 4_096
LSM_COMPACT_CEIL = 1 << 20
MIN_BG_COMPACTIONS = 4
CHAIN_DEEP_FRAC = 0.75
#: routing must shard at least this share of the bytes to be worth a
#: mesh (membership-dominated snapshots replicate everywhere anyway)
PLACEMENT_MIN_SHARD_FRAC = 0.25


@dataclass(frozen=True)
class KnobDiff:
    """One reviewable knob change: what, from, to, WHY (measured), and
    what the tuner predicts the change buys."""

    knob: str
    layer: str  # "engine" | "serve" | "cache" | "deploy"
    current: Any
    proposed: Any
    evidence: str
    predicted: Mapping[str, float] = field(default_factory=dict)

    def to_obj(self) -> Dict[str, Any]:
        cur = self.current
        prop = self.proposed
        return {
            "knob": self.knob, "layer": self.layer,
            "current": list(cur) if isinstance(cur, tuple) else cur,
            "proposed": list(prop) if isinstance(prop, tuple) else prop,
            "evidence": self.evidence,
            "predicted": dict(self.predicted),
        }


@dataclass(frozen=True)
class TuneDiff:
    """The emitted proposal set — JSON round-trippable, so a diff can
    be reviewed, stored, and applied in a different process."""

    knobs: Tuple[KnobDiff, ...] = ()

    def __bool__(self) -> bool:
        return bool(self.knobs)

    def get(self, knob: str) -> Optional[KnobDiff]:
        for k in self.knobs:
            if k.knob == knob:
                return k
        return None

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(
            {"version": 1, "knobs": [k.to_obj() for k in self.knobs]},
            indent=indent,
        )

    @staticmethod
    def from_json(blob: str) -> "TuneDiff":
        doc = json.loads(blob)
        knobs = []
        for k in doc.get("knobs", ()):
            cur, prop = k["current"], k["proposed"]
            if k["knob"] == "latency_tiers":
                cur = tuple(int(t) for t in cur)
                prop = tuple(int(t) for t in prop)
            knobs.append(KnobDiff(
                knob=k["knob"], layer=k["layer"], current=cur,
                proposed=prop, evidence=k.get("evidence", ""),
                predicted=dict(k.get("predicted", {})),
            ))
        return TuneDiff(tuple(knobs))

    def render(self) -> str:
        """Human-readable review table (scripts/tune.py prints this)."""
        if not self.knobs:
            return "tune: no changes proposed — config matches workload"
        lines = []
        for k in self.knobs:
            pred = ", ".join(
                f"{n} {v:+g}" for n, v in sorted(k.predicted.items())
            )
            lines.append(
                f"[{k.layer}] {k.knob}: {k.current!r} -> {k.proposed!r}"
                + (f"  (predicted: {pred})" if pred else "")
            )
            lines.append(f"    {k.evidence}")
        return "\n".join(lines)


@dataclass(frozen=True)
class TuneTarget:
    """The full tunable surface as one value.  EngineConfig and
    ServeConfig carry their own knobs; cache budget and placement are
    deploy-level choices with no config field (the cache budget is a
    VerdictCache constructor arg, placement is ``client.with_mesh``),
    so they ride alongside."""

    engine: EngineConfig
    serve: ServeConfig
    cache_bytes: Optional[int] = None
    placement: str = "replicated"


# ---------------------------------------------------------------------------
# snapshot readers
# ---------------------------------------------------------------------------

def hist_quantile(h: Mapping[str, Any], q: float) -> float:
    """Bucket-upper at the q-th cumulative count of a snapshot
    histogram ({buckets, counts, count, sum}).  Overflow (+Inf) mass
    reports as the last finite upper — the per-tier occupancy hists top
    out at the tier itself, so overflow cannot occur there by
    construction."""
    count = int(h.get("count") or 0)
    if count <= 0:
        return 0.0
    target = q * count
    cum = 0
    uppers = list(h["buckets"])
    for u, c in zip(uppers, h["counts"]):
        cum += int(c)
        if cum >= target:
            return float(u)
    return float(uppers[-1]) if uppers else 0.0


def _occ_fill_frac(snap: Mapping[str, Any]) -> Optional[float]:
    """COUNT-weighted mean fill fraction across the per-tier occupancy
    hists: the typical formed batch's live/tier ratio — None without
    data.  Count-weighted (each batch votes once), not lane-weighted: a
    mixed workload's few big-tier batches would otherwise drown the many
    near-empty small-tier ones the hold decision is actually about."""
    fill = 0.0
    n_total = 0
    for tier, h in (snap.get("occupancy") or {}).items():
        n = int(h.get("count") or 0)
        if n:
            fill += float(h["sum"]) / float(int(tier))
            n_total += n
    return (fill / n_total) if n_total else None


def _ladder_step(ladder: Tuple[float, ...], v: float, up: bool) -> float:
    """Nearest quantized step above/below ``v`` — ``v`` itself when
    already at the ladder's edge."""
    if up:
        above = [x for x in ladder if x > v * 1.0001]
        return min(above) if above else v
    below = [x for x in ladder if x < v * 0.9999]
    return max(below) if below else v


# ---------------------------------------------------------------------------
# per-knob rules: snapshot -> Optional[(desired, evidence, predicted)]
# ---------------------------------------------------------------------------

def _rule_tiers(snap):
    cfg = snap.get("config") or {}
    ladder = cfg.get("latency_tiers")
    occ = snap.get("occupancy") or {}
    if not ladder or not occ:
        return None
    ladder = sorted(int(t) for t in ladder)
    pad_tiers = (snap.get("pad") or {}).get("per_tier") or {}
    # when the hold rule is simultaneously dropping the hold to its
    # floor (maxhold-dominated flushes at near-empty fill), the
    # occupancy tail above the typical batch is a COALESCING ARTIFACT
    # of the very hold this diff removes — size tiers to the p50
    # typical batch then, not the p90 of a distribution that won't
    # exist under the proposed config
    f = snap.get("flush") or {}
    ftot = sum(int(f.get(k, 0)) for k in ("full", "maxhold", "deadline"))
    fillc = _occ_fill_frac(snap)
    hold_dropping = (
        ftot >= MIN_FLUSHES
        and int(f.get("maxhold", 0)) / ftot >= 0.6
        and int(f.get("deadline", 0)) / ftot < 0.3
        and fillc is not None and fillc <= 0.2
    )
    q = 0.5 if hold_dropping else 0.9
    out: List[int] = []
    notes: List[str] = []
    live = lanes_now = lanes_new = 0.0
    for i, t in enumerate(ladder):
        h = occ.get(str(t))
        keep = t
        insert = None
        if (
            h is not None and int(h["count"]) >= MIN_HIST_SAMPLES
            and i < len(ladder) - 1
        ):
            p90 = hist_quantile(h, q)
            if p90 <= TIER_SHRINK_AT * t:
                nt = max(
                    TIER_QUANTUM,
                    int(math.ceil(p90 * TIER_HEADROOM / TIER_QUANTUM))
                    * TIER_QUANTUM,
                )
                if nt < t:
                    mean = h["sum"] / h["count"]
                    # the occupancy histogram only sees the batcher's
                    # formed batches, but the ladder serves EVERY
                    # dispatch path — the pad ledger does see them all,
                    # so its excess over the batcher's share tells us
                    # whether lookups/direct calls still fill this rung
                    # past the shrunk size.  If they do, INSERT the
                    # small rung below instead of replacing.
                    pt = pad_tiers.get(str(t))
                    ns_batches = ns_live = 0.0
                    if pt:
                        ns_batches = max(
                            0.0, float(pt["total"]) / t - float(h["count"])
                        )
                        ns_live = max(
                            0.0, float(pt["live"]) - float(h["sum"])
                        )
                    ql = f"p{int(q * 100)}"
                    if ns_batches >= 4 and ns_live / ns_batches > nt:
                        insert = nt
                        notes.append(
                            f"tier {t} {ql} batcher occupancy {p90:.0f}"
                            f" (mean {mean:.0f}, n={h['count']}) ->"
                            f" insert tier {nt}; non-batcher dispatches"
                            f" still fill {ns_live / ns_batches:.0f}"
                            f" lanes so tier {t} stays"
                        )
                    else:
                        keep = nt
                        notes.append(
                            f"tier {t} {ql} occupancy {p90:.0f} (mean"
                            f" {mean:.0f}, n={h['count']}) -> tier {nt}"
                            + (" (sized to the typical batch: the"
                               " occupancy tail is coalescing under the"
                               " hold this diff also drops)"
                               if hold_dropping else "")
                        )
        if h is not None and int(h["count"]):
            n = int(h["count"])
            live += float(h["sum"])
            lanes_now += float(t) * n
            # batcher traffic lands on the new small rung either way;
            # the kept big rung keeps serving the non-batcher paths
            lanes_new += float(insert if insert is not None else keep) * n
        if insert is not None:
            out.append(insert)
        out.append(keep)
    desired = tuple(sorted(set(out)))
    if desired == tuple(ladder) or not lanes_now:
        return None
    pad_now = 1.0 - live / lanes_now
    pad_new = max(0.0, 1.0 - live / lanes_new)
    rel = (pad_new - pad_now) / pad_now if pad_now > 0 else 0.0
    evidence = (
        "; ".join(notes)
        + f" — predicted pad-waste {pad_now:.2f} -> {pad_new:.2f}"
        f" ({rel:+.0%})"
    )
    return desired, evidence, {"pad_waste_frac": round(pad_new - pad_now, 4)}


def _rule_hold(snap):
    cfg = snap.get("config") or {}
    H = cfg.get("hold_max_s")
    f = snap.get("flush") or {}
    tot = int(f.get("full", 0)) + int(f.get("maxhold", 0)) + int(
        f.get("deadline", 0)
    )
    if H is None or tot < MIN_FLUSHES:
        return None
    H = float(H)
    mh = f.get("maxhold", 0) / tot
    dl = f.get("deadline", 0) / tot
    occ = _occ_fill_frac(snap)
    if dl >= 0.3 or (mh >= 0.6 and occ is not None and occ <= 0.25):
        # the offline pass can jump, unlike the online controller's
        # one-rung bounded steps: when flushes are maxhold-bound at
        # near-empty fill the hold buys NO coalescing at any length —
        # the evidence supports the ladder floor directly
        if mh >= 0.6 and occ is not None and occ <= 0.2 and dl < 0.3:
            desired = HOLD_LADDER[0]
        else:
            desired = _ladder_step(HOLD_LADDER, H, up=False)
        if desired >= H:
            return None
        why = (
            f"deadline flushes {dl:.0%}" if dl >= 0.3
            else f"maxhold flushes {mh:.0%} at {occ:.2f} mean fill"
        )
        evidence = (
            f"{why} under hold {H * 1000:g}ms — batches flush on the"
            f" clock, not on fill: hold {desired * 1000:g}ms trims the"
            " wait without losing coalescing"
        )
        # requests flushing at maxhold waited the full hold; they save
        # the difference (scaled by how often that path fired)
        return desired, evidence, {
            "p99_ms": round(-(H - desired) * 1000.0 * mh, 3)
        }
    if mh >= 0.6 and occ is not None and occ >= 0.6:
        desired = _ladder_step(HOLD_LADDER, H, up=True)
        if desired <= H:
            return None
        occ_new = min(1.0, occ * desired / H)
        evidence = (
            f"maxhold flushes {mh:.0%} at {occ:.2f} mean fill under hold"
            f" {H * 1000:g}ms — batches nearly fill: hold"
            f" {desired * 1000:g}ms converts clock flushes to full ones"
        )
        return desired, evidence, {
            "pad_waste_frac": round((1 - occ_new) - (1 - occ), 4)
        }
    return None


def _rule_cache(snap):
    c = snap.get("cache")
    if not c or c.get("max_bytes") is None:
        return None
    lookups = int(c.get("hits", 0)) + int(c.get("misses", 0))
    if lookups < MIN_CACHE_LOOKUPS:
        return None
    mx = int(c["max_bytes"])
    used = int(c.get("bytes", 0))
    hr = float(c.get("hit_rate", 0.0))
    ev = int(c.get("evicted_revisions", 0))
    if hr >= 0.2 and used >= 0.85 * mx and ev > 0 and mx < CACHE_MAX_BYTES:
        desired = min(mx * 2, CACHE_MAX_BYTES)
        evidence = (
            f"hit rate {hr:.0%} with {used / mx:.0%} of {mx >> 20}MiB"
            f" used and {ev} revision shards evicted — the budget, not"
            f" the workload, is the ceiling: grow to {desired >> 20}MiB"
        )
        return desired, evidence, {"cache_bytes": desired - mx}
    if hr < 0.02 and used <= 0.25 * mx and mx > CACHE_MIN_BYTES:
        desired = max(mx // 2, CACHE_MIN_BYTES)
        evidence = (
            f"hit rate {hr:.1%} with only {used / mx:.0%} of"
            f" {mx >> 20}MiB used — reclaim host memory:"
            f" {desired >> 20}MiB"
        )
        return desired, evidence, {"cache_bytes": desired - mx}
    return None


def _rule_dedup(snap):
    cfg = snap.get("config") or {}
    if cfg.get("dedup") is None:
        return None
    s = snap.get("serve") or {}
    checks = int(s.get("checks", 0))
    unique = int(s.get("unique_checks", 0))
    if checks < MIN_CHECKS or unique <= 0:
        # duplicate fraction is only measured while dedup runs (the
        # unique-work count comes from the singleflight key pass) —
        # no measurement, no proposal
        return None
    # serve.checks already counts parked twins (the singleflight window
    # settles them as served checks), so unique/checks is the honest
    # duplicate fraction across both in-batch and cross-batch dedup
    parked = int(s.get("dedup_parked", 0))
    dup = max(0.0, 1.0 - unique / checks)
    if dup >= DEDUP_ON_FRAC:
        desired = True
        evidence = (
            f"duplicate fraction {dup:.1%} over {checks} checks"
            f" ({parked} parked on in-flight twins) — dedup collapses"
            " that work before it reaches a tier lane"
        )
        predicted = {"goodput_frac": round(dup, 4)}
    elif dup < DEDUP_OFF_FRAC:
        desired = False
        evidence = (
            f"duplicate fraction {dup:.2%} over {checks} checks — below"
            f" {DEDUP_OFF_FRAC:.1%}: the per-batch key pass buys"
            " nothing, drop it from the dispatch path"
        )
        predicted = {"goodput_frac": 0.0}
    else:
        return None  # hysteresis band: keep whatever runs today
    return desired, evidence, predicted


def _rule_packed(snap):
    by = snap.get("bytes") or {}
    cand = by.get("candidates")
    if not cand or "packed" not in cand or "unpacked" not in cand:
        return None
    p, u = float(cand["packed"]), float(cand["unpacked"])
    if p <= 0 or u <= 0:
        return None
    if p <= (1.0 - PACKED_MARGIN) * u:
        desired = True
        rel = (p - u) / u
    elif u <= (1.0 - PACKED_MARGIN) * p:
        desired = False
        rel = 0.0
    else:
        return None  # within margin: not worth a layout change
    evidence = (
        f"gathered bytes/check packed {p:.0f} vs unpacked {u:.0f}"
        f" (offline A/B prepare) — flat_packed={desired}"
    )
    return desired, evidence, {"bytes_per_check_frac": round(rel, 4)}


def _rule_pallas(snap):
    """Propose the fused Pallas probe backend from the one-pass byte
    model prepare publishes (utils/perf.publish_pallas_model gauges):
    fused HBM bytes/check against the XLA chain's gather + decode
    traffic.  Two vetoes run first — the feature probe and the runtime
    ``pallas.degraded`` counter — because a knob the engine cannot
    serve (or has already fallen back from at dispatch) must be
    proposed off regardless of how good the model looks."""
    pl = snap.get("pallas")
    if not pl:
        return None
    degraded = int(pl.get("degraded") or 0)
    if not pl.get("available") or degraded:
        why = (
            "jax.experimental.pallas unavailable on this jaxlib"
            if not pl.get("available")
            else f"{degraded} runtime degrade(s) to the XLA path"
        )
        return (False, f"fused probe vetoed: {why} — pallas=False",
                {"bytes_per_check_frac": 0.0})
    fused = float(pl.get("bytes_per_check") or 0.0)
    saved = float(pl.get("bytes_saved_per_check") or 0.0)
    if fused <= 0:
        return None  # no fused prepare measured this window: stay silent
    xla = fused + saved
    if xla <= 0:
        return None
    frac = saved / xla
    if frac >= PALLAS_MARGIN:
        desired, rel = True, -frac
    elif frac <= 0.0:
        desired, rel = False, 0.0
    else:
        return None  # within margin: not worth a backend change
    evidence = (
        f"one-pass byte model: fused {fused:.0f} vs XLA {xla:.0f}"
        f" bytes/check ({frac:.0%} saved) — pallas={desired}"
    )
    return desired, evidence, {"bytes_per_check_frac": round(rel, 4)}


def _rule_lsm_compact(snap):
    """Move the host LSM materialization floor off chain-depth
    telemetry (store/group.py ChainCompactor gauges): merge churn means
    the floor is too low (each merge rewrites the O(E) base), a deep
    merge-free resident chain means it is too high (every probe pays
    the chain's extra binary search).  Moves are ×2 / ÷2, clamped —
    the cache rule's quantization discipline."""
    cfg = snap.get("config") or {}
    cm = cfg.get("lsm_compact_min")
    ch = snap.get("chain") or {}
    if cm is None or not ch:
        return None
    cm = int(cm)
    rows = float(ch.get("overlay_rows", 0.0))
    chain_len = float(ch.get("chain_len", 0.0))
    merges = int(ch.get("bg_compactions", 0))
    if merges >= MIN_BG_COMPACTIONS and cm < LSM_COMPACT_CEIL:
        desired = min(cm * 2, LSM_COMPACT_CEIL)
        evidence = (
            f"{merges} background chain merges in the window at floor"
            f" {cm} — each merge rewrites the whole base: doubling the"
            f" floor to {desired} halves merge frequency while the"
            " compactor's early trip keeps probe depth bounded"
        )
        return desired, evidence, {"bg_compactions": -(merges // 2)}
    if (
        merges == 0
        and rows >= CHAIN_DEEP_FRAC * cm
        and cm > LSM_COMPACT_FLOOR
    ):
        desired = max(cm // 2, LSM_COMPACT_FLOOR)
        evidence = (
            f"resident chain at {rows:.0f} overlay rows"
            f" ({chain_len:.0f} revisions, {rows / cm:.0%} of the {cm}"
            " floor) with no background merge all window — every probe"
            " pays the chain's extra binary search; halve the floor to"
            f" {desired} so compaction lands earlier"
        )
        return desired, evidence, {
            "probe_overlay_rows": round(float(desired) - rows, 1)
        }
    return None


def _rule_placement(snap, hbm_budget_bytes: int):
    by = snap.get("bytes") or {}
    total = by.get("total")
    sharded = by.get("sharded")
    if total is None or sharded is None or total <= 0:
        return None
    if (
        total > hbm_budget_bytes
        and sharded >= PLACEMENT_MIN_SHARD_FRAC * total
    ):
        desired = "routed"
        evidence = (
            f"replicated device tables {total >> 20}MiB exceed the"
            f" {hbm_budget_bytes >> 20}MiB HBM budget and"
            f" {sharded / total:.0%} of them are primary/fold-point"
            " tables a routed serve shards along the model axis"
        )
        predicted = {"device_bytes": -int(sharded)}
    if total > hbm_budget_bytes:
        # over budget but membership-dominated: routing can't shard
        # enough to matter — keep replicated, say why
        evidence = (
            f"device tables {total >> 20}MiB exceed the"
            f" {hbm_budget_bytes >> 20}MiB HBM budget but only"
            f" {sharded / total:.0%} are shardable primary/fold-point"
            " tables — routing buys too little, stay replicated"
        )
    else:
        evidence = (
            f"device tables {total >> 20}MiB fit the"
            f" {hbm_budget_bytes >> 20}MiB HBM budget — replicate"
            " whole, no collectives on any probe"
        )
    return "replicated", evidence, {}


# ---------------------------------------------------------------------------
# propose / apply
# ---------------------------------------------------------------------------

def _current_of(snap: Mapping[str, Any], target: Optional[TuneTarget],
                knob: str):
    """The knob's value on the comparison side: the explicit target
    when given, else the config the snapshot was measured under
    (missing → None, which suppresses the knob)."""
    cfg = snap.get("config") or {}
    if target is None:
        if knob == "latency_tiers":
            v = cfg.get("latency_tiers")
            return tuple(int(t) for t in v) if v is not None else None
        if knob == "flat_packed":
            return cfg.get("flat_packed_resolved")
        if knob == "pallas":
            return cfg.get("pallas_resolved")
        if knob == "cache_max_bytes":
            return cfg.get("cache_max_bytes")
        if knob == "placement":
            return cfg.get("placement")
        return cfg.get(knob)
    if knob == "latency_tiers":
        return tuple(target.engine.latency_tiers)
    if knob == "flat_packed":
        return bool(target.engine.packed_on())
    if knob == "pallas":
        return bool(_pallas.resolve(target.engine))
    if knob == "hold_max_s":
        return float(target.serve.hold_max_s)
    if knob == "dedup":
        return bool(target.serve.dedup)
    if knob == "cache_max_bytes":
        return target.cache_bytes
    if knob == "placement":
        return target.placement
    if knob == "lsm_compact_min":
        return int(target.engine.lsm_compact_min)
    raise KeyError(knob)


def propose(
    snapshot: Mapping[str, Any],
    target: Optional[TuneTarget] = None,
    *,
    hbm_budget_bytes: int = HBM_BUDGET_BYTES,
) -> TuneDiff:
    """Run every rule against the snapshot and emit the knobs whose
    desired value differs from the current one.  Deterministic:
    identical snapshot + target always emits the identical diff."""
    rules = (
        ("latency_tiers", "engine", lambda: _rule_tiers(snapshot)),
        ("flat_packed", "engine", lambda: _rule_packed(snapshot)),
        ("pallas", "engine", lambda: _rule_pallas(snapshot)),
        ("lsm_compact_min", "engine", lambda: _rule_lsm_compact(snapshot)),
        ("hold_max_s", "serve", lambda: _rule_hold(snapshot)),
        ("dedup", "serve", lambda: _rule_dedup(snapshot)),
        ("cache_max_bytes", "cache", lambda: _rule_cache(snapshot)),
        ("placement", "deploy",
         lambda: _rule_placement(snapshot, hbm_budget_bytes)),
    )
    knobs: List[KnobDiff] = []
    for knob, layer, rule in rules:
        got = rule()
        if got is None:
            continue
        desired, evidence, predicted = got
        current = _current_of(snapshot, target, knob)
        if current is None or current == desired:
            continue
        knobs.append(KnobDiff(
            knob=knob, layer=layer, current=current, proposed=desired,
            evidence=evidence, predicted=predicted,
        ))
    return TuneDiff(tuple(knobs))


def apply_diff(target: TuneTarget, diff: TuneDiff) -> TuneTarget:
    """Apply a diff to a TuneTarget — pure, returns a new target (the
    frozen-config discipline: applying is dataclasses.replace, nothing
    mutates in place)."""
    engine, serve = target.engine, target.serve
    cache_bytes, placement = target.cache_bytes, target.placement
    for k in diff.knobs:
        if k.knob == "latency_tiers":
            engine = replace(
                engine, latency_tiers=tuple(int(t) for t in k.proposed)
            )
        elif k.knob == "flat_packed":
            engine = replace(engine, flat_packed=bool(k.proposed))
        elif k.knob == "pallas":
            engine = replace(engine, pallas=bool(k.proposed))
        elif k.knob == "lsm_compact_min":
            engine = replace(engine, lsm_compact_min=int(k.proposed))
        elif k.knob == "hold_max_s":
            serve = replace(serve, hold_max_s=float(k.proposed))
        elif k.knob == "dedup":
            serve = replace(serve, dedup=bool(k.proposed))
        elif k.knob == "cache_max_bytes":
            cache_bytes = int(k.proposed)
        elif k.knob == "placement":
            placement = str(k.proposed)
        else:
            raise KeyError(f"unknown tune knob {k.knob!r}")
    return TuneTarget(
        engine=engine, serve=serve, cache_bytes=cache_bytes,
        placement=placement,
    )

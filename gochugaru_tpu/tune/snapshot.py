"""Telemetry snapshot: everything the tuner reads, in one JSON blob.

A snapshot is a pure data capture — no proposals, no judgment — of the
serving telemetry a measurement window produced, stamped with the
config it was measured under.  Stamping the config into the snapshot is
what makes the tuner's fixed-point property structural: ``propose`` is
a pure function of the snapshot (plus constants), so applying its diff
and re-proposing against the SAME snapshot can only converge.

Sections (all JSON-serializable; absent sections simply disable the
rules that read them):

- ``config``   — the knob values the window ran under
- ``occupancy``— per-tier live-lane histograms (``serve.occupancy.t*``)
- ``flush``    — formed-batch flush-reason counts
- ``serve``    — check/unique/shed/batch counters
- ``queue_wait``— submit→form wait quantiles
- ``cache``    — verdict-cache stats (engine/vcache.py ``stats()``)
- ``pad``      — pinned-tier pad-waste ledger (utils/perf.py)
- ``cost``     — per-tier expected dispatch cost (utils/admission.py)
- ``bytes``    — gathered-bytes model + device-table placement split
- ``pallas``   — fused-probe backend evidence: feature probe, the
  one-pass byte-model gauges prepare publishes (utils/perf.py
  ``publish_pallas_model``), and the degrade counter
- ``wall``     — last closed wall-ledger window's bucket fractions
- ``chain``    — write-path delta-chain depth (store/group.py gauges:
  overlay rows, chain length in revisions, background compactions,
  batched closure advances) — the lsm_compact_min rule's evidence
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..engine import pallas as _pallas
from ..utils import metrics as _metrics
from ..utils import perf as _perf

#: snapshot format version (bumped on breaking shape changes)
SNAPSHOT_VERSION = 1

#: the flush reasons serve/batcher.py counts (drain excluded from rule
#: denominators — it is lifecycle, not workload)
FLUSH_REASONS = ("full", "maxhold", "deadline", "drain")


def _occupancy_of(registry: _metrics.Metrics) -> Dict[str, Dict[str, Any]]:
    """``serve.occupancy.t{tier}`` histograms → {tier: {buckets, counts,
    count, sum}} — the per-tier live-lane distributions the ladder rule
    reads (exemplars dropped: they are trace pointers, not data)."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, (buckets, counts, count, total, _ex) in (
        registry.hist_snapshot().items()
    ):
        if not name.startswith("serve.occupancy.t"):
            continue
        tier = name[len("serve.occupancy.t"):]
        out[tier] = {
            "buckets": [float(b) for b in buckets],
            "counts": [int(c) for c in counts],
            "count": int(count),
            "sum": float(total),
        }
    return out


def collect_snapshot(
    registry: Optional[_metrics.Metrics] = None,
    *,
    engine_config=None,
    serve_config=None,
    vcache=None,
    cost=None,
    dsnap=None,
    placement: str = "replicated",
    packed_candidates: Optional[Dict[str, float]] = None,
) -> Dict[str, Any]:
    """Capture one tuner input from live telemetry.

    ``engine_config``/``serve_config``/``vcache`` stamp the measured-
    under config; any left None stamps that knob as unknown and the
    rules needing it stay silent.  ``dsnap`` (a prepared
    DeviceSnapshot) enables the bytes/placement section;
    ``packed_candidates`` ({"packed": bytes/check, "unpacked": ...}
    from an offline A/B prepare, scripts/tune.py) enables the pack-spec
    rule — a live snapshot can only see the layout it runs, so the
    counterfactual is collected offline or not at all."""
    m = registry or _metrics.default
    snap: Dict[str, Any] = {"version": SNAPSHOT_VERSION}

    cfg: Dict[str, Any] = {"placement": placement}
    if engine_config is not None:
        cfg["latency_tiers"] = [int(t) for t in engine_config.latency_tiers]
        cfg["flat_packed"] = engine_config.flat_packed
        cfg["flat_packed_resolved"] = bool(engine_config.packed_on())
        cfg["pallas"] = engine_config.pallas
        cfg["pallas_resolved"] = bool(_pallas.resolve(engine_config))
        cfg["lsm_compact_min"] = int(engine_config.lsm_compact_min)
    if serve_config is not None:
        cfg["hold_max_s"] = float(serve_config.hold_max_s)
        cfg["dedup"] = bool(serve_config.dedup)
    if vcache is not None:
        cfg["cache_max_bytes"] = int(vcache.max_bytes)
    snap["config"] = cfg

    snap["occupancy"] = _occupancy_of(m)
    snap["flush"] = {
        r: int(m.counter(f"serve.flush_{r}")) for r in FLUSH_REASONS
    }
    snap["serve"] = {
        "checks": int(m.counter("serve.checks")),
        "unique_checks": int(m.counter("serve.unique_checks")),
        "submissions": int(m.counter("serve.submissions")),
        "batches": int(m.counter("serve.batches")),
        "sheds": int(m.counter("serve.sheds")),
        "dedup_parked": int(m.counter("serve.dedup_parked")),
    }
    qw: Dict[str, Any] = {"count": m.timer_counts("serve.queue_wait_s")[0]}
    for q, key in ((0.5, "p50_s"), (0.99, "p99_s")):
        v = m.percentile("serve.queue_wait_s", q)
        if v is not None:
            qw[key] = round(float(v), 6)
    snap["queue_wait"] = qw

    if vcache is not None:
        c = dict(vcache.stats())
        c["evicted_revisions"] = int(m.counter("cache.evicted_revisions"))
        snap["cache"] = c

    snap["pad"] = _perf.pad_stats(m)
    snap["pallas"] = {
        "available": bool(_pallas.available()),
        "bytes_per_check": float(m.gauge("perf.pallas.bytes_per_check")),
        "bytes_saved_per_check": float(
            m.gauge("perf.pallas.bytes_saved_per_check")
        ),
        "degraded": int(m.counter("pallas.degraded")),
    }
    if cost is not None:
        snap["cost"] = cost.state()

    by: Dict[str, Any] = {}
    model = _perf.last_model()
    if dsnap is not None:
        try:
            model = _perf.gathered_bytes_model(dsnap)
        except Exception:
            pass
        from ..engine.flat import placement_split

        by.update(placement_split(dsnap))
    if model is not None:
        by["per_check"] = round(float(model.total), 2)
    if packed_candidates:
        by["candidates"] = {
            k: round(float(v), 2) for k, v in packed_candidates.items()
        }
    if by:
        snap["bytes"] = by

    wall = _perf.last_wall()
    if wall is not None:
        snap["wall"] = dict(wall.get("fracs") or {})

    # write-path chain depth: only present once the compactor (or a
    # write) has published anything — an all-zero section would make
    # the lsm_compact_min rule read "no chain" as evidence
    chain = {
        "overlay_rows": float(m.gauge("store.lsm_overlay_rows")),
        "chain_len": float(m.gauge("store.lsm_chain_len")),
        "bg_compactions": int(m.counter("store.bg_compactions")),
        "batch_applies": int(m.counter("closure.batch_applies")),
        "groups": int(m.counter("write.groups")),
    }
    if any(chain.values()):
        snap["chain"] = chain
    return snap

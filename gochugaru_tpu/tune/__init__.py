"""Workload-adaptive self-tuning: close the loop from the perf ledger
to EngineConfig.

Every geometry and scheduling knob in the engine is measured somewhere
— pad waste per pinned tier (utils/perf.py), batch occupancy and flush
reasons (serve/batcher.py), verdict-cache hit rates (engine/vcache.py),
gathered-bytes models and device residency (engine/flat.py) — but until
this package nothing READ those measurements back into config.  A
workload inherited a preset tuned for a different one.

Three pieces, offline-first:

- ``snapshot.collect_snapshot``: one JSON-serializable capture of the
  telemetry the tuner consumes, stamped with the config it was measured
  under (the tuner reasons about the config the data came from, which
  also makes emit → apply → re-emit a structural fixed point).
- ``tuner.propose``: deterministic rules mapping a snapshot to a
  ``TuneDiff`` — per knob: current value, proposed value, the measured
  evidence string, and predicted deltas the bench A/B verifies
  mechanically (benchmarks/bench11_tune.py).
- ``controller.OnlineController``: the three cheap knobs (hold-back
  deadline, verdict-cache byte budget, dedup window) adjusted live off
  telemetry deltas — hysteresis, clamped ranges, bounded ×2 steps,
  per-knob cooldown, a flight-recorder incident on oscillation, and a
  one-call ``revert()`` to the captured preset.

Expensive knobs (tier ladder, pack spec, placement) stay OFFLINE by
design: changing them means recompiling pinned executables or
re-preparing device tables, which is a deploy, not a nudge.
"""

from .snapshot import collect_snapshot  # noqa: F401
from .tuner import (  # noqa: F401
    KnobDiff,
    TuneDiff,
    TuneTarget,
    apply_diff,
    propose,
)
from .controller import OnlineController  # noqa: F401

"""Benchmark: BASELINE config 2 — GitHub-style RBAC, 10k repos x 1k users,
2-hop org→team→repo rewrites, 100k-check batches on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "checks/sec/chip", "vs_baseline": N,
   "p99_ms": N, "batch": N, "edges": N[, "note": ...]}

``vs_baseline`` is the fraction of the BASELINE.json north-star target
(10M checks/sec/chip); the reference itself publishes no numbers
(BASELINE.md), so the target is the denominator.  ``p99_ms`` is the p99
batch-evaluation latency (north star: p99 < 2 ms, BASELINE.md:22).

Robustness contract (the driver runs this unattended): the parent process
NEVER imports jax — it orchestrates child subprocesses under bounded
timeouts.  Attempt 1 runs on the default platform (the real TPU chip);
if the backend hangs or errors, attempt 2 re-runs degraded on CPU with a
"note" naming the failure.  If even that fails, a last-resort JSON line
with value 0 is emitted.  The process always exits 0 with a parseable
line on stdout.

Methodology (child): the graph is materialized once (columnar bulk path),
queries are lowered to int32 arrays once, and the check is timed in forced-
synchronous mode with null-program calibration (benchmarks/common.py
sync_rate): on remote-attached TPUs, block_until_ready does not actually
wait until the process performs its first device→host fetch, so
enqueue-loop timings are fantasy; after one fetch every blocked execution
is real but pays a fixed dispatch round trip, which timing a
same-signature null program cancels.  Host-side query lowering is
excluded, matching how the reference's client-side proto building is not
part of SpiceDB's evaluation numbers.
"""

import json
import os
import subprocess
import sys
import time

TPU_CHILD_TIMEOUT_S = int(os.environ.get("GOCHUGARU_BENCH_TPU_TIMEOUT", "300"))
CPU_CHILD_TIMEOUT_S = int(os.environ.get("GOCHUGARU_BENCH_CPU_TIMEOUT", "180"))


def build_world(n_repos=10_000, n_users=1_000, n_teams=100, n_orgs=10, seed=11):
    import numpy as np

    from gochugaru_tpu import rel  # noqa: F401
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot_from_columns

    schema = """
    definition user {}
    definition team { relation member: user }
    definition org {
        relation admin: user
        relation member: user | team#member
    }
    definition repo {
        relation org: org
        relation maintainer: user | team#member
        relation reader: user
        permission admin = org->admin + maintainer
        permission read = reader + admin + org->member
    }
    """
    cs = compile_schema(parse_schema(schema))
    interner = Interner()
    rng = np.random.default_rng(seed)

    users = np.array([interner.node("user", f"u{i}") for i in range(n_users)], np.int64)
    teams = np.array([interner.node("team", f"t{i}") for i in range(n_teams)], np.int64)
    orgs = np.array([interner.node("org", f"o{i}") for i in range(n_orgs)], np.int64)
    repos = np.array([interner.node("repo", f"r{i}") for i in range(n_repos)], np.int64)

    slot = cs.slot_of_name
    member, admin, org_rel = slot["member"], slot["admin"], slot["org"]
    maintainer, reader = slot["maintainer"], slot["reader"]

    res, rel_s, subj, srel = [], [], [], []

    def add(r, rl, s, sr):
        res.append(r); rel_s.append(rl); subj.append(s); srel.append(sr)

    # team members: each team gets n_users/10 members
    per_team = max(2, n_users // 10)
    for t in teams:
        for u in rng.choice(users, per_team, replace=False):
            add(t, member, u, -1)
    # orgs: admins + team usersets + direct members
    for o in orgs:
        add(o, admin, rng.choice(users), -1)
        for t in rng.choice(teams, 2, replace=False):
            add(o, member, t, member)
        for u in rng.choice(users, 5, replace=False):
            add(o, member, u, -1)
    # repos: org edge + maintainer team + direct readers (vectorized)
    repo_orgs = rng.choice(orgs, n_repos)
    repo_teams = rng.choice(teams, n_repos)
    res.extend(repos); rel_s.extend([org_rel] * n_repos)
    subj.extend(repo_orgs); srel.extend([-1] * n_repos)
    res.extend(repos); rel_s.extend([maintainer] * n_repos)
    subj.extend(repo_teams); srel.extend([member] * n_repos)
    for k in range(2):
        res.extend(repos); rel_s.extend([reader] * n_repos)
        subj.extend(rng.choice(users, n_repos)); srel.extend([-1] * n_repos)

    snap = build_snapshot_from_columns(
        1, cs, interner,
        res=np.asarray(res, np.int64), rel=np.asarray(rel_s, np.int64),
        subj=np.asarray(subj, np.int64), srel=np.asarray(srel, np.int64),
        epoch_us=1_700_000_000_000_000,
    )
    return cs, snap, users, repos, slot


def run_bench(batch, world_kw, note=None):
    """The real measurement; runs in a child process.  Returns the result
    dict that becomes the driver-facing JSON line."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from gochugaru_tpu.engine.device import DeviceEngine

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmarks.common import sync_rate

    cs, snap, users, repos, slot = build_world(**world_kw)
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)

    rng = np.random.default_rng(5)
    B = 1 << (batch - 1).bit_length()
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = rng.choice(
        np.array([slot["read"], slot["admin"]], np.int32), B
    )
    q_subj = rng.choice(users, B).astype(np.int32)
    q_srel = np.full(B, -1, np.int32)
    q_wc = np.full(B, -1, np.int32)
    q_self = np.zeros(B, bool)
    uniq, q_row = np.unique(q_subj, return_inverse=True)
    UP = 1 << (len(uniq) - 1).bit_length()
    u_subj = np.full(UP, -1, np.int32)
    u_subj[: len(uniq)] = uniq
    u_other = np.full(UP, -1, np.int32)

    now = jnp.int32(snap.now_rel32(1_700_000_000_000_000))
    q_ctx = np.full(B, -1, np.int32)
    qctx = engine._encode_query_contexts([], dsnap.strings)
    args = (
        dsnap.arrays, dsnap.tid_map, now,
        jnp.asarray(u_subj), jnp.asarray(u_other), jnp.asarray(u_other),
        jnp.asarray(u_other),
        jnp.asarray(q_res), jnp.asarray(q_perm), jnp.asarray(q_subj),
        jnp.asarray(q_srel), jnp.asarray(q_wc),
        jnp.asarray(q_row.astype(np.int32)), jnp.asarray(q_self),
        jnp.asarray(q_ctx),
        {k: jnp.asarray(v) for k, v in qctx.items()},
    )

    # correctness signal first (one real fetch; also flips the platform
    # into synchronous execution for honest timing)
    d, p, ovf = jax.device_get(engine._fn(*args))

    # null program with the same signature calibrates the fixed
    # per-dispatch cost so the reported rate is pure evaluation
    null_fn = jax.jit(
        lambda arrs, tid_map, now, us, ur, uw, uq,
        qr, qp, qs, qsr, qw, qrow, qself, qctx_i, qctx:
        (qself, qself, qself)
    )
    rate, step, overhead = sync_rate(engine._fn, null_fn, args, B)

    # p99 batch-evaluation latency: individually blocked executions of the
    # real program, fixed dispatch round trip subtracted (north star is
    # evaluation latency, not tunnel latency)
    ts = []
    for _ in range(30):
        t0 = time.perf_counter()
        jax.block_until_ready(engine._fn(*args))
        ts.append(time.perf_counter() - t0)
    lat = np.maximum(np.asarray(ts) - overhead, 0.0) * 1000.0
    p99_ms = float(np.percentile(lat, 99))

    result = {
        "metric": "rbac_2hop_bulk_check_throughput",
        "value": round(rate, 1),
        "unit": "checks/sec/chip",
        "vs_baseline": round(rate / 10_000_000, 4),
        "p99_ms": round(p99_ms, 3),
        "batch": int(B),
        "edges": int(snap.num_edges),
        "platform": jax.default_backend(),
    }
    if note:
        result["note"] = note
    print(
        f"# batch={B} step={step*1000:.2f}ms dispatch_overhead={overhead*1000:.1f}ms"
        f" p99={p99_ms:.2f}ms granted={int(d.sum())} overflow={int(ovf.sum())}"
        f" edges={snap.num_edges}",
        file=sys.stderr,
    )
    return result


def child_main(mode: str, note: str | None) -> None:
    if mode == "cpu":
        from gochugaru_tpu.utils.platform import force_cpu_platform

        force_cpu_platform()
        result = run_bench(
            batch=32_768,
            world_kw=dict(n_repos=2_000, n_users=500, n_teams=50, n_orgs=5),
            note=note or "degraded: cpu fallback",
        )
    else:
        result = run_bench(batch=100_000, world_kw={}, note=note)
    print(json.dumps(result))


def _run_child(mode: str, timeout_s: int, note: str | None):
    """Run one child attempt; returns (json_line|None, failure_reason)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child", mode]
    if note:
        cmd.append(note)
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s
        )
    except subprocess.TimeoutExpired:
        return None, f"{mode} attempt timed out after {timeout_s}s"
    if r.stderr:
        sys.stderr.write(r.stderr)
    for line in reversed(r.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                if "metric" in parsed and "value" in parsed:
                    return line, None
            except json.JSONDecodeError:
                continue
    err = (r.stderr or "").strip().splitlines()
    tail = err[-1][:200] if err else f"rc={r.returncode}, no JSON line"
    return None, f"{mode} attempt failed: {tail}"


PROBE_TIMEOUT_S = int(os.environ.get("GOCHUGARU_BENCH_PROBE_TIMEOUT", "75"))


def _probe_backend() -> str | None:
    """Cheap bounded liveness probe of the default (TPU) backend; returns
    a failure reason, or None when the backend is usable."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()), jax.default_backend())"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return f"backend probe timed out after {PROBE_TIMEOUT_S}s"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return f"backend probe failed: {tail[-1][:200] if tail else r.returncode}"
    return None


def main() -> int:
    # Parent orchestrator: no jax import here, so a hung TPU backend can
    # never keep the driver-facing process from printing a parseable line.
    reason = _probe_backend()
    if reason is None:
        line, reason = _run_child("tpu", TPU_CHILD_TIMEOUT_S, None)
    else:
        line = None
        sys.stderr.write(f"# {reason}\n")
    if line is None:
        sys.stderr.write(f"# {reason}; retrying degraded on cpu\n")
        line, reason2 = _run_child(
            "cpu", CPU_CHILD_TIMEOUT_S, f"degraded cpu run ({reason})"
        )
        if line is None:
            line = json.dumps(
                {
                    "metric": "rbac_2hop_bulk_check_throughput",
                    "value": 0.0,
                    "unit": "checks/sec/chip",
                    "vs_baseline": 0.0,
                    "p99_ms": 0.0,
                    "batch": 0,
                    "edges": 0,
                    "platform": "none",
                    "note": f"all attempts failed: {reason}; {reason2}",
                }
            )
    print(line)
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        child_main(sys.argv[2], sys.argv[3] if len(sys.argv) > 3 else None)
    else:
        sys.exit(main())

"""Benchmark: BASELINE config 2 — GitHub-style RBAC, 10k repos x 1k users,
2-hop org→team→repo rewrites, 100k-check batches on one chip.

Prints one JSON line per metric (headline first):
  {"metric": ..., "value": N, "unit": "checks/sec/chip", "vs_baseline": N,
   "p99_ms": N, "batch": N, "edges": N[, "note": ...]}

``vs_baseline`` is the fraction of the BASELINE.json north-star target
(10M checks/sec/chip); the reference itself publishes no numbers
(BASELINE.md), so the target is the denominator.  ``p99_ms`` is the p99
batch-evaluation latency (north star: p99 < 2 ms, BASELINE.md:22); the
``rbac_2hop_small_batch_p99_latency`` row measures it the way a serving
path would — a warm B=1024 latency-mode dispatch (engine/latency.py)
with its host/H2D/kernel/D2H budget on the row.

Honesty contract: ``value``/``vs_baseline`` are the repeat-harness TRUE
wall-clock rate (N whole-batch evaluations inside one dispatch,
t(2K)-t(K) — nothing overlapped, nothing amortized away); the pipelined
rate (back-to-back queued dispatches) rides along as the secondary
``pipelined_rate`` field.  While the true rate for a batch is still
being measured, a provisional line carries ``rate_basis:
"blocked-dispatch"`` (median individually-blocked dispatch — also
honest wall clock, slightly pessimistic); the final line for the batch
carries ``rate_basis: "repeat-harness"`` and supersedes it.

Robustness contract (the driver runs this unattended):
- the parent NEVER imports jax; children run under bounded timeouts;
- the TPU child BATCH-RAMPS (8192 → 32768 → 131072) and emits a JSON line
  after EVERY batch size, so even a timeout mid-ramp leaves a real TPU
  number on stdout — the parent salvages partial stdout from a killed
  child (TimeoutExpired.stdout) and keeps the best parsed line per
  metric;
- every stage is stamped on stderr (world/prepare/compile/measure), so a
  timeout names the stage it died in;
- a persistent XLA compile cache (/tmp/gochugaru_xla_cache_h2) makes attempt
  2 reuse attempt 1's compilation;
- if the TPU backend is unusable, attempt 2 reruns degraded on CPU with a
  note; last resort emits value 0.  Always exits 0 with a parseable line.
"""

import json
import os
import subprocess
import sys
import time

TPU_CHILD_TIMEOUT_S = int(os.environ.get("GOCHUGARU_BENCH_TPU_TIMEOUT", "300"))
CPU_CHILD_TIMEOUT_S = int(os.environ.get("GOCHUGARU_BENCH_CPU_TIMEOUT", "180"))
PROBE_TIMEOUT_S = int(os.environ.get("GOCHUGARU_BENCH_PROBE_TIMEOUT", "75"))
NORTH_STAR = 10_000_000


def stage(msg: str) -> None:
    print(f"# stage[{time.strftime('%H:%M:%S')}]: {msg}", file=sys.stderr, flush=True)


def build_world(n_repos=10_000, n_users=1_000, n_teams=100, n_orgs=10, seed=11):
    import numpy as np

    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot_from_columns

    schema = """
    definition user {}
    definition team { relation member: user }
    definition org {
        relation admin: user
        relation member: user | team#member
    }
    definition repo {
        relation org: org
        relation maintainer: user | team#member
        relation reader: user
        permission admin = org->admin + maintainer
        permission read = reader + admin + org->member
    }
    """
    cs = compile_schema(parse_schema(schema))
    interner = Interner()
    rng = np.random.default_rng(seed)

    users = np.array([interner.node("user", f"u{i}") for i in range(n_users)], np.int64)
    teams = np.array([interner.node("team", f"t{i}") for i in range(n_teams)], np.int64)
    orgs = np.array([interner.node("org", f"o{i}") for i in range(n_orgs)], np.int64)
    repos = np.array([interner.node("repo", f"r{i}") for i in range(n_repos)], np.int64)

    slot = cs.slot_of_name
    member, admin, org_rel = slot["member"], slot["admin"], slot["org"]
    maintainer, reader = slot["maintainer"], slot["reader"]

    res, rel_s, subj, srel = [], [], [], []

    def add(r, rl, s, sr):
        res.append(r); rel_s.append(rl); subj.append(s); srel.append(sr)

    # team members: each team gets n_users/10 members
    per_team = max(2, n_users // 10)
    for t in teams:
        for u in rng.choice(users, per_team, replace=False):
            add(t, member, u, -1)
    # orgs: admins + team usersets + direct members
    for o in orgs:
        add(o, admin, rng.choice(users), -1)
        for t in rng.choice(teams, 2, replace=False):
            add(o, member, t, member)
        for u in rng.choice(users, 5, replace=False):
            add(o, member, u, -1)
    # repos: org edge + maintainer team + direct readers (vectorized)
    repo_orgs = rng.choice(orgs, n_repos)
    repo_teams = rng.choice(teams, n_repos)
    res.extend(repos); rel_s.extend([org_rel] * n_repos)
    subj.extend(repo_orgs); srel.extend([-1] * n_repos)
    res.extend(repos); rel_s.extend([maintainer] * n_repos)
    subj.extend(repo_teams); srel.extend([member] * n_repos)
    for k in range(2):
        res.extend(repos); rel_s.extend([reader] * n_repos)
        subj.extend(rng.choice(users, n_repos)); srel.extend([-1] * n_repos)

    snap = build_snapshot_from_columns(
        1, cs, interner,
        res=np.asarray(res, np.int64), rel=np.asarray(rel_s, np.int64),
        subj=np.asarray(subj, np.int64), srel=np.asarray(srel, np.int64),
        epoch_us=1_700_000_000_000_000,
    )
    return cs, snap, users, repos, slot


def _flat_args(engine, dsnap, snap, q_res, q_perm, q_subj):
    """Lower pre-interned query columns to the flat kernel + padded args
    (the signature lives in DeviceEngine.flat_fn_and_args)."""
    import jax.numpy as jnp

    queries, qctx = engine._columns_preamble(
        dsnap, q_res, q_perm, q_subj, None, None, None, None
    )
    got = engine.flat_fn_and_args(
        dsnap, queries, qctx,
        jnp.int32(snap.now_rel32(1_700_000_000_000_000)), q_res.shape[0],
    )
    assert got is not None
    return got


def measure_batch(engine, dsnap, snap, users, repos, slot, B, note):
    """Compile + measure one batch size; returns (result dict,
    (q_perm, args) for the repeat-harness pass).  ``value`` in the
    returned dict is the PROVISIONAL honest rate — the median
    individually-blocked dispatch (no overlap) — which run_bench
    upgrades to the repeat-harness true rate; the pipelined
    (overlapped-dispatch) rate rides as the secondary
    ``pipelined_rate`` field."""
    import numpy as np
    import jax

    rng = np.random.default_rng(5)
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B)
    q_subj = rng.choice(users, B).astype(np.int32)
    fn, args = _flat_args(engine, dsnap, snap, q_res, q_perm, q_subj)

    stage(f"compiling B={B}")
    t0 = time.time()
    out = fn(*args)
    jax.block_until_ready(out)
    # one fetch → synchronous stream from here; surface overflow/possible
    # counts so a capped world can't report fantasy throughput silently
    d, p, ovf = jax.device_get(out)
    host_work = int((p[:B] & ~d[:B]).sum() + ovf[:B].sum())
    stage(
        f"first dispatch B={B}: {time.time()-t0:.1f}s"
        f" granted={int(d[:B].sum())} host_fallback={host_work}"
    )

    # pipelined throughput: N back-to-back dispatches, blocked at the end
    stage(f"measuring pipelined rate B={B}")
    reps = 4 if B >= 100_000 else 8
    pipelined_rate = 0.0
    for _ in range(2):
        t0 = time.time()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        dt = time.time() - t0
        pipelined_rate = max(pipelined_rate, reps * B / dt)

    # p99 evaluation latency: blocked per-dispatch timings minus the fixed
    # dispatch round trip of a same-signature null program
    stage(f"measuring p99 B={B}")
    null_fn = jax.jit(
        lambda arrs, tid_map, now, qm, qctx:
        (qm[6] != 0, qm[6] != 0, qm[6] != 0)
    )
    jax.block_until_ready(null_fn(*args))

    def timed(f, reps):
        ts = []
        for _ in range(reps):
            t0 = time.time()
            jax.block_until_ready(f(*args))
            ts.append(time.time() - t0)
        return np.asarray(ts)

    # enough samples that p99 isn't just the max of a handful: scale down
    # only when each blocked dispatch is itself long
    reps = 50 if B <= 40_000 else 20
    overhead = float(np.median(timed(null_fn, 12)))
    raw = timed(fn, reps)
    lat = np.maximum(raw - overhead, 0.0) * 1000.0
    p99_ms = float(np.percentile(lat, 99))
    blocked_rate = B / float(np.median(raw))

    from benchmarks.common import roofline_columns, table_bytes

    out = {
        "metric": "rbac_2hop_bulk_check_throughput",
        "value": round(blocked_rate, 1),
        "unit": "checks/sec/chip",
        "vs_baseline": round(blocked_rate / NORTH_STAR, 4),
        "rate_basis": "blocked-dispatch",
        "pipelined_rate": round(pipelined_rate, 1),
        "p99_ms": round(p99_ms, 3),
        "batch": int(B),
        "edges": int(snap.num_edges),
        "host_fallback": host_work,
        # the HBM roofline columns next to checks/s: resident table
        # bytes per edge + gathered bytes per check (perf ledger) +
        # achieved GB/s against the MEASURED triad-microbench ceiling
        "table_bytes_per_edge": round(
            table_bytes(dsnap) / max(int(snap.num_edges), 1), 2
        ),
        **roofline_columns(blocked_rate, dsnap=dsnap),
        "platform": jax.default_backend(),
        **({"note": note} if note else {}),
    }
    return out, (q_perm, args)


def measure_small_batch(engine, dsnap, snap, users, repos, slot, note):
    """The latency-mode row: warm B=1024 pinned-kernel dispatch p99 with
    the host/H2D/kernel/D2H stage budget (engine/latency.py) — the half
    of the north-star metric (p99 < 2 ms) a 131k-item scan cannot
    measure.  Measured AND emitted through the shared
    benchmarks.common.emit_small_batch_row, so this row's shape cannot
    drift from the config-1/3/4 rows."""
    import sys

    import numpy as np
    import jax

    from benchmarks.common import emit_small_batch_row

    rng = np.random.default_rng(9)
    B = 1024
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = np.full(B, slot["read"], np.int32)
    q_subj = rng.choice(users, B).astype(np.int32)
    stage(f"measuring latency-mode small batch B={B}")
    emit_small_batch_row(
        "rbac_2hop_small_batch_p99_latency", engine, dsnap,
        q_res, q_perm, q_subj, edges=int(snap.num_edges),
        platform=jax.default_backend(),
        **({"note": note} if note else {}),
    )
    sys.stdout.flush()  # the line must survive a mid-ramp child kill


def measure_true_rate(engine, dsnap, B, q_perm, args):
    """Repeat-harness true rate (N evaluations inside ONE dispatch,
    t(2K)-t(K)) — the tunnel-amortized number the round-2 verdict
    measured by hand.  Runs AFTER the batch's headline line is already on
    stdout, so a hang here can only cost this extra figure."""
    import numpy as np

    from benchmarks.common import measured_rate_flat

    # same slot derivation as DeviceEngine.flat_fn_and_args: the harness
    # must compile the very program being benchmarked
    slots = tuple(sorted({int(s) for s in np.unique(q_perm) if s >= 0}))
    stage(f"measuring repeat-harness true rate B={B}")
    # enough loop iterations that t1 is ~100ms-class: small batches with
    # few iterations let host timing jitter swallow the t2 - t1 signal
    iters = max(16, (1 << 19) // B)
    return round(measured_rate_flat(engine, dsnap, slots, B, args, iters=iters), 1)


def run_bench(batches, world_kw, budget_s, note=None):
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/gochugaru_xla_cache_h2")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from gochugaru_tpu.engine.device import DeviceEngine

    t_start = time.time()
    stage(f"backend={jax.default_backend()}")
    cs, snap, users, repos, slot = build_world(**world_kw)
    stage(f"world built: edges={snap.num_edges}")
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    stage("prepared: closure + hash indexes on device")
    assert dsnap.flat_meta is not None

    for i, B in enumerate(batches):
        elapsed = time.time() - t_start
        if i > 0 and elapsed > budget_s * 0.55:
            stage(f"budget {elapsed:.0f}s/{budget_s}s spent; skipping B≥{B}")
            break
        result, tr_inputs = measure_batch(
            engine, dsnap, snap, users, repos, slot, B, note
        )
        # provisional line FIRST (blocked-dispatch basis): a hang in the
        # repeat harness below costs only the upgrade, never the batch's
        # salvageable result
        print(json.dumps(result), flush=True)
        if time.time() - t_start <= budget_s * 0.7:
            try:
                result["value"] = measure_true_rate(
                    engine, dsnap, B, *tr_inputs
                )
                result["vs_baseline"] = round(result["value"] / NORTH_STAR, 4)
                result["rate_basis"] = "repeat-harness"
                # the roofline columns follow the honest rate upgrade:
                # achieved GB/s is a function of the TRUE rate
                from benchmarks.common import roofline_columns

                result.update(roofline_columns(
                    result["value"],
                    bytes_per_check=result.get("bytes_per_check"),
                ))
                print(json.dumps(result), flush=True)
            except Exception as e:
                stage(f"true-rate measurement failed: {type(e).__name__}: {e}")
        else:
            stage(f"budget: keeping blocked-dispatch value for B={B}")
        if i == 0:
            # the latency-mode p99 row rides right after the first
            # (cheapest) batch: early enough to survive a short tunnel
            # window, late enough that the headline is already out
            try:
                measure_small_batch(
                    engine, dsnap, snap, users, repos, slot, note
                )
            except Exception as e:
                stage(f"small-batch latency failed: {type(e).__name__}: {e}")


def child_main(mode: str, note: str | None) -> None:
    try:
        if mode == "cpu":
            _child_body_cpu(note)
        else:
            _child_body_accel(note)
    finally:
        # --metrics rides up through the parent's metric-line relay
        from benchmarks.common import maybe_emit_metrics_snapshot

        maybe_emit_metrics_snapshot()


def _child_body_cpu(note: str | None) -> None:
    from gochugaru_tpu.utils.platform import force_cpu_platform

    force_cpu_platform()
    # SPEC world even on the CPU fallback (10k repos × 1k users,
    # ramp to the 100k-class batch): a degraded run must measure the
    # config it names, just slower — never a silently smaller graph
    run_bench(
        batches=(8_192, 32_768, 131_072),
        world_kw={},
        budget_s=CPU_CHILD_TIMEOUT_S,
        note=note or "degraded: cpu fallback",
    )


def _child_body_accel(note: str | None) -> None:
    # ramp past 131k: with the aligned-table kernel the dispatch is
    # ~6 row gathers, so bigger batches keep amortizing the tunnel
    # round trip (budget gating skips the tail on a short window)
    run_bench(
        batches=(8_192, 32_768, 131_072, 262_144),
        world_kw={},
        budget_s=TPU_CHILD_TIMEOUT_S,
        note=note,
    )


HEADLINE_METRIC = "rbac_2hop_bulk_check_throughput"


def _parse_best(stdout: str):
    """Reduce a child's stdout to one line per metric.  For the headline
    throughput metric, repeat-harness lines beat provisional
    blocked-dispatch ones (same batch emits both; the honest final value
    must win regardless of magnitude) and the best batch size wins among
    equals; secondary metrics keep their last emitted line.  Returns
    {metric: line} or None when nothing parsed."""
    by_metric = {}
    for line in (stdout or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" not in parsed or "value" not in parsed:
            continue
        m = parsed["metric"]
        if m != HEADLINE_METRIC:
            by_metric[m] = parsed
            continue
        cur = by_metric.get(m)
        def rank(ln):
            return (ln.get("rate_basis") == "repeat-harness", ln["value"])
        if cur is None or rank(parsed) > rank(cur):
            by_metric[m] = parsed
    return by_metric or None


def _run_child(mode: str, timeout_s: int, note: str | None):
    """Run one child attempt; returns (result_dict|None, failure_reason)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child", mode]
    if note:
        cmd.append(note)
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout_s)
        stdout, stderr, rc = r.stdout, r.stderr, r.returncode
        reason = None if rc == 0 else f"{mode} child rc={rc}"
    except subprocess.TimeoutExpired as e:
        # salvage the per-batch lines already emitted before the kill
        stdout = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        stderr = e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        reason = f"{mode} attempt timed out after {timeout_s}s"
    if stderr:
        sys.stderr.write(stderr)
    lines = _parse_best(stdout)
    if lines is not None:
        if reason and HEADLINE_METRIC in lines:
            best = lines[HEADLINE_METRIC]
            best.setdefault("note", "")
            best["note"] = (best["note"] + f"; partial ramp: {reason}").lstrip("; ")
        return lines, None
    if reason is None:
        reason = f"{mode} attempt produced no JSON line"
    err = (stderr or "").strip().splitlines()
    tail = err[-1][:200] if err else reason
    return None, f"{reason}: {tail}"


_PROBE_VERDICT: "list[str | None]" = []  # memoized per process

#: on-disk probe verdict cache: the subprocess probe exists to guard
#: against a HUNG TPU init, and a hung probe costs the full 75 s
#: timeout — once per PROCESS under the memo above, which standalone
#: repeat runs of bench.py re-paid every time (BENCH_r05 tail).  The
#: verdict persists here keyed by jaxlib version + TPU env, matching
#: the GOCHUGARU_BACKEND_PROBED parent-inherit path run_all.py uses.
#: GOCHUGARU_PROBE_CACHE=0 disables; the path is overridable for tests.
PROBE_CACHE_PATH = os.environ.get(
    "GOCHUGARU_PROBE_CACHE_PATH", "/tmp/gochugaru_backend_probe.json"
)


def _probe_cache_key() -> str:
    try:
        from importlib.metadata import version

        jaxlib = version("jaxlib")
    except Exception:
        jaxlib = "unknown"
    tpu_env = ",".join(
        f"{k}={os.environ.get(k, '')}"
        for k in ("TPU_NAME", "TPU_WORKER_ID", "TPU_SKIP_MDS_QUERY")
    )
    return f"jaxlib={jaxlib};{tpu_env}"


def _probe_cache_read() -> "str | None | bool":
    """The cached verdict (a reason string or None=usable), or False
    when absent/stale/disabled."""
    if os.environ.get("GOCHUGARU_PROBE_CACHE", "1") == "0":
        return False
    try:
        with open(PROBE_CACHE_PATH) as f:
            blob = json.load(f)
        if blob.get("key") != _probe_cache_key():
            return False
        return blob.get("reason", False)
    except (OSError, ValueError):
        return False


def _probe_cache_write(reason: "str | None") -> None:
    if os.environ.get("GOCHUGARU_PROBE_CACHE", "1") == "0":
        return
    try:
        tmp = PROBE_CACHE_PATH + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"key": _probe_cache_key(), "reason": reason}, f)
        os.replace(tmp, PROBE_CACHE_PATH)
    except OSError:
        pass  # cache is best-effort; next run just re-probes


def _probe_backend() -> str | None:
    """Cheap bounded liveness probe of the default (TPU) backend; returns
    a failure reason, or None when the backend is usable.

    Respects the caller's platform pins — the probe exists only to guard
    against a HUNG TPU init, so when the platform is already decided it
    is pure waste (BENCH_r05 paid a 75 s probe timeout before every
    degraded CPU stage):

    - ``JAX_PLATFORMS`` set and TPU-free → no TPU init can hang; skip
      the subprocess and go straight to the pinned platform.
    - ``JAX_PLATFORMS`` includes tpu → the user pinned it; trust it.
    - ``GOCHUGARU_FORCE_CPU=1`` / ``GOCHUGARU_BACKEND_PROBED`` (exported
      by run_all.py after ITS probe) → reuse that verdict.

    The verdict is memoized for the process so repeat stages never
    re-pay the subprocess."""
    if _PROBE_VERDICT:
        return _PROBE_VERDICT[0]

    def remember(v: "str | None") -> "str | None":
        _PROBE_VERDICT.append(v)
        return v

    plats = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if plats:
        if "tpu" in plats:
            return remember(None)
        return remember(
            f"JAX_PLATFORMS={plats} pins a TPU-free platform (probe skipped)"
        )
    if os.environ.get("GOCHUGARU_FORCE_CPU") == "1":
        return remember("GOCHUGARU_FORCE_CPU=1 (probe skipped)")
    probed = os.environ.get("GOCHUGARU_BACKEND_PROBED", "").strip().lower()
    if probed:
        return remember(
            None if probed == "tpu"
            else f"parent probe found backend={probed} (probe skipped)"
        )
    cached = _probe_cache_read()
    if cached is not False:
        return remember(
            cached if cached is None
            else f"{cached} (cached verdict, {PROBE_CACHE_PATH})"
        )
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()), jax.default_backend())"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        reason = f"backend probe timed out after {PROBE_TIMEOUT_S}s"
        _probe_cache_write(reason)
        return remember(reason)
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        reason = (
            f"backend probe failed: {tail[-1][:200] if tail else r.returncode}"
        )
        _probe_cache_write(reason)
        return remember(reason)
    _probe_cache_write(None)
    return remember(None)


def main() -> int:
    # Parent orchestrator: no jax import here, so a hung TPU backend can
    # never keep the driver-facing process from printing a parseable line.
    reason = _probe_backend()
    if reason is None:
        lines, reason = _run_child("tpu", TPU_CHILD_TIMEOUT_S, None)
    else:
        lines = None
        sys.stderr.write(f"# {reason}\n")
    if lines is None:
        sys.stderr.write(f"# {reason}; retrying degraded on cpu\n")
        lines, reason2 = _run_child(
            "cpu", CPU_CHILD_TIMEOUT_S, f"degraded cpu run ({reason})"
        )
        if lines is None:
            lines = {HEADLINE_METRIC: {
                "metric": HEADLINE_METRIC,
                "value": 0.0,
                "unit": "checks/sec/chip",
                "vs_baseline": 0.0,
                "p99_ms": 0.0,
                "batch": 0,
                "edges": 0,
                "platform": "none",
                "note": f"all attempts failed: {reason}; {reason2}",
            }}
    # headline first (drivers that read only line 1 keep working), then
    # the secondary metrics (small-batch p99 etc.)
    if HEADLINE_METRIC in lines:
        print(json.dumps(lines[HEADLINE_METRIC]))
    for m, line in lines.items():
        if m != HEADLINE_METRIC:
            print(json.dumps(line))
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        child_main(sys.argv[2], sys.argv[3] if len(sys.argv) > 3 else None)
    else:
        sys.exit(main())

#!/bin/bash
# TPU tunnel watcher (round 6). Loops until killed: probe the axon tunnel;
# if alive, harvest the window GREEDILY in priority order (VERDICT r05
# weak #1 — a window must leave with everything scripted, unattended):
#   1. config 2 (bench.py child): aligned-table kernel, all batch tiers
#      INCLUDING the latency-mode small-batch p99 row with its
#      host/H2D/kernel/D2H budget breakdown;
#   2. a jax.profiler trace dump of the aligned kernel (big-batch +
#      latency-mode loops) for offline analysis;
#   3. aligned-vs-legacy A/B on the same world — the measurement the
#      round-5 kernel rebuild was made for and never got;
#   4. the wider ladder (config 1 founders p99, config 3 docs) while
#      the window lasts.
# Each step bounded, each emitting JSON per stage so a mid-window kill
# still leaves numbers. After a run that produced a JSON line it keeps
# probing (a later window can still improve the number) but backs off to
# 15-min cycles.
# Stop with: pkill -f 'tpu_watch\.sh'
cd /root/repo || exit 1
mkdir -p tpu_attempts
log() { echo "[$(date +%H:%M:%S)] $*" >> tpu_attempts/log.txt; }

probe() {
  timeout 90 python -u -c "import jax; print(len(jax.devices()), jax.default_backend())" \
    >> tpu_attempts/log.txt 2>&1
}

SLEEP=210
attempt=0
while true; do
  attempt=$((attempt + 1))
  if probe; then
    log "probe OK — running TPU harvest ladder"
    TS=$(date +%H%M%S)
    # flight-recorder bundles: every child in this window dumps
    # anomaly-triggered incident bundles here (breaker trips, SLO
    # burns, pinned-path recompiles) — harvested next to the capture
    export GOCHUGARU_INCIDENT_DIR="tpu_attempts/incidents_${TS}"
    # priority 1: config-2 aligned kernel, all tiers + small-batch p99
    timeout 560 python bench.py --child tpu \
      > "tpu_attempts/bench_${TS}.out" 2> "tpu_attempts/bench_${TS}.err"
    log "config2 child rc=$? → tpu_attempts/bench_${TS}.out"
    if grep -q '^{' "tpu_attempts/bench_${TS}.out"; then
      touch tpu_attempts/TPU_CONTACT
      SLEEP=900
      # roofline note (perf ledger): measure the window's HBM bandwidth
      # ceiling once (cached per backend fingerprint) and dump it next
      # to the capture — every config-2 row above already carries
      # achieved_gbps/roofline_frac against this denominator, so the
      # first silicon number ships its roofline note mechanically
      mkdir -p "tpu_attempts/trace_${TS}"
      timeout 180 python -m gochugaru_tpu.utils.perf --refresh \
        > "tpu_attempts/trace_${TS}/roofline.json" 2>> tpu_attempts/log.txt
      log "roofline rc=$? → tpu_attempts/trace_${TS}/roofline.json"
      # priority 2: profiler trace of the aligned kernel
      timeout 420 python benchmarks/bench_tpu_harvest.py \
        --trace "tpu_attempts/trace_${TS}" \
        > "tpu_attempts/trace_${TS}.out" 2> "tpu_attempts/trace_${TS}.err"
      log "trace rc=$? → tpu_attempts/trace_${TS}"
      # harvest any incident bundles the window produced NEXT TO the XLA
      # capture (the request-annotated traces already land there), so a
      # mid-window anomaly ships with the profile that explains it
      if compgen -G "${GOCHUGARU_INCIDENT_DIR}/incident_*.jsonl" > /dev/null; then
        mkdir -p "tpu_attempts/trace_${TS}"
        cp "${GOCHUGARU_INCIDENT_DIR}"/incident_*.jsonl "tpu_attempts/trace_${TS}/"
        log "incident bundles copied → tpu_attempts/trace_${TS}/"
      fi
      # priority 3: aligned-vs-legacy A/B on silicon
      timeout 560 python benchmarks/bench_tpu_harvest.py --ab \
        > "tpu_attempts/ab_${TS}.out" 2> "tpu_attempts/ab_${TS}.err"
      log "aligned-vs-legacy A/B rc=$? → tpu_attempts/ab_${TS}.out"
      # priority 3.5: packed-vs-unpacked A/B (HBM-lean tables): the
      # roofline question — does the shift/mask decode hide under
      # gather latency on real silicon? — plus measured table bytes
      # (bench7 emits both layouts' true rates + bytes/check columns)
      timeout 700 python benchmarks/bench7_hbm.py --scale 0.2 \
        > "tpu_attempts/hbm_${TS}.out" 2> "tpu_attempts/hbm_${TS}.err"
      log "packed-vs-unpacked A/B rc=$? → tpu_attempts/hbm_${TS}.out"
      # priority 3.7: verdict-cache on/off A/B on silicon (bench9's
      # serve_cache_ab + serve_cache_openloop_ab rows): on the 1-core
      # CPU proxy the open-loop arm reads ~parity because the device
      # kernel hides under host Python — on TPU, where the device is
      # the bottleneck and the host core is free, the cache's 100x
      # device-row collapse should finally convert into open-loop
      # goodput (the request-path arm is the CPU-side headline)
      timeout 700 python benchmarks/bench9_serve.py --quick \
        > "tpu_attempts/cache_${TS}.out" 2> "tpu_attempts/cache_${TS}.err"
      log "verdict-cache A/B rc=$? → tpu_attempts/cache_${TS}.out"
      # priority 3.8 (low): witness-extraction on/off A/B — price the
      # decision-provenance witness plane (engine/flat.py armed kernel)
      # on real silicon with the interleaved-rep discipline, so the
      # first window also answers "does the witness select cascade hide
      # under the probe pipeline on TPU the way it does on CPU"
      timeout 300 python - > "tpu_attempts/witness_${TS}.out" \
          2> "tpu_attempts/witness_${TS}.err" <<'WEOF'
import json
import sys

import numpy as np

sys.path.insert(0, "tests")
from test_latency_path import build_rbac_world

from benchmarks.common import small_batch_latency
from gochugaru_tpu.engine.device import DeviceEngine

cs, snap, users, repos, slot = build_rbac_world()
engine = DeviceEngine(cs)
dsnap = engine.prepare(snap)
rng = np.random.default_rng(5)
B = 1024
q_res = rng.choice(repos, B).astype(np.int32)
q_perm = rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B)
q_subj = rng.choice(users, B).astype(np.int32)
lp = engine.latency_path(dsnap)
for armed in (True, False):  # pre-warm both pin sets
    lp.arm_witness(armed)
    for i in range(10):
        lp.dispatch_columns(np.roll(q_res, i), q_perm, q_subj)
lp.arm_witness(False)
r = small_batch_latency(
    engine, dsnap, q_res, q_perm, q_subj, warmup=30, reps=600,
    interleave=(lp.arm_witness, lambda: lp.arm_witness(False)),
)
import jax

print(json.dumps({
    "metric": "witness_ab_small_batch", "value": r["p50_ms_on"],
    "unit": "ms", "platform": jax.default_backend(), "batch": B,
    "p50_ms_off": r["p50_ms_off"], "p50_ms_on": r["p50_ms_on"],
    "p99_ms_off": r["p99_ms_off"], "p99_ms_on": r["p99_ms_on"],
    "delta_p50_ms": r["delta_p50_ms"],
    "note": "witness-armed vs disarmed pinned dispatch, interleaved reps",
}))
WEOF
      log "witness on/off A/B rc=$? → tpu_attempts/witness_${TS}.out"
      # priority 3.9: fused-vs-looped hop A/B (unified SpMM core): on
      # the CPU proxy the fused K-hop program pays its fixed cost
      # against ~free Python hops — on TPU, where every looped hop eats
      # a real dispatch floor, the one-dispatch fixpoint is the whole
      # bet (bench8's lookup_fused_vs_looped row: same snapshot, same
      # mixed users, spmm on vs off).  Re-dump the roofline note AFTER
      # the A/B so the fused SpMM programs the window just launched are
      # in the /perf cost ledger beside the capture.
      timeout 700 python benchmarks/bench8_lookup.py --scale 0.2 \
        > "tpu_attempts/spmm_${TS}.out" 2> "tpu_attempts/spmm_${TS}.err"
      log "fused-vs-looped A/B rc=$? → tpu_attempts/spmm_${TS}.out"
      timeout 180 python -m gochugaru_tpu.utils.perf --refresh \
        > "tpu_attempts/trace_${TS}/roofline.json" 2>> tpu_attempts/log.txt
      log "roofline (post-SpMM) rc=$? → tpu_attempts/trace_${TS}/roofline.json"
      # priority 4.0: pallas-vs-xla A/B (engine/pallas.py fused probe).
      # Interpret-mode CI only proves parity — THIS is where the
      # one-pass bytes model meets silicon: same worlds (config-2 RBAC
      # + config-3 docs at 10% scale), same column batches, interleaved
      # pallas-on/pallas-off bulk reps, one JSON row per world carrying
      # both rates + both modeled bytes/check + the VMEM residency, so
      # the first window scores the kernel without operator thought.
      timeout 700 python - > "tpu_attempts/pallas_${TS}.out" \
          2> "tpu_attempts/pallas_${TS}.err" <<'PEOF'
import json
import sys
import time

import numpy as np

sys.path.insert(0, "tests")
sys.argv = ["bench3_docs", "--scale", "0.1"]
from test_latency_path import build_rbac_world

from benchmarks import bench3_docs
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.utils import perf as _perf
from gochugaru_tpu.utils.metrics import default as _m

import jax


def bulk_rate(engine, dsnap, q_res, q_perm, q_subj, reps):
    d, p, o = engine.check_columns(dsnap, q_res, q_perm, q_subj)
    np.asarray(d)  # compile + sync
    t0 = time.perf_counter()
    for _ in range(reps):
        d, p, o = engine.check_columns(dsnap, q_res, q_perm, q_subj)
    np.asarray(d)
    return reps * q_res.shape[0] / (time.perf_counter() - t0), (d, p, o)


def ab(world_name, cs, snap, q_res, q_perm, q_subj, reps=8):
    rows = {}
    for knob in (False, True):
        eng = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=knob))
        ds = eng.prepare(snap)
        rate, out = bulk_rate(eng, ds, q_res, q_perm, q_subj, reps)
        model = _perf.pallas_bytes_model(ds)
        rows[knob] = (rate, out, model)
    (r0, o0, m0), (r1, o1, m1) = rows[False], rows[True]
    for a, b in zip(o0, o1):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            f"{world_name}: pallas answers diverged on silicon"
    xla_b = sum(r["xla"] for r in m1.values())
    fused_b = sum(r["pallas"] for r in m1.values())
    print(json.dumps({
        "metric": f"pallas_ab_{world_name}", "value": round(r1, 1),
        "unit": "checks/sec", "platform": jax.default_backend(),
        "batch": int(q_res.shape[0]), "reps": reps,
        "rate_xla": round(r0, 1), "rate_pallas": round(r1, 1),
        "speedup": round(r1 / max(r0, 1e-9), 3),
        "bytes_accessed_per_check": round(fused_b, 1),
        "bytes_accessed_per_check_xla": round(xla_b, 1),
        "vmem_resident_bytes": _m.gauge("perf.vmem_resident_bytes"),
        "note": "bitwise-asserted A/B, same world + batches",
    }), flush=True)


rng = np.random.default_rng(5)
cs, snap, users, repos, slot = build_rbac_world()
B = 100_000
ab("rbac_config2", cs, snap,
   rng.choice(repos, B).astype(np.int32),
   rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B),
   rng.choice(users, B).astype(np.int32))

cs3, snap3, users3, docs3, slot3 = bench3_docs.build_world()
ab("docs_config3", cs3, snap3,
   rng.choice(docs3, B).astype(np.int32),
   np.full(B, slot3["view"], np.int32),
   rng.choice(users3, B).astype(np.int32))
PEOF
      log "pallas-vs-xla A/B rc=$? → tpu_attempts/pallas_${TS}.out"
      # roofline note beside the capture AFTER the pallas A/B, so the
      # fused kernels the window just launched are in the cost ledger
      timeout 180 python -m gochugaru_tpu.utils.perf --refresh \
        > "tpu_attempts/trace_${TS}/roofline.json" 2>> tpu_attempts/log.txt
      log "roofline (post-pallas) rc=$? → tpu_attempts/trace_${TS}/roofline.json"
      # priority 4: the wider ladder while the window lasts
      timeout 420 python benchmarks/bench1_founders.py \
        > "tpu_attempts/b1_${TS}.out" 2> "tpu_attempts/b1_${TS}.err"
      log "config1 rc=$?"
      timeout 900 python benchmarks/bench3_docs.py \
        > "tpu_attempts/b3_${TS}.out" 2> "tpu_attempts/b3_${TS}.err"
      log "config3 rc=$?"
      # late-window incidents (bench7/b1/b3 anomalies) ride along too
      if compgen -G "${GOCHUGARU_INCIDENT_DIR}/incident_*.jsonl" > /dev/null; then
        mkdir -p "tpu_attempts/trace_${TS}"
        cp -u "${GOCHUGARU_INCIDENT_DIR}"/incident_*.jsonl "tpu_attempts/trace_${TS}/" 2>/dev/null \
          || cp "${GOCHUGARU_INCIDENT_DIR}"/incident_*.jsonl "tpu_attempts/trace_${TS}/"
        log "incident bundles (late window) copied → tpu_attempts/trace_${TS}/"
      fi
    fi
  else
    log "probe FAIL (attempt ${attempt})"
  fi
  sleep "$SLEEP"
done

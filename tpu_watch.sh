#!/bin/bash
# TPU tunnel watcher (round 4). One bounded pass: probe the axon tunnel;
# if alive, immediately run the bench TPU child (it emits a JSON line per
# batch size, so even a mid-ramp kill leaves a real number on stdout).
# Designed to be re-launched by the agent after each exit.
cd /root/repo || exit 1
mkdir -p tpu_attempts
log() { echo "[$(date +%H:%M:%S)] $*" >> tpu_attempts/log.txt; }

probe() {
  timeout 90 python -u -c "import jax; print(len(jax.devices()), jax.default_backend())" \
    >> tpu_attempts/log.txt 2>&1
}

for attempt in $(seq 1 11); do
  if probe; then
    log "probe OK — running TPU bench child"
    TS=$(date +%H%M%S)
    timeout 420 python bench.py --child tpu \
      > "tpu_attempts/bench_${TS}.out" 2> "tpu_attempts/bench_${TS}.err"
    log "bench child rc=$? → tpu_attempts/bench_${TS}.out"
    exit 0
  fi
  log "probe FAIL (attempt ${attempt})"
  [ "$attempt" != 11 ] && sleep 210
done
exit 1

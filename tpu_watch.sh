#!/bin/bash
# TPU tunnel watcher (round 5). Loops until killed: probe the axon tunnel;
# if alive, run the bench ladder — config 2 (bench.py child, aligned-table
# kernel), config 1 founders p99, config 3 docs — each bounded, each
# emitting JSON per stage so a mid-window kill still leaves numbers.
# After a run that produced a JSON line it keeps probing (a later window
# can still improve the number) but backs off to 15-min cycles.
# Stop with: pkill -f 'tpu_watch\.sh'
cd /root/repo || exit 1
mkdir -p tpu_attempts
log() { echo "[$(date +%H:%M:%S)] $*" >> tpu_attempts/log.txt; }

probe() {
  timeout 90 python -u -c "import jax; print(len(jax.devices()), jax.default_backend())" \
    >> tpu_attempts/log.txt 2>&1
}

SLEEP=210
attempt=0
while true; do
  attempt=$((attempt + 1))
  if probe; then
    log "probe OK — running TPU bench ladder"
    TS=$(date +%H%M%S)
    timeout 560 python bench.py --child tpu \
      > "tpu_attempts/bench_${TS}.out" 2> "tpu_attempts/bench_${TS}.err"
    log "config2 child rc=$? → tpu_attempts/bench_${TS}.out"
    if grep -q '^{' "tpu_attempts/bench_${TS}.out"; then
      touch tpu_attempts/TPU_CONTACT
      SLEEP=900
      # window is live: harvest more configs while it lasts
      timeout 420 python benchmarks/bench1_founders.py \
        > "tpu_attempts/b1_${TS}.out" 2> "tpu_attempts/b1_${TS}.err"
      log "config1 rc=$?"
      timeout 900 python benchmarks/bench3_docs.py \
        > "tpu_attempts/b3_${TS}.out" 2> "tpu_attempts/b3_${TS}.err"
      log "config3 rc=$?"
    fi
  else
    log "probe FAIL (attempt ${attempt})"
  fi
  sleep "$SLEEP"
done

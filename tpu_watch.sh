#!/bin/bash
# TPU tunnel watcher (round 5). Loops until killed: probe the axon tunnel;
# if alive, immediately run the bench TPU child (it emits a JSON line per
# batch size, so even a mid-ramp kill leaves a real number on stdout).
# After a run that actually produced a JSON line it keeps probing (a later
# window can still improve the number) but backs off to 15-min cycles.
# Stop with: pkill -f tpu_watch
cd /root/repo || exit 1
mkdir -p tpu_attempts
log() { echo "[$(date +%H:%M:%S)] $*" >> tpu_attempts/log.txt; }

probe() {
  timeout 90 python -u -c "import jax; print(len(jax.devices()), jax.default_backend())" \
    >> tpu_attempts/log.txt 2>&1
}

SLEEP=210
attempt=0
while true; do
  attempt=$((attempt + 1))
  if probe; then
    log "probe OK — running TPU bench child"
    TS=$(date +%H%M%S)
    timeout 420 python bench.py --child tpu \
      > "tpu_attempts/bench_${TS}.out" 2> "tpu_attempts/bench_${TS}.err"
    rc=$?
    log "bench child rc=$rc → tpu_attempts/bench_${TS}.out"
    if grep -q '^{' "tpu_attempts/bench_${TS}.out"; then
      # a real JSON line landed: signal + slow down, don't hammer the chip
      touch tpu_attempts/TPU_CONTACT
      SLEEP=900
    fi
  else
    log "probe FAIL (attempt ${attempt})"
  fi
  sleep "$SLEEP"
done

"""Continuous-batching serving front-end (gochugaru_tpu/serve/):
coalescing parity against the oracle, per-client fairness under a
zipf-heavy aggressor, deadline-aware flush vs the max-hold timer, the
no-retrace invariant across 100+ formed batches (reusing the
test_latency_path pin-reuse harness), breaker-trip re-forming onto the
batch path with zero lost/duplicated results, queue-depth shedding, the
shared cost model, and a chaos-soak round with the ``batcher.*`` fault
sites armed."""

import threading
import time

import numpy as np
import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_admission_control,
    with_host_only_evaluation,
    with_latency_mode,
    with_store,
)
from gochugaru_tpu.serve import MicroBatcher, ServeConfig
from gochugaru_tpu.utils import faults, metrics
from gochugaru_tpu.utils.admission import AdmissionConfig, CostModel
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import (
    DeadlineExceededError,
    ShedError,
    UnavailableError,
)

from tests.test_latency_path import EPOCH, build_rbac_world

CS = consistency.full()


def _store_world():
    """Store-backed RBAC world + (latency client, oracle client)."""
    c = new_tpu_evaluator(with_latency_mode())
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition org { relation admin: user  relation member: user }
    definition repo {
        relation org: org
        relation reader: user
        permission admin = org->admin
        permission read = reader + admin + org->member
    }
    """)
    rng = np.random.default_rng(7)
    txn = rel.Txn()
    for i in range(120):
        txn.touch(rel.must_from_triple(
            f"repo:r{i}", "reader", f"user:u{rng.integers(60)}"
        ))
        txn.touch(rel.must_from_triple(f"repo:r{i}", "org", f"org:o{i % 3}"))
    for o in range(3):
        txn.touch(rel.must_from_triple(f"org:o{o}", "admin", f"user:u{o}"))
        txn.touch(rel.must_from_triple(
            f"org:o{o}", "member", f"user:u{o + 10}"
        ))
    c.write(ctx, txn)
    oracle = new_tpu_evaluator(with_host_only_evaluation(), with_store(c.store))
    return c, oracle


@pytest.fixture(scope="module")
def store_world():
    return _store_world()


def _rand_checks(rng, n):
    return [
        rel.must_from_triple(
            f"repo:r{rng.integers(120)}", "read", f"user:u{rng.integers(60)}"
        )
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# parity + coalescing
# ---------------------------------------------------------------------------

def test_serve_concurrent_parity_and_coalescing(store_world):
    """Concurrent submitters through the handle answer exactly like the
    host oracle, and the batcher genuinely coalesces (fewer formed
    batches than submissions)."""
    c, oracle = store_world
    ctx = background()
    m = metrics.default
    sub0 = m.counter("serve.submissions")
    bat0 = m.counter("serve.batches")
    errors = []
    with c.with_serving() as h:
        def worker(w):
            lr = np.random.default_rng(w)
            for _ in range(8):
                qs = _rand_checks(lr, 6)
                got = h.check(ctx, *qs, client_id=w)
                want = oracle.check(ctx, CS, *qs)
                if list(got) != list(want):
                    errors.append((w, got, want))
        ts = [threading.Thread(target=worker, args=(w,)) for w in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert not errors
    subs = m.counter("serve.submissions") - sub0
    bats = m.counter("serve.batches") - bat0
    assert subs == 48
    assert 0 < bats < subs, "no coalescing happened"


def test_serve_columns_parity(store_world):
    """The columnar surface answers like the engine's own columnar
    check (definite slice) and resolves the conditional slice."""
    c, oracle = store_world
    ctx = background()
    snap = c.store.snapshot_for(CS)
    inter = snap.interner
    slot = snap.compiled.slot_of_name
    rng = np.random.default_rng(3)
    B = 80
    q_res = np.array(
        [inter.node("repo", f"r{rng.integers(120)}") for _ in range(B)],
        np.int32,
    )
    q_perm = np.full(B, slot["read"], np.int32)
    q_subj = np.array(
        [inter.node("user", f"u{rng.integers(60)}") for _ in range(B)],
        np.int32,
    )
    with c.with_serving() as h:
        got = np.asarray(h.check_columns(ctx, q_res, q_perm, q_subj))
    want = [
        oracle.check(ctx, CS, rel.must_from_triple(
            f"repo:{inter.key_of(int(q_res[i]))[1]}", "read",
            f"user:{inter.key_of(int(q_subj[i]))[1]}",
        ))[0]
        for i in range(B)
    ]
    assert got.tolist() == want


def test_serve_over_partitioned_mesh():
    """The serving handle rides the partitioned mesh client too: the
    latency path declines sharded metas, so formed batches serve on the
    owner-routed throughput path — same answers."""
    from gochugaru_tpu.client import with_mesh
    from gochugaru_tpu.parallel import make_mesh

    c = new_tpu_evaluator(with_mesh(make_mesh(1, 4), partitioned=True))
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition doc { relation reader: user  permission read = reader }
    """)
    txn = rel.Txn()
    for i in range(60):
        txn.touch(rel.must_from_triple(
            f"doc:d{i}", "reader", f"user:u{i % 9}"
        ))
    c.write(ctx, txn)
    oracle = new_tpu_evaluator(
        with_host_only_evaluation(), with_store(c.store)
    )
    lr = np.random.default_rng(17)
    qs = [rel.must_from_triple(
        f"doc:d{lr.integers(60)}", "read", f"user:u{lr.integers(9)}"
    ) for _ in range(32)]
    with c.with_serving() as h:
        got = h.check(ctx.with_timeout(120.0), *qs)
    assert list(got) == list(oracle.check(ctx, CS, *qs))


# ---------------------------------------------------------------------------
# fairness
# ---------------------------------------------------------------------------

def test_fairness_zipf_aggressor_round_robin():
    """A bulk aggressor whose queued volume alone exceeds the formed
    batch cannot starve interactive clients: round-robin formation
    admits every client's head into the batch, while plain FIFO order
    would place the interactive submissions far past the cut.

    dedup=False pins the pre-dedup raw-count formation this test's cut
    arithmetic assumes (the aggressor's zipf%97 rows are duplicate-heavy
    — with dedup on they collapse into one batch by design; the dedup
    accounting has its own tests in test_vcache.py)."""
    b = MicroBatcher(
        tiers=(256, 1024, 4096), cost=CostModel(), start=False,
        registry=metrics.Metrics(), config=ServeConfig(dedup=False),
    )
    zipf = np.random.default_rng(1).zipf(1.3, 64 * 70)
    # the aggressor queues 70 CheckMany submissions of 64 first ...
    for i in range(70):
        cols = np.asarray(zipf[i * 64:(i + 1) * 64] % 97, np.int32)
        b.submit_columns("aggressor", cols, cols, cols)
    # ... then three interactive clients queue a single check each
    for w in range(3):
        one = np.zeros(1, np.int32)
        b.submit_columns(f"interactive{w}", one, one, one)
    assert b.depth == 70 * 64 + 3
    batch = b.form_batch()  # depth ≥ top tier → flushes 'full'
    assert batch.reason == "full"
    by_client = {}
    for s in batch.subs:
        by_client.setdefault(s.client_id, 0)
        by_client[s.client_id] += 1
    # every interactive client made it into THIS batch, despite being
    # submitted after 70×64 = 4480 aggressor checks (FIFO would need
    # the cut at 4483; the batch holds ≤ 4096)
    for w in range(3):
        assert by_client.get(f"interactive{w}") == 1, by_client
    assert by_client["aggressor"] >= 1  # aggressor still progresses
    assert b.depth > 0  # its tail is deferred, not lost
    b.close()


# ---------------------------------------------------------------------------
# deadline-aware hold-back
# ---------------------------------------------------------------------------

def test_deadline_flush_beats_maxhold():
    """With a long max-hold, a deadline-bearing submission flushes when
    its budget says waiting longer would miss it — far before the
    max-hold timer."""
    reg = metrics.Metrics()
    cost = CostModel()
    cost.observe(0.01, tier=256)  # "a tier-256 dispatch costs ~10 ms"
    done = threading.Event()

    def dispatch_cols(q_res, q_perm, q_subj, latency, span):
        done.set()
        return np.zeros(q_res.shape[0], bool)

    b = MicroBatcher(
        tiers=(256, 1024, 4096), cost=cost, registry=reg,
        config=ServeConfig(hold_max_s=2.0),
        dispatch_cols=dispatch_cols,
    )
    try:
        ctx = background().with_timeout(0.25)
        t0 = time.perf_counter()
        one = np.zeros(1, np.int32)
        fut = b.submit_columns("c", one, one, one, ctx=ctx)
        out = fut.result(ctx, timeout=5.0)
        held = time.perf_counter() - t0
        assert out.shape == (1,)
        # flushed by the deadline rule, nowhere near the 2 s max-hold
        assert held < 1.0, f"held {held:.3f}s — deadline rule never fired"
        assert reg.counter("serve.flush_deadline") == 1
        assert reg.counter("serve.flush_maxhold") == 0
    finally:
        b.close()


def test_deadline_expired_in_queue_rejected():
    """A submission whose deadline passes while queued is rejected at
    formation (classified, retriable) instead of burning batch slots."""
    reg = metrics.Metrics()
    b = MicroBatcher(
        tiers=(256,), cost=CostModel(), start=False, registry=reg,
        config=ServeConfig(hold_max_s=0.001),
    )
    ctx = background().with_timeout(0.005)
    one = np.zeros(1, np.int32)
    fut = b.submit_columns("c", one, one, one, ctx=ctx)
    time.sleep(0.02)  # deadline passes while "queued"
    batch = b.form_batch()
    assert batch.total == 0
    assert fut.done()
    with pytest.raises(DeadlineExceededError):
        fut.result()
    assert reg.counter("serve.deadline_expired") == 1
    b.close()


# ---------------------------------------------------------------------------
# queue-depth shed
# ---------------------------------------------------------------------------

def test_queue_depth_shed_raises_shederror():
    reg = metrics.Metrics()
    b = MicroBatcher(
        tiers=(256,), cost=CostModel(), start=False, registry=reg,
        config=ServeConfig(queue_max=64),
    )
    cols = np.zeros(60, np.int32)
    b.submit_columns("a", cols, cols, cols)
    with pytest.raises(ShedError):
        b.submit_columns("b", cols[:8], cols[:8], cols[:8])
    assert reg.counter("serve.sheds") == 1
    # ShedError ⊂ UnavailableError: the retry envelope engages
    assert issubclass(ShedError, UnavailableError)
    b.close()


def test_close_rejects_undispatched():
    b = MicroBatcher(
        tiers=(256,), cost=CostModel(), start=False,
        registry=metrics.Metrics(),
    )
    one = np.zeros(1, np.int32)
    fut = b.submit_columns("c", one, one, one)
    b.close()
    with pytest.raises(UnavailableError):
        fut.result()


# ---------------------------------------------------------------------------
# no-retrace across formed batches (the pin-reuse harness)
# ---------------------------------------------------------------------------

def test_no_retrace_across_formed_batches():
    """100+ formed batches of varying occupancy through the pinned tier
    ladder pay ZERO XLA compiles after warmup — the continuous batcher
    inherits the latency path's no-retrace invariant by construction
    (every formed batch lands on a pinned pow2 tier shape)."""
    from gochugaru_tpu.engine.device import DeviceEngine

    cs, snap, users, repos, slot = build_rbac_world()
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    lp = engine.latency_path(dsnap)

    def dispatch_cols(q_res, q_perm, q_subj, latency, span):
        out = None
        if latency:
            out = lp.dispatch_columns(q_res, q_perm, q_subj, now_us=EPOCH,
                                      span=span)
        if out is None:
            out = engine.check_columns(dsnap, q_res, q_perm, q_subj,
                                       now_us=EPOCH)
        d, p, ovf = out
        return np.asarray(d, bool)

    reg = metrics.Metrics()
    b = MicroBatcher(
        tiers=engine.config.latency_tiers, cost=CostModel(), registry=reg,
        config=ServeConfig(hold_max_s=0.0005),
        dispatch_cols=dispatch_cols,
    )
    rng = np.random.default_rng(23)
    try:
        # warm: one dispatch per perm subset the stream will use
        for perm in ("read", "admin"):
            B = 64
            q_res = rng.choice(repos, B).astype(np.int32)
            q_perm = np.full(B, slot[perm], np.int32)
            q_subj = rng.choice(users, B).astype(np.int32)
            b.submit_columns("warm", q_res, q_perm, q_subj).result(timeout=30)
        warm_compiles = lp.compile_count
        bat0 = reg.counter("serve.batches")
        for i in range(110):
            B = int(rng.integers(1, 200))
            q_res = rng.choice(repos, B).astype(np.int32)
            perm = "read" if i % 2 else "admin"
            q_perm = np.full(B, slot[perm], np.int32)
            q_subj = rng.choice(users, B).astype(np.int32)
            got = b.submit_columns("t", q_res, q_perm, q_subj).result(
                timeout=30
            )
            if i % 37 == 0:  # spot-check the coalesced answers stay right
                d, p, ovf = engine.check_columns(
                    dsnap, q_res, q_perm, q_subj, now_us=EPOCH
                )
                assert (np.asarray(got) == np.asarray(d, bool)).all()
        formed = reg.counter("serve.batches") - bat0
        assert formed >= 100
        assert lp.compile_count == warm_compiles, (
            f"batcher retraced: {lp.compile_count - warm_compiles} extra"
            f" compiles across {formed:.0f} formed batches"
        )
    finally:
        b.close()


# ---------------------------------------------------------------------------
# breaker trip mid-queue → re-form for the batch path (satellite fix)
# ---------------------------------------------------------------------------

def test_breaker_trip_midqueue_reforms_batch_path():
    """Trip the latency-path breaker while submissions are queued: the
    batcher's futures reject with classified errors, the envelopes
    re-submit, the breaker reroutes evaluation onto the batch path, and
    formation re-tiers (serve.reformed_batchpath) — with every answer
    still oracle-correct and no result lost or duplicated (a double
    future resolution raises by construction)."""
    c, oracle = (
        new_tpu_evaluator(
            with_latency_mode(),
            with_admission_control(AdmissionConfig(
                breaker_threshold=2, breaker_cooldown_s=120.0,
            )),
        ),
        None,
    )
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition doc { relation reader: user  permission read = reader }
    """)
    txn = rel.Txn()
    for i in range(40):
        txn.touch(rel.must_from_triple(f"doc:d{i}", "reader", f"user:u{i % 7}"))
    c.write(ctx, txn)
    oracle = new_tpu_evaluator(with_host_only_evaluation(), with_store(c.store))

    m = metrics.default
    lat0 = m.counter("latency.dispatches")
    results = {}
    errors = []
    with c.with_serving() as h:
        # wave 1 under an armed latency fault: enough consecutive
        # failures to trip threshold=2 while requests are queued
        with faults.default.armed("latency.dispatch", times=4):
            def worker(w):
                lr = np.random.default_rng(w)
                for j in range(6):
                    qs = [rel.must_from_triple(
                        f"doc:d{lr.integers(40)}", "read",
                        f"user:u{lr.integers(7)}",
                    ) for _ in range(3)]
                    try:
                        got = h.check(
                            ctx.with_timeout(30.0), *qs, client_id=w
                        )
                        results[(w, j)] = (qs, got)
                    except Exception as e:  # pragma: no cover
                        errors.append((w, j, e))
            ts = [threading.Thread(target=worker, args=(w,))
                  for w in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        assert not errors
        assert c._admission.breaker.state != 0, "breaker never tripped"
        # wave 2 with the breaker OPEN (120 s cooldown): formation must
        # re-tier for the batch path, and the pinned latency shapes
        # must NOT be replayed
        lat_open0 = m.counter("latency.dispatches")
        reform0 = m.counter("serve.reformed_batchpath")
        qs = _rand_docs_checks(12)
        got = h.check(ctx.with_timeout(30.0), *qs, client_id="wave2")
        results[("wave2", 0)] = (qs, got)
        assert m.counter("latency.dispatches") == lat_open0, (
            "pinned-tier shapes were replayed while the breaker was open"
        )
        assert m.counter("serve.reformed_batchpath") > reform0
    # zero lost: every submitted wave answered; zero wrong: oracle parity
    assert len(results) == 4 * 6 + 1
    for (w, j), (qs, got) in results.items():
        want = oracle.check(ctx, CS, *qs)
        assert list(got) == list(want), (w, j)
    assert m.counter("breaker.trips") >= 1


def _rand_docs_checks(n, seed=99):
    lr = np.random.default_rng(seed)
    return [rel.must_from_triple(
        f"doc:d{lr.integers(40)}", "read", f"user:u{lr.integers(7)}"
    ) for _ in range(n)]


# ---------------------------------------------------------------------------
# chaos soak with batcher.* sites armed
# ---------------------------------------------------------------------------

def test_chaos_soak_batcher_sites(store_world):
    """A soak round with ``batcher.form`` + ``batcher.dispatch`` +
    ``latency.dispatch`` armed at seeded probabilities: every coalesced
    answer still matches the oracle, nothing hangs, nothing is lost —
    form faults leave the queue intact, dispatch faults reject onto the
    submitters' retry envelopes."""
    c, oracle = store_world
    ctx = background()
    m = metrics.default
    inj0 = m.counter("faults.injected")
    errors = []
    with c.with_serving() as h:
        with faults.default.armed("batcher.form", probability=0.3,
                                  times=6, seed=101), \
             faults.default.armed("batcher.dispatch", probability=0.3,
                                  times=6, seed=102), \
             faults.default.armed("latency.dispatch", probability=0.15,
                                  times=4, seed=103):
            def worker(w):
                lr = np.random.default_rng(200 + w)
                for _ in range(8):
                    qs = _rand_checks(lr, 4)
                    got = h.check(
                        ctx.with_timeout(30.0), *qs, client_id=w
                    )
                    want = oracle.check(ctx, CS, *qs)
                    if list(got) != list(want):
                        errors.append((w, got, want))
            ts = [threading.Thread(target=worker, args=(w,))
                  for w in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
    assert not errors
    assert m.counter("faults.injected") > inj0, "chaos round injected nothing"


# ---------------------------------------------------------------------------
# shared cost model (satellite fix) + histogram export
# ---------------------------------------------------------------------------

def test_cost_model_per_tier_shared():
    cm = CostModel(floor_s=0.0)
    assert not cm.has_samples()
    assert cm.expected_s() == 0.0
    cm.observe(0.010, tier=256)
    cm.observe(0.030, tier=1024)
    # tier-specific estimates; unseen tier falls back to the overall
    assert cm.expected_s(256) == pytest.approx(0.010)
    assert cm.expected_s(1024) == pytest.approx(0.030)
    assert cm.expected_s(4096) == cm.expected_s()
    overall = cm.expected_s()
    t256 = cm.expected_s(256)
    cm.decay()
    # decay targets the channel the tier-less shed read (here the
    # cheapest tier, 256) and leaves other tier estimates alone — the
    # serving hold-back must not learn that 1024 dispatches are free
    # from repeated caller-formed sheds
    assert cm.expected_s() == pytest.approx(overall / 2)
    assert cm.expected_s(256) == pytest.approx(t256 / 2)
    assert cm.expected_s(1024) == pytest.approx(0.030)
    # with an overall sample present, decay halves ONLY that channel
    cm3 = CostModel()
    cm3.observe(0.004)
    cm3.observe(0.020, tier=1024)
    cm3.decay()
    assert cm3.expected_s() == pytest.approx(0.002)
    assert cm3.expected_s(1024) == pytest.approx(0.020)
    # floor applies to every readout
    cm2 = CostModel(floor_s=0.5)
    cm2.observe(0.001, tier=256)
    assert cm2.expected_s(256) == 0.5


def test_serving_handle_shares_admission_cost_model(store_world):
    """The batcher's hold-back and the client's deadline shed read the
    SAME CostModel object — no duplicated EWMA (the satellite's whole
    point)."""
    c, _oracle = store_world
    h = c.with_serving()
    try:
        assert h.batcher._cost is c._admission.cost
        # a serving dispatch feeds the per-tier estimate the deadline
        # shed reads through expected_cost_s
        ctx = background()
        h.check(ctx, rel.must_from_triple("repo:r0", "read", "user:u0"))
        assert c._admission.cost.has_samples()
        assert c._admission.expected_cost_s(256) > 0.0
    finally:
        h.close()


def test_serving_handle_enforces_overlap_required():
    """with_overlap_required applies to the serving surface too — the
    handle must not drop the guard the client was configured with."""
    from gochugaru_tpu.client import with_overlap_required
    from gochugaru_tpu.consistency import with_overlap_key
    from gochugaru_tpu.utils.errors import OverlapKeyMissingError

    c = new_tpu_evaluator(with_overlap_required())
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition doc { relation reader: user  permission read = reader }
    """)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:d", "reader", "user:u"))
    c.write(ctx, txn)
    r = rel.must_from_triple("doc:d", "read", "user:u")
    with c.with_serving() as h:
        with pytest.raises(OverlapKeyMissingError):
            h.check(ctx, r)
        with pytest.raises(OverlapKeyMissingError):
            h.submit(ctx, r)
        assert h.check(with_overlap_key(ctx, "k"), r) == [True]


def test_tiered_costs_do_not_inflate_tierless_estimate():
    """Whole-batch serving costs (tier-tagged) must not inflate the
    tier-less estimate the deadline shed reads — a hot serving pool of
    expensive 4096-tier batches would otherwise spuriously shed every
    small deadline-bearing direct check."""
    cm = CostModel()
    cm.observe(0.001)              # small caller-formed dispatches
    for _ in range(20):
        cm.observe(0.050, tier=4096)   # hot serving traffic
    assert cm.expected_s() == pytest.approx(0.001)
    assert cm.expected_s(4096) == pytest.approx(0.050)
    # serve-only process (no tier-less samples): the shed estimate is
    # the CHEAPEST tier, not the priciest
    cm2 = CostModel()
    cm2.observe(0.050, tier=4096)
    cm2.observe(0.002, tier=256)
    assert cm2.expected_s() == pytest.approx(0.002)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_dispatcher_death_settles_futures_and_closes():
    """A BaseException escaping dispatch (the emergency path) must not
    strand its batch's futures or leave later submitters hanging: the
    in-flight batch rejects in the settle backstop and the batcher
    closes itself."""
    reg = metrics.Metrics()
    calls = []

    def dispatch_cols(q_res, q_perm, q_subj, latency, span):
        calls.append(1)
        raise SystemExit("simulated dispatcher death")

    b = MicroBatcher(
        tiers=(256,), cost=CostModel(), registry=reg,
        config=ServeConfig(hold_max_s=0.0005),
        dispatch_cols=dispatch_cols,
    )
    one = np.zeros(1, np.int32)
    fut = b.submit_columns("c", one, one, one)
    with pytest.raises(UnavailableError):
        fut.result(timeout=10.0)
    # the emergency close lands asynchronously; new submissions are
    # refused once it does
    deadline = time.perf_counter() + 10.0
    while time.perf_counter() < deadline:
        try:
            f2 = b.submit_columns("c", one, one, one)
        except UnavailableError:
            break  # closed
        try:
            f2.result(timeout=10.0)
        except UnavailableError:
            pass
        time.sleep(0.01)
    else:
        pytest.fail("batcher never closed after dispatcher death")
    assert reg.counter("serve.thread_crashes") >= 1


def test_bulk_item_error_slices_per_submission():
    """A batch-relative BulkCheckItemError from the evaluation slices
    back onto submissions: earlier ones resolve from the partial
    results, the failing one gets a SUBMISSION-relative error with only
    its own verdicts, later ones reject retriable (their envelopes
    re-submit) — no cross-submitter verdict leakage, no out-of-range
    index."""
    from gochugaru_tpu.utils.errors import BulkCheckItemError

    def dispatch_rels(rels, latency, span):
        # item 6 (0-based) fails; verdicts 0..5 were accumulated
        raise BulkCheckItemError(6, [True] * 6, ValueError("bad caveat"))

    b = MicroBatcher(
        tiers=(256,), cost=CostModel(), start=False,
        registry=metrics.Metrics(), dispatch_rels=dispatch_rels,
    )
    r = rel.must_from_triple("doc:d", "read", "user:u")
    fa = b.submit_rels("A", [r] * 4)   # fully evaluated
    fb = b.submit_rels("B", [r] * 4)   # fails at its item 2
    fc = b.submit_rels("C", [r] * 4)   # never evaluated
    batch = b.form_batch()
    assert batch.total == 12
    b.dispatch_batch(batch)
    assert fa.result() == [True] * 4
    with pytest.raises(BulkCheckItemError) as ei:
        fb.result()
    assert ei.value.index == 2            # submission-relative
    assert ei.value.results == [True] * 2  # B's own verdicts only
    with pytest.raises(UnavailableError):
        fc.result()                        # retriable → re-submits
    b.close()


def test_bulk_item_error_cols_ndarray_slicing():
    """The columnar evaluation raises BulkCheckItemError with ndarray
    partial results (client._evaluate_columns per-item isolation) — the
    batcher's slicing handles that shape identically to the rels path's
    list results."""
    from gochugaru_tpu.utils.errors import BulkCheckItemError

    def dispatch_cols(q_res, q_perm, q_subj, latency, span):
        raise BulkCheckItemError(
            6, np.ones(6, bool), ValueError("bad item")
        )

    b = MicroBatcher(
        tiers=(256,), cost=CostModel(), start=False,
        registry=metrics.Metrics(), dispatch_cols=dispatch_cols,
    )
    four = np.zeros(4, np.int32)
    fa = b.submit_columns("A", four, four, four)
    fb = b.submit_columns("B", four, four, four)
    fc = b.submit_columns("C", four, four, four)
    b.dispatch_batch(b.form_batch())
    assert np.asarray(fa.result()).tolist() == [True] * 4
    with pytest.raises(BulkCheckItemError) as ei:
        fb.result()
    assert ei.value.index == 2
    assert np.asarray(ei.value.results).tolist() == [True, True]
    with pytest.raises(UnavailableError):
        fc.result()
    b.close()


def test_batchpath_costs_tagged_not_tierless():
    """Breaker-open (batch-path) dispatch costs tag the cost model with
    the batch's target cap, never the tier-less channel the deadline
    shed reads."""
    from gochugaru_tpu.utils.admission import CircuitBreaker

    cm = CostModel()
    br = CircuitBreaker(1, 1000.0, registry=metrics.Metrics())
    br.record_failure()  # trips OPEN
    b = MicroBatcher(
        tiers=(256,), cost=cm, breaker=br, start=False,
        registry=metrics.Metrics(),
        config=ServeConfig(batch_path_max=512),
        dispatch_cols=lambda q_res, q_perm, q_subj, latency, span:
            np.zeros(q_res.shape[0], bool),
    )
    one = np.zeros(8, np.int32)
    fut = b.submit_columns("c", one, one, one)
    batch = b.form_batch()
    assert batch.tier is None and batch.target == 512  # re-tiered
    b.dispatch_batch(batch)
    fut.result()
    assert cm.expected_s() == 0.0 or not cm.has_samples() or (
        cm.expected_s(512) > 0.0
    )
    # the tier-less overall channel stayed empty; the cost landed on
    # the 512 cap key
    assert cm.expected_s(512) > 0.0
    assert cm.expected_s(99999) == cm.expected_s(512)  # min-tier fallback
    b.close()


def test_metrics_histogram_and_prometheus_render():
    """The fixed-bucket histogram counts correctly (inclusive uppers,
    +Inf overflow) and renders as a Prometheus histogram with
    cumulative le buckets."""
    from gochugaru_tpu.utils.telemetry import render_prometheus

    reg = metrics.Metrics()
    for v in (1, 64, 64, 200, 256, 5000):
        reg.observe_hist("serve.batch_fill", v, (64, 256, 1024))
    hs = reg.hist_snapshot()
    buckets, counts, n, total, exemplars = hs["serve.batch_fill"]
    assert buckets == (64.0, 256.0, 1024.0)
    assert counts == [3, 2, 0, 1]  # le64: 1,64,64; le256: 200,256; +Inf: 5000
    assert n == 6 and total == pytest.approx(5585.0)
    assert exemplars == [None] * 4  # no trace ids recorded yet
    # exemplar recording: the LAST trace id per bucket, value + stamp
    reg.observe_hist("serve.request_latency", 40, (64, 256), trace_id="t-a")
    reg.observe_hist("serve.request_latency", 41, (64, 256), trace_id="t-b")
    ex = reg.hist_snapshot()["serve.request_latency"][4]
    assert ex[0][0] == "t-b" and ex[0][1] == 41.0 and ex[0][2] > 0
    assert ex[1:] == [None] * 2
    snap = reg.snapshot()
    assert snap["serve.batch_fill.le_64"] == 3
    assert snap["serve.batch_fill.le_256"] == 5  # cumulative
    assert snap["serve.batch_fill.count"] == 6
    text = render_prometheus(reg)
    assert "# TYPE gochugaru_serve_batch_fill histogram" in text
    assert 'gochugaru_serve_batch_fill_bucket{le="256"} 5' in text
    assert 'gochugaru_serve_batch_fill_bucket{le="+Inf"} 6' in text
    assert "gochugaru_serve_batch_fill_count 6" in text

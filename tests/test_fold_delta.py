"""Incremental fold maintenance (engine/fold.py fold_delta_update).

Contract: on a folded world, a Watch-delta chain KEEPS answering folded
permissions from the pf probe pair — base hits at dirty resources are
voided and replacement rows ride the dl_pf* overlays — and every check
stays EXACTLY equal to a full prepare of the same revision.  Conditions
the subset recompute can't keep sound (self-recursive tupleset edits,
eligibility flips, hot-ancestor dirty sets) must fall back to a full
prepare, never to wrong answers.  Reference behavior being reproduced:
Watch-driven incremental re-index over CheckBulkPermissions semantics
(/root/reference/client/client.go:364-413, :238-266).
"""

import random

import numpy as np
import pytest

from gochugaru_tpu import rel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.delta import apply_delta
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot

NOW = 1_700_000_000_000_000

DOCS = """
definition user {}
definition group { relation member: user | group#member }
definition folder {
    relation parent: folder
    relation viewer: user | group#member
    permission view = viewer + parent->view
}
definition document {
    relation folder: folder
    relation viewer: user | group#member
    permission view = viewer + folder->view
}
"""


def _docs_rels(rng: random.Random):
    rels = []
    for i in range(6):
        if i % 3 != 2:
            rels.append(rel.must_from_tuple(
                f"group:g{i}#member", f"group:g{i+1}#member"
            ))
        for u in rng.sample(range(20), 2):
            rels.append(rel.must_from_tuple(f"group:g{i}#member", f"user:u{u}"))
    for i in range(1, 12):
        rels.append(rel.must_from_tuple(
            f"folder:f{i}#parent", f"folder:f{(i-1)//3}"
        ))
    for i in range(12):
        rels.append(rel.must_from_tuple(
            f"folder:f{i}#viewer",
            f"user:u{rng.randrange(20)}" if i % 2
            else f"group:g{rng.randrange(6)}#member",
        ))
    # a couple of expiring rows so the base layouts carry exp columns
    # (delta rows with gates a base view lacks bail by design)
    import datetime as _dt

    exp = _dt.datetime.fromtimestamp(
        (NOW + 7_200_000_000) / 1e6, _dt.timezone.utc
    )
    rels.append(rel.must_from_triple(
        "document:d0", "viewer", "user:u0"
    ).with_expiration(exp))
    rels.append(rel.must_from_triple(
        "folder:f0", "viewer", "user:u1"
    ).with_expiration(exp))
    for d in range(30):
        rels.append(rel.must_from_tuple(
            f"document:d{d}#folder", f"folder:f{rng.randrange(12)}"
        ))
        if d % 3 == 0:
            rels.append(rel.must_from_tuple(
                f"document:d{d}#viewer", f"group:g{rng.randrange(6)}#member"
            ))
        if d % 4 == 0:
            rels.append(rel.must_from_tuple(
                f"document:d{d}#viewer", f"user:u{rng.randrange(20)}"
            ))
    return rels


def _prep(seed=5, **cfg):
    rng = random.Random(seed)
    rels = _docs_rels(rng)
    cs = compile_schema(parse_schema(DOCS))
    interner = Interner()
    snap = build_snapshot(1, cs, interner, rels, epoch_us=NOW)
    cfg.setdefault("flat_recursion", 3)
    cfg.setdefault("flat_max_width", 32)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs, **cfg))
    dsnap = engine.prepare(snap)
    assert dsnap.flat_meta is not None and dsnap.flat_meta.fold_pairs
    assert dsnap.fold_state is not None
    return rng, rels, cs, interner, snap, engine, dsnap


def _checks(rng: random.Random, n=60):
    out = [
        rel.must_from_triple(
            f"document:d{rng.randrange(30)}", "view", f"user:u{rng.randrange(20)}"
        )
        for _ in range(n)
    ]
    out += [
        rel.must_from_triple(
            f"folder:f{rng.randrange(12)}", "view", f"user:u{rng.randrange(20)}"
        )
        for _ in range(n // 2)
    ]
    return out


def _assert_parity(engine, ds_inc, ds_full, checks):
    di, pi, oi = engine.check_batch(ds_inc, checks, now_us=NOW)
    df, pf, of = engine.check_batch(ds_full, checks, now_us=NOW)
    for i, q in enumerate(checks):
        assert bool(di[i]) == bool(df[i]), (
            f"definite differs for {q}: inc={di[i]} full={df[i]}"
        )
        assert bool(pi[i]) == bool(pf[i]), (
            f"possible differs for {q}: inc={pi[i]} full={pf[i]}"
        )
        assert bool(oi[i]) == bool(of[i]), f"overflow differs for {q}"


def _assert_sound_vs_full(engine, ds_inc, ds_full, checks):
    """Downgraded (pf_off / walked) snapshots may leave more queries in
    the possible/host-fallback band than the folded full prepare — but
    they must never DECIDE differently: definite never over-claims,
    possible never under-claims, and queries both sides decide agree."""
    di, pi, oi = engine.check_batch(ds_inc, checks, now_us=NOW)
    df, pf, of = engine.check_batch(ds_full, checks, now_us=NOW)
    for i, q in enumerate(checks):
        assert not (bool(di[i]) and not bool(pf[i])), f"inc over-claims {q}"
        assert not (bool(df[i]) and not bool(pi[i])), f"inc under-claims {q}"
        inc_decided = bool(di[i]) == bool(pi[i]) and not bool(oi[i])
        full_decided = bool(df[i]) == bool(pf[i]) and not bool(of[i])
        if inc_decided and full_decided:
            assert bool(di[i]) == bool(df[i]), f"decided answers differ {q}"


def test_fold_maintained_across_40_revision_chain():
    """40 revisions of adds/tombstones on folded leaves and (non-self)
    arrows: every revision stays on the incremental path (meta.delta
    present ⇒ folded slots answered by pf + dl_pf* overlay, since
    fold_pairs stays set and the kernel no longer reverts to the walk)
    and matches a full prepare exactly."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=5)
    py = random.Random(17)
    live_viewers = [
        r for r in rels
        if r.resource_relation == "viewer" and r.subject_type == "user"
    ]
    live_arrows = [r for r in rels if r.resource_relation == "folder"]
    saw_dirty = saw_ovl = 0
    for revision in range(2, 42):
        adds, deletes = [], []
        kind = revision % 5
        if kind == 0:  # direct viewer add (new node too)
            adds.append(rel.must_from_triple(
                f"document:d{py.randrange(30)}", "viewer",
                f"user:nu{revision}",
            ))
        elif kind == 1:  # userset viewer add on folder (lifts to docs)
            adds.append(rel.must_from_tuple(
                f"folder:f{py.randrange(12)}#viewer",
                f"group:g{py.randrange(6)}#member",
            ))
        elif kind == 2 and live_viewers:  # tombstone a base viewer row
            deletes.append(live_viewers.pop(py.randrange(len(live_viewers))))
        elif kind == 3 and live_arrows:  # retarget a doc→folder arrow
            old = live_arrows.pop(py.randrange(len(live_arrows)))
            deletes.append(old)
            repl = rel.must_from_tuple(
                f"document:{old.resource_id}#folder",
                f"folder:f{py.randrange(12)}",
            )
            adds.append(repl)
            live_arrows.append(repl)
        else:  # expiring direct viewer add
            import datetime as _dt

            exp = _dt.datetime.fromtimestamp(
                (NOW + 3_600_000_000) / 1e6, _dt.timezone.utc
            )
            adds.append(rel.must_from_triple(
                f"document:d{py.randrange(30)}", "viewer",
                f"user:u{py.randrange(20)}",
            ).with_expiration(exp))
        snap = apply_delta(snap, revision, adds, deletes, interner=interner)
        ds_inc = engine.prepare(snap, prev=dsnap)
        assert ds_inc.flat_meta.delta is not None, f"rev {revision} fell back"
        assert ds_inc.flat_meta.fold_pairs, "fold must stay armed"
        dm = ds_inc.flat_meta.delta
        saw_dirty += bool(dm.pf_dirty)
        saw_ovl += bool(dm.pf_ovl_e or dm.pf_ovl_u)
        ds_full = engine.prepare(snap)
        checks = _checks(py) + [
            rel.must_from_triple(
                f"document:{a.resource_id}" if a.resource_type == "document"
                else f"folder:{a.resource_id}",
                "view", f"user:nu{revision}",
            )
            for a in adds
        ]
        _assert_parity(engine, ds_inc, ds_full, checks)
        dsnap = ds_inc  # chain
    assert saw_dirty >= 30, "fold maintenance should have run"
    assert saw_ovl >= 20, "overlay rows should have shipped"


def test_fold_delta_deletion_and_restore_exact():
    """Deleting a folder's viewer revokes folded access at the documents
    under it; re-adding restores it — both through the overlay, chained."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=7)
    target = next(
        r for r in rels
        if r.resource_type == "folder" and r.resource_relation == "viewer"
        and r.subject_type == "user"
    )
    probe = [
        rel.must_from_triple(
            f"document:d{d}", "view", f"{target.subject_type}:{target.subject_id}"
        )
        for d in range(30)
    ] + [rel.must_from_triple(
        f"folder:{target.resource_id}", "view",
        f"{target.subject_type}:{target.subject_id}",
    )]
    snap2 = apply_delta(snap, 2, [], [target], interner=interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    assert ds2.flat_meta.delta is not None and ds2.flat_meta.delta.pf_dirty
    _assert_parity(engine, ds2, engine.prepare(snap2), probe)
    snap3 = apply_delta(snap2, 3, [target], [], interner=interner)
    ds3 = engine.prepare(snap3, prev=ds2)
    assert ds3.flat_meta.delta is not None
    ds3_full = engine.prepare(snap3)
    _assert_parity(engine, ds3, ds3_full, probe)
    # restored world answers like the original base
    d0, p0, _ = engine.check_batch(dsnap, probe, now_us=NOW)
    d3, p3, _ = engine.check_batch(ds3, probe, now_us=NOW)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d3))
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p3))


def test_fold_delta_self_ts_edit_declines_fold():
    """Edits to a self-recursive tupleset (folder.parent) shift the
    ancestor closure: fold maintenance must decline — either the rc bail
    forces a full prepare (flattened hierarchies) or the chain stays
    incremental with folded pairs DOWNGRADED to their walked programs
    (sticky pf_off).  Never answers from stale fold tables."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=9)
    adds = [rel.must_from_tuple("folder:f11#parent", "folder:f2")]
    snap2 = apply_delta(snap, 2, adds, [], interner=interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    if ds2.flat_meta.delta is not None:
        assert ds2.flat_meta.delta.pf_off  # fold declined, walk answers
        _assert_sound_vs_full(
            engine, ds2, engine.prepare(snap2), _checks(random.Random(1))
        )
    else:
        assert ds2.fold_state is not None  # full prepare re-armed the fold
        _assert_parity(
            engine, ds2, engine.prepare(snap2), _checks(random.Random(1))
        )


def test_fold_delta_caveated_userset_row_falls_back():
    """A caveated userset viewer row flips the leaf's fold eligibility —
    the maintenance path must decline rather than fold an ungateable row."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=11)
    caveated = parse_schema("""
    caveat tier(min int) { min > 3 }
    """ + DOCS.replace(
        "relation viewer: user | group#member",
        "relation viewer: user | group#member | user with tier",
        2,
    ))
    cs2 = compile_schema(caveated)
    interner2 = Interner()
    base = _docs_rels(random.Random(5))
    snap = build_snapshot(1, cs2, interner2, base, epoch_us=NOW)
    engine2 = DeviceEngine(cs2, EngineConfig.for_schema(
        cs2, flat_recursion=3, flat_max_width=32
    ))
    ds = engine2.prepare(snap)
    if not (ds.flat_meta and ds.flat_meta.fold_pairs):
        pytest.skip("caveated schema variant did not fold")
    adds = [rel.must_from_tuple(
        "document:d3#viewer", "group:g1#member"
    ).with_caveat("tier", {"min": 5})]
    snap2 = apply_delta(snap, 2, adds, [], interner=interner2)
    ds2 = engine2.prepare(snap2, prev=ds)
    checks = [
        rel.must_from_triple(f"document:d3", "view", f"user:u{u}")
        for u in range(20)
    ]
    _assert_parity(engine2, ds2, engine2.prepare(snap2), checks)


def test_fold_delta_dirty_cap_downgrades_to_walk():
    """A dirty-cap of zero declines every fold-touching delta: the chain
    stays INCREMENTAL but downgrades folded pairs to their walked
    programs (sticky pf_off) — never a full O(E) rebuild, never wrong
    answers.  The downgrade must persist across later revisions."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(
        seed=13, flat_fold_delta_dirty_cap=0
    )
    adds = [rel.must_from_triple("document:d1", "viewer", "user:u1")]
    snap2 = apply_delta(snap, 2, adds, [], interner=interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    assert ds2.flat_meta.delta is not None  # still incremental
    assert ds2.flat_meta.delta.pf_off  # ... but folded pairs walk
    _assert_sound_vs_full(
        engine, ds2, engine.prepare(snap2), _checks(random.Random(2))
    )
    # sticky: the next revision stays downgraded without re-attempting
    snap3 = apply_delta(
        snap2, 3,
        [rel.must_from_triple("document:d2", "viewer", "user:u2")], [],
        interner=interner,
    )
    ds3 = engine.prepare(snap3, prev=ds2)
    assert ds3.flat_meta.delta is not None and ds3.flat_meta.delta.pf_off
    _assert_sound_vs_full(
        engine, ds3, engine.prepare(snap3), _checks(random.Random(3))
    )


# ---------------------------------------------------------------------------
# membership deltas: incremental closure maintenance keeps the chain alive
# ---------------------------------------------------------------------------


def _member_rows(rels):
    return [
        r for r in rels
        if r.resource_type == "group" and r.subject_type == "user"
        and not r.has_expiration() and not r.caveat_name
    ]


def test_membership_delta_stays_incremental_and_fold_armed():
    """Member-edge writes (the closure's top bail class) now ride the
    incremental path: the flattened closure advances in place
    (store/closure.py advance_closure), the fold stays armed (its pf_u
    side is closure-independent), and answers match a full prepare
    exactly — adds, deletes, and nested-group (mp) edges alike."""
    from gochugaru_tpu.utils import metrics

    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=21)
    py = random.Random(31)
    live_members = _member_rows(rels)
    rebuilds0 = metrics.default.counter("closure.rebuilds")
    for revision in range(2, 14):
        adds, deletes = [], []
        kind = revision % 4
        if kind == 0:  # new user into a group (fresh node)
            adds.append(rel.must_from_tuple(
                f"group:g{py.randrange(6)}#member", f"user:mnu{revision}"
            ))
        elif kind == 1:  # existing user into another group
            adds.append(rel.must_from_tuple(
                f"group:g{py.randrange(6)}#member",
                f"user:u{py.randrange(20)}",
            ))
        elif kind == 2 and live_members:  # remove a member edge
            deletes.append(live_members.pop(py.randrange(len(live_members))))
        else:  # nested-group (mp) edge add
            adds.append(rel.must_from_tuple(
                f"group:g{py.randrange(6)}#member",
                f"group:g{py.randrange(6)}#member",
            ))
        snap = apply_delta(snap, revision, adds, deletes, interner=interner)
        ds_inc = engine.prepare(snap, prev=dsnap)
        assert ds_inc.flat_meta.delta is not None, f"rev {revision} fell back"
        assert ds_inc.flat_meta.fold_pairs, "fold must stay armed"
        assert ds_inc.closure_state is not None
        ds_full = engine.prepare(snap)
        checks = _checks(py)
        for a in adds:
            if a.subject_type == "user":
                checks += [
                    rel.must_from_triple(
                        f"document:d{d}", "view",
                        f"user:{a.subject_id}",
                    )
                    for d in range(0, 30, 3)
                ]
        _assert_parity(engine, ds_inc, ds_full, checks)
        dsnap = ds_inc  # chain
    assert metrics.default.counter("closure.rebuilds") - rebuilds0 > 0, (
        "the parity full-prepares above should count as rebuilds"
    )


def test_membership_delta_soak_30_rounds_zero_rebuilds():
    """The acceptance soak: 30 consecutive member-edge write rounds on a
    folded world advance the closure with closure.rebuilds == 0 — every
    round incremental, every fresh edge immediately visible."""
    from gochugaru_tpu.utils import metrics

    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=23)
    py = random.Random(41)
    live_members = _member_rows(rels)
    rebuilds0 = metrics.default.counter("closure.rebuilds")
    applies0 = metrics.default.counter("closure.delta_applies")
    for revision in range(2, 32):
        # one fresh user + two existing per round: fresh nodes must stay
        # inside the base radix's 2× headroom (outgrowing it is a
        # by-design repack/rebuild, not what this soak measures)
        adds = [rel.must_from_tuple(
            f"group:g{py.randrange(6)}#member", f"user:soak{revision}"
        )] + [
            rel.must_from_tuple(
                f"group:g{py.randrange(6)}#member",
                f"user:u{py.randrange(20)}",
            )
            for _ in range(2)
        ]
        deletes = []
        if live_members and revision % 3 == 0:
            deletes.append(live_members.pop(py.randrange(len(live_members))))
        snap = apply_delta(snap, revision, adds, deletes, interner=interner)
        dsnap = engine.prepare(snap, prev=dsnap)
        assert dsnap.flat_meta.delta is not None, f"rev {revision} fell back"
        # freshness: a user just added to a group must see every document
        # whose folder chain grants that group — probe one group viewer
        d, p, ovf = engine.check_batch(dsnap, [rel.must_from_tuple(
            f"group:{adds[0].resource_id}#member",
            f"user:soak{revision}",
        )], now_us=NOW)
        # (direct member probe: definite via the delta e-level + closure)
        assert bool(d[0]), f"rev {revision}: fresh member edge invisible"
    assert metrics.default.counter("closure.rebuilds") == rebuilds0, (
        "member-edge soak must not rebuild the closure"
    )
    assert (
        metrics.default.counter("closure.delta_applies") - applies0 >= 30
    )
    # end-state correctness: the chained snapshot answers like a fresh one
    _assert_parity(
        engine, dsnap, engine.prepare(snap), _checks(random.Random(43))
    )


def test_membership_delta_tindex_dirty_cap_flips_t_off():
    """With a zero T-dirty budget, the first membership delta flips the
    chain's T-index off (sticky) — still incremental, still exact (the
    KU path probes the live closure)."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(
        seed=25, flat_tindex_dirty_cap=0
    )
    if not dsnap.flat_meta.has_tindex:
        import pytest as _pytest

        _pytest.skip("world did not build a T-index")
    adds = [rel.must_from_tuple("group:g1#member", "user:u3")]
    snap2 = apply_delta(snap, 2, adds, [], interner=interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    assert ds2.flat_meta.delta is not None
    assert ds2.flat_meta.delta.t_off
    _assert_parity(engine, ds2, engine.prepare(snap2), _checks(random.Random(4)))
    # sticky across the next (non-membership) revision
    snap3 = apply_delta(
        snap2, 3,
        [rel.must_from_triple("document:d2", "viewer", "user:u2")], [],
        interner=interner,
    )
    ds3 = engine.prepare(snap3, prev=ds2)
    assert ds3.flat_meta.delta is not None and ds3.flat_meta.delta.t_off
    _assert_parity(engine, ds3, engine.prepare(snap3), _checks(random.Random(5)))


def test_membership_delta_dereference_and_revival_stay_exact():
    """Deleting the LAST userset-subject row referencing a group leaves
    the maintained closure a probe-equivalent SUPERSET (the dereferenced
    group's rows are unreachable); re-referencing the group later must
    find its membership still exact — all without leaving the
    incremental path."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=27)
    # rev 2: introduce a group userset referenced by exactly ONE row
    # (a brand-new userset subject forces the expected full prepare)
    only = rel.must_from_tuple("document:d5#viewer", "group:gonly#member")
    member = rel.must_from_tuple("group:gonly#member", "user:nu2")
    snap2 = apply_delta(snap, 2, [only, member], [], interner=interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    assert ds2.flat_meta.delta is None  # new userset subject: rebuild
    probe = [rel.must_from_triple("document:d5", "view", "user:nu2")]
    d, _, _ = engine.check_batch(ds2, probe, now_us=NOW)
    assert bool(d[0])
    # rev 3: delete the single referencing row — group dereferenced; the
    # chain stays incremental (the stale superset rows are unreachable)
    snap3 = apply_delta(snap2, 3, [], [only], interner=interner)
    ds3 = engine.prepare(snap3, prev=ds2)
    assert ds3.flat_meta.delta is not None, "us-row delete must not rebuild"
    _assert_parity(engine, ds3, engine.prepare(snap3),
                   _checks(random.Random(6)) + probe)
    # rev 4: the group's membership keeps advancing while dereferenced
    snap4 = apply_delta(
        snap3, 4,
        [rel.must_from_tuple("group:gonly#member", "user:u7")], [],
        interner=interner,
    )
    ds4 = engine.prepare(snap4, prev=ds3)
    assert ds4.flat_meta.delta is not None
    # rev 5: re-reference the group — its (incrementally maintained)
    # membership must answer exactly like a fresh build
    snap5 = apply_delta(snap4, 5, [only], [], interner=interner)
    ds5 = engine.prepare(snap5, prev=ds4)
    assert ds5.flat_meta.delta is not None, "revival must stay incremental"
    revived = probe + [
        rel.must_from_triple("document:d5", "view", "user:u7"),
    ]
    _assert_parity(engine, ds5, engine.prepare(snap5),
                   _checks(random.Random(8)) + revived)
    d, _, _ = engine.check_batch(ds5, revived, now_us=NOW)
    assert bool(d[0]) and bool(d[1])


def test_membership_then_overlay_userset_sees_advanced_closure():
    """Regression (review round 8): a fold armed with ZERO base userset
    rows (pf_has_u=False) must still reship the csr subject view on
    membership deltas — a later overlay userset row (dl_pfu) intersects
    against it, and a stale view would silently deny a fresh member."""
    cs = compile_schema(parse_schema("""
    definition user {}
    definition group { relation member: user }
    definition anchor { relation keeper: user | group#member }
    definition doc {
        relation viewer: user
        permission view = viewer
    }
    """))
    interner = Interner()
    base = [
        # keeps group:g#member "used" without any folded userset row
        rel.must_from_tuple("anchor:a#keeper", "group:g#member"),
        rel.must_from_tuple("group:g#member", "user:original"),
        rel.must_from_triple("doc:d1", "viewer", "user:direct"),
    ]
    snap = build_snapshot(1, cs, interner, base, epoch_us=NOW)
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    meta = dsnap.flat_meta
    if not (meta and any(s == cs.slot_of_name["view"] for _, s in meta.fold_pairs)):
        pytest.skip("doc.view did not fold in this configuration")
    assert not meta.pf_has_u  # no folded userset rows at base
    # rev 2: membership write — closure advances, csr must reship
    snap2 = apply_delta(
        snap, 2, [rel.must_from_tuple("group:g#member", "user:newbie")], [],
        interner=interner,
    )
    ds2 = engine.prepare(snap2, prev=dsnap)
    assert ds2.flat_meta.delta is not None
    # rev 3: a userset viewer lands on the folded pair → dl_pfu overlay
    snap3 = apply_delta(
        snap2, 3,
        [rel.must_from_tuple("doc:d1#viewer", "group:g#member")], [],
        interner=interner,
    )
    ds3 = engine.prepare(snap3, prev=ds2)
    checks = [
        rel.must_from_triple("doc:d1", "view", "user:newbie"),
        rel.must_from_triple("doc:d1", "view", "user:original"),
        rel.must_from_triple("doc:d1", "view", "user:direct"),
        rel.must_from_triple("doc:d1", "view", "user:uninvolved"),
    ]
    if ds3.flat_meta.delta is not None and ds3.flat_meta.delta.pf_ovl_u:
        d, p, ovf = engine.check_batch(ds3, checks, now_us=NOW)
        assert list(map(bool, d[:3])) == [True, True, True], d[:3]
        assert not bool(d[3])
    _assert_parity(engine, ds3, engine.prepare(snap3), checks)

"""Decision provenance (engine/explain.py): explain-tree parity against
the host oracle on randomized worlds (caveats / wildcards / expirations
/ closure overflow / nested-team T-join / arrow chains), device witness
⊆ oracle path, denial trees carrying the exhausted frontier, cache-hit
re-derivation at the pinned revision, chaos on the ``explain.walk``
fault site, and the zero-cost disarmed contract for witness extraction
on the pinned latency path."""

import datetime as dt
import time

import numpy as np
import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_engine_config,
    with_host_only_evaluation,
    with_latency_mode,
    with_store,
    with_verdict_cache,
)
from gochugaru_tpu.engine import explain as ex
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.utils import faults
from gochugaru_tpu.utils import metrics as _metrics
from gochugaru_tpu.utils.context import background

SCHEMA = """
caveat tier_at_least(tier int, minimum int) { tier >= minimum }
definition user {}
definition team { relation member: user | team#member }
definition folder {
    relation parent: folder
    relation viewer: user | team#member
    permission view = viewer + parent->view
}
definition doc {
    relation folder: folder
    relation reader: user | user:* | team#member | user with tier_at_least
    relation banned: user
    permission read = (reader - banned) + folder->view
}
"""


def _build_world(seed, *, n_users=24, n_teams=5, n_folders=6, n_docs=18,
                 engine_config=None, wildcard_docs=2):
    """One randomized world through the real client: nested teams
    (closure/T-join), a folder parent chain (arrow recursion), direct /
    wildcard / userset / caveated / expiring reader edges, bans."""
    opts = [with_latency_mode()]
    if engine_config is not None:
        opts.append(with_engine_config(engine_config))
    c = new_tpu_evaluator(*opts)
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    rng = np.random.default_rng(seed)
    txn = rel.Txn()
    # nested teams: t0 ⊇ t1 ⊇ … (T-join + closure material)
    for t in range(n_teams):
        for u in rng.choice(n_users, 3, replace=False):
            txn.touch(rel.must_from_tuple(f"team:t{t}#member", f"user:u{u}"))
        if t + 1 < n_teams:
            txn.touch(rel.must_from_tuple(
                f"team:t{t}#member", f"team:t{t + 1}#member"
            ))
    # folder chain: f0 ← f1 ← … (arrow recursion)
    for f in range(n_folders):
        if f + 1 < n_folders:
            txn.touch(rel.must_from_triple(
                f"folder:f{f + 1}", "parent", f"folder:f{f}"
            ))
        if rng.random() < 0.7:
            txn.touch(rel.must_from_triple(
                f"folder:f{f}", "viewer", f"user:u{rng.integers(n_users)}"
            ))
        if rng.random() < 0.3:
            txn.touch(rel.must_from_tuple(
                f"folder:f{f}#viewer", f"team:t{rng.integers(n_teams)}#member"
            ))
    now_s = time.time()
    for d in range(n_docs):
        txn.touch(rel.must_from_triple(
            f"doc:d{d}", "folder", f"folder:f{d % n_folders}"
        ))
        for u in rng.choice(n_users, 2, replace=False):
            r = rel.must_from_triple(f"doc:d{d}", "reader", f"user:u{u}")
            roll = rng.random()
            if roll < 0.2:  # stored-context caveat
                r = r.with_caveat("tier_at_least", {"minimum": 5})
            elif roll < 0.35:  # expiring edge: half already expired
                r = r.with_expiration(dt.datetime.fromtimestamp(
                    now_s + (3600 if rng.random() < 0.5 else -3600),
                    tz=dt.timezone.utc,
                ))
            txn.touch(r)
        if rng.random() < 0.5:
            txn.touch(rel.must_from_tuple(
                f"doc:d{d}#reader", f"team:t{rng.integers(n_teams)}#member"
            ))
        if d < wildcard_docs:
            txn.touch(rel.must_from_triple(f"doc:d{d}", "reader", "user:*"))
        if rng.random() < 0.25:
            txn.touch(rel.must_from_triple(
                f"doc:d{d}", "banned", f"user:u{rng.integers(n_users)}"
            ))
    c.write(ctx, txn)
    oracle_client = new_tpu_evaluator(
        with_host_only_evaluation(), with_store(c.store)
    )
    return c, oracle_client, rng


def _random_checks(rng, n, *, n_users=24, n_docs=18):
    out = []
    for _ in range(n):
        r = rel.must_from_triple(
            f"doc:d{rng.integers(n_docs)}",
            rng.choice(["read", "reader"]),
            f"user:u{rng.integers(n_users)}",
        )
        roll = rng.random()
        if roll < 0.2:  # query caveat context (live-context path)
            r = r.with_caveat("", {"tier": int(rng.integers(0, 10))})
        out.append(r)
    return out


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_explain_parity_and_witness_fuzz(seed):
    """Witness-seeded device explain == instrumented oracle walk, for
    allowed AND denied verdicts, on randomized worlds with caveats,
    wildcards, expirations and fold/T-join paths."""
    c, oc, rng = _build_world(seed)
    ctx = background()
    cs = consistency.full()
    checks = _random_checks(rng, 30)
    want = oc.check(ctx, cs, *checks)
    got = c.check(ctx, cs, *checks)
    assert got == want  # device parity (pre-existing contract)
    snap = c.store.snapshot_for(cs)
    engine = c._engine_for(snap)
    dsnap = c._dsnap_for(engine, snap)
    codes = engine.witness_codes(dsnap, checks)
    assert codes is not None
    for i, r in enumerate(checks):
        tree = c.explain(ctx, cs, r)
        # bool collapse parity: allowed ⇔ True; conditional/denied ⇔ False
        assert (tree["result"] == "allowed") == want[i], (r, tree)
        assert tree["revision"] == snap.revision
        # witness ⊆ oracle path (code 0 ⇒ unseeded, trivially consistent
        # only for non-allowed — an allowed device-definite verdict must
        # carry a branch)
        w = int(codes[i])
        if w or tree["result"] != "allowed":
            assert ex.witness_consistent(tree, w), (r, w, tree)


def test_witness_branch_classes_deterministic():
    """Every witness branch class appears and maps onto the matching
    oracle-tree structure on a hand-built world."""
    c = new_tpu_evaluator(with_latency_mode())
    ctx = background()
    c.write_schema(ctx, """
definition user {}
definition team { relation member: user }
definition org { relation admin: user }
definition doc {
    relation org: org
    relation reader: user | user:* | team#member
    permission admin = org->admin
    permission read = reader
}
""")
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:a", "reader", "user:alice"))
    txn.touch(rel.must_from_triple("doc:w", "reader", "user:*"))
    txn.touch(rel.must_from_triple("team:t", "member", "user:bob"))
    txn.touch(rel.must_from_tuple("doc:t#reader", "team:t#member"))
    txn.touch(rel.must_from_triple("doc:a", "org", "org:o"))
    txn.touch(rel.must_from_triple("org:o", "admin", "user:root"))
    c.write(ctx, txn)
    cs = consistency.full()
    snap = c.store.snapshot_for(cs)
    engine = c._engine_for(snap)
    dsnap = c._dsnap_for(engine, snap)
    cases = [
        (rel.must_from_triple("doc:a", "reader", "user:alice"), "direct"),
        (rel.must_from_triple("doc:w", "reader", "user:zed"), "wildcard"),
        (rel.must_from_tuple("team:t#member", "team:t#member"), "self"),
    ]
    rels = [r for r, _ in cases]
    codes = engine.witness_codes(dsnap, rels)
    for (r, branch), w in zip(cases, codes):
        assert ex.witness_name(int(w)) == branch, (r, int(w))
        tree = c.explain(background(), cs, r)
        assert ex.witness_consistent(tree, int(w))
    # userset/T and fold/rewrite classes on the remaining shapes
    t_code = int(engine.witness_codes(
        dsnap, [rel.must_from_triple("doc:t", "reader", "user:bob")]
    )[0])
    assert ex.witness_name(t_code) in ("t_probe", "userset")
    f_code = int(engine.witness_codes(
        dsnap, [rel.must_from_triple("doc:a", "admin", "user:root")]
    )[0])
    assert ex.witness_name(f_code) in ("fold", "rewrite")
    # seeded walk: the witness steers the root relation's exploration
    # order, so the tree's first explored grant is the witness class
    tree = c.explain(
        background(), cs,
        rel.must_from_triple("doc:t", "reader", "user:bob"),
    )
    assert tree["witness"] in ("t_probe", "userset")
    assert ex.witness_consistent(tree, t_code)


def test_denial_tree_carries_exhausted_frontier():
    """A denial's tree lists every explored-and-failed edge: the gated
    wildcard/caveat/expiry details and sub-verdicts, plus the count of
    non-matching direct edges."""
    c = new_tpu_evaluator()
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    now_s = time.time()
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:x", "reader", "user:other"))
    txn.touch(rel.must_from_triple("doc:x", "reader", "user:expired")
              .with_expiration(dt.datetime.fromtimestamp(
                  now_s - 60, tz=dt.timezone.utc)))
    txn.touch(rel.must_from_triple("doc:x", "reader", "user:victim")
              .with_caveat("tier_at_least", {"minimum": 9}))
    txn.touch(rel.must_from_tuple("doc:x#reader", "team:empty#member"))
    c.write(ctx, txn)
    tree = c.explain(
        ctx, consistency.full(),
        rel.must_from_triple("doc:x", "read", "user:victim")
        .with_caveat("", {"tier": 1}),
    )
    assert tree["result"] == "denied"

    def flatten(node, out):
        out.append(node)
        for ch in node.get("children", ()):
            flatten(ch, out)
        return out

    nodes = flatten(tree["tree"], [])
    rel_nodes = [
        n for n in nodes
        if n["kind"] == "relation" and n.get("item") == "reader"
    ]
    assert rel_nodes and rel_nodes[0]["verdict"] == "denied"
    # the caveat-gated direct edge is IN the frontier with its context
    gated = [
        n for n in nodes
        if n["kind"] == "direct" and n.get("gate", {}).get("caveat")
    ]
    assert gated, nodes
    g = gated[0]["gate"]
    assert g["caveat"] == "tier_at_least"
    assert g["caveat_result"] is False
    assert g["context"]["minimum"] == 9 and g["context"]["tier"] == 1
    # a skipped non-matching direct edge is counted, the empty userset
    # expansion appears denied
    assert rel_nodes[0].get("edges_skipped", 0) >= 1
    assert any(n["kind"] == "userset" and n["verdict"] == "denied"
               for n in nodes)


def test_closure_overflow_world_explains_exactly():
    """Worlds past the device's static caps (closure overflow → host
    fallback) still explain oracle-exactly; overflowed rows carry no
    device witness."""
    cfg = EngineConfig(closure_size=8, seed_cap=4, us_leaf_cap=2)
    c, oc, rng = _build_world(101, engine_config=cfg, n_teams=8)
    ctx = background()
    cs = consistency.full()
    checks = _random_checks(rng, 20)
    want = oc.check(ctx, cs, *checks)
    assert c.check(ctx, cs, *checks) == want
    for i, r in enumerate(checks):
        tree = c.explain(ctx, cs, r)
        assert (tree["result"] == "allowed") == want[i], (r, tree)


def test_cache_hit_rederivation_at_pinned_revision():
    """A vcache-served verdict explains with ``cached: true`` and the
    pinned revision — and the tree is RE-DERIVED (it matches the oracle,
    not a stored blob), including after the head moves."""
    c = new_tpu_evaluator(with_latency_mode(), with_verdict_cache())
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:c", "reader", "user:hit"))
    c.write(ctx, txn)
    cs = consistency.min_latency()
    q = rel.must_from_triple("doc:c", "read", "user:hit")
    assert c.check(ctx, cs, q) == [True]
    assert c.check(ctx, cs, q) == [True]  # now cache-served
    assert _metrics.default.counter("cache.hits") >= 1
    snap = c.store.snapshot_for(cs)
    tree = c.explain(ctx, cs, q)
    assert tree["cached"] is True
    assert tree["revision"] == snap.revision
    assert tree["result"] == "allowed"
    assert tree["strategy"] == "min_latency"
    # full() bypasses the cache — provenance must not claim cached
    tree_full = c.explain(ctx, consistency.full(), q)
    assert "cached" not in tree_full


def test_explain_walk_chaos_no_torn_trees():
    """The ``explain.walk`` fault site classifies into the client retry
    envelope; every returned tree is complete and verdict-exact."""
    c, oc, rng = _build_world(77)
    ctx = background()
    cs = consistency.full()
    checks = _random_checks(rng, 12)
    want = oc.check(ctx, cs, *checks)
    m = _metrics.default
    r0 = m.counter("retry.retries")
    with faults.default.armed("explain.walk", probability=0.5,
                              seed=9) as spec:
        for i, q in enumerate(checks):
            tree = c.explain(ctx, cs, q)
            assert (tree["result"] == "allowed") == want[i]
            assert tree["tree"] is not None
            assert "verdict" in tree["tree"]  # fully popped root = no tear
    assert spec.fired > 0
    assert m.counter("retry.retries") > r0


def test_disarmed_witness_zero_cost_on_pinned_path():
    """The zero-overhead contract: with witness extraction DISARMED the
    kernel has exactly three outputs (no witness plane ships), the
    pinned latency path allocates no witness state, and re-dispatching
    after an arm/disarm cycle reuses the original pins (no retrace)."""
    import jax

    from test_latency_path import build_rbac_world

    from gochugaru_tpu.engine.device import DeviceEngine

    cs, snap, users, repos, slot = build_rbac_world()
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    rng = np.random.default_rng(3)
    B = 256
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = np.full(B, slot["read"], np.int32)
    q_subj = rng.choice(users, B).astype(np.int32)

    # (1) no device output: the disarmed kernel's abstract output is a
    # 3-tuple, the armed variant a 4-tuple — asserted on the SAME args
    got = engine.flat_fn_and_args(
        dsnap,
        {"q_perm": q_perm, "q_res": q_res, "q_subj": q_subj,
         "q_srel": np.full(B, -1, np.int32),
         "q_wc": np.full(B, -1, np.int32),
         "q_ctx": np.full(B, -1, np.int32),
         "q_self": np.zeros(B, bool)},
        engine._encode_query_contexts([], dsnap.strings),
        np.int32(0), B,
    )
    assert got is not None
    fn, args = got
    assert len(jax.eval_shape(fn, *args)) == 3
    wfn = engine._flat_fn_for(
        tuple(sorted({int(s) for s in np.unique(q_perm)})),
        dsnap.flat_meta, witness=True,
    )
    assert len(jax.eval_shape(wfn, *args)) == 4

    # (2) no host allocations / no witness state on the pinned path
    lp = engine.latency_path(dsnap)
    for i in range(4):
        out = lp.dispatch_columns(np.roll(q_res, i), q_perm, q_subj)
        assert out is not None and len(out) == 3
    assert lp.last_witness is None
    assert lp.witness_armed is False
    assert all(len(k) == 3 for k in lp._local)  # no armed pin built

    # (3) arming pins a SEPARATE executable; disarming returns to the
    # original pins without recompiling
    disarmed_pins = set(lp._local)
    lp.arm_witness()
    out = lp.dispatch_columns(q_res, q_perm, q_subj)
    assert len(out) == 3  # caller contract unchanged
    assert lp.last_witness is not None and lp.last_witness.shape == (B,)
    assert set(lp._local) - disarmed_pins  # armed pin is NEW
    lp.arm_witness(False)
    assert lp.last_witness is None
    cc = lp.compile_count
    lp.dispatch_columns(q_res, q_perm, q_subj)
    assert lp.compile_count == cc, "disarm retraced the pinned path"
    assert lp.last_witness is None

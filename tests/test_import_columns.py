"""Columnar bulk-import semantics (Store.import_columns /
Client.import_relationship_columns).

The contract mirrors the object path's BulkImport behavior
(/root/reference/client/client.go:438-465): duplicates — in-batch,
against the live dict, or against base segments — raise
AlreadyExistsError with NOTHING applied; the client falls back to a
retried TOUCH that upserts instead.
"""

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import Client
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import AlreadyExistsError

SCHEMA = """
definition user {}
definition doc {
    relation reader: user
    permission read = reader
}
"""


def _client() -> Client:
    c = Client()
    c.write_schema(background(), SCHEMA)
    return c


def test_columnar_import_visibility_and_parity():
    c = _client()
    ctx = background()
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=[f"d{i}" for i in range(50)],
        resource_relation="reader",
        subject_type="user", subject_ids=[f"u{i % 7}" for i in range(50)],
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:d3", "read", "user:u3"))
    assert not c.check_one(ctx, cs, rel.must_from_triple("doc:d3", "read", "user:u4"))
    got = sorted(
        str(r) for r in c.read_relationships(ctx, cs, rel.Filter("doc", "d3"))
    )
    assert got == ["doc:d3#reader@user:u3"]


def test_columnar_import_in_batch_duplicate_raises_atomically():
    c = _client()
    with pytest.raises(AlreadyExistsError):
        c._store.import_columns(
            resource_type="doc", resource_ids=["a", "b", "a"],
            resource_relation="reader",
            subject_type="user", subject_ids=["u", "u", "u"],
        )
    # nothing applied
    assert not c.check_one(
        background(), consistency.full(),
        rel.must_from_triple("doc:b", "read", "user:u"),
    )


def test_columnar_import_duplicate_vs_live_dict_touches_via_client():
    c = _client()
    ctx = background()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:a", "reader", "user:u"))
    c.write(ctx, txn)
    # client path: AlreadyExists → TOUCH fallback upserts, no error
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=["a", "b"],
        resource_relation="reader", subject_type="user",
        subject_ids=["u", "u"],
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:b", "read", "user:u"))
    got = list(c.read_relationships(ctx, cs, rel.Filter("doc", "a")))
    assert len(got) == 1  # upsert, not a duplicate row


def test_columnar_import_vs_larger_live_dict_probes_batchwise():
    """len(_live) > B flips _commit_columns_locked to the per-batch-row
    probe direction; semantics must be identical — including an in-batch
    TOUCH dup that also collides with a live row (one dict delete, not
    two)."""
    c = _client()
    ctx = background()
    txn = rel.Txn()
    for i in range(8):
        txn.create(rel.must_from_triple(f"doc:a{i}", "reader", "user:u"))
    c.write(ctx, txn)
    with pytest.raises(AlreadyExistsError):
        c._store.import_columns(
            resource_type="doc", resource_ids=["a3", "zz"],
            resource_relation="reader",
            subject_type="user", subject_ids=["u", "u"],
        )
    # TOUCH: in-batch dup of a colliding key upserts once
    c._store.import_columns(
        resource_type="doc", resource_ids=["a3", "a3", "zz"],
        resource_relation="reader",
        subject_type="user", subject_ids=["u", "u", "u"],
        touch=True,
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:zz", "read", "user:u"))
    got = list(c.read_relationships(ctx, cs, rel.Filter("doc", "a3")))
    assert len(got) == 1


def test_columnar_import_duplicate_vs_segment_raises_then_touch():
    c = _client()
    ctx = background()
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=[f"d{i}" for i in range(20)],
        resource_relation="reader",
        subject_type="user", subject_ids=["u"] * 20,
    )
    with pytest.raises(AlreadyExistsError):
        c._store.import_columns(
            resource_type="doc", resource_ids=["d5", "x"],
            resource_relation="reader",
            subject_type="user", subject_ids=["u", "u"],
        )
    # client-level recovery
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=["d5", "x"],
        resource_relation="reader",
        subject_type="user", subject_ids=["u", "u"],
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:x", "read", "user:u"))
    got = list(c.read_relationships(ctx, cs, rel.Filter("doc", "d5")))
    assert len(got) == 1


def test_columnar_import_invalid_shape_rejected():
    c = _client()
    with pytest.raises(Exception):
        c._store.import_columns(
            resource_type="doc", resource_ids=["a"],
            resource_relation="reader",
            subject_type="doc", subject_ids=["b"],  # doc not allowed
        )


def test_columnar_import_userset_subjects():
    c = Client()
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition team { relation member: user }
    definition doc {
        relation reader: user | team#member
        permission read = reader
    }
    """)
    txn = rel.Txn()
    txn.create(rel.must_from_tuple("team:eng#member", "user:bob"))
    c.write(ctx, txn)
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=["a", "b"],
        resource_relation="reader",
        subject_type="team", subject_ids=["eng", "eng"],
        subject_relation="member",
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:a", "read", "user:bob"))


def test_columnar_export_round_trips_with_import():
    # backup/restore loop entirely on the columnar paths, including
    # caveats/expiry rows falling back to correct list values
    import datetime as dt

    c = Client()
    ctx = background()
    c.write_schema(ctx, """
    caveat tier(t int, min int) { t >= min }
    definition user {}
    definition doc {
        relation reader: user | user with tier
        permission read = reader
    }
    """)
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:c", "reader", "user:u1").with_caveat(
        "tier", {"min": 2}))
    exp = dt.datetime.fromtimestamp(4_000_000_000, tz=dt.timezone.utc)
    txn.create(rel.must_from_triple("doc:e", "reader", "user:u2").with_expiration(exp))
    c.write(ctx, txn)
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=[f"d{i}" for i in range(100)],
        resource_relation="reader",
        subject_type="user", subject_ids=[f"u{i % 9}" for i in range(100)],
    )
    rev = c.read_schema(ctx)[1]
    chunks = list(c.export_relationship_columns(ctx, rev))
    rows = sum(len(ch["resource_ids"]) for ch in chunks)
    assert rows == 102
    flat = {
        k: [v for ch in chunks for v in ch[k]]
        for k in chunks[0]
    }
    i = flat["resource_ids"].index("c")
    assert flat["caveat_names"][i] == "tier"
    assert flat["caveat_contexts"][i] == {"min": 2}
    j = flat["resource_ids"].index("e")
    assert flat["expirations_us"][j] == 4_000_000_000 * 1_000_000
    # restore the plain rows into a fresh store via the columnar import
    c2 = Client()
    c2.write_schema(background(), "definition user {} definition doc { relation reader: user  permission read = reader }")
    plain = [k for k in range(rows) if not flat["caveat_names"][k]
             and not flat["expirations_us"][k]]
    c2.import_relationship_columns(
        background(), resource_type="doc",
        resource_ids=[flat["resource_ids"][k] for k in plain],
        resource_relation="reader", subject_type="user",
        subject_ids=[flat["subject_ids"][k] for k in plain],
    )
    import gochugaru_tpu.consistency as cons
    assert c2.check_one(background(), cons.full(),
                        rel.must_from_triple("doc:d5", "read", "user:u5"))


MIXED = """
definition user {}
definition team { relation member: user }
definition doc {
    relation reader: user | user:* | team#member
    permission read = reader
}
definition folder {
    relation owner: user
}
"""


def test_interned_import_roundtrip_and_check():
    import numpy as np

    c = Client()
    ctx = background()
    c.write_schema(ctx, MIXED)
    st = c._store
    itn = st.interner
    docs = itn.node_batch("doc", [f"d{i}" for i in range(50)])
    users = itn.node_batch("user", [f"u{i}" for i in range(10)])
    c.import_relationship_id_columns(
        ctx,
        resource_ids=np.repeat(docs, 2),
        resource_relation="reader",
        subject_ids=np.tile(users[:2], 50),
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:d7", "read", "user:u0"))
    assert not c.check_one(ctx, cs, rel.must_from_triple("doc:d7", "read", "user:u5"))
    rev = c.read_schema(ctx)[1]
    chunks = list(c.export_relationship_id_columns(ctx, rev))
    total = sum(ch["res"].shape[0] for ch in chunks)
    assert total == 100
    assert all(ch["resource_relation"] == "reader" for ch in chunks)

    # restore into the same store via TOUCH fallback: no-op but succeeds
    for ch in chunks:
        c.import_relationship_id_columns(
            ctx,
            resource_ids=ch["res"], resource_relation=ch["resource_relation"],
            subject_ids=ch["subj"], subject_relation=ch["subject_relation"],
        )
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:d7", "read", "user:u0"))


def test_interned_import_mixed_types_and_usersets():
    import numpy as np

    c = Client()
    ctx = background()
    c.write_schema(ctx, MIXED)
    itn = c._store.interner
    d = itn.node_batch("doc", ["a", "b"])
    t = itn.node_batch("team", ["eng"])
    u = itn.node_batch("user", ["x", "y"])
    # team membership, then userset + wildcard subjects in ONE call
    c.import_relationship_id_columns(
        ctx, resource_ids=t, resource_relation="member", subject_ids=u[:1],
    )
    wc = itn.node("user", "*")
    # userset subjects (team#member) and a wildcard row, one call each
    c.import_relationship_id_columns(
        ctx, resource_ids=d[:1], resource_relation="reader",
        subject_ids=t, subject_relation="member",
    )
    c.import_relationship_id_columns(
        ctx, resource_ids=d[1:], resource_relation="reader",
        subject_ids=np.array([wc], np.int32),
    )
    cs = consistency.full()
    # x reads doc:a via team#member; everyone reads doc:b via wildcard
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:a", "read", "user:x"))
    assert not c.check_one(ctx, cs, rel.must_from_triple("doc:a", "read", "user:y"))
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:b", "read", "user:y"))


def test_interned_import_validation_errors():
    import numpy as np

    from gochugaru_tpu.schema.compiler import SchemaValidationError

    c = Client()
    ctx = background()
    c.write_schema(ctx, MIXED)
    itn = c._store.interner
    d = itn.node_batch("doc", ["a"])
    f = itn.node_batch("folder", ["f1"])
    u = itn.node_batch("user", ["x"])
    t = itn.node_batch("team", ["eng"])
    # folder as subject of doc#reader: not allowed
    with pytest.raises(SchemaValidationError):
        c.import_relationship_id_columns(
            ctx, resource_ids=d, resource_relation="reader", subject_ids=f,
        )
    # team as DIRECT subject (needs #member)
    with pytest.raises(SchemaValidationError):
        c.import_relationship_id_columns(
            ctx, resource_ids=d, resource_relation="reader", subject_ids=t,
        )
    # userset form allowed
    c.import_relationship_id_columns(
        ctx, resource_ids=d, resource_relation="reader",
        subject_ids=t, subject_relation="member",
    )
    # permission target rejected
    with pytest.raises(SchemaValidationError):
        c.import_relationship_id_columns(
            ctx, resource_ids=d, resource_relation="read", subject_ids=u,
        )
    # out-of-range id
    with pytest.raises(ValueError):
        c.import_relationship_id_columns(
            ctx, resource_ids=np.array([99999], np.int32),
            resource_relation="reader", subject_ids=u,
        )
    # wildcard allowed on doc.reader (user:*), forbidden on team.member
    wc = itn.node("user", "*")
    c.import_relationship_id_columns(
        ctx, resource_ids=d, resource_relation="reader",
        subject_ids=np.array([wc], np.int32),
    )
    with pytest.raises(SchemaValidationError):
        c.import_relationship_id_columns(
            ctx, resource_ids=t, resource_relation="member",
            subject_ids=np.array([wc], np.int32),
        )

"""Columnar bulk-import semantics (Store.import_columns /
Client.import_relationship_columns).

The contract mirrors the object path's BulkImport behavior
(/root/reference/client/client.go:438-465): duplicates — in-batch,
against the live dict, or against base segments — raise
AlreadyExistsError with NOTHING applied; the client falls back to a
retried TOUCH that upserts instead.
"""

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import Client
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import AlreadyExistsError

SCHEMA = """
definition user {}
definition doc {
    relation reader: user
    permission read = reader
}
"""


def _client() -> Client:
    c = Client()
    c.write_schema(background(), SCHEMA)
    return c


def test_columnar_import_visibility_and_parity():
    c = _client()
    ctx = background()
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=[f"d{i}" for i in range(50)],
        resource_relation="reader",
        subject_type="user", subject_ids=[f"u{i % 7}" for i in range(50)],
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:d3", "read", "user:u3"))
    assert not c.check_one(ctx, cs, rel.must_from_triple("doc:d3", "read", "user:u4"))
    got = sorted(
        str(r) for r in c.read_relationships(ctx, cs, rel.Filter("doc", "d3"))
    )
    assert got == ["doc:d3#reader@user:u3"]


def test_columnar_import_in_batch_duplicate_raises_atomically():
    c = _client()
    with pytest.raises(AlreadyExistsError):
        c._store.import_columns(
            resource_type="doc", resource_ids=["a", "b", "a"],
            resource_relation="reader",
            subject_type="user", subject_ids=["u", "u", "u"],
        )
    # nothing applied
    assert not c.check_one(
        background(), consistency.full(),
        rel.must_from_triple("doc:b", "read", "user:u"),
    )


def test_columnar_import_duplicate_vs_live_dict_touches_via_client():
    c = _client()
    ctx = background()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:a", "reader", "user:u"))
    c.write(ctx, txn)
    # client path: AlreadyExists → TOUCH fallback upserts, no error
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=["a", "b"],
        resource_relation="reader", subject_type="user",
        subject_ids=["u", "u"],
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:b", "read", "user:u"))
    got = list(c.read_relationships(ctx, cs, rel.Filter("doc", "a")))
    assert len(got) == 1  # upsert, not a duplicate row


def test_columnar_import_duplicate_vs_segment_raises_then_touch():
    c = _client()
    ctx = background()
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=[f"d{i}" for i in range(20)],
        resource_relation="reader",
        subject_type="user", subject_ids=["u"] * 20,
    )
    with pytest.raises(AlreadyExistsError):
        c._store.import_columns(
            resource_type="doc", resource_ids=["d5", "x"],
            resource_relation="reader",
            subject_type="user", subject_ids=["u", "u"],
        )
    # client-level recovery
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=["d5", "x"],
        resource_relation="reader",
        subject_type="user", subject_ids=["u", "u"],
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:x", "read", "user:u"))
    got = list(c.read_relationships(ctx, cs, rel.Filter("doc", "d5")))
    assert len(got) == 1


def test_columnar_import_invalid_shape_rejected():
    c = _client()
    with pytest.raises(Exception):
        c._store.import_columns(
            resource_type="doc", resource_ids=["a"],
            resource_relation="reader",
            subject_type="doc", subject_ids=["b"],  # doc not allowed
        )


def test_columnar_import_userset_subjects():
    c = Client()
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition team { relation member: user }
    definition doc {
        relation reader: user | team#member
        permission read = reader
    }
    """)
    txn = rel.Txn()
    txn.create(rel.must_from_tuple("team:eng#member", "user:bob"))
    c.write(ctx, txn)
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=["a", "b"],
        resource_relation="reader",
        subject_type="team", subject_ids=["eng", "eng"],
        subject_relation="member",
    )
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:a", "read", "user:bob"))

"""Request-scoped tracing (utils/trace.py): span-tree coverage of the
check lifecycle (admission → dispatch → stage events), error attributes
on the shed/retry path, the zero-allocation no-op contract when sampling
is off, the keep-slow tail rule, and watch/write spans."""

import json
import threading
import time

import numpy as np
import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_admission_control,
    with_latency_mode,
)
from gochugaru_tpu.utils import metrics, trace
from gochugaru_tpu.utils.admission import AdmissionConfig
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import DeadlineExceededError, ShedError

SCHEMA = """
definition user {}
definition doc { relation reader: user  permission read = reader }
"""


@pytest.fixture(autouse=True)
def _trace_hygiene():
    """No test may leak an installed tracer into the next (the tracer is
    process-global by design, like the fault registry)."""
    trace.disable()
    yield
    trace.disable()


@pytest.fixture(scope="module")
def doc_client():
    c = new_tpu_evaluator(with_latency_mode())
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    for i in range(16):
        txn.create(rel.must_from_triple(f"doc:d{i}", "reader", f"user:u{i}"))
    c.write(ctx, txn)
    rs = [rel.must_from_triple(f"doc:d{i}", "read", f"user:u{i}") for i in range(8)]
    # warm: first dispatch compiles; the traced assertions below want a
    # warm (budget-recording) latency dispatch
    assert c.check(ctx, consistency.full(), *rs) == [True] * 8
    return c, ctx, rs


def _spans_by_name(t):
    out = {}
    for sp in t["spans"]:
        out.setdefault(sp["name"], []).append(sp)
    return out


def test_sampled_check_covers_admission_dispatch_stages(doc_client):
    c, ctx, rs = doc_client
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None, capacity=32)
    assert c.check(ctx, consistency.full(), *rs) == [True] * 8
    traces = [t for t in tr.traces() if t["name"] == "check"]
    assert len(traces) == 1, "one sampled check → exactly one trace"
    t = traces[0]
    by = _spans_by_name(t)

    # tree shape: check → dispatch → device.check_batch → latency.dispatch
    # → four stage spans
    root = by["check"][0]
    assert root["parent_id"] == -1 and root["attrs"]["batch"] == 8
    disp = by["dispatch"][0]
    assert disp["parent_id"] == root["span_id"]
    assert any(e["name"] == "admission.admit" for e in root["events"])
    dev = by["device.check_batch"][0]
    assert dev["parent_id"] == disp["span_id"]
    lat = by["latency.dispatch"][0]
    assert lat["parent_id"] == dev["span_id"]
    assert lat["attrs"]["compiled"] is False, "warm dispatch must not compile"
    stage_names = {"stage.host_lower", "stage.h2d", "stage.kernel", "stage.d2h"}
    assert stage_names <= set(by), set(by)
    for s in stage_names:
        assert by[s][0]["parent_id"] == lat["span_id"]

    # the stage span durations must agree with the metrics stage timers:
    # both are built from the SAME perf_counter stamps, so the last
    # budget's values match the span durations exactly (within the
    # float rounding the JSONL dump applies)
    engine = c._engine
    dsnap = next(iter(c._dsnap_cache.values()))
    b = dsnap.latency_path.last_budget
    for sname, bval in [
        ("stage.host_lower", b.host_lower_s), ("stage.h2d", b.h2d_s),
        ("stage.kernel", b.kernel_s), ("stage.d2h", b.d2h_s),
    ]:
        assert by[sname][0]["dur_s"] == pytest.approx(bval, abs=1e-9), sname
    assert lat["dur_s"] == pytest.approx(b.total_s, abs=1e-9)
    # ... and the metrics registry really did observe that kernel sample
    ring = metrics.default._samples.get("latency.kernel_s")
    assert ring and any(abs(v - b.kernel_s) < 1e-12 for v in ring)

    # the JSONL dump round-trips
    lines = [ln for ln in tr.dump_jsonl().splitlines() if ln]
    parsed = [json.loads(ln) for ln in lines]
    assert any(p["trace_id"] == t["trace_id"] for p in parsed)


def test_shed_retry_path_records_shed_error():
    c = new_tpu_evaluator(
        with_latency_mode(),
        with_admission_control(AdmissionConfig(max_inflight=1)),
    )
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:d0", "reader", "user:u0"))
    c.write(ctx, txn)
    r = rel.must_from_triple("doc:d0", "read", "user:u0")
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None, capacity=32)

    # occupy the single admission slot so every dispatch sheds
    cm = c._admission.gate.admit()
    cm.__enter__()
    try:
        with pytest.raises((DeadlineExceededError, ShedError)):
            c.check(ctx.with_timeout(0.30), consistency.full(), r)
    finally:
        cm.__exit__(None, None, None)

    traces = [t for t in tr.traces() if t["name"] == "check"]
    assert traces, "shed check must still finish (and keep) its trace"
    t = traces[-1]
    root = t["spans"][0]
    # the ShedError lands as a root attribute (set by the gate) ...
    assert root["attrs"].get("shed_error") == "ShedError"
    # ... as admission.shed events ...
    evs = [e for sp in t["spans"] for e in sp.get("events", ())]
    assert any(
        e["name"] == "admission.shed" and e.get("error") == "ShedError"
        for e in evs
    )
    # ... and the retry envelope recorded at least one backoff on it
    assert any(
        e["name"] == "retry" and e.get("error") == "ShedError" for e in evs
    )
    # the terminal error is attributed on the root
    assert root["attrs"].get("error") in ("DeadlineExceededError", "ShedError")


def test_sampling_off_allocates_zero_spans(doc_client):
    c, ctx, rs = doc_client
    # rate 0: tracer installed but every head decision is "no"
    trace.configure(sample_rate=0.0, slow_threshold_s=None)
    assert trace.root_span("check") is trace.NOOP
    n0 = trace.spans_created()
    assert c.check(ctx, consistency.full(), *rs) == [True] * 8
    assert trace.spans_created() == n0, (
        "sampling off must allocate no Span objects anywhere on the path"
    )
    # tracer absent entirely: same contract, and the context rides free
    trace.disable()
    ctx2 = ctx.with_span(trace.NOOP)
    assert ctx2 is ctx, "NOOP span must not grow the context chain"
    assert ctx.span() is trace.NOOP
    n0 = trace.spans_created()
    assert c.check(ctx, consistency.full(), *rs) == [True] * 8
    assert trace.spans_created() == n0


def test_keep_slow_tail_rule(doc_client):
    c, ctx, rs = doc_client
    # head sampling off, tail threshold 0 → every request is "slow"
    tr = trace.configure(sample_rate=0.0, slow_threshold_s=0.0, capacity=8)
    assert c.check(ctx, consistency.full(), *rs) == [True] * 8
    kept = [t for t in tr.traces() if t["name"] == "check"]
    assert kept and kept[-1]["tail_kept"] is True
    assert kept[-1]["spans"][0]["attrs"]["batch"] == 8
    assert kept[-1]["duration_s"] > 0
    # and a high threshold keeps nothing
    tr = trace.configure(sample_rate=0.0, slow_threshold_s=60.0)
    assert c.check(ctx, consistency.full(), *rs) == [True] * 8
    assert not tr.traces()


def test_watch_and_write_spans(doc_client):
    c, ctx, _ = doc_client
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None, capacity=32)
    wctx = ctx.with_cancel()
    from gochugaru_tpu.rel.update import UpdateFilter

    stream = c.updates_since_revision(wctx, UpdateFilter(), "")
    got = []

    def consume():  # exactly one update, then the thread exits
        try:
            got.append(next(stream))
        except StopIteration:
            pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    try:
        time.sleep(0.05)
        txn = rel.Txn()
        txn.touch(rel.must_from_triple("doc:d0", "reader", "user:watcher"))
        c.write(ctx, txn)
        t.join(timeout=10)
        assert not t.is_alive() and len(got) == 1
    finally:
        wctx.cancel()
        t.join(timeout=5)
        stream.close()
    names = {t_["name"] for t_ in tr.traces()}
    assert "write" in names, names
    assert "watch" in names, names
    watch = [t_ for t_ in tr.traces() if t_["name"] == "watch"][-1]
    assert watch["spans"][0]["attrs"]["delivered"] == 1
    write = [t_ for t_ in tr.traces() if t_["name"] == "write"][-1]
    assert write["spans"][0]["attrs"]["applied"] == 1
    assert "revision" in write["spans"][0]["attrs"]


def test_span_event_cap_bounded():
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None, capacity=4)
    sp = trace.root_span("flood")
    for i in range(trace.MAX_EVENTS + 50):
        sp.event("e", i=i)
    sp.end()
    t = tr.traces()[-1]
    root = t["spans"][0]
    assert len(root["events"]) == trace.MAX_EVENTS
    assert root["attrs"]["events_dropped"] == 50
    # the ring itself is bounded too
    for i in range(10):
        trace.root_span("r", i=i).end()
    assert len(tr.traces()) == 4

"""Structured decision log (utils/decisions.py): ring + sampling +
always-keep-denied, JSONL sink rotation with drop counters, per-strategy
verdict counters, the /decisions endpoint, incident-bundle carriage, and
serve-path provenance (cache_hit / dedup_parked)."""

import json
import os
import urllib.request

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_decision_log,
    with_latency_mode,
    with_telemetry,
)
from gochugaru_tpu.utils import decisions as _decisions
from gochugaru_tpu.utils import metrics as _metrics
from gochugaru_tpu.utils import trace as _trace
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.decisions import DecisionLog, strategy_name
from gochugaru_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _log_hygiene():
    yield
    _decisions.install(None)


def _r(i=0, allowed=True):
    return rel.must_from_triple(f"doc:d{i}", "read", f"user:u{i}")


def test_strategy_names():
    assert strategy_name(None) == "direct"
    assert strategy_name(consistency.full()) == "full"
    assert strategy_name(consistency.min_latency()) == "min_latency"
    assert strategy_name(consistency.at_least("3")) == "at_least"
    assert strategy_name(consistency.snapshot("3")) == "snapshot"


def test_sampling_and_always_keep_denied():
    m = Metrics()
    log = DecisionLog(capacity=4096, sample_rate=0.0, registry=m, seed=1)
    _decisions.install(log)
    rels = [_r(i) for i in range(50)]
    verdicts = [i % 5 != 0 for i in range(50)]  # 10 denied
    _decisions.record_rels(rels, verdicts, revision=7,
                           strategy=consistency.full(), latency_s=0.001)
    entries = log.tail()
    # 0% head sample: ONLY the denied decisions survive
    assert len(entries) == 10
    assert all(e["verdict"] == "denied" for e in entries)
    assert all(e["revision"] == 7 and e["strategy"] == "full"
               for e in entries)
    assert m.counter("decisions.denied_kept") == 10
    assert m.counter("decisions.sampled_out") == 40
    assert m.counter("decisions.recorded") == 10
    # denied keep is bounded per batch — and the cap is its OWN counter
    # (never folded into sampling: a capped denied entry is an audit
    # hole the operator must be able to see)
    log2 = DecisionLog(sample_rate=0.0, denied_keep_max=3, registry=m)
    _decisions.install(log2)
    _decisions.record_rels(rels, [False] * 50, strategy="direct")
    assert len(log2.tail()) == 3
    assert m.counter("decisions.denied_capped") == 47
    assert m.counter("decisions.sampled_out") == 40  # unchanged
    assert log2.stats()["denied_capped"] == 47


def test_ring_bound_and_entry_fields():
    m = Metrics()
    log = DecisionLog(capacity=8, registry=m)
    _decisions.install(log)
    _decisions.record_rels(
        [_r(i) for i in range(20)], [True] * 20, revision=3,
        strategy=consistency.min_latency(),
        cache_hits=[i % 2 == 0 for i in range(20)],
        latency_s=0.002, trace_id="tid-1", client_id="w7",
    )
    entries = log.tail()
    assert len(entries) == 8  # ring bound
    e = entries[-1]
    assert e["resource"] == "doc:d19" and e["permission"] == "read"
    assert e["subject"] == "user:u19" and e["verdict"] == "allowed"
    assert e["latency_ms"] == 2.0 and e["trace_id"] == "tid-1"
    assert e["client"] == "w7"
    assert any(x.get("cache_hit") for x in entries)


def test_sink_rotation_and_drop_counters(tmp_path):
    m = Metrics()
    sink = str(tmp_path / "d.jsonl")
    log = DecisionLog(sink_path=sink, rotate_bytes=600, rotate_keep=2,
                      registry=m)
    _decisions.install(log)
    for batch in range(20):
        _decisions.record_rels([_r(batch)], [True], revision=batch,
                               strategy="direct")
    files = sorted(p for p in os.listdir(tmp_path))
    assert any(p.startswith("d.jsonl.") for p in files)
    assert m.counter("decisions.rotated") > 0
    # never more than rotate_keep rotated files
    assert len([p for p in files if p.startswith("d.jsonl.")]) <= 2
    # rotated content is valid JSONL
    with open(tmp_path / "d.jsonl.1") as f:
        for line in f:
            json.loads(line)
    # a dead sink counts drops instead of raising into the caller
    log2 = DecisionLog(sink_path=str(tmp_path / "nodir" / "x.jsonl"),
                       registry=m)
    _decisions.install(log2)
    _decisions.record_rels([_r(1)], [True], strategy="direct")
    assert m.counter("decisions.dropped") >= 1
    assert len(log2.tail()) == 1  # the RING still has it


def test_verdict_counters_by_strategy_and_cache_hit():
    m = Metrics()
    _decisions.count_verdicts(m, 5, 2, "min_latency", cache_hits=3)
    _decisions.count_verdicts(m, 1, 0, "full")
    fam = m.counters_prefixed("check.verdicts.")
    assert fam["check.verdicts.allowed"] == 6
    assert fam["check.verdicts.denied"] == 2
    assert fam["check.verdicts.allowed.min_latency"] == 5
    assert fam["check.verdicts.denied.min_latency"] == 2
    assert fam["check.verdicts.allowed.full"] == 1
    assert fam["check.verdicts.cache_hit"] == 3


def test_end_to_end_client_decisions_and_endpoint(tmp_path):
    c = new_tpu_evaluator(
        with_latency_mode(),
        with_decision_log(capacity=512),
        with_telemetry(port=0),
    )
    ctx = background()
    c.write_schema(ctx, """
definition user {}
definition doc { relation reader: user  permission read = reader }
""")
    txn = rel.Txn()
    for i in range(10):
        txn.touch(rel.must_from_triple(f"doc:d{i}", "reader", f"user:u{i}"))
    c.write(ctx, txn)
    cs = consistency.full()
    for i in range(10):
        c.check(ctx, cs, rel.must_from_triple(f"doc:d{i}", "read",
                                              f"user:u{(i + 1) % 10}"))
    m = _metrics.default
    assert m.counter("check.verdicts.denied.full") > 0
    log = _decisions.get()
    assert log is not None and len(log) > 0
    denied = [e for e in log.tail() if e["verdict"] == "denied"]
    assert denied and all("revision" in e for e in denied)
    # /decisions: summary head + JSONL entries
    body = urllib.request.urlopen(
        c.telemetry.url + "/decisions?n=4"
    ).read().decode()
    lines = body.strip().splitlines()
    head = json.loads(lines[0])
    assert head["kind"] == "summary" and head["enabled"] is True
    assert head["verdicts"]["check.verdicts.denied"] > 0
    assert head["stats"]["ring"] == len(log)
    assert len(lines) == 5
    for ln in lines[1:]:
        e = json.loads(ln)
        assert {"resource", "permission", "subject", "verdict"} <= set(e)
    # incident bundles carry the last-N decisions
    rec = _trace.recorder()
    iid = rec.trigger("test.decision_carriage")
    rec.flush()
    bundle_head = json.loads(rec.bundle(iid).splitlines()[0])
    assert bundle_head["decisions"]
    assert bundle_head["decisions"][-1]["verdict"] in ("allowed", "denied")


def test_serving_provenance_dedup_parked_and_cache_hit():
    c = new_tpu_evaluator(with_latency_mode(), with_decision_log())
    ctx = background()
    c.write_schema(ctx, """
definition user {}
definition doc { relation reader: user  permission read = reader }
""")
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:a", "reader", "user:u"))
    c.write(ctx, txn)
    log = _decisions.get()
    with c.with_serving(cs=consistency.min_latency(), cache=True) as h:
        q = rel.must_from_triple("doc:a", "read", "user:u")
        assert h.check(ctx, q) == [True]
        assert h.check(ctx, q) == [True]  # cache-served
    entries = log.tail()
    assert any(e.get("cache_hit") for e in entries)
    # the dedup_parked flag rides the future → handle records it
    from gochugaru_tpu.serve.batcher import SubmitFuture

    fut = SubmitFuture(0.0)
    assert fut.dedup_parked is False
    fut.dedup_parked = True
    _decisions.record_rels([q], [True], strategy=consistency.min_latency(),
                           dedup_parked=True, latency_s=0.001)
    assert log.tail()[-1]["dedup_parked"] is True


def test_decisions_endpoint_disabled_and_columnar_decode():
    c = new_tpu_evaluator(with_latency_mode(), with_telemetry(port=0))
    body = urllib.request.urlopen(
        c.telemetry.url + "/decisions"
    ).read().decode()
    head = json.loads(body.strip().splitlines()[0])
    assert head["enabled"] is False
    # columnar recording decodes only kept rows
    m = Metrics()
    log = DecisionLog(registry=m, sample_rate=1.0)
    _decisions.install(log)
    decoded = []

    def decode(i):
        decoded.append(i)
        return f"doc:d{i}", "read", f"user:u{i}"

    _decisions.record_cols(4, [True, False, True, True], decode,
                           revision=2, strategy="min_latency",
                           latency_s=0.01)
    assert len(log.tail()) == 4 and sorted(decoded) == [0, 1, 2, 3]
    e = log.tail()[1]
    assert e["verdict"] == "denied" and e["resource"] == "doc:d1"

"""Device-engine tests: every check differentially validated against the
oracle (the tier-2 strategy from SURVEY.md §4 — the oracle plays the role
of `spicedb serve-testing`)."""

import random

import numpy as np
import pytest

from gochugaru_tpu import rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.oracle import F, T, U, Oracle
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot


def setup(schema_text, tuples, config=None, now_us=1_700_000_000_000_000):
    cs = compile_schema(parse_schema(schema_text))
    rels = [t if isinstance(t, rel.Relationship) else rel.must_from_tuple(*t) for t in tuples]
    interner = Interner()
    snap = build_snapshot(1, cs, interner, rels, epoch_us=now_us)
    programs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    oracle = Oracle(cs, rels, programs, now_us=now_us)
    engine = DeviceEngine(cs, config)
    dsnap = engine.prepare(snap)
    return engine, dsnap, oracle, now_us


def run_checks(engine, dsnap, oracle, now_us, queries):
    """queries: list of (resource, permission, subject) triple strings.
    Asserts device (definite, possible) matches oracle tri-state."""
    rels = [rel.must_from_triple(r, p, s) for (r, p, s) in queries]
    d, p, ovf = engine.check_batch(dsnap, rels, now_us=now_us)
    for i, (r, pm, s) in enumerate(queries):
        tri = oracle.check_relationship(rels[i])
        assert not ovf[i], f"unexpected overflow for {queries[i]}"
        assert d[i] == (tri == T), f"{queries[i]}: device definite={d[i]} oracle={tri}"
        assert p[i] == (tri >= U), f"{queries[i]}: device possible={p[i]} oracle={tri}"


EXAMPLE = """
definition user {}
definition document {
    relation writer: user
    relation reader: user
    permission edit = writer
    permission view = reader + edit
}
"""


def test_reference_matrix_on_device():
    engine, dsnap, oracle, now = setup(
        EXAMPLE,
        [
            ("document:t1#writer", "user:alice"),
            ("document:t1#reader", "user:bob"),
            ("document:t2#writer", "user:charlie"),
        ],
    )
    run_checks(
        engine, dsnap, oracle, now,
        [
            ("document:t1", "edit", "user:alice"),
            ("document:t1", "edit", "user:bob"),
            ("document:t1", "view", "user:bob"),
            ("document:t1", "view", "user:alice"),
            ("document:t2", "edit", "user:charlie"),
            ("document:t2", "view", "user:alice"),
            ("document:nonexistent", "edit", "user:alice"),
            ("document:t1", "ghost", "user:alice"),
            ("document:t1", "edit", "user:ghost"),
        ],
    )


NESTED = """
definition user {}
definition group { relation member: user | group#member }
definition document {
    relation viewer: group#member
    permission view = viewer
}
"""


def test_nested_groups_on_device():
    engine, dsnap, oracle, now = setup(
        NESTED,
        [
            ("group:leaf#member", "user:amy"),
            ("group:mid#member", "group:leaf#member"),
            ("group:top#member", "group:mid#member"),
            ("document:d#viewer", "group:top#member"),
            ("document:e#viewer", "group:leaf#member"),
        ],
    )
    run_checks(
        engine, dsnap, oracle, now,
        [
            ("document:d", "view", "user:amy"),
            ("document:e", "view", "user:amy"),
            ("document:d", "view", "user:bob"),
            ("group:top", "member", "user:amy"),
            ("group:mid", "member", "user:amy"),
        ],
    )


def test_userset_self_identity_on_device():
    engine, dsnap, oracle, now = setup(
        NESTED, [("document:d#viewer", "group:g#member")]
    )
    rels = [
        rel.must_from_tuple("group:g#member", "group:g#member"),
        rel.must_from_tuple("document:d#view", "group:g#member"),
    ]
    d, p, ovf = engine.check_batch(dsnap, rels, now_us=now)
    assert d[0] and d[1]
    assert oracle.check_relationship(rels[0]) == T
    assert oracle.check_relationship(rels[1]) == T


FOLDERS = """
definition user {}
definition folder {
    relation parent: folder
    relation owner: user
    permission view = owner + parent->view
}
definition document {
    relation folder: folder
    relation viewer: user
    relation banned: user
    permission view = (viewer + folder->view) - banned
}
"""


def test_folder_recursion_on_device():
    triples = [("folder:f0#owner", "user:root")]
    for i in range(1, 6):
        triples.append((f"folder:f{i}#parent", f"folder:f{i-1}"))
    triples.append(("document:d#folder", "folder:f5"))
    triples.append(("document:d#viewer", "user:amy"))
    triples.append(("document:d#banned", "user:amy"))
    engine, dsnap, oracle, now = setup(FOLDERS, triples)
    run_checks(
        engine, dsnap, oracle, now,
        [
            ("document:d", "view", "user:root"),  # 5-hop arrow chain
            ("folder:f5", "view", "user:root"),
            ("folder:f0", "view", "user:root"),
            ("document:d", "view", "user:amy"),  # banned beats viewer
            ("document:d", "view", "user:other"),
        ],
    )


def test_intersection_and_wildcard_on_device():
    engine, dsnap, oracle, now = setup(
        """
        definition user {}
        definition vault {
            relation manager: user
            relation auditor: user | user:*
            permission open = manager & auditor
        }
        """,
        [
            ("vault:v#manager", "user:amy"),
            ("vault:v#auditor", "user:amy"),
            ("vault:v#manager", "user:bob"),
            ("vault:w#manager", "user:cat"),
            ("vault:w#auditor", "user:*"),
        ],
    )
    run_checks(
        engine, dsnap, oracle, now,
        [
            ("vault:v", "open", "user:amy"),
            ("vault:v", "open", "user:bob"),
            ("vault:w", "open", "user:cat"),  # wildcard satisfies auditor
            ("vault:w", "open", "user:amy"),
            ("vault:w", "auditor", "user:never_seen"),  # wildcard, unknown subject
        ],
    )


def test_caveats_flow_to_possible_plane():
    r1 = rel.must_from_triple("doc:d", "viewer", "user:amy").with_caveat("c", {})
    engine, dsnap, oracle, now = setup(
        """
        caveat c(flag bool) { flag }
        definition user {}
        definition doc {
            relation viewer: user | user with c
            permission view = viewer
        }
        """,
        [r1, ("doc:d#viewer", "user:bob")],
    )
    rels = [
        rel.must_from_triple("doc:d", "view", "user:amy"),
        rel.must_from_triple("doc:d", "view", "user:bob"),
        rel.must_from_triple("doc:d", "view", "user:eve"),
    ]
    d, p, ovf = engine.check_batch(dsnap, rels, now_us=now)
    # amy: conditional → not definite but possible (client resolves on host)
    assert not d[0] and p[0]
    assert oracle.check_relationship(rels[0]) == U
    # bob: unconditional
    assert d[1] and p[1]
    # eve: nothing
    assert not d[2] and not p[2]


def test_expiration_on_device():
    import datetime as dt

    now_us = 1_700_000_000_000_000
    past = dt.datetime.fromtimestamp((now_us - 3600_000_000) / 1e6, tz=dt.timezone.utc)
    future = dt.datetime.fromtimestamp((now_us + 3600_000_000) / 1e6, tz=dt.timezone.utc)
    engine, dsnap, oracle, now = setup(
        """
        use expiration
        definition user {}
        definition door { relation opener: user with expiration
                          permission open = opener }
        """,
        [
            rel.must_from_triple("door:front", "opener", "user:old").with_expiration(past),
            rel.must_from_triple("door:front", "opener", "user:new").with_expiration(future),
        ],
        now_us=now_us,
    )
    run_checks(
        engine, dsnap, oracle, now,
        [
            ("door:front", "open", "user:old"),
            ("door:front", "open", "user:new"),
        ],
    )


def test_overflow_flags_instead_of_wrong_answers():
    # fanout bigger than the arrow cap → overflow must be reported
    triples = [("document:d#viewer", "user:amy")]
    for i in range(10):
        triples.append((f"document:d#folder", f"folder:f{i}"))
    triples.append(("folder:f7#owner", "user:amy"))
    engine, dsnap, oracle, now = setup(
        FOLDERS, triples, config=EngineConfig.for_schema(
            compile_schema(parse_schema(FOLDERS)), arrow_fanout=4
        )
    )
    rels = [rel.must_from_triple("document:d", "view", "user:bob")]
    d, p, ovf = engine.check_batch(dsnap, rels, now_us=now)
    assert ovf[0]  # 10 folder edges > fanout 4


GH_RBAC = """
definition user {}
definition team {
    relation member: user
}
definition org {
    relation admin: user
    relation member: user | team#member
}
definition repo {
    relation org: org
    relation maintainer: user | team#member
    relation reader: user
    permission admin = org->admin + maintainer
    permission read = reader + admin + org->member
}
"""


def test_github_rbac_differential_random():
    rng = random.Random(42)
    users = [f"user:u{i}" for i in range(30)]
    teams = [f"team:t{i}" for i in range(5)]
    orgs = [f"org:o{i}" for i in range(3)]
    repos = [f"repo:r{i}" for i in range(10)]
    triples = []
    for t in teams:
        for u in rng.sample(users, 6):
            triples.append((f"{t}#member", u))
    for o in orgs:
        triples.append((f"{o}#admin", rng.choice(users)))
        for t in rng.sample(teams, 2):
            triples.append((f"{o}#member", f"{t}#member"))
        for u in rng.sample(users, 4):
            triples.append((f"{o}#member", u))
    for r in repos:
        triples.append((f"{r}#org", rng.choice(orgs)))
        triples.append((f"{r}#maintainer", f"{rng.choice(teams)}#member"))
        for u in rng.sample(users, 2):
            triples.append((f"{r}#reader", u))

    engine, dsnap, oracle, now = setup(GH_RBAC, triples)
    queries = []
    for r in repos:
        for u in rng.sample(users, 10):
            perm = rng.choice(["read", "admin"])
            queries.append((r, perm, u))
    run_checks(engine, dsnap, oracle, now, queries)


def test_empty_batch():
    engine, dsnap, oracle, now = setup(EXAMPLE, [("document:a#reader", "user:u")])
    d, p, ovf = engine.check_batch(dsnap, [], now_us=now)
    assert d.shape == (0,)

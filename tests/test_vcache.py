"""Revision-pinned verdict cache + serving dedup (engine/vcache.py):
key packing exactness, byte-bounded revision-shard LRU, the consistency
strategies as read policy, the delta-chain zero-stale guarantee across
all four strategies, the live-context caveat exclusion, pinned now_us on
time-gated entries, in-batch dedup parity, the singleflight dispatch
window (park/fan-out/failure), chaos with ``cache.lookup`` armed, and
cache-off bitwise behavior."""

import threading
import time

import numpy as np
import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_host_only_evaluation,
    with_latency_mode,
    with_store,
    with_verdict_cache,
)
from gochugaru_tpu.engine import vcache
from gochugaru_tpu.serve import MicroBatcher, ServeConfig
from gochugaru_tpu.utils import faults, metrics
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import BulkCheckItemError, UnavailableError

CTX = background()
ALL_CS = ("full", "min_latency", "at_least", "snapshot")


def _strategy(name, rev_token):
    if name == "full":
        return consistency.full()
    if name == "min_latency":
        return consistency.min_latency()
    if name == "at_least":
        return consistency.at_least(rev_token)
    return consistency.snapshot(rev_token)


def _world(*opts):
    """RBAC world through a store-backed client + host-only oracle
    client sharing the store."""
    c = new_tpu_evaluator(with_latency_mode(), *opts)
    c.write_schema(CTX, """
    definition user {}
    definition org { relation admin: user  relation member: user }
    definition repo {
        relation org: org
        relation reader: user
        permission admin = org->admin
        permission read = reader + admin + org->member
    }
    """)
    rng = np.random.default_rng(11)
    txn = rel.Txn()
    for i in range(120):
        txn.touch(rel.must_from_triple(
            f"repo:r{i}", "reader", f"user:u{rng.integers(60)}"))
        txn.touch(rel.must_from_triple(f"repo:r{i}", "org", f"org:o{i % 3}"))
    for o in range(3):
        txn.touch(rel.must_from_triple(f"org:o{o}", "admin", f"user:u{o}"))
        txn.touch(rel.must_from_triple(
            f"org:o{o}", "member", f"user:u{o + 10}"))
    rev = c.write(CTX, txn)
    oracle = new_tpu_evaluator(with_host_only_evaluation(),
                               with_store(c.store))
    return c, oracle, rev


def _checks(rng, n):
    return [rel.must_from_triple(
        f"repo:r{rng.integers(120)}", "read", f"user:u{rng.integers(60)}")
        for _ in range(n)]


# ---------------------------------------------------------------------------
# keys / packing
# ---------------------------------------------------------------------------

def test_pack_cols_exact_int64_and_tuple_fallback():
    p = np.array([3, 3, 7], np.int32)
    r = np.array([10, 10, 99], np.int32)
    s = np.array([5, 5, 5], np.int32)
    k = vcache.pack_cols(p, r, s)
    assert isinstance(k, np.ndarray) and k.dtype == np.int64
    assert k[0] == k[1] != k[2]
    # scalar pack matches the vectorized layout exactly
    assert vcache.pack_one(3, 10, 5) == int(k[0])
    # distinct triples can never alias under the exact pack
    assert len({int(x) for x in k}) == 2
    # ids past the pack bounds degrade to exact tuples, not wrong ints
    big = np.array([1 << 25, 7], np.int32)
    kt = vcache.pack_cols(np.array([1, 1], np.int32), big,
                          np.array([2, 3], np.int32))
    assert isinstance(kt, list) and kt[0] == (1, 1 << 25, 2)
    assert vcache.pack_one(1, 1 << 25, 2) == (1, 1 << 25, 2)


def test_rel_key_and_context_fingerprint():
    r1 = rel.must_from_triple("repo:r1", "read", "user:u1")
    r2 = rel.must_from_triple("repo:r1", "read", "user:u1")
    assert vcache.rel_key(r1) == vcache.rel_key(r2)
    assert vcache.rel_key(r1)[1] == vcache.EMPTY_CTX_FP
    rc = r1.with_caveat("c", {"tier": 3})
    rc2 = r1.with_caveat("c", {"tier": 3})
    rc3 = r1.with_caveat("c", {"tier": 4})
    assert vcache.rel_key(rc) == vcache.rel_key(rc2)
    assert vcache.rel_key(rc)[1] != vcache.EMPTY_CTX_FP
    assert vcache.rel_key(rc) != vcache.rel_key(rc3)


# ---------------------------------------------------------------------------
# VerdictCache structure
# ---------------------------------------------------------------------------

def test_cache_lookup_insert_and_snapshot_rebuild():
    m = metrics.Metrics()
    vc = vcache.VerdictCache(registry=m)
    rng = np.random.default_rng(0)
    keys = vcache.pack_cols(
        np.full(5000, 2, np.int32),
        rng.permutation(5000).astype(np.int32),
        rng.integers(0, 100, 5000).astype(np.int32),
    )
    verd = rng.random(5000) < 0.5
    vc.insert_cols(7, keys, verd, now_us=123)
    # rebuild threshold (1024) crossed → sorted snapshot + extra dict
    sh = vc._revs[7]["c"]
    assert sh.snap[0].shape[0] > 0
    arr = vc.lookup_cols(7, keys)
    assert ((arr >= 0)).all()
    assert ((arr & 1).astype(bool) == verd).all()
    assert (arr >> 1 == 123).all()  # pinned now_us rides every entry
    # misses at another revision; hit/miss counters add up
    assert vc.lookup_cols(8, keys) is None
    assert m.counter("cache.hits") == 5000
    assert m.counter("cache.misses") == 5000
    assert vc.get_col(7, int(vcache.keys_list(keys)[0])) == (
        bool(verd[0]), 123
    )


def test_cache_byte_bound_evicts_oldest_revision_shard():
    m = metrics.Metrics()
    vc = vcache.VerdictCache(
        max_bytes=vcache.VerdictCache.COL_ENTRY_BYTES * 1000, registry=m
    )
    for rev in range(1, 5):
        keys = np.arange(rev * 1000, rev * 1000 + 400, dtype=np.int64)
        vc.insert_cols(rev, keys, np.ones(400, bool), now_us=1)
    assert 1 not in vc.resident_revisions
    assert vc.stats()["bytes"] <= vc.max_bytes
    assert m.counter("cache.evicted_revisions") >= 1
    # most-recently-used revision survives
    assert 4 in vc.resident_revisions


def test_cache_drop_revision_structural_invalidation():
    vc = vcache.VerdictCache(registry=metrics.Metrics())
    keys = np.arange(10, dtype=np.int64)
    vc.insert_cols(3, keys, np.ones(10, bool), now_us=1)
    vc.drop_revision(3)
    assert vc.lookup_cols(3, keys) is None
    assert vc.stats()["entries"] == 0


def test_policy_for_maps_strategies():
    assert vcache.policy_for(consistency.full()) == vcache.CACHE_OFF
    assert vcache.policy_for(None) == vcache.CACHE_OFF
    for cs in (consistency.min_latency(), consistency.at_least("gtz1.1"),
               consistency.snapshot("gtz1.1")):
        assert vcache.policy_for(cs) == vcache.CACHE_RW


# ---------------------------------------------------------------------------
# client integration: read policy + revision keying
# ---------------------------------------------------------------------------

def test_cached_checks_hit_and_full_bypasses():
    c, oracle, rev = _world(with_verdict_cache())
    m = metrics.default
    rng = np.random.default_rng(1)
    qs = _checks(rng, 12)
    ml = consistency.min_latency()
    want = oracle.check(CTX, consistency.full(), *qs)
    assert c.check(CTX, ml, *qs) == want
    h0 = m.counter("cache.hits")
    assert c.check(CTX, ml, *qs) == want  # warm repeat
    assert m.counter("cache.hits") - h0 >= len(qs)
    # full() bypasses the cache entirely — no reads, no hits
    h1, mi1 = m.counter("cache.hits"), m.counter("cache.misses")
    assert c.check(CTX, consistency.full(), *qs) == want
    assert m.counter("cache.hits") == h1
    assert m.counter("cache.misses") == mi1


def test_delta_chain_zero_stale_verdicts_all_strategies():
    """Writes interleave with cached checks at all four consistency
    strategies: every verdict must equal the host oracle's at the SAME
    strategy (identical snapshot resolution), across the whole chain —
    revision-keyed reads only, zero stale verdicts."""
    c, oracle, rev0 = _world(with_verdict_cache())
    m = metrics.default
    rng = np.random.default_rng(2)
    qs = _checks(rng, 10)
    pinned = []  # (rev_token, verdicts at that revision)
    for round_i in range(6):
        # a write that flips real verdicts: toggle reader edges
        txn = rel.Txn()
        i = int(rng.integers(120))
        e = rel.must_from_triple(f"repo:r{i}", "reader",
                                 f"user:u{int(rng.integers(60))}")
        (txn.delete if round_i % 2 else txn.touch)(e)
        rev = c.write(CTX, txn)
        for name in ALL_CS:
            cs = _strategy(name, rev)
            got = c.check(CTX, cs, *qs)
            want = oracle.check(CTX, cs, *qs)
            assert got == want, (round_i, name)
            # repeat immediately — served warm, still exact
            assert c.check(CTX, cs, *qs) == want, (round_i, name, "warm")
        snap = c.store.snapshot_for(consistency.full())
        pinned.append((rev, c.check(CTX, consistency.snapshot(rev), *qs)))
        assert int(snap.revision) == int(rev.split(".")[-1])
    # pinned revisions still answer their own (historical) verdicts as
    # long as they stay resident — revision keying, not invalidation
    for rev, verdicts in pinned[-2:]:
        assert c.check(CTX, consistency.snapshot(rev), *qs) == verdicts
    assert m.counter("cache.hits") > 0


def test_min_latency_write_opens_fresh_keyspace():
    """A write mints a new revision; once the store serves it, cached
    verdicts from the previous revision are structurally unreachable —
    no stale read is possible through the cache."""
    c, oracle, _ = _world(with_verdict_cache())
    q = rel.must_from_triple("repo:r0", "read", "user:u55")
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("repo:r0", "reader", "user:u55"))
    c.write(CTX, txn)
    assert c.check(CTX, consistency.full(), q) == [True]
    ml = consistency.min_latency()
    assert c.check(CTX, ml, q) == [True]
    assert c.check(CTX, ml, q) == [True]  # cached at this revision
    txn = rel.Txn()
    txn.delete(rel.must_from_triple("repo:r0", "reader", "user:u55"))
    rev = c.write(CTX, txn)
    # full() materializes the new head; the cached True at the old
    # revision must not leak into the new revision's reads
    assert c.check(CTX, consistency.full(), q) == [False]
    assert c.check(CTX, consistency.at_least(rev), q) == [False]
    assert c.check(CTX, consistency.min_latency(), q) == [False]


# ---------------------------------------------------------------------------
# caveats and time
# ---------------------------------------------------------------------------

def _caveat_world():
    c = new_tpu_evaluator(with_latency_mode(), with_verdict_cache())
    c.write_schema(CTX, """
    caveat tier_at_least(tier int, minimum int) { tier >= minimum }
    definition user {}
    definition doc {
        relation viewer: user with tier_at_least
        permission view = viewer
    }
    """)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat(
        "tier_at_least", {"minimum": 5}))
    txn.touch(rel.must_from_triple("doc:b", "viewer", "user:u2").with_caveat(
        "tier_at_least", {"minimum": 5, "tier": 9}))
    c.write(CTX, txn)
    return c


def test_live_context_caveat_never_served_from_cache():
    """A check whose caveat reads LIVE query context must never read or
    write the cache — repeated identical context-bearing checks show no
    hits, and flipping the context flips the verdict."""
    c = _caveat_world()
    m = metrics.default
    ml = consistency.min_latency()
    q_hi = rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
        "", {"tier": 7})
    q_lo = rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
        "", {"tier": 3})
    h0 = m.counter("cache.hits")
    for _ in range(3):
        assert c.check(CTX, ml, q_hi) == [True]
        assert c.check(CTX, ml, q_lo) == [False]
    assert m.counter("cache.hits") == h0, "live-context verdict was cached"
    assert m.counter("cache.bypass") > 0


def test_context_free_caveat_outcome_caches():
    """Context-free caveat outcomes (stored context decides, or missing
    context → no grant) cache normally with a pinned now_us."""
    c = _caveat_world()
    m = metrics.default
    ml = consistency.min_latency()
    # doc:b's stored context is complete → definite, context-free
    qb = rel.must_from_triple("doc:b", "view", "user:u2")
    # doc:a without context → caveat cannot pass → definite False
    qa = rel.must_from_triple("doc:a", "view", "user:u1")
    assert c.check(CTX, ml, qb, qa) == [True, False]
    h0 = m.counter("cache.hits")
    assert c.check(CTX, ml, qb, qa) == [True, False]
    assert m.counter("cache.hits") - h0 == 2


def test_expiring_edge_verdict_pins_now_us():
    import datetime as dt

    c = new_tpu_evaluator(with_latency_mode(), with_verdict_cache())
    c.write_schema(CTX, """
    definition user {}
    definition doc { relation viewer: user  permission view = viewer }
    """)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:x", "viewer", "user:u1")
              .with_expiration(dt.datetime.now(dt.timezone.utc)
                               + dt.timedelta(hours=1)))
    c.write(CTX, txn)
    ml = consistency.min_latency()
    q = rel.must_from_triple("doc:x", "view", "user:u1")
    t0 = int(time.time() * 1_000_000)
    assert c.check(CTX, ml, q) == [True]
    snap = c.store.snapshot_for(ml)
    entry = c._vcache._revs[snap.revision]["r"][vcache.rel_key(q)]
    # the entry records the evaluation-time pin (LookupCursor
    # discipline): a later hit serves the verdict AS OF that time
    assert abs(entry[1] - t0) < 60_000_000
    h0 = metrics.default.counter("cache.hits")
    assert c.check(CTX, ml, q) == [True]
    assert metrics.default.counter("cache.hits") == h0 + 1


# ---------------------------------------------------------------------------
# dedup: in-batch + the singleflight window
# ---------------------------------------------------------------------------

def test_columns_dedup_parity_and_batch_dups_counter():
    c, oracle, _ = _world(with_verdict_cache())
    m = metrics.default
    snap = c.store.snapshot_for(consistency.full())
    inter = snap.interner
    slot = snap.compiled.slot_of_name["read"]
    rng = np.random.default_rng(3)
    user_pool = [n for i in range(60)
                 if (n := inter.lookup("user", f"u{i}")) >= 0]
    res = np.array([inter.lookup("repo", f"r{i}")
                    for i in rng.integers(0, 120, 64)], np.int32)
    subj = np.array([user_pool[i]
                     for i in rng.integers(0, len(user_pool), 64)], np.int32)
    res = np.tile(res, 4)  # heavy duplication
    subj = np.tile(subj, 4)
    perm = np.full(res.shape[0], slot, np.int32)
    d0 = m.counter("dedup.batch_dups")
    got = c._evaluate_columns(
        snap, res, perm, subj, latency=True,
        cs=consistency.min_latency(), dedup=True,
    )
    assert m.counter("dedup.batch_dups") - d0 >= 192
    want = np.fromiter(
        (c._check_interned(c._oracle_for(snap), snap, res[i], perm[i],
                           subj[i]) for i in range(res.shape[0])),
        bool, count=res.shape[0],
    )
    assert (got == want).all()


def test_bulk_item_error_remaps_to_caller_space():
    c, _, _ = _world(with_verdict_cache())
    snap = c.store.snapshot_for(consistency.full())
    q = np.arange(8, dtype=np.int32)
    dup = np.concatenate([q, q])  # 16 rows → 8 unique

    def boom(snap_, r, p, s, latency, span=None):
        # unique-space failure at index 3 with 3 resolved results
        raise BulkCheckItemError(3, np.array([True, False, True]),
                                 RuntimeError("x"))

    c._evaluate_columns_direct = boom
    with pytest.raises(BulkCheckItemError) as ei:
        c._evaluate_columns(
            snap, dup, np.zeros(16, np.int32), dup, latency=False,
            cs=consistency.min_latency(), dedup=True,
        )
    e = ei.value
    # caller-space: the reported prefix is fully resolved and the index
    # points at the first unresolved caller row
    assert e.index == 3
    assert len(e.results) == 3


def test_singleflight_window_park_and_fanout_cols():
    m = metrics.Metrics()
    sf = vcache.Singleflight(registry=m)
    keys = np.array([10, 20, 30, 40], np.int64)
    sf.open_cols(keys, np.sort(keys))
    assert sf.active
    assert sf.probe(20) and not sf.probe(99)
    from gochugaru_tpu.serve.batcher import SubmitFuture

    fut = SubmitFuture(time.perf_counter())
    assert sf.try_park(np.array([30, 10], np.int64), fut, "cols", 2)
    # partial overlap refuses to park
    fut2 = SubmitFuture(time.perf_counter())
    assert not sf.try_park(np.array([30, 99], np.int64), fut2, "cols", 2)
    verdicts = np.array([True, False, True, False])
    assert sf.close(verdicts, None, time.perf_counter()) == 1
    out = fut.result(timeout=1.0)
    assert out.tolist() == [True, True]  # rows 30→True, 10→True
    assert not sf.active
    assert m.counter("serve.dedup_parked") == 2
    assert m.counter("serve.checks") == 2


def test_singleflight_window_failure_rejects_retriable():
    sf = vcache.Singleflight(registry=metrics.Metrics())
    km = {vcache.rel_key(rel.must_from_triple("a:1", "r", "b:2")): 0}
    sf.open_map(km)
    from gochugaru_tpu.serve.batcher import SubmitFuture

    fut = SubmitFuture(time.perf_counter())
    assert sf.try_park(list(km.keys()), fut, "rels", 1)
    sf.close(None, UnavailableError("twin failed"), time.perf_counter())
    with pytest.raises(UnavailableError):
        fut.result(timeout=1.0)


def test_serving_parks_duplicate_submission_on_inflight_batch():
    """End-to-end: a submission arriving while its twin's batch is
    mid-dispatch parks on the window and resolves from the same
    verdicts — no queue slot, no second dispatch."""
    release = threading.Event()
    entered = threading.Event()

    def dispatch_cols(q_res, q_perm, q_subj, latency, span):
        entered.set()
        assert release.wait(5.0)
        return q_res > 0

    m = metrics.Metrics()
    b = MicroBatcher(
        tiers=(256, 1024, 4096), start=False, registry=m,
        dispatch_cols=dispatch_cols,
    )
    cols = (np.array([1, 0, 2], np.int32), np.array([0, 0, 0], np.int32),
            np.array([7, 8, 9], np.int32))
    f1 = b.submit_columns("a", *cols)
    batch = b.form_batch()
    t = threading.Thread(target=b.dispatch_batch, args=(batch,))
    t.start()
    assert entered.wait(5.0)
    # twin arrives mid-dispatch → parks (depth stays zero)
    f2 = b.submit_columns("b", *cols)
    assert b.depth == 0
    assert m.counter("serve.dedup_parked") == 3
    release.set()
    t.join(5.0)
    assert f1.result(timeout=5.0).tolist() == [True, False, True]
    assert f2.result(timeout=5.0).tolist() == [True, False, True]
    assert m.counter("serve.batches") == 1
    b.close()


def test_serving_window_failure_parked_future_retriable():
    def dispatch_cols(q_res, q_perm, q_subj, latency, span):
        entered.set()
        assert release.wait(5.0)
        raise UnavailableError("transient device fault")

    release = threading.Event()
    entered = threading.Event()
    m = metrics.Metrics()
    b = MicroBatcher(
        tiers=(256,), start=False, registry=m, dispatch_cols=dispatch_cols,
    )
    cols = (np.array([1], np.int32),) * 3
    f1 = b.submit_columns("a", *cols)
    batch = b.form_batch()
    t = threading.Thread(target=b.dispatch_batch, args=(batch,))
    t.start()
    assert entered.wait(5.0)
    f2 = b.submit_columns("b", *cols)
    release.set()
    t.join(5.0)
    with pytest.raises(UnavailableError):
        f1.result(timeout=5.0)
    with pytest.raises(UnavailableError):
        f2.result(timeout=5.0)
    b.close()


def test_full_strategy_handle_never_parks():
    c, _, _ = _world()
    h = c.with_serving(cs=consistency.full())
    try:
        assert h.batcher._sf is None  # Full must see its own head
    finally:
        h.close()
    h2 = c.with_serving(cs=consistency.min_latency())
    try:
        assert h2.batcher._sf is not None
    finally:
        h2.close()


def test_dedup_off_config_disables_all_of_it():
    """dedup=False keeps duplicate submissions off the parked-twin
    path.  The Singleflight window stays BUILT (the online controller
    toggles dedup by swapping the config — tune/controller.py), so the
    assertion is behavioral: a twin arriving mid-dispatch queues for
    its own dispatch instead of parking, and a live ``apply_config``
    swap re-arms parking without rebuilding the batcher."""
    release = threading.Event()
    entered = threading.Event()

    def dispatch_cols(q_res, q_perm, q_subj, latency, span):
        entered.set()
        assert release.wait(5.0)
        return q_res > 0

    m = metrics.Metrics()
    b = MicroBatcher(
        tiers=(256, 1024, 4096), start=False, registry=m,
        dispatch_cols=dispatch_cols, config=ServeConfig(dedup=False),
    )
    cols = (np.array([1, 0, 2], np.int32), np.array([0, 0, 0], np.int32),
            np.array([7, 8, 9], np.int32))
    f1 = b.submit_columns("a", *cols)
    batch = b.form_batch()
    t = threading.Thread(target=b.dispatch_batch, args=(batch,))
    t.start()
    assert entered.wait(5.0)
    # twin arrives mid-dispatch → queues, no park, no shared verdicts
    f2 = b.submit_columns("b", *cols)
    assert b.depth == 3
    assert m.counter("serve.dedup_parked") == 0
    release.set()
    t.join(5.0)
    assert f1.result(timeout=5.0).tolist() == [True, False, True]
    b.dispatch_batch(b.form_batch())
    assert f2.result(timeout=5.0).tolist() == [True, False, True]
    assert m.counter("serve.batches") == 2

    # live re-arm: the same batcher parks once the config says dedup
    b.apply_config(ServeConfig(dedup=True))
    entered.clear()
    release.clear()
    f3 = b.submit_columns("a", *cols)
    batch = b.form_batch()
    t = threading.Thread(target=b.dispatch_batch, args=(batch,))
    t.start()
    assert entered.wait(5.0)
    f4 = b.submit_columns("b", *cols)
    assert b.depth == 0  # parked on f3's in-flight batch
    assert m.counter("serve.dedup_parked") == 3
    release.set()
    t.join(5.0)
    assert f3.result(timeout=5.0).tolist() == [True, False, True]
    assert f4.result(timeout=5.0).tolist() == [True, False, True]
    assert m.counter("serve.batches") == 3
    b.close()


# ---------------------------------------------------------------------------
# chaos + cache-off behavior
# ---------------------------------------------------------------------------

def test_chaos_soak_cache_lookup_and_dedup_fanout():
    """cache.lookup + batcher sites armed under concurrent duplicate-
    heavy serving load: oracle parity on every answer, zero lost or
    duplicated futures through the dedup fan-out (SubmitFuture asserts
    double-resolution; a hang would time out)."""
    c, oracle, _ = _world(with_verdict_cache())
    m = metrics.default
    pool = [_checks(np.random.default_rng(5), 6) for _ in range(10)]
    want = [oracle.check(CTX, consistency.full(), *qs) for qs in pool]
    mismatches = []
    with c.with_serving(cs=consistency.min_latency()) as h:
        with faults.default.armed("cache.lookup", probability=0.25,
                                  seed=3) as spec:
            with faults.default.armed("batcher.dispatch", probability=0.1,
                                      seed=4):
                def worker(w):
                    lr = np.random.default_rng(w)
                    for _ in range(12):
                        i = int(lr.integers(len(pool)))
                        got = h.check(CTX.with_timeout(60.0), *pool[i],
                                      client_id=w)
                        if list(got) != want[i]:
                            mismatches.append((w, i))

                ts = [threading.Thread(target=worker, args=(w,))
                      for w in range(6)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
    assert not mismatches
    assert spec.fired > 0, "cache.lookup never fired"
    assert m.counter("cache.hits") > 0


def test_cache_off_client_touches_no_cache_state():
    base = metrics.default.snapshot()
    c, oracle, _ = _world()  # no with_verdict_cache
    rng = np.random.default_rng(9)
    qs = _checks(rng, 8)
    want = oracle.check(CTX, consistency.full(), *qs)
    assert c.check(CTX, consistency.min_latency(), *qs) == want
    with c.with_serving(cs=consistency.min_latency(), cache=False,
                        config=ServeConfig(dedup=False)) as h:
        assert h.check(CTX, *qs) == want
    now = metrics.default.snapshot()
    for k in ("cache.hits", "cache.misses", "cache.puts", "dedup.batch_dups",
              "serve.dedup_parked"):
        assert now.get(k, 0) == base.get(k, 0), k
    assert c._vcache is None


def test_dsnap_eviction_drops_cache_shard():
    c, _, _ = _world(with_verdict_cache())
    ml = consistency.min_latency()
    q = rel.must_from_triple("repo:r1", "read", "user:u1")
    revs = []
    for i in range(c.SNAPSHOT_CACHE_MAX + 2):
        txn = rel.Txn()
        txn.touch(rel.must_from_triple(
            f"repo:r{i}", "reader", f"user:uev{i}"))
        revs.append(c.write(CTX, txn))
        c.check(CTX, consistency.full(), q)  # materialize + prepare
        c.check(CTX, consistency.at_least(revs[-1]), q)  # populate shard
    resident = c._vcache.resident_revisions
    first = int(revs[0].split(".")[-1])
    assert first not in resident, (
        "evicted dsnap revision kept its verdict shard"
    )


def test_perf_report_carries_cache_section():
    from gochugaru_tpu.utils import perf as _perf

    c, _, _ = _world(with_verdict_cache())
    c.check(CTX, consistency.min_latency(),
            rel.must_from_triple("repo:r1", "read", "user:u1"))
    rep = _perf.render_report()
    assert "vcache" in rep and rep["vcache"]["entries"] >= 1


def test_interner_memo_hits_and_append_only_safety():
    c, oracle, _ = _world()
    m = metrics.default
    q = rel.must_from_triple("repo:r1", "read", "user:u1")
    c.check(CTX, consistency.full(), q)
    h0 = m.counter("intern.memo_hits")
    c.check(CTX, consistency.full(), q)
    assert m.counter("intern.memo_hits") > h0
    # a NEW object interned by a later write must be found (negative
    # lookups are never memoized)
    q2 = rel.must_from_triple("repo:r1", "read", "user:brand_new")
    assert c.check(CTX, consistency.full(), q2) == [False]
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("repo:r1", "reader", "user:brand_new"))
    c.write(CTX, txn)
    assert c.check(CTX, consistency.full(), q2) == [True]

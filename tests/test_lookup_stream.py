"""Cursor-paginated frontier lookups (engine/spmv.py + engine/lookup.py
page APIs): exact resume semantics, revision pinning, fault-injection
retry through the client envelope, and frontier-vs-walker parity —
including the sharded owner-routed hop path.

The cursor contract under test: a lookup's result stream is
DETERMINISTIC per (snapshot revision, query), pages resume exactly (no
duplicate and no lost IDs) whether the live stream is still cached or
the resume deterministically recomputes, and a cursor never silently
serves a different revision or query."""

import numpy as np
import pytest

import test_lookup as tl
from gochugaru_tpu import rel
from gochugaru_tpu.engine import lookup as lm
from gochugaru_tpu.engine import spmv
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.oracle import Oracle
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.utils import faults
from gochugaru_tpu.utils.errors import PreconditionFailedError

NOW = tl.NOW


def _paged_ids(engine, dsnap, oracle, uid, page_size, *, churn=False,
               through_strings=False):
    """Drain lookup_resources for ``uid`` via cursored pages; optionally
    drop the continuation cache between pages (forcing the
    recompute-and-skip path) or round-trip cursors through their string
    encoding."""
    out, pages, cursor = [], 0, None
    while True:
        if churn:
            dsnap.__dict__.pop("_lookup_streams", None)
        ids, cursor = lm.lookup_resources_page(
            engine, dsnap, "repo", "read", "user", uid,
            page_size=page_size, cursor=cursor, now_us=NOW,
            oracle_factory=lambda: oracle,
        )
        out.extend(ids)
        pages += 1
        if through_strings and cursor is not None:
            cursor = spmv.LookupCursor.decode(cursor.encode())
        if cursor is None:
            return out, pages


@pytest.fixture(scope="module")
def rbac():
    rels, users, teams, orgs, repos = tl.rbac_world(
        seed=7, n_users=24, n_repos=16
    )
    cs, engine, dsnap, oracle = tl.world(tl.RBAC, rels)
    return cs, engine, dsnap, oracle, rels, users, repos


def test_pagination_resumes_exactly_across_boundaries(rbac):
    cs, engine, dsnap, oracle, rels, users, repos = rbac
    assert spmv.frontier_ok(engine, dsnap)
    for uid in [u.split(":")[1] for u in users[:6]]:
        full = lm.lookup_resources_device(
            engine, dsnap, "repo", "read", "user", uid,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        for page_size in (1, 3):
            got, pages = _paged_ids(engine, dsnap, oracle, uid, page_size,
                                    through_strings=True)
            assert len(got) == len(set(got)), "duplicate id across pages"
            assert sorted(got) == full
            if full:
                assert pages >= len(full) // max(page_size, 1)


def test_pagination_recompute_resume_is_exact(rbac):
    """An evicted continuation (process restart, cache churn) resumes by
    deterministic recompute-and-skip — same exact page stream."""
    cs, engine, dsnap, oracle, rels, users, repos = rbac
    # a subject with a multi-page answer, so a resume really happens
    answers = {}
    for u in users:
        uid = u.split(":")[1]
        answers[uid] = lm.lookup_resources_device(
            engine, dsnap, "repo", "read", "user", uid,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
    uid = max(answers, key=lambda k: len(answers[k]))
    full = answers[uid]
    assert len(full) >= 3, "world must give someone a multi-page answer"
    got, _ = _paged_ids(engine, dsnap, oracle, uid, 2, churn=True)
    assert len(got) == len(set(got)) and sorted(got) == full
    from gochugaru_tpu.utils.metrics import default as m

    assert m.counter("lookup.stream_recomputes") > 0


def _heavy_uid(engine, dsnap, oracle, users):
    """A subject whose answer spans multiple 1-result pages."""
    best, n = None, -1
    for u in users:
        uid = u.split(":")[1]
        got = lm.lookup_resources_device(
            engine, dsnap, "repo", "read", "user", uid,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        if len(got) > n:
            best, n = uid, len(got)
    assert n >= 2, "world must give someone a multi-page answer"
    return best


def test_cursor_rejects_wrong_query_and_revision(rbac):
    cs, engine, dsnap, oracle, rels, users, repos = rbac
    uid = _heavy_uid(engine, dsnap, oracle, users)
    ids, cursor = lm.lookup_resources_page(
        engine, dsnap, "repo", "read", "user", uid,
        page_size=1, now_us=NOW, oracle_factory=lambda: oracle,
    )
    assert cursor is not None
    # different query, same cursor
    with pytest.raises(PreconditionFailedError):
        lm.lookup_resources_page(
            engine, dsnap, "repo", "admin", "user", uid,
            page_size=1, cursor=cursor, now_us=NOW,
            oracle_factory=lambda: oracle,
        )
    # stale revision
    bad = spmv.LookupCursor(cursor.revision + 1, cursor.token, cursor.pos)
    with pytest.raises(PreconditionFailedError):
        lm.lookup_resources_page(
            engine, dsnap, "repo", "read", "user", uid,
            page_size=1, cursor=bad, now_us=NOW,
            oracle_factory=lambda: oracle,
        )
    # malformed encoding
    with pytest.raises(PreconditionFailedError):
        spmv.LookupCursor.decode("not-a-cursor")


def test_cursor_revision_pinned_across_delta(rbac):
    """A cursor taken at revision R keeps serving R's answer after the
    store advances — the walker-backed page path covers the advanced
    revision (delta chains decline the frontier), and the R-pinned
    pagination completes with no dup/lost IDs."""
    from gochugaru_tpu.store.delta import apply_delta

    cs, engine, dsnap, oracle, rels, users, repos = rbac
    uid = users[2].split(":")[1]
    full_r1 = lm.lookup_resources_device(
        engine, dsnap, "repo", "read", "user", uid,
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    got, cursor = lm.lookup_resources_page(
        engine, dsnap, "repo", "read", "user", uid,
        page_size=2, now_us=NOW, oracle_factory=lambda: oracle,
    )
    # the world advances: this user gains a direct reader edge
    snap = dsnap.snapshot
    adds = [rel.must_from_tuple(f"{repos[-1]}#reader", f"user:{uid}")]
    snap2 = apply_delta(snap, 2, adds, [], interner=snap.interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    oracle2 = Oracle(cs, rels + adds, {}, now_us=NOW)
    want2 = sorted(oracle2.lookup_resources("repo", "read", "user", uid, ""))
    got2 = lm.lookup_resources_device(
        engine, ds2, "repo", "read", "user", uid,
        now_us=NOW, oracle_factory=lambda: oracle2,
    )
    assert got2 == want2 and got2 != full_r1
    # ... while the pinned cursor still completes revision 1's answer
    while cursor is not None:
        ids, cursor = lm.lookup_resources_page(
            engine, dsnap, "repo", "read", "user", uid,
            page_size=2, cursor=cursor, now_us=NOW,
            oracle_factory=lambda: oracle,
        )
        got.extend(ids)
    assert len(got) == len(set(got)) and sorted(got) == full_r1


def test_walker_backed_pages_on_delta_snapshots(rbac):
    """Delta-prepared snapshots decline the frontier (their reverse
    tables are at the base revision); the SAME page API serves them
    through the walker with identical cursor semantics."""
    from gochugaru_tpu.store.delta import apply_delta

    cs, engine, dsnap, oracle, rels, users, repos = rbac
    snap = dsnap.snapshot
    uid = users[3].split(":")[1]
    adds = [rel.must_from_tuple(f"{repos[0]}#reader", f"user:{uid}")]
    snap2 = apply_delta(snap, 2, adds, [], interner=snap.interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    oracle2 = Oracle(cs, rels + adds, {}, now_us=NOW)
    if ds2.flat_meta is not None and ds2.flat_meta.delta is not None:
        assert not spmv.frontier_ok(engine, ds2)
    out, cursor = [], None
    while True:
        ids, cursor = lm.lookup_resources_page(
            engine, ds2, "repo", "read", "user", uid,
            page_size=3, cursor=cursor, now_us=NOW,
            oracle_factory=lambda: oracle2,
        )
        out.extend(ids)
        if cursor is None:
            break
    want = sorted(oracle2.lookup_resources("repo", "read", "user", uid, ""))
    assert sorted(out) == want and len(out) == len(set(out))


def test_lookup_subjects_pages(rbac):
    cs, engine, dsnap, oracle, rels, users, repos = rbac
    rid = repos[0].split(":")[1]
    full = lm.lookup_subjects_device(
        engine, dsnap, "repo", rid, "read", "user",
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    out, cursor = [], None
    while True:
        ids, cursor = lm.lookup_subjects_page(
            engine, dsnap, "repo", rid, "read", "user",
            page_size=2, cursor=cursor, now_us=NOW,
            oracle_factory=lambda: oracle,
        )
        out.extend(ids)
        if cursor is None:
            break
    assert sorted(out) == full and len(out) == len(set(out))


def test_frontier_equals_walker_paths(rbac):
    """The device frontier engine and the host walker are two
    implementations of one contract: identical answers on the same
    snapshot (the walker is the parity oracle the bench enforces too)."""
    cs, engine, dsnap, oracle, rels, users, repos = rbac
    snap = dsnap.snapshot
    walker_engine = DeviceEngine(
        cs, EngineConfig.for_schema(cs, flat_rev_index=False)
    )
    wds = walker_engine.prepare(snap)
    assert not spmv.frontier_ok(walker_engine, wds)
    for u in users[:8]:
        uid = u.split(":")[1]
        got = lm.lookup_resources_device(
            engine, dsnap, "repo", "read", "user", uid,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        ref = lm.lookup_resources_device(
            walker_engine, wds, "repo", "read", "user", uid,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        assert got == ref


def test_client_envelope_retries_lookup_dispatch_fault():
    """An injected transient fault at the ``lookup.dispatch`` site
    surfaces as UnavailableError and the client's lookup surface retries
    it under the reference's backoff envelope — same contract as check
    dispatch (utils/faults.py round-7 discipline)."""
    from gochugaru_tpu import consistency, new_tpu_evaluator
    from gochugaru_tpu.rel.txn import Txn
    from gochugaru_tpu.utils import background
    from gochugaru_tpu.utils.metrics import default as m

    c = new_tpu_evaluator()
    ctx = background()
    c.write_schema(ctx, tl.RBAC)
    rels, users, teams, orgs, repos = tl.rbac_world(
        seed=3, n_users=10, n_repos=6
    )
    txn = Txn()
    for r in rels:
        txn.create(r)
    rev = c.write(ctx, txn)
    cs = consistency.at_least(rev)
    base_retries = m.counter("retry.retries")
    with faults.default.armed("lookup.dispatch", times=1) as spec:
        got = sorted(c.lookup_resources(ctx, cs, "repo#read", users[0]))
    assert spec.fired == 1
    assert m.counter("retry.retries") >= base_retries + 1
    snap = c.store.snapshot_for(cs)
    oracle = c._oracle_for(snap)
    stype, sid = users[0].split(":")
    assert got == sorted(oracle.lookup_resources("repo", "read", stype, sid, ""))
    # paged surface retries too
    with faults.default.armed("lookup.dispatch", times=1) as spec:
        page = c.lookup_resources_page(
            ctx, cs, "repo#read", users[1], page_size=3
        )
    assert spec.fired == 1
    out = list(page.ids)
    while page.cursor is not None:
        page = c.lookup_resources_page(
            ctx, cs, "repo#read", users[1], page_size=3, cursor=page.cursor
        )
        out.extend(page.ids)
    stype, sid = users[1].split(":")
    assert sorted(out) == sorted(
        oracle.lookup_resources("repo", "read", stype, sid, "")
    )


def test_sharded_routed_lookup_parity():
    """The bucket-sharded stacked layout serves lookups through the
    owner-routed hop path (parallel/sharded.py _ShardedLookupHops):
    answers bitwise-match the single-chip frontier and the oracle, and
    the hops actually run (no silent walker fallback)."""
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh
    from gochugaru_tpu.utils.metrics import default as m

    rels, users, teams, orgs, repos = tl.rbac_world(
        seed=11, n_users=20, n_repos=12
    )
    cs, engine, dsnap, oracle = tl.world(tl.RBAC, rels)
    snap = dsnap.snapshot
    sh = ShardedEngine(cs, make_mesh(1, 4))
    ds = sh.prepare(snap)
    assert ds.flat_meta.sharded and ds.flat_meta.has_rev
    assert spmv.frontier_ok(sh, ds)
    base_hops = m.counter("lookup.hops")
    base_walk = m.counter("lookups.walker")
    for u in users[:5]:
        uid = u.split(":")[1]
        got = lm.lookup_resources_device(
            sh, ds, "repo", "read", "user", uid,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        ref = lm.lookup_resources_device(
            engine, dsnap, "repo", "read", "user", uid,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        assert got == ref
    for r in repos[:3]:
        rid = r.split(":")[1]
        got = lm.lookup_subjects_device(
            sh, ds, "repo", rid, "read", "user",
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        want = sorted(oracle.lookup_subjects("repo", rid, "read", "user", ""))
        assert got == want
    assert m.counter("lookup.hops") > base_hops
    assert m.counter("lookups.walker") == base_walk


def test_fuzz_pagination_matches_full_answer():
    """Randomized caveat/wildcard/overflow worlds: pages concatenate to
    the full sorted answer with no dup/lost IDs (frontier path)."""
    import random

    rng = random.Random(4)
    users = [f"user:u{i}" for i in range(10)]
    groups = [f"group:g{i}" for i in range(4)]
    projs = [f"proj:p{i}" for i in range(6)]
    rels = []
    for g in groups:
        for u in rng.sample(users, 3):
            rels.append(rel.must_from_tuple(f"{g}#member", u))
        if rng.random() < 0.5:
            rels.append(
                rel.must_from_tuple(f"{g}#member", f"{rng.choice(groups)}#member")
            )
    for p in projs:
        rels.append(rel.must_from_tuple(f"{p}#owner", rng.choice(users)))
        rels.append(
            rel.must_from_tuple(f"{p}#owner", f"{rng.choice(groups)}#member")
        )
        for u in rng.sample(users, 2):
            r = rel.must_from_tuple(f"{p}#writer", u)
            if rng.random() < 0.4:
                r = r.with_caveat("lim", {"v": rng.randint(0, 9), "cap": 5})
            rels.append(r)
    cs, engine, dsnap, oracle = tl.world(tl.FUZZ_SCHEMA, rels)
    for u in users[:5]:
        uid = u.split(":")[1]
        full = lm.lookup_resources_device(
            engine, dsnap, "proj", "write", "user", uid,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        out, cursor = [], None
        while True:
            ids, cursor = lm.lookup_resources_page(
                engine, dsnap, "proj", "write", "user", uid,
                page_size=2, cursor=cursor, now_us=NOW,
                oracle_factory=lambda: oracle,
            )
            out.extend(ids)
            if cursor is None:
                break
        assert sorted(out) == full and len(out) == len(set(out))


def test_cursor_pins_implicit_evaluation_time(rbac):
    """A lookup with no explicit now_us resolves wall clock ONCE and
    pins it in the cursor: a recompute-resume at a later wall clock
    re-evaluates expiry gates at the SAME instant (the no-dup/no-loss
    contract would otherwise break for expiring worlds), and an
    explicit different now_us is a different query (token mismatch)."""
    cs, engine, dsnap, oracle, rels, users, repos = rbac
    uid = _heavy_uid(engine, dsnap, oracle, users)
    ids, cursor = lm.lookup_resources_page(
        engine, dsnap, "repo", "read", "user", uid,
        page_size=1, oracle_factory=lambda: oracle,
    )
    assert cursor is not None and cursor.now_us is not None
    pinned = cursor.now_us
    # churn the continuation cache: the resume must recompute at the
    # PINNED time, produce the same stream, and keep carrying it
    out = list(ids)
    while cursor is not None:
        dsnap.__dict__.pop("_lookup_streams", None)
        ids, cursor = lm.lookup_resources_page(
            engine, dsnap, "repo", "read", "user", uid,
            page_size=1, cursor=cursor, oracle_factory=lambda: oracle,
        )
        out.extend(ids)
        if cursor is not None:
            assert cursor.now_us == pinned
    full = lm.lookup_resources_device(
        engine, dsnap, "repo", "read", "user", uid,
        now_us=pinned, oracle_factory=lambda: oracle,
    )
    assert sorted(out) == full and len(out) == len(set(out))
    # an explicit, different evaluation time is a different query
    _ids, c2 = lm.lookup_resources_page(
        engine, dsnap, "repo", "read", "user", uid,
        page_size=1, now_us=pinned, oracle_factory=lambda: oracle,
    )
    with pytest.raises(PreconditionFailedError):
        lm.lookup_resources_page(
            engine, dsnap, "repo", "read", "user", uid,
            page_size=1, cursor=c2, now_us=pinned + 1_000_000,
            oracle_factory=lambda: oracle,
        )

"""Golden tests for the rel data model, mirroring the reference's tier-1
tests (rel/relationship_test.go) plus filter/txn behavior."""

import datetime as dt

import pytest

from gochugaru_tpu import rel


# -- parser table tests (rel/relationship_test.go:11-29) -------------------

@pytest.mark.parametrize(
    "resource,relation,subject,err",
    [
        ("document:example", "viewer", "user:jzelinskie", None),
        ("", "viewer", "user:jzelinskie", rel.InvalidResourceError),
        ("document:example", "", "user:jzelinskie", rel.InvalidRelationError),
        ("document:example", "viewer", "", rel.InvalidSubjectError),
    ],
)
def test_from_triple_parsing(resource, relation, subject, err):
    if err is None:
        rel.from_triple(resource, relation, subject)
    else:
        with pytest.raises(err):
            rel.from_triple(resource, relation, subject)


def test_subject_relation_optional():
    r = rel.must_from_tuple("document:example#viewer", "team:admin#member")
    assert r.subject_relation == "member"
    r2 = rel.must_from_tuple("document:example#viewer", "user:jake")
    assert r2.subject_relation == ""


# -- canonical string goldens (rel/relationship_test.go:31-55) -------------

def test_string_plain():
    r = rel.must_from_triple("document:example", "viewer", "user:jzelinskie")
    assert str(r) == "document:example#viewer@user:jzelinskie"


def test_string_with_caveat():
    r = rel.must_from_triple("document:example", "viewer", "user:jzelinskie")
    r = r.with_caveat("only_on_tuesday", {"day_of_the_week": "wednesday"})
    assert (
        str(r)
        == 'document:example#viewer@user:jzelinskie[only_on_tuesday:{"day_of_the_week":"wednesday"}]'
    )


def test_string_with_expiration():
    expiry = dt.datetime(2024, 12, 25, 15, 30, 0, tzinfo=dt.timezone.utc)
    r = rel.must_from_triple("document:example", "viewer", "user:jzelinskie")
    r = r.with_expiration(expiry)
    assert (
        str(r)
        == "document:example#viewer@user:jzelinskie[expiration:2024-12-25T15:30:00Z]"
    )


def test_string_with_subject_relation():
    r = rel.must_from_tuple("document:example#viewer", "team:admin#member")
    assert str(r) == "document:example#viewer@team:admin#member"


# -- expiration edge cases (rel/relationship_test.go:57-100) ---------------

@pytest.mark.parametrize(
    "expiration,has_exp,formatted",
    [
        (None, False, "document:example#viewer@user:jzelinskie"),
        (dt.datetime(1, 1, 1), False, "document:example#viewer@user:jzelinskie"),
        (
            dt.datetime(2024, 12, 25, 15, 30, 0, tzinfo=dt.timezone.utc),
            True,
            "document:example#viewer@user:jzelinskie[expiration:2024-12-25T15:30:00Z]",
        ),
    ],
)
def test_expiration_cases(expiration, has_exp, formatted):
    r = rel.must_from_triple("document:example", "viewer", "user:jzelinskie")
    if expiration is not None:
        r = r.with_expiration(expiration)
    assert r.has_expiration() == has_exp
    assert str(r) == formatted


def test_rfc3339_nano_trims_trailing_zeros():
    t = dt.datetime(2024, 1, 2, 3, 4, 5, 120000, tzinfo=dt.timezone.utc)
    from gochugaru_tpu.rel.relationship import format_rfc3339_nano

    assert format_rfc3339_nano(t) == "2024-01-02T03:04:05.12Z"


# -- builders are immutable copies (rel/relationship.go:93-120) ------------

def test_with_caveat_is_copy():
    r = rel.must_from_triple("document:example", "viewer", "user:jzelinskie")
    r2 = r.with_caveat("c", {"x": 1})
    assert not r.has_caveat()
    assert r2.has_caveat()
    name, ctx, ok = r2.caveat()
    assert (name, ok) == ("c", True)
    assert ctx["x"] == 1


# -- interface acceptance (rel.Interface, rel/relationship.go:26) ----------

def test_interface_duck_typing():
    class MyGrant:
        def relationship(self):
            return rel.must_from_triple("document:d", "viewer", "user:u")

    from gochugaru_tpu.rel.relationship import as_relationship

    assert as_relationship(MyGrant()).resource_id == "d"
    with pytest.raises(TypeError):
        as_relationship(42)


# -- objects (rel/relationship.go:198-218) ---------------------------------

def test_from_objects():
    r = rel.from_objects(
        rel.Object("document", "readme", "viewer"), rel.Object("user", "jake")
    )
    assert str(r) == "document:readme#viewer@user:jake"


# -- filters ---------------------------------------------------------------

def test_relationship_filter_roundtrip():
    r = rel.must_from_triple("document:readme", "viewer", "user:jake")
    f = r.filter()
    assert f.matches(r)
    assert not f.matches(rel.must_from_triple("document:readme", "viewer", "user:amy"))


def test_filter_wildcards():
    f = rel.new_filter("document", "", "")
    assert f.matches(rel.must_from_triple("document:a", "viewer", "user:x"))
    assert not f.matches(rel.must_from_triple("folder:a", "viewer", "user:x"))
    f2 = rel.new_filter("document", "", "viewer")
    assert not f2.matches(rel.must_from_triple("document:a", "editor", "user:x"))


def test_subject_filter_relation_semantics():
    f = rel.new_filter("document", "", "")
    f.with_subject_filter("team", "", "member")
    assert f.matches(rel.must_from_tuple("document:a#viewer", "team:eng#member"))
    assert not f.matches(rel.must_from_tuple("document:a#viewer", "team:eng"))
    # empty optional_relation = any subject relation
    g = rel.new_filter("document", "", "")
    g.with_subject_filter("team", "")
    assert g.matches(rel.must_from_tuple("document:a#viewer", "team:eng#member"))
    assert g.matches(rel.must_from_tuple("document:a#viewer", "team:eng"))


# -- txn builder (rel/txn.go) ----------------------------------------------

def test_txn_builder():
    txn = rel.Txn()
    txn.must_not_match(rel.must_from_triple("m:g", "creator", "user:rival").filter())
    txn.touch(rel.must_from_triple("m:g", "creator", "user:jimmy"))
    txn.create(rel.must_from_triple("m:g", "maintainer", "user:sam"))
    txn.delete(rel.must_from_triple("m:g", "maintainer", "user:old"))
    assert [u.update_type for u in txn.updates] == [
        rel.UpdateType.TOUCH,
        rel.UpdateType.CREATE,
        rel.UpdateType.DELETE,
    ]
    assert len(txn.preconditions) == 1 and not txn.preconditions[0].must_match


# -- string parsers (rel/strings.go) ---------------------------------------

def test_parse_object_set():
    assert rel.parse_object_set("document:README") == ("document", "README", "")
    assert rel.parse_object_set("document:README#reader") == (
        "document",
        "README",
        "reader",
    )
    with pytest.raises(rel.InvalidObjectStringError):
        rel.parse_object_set("document")


def test_parse_typed_relation():
    assert rel.parse_typed_relation("document#reader") == ("document", "reader")
    with pytest.raises(rel.InvalidTypedRelationStringError):
        rel.parse_typed_relation("document")
